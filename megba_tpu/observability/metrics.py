"""Process-local metrics registry with Prometheus exposition.

The serving tier's counterpart to per-solve SolveReport telemetry: a
thread-safe registry of counters / gauges / histograms that the queue,
batcher, compile pool, FleetRouter and the solve entry points increment
from the HOST side only (the hot-path contract — compiled programs are
byte-identical with metrics on; the HLO audit budgets pin this).

Three consumption surfaces:

- ``registry.snapshot()`` — a JSON-round-trippable dict with sorted,
  deterministic keys (the harvesting seam ROADMAP item 4's learned
  router consults; ``FleetRouter.metrics_snapshot()`` pulls one per
  worker over the RPC and merges them with :func:`merge_snapshots`).
- :func:`render_prometheus` — the Prometheus text exposition format
  (``# HELP`` / ``# TYPE`` headers, ``_bucket``/``_sum``/``_count``
  histogram series with cumulative ``le`` labels), renderable from any
  snapshot, merged or local.
- ``summarize --fleet`` renders a snapshot as a human table.

Off by default: nothing in the package imports this module unless
``MEGBA_METRICS`` (or the per-solve ``ProblemOption.metrics`` knob) is
set — consumers go through ``observability.metrics_registry()``, which
lazily imports it, matching the telemetry-sink posture pinned by
tests/test_observability.py.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SCHEMA = "megba_tpu.metrics/v1"

# Fixed log-spaced latency buckets (seconds): 1ms .. 60s in 1/2.5/5
# decades.  Fixed on purpose — merged snapshots from N workers must share
# bucket boundaries or the merge is meaningless.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
)

# Fill/padding ratios and other [0, 1] observables.
RATIO_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0,
)

# Iteration-count observables (LM/PCG iterations per solve).
ITER_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
)


def _label_key(labels: Dict[str, str]) -> str:
    """Canonical series key: sorted ``k=v`` pairs joined by ``,``.

    Sorted so that snapshots (and their merges) are order-independent
    and bitwise-deterministic regardless of increment interleaving.
    """
    return ",".join(f"{k}={labels[k]}" for k in sorted(labels))


def _parse_label_key(key: str) -> List[Tuple[str, str]]:
    if not key:
        return []
    return [tuple(part.split("=", 1)) for part in key.split(",")]


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """One named metric family; per-label-set series live in `_series`."""

    kind = "untyped"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        self.name = name
        self.help = help
        self._registry = registry
        # One registry-wide lock shared by every family: series updates
        # and whole-registry snapshots serialize against each other.
        self._lock = registry._lock
        self._series: Dict[str, object] = {}  # megba: guarded-by(_lock)

    def _series_dict(self):
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1, **labels: str) -> None:
        key = _label_key({k: str(v) for k, v in labels.items()})
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + n

    def _series_dict(self):
        return {k: self._series[k] for k in sorted(self._series)}


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **labels: str) -> None:
        key = _label_key({k: str(v_) for k, v_ in labels.items()})
        with self._lock:
            self._series[key] = float(v)

    def max(self, v: float, **labels: str) -> None:
        """Record a high-water mark (e.g. peak queue depth)."""
        key = _label_key({k: str(v_) for k, v_ in labels.items()})
        with self._lock:
            self._series[key] = max(float(v), self._series.get(key, -math.inf))

    def _series_dict(self):
        return {k: self._series[k] for k in sorted(self._series)}


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, registry,
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        super().__init__(name, help, registry)
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(
                f"histogram {name!r} buckets must be strictly increasing, "
                f"got {buckets}")

    def observe(self, v: float, **labels: str) -> None:
        key = _label_key({k: str(v_) for k, v_ in labels.items()})
        v = float(v)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = {"buckets": [0] * len(self.buckets),
                          "sum": 0.0, "count": 0}
                self._series[key] = series
            # Non-cumulative per-bucket counts internally; exposition
            # renders the Prometheus cumulative form.
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    series["buckets"][i] += 1
                    break
            series["sum"] += v
            series["count"] += 1

    def _series_dict(self):
        out = {}
        for k in sorted(self._series):
            s = self._series[k]
            out[k] = {"buckets": list(s["buckets"]),
                      "sum": s["sum"], "count": s["count"]}
        return out


class MetricsRegistry:
    """Thread-safe get-or-create registry of named metric families."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}  # megba: guarded-by(_lock)

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def snapshot(self) -> Dict:
        """JSON-round-trippable snapshot with deterministic key order."""
        with self._lock:
            metrics = {}
            for name in sorted(self._metrics):
                m = self._metrics[name]
                entry = {"kind": m.kind, "help": m.help,
                         "series": m._series_dict()}
                if isinstance(m, Histogram):
                    entry["buckets"] = list(m.buckets)
                metrics[name] = entry
            return {"schema": SCHEMA, "metrics": metrics}

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


def merge_snapshots(snapshots: Iterable[Dict]) -> Dict:
    """Merge snapshots from N processes into one.

    Counters and histogram series sum; gauges sum too (the fleet gauges
    — queue depth, in-flight — are additive across workers, and summing
    in sorted-series order keeps the result bitwise-deterministic for
    any input order of equal snapshots).  Histogram merges require equal
    bucket boundaries (they are fixed module constants, so drift means a
    version skew worth failing loudly on).
    """
    merged: Dict[str, Dict] = {}
    for snap in snapshots:
        if not snap:
            continue
        for name, entry in sorted(snap.get("metrics", {}).items()):
            tgt = merged.get(name)
            if tgt is None:
                tgt = {"kind": entry["kind"], "help": entry.get("help", ""),
                       "series": {}}
                if "buckets" in entry:
                    tgt["buckets"] = list(entry["buckets"])
                merged[name] = tgt
            if tgt["kind"] != entry["kind"]:
                raise ValueError(
                    f"metric {name!r} kind mismatch in merge: "
                    f"{tgt['kind']} vs {entry['kind']}")
            if entry["kind"] == "histogram" and (
                    list(entry.get("buckets", [])) != tgt.get("buckets")):
                raise ValueError(
                    f"histogram {name!r} bucket mismatch in merge")
            for key in sorted(entry["series"]):
                s = entry["series"][key]
                t = tgt["series"].get(key)
                if entry["kind"] == "histogram":
                    if t is None:
                        t = {"buckets": [0] * len(s["buckets"]),
                             "sum": 0.0, "count": 0}
                        tgt["series"][key] = t
                    t["buckets"] = [a + b for a, b
                                    in zip(t["buckets"], s["buckets"])]
                    t["sum"] += s["sum"]
                    t["count"] += s["count"]
                else:
                    tgt["series"][key] = (0.0 if t is None else t) + s
    return {"schema": SCHEMA,
            "metrics": {k: _sorted_entry(merged[k]) for k in sorted(merged)}}


def _sorted_entry(entry: Dict) -> Dict:
    out = {"kind": entry["kind"], "help": entry.get("help", ""),
           "series": {k: entry["series"][k] for k in sorted(entry["series"])}}
    if "buckets" in entry:
        out["buckets"] = entry["buckets"]
    return out


def render_prometheus(snapshot: Dict) -> str:
    """Prometheus text exposition (format 0.0.4) of a snapshot."""
    lines: List[str] = []
    for name in sorted(snapshot.get("metrics", {})):
        entry = snapshot["metrics"][name]
        if entry.get("help"):
            lines.append(f"# HELP {name} {entry['help']}")
        lines.append(f"# TYPE {name} {entry['kind']}")
        if entry["kind"] == "histogram":
            buckets = entry["buckets"]
            for key in sorted(entry["series"]):
                s = entry["series"][key]
                base = _parse_label_key(key)
                cum = 0
                for ub, n in zip(buckets, s["buckets"]):
                    cum += n
                    lines.append(_sample(f"{name}_bucket",
                                         base + [("le", _fmt_value(ub))],
                                         cum))
                lines.append(_sample(f"{name}_bucket",
                                     base + [("le", "+Inf")], s["count"]))
                lines.append(_sample(f"{name}_sum", base, s["sum"]))
                lines.append(_sample(f"{name}_count", base, s["count"]))
        else:
            for key in sorted(entry["series"]):
                lines.append(_sample(name, _parse_label_key(key),
                                     entry["series"][key]))
    return "\n".join(lines) + ("\n" if lines else "")


def _sample(name: str, labels: List[Tuple[str, str]], value: float) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def snapshot_to_json(snapshot: Dict) -> str:
    """Canonical JSON encoding (sorted keys, no whitespace drift) — the
    bitwise-determinism surface metrics_snapshot() tests pin."""
    return json.dumps(snapshot, sort_keys=True, separators=(",", ":"))


# --- process default registry ---------------------------------------------

_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def reset_default_registry() -> None:
    """Testing hook: drop the process-default registry's contents."""
    default_registry().reset()
