from megba_tpu.core.types import BALData, BAState

__all__ = ["BALData", "BAState"]
