"""Core pytree data model.

The TPU-native replacement for the reference's object-graph problem
representation (BaseVertex/BaseEdge/EdgeVector SoA,
reference include/vertex/base_vertex.h:153-171 and
include/edge/base_edge.h:69-163): a flat struct-of-arrays pytree.  Cameras
and points are dense parameter arrays; edges are index pairs into them plus
per-edge observations — `jnp.take` gathers replace the reference's
positionContainer machinery (reference src/edge/base_edge.cpp:224-262) and
`segment_sum` scatter-reduces replace its atomicAdd kernels
(reference src/edge/build_linear_system.cu:88-146).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BALData:
    """A vectorised BA problem instance (static topology + dynamic params).

    Attributes:
      cameras: [num_cameras, camera_dim] parameter blocks (BAL: 9 =
        angle-axis(3) + translation(3) + f + k1 + k2).
      points:  [num_points, point_dim] parameter blocks (BAL: 3).
      obs:     [n_edge, obs_dim] per-edge measurements (BAL: 2).
      cam_idx: [n_edge] int32 camera index of each edge.
      pt_idx:  [n_edge] int32 point index of each edge.
      mask:    [n_edge] weight, 1.0 for real edges, 0.0 for padding edges
        (the TPU equivalent of the reference's remainder-shard handling,
        memory_pool.h:48-63 — shards must be equal-size static shapes).
      sqrt_info: optional [n_edge, obs_dim, obs_dim] square-root information
        matrices (reference BaseEdge information matrix semantics,
        build_linear_system.cu:148-239); None means identity.
      cam_fixed: optional [num_cameras] bool, True = frozen (reference
        BaseVertex::fixed, base_vertex.h:48-50).
      pt_fixed: optional [num_points] bool.
    """

    cameras: jax.Array
    points: jax.Array
    obs: jax.Array
    cam_idx: jax.Array
    pt_idx: jax.Array
    mask: jax.Array
    sqrt_info: Optional[jax.Array] = None
    cam_fixed: Optional[jax.Array] = None
    pt_fixed: Optional[jax.Array] = None

    @property
    def num_cameras(self) -> int:
        return self.cameras.shape[0]

    @property
    def num_points(self) -> int:
        return self.points.shape[0]

    @property
    def n_edge(self) -> int:
        return self.obs.shape[0]

    @property
    def camera_dim(self) -> int:
        return self.cameras.shape[1]

    @property
    def point_dim(self) -> int:
        return self.points.shape[1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BAState:
    """The parameter state carried through the LM loop.

    The functional replacement for the reference's backup/rollback device
    copies (base_edge.cu:17-44, schur_LM_linear_system.cu:187-209): LM
    accept/reject simply selects which pytree to carry forward.
    """

    cameras: jax.Array
    points: jax.Array


def is_cam_sorted(cam_idx: np.ndarray) -> bool:
    """True when edges are ordered by nondecreasing camera index — the
    promise behind `indices_are_sorted` in the Hessian scatter-reduces."""
    return bool(np.all(np.diff(cam_idx) >= 0))


def pad_edges(
    obs: np.ndarray,
    cam_idx: np.ndarray,
    pt_idx: np.ndarray,
    multiple: int,
    dtype: Any = np.float64,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pad the edge axis to a multiple of `multiple` with masked-out edges.

    Padding edges repeat the LAST edge's vertex indices with weight 0 so
    gathers stay in bounds, segment_sums contribute nothing, and a
    camera-sorted edge order STAYS sorted (which lets the Hessian
    scatter-reduces use `indices_are_sorted`).  This replaces the
    reference's uneven remainder shard (MemoryPool::getItemNum,
    memory_pool.h:48-63) with the static equal shapes XLA sharding
    requires.
    """
    n = obs.shape[0]
    n_pad = (-n) % multiple
    mask = np.ones(n + n_pad, dtype=dtype)
    if n_pad:
        mask[n:] = 0.0
        obs = np.concatenate([obs, np.zeros((n_pad,) + obs.shape[1:], obs.dtype)])
        cam_idx = np.concatenate([cam_idx, np.full(n_pad, cam_idx[-1] if n else 0, cam_idx.dtype)])
        pt_idx = np.concatenate([pt_idx, np.full(n_pad, pt_idx[-1] if n else 0, pt_idx.dtype)])
    return obs, cam_idx, pt_idx, mask
