"""Feature-major ("fm") edge-array layout helpers.

THE central TPU design decision of this framework: every per-edge and
per-point array is stored feature-major — `[F, N]` with the huge axis
minor — instead of the reference's edge-major structs
(reference include/edge/base_edge.h:69-163 stores per-edge blocks as
arrays-of-structs; its CUDA kernels index them thread-per-edge).

Why: XLA:TPU tiles the two minor dimensions of every f32 buffer to
(8, 128).  An edge-major `[nE, 2, 9]` Jacobian therefore pads each
(2, 9) block to (8, 128) — a 57x memory inflation that makes BAL-Venice
(5M edges) need 57 GB of HBM.  Feature-major `[18, nE]` pads 18 -> 24
sublanes: 1.33x.  The same applies to per-point blocks: `[Np, 3, 3]`
Hessian diagonals inflate 114x, `[9, Np]` rows inflate 1.78x.  (Measured
on a v5e: the round-1 edge-major pipeline OOMs at 57.8/15.75 GB on
Venice; feature-major fits with room to spare.)

Row convention for flattened blocks: `J[o * d + a]` is d r_o / d x_a —
o-major, matching C row-major reshape of the logical [od, d] block.

Segment reductions scatter along the minor axis.  To bound transient
memory the reduction is CHUNKED over the edge axis (`lax.scan` over
static-size slices): the scatter's updates operand — the only large
materialisation — is [F, chunk] instead of [F, nE].  This replaces the
reference's atomicAdd accumulation (build_linear_system.cu:88-146) in a
race-free, deterministic form.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# Edge counts are padded to a multiple of this quantum at lowering
# (core.types.pad_edges callers): keeps every chunk slice static-shape,
# lets the Pallas assembly kernel tile without copying, and keeps
# per-shard counts equal under the edge mesh.
EDGE_QUANTUM = 2048

# Target edges per build chunk: bounds the scatter-updates transient to
# [~102 rows, CHUNK] ~ 100 MB while keeping scan trip counts tiny.
DEFAULT_CHUNK = 1 << 18


def to_fm(x: jnp.ndarray) -> jnp.ndarray:
    """[N, F...] edge-major -> [F..., N] feature-major (boundary only)."""
    return jnp.moveaxis(x, 0, -1)


def from_fm(x: jnp.ndarray) -> jnp.ndarray:
    """[F..., N] feature-major -> [N, F...] edge-major (boundary only)."""
    return jnp.moveaxis(x, -1, 0)


def gather_fm(params: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Per-edge gather: [F, N] params, [nE] indices -> [F, nE]."""
    return jnp.take(params, idx, axis=1)


def segsum_fm(
    data: jnp.ndarray,
    idx: jnp.ndarray,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jnp.ndarray:
    """Un-chunked scatter-add of [F, nE] rows into [F, num_segments].

    For per-iteration PCG products (F <= ~9) the updates transient is
    small; the Hessian build (F ~ 100) goes through `chunked_edge_reduce`
    instead.
    """
    out = jnp.zeros((data.shape[0], num_segments), data.dtype)
    return out.at[:, idx].add(
        data, indices_are_sorted=indices_are_sorted, unique_indices=False,
        mode="drop")


def chunk_sizes(n: int, target: int = DEFAULT_CHUNK) -> Tuple[int, int, int]:
    """Split n = n_full * chunk + tail into static scan shapes.

    n must be a multiple of EDGE_QUANTUM (lowering guarantees it); chunk
    is the largest EDGE_QUANTUM multiple <= target, tail < chunk.
    """
    q = EDGE_QUANTUM
    if n <= target or n <= q:
        return 0, max(n, 1), n if n else 0  # single tail call
    chunk = max(q, (target // q) * q)
    n_full, tail = divmod(n, chunk)
    return n_full, chunk, tail


def chunked_edge_reduce(
    n_edge: int,
    inits: Sequence[jnp.ndarray],
    body: Callable[[int, jnp.ndarray, Sequence[jnp.ndarray]], Sequence[jnp.ndarray]],
    target: int = DEFAULT_CHUNK,
) -> Sequence[jnp.ndarray]:
    """Accumulate `inits` over edge chunks with bounded transients.

    `body(start, size, accs) -> accs` processes edges [start, start+size)
    — `size` is a STATIC python int (one compiled body per distinct size;
    at most two sizes occur: chunk and tail).  The large feature
    matrices the body builds live only at [F, size].
    """
    n_full, chunk, tail = chunk_sizes(n_edge, target)
    accs = tuple(inits)
    if n_full == 1 and tail == 0:
        return tuple(body(0, chunk, accs))
    if n_full:
        def scan_body(accs, i):
            return tuple(body(i * chunk, chunk, accs)), None

        accs, _ = jax.lax.scan(
            scan_body, accs, jnp.arange(n_full, dtype=jnp.int32))
    if tail:
        accs = tuple(body(n_full * chunk, tail, accs))
    return accs


def slice_fm(x: jnp.ndarray, start, size: int) -> jnp.ndarray:
    """Static-size dynamic slice along the minor (edge) axis."""
    return jax.lax.dynamic_slice_in_dim(x, start, size, axis=x.ndim - 1)


def coupling_rows(Jc: jnp.ndarray, Jp: jnp.ndarray, od: int) -> jnp.ndarray:
    """Per-edge coupling block rows W = Jc^T Jp: [cd*pd, n], row a*pd+b.

    The single definition of the W-row flattening convention, shared by
    the explicit build, the dense validation solver and the Schur-diag
    preconditioner.
    """
    cd = Jc.shape[0] // od
    pd = Jp.shape[0] // od
    return jnp.stack([
        sum(Jc[o * cd + a] * Jp[o * pd + b] for o in range(od))
        for a in range(cd) for b in range(pd)
    ])


def block_matvec_fm(H: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Row-form block-diagonal matvec: H [d*d, N] times x [d, N] -> [d, N]."""
    d = x.shape[0]
    return jnp.stack(
        [sum(H[i * d + j] * x[j] for j in range(d)) for i in range(d)])


def block_inv_fm(H: jnp.ndarray) -> jnp.ndarray:
    """Row-form batched inverse of [d*d, N] SPD blocks, d in {1, 2, 3}.

    Closed-form adjugate — branch-free VPU math over the minor axis (the
    feature-major analog of the reference's cublasGmatinvBatched,
    schur_pcg_solver.cu:60-97).
    """
    dd = H.shape[0]
    if dd == 1:
        return 1.0 / H
    if dd == 4:
        a, b, c, e = H[0], H[1], H[2], H[3]
        det = a * e - b * c
        return jnp.stack([e, -b, -c, a]) / det
    if dd == 9:
        a, b, c = H[0], H[1], H[2]
        d_, e, f = H[3], H[4], H[5]
        g, h, i = H[6], H[7], H[8]
        A = e * i - f * h
        B = c * h - b * i
        C = b * f - c * e
        D = f * g - d_ * i
        E = a * i - c * g
        F = c * d_ - a * f
        G = d_ * h - e * g
        Hc = b * g - a * h
        I = a * e - b * d_
        det = a * A + b * D + c * G
        return jnp.stack([A, B, C, D, E, F, G, Hc, I]) / det
    raise NotImplementedError(f"block_inv_fm: unsupported block size {dd}")


def damp_rows_fm(H: jnp.ndarray, region: jnp.ndarray) -> jnp.ndarray:
    """LM damping on [d*d, N] rows: diagonal rows scale by (1 + 1/region).

    Row-form of linear_system.builder.damp_blocks (the reference's
    extractOldAndApplyNewDiag, schur_LM_linear_system.cu:112-160).
    """
    dd = H.shape[0]
    d = int(round(dd ** 0.5))
    diag = jnp.asarray([1.0 if r % (d + 1) == 0 else 0.0 for r in range(dd)],
                       H.dtype)
    factor = 1.0 + diag / region
    return H * factor[:, None]
