"""Vectorised host-side (numpy) SE(3) helpers.

Problem construction and file IO run on the host before anything touches
a device; per-element JAX dispatches there cost more than the whole
batched computation (measured on the g2o parse path: 5.7x).  This
module is the one home for that math — quaternion (xyzw) <-> angle-axis
charts with the double-cover fold, quaternion algebra, and batched SE(3)
compose/relative on [..., 6] = [angle_axis, translation] pose arrays.

The device-side equivalents live in ops/geo.py (jax); the chart
conventions match exactly (principal branch, small-angle series) and
are cross-checked by tests/test_g2o_io.py and tests/test_pgo.py.
"""

from __future__ import annotations

import numpy as np


def quat_to_aa(q_xyzw: np.ndarray) -> np.ndarray:
    """[..., 4] (qx,qy,qz,qw) -> [..., 3] angle-axis, principal branch.

    angle = 2 atan2(||v||, w) after folding the double cover (q and -q
    are the same rotation; w >= 0 keeps the angle in [0, pi]); the
    small-angle series 2/w (1 - ||v||^2 / (3 w^2)) guards ||v|| -> 0.
    Matches ops/geo.quaternion_to_angle_axis.
    """
    q = np.asarray(q_xyzw, np.float64)
    v = q[..., :3]
    w = q[..., 3]
    v = np.where(w[..., None] < 0, -v, v)
    w = np.abs(w)
    s2 = np.einsum("...i,...i->...", v, v)
    s = np.sqrt(s2)
    big = s > 1e-8
    with np.errstate(invalid="ignore", divide="ignore"):
        k_big = 2.0 * np.arctan2(s, w) / np.where(big, s, 1.0)
    w_safe = np.where(w == 0.0, 1.0, w)
    k_small = 2.0 / w_safe * (1.0 - s2 / (3.0 * w_safe * w_safe))
    k = np.where(big, k_big, k_small)
    return v * k[..., None]


def aa_to_quat(aa: np.ndarray) -> np.ndarray:
    """[..., 3] angle-axis -> [..., 4] (qx,qy,qz,qw).

    q = [sin(theta/2) axis, cos(theta/2)]; the small-angle branch uses
    sin(theta/2)/theta ~= 1/2 - theta^2/48.
    """
    a = np.asarray(aa, np.float64)
    theta2 = np.einsum("...i,...i->...", a, a)
    theta = np.sqrt(theta2)
    big = theta > 1e-8
    with np.errstate(invalid="ignore", divide="ignore"):
        k_big = np.sin(theta / 2.0) / np.where(big, theta, 1.0)
    k = np.where(big, k_big, 0.5 - theta2 / 48.0)
    return np.concatenate(
        [a * k[..., None], np.cos(theta / 2.0)[..., None]], axis=-1)


def quat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Hamilton product, xyzw layout, batched."""
    av, aw = a[..., :3], a[..., 3:4]
    bv, bw = b[..., :3], b[..., 3:4]
    v = aw * bv + bw * av + np.cross(av, bv)
    w = aw * bw - np.einsum("...i,...i->...", av, bv)[..., None]
    return np.concatenate([v, w], axis=-1)


def quat_conj(q: np.ndarray) -> np.ndarray:
    return np.concatenate([-q[..., :3], q[..., 3:4]], axis=-1)


def quat_rotate(q: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Rotate vectors [..., 3] by unit quaternions [..., 4] (xyzw)."""
    qv, w = q[..., :3], q[..., 3:4]
    t = 2.0 * np.cross(qv, v)
    return v + w * t + np.cross(qv, t)


def compose(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """T_a o T_b on [..., 6] poses ([angle_axis, translation])."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    qa = aa_to_quat(a[..., :3])
    qb = aa_to_quat(b[..., :3])
    aa = quat_to_aa(quat_mul(qa, qb))
    t = quat_rotate(qa, b[..., 3:]) + a[..., 3:]
    return np.concatenate([aa, t], axis=-1)


def relative(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """T_a^{-1} o T_b on [..., 6] poses (the between-factor measurement)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    qa_inv = quat_conj(aa_to_quat(a[..., :3]))
    aa = quat_to_aa(quat_mul(qa_inv, aa_to_quat(b[..., :3])))
    t = quat_rotate(qa_inv, b[..., 3:] - a[..., 3:])
    return np.concatenate([aa, t], axis=-1)
