"""Small host-side (numpy) linear-algebra helpers shared across layers."""

from __future__ import annotations

import numpy as np


def psd_sqrt(info: np.ndarray, what: str = "element") -> np.ndarray:
    """Matrix square-root weights W with W^T W = info, batched [..., n, n].

    Uses a symmetric eigendecomposition rather than Cholesky so
    positive-SEMIdefinite matrices (a zero row = deliberately
    unconstrained DOF, common in partial-sensor pose-graph exports)
    factor cleanly instead of crashing; small negative eigenvalues from
    text round-off are clamped to zero.  Raises ValueError naming the
    first offending batch element for genuinely indefinite input.
    """
    info = np.asarray(info)
    w, v = np.linalg.eigh(info)  # info = V diag(w) V^T
    floor = -1e-9 * np.maximum(w.max(axis=-1, keepdims=True), 1.0)
    bad = np.nonzero((w < floor).reshape(-1, w.shape[-1]).any(axis=-1))[0]
    if bad.size:
        flat_w = w.reshape(-1, w.shape[-1])
        raise ValueError(
            f"{what} {int(bad[0])} (of {flat_w.shape[0]}) has an "
            f"indefinite information matrix (eigenvalues "
            f"{flat_w[bad[0]]})")
    # W = diag(sqrt(w)) V^T satisfies W^T W = info.
    return np.sqrt(np.maximum(w, 0.0))[..., :, None] * np.swapaxes(
        v, -1, -2)
