"""g2o-compatible Problem / Vertex / Edge user API.

The object facade with the semantics of the reference's user layer
(include/problem/base_problem.h:54-82, include/vertex/base_vertex.h:24-77,
include/edge/base_edge.h:25-67): `append_vertex` / `append_edge` /
`get_vertex` / `erase_vertex` / `solve`, camera/point vertex kinds, fixed
vertices, per-edge measurements and information matrices, and
user-defined `forward()` residuals.

Unlike the reference — where the object graph IS the runtime data
structure, flattened scalar-by-scalar into SoA JetVectors on every push
(base_vertex.h:153-171, the host-side scalability bottleneck noted in
SURVEY.md §3.1) — this facade is a thin builder: `solve()` lowers the
graph once into flat index/parameter arrays and hands them to the jitted
mesh-sharded LM solver.  A user `forward()` is traced ONCE under
`jax.vmap` (plain jnp math on vertex estimations), replacing the entire
JetVector/eigen_injector operator stack.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from megba_tpu.algo.lm import LMResult
from megba_tpu.common import JacobianMode, ProblemOption, validate_options
from megba_tpu.ops.residuals import (
    bal_residual,
    bal_residual_jacobian_analytical,
    build_residual_jacobian_fn,
    make_residual_jacobian_fn,
)


class VertexKind(enum.Enum):
    """Reference BaseVertex kind() (base_vertex.h:52-56), extended with
    POSE for the pose-graph family the reference cannot express."""

    CAMERA = 0
    POINT = 1
    NONE = 2
    POSE = 3


class BaseVertex:
    """A parameter block (reference BaseVertex, base_vertex.h:24-63)."""

    kind = VertexKind.NONE

    def __init__(self, estimation: np.ndarray, fixed: bool = False):
        self.estimation = np.atleast_1d(np.asarray(estimation, dtype=np.float64)).copy()
        self.fixed = bool(fixed)

    @property
    def grad_shape(self) -> int:
        """Differentiable width: 0 when fixed (base_vertex.h:48-50)."""
        return 0 if self.fixed else int(self.estimation.size)

    def __repr__(self):
        return f"{type(self).__name__}(dim={self.estimation.size}, fixed={self.fixed})"


class CameraVertex(BaseVertex):
    kind = VertexKind.CAMERA


class PointVertex(BaseVertex):
    kind = VertexKind.POINT


class PoseVertex(BaseVertex):
    """An SE(3) pose [angle_axis (3), translation (3)] — the pose-graph
    family (models/pgo.py).  Inexpressible in the reference: its edges
    are hard-wired to camera+landmark pairs (base_edge.h)."""

    kind = VertexKind.POSE

    def __init__(self, estimation: np.ndarray, fixed: bool = False):
        super().__init__(estimation, fixed)
        if self.estimation.shape != (6,):
            raise ValueError(
                f"PoseVertex needs 6 parameters [angle_axis, t], got "
                f"shape {self.estimation.shape}")


class BaseEdge:
    """A residual term over its vertices (reference BaseEdge,
    base_edge.h:25-67).

    Subclass and override `forward()` for custom residual models;
    `forward` reads `self.vertex_estimation(i)` (a jnp array during
    tracing) and `self.measurement`, and returns the residual as a jnp
    array.  It is traced once under jax.vmap, so it must be pure jnp math
    (the reference's equivalent constraint: JetVector-compatible Eigen
    ops).  If `forward` is not overridden, the edge uses the built-in BAL
    reprojection model (examples/BAL_Double.cpp:18-33).
    """

    def __init__(
        self,
        vertices: Optional[Sequence[BaseVertex]] = None,
        measurement: Optional[np.ndarray] = None,
        information: Optional[np.ndarray] = None,
    ):
        self.vertices: List[BaseVertex] = list(vertices) if vertices else []
        self.measurement = (
            None if measurement is None else np.atleast_1d(np.asarray(measurement, np.float64))
        )
        self.information = None if information is None else np.asarray(information, np.float64)
        # Trace-time storage (set by the vectoriser while forward() runs).
        self._traced_estimations: Optional[List[jnp.ndarray]] = None
        self._traced_measurement: Optional[jnp.ndarray] = None

    def append_vertex(self, v: BaseVertex) -> "BaseEdge":
        self.vertices.append(v)
        return self

    def vertex_estimation(self, i: int) -> jnp.ndarray:
        """The i-th vertex's parameters; traced value inside forward()."""
        if self._traced_estimations is not None:
            return self._traced_estimations[i]
        return jnp.asarray(self.vertices[i].estimation)

    def get_measurement(self) -> jnp.ndarray:
        if self._traced_measurement is not None:
            return self._traced_measurement
        return jnp.asarray(self.measurement)

    def forward(self) -> jnp.ndarray:
        """Default: the BAL reprojection residual (camera, point)."""
        camera = self.vertex_estimation(0)
        point = self.vertex_estimation(1)
        return bal_residual(camera, point, self.get_measurement())


class BetweenEdge(BaseEdge):
    """SE(3) between-factor over two PoseVertex (models/pgo.py).

    measurement: the expected relative pose T_i^{-1} T_j as
    [angle_axis (3), translation (3)]; information: optional 6x6 matrix
    in the solver's [rotation, translation] row order.  The residual is
    the fixed between-factor of the PGO pipeline
    (pgo.between_residual); custom forward() is not supported here.
    """

    def __init__(self, vertices=None, measurement=None, information=None):
        super().__init__(vertices, measurement, information)
        if self.measurement is not None and self.measurement.shape != (6,):
            raise ValueError(
                f"BetweenEdge measurement must be 6 values "
                f"[angle_axis, t], got shape {self.measurement.shape}")
        if (self.information is not None
                and self.information.shape != (6, 6)):
            raise ValueError(
                f"BetweenEdge information must be 6x6, got shape "
                f"{self.information.shape}")

    def forward(self) -> jnp.ndarray:  # pragma: no cover - guard only
        raise NotImplementedError(
            "BetweenEdge uses the PGO pipeline's fixed between-factor "
            "residual; custom forward() is not supported for pose edges")


def _edge_residual_jac_fn(proto: BaseEdge):
    """Vectorised autodiff engine for a custom edge's forward().

    One prototype edge stands in for every edge during tracing, so
    anything forward() reads beyond the traced vertex estimations and
    measurement (e.g. a per-instance constant) is baked in from THIS
    prototype.  The engine is therefore cached per problem (see
    BaseProblem._engine), never shared across problems whose prototypes
    might differ — a class-level cache was reproduced serving one
    problem's constants to another.
    """

    def residual(camera, point, obs, proto=proto):
        proto._traced_estimations = [camera, point]
        proto._traced_measurement = obs
        try:
            return proto.forward()
        finally:
            proto._traced_estimations = None
            proto._traced_measurement = None

    return build_residual_jacobian_fn(
        residual_fn=residual, mode=JacobianMode.AUTODIFF)


class BaseProblem:
    """The user facade + orchestration (reference BaseProblem,
    base_problem.h:54-82 / base_problem.cpp).

    Usage mirrors the reference examples: append vertices by id, append
    edges (each holding a camera vertex and a point vertex plus a 2-d
    measurement), then `solve()`; solutions are written back into the
    vertex `estimation` arrays (reference writeBack,
    base_problem.cpp:249-272).
    """

    def __init__(self, option: Optional[ProblemOption] = None):
        self.option = option or ProblemOption()
        validate_options(self.option)
        self._vertices: Dict[int, BaseVertex] = {}
        self._vertex_ids: set = set()  # id(vertex) for O(1) membership
        self._edges: List[BaseEdge] = []
        self._edge_type: Optional[type] = None
        self._engine: Optional[Callable] = None  # cached custom-edge engine
        # Problem-owned jitted-program cache for custom-edge engines: the
        # engine closure bakes in THIS problem's prototype edge, so its
        # compiled programs must die with the problem, not sit in the
        # global lru (see solve.flat_solve jit_cache).
        self._jit_cache: dict = {}
        self.result: Optional[LMResult] = None

    # -- graph construction ------------------------------------------------
    def append_vertex(self, vertex_id: int, vertex: BaseVertex) -> None:
        if vertex_id in self._vertices:
            raise ValueError(f"duplicate vertex id {vertex_id}")
        self._vertices[vertex_id] = vertex
        self._vertex_ids.add(id(vertex))

    def append_edge(self, edge: BaseEdge) -> None:
        # Homogeneous edge types only, like the reference's typeid check
        # (base_edge.cpp:49,84-86).
        if self._edge_type is None:
            self._edge_type = type(edge)
        elif type(edge) is not self._edge_type:
            raise TypeError(
                f"heterogeneous edge types: {type(edge).__name__} vs "
                f"{self._edge_type.__name__}"
            )
        kinds = [v.kind for v in edge.vertices]
        if kinds == [VertexKind.POSE, VertexKind.POSE]:
            if not isinstance(edge, BetweenEdge):
                raise TypeError(
                    "pose-pose edges must be BetweenEdge (the PGO "
                    "pipeline's fixed between-factor residual)")
        elif isinstance(edge, BetweenEdge):
            # The converse guard: a BetweenEdge over non-pose vertices
            # would otherwise be misrouted to the PGO pipeline.
            raise TypeError(
                "BetweenEdge requires two PoseVertex endpoints, got "
                f"{[k.name for k in kinds]}")
        elif kinds != [VertexKind.CAMERA, VertexKind.POINT]:
            # The reference classifies ONE/TWO_CAMERA/MULTI kinds
            # (base_edge.cpp:27-36) but, like us, only implements the
            # Schur pipeline for ONE_CAMERA_ONE_POINT; pose graphs go
            # through the PGO pipeline (a family beyond the reference).
            raise NotImplementedError(
                "edges must be (CameraVertex, PointVertex) or "
                "(PoseVertex, PoseVertex)"
            )
        for v in edge.vertices:
            if id(v) not in self._vertex_ids:
                raise ValueError("edge references a vertex not in the problem")
        if edge.measurement is None:
            raise ValueError("edge has no measurement")
        self._edges.append(edge)

    def get_vertex(self, vertex_id: int) -> BaseVertex:
        return self._vertices[vertex_id]

    def erase_vertex(self, vertex_id: int) -> None:
        """Remove a vertex and every edge touching it (reference
        eraseVertex, base_problem.cpp:145-157)."""
        v = self._vertices.pop(vertex_id)
        self._vertex_ids.discard(id(v))
        self._edges = [e for e in self._edges if all(u is not v for u in e.vertices)]
        self._engine = None
        self._jit_cache.clear()
        if not self._edges:
            self._edge_type = None

    # -- lowering + solve ----------------------------------------------------
    def _lower(self):
        cams = [(i, v) for i, v in self._vertices.items() if v.kind == VertexKind.CAMERA]
        pts = [(i, v) for i, v in self._vertices.items() if v.kind == VertexKind.POINT]
        if not cams or not pts or not self._edges:
            raise ValueError("problem needs cameras, points, and edges")
        cam_rank = {id(v): r for r, (_, v) in enumerate(cams)}
        pt_rank = {id(v): r for r, (_, v) in enumerate(pts)}
        cameras = np.stack([v.estimation for _, v in cams])
        points = np.stack([v.estimation for _, v in pts])
        cam_fixed = np.array([v.fixed for _, v in cams])
        pt_fixed = np.array([v.fixed for _, v in pts])
        cam_idx = np.array([cam_rank[id(e.vertices[0])] for e in self._edges], np.int32)
        pt_idx = np.array([pt_rank[id(e.vertices[1])] for e in self._edges], np.int32)
        obs = np.stack([e.measurement for e in self._edges])
        sqrt_info = None
        if any(e.information is not None for e in self._edges):
            od = obs.shape[1]
            infos = np.stack(
                [e.information if e.information is not None else np.eye(od) for e in self._edges]
            )
            # Whitening factor: info = L L^T (Cholesky), use L^T so that
            # r~^T r~ = r^T (L L^T) r = r^T info r (WLS semantics; the
            # reference multiplies J by the information matrix,
            # build_linear_system.cu:148-239).
            sqrt_info = np.transpose(np.linalg.cholesky(infos), (0, 2, 1))
        return cameras, points, obs, cam_idx, pt_idx, cam_fixed, pt_fixed, sqrt_info, cams, pts

    def _lower_pgo(self):
        poses = [(i, v) for i, v in self._vertices.items()
                 if v.kind == VertexKind.POSE]
        if not poses or not self._edges:
            raise ValueError("pose-graph problem needs poses and edges")
        rank = {id(v): r for r, (_, v) in enumerate(poses)}
        table = np.stack([v.estimation for _, v in poses])
        fixed = np.array([v.fixed for _, v in poses])
        edge_i = np.array([rank[id(e.vertices[0])] for e in self._edges],
                          np.int32)
        edge_j = np.array([rank[id(e.vertices[1])] for e in self._edges],
                          np.int32)
        meas = np.stack([e.measurement for e in self._edges])
        sqrt_info = None
        if any(e.information is not None for e in self._edges):
            from megba_tpu.core.linalg import psd_sqrt

            infos = np.stack(
                [e.information if e.information is not None else np.eye(6)
                 for e in self._edges])
            # PSD-safe (zero rows = unconstrained DOFs are common in
            # pose graphs; W^T W = info, same contract as the g2o path).
            sqrt_info = psd_sqrt(infos, what="edge")
        return table, edge_i, edge_j, meas, fixed, sqrt_info, poses

    def _solve_pgo(self, verbose: bool):
        from megba_tpu.models.pgo import solve_pgo

        table, edge_i, edge_j, meas, fixed, sqrt_info, poses = \
            self._lower_pgo()
        result = solve_pgo(
            table, edge_i, edge_j, meas, self.option,
            sqrt_info=sqrt_info,
            # No FIX-ed vertex -> solve_pgo's default gauge anchor
            # (the first pose).
            fixed=fixed if fixed.any() else None,
            verbose=verbose)
        out = np.asarray(result.poses, dtype=np.float64)
        for r, (_, v) in enumerate(poses):
            v.estimation = out[r].copy()
        self.result = result
        return result

    def solve(self, verbose: bool = False):
        """Solve and write back (reference base_problem.cpp:273-278).

        Returns an LMResult for BA graphs; pose graphs (PoseVertex +
        BetweenEdge) route through the PGO pipeline and return a
        PGOResult.
        """
        if self._edges and isinstance(self._edges[0], BetweenEdge):
            return self._solve_pgo(verbose)
        opt = self.option
        (cameras, points, obs, cam_idx, pt_idx,
         cam_fixed, pt_fixed, sqrt_info, cams, pts) = self._lower()

        # Jacobian engine: the built-in analytical path only applies to the
        # untouched BAL forward; custom forwards always go through autodiff.
        custom_forward = (
            self._edge_type is not None
            and self._edge_type.forward is not BaseEdge.forward
        )
        jit_cache = None
        if custom_forward:
            if self._engine is None:
                self._engine = _edge_residual_jac_fn(self._edges[0])
            residual_jac_fn = self._engine
            jit_cache = self._jit_cache
        else:
            residual_jac_fn = make_residual_jacobian_fn(mode=opt.jacobian_mode)

        # All lowering (dtype cast, camera sort, pad/shard, jit caching)
        # lives in the shared pipeline.
        from megba_tpu.solve import flat_solve

        result = flat_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx, opt,
            sqrt_info=sqrt_info,
            cam_fixed=cam_fixed if cam_fixed.any() else None,
            pt_fixed=pt_fixed if pt_fixed.any() else None,
            verbose=verbose, jit_cache=jit_cache)

        # Write back (reference base_problem.cpp:249-272).
        cams_out = np.asarray(result.cameras, dtype=np.float64)
        pts_out = np.asarray(result.points, dtype=np.float64)
        for r, (_, v) in enumerate(cams):
            v.estimation = cams_out[r].copy()
        for r, (_, v) in enumerate(pts):
            v.estimation = pts_out[r].copy()
        self.result = result
        return result
