"""Distributed Schur-complement preconditioned conjugate gradients.

TPU-native replacement for the reference's SchurPCGSolver /
ImplicitSchurPCGSolver (src/solver/schur_pcg_solver.cu:598-639,
src/solver/implicit_schur_pcg_solver.cu): the same pipeline —

  1. invert the damped Hll blocks (cublasGmatinvBatched there, a vmapped
     batched inverse here);
  2. reduced RHS v = g_cam - Hpl Hll^-1 g_pt     [1 psum]
  3. PCG on S x = v with S = Hpp - Hpl Hll^-1 Hlp, block-Jacobi
     preconditioner M^-1 = Hpp^-1                 [2 psums / iteration]
  4. back-substitute dx_pt = Hll^-1 (g_pt - Hlp x) [1 psum]

— but as one jitted `lax.while_loop` with everything on-device: the
reference's per-iteration host-blocking dot products
(schur_pcg_solver.cu:277-287,368-384) become plain on-device reductions
over replicated vectors, and its NCCL allreduces of the coupling products
(schur_pcg_solver.cu:211-242,325-357,502-509,568-575) become
`jax.lax.psum` of the segment_sum outputs.

The Hpl/Hlp products never materialise a sparse matrix: EXPLICIT mode
uses the per-edge W_e = Jc^T Jp blocks (gather -> batched matmul ->
segment_sum), IMPLICIT mode recomputes Jc^T (Jp x) from the stored
Jacobians (matrix-free, the reference's implicitEMulx / implicitETMulx,
implicit_schur_pcg_solver.cu:20-90).  Both are dense batched einsums —
the natural MXU mapping; there is no cuSPARSE analog to port.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import ComputeKind, PreconditionerKind
from megba_tpu.linear_system.builder import SchurSystem, damp_blocks
from megba_tpu.ops.accum import comp_dot

HI = jax.lax.Precision.HIGHEST

# Absolute floor for the relative PCG threshold (guards rho0 == 0).
_TINY_RHO = 1e-30


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PCGResult:
    """Solve output: the Schur update and diagnostics."""

    dx_cam: jax.Array  # [Nc, cd]
    dx_pt: jax.Array  # [Np, pd]
    iterations: jax.Array  # scalar int32
    rho: jax.Array  # final residual-energy <r, M^-1 r>


def block_matvec(H: jax.Array, x: jax.Array) -> jax.Array:
    """[N,d,d] block-diagonal times [N,d] -> [N,d]."""
    return jnp.einsum("nij,nj->ni", H, x, precision=HI)


def block_inv(H: jax.Array) -> jax.Array:
    """Batched inverse of SPD blocks [N,d,d].

    The analog of the reference's cublasGmatinvBatched calls
    (schur_pcg_solver.cu:60-97).  Point blocks (d<=3) use the closed-form
    adjugate — branch-free elementwise VPU math, no factorisation —
    while larger (camera 9x9) blocks use Cholesky, which is stable on the
    damped SPD blocks.
    """
    d = H.shape[-1]
    if d == 1:
        return 1.0 / H
    if d == 2:
        a, b = H[..., 0, 0], H[..., 0, 1]
        c, e = H[..., 1, 0], H[..., 1, 1]
        det = a * e - b * c
        inv = jnp.stack([jnp.stack([e, -b], -1), jnp.stack([-c, a], -1)], -2)
        return inv / det[..., None, None]
    if d == 3:
        a, b, c = H[..., 0, 0], H[..., 0, 1], H[..., 0, 2]
        dd, e, f = H[..., 1, 0], H[..., 1, 1], H[..., 1, 2]
        g, h, i = H[..., 2, 0], H[..., 2, 1], H[..., 2, 2]
        A = e * i - f * h
        B = c * h - b * i
        C = b * f - c * e
        D = f * g - dd * i
        E = a * i - c * g
        F = c * dd - a * f
        G = dd * h - e * g
        Hc = b * g - a * h
        I = a * e - b * dd
        det = a * A + b * D + c * G
        adj = jnp.stack(
            [jnp.stack([A, B, C], -1), jnp.stack([D, E, F], -1), jnp.stack([G, Hc, I], -1)],
            -2,
        )
        return adj / det[..., None, None]
    chol = jnp.linalg.cholesky(H)
    eye = jnp.broadcast_to(jnp.eye(d, dtype=H.dtype), H.shape)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return jnp.einsum("nki,nkj->nij", inv_l, inv_l, precision=HI)


def _dot(a: jax.Array, b: jax.Array) -> jax.Array:
    # Compensated elementwise multiply + two-sum tree (ops/accum.py):
    # stays on the VPU, f64-class accuracy in f32 — alpha/beta from
    # noisy dots stall CG convergence at BAL-Final scale.  Vectors are
    # replicated across shards, so no psum is needed — unlike the
    # reference's per-rank sliced dots + host sum
    # (schur_pcg_solver.cu:277-287).
    return comp_dot(a, b)


def make_coupling_matvecs(
    W: Optional[jax.Array],
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    num_cameras: int,
    num_points: int,
    compute_kind: ComputeKind,
    axis_name: Optional[str] = None,
    mixed_precision: bool = False,
    cam_sorted: bool = False,
) -> Tuple[Callable[[jax.Array], jax.Array], Callable[[jax.Array], jax.Array]]:
    """Build hpl(q_pt)->[Nc,cd] and hlp(p_cam)->[Np,pd] matvec closures.

    EXPLICIT mode reads only `W` (per-edge coupling blocks); IMPLICIT mode
    reads only `Jc`/`Jp`.  Edge arrays are shard-local; outputs are
    psum-reduced to replicated.

    `mixed_precision` (BASELINE.md config 5) expects the used operands to
    be pre-equilibrated and bf16-cast (see schur_pcg_solve) and
    accumulates in float32 (`preferred_element_type`) — the coupling
    products are the PCG's bandwidth-dominant work, so this halves HBM
    traffic while the Krylov vectors, reductions and preconditioner stay
    float32.
    """
    ed = jnp.bfloat16 if mixed_precision else None

    def cast(x):
        return x.astype(ed) if ed is not None else x

    def ee(spec, a, b):
        if mixed_precision:
            return jnp.einsum(spec, a, b, preferred_element_type=jnp.float32)
        return jnp.einsum(spec, a, b, precision=HI)

    def psum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    if compute_kind == ComputeKind.EXPLICIT:

        def hlp(p_cam: jax.Array) -> jax.Array:
            pe = cast(jnp.take(p_cam, cam_idx, axis=0))  # [nE, cd]
            te = ee("ecp,ec->ep", W, pe)
            return psum(jax.ops.segment_sum(te, pt_idx, num_segments=num_points))

        def hpl(q_pt: jax.Array) -> jax.Array:
            qe = cast(jnp.take(q_pt, pt_idx, axis=0))  # [nE, pd]
            te = ee("ecp,ep->ec", W, qe)
            return psum(jax.ops.segment_sum(te, cam_idx, num_segments=num_cameras,
                                            indices_are_sorted=cam_sorted))

    else:

        def hlp(p_cam: jax.Array) -> jax.Array:
            pe = cast(jnp.take(p_cam, cam_idx, axis=0))
            u = ee("eoc,ec->eo", Jc, pe)  # Jc p
            te = ee("eop,eo->ep", Jp, cast(u))  # Jp^T (Jc p)
            return psum(jax.ops.segment_sum(te, pt_idx, num_segments=num_points))

        def hpl(q_pt: jax.Array) -> jax.Array:
            qe = cast(jnp.take(q_pt, pt_idx, axis=0))
            u = ee("eop,ep->eo", Jp, qe)  # Jp q
            te = ee("eoc,eo->ec", Jc, cast(u))  # Jc^T (Jp q)
            return psum(jax.ops.segment_sum(te, cam_idx, num_segments=num_cameras,
                                            indices_are_sorted=cam_sorted))

    return hpl, hlp


def _pcg_core(matvec, precond, b, max_iter, tol, refuse_ratio, tol_relative):
    """Preconditioned CG over an arbitrary pytree "vector".

    One implementation of the reference's stopping + refuse semantics
    (|rho| < tol exit, schur_pcg_solver.cu:406-407; rho > refuse_ratio *
    min(rho) -> restore best iterate, :288-296) shared by the Schur
    solver (vector = one array) and the plain full-system solver
    (vector = a (camera, point) pair).  Returns (x, iterations, rho).
    """
    tm = jax.tree_util.tree_map

    def tdot(a, c):
        return jax.tree_util.tree_reduce(
            lambda acc, v: acc + v, tm(_dot, a, c))

    def axpy(a, x, y):  # y + a * x, leafwise
        return tm(lambda xi, yi: yi + a * xi, x, y)

    def select(pred, a, c):
        return tm(lambda ai, ci: jnp.where(pred, ai, ci), a, c)

    x0 = tm(jnp.zeros_like, b)
    r0 = b  # x0 = 0 so r0 = b - A x0 = b
    z0 = precond(r0)
    rho0 = tdot(r0, z0)
    # Reference semantics: absolute threshold on rho; tol_relative scales
    # it by rho0, floored so a zero RHS exits immediately instead of
    # iterating into 0/0 NaNs.
    threshold = (
        jnp.maximum(tol * jnp.abs(rho0), jnp.asarray(_TINY_RHO, rho0.dtype))
        if tol_relative else tol
    )

    state0 = (jnp.int32(0), x0, r0, z0, rho0, jnp.abs(rho0), x0,
              jnp.bool_(False))

    def cond(state):
        k, _, _, _, rho, _, _, refused = state
        return (k < max_iter) & (jnp.abs(rho) >= threshold) & (~refused)

    def body(state):
        k, x, r, p, rho, rho_min, x_best, _ = state
        q = matvec(p)
        alpha = rho / tdot(p, q)
        x = axpy(alpha, p, x)
        r = axpy(-alpha, q, r)
        z = precond(r)
        rho_new = tdot(r, z)
        refused = jnp.abs(rho_new) > refuse_ratio * rho_min
        improved = jnp.abs(rho_new) < rho_min
        rho_min = jnp.where(improved, jnp.abs(rho_new), rho_min)
        x_best = select(improved, x, x_best)
        beta = rho_new / rho
        p = axpy(beta, p, z)
        return (k + 1, x, r, p, rho_new, rho_min, x_best, refused)

    k, x, _, _, rho, _, x_best, refused = jax.lax.while_loop(cond, body, state0)
    return select(~refused, x, x_best), k, rho


def plain_pcg_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
    max_iter: int = 100,
    tol: float = 1e-1,
    refuse_ratio: float = 1.0,
    tol_relative: bool = False,
    compute_kind: ComputeKind = ComputeKind.IMPLICIT,
    axis_name: Optional[str] = None,
    mixed_precision: bool = False,
    cam_sorted: bool = False,
    preconditioner: PreconditionerKind = PreconditionerKind.HPP,
) -> PCGResult:
    """Solve the damped FULL system H dx = g without Schur reduction.

    `preconditioner` is accepted for signature parity and ignored: the
    full system's exact block diagonal (Hpp, Hll) IS this solver's
    preconditioner, so both kinds coincide here.

    The path the reference left as `// TODO(Jie Ren)` behind
    `useSchur=false` (base_problem.cpp:112-123) — implemented here: PCG
    over the concatenated (camera, point) unknowns with the block-diagonal
    H as preconditioner, coupling applied by the same matrix-free /
    per-edge-block matvecs as the Schur solver.  Useful when the point
    blocks are ill-conditioned enough that the Schur complement's
    Hll^-1 amplifies error, and as an independent cross-check of the
    Schur pipeline (both solve the same damped normal equations).
    """
    num_cameras = system.Hpp.shape[0]
    num_points = system.Hll.shape[0]

    if mixed_precision:
        raise NotImplementedError(
            "mixed_precision is only implemented for the Schur solver")

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_blocks(system.Hll, region)
    Minv_c = block_inv(Hpp_d)
    Minv_p = block_inv(Hll_d)

    hpl, hlp = make_coupling_matvecs(
        system.W, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
        compute_kind, axis_name, cam_sorted=cam_sorted,
    )

    def h_matvec(x):
        # [Hpp Hpl; Hlp Hll] applied blockwise      [2 psums]
        xc, xp = x
        return (block_matvec(Hpp_d, xc) + hpl(xp),
                hlp(xc) + block_matvec(Hll_d, xp))

    def precond(r):
        rc, rp = r
        return block_matvec(Minv_c, rc), block_matvec(Minv_p, rp)

    (xc, xp), k, rho = _pcg_core(
        h_matvec, precond, (system.g_cam, system.g_pt),
        max_iter, tol, refuse_ratio, tol_relative)
    return PCGResult(dx_cam=xc, dx_pt=xp, iterations=k, rho=rho)


def schur_pcg_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
    max_iter: int = 100,
    tol: float = 1e-1,
    refuse_ratio: float = 1.0,
    tol_relative: bool = False,
    compute_kind: ComputeKind = ComputeKind.IMPLICIT,
    axis_name: Optional[str] = None,
    mixed_precision: bool = False,
    cam_sorted: bool = False,
    preconditioner: PreconditionerKind = PreconditionerKind.HPP,
) -> PCGResult:
    """Solve the damped Schur system for (dx_cam, dx_pt).

    Semantics follow the reference (SolverOption defaults common.h:27-33):
    `tol` is the absolute threshold on rho = <r, M^-1 r> (loop exits when
    |rho| < tol, schur_pcg_solver.cu:406-407); `refuse_ratio` is the
    divergence guard — when rho exceeds refuse_ratio * min(rho) the solver
    restores the best iterate and stops (schur_pcg_solver.cu:288-296).
    `region` is the LM trust region; damping multiplies block diagonals by
    (1 + 1/region).
    """
    num_cameras = system.Hpp.shape[0]
    num_points = system.Hll.shape[0]

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_blocks(system.Hll, region)
    g_cam, g_pt = system.g_cam, system.g_pt
    W = system.W

    d_cam = d_pt = None
    if mixed_precision:
        # Jacobi (scale-then-cast) equilibration: BA Jacobian columns span
        # ~6 orders of magnitude (rotation vs focal), far beyond bf16's
        # dynamic range.  Solve the symmetrically scaled system
        # (D S D) x~ = D v with D = diag(H)^-1/2 — unit-diagonal, so the
        # bf16-cast coupling operands are well-ranged — and unscale the
        # solution at the end.
        d_cam = jax.lax.rsqrt(jnp.diagonal(Hpp_d, axis1=-2, axis2=-1))
        d_pt = jax.lax.rsqrt(jnp.diagonal(Hll_d, axis1=-2, axis2=-1))
        Hpp_d = Hpp_d * d_cam[:, :, None] * d_cam[:, None, :]
        Hll_d = Hll_d * d_pt[:, :, None] * d_pt[:, None, :]
        g_cam = g_cam * d_cam
        g_pt = g_pt * d_pt
        bf = jnp.bfloat16
        if compute_kind == ComputeKind.EXPLICIT:
            W = (
                W
                * jnp.take(d_cam, cam_idx, axis=0)[:, :, None]
                * jnp.take(d_pt, pt_idx, axis=0)[:, None, :]
            ).astype(bf)
        else:
            Jc = (Jc * jnp.take(d_cam, cam_idx, axis=0)[:, None, :]).astype(bf)
            Jp = (Jp * jnp.take(d_pt, pt_idx, axis=0)[:, None, :]).astype(bf)

    Hll_inv = block_inv(Hll_d)
    if preconditioner == PreconditionerKind.SCHUR_DIAG:
        # True Schur block diagonal: Hpp_c - sum_e W_e Hll^-1 W_e^T,
        # one segment_sum of per-edge [cd,cd] blocks (see
        # common.PreconditionerKind).  W_e from storage (EXPLICIT) or
        # recomputed (IMPLICIT); Hll_inv gathered per edge.
        if compute_kind == ComputeKind.EXPLICIT:
            W_e = W
        else:
            W_e = (jnp.einsum("eoc,eop->ecp", Jc, Jp,
                              preferred_element_type=jnp.float32)
                   if mixed_precision else
                   jnp.einsum("eoc,eop->ecp", Jc, Jp, precision=HI))
        W_e = W_e.astype(Hpp_d.dtype)  # bf16 operands -> full precision
        Hinv_e = jnp.take(Hll_inv, pt_idx, axis=0)  # [nE, pd, pd]
        corr_e = jnp.einsum("ecp,epq,edq->ecd", W_e, Hinv_e, W_e,
                            precision=HI)
        corr = jax.ops.segment_sum(corr_e, cam_idx,
                                   num_segments=num_cameras,
                                   indices_are_sorted=cam_sorted)
        if axis_name is not None:
            corr = jax.lax.psum(corr, axis_name)
        # In exact arithmetic Hpp_d - corr is SPD (a principal block of
        # S), but rounding (especially equilibrated bf16 operands) can
        # push a weakly-determined camera block indefinite -> Cholesky
        # NaN.  Fall back to the Hpp preconditioner for exactly those
        # blocks instead of letting NaN masquerade as convergence.
        minv_hpp = block_inv(Hpp_d)
        minv_sd = block_inv(Hpp_d - corr.astype(Hpp_d.dtype))
        bad = ~jnp.all(jnp.isfinite(minv_sd), axis=(-2, -1), keepdims=True)
        Minv = jnp.where(bad, minv_hpp, minv_sd)
    else:
        Minv = block_inv(Hpp_d)  # reference block-Jacobi (Hpp)

    hpl, hlp = make_coupling_matvecs(
        W, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
        compute_kind, axis_name, mixed_precision=mixed_precision,
        cam_sorted=cam_sorted,
    )

    def s_matvec(p: jax.Array) -> jax.Array:
        # S p = Hpp_d p - Hpl Hll_d^-1 Hlp p     [2 psums]
        t = block_matvec(Hll_inv, hlp(p))
        return block_matvec(Hpp_d, p) - hpl(t)

    # Reduced RHS v = g_cam - Hpl Hll^-1 g_pt    [1 psum]
    v = g_cam - hpl(block_matvec(Hll_inv, g_pt))

    x, k, rho = _pcg_core(
        s_matvec, lambda r: block_matvec(Minv, r), v,
        max_iter, tol, refuse_ratio, tol_relative)

    # Back-substitute the point update       [1 psum]
    dx_pt = block_matvec(Hll_inv, g_pt - hlp(x))
    if mixed_precision:
        x = x * d_cam  # unscale back to the original variables
        dx_pt = dx_pt * d_pt
    return PCGResult(dx_cam=x, dx_pt=dx_pt, iterations=k, rho=rho)
