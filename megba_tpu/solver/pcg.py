"""Distributed Schur-complement preconditioned conjugate gradients
(feature-major).

TPU-native replacement for the reference's SchurPCGSolver /
ImplicitSchurPCGSolver (src/solver/schur_pcg_solver.cu:598-639,
src/solver/implicit_schur_pcg_solver.cu): the same pipeline —

  1. invert the damped Hll blocks (cublasGmatinvBatched there, row-form
     closed-form adjugates here — core/fm.py);
  2. reduced RHS v = g_cam - Hpl Hll^-1 g_pt     [1 psum]
  3. PCG on S x = v with S = Hpp - Hpl Hll^-1 Hlp, block-Jacobi
     preconditioner M^-1 = Hpp^-1                 [2 psums / iteration]
  4. back-substitute dx_pt = Hll^-1 (g_pt - Hlp x) [1 psum]

— but as one jitted `lax.while_loop` with everything on-device: the
reference's per-iteration host-blocking dot products
(schur_pcg_solver.cu:277-287,368-384) become plain on-device reductions
over replicated vectors, and its NCCL allreduces of the coupling products
(schur_pcg_solver.cu:211-242,325-357,502-509,568-575) become
`jax.lax.psum` of the scatter-add outputs.

The Hpl/Hlp products never materialise a sparse matrix: EXPLICIT mode
uses the per-edge W rows (gather -> row products -> scatter-add),
IMPLICIT mode recomputes Jc^T (Jp x) from the stored Jacobian rows
(matrix-free, the reference's implicitEMulx / implicitETMulx,
implicit_schur_pcg_solver.cu:20-90).  All per-edge work is row-wise VPU
math over 128-edge lanes (see core/fm.py for the layout rationale);
there is no cuSPARSE analog to port.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.analysis.retrace import note_trace, static_key
from megba_tpu.common import ComputeKind, PrecondKind, PreconditionerKind
from megba_tpu.core.fm import (
    block_inv_fm,
    block_matvec_fm,
    damp_rows_fm,
    gather_fm,
    segsum_fm,
)
from megba_tpu.linear_system.builder import SchurSystem, damp_blocks
from megba_tpu.ops.accum import comp_dot
from megba_tpu.ops.segtiles import DualPlans, seg_expand, seg_reduce
# The preconditioner subsystem (solver/precond.py) owns the operator
# family; block_inv / cam_block_matvec / _schur_diag_precond are
# re-exported here for the historical import path.
from megba_tpu.solver.precond import (  # noqa: F401  (re-exports)
    _schur_diag_precond,
    block_inv,
    cam_block_matvec,
    make_schur_preconditioner,
)

HI = jax.lax.Precision.HIGHEST

# Absolute floor for the relative PCG threshold (guards rho0 == 0).
_TINY_RHO = 1e-30

# Relative-energy floor for the bf16 MXU pipeline's inner solve: the
# bf16-operand matvec resolves residual NORMS down to ~several
# eps_bf16 (eps_bf16 = 2⁻⁸ ≈ 3.9e-3, conditioning-amplified); energies
# are norms squared, so relative thresholds below ~1e-3 (norm ~3e-2 —
# still a conventional inexact-Newton forcing term) ask the inner
# solve for digits the operator does not carry and spin it at its
# noise floor until the breakdown guard fires.  Applied only under
# tol_relative (schur_pcg_solve); measured on small noised BA systems
# 1e-4 still stagnates, 1e-3 runs guard-clean.
_BF16_TOL_FLOOR = 1e-3


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PCGResult:
    """Solve output: the Schur update and diagnostics (feature-major)."""

    dx_cam: jax.Array  # [cd, Nc]
    dx_pt: jax.Array  # [pd, Np]
    iterations: jax.Array  # scalar int32
    rho: jax.Array  # final residual-energy <r, M^-1 r>
    # |<r0, M^-1 r0>| / |<b, M^-1 b>|: how much of the RHS energy the
    # warm start already removed (1.0 for a cold start).  The LM loop
    # records it per iteration (observability/trace.py).
    r0_ratio: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.float32(1.0))
    # Robustness diagnostics (RobustOption.guards): in-loop cold
    # restarts the breakdown guard performed, whether the solve exited
    # flagged (restart budget exhausted), and how many Schur-diagonal
    # preconditioner blocks fell back to the Hpp preconditioner after a
    # Cholesky NaN (0 for the HPP preconditioner).
    breakdowns: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.int32(0))
    broken: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.bool_(False))
    precond_fallback: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.int32(0))


def _dot(a: jax.Array, b: jax.Array) -> jax.Array:
    # Compensated elementwise multiply + two-sum tree (ops/accum.py):
    # stays on the VPU, f64-class accuracy in f32 — alpha/beta from
    # noisy dots stall CG convergence at BAL-Final scale.  Vectors are
    # replicated across shards, so no psum is needed — unlike the
    # reference's per-rank sliced dots + host sum
    # (schur_pcg_solver.cu:277-287).
    return comp_dot(a, b)


# Per-edge coupling contractions shared by the 1-D hlp/hpl closures and
# the 2-D tiled matvec (make_matvec_2d steps 1 and 4): ONE copy of each
# W / Jc-Jp block-row layout (EXPLICIT rows W[a*pd+b]; Jacobian-mode
# rows Jc[o*cd+a], Jp[o*pd+b]) so a layout change cannot silently land
# on only one path.  The three precision hooks come from
# `_edge_precision`: `up` is applied to every stored row before it is
# multiplied (the mixed-precision upcast), `acc` to every per-edge
# PRODUCT before it enters a sum (the bf16 pipeline's f32-accumulation
# upcast), `vec` to the gathered Krylov-vector rows / intermediates
# (the bf16 pipeline's operand downcast).  In f32 and mixed modes `acc`
# and `vec` are identities that emit no ops, so every pre-bf16 program
# lowers byte-identically.


def _ident(x):
    return x


def _edge_precision(mixed_precision: bool, bf16_ops: bool):
    """(up, vec, acc) casts for the per-edge coupling products.

    f32 (default):  multiply f32 x f32, accumulate f32 — all identity.
    mixed:          stored rows are bf16; upcast BEFORE multiplying
                    (f32 x f32 products — the PR-era mixed rung).
    bf16 pipeline:  stored rows stay bf16, the gathered vector rows are
                    downcast to bf16 (`vec`), products run bf16 x bf16
                    (the MXU operand format) and every product is
                    upcast to f32 (`acc`) before the tiny row sums and
                    the edge-axis segment reductions accumulate it —
                    bf16 storage, f32 accumulation.
    """
    if bf16_ops:
        def vec(x):
            return x.astype(jnp.bfloat16)

        def acc(x):
            return x.astype(jnp.float32)

        return _ident, vec, acc
    if mixed_precision:
        def up(x):
            return x.astype(jnp.float32)

        return up, _ident, _ident
    return _ident, _ident, _ident


def _edge_cam_to_pt_explicit(W, pe, cd, pd, up, acc=_ident):
    """W^T applied per edge: [cd, nE] camera rows -> [pd, nE]."""
    return jnp.stack([
        sum(acc(up(W[a * pd + b]) * pe[a]) for a in range(cd))
        for b in range(pd)
    ])


def _edge_pt_to_cam_explicit(W, qe, cd, pd, up, acc=_ident):
    """W applied per edge: [pd, nE] point rows -> [cd, nE]."""
    return jnp.stack([
        sum(acc(up(W[a * pd + b]) * qe[b]) for b in range(pd))
        for a in range(cd)
    ])


def _edge_cam_to_pt_fwd(Jc, Jp, pe, cd, pd, od, up, acc=_ident, vec=_ident):
    """Jp^T (Jc p) per edge via the [od] residual components.

    `vec` re-downcasts the f32-accumulated [od] intermediate before the
    second product under the bf16 pipeline (bf16 operands throughout,
    f32 sums only)."""
    u = [vec(sum(acc(up(Jc[o * cd + a]) * pe[a]) for a in range(cd)))
         for o in range(od)]
    return jnp.stack([
        sum(acc(up(Jp[o * pd + b]) * u[o]) for o in range(od))
        for b in range(pd)
    ])


def _edge_pt_to_cam_fwd(Jc, Jp, qe, cd, pd, od, up, acc=_ident, vec=_ident):
    """Jc^T (Jp q) per edge via the [od] residual components."""
    u = [vec(sum(acc(up(Jp[o * pd + b]) * qe[b]) for b in range(pd)))
         for o in range(od)]
    return jnp.stack([
        sum(acc(up(Jc[o * cd + a]) * u[o]) for o in range(od))
        for a in range(cd)
    ])


def make_coupling_matvecs(
    W: Optional[jax.Array],
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    num_cameras: int,
    num_points: int,
    compute_kind: ComputeKind,
    axis_name: Optional[str] = None,
    mixed_precision: bool = False,
    cam_sorted: bool = False,
    plans: Optional[DualPlans] = None,
    bf16_ops: bool = False,
    bf16_collectives: bool = False,
    fused_kernels: bool = False,
) -> Tuple[Callable[[jax.Array], jax.Array], Callable[[jax.Array], jax.Array]]:
    """Build hpl(q_pt [pd,Np])->[cd,Nc] and hlp(p_cam [cd,Nc])->[pd,Np].

    EXPLICIT mode reads only `W` (per-edge coupling rows [cd*pd, nE]);
    IMPLICIT mode reads only `Jc`/`Jp` rows.  Edge arrays are
    shard-local; outputs are psum-reduced to replicated.

    With `plans` (the TPU fast path) every segment reduction is a
    block-aligned tiled MXU reduction and every vertex->edge expansion a
    tiled one-hot matmul (ops/segtiles.py): `Jc`/`W` live in cam-slot
    order, `Jp` in pt-slot order, and per-edge intermediates hop between
    the orders via the 2-3 row cross permutes — the only non-streaming
    traffic in the whole product.  This replaces the reference's
    cuSPARSE SpMVs / implicitEMulx-ETMulx scatter kernels
    (schur_pcg_solver.cu:315-366, implicit_schur_pcg_solver.cu:20-90).

    `mixed_precision` (BASELINE.md config 5) expects the edge operands to
    be pre-equilibrated and bf16-cast (see schur_pcg_solve); products are
    computed after upcast to float32, so only the stored rows — the PCG's
    bandwidth-dominant traffic — are halved, while Krylov vectors,
    reductions and the preconditioner stay float32.

    `bf16_ops` (SolverOption.bf16) is the rung below: the stored rows
    stay bf16 THROUGH the multiply and the gathered Krylov-vector rows
    are downcast to match — bf16 x bf16 products (the MXU operand
    format, and half the HBM traffic of the edge-expanded transients)
    with every accumulation upcast to f32 first (`_edge_precision`).
    The segment reductions and psums still run on the f32-accumulated
    rows unless `bf16_collectives` ALSO compresses the wire payload to
    bf16 (parallel/mesh.collective_payload_cast) — schur_pcg_solve
    builds the compressed pair only for the S·p matvec the PCG body
    dispatches, never for the once-per-solve RHS/back-substitution
    products.  Requires the XLA (plans=None) lowering.
    """
    up, vec, acc = _edge_precision(mixed_precision, bf16_ops)
    use_fused = (fused_kernels and plans is not None
                 and plans.fused_to_pt is not None
                 and plans.fused_to_cam is not None)
    if (bf16_ops and plans is not None and not use_fused
            and compute_kind != ComputeKind.EXPLICIT):
        raise NotImplementedError(
            "SolverOption.bf16 does not compose with the tiled "
            "coupling kernels in IMPLICIT mode (ops/segtiles."
            "coupling_expand has no bf16 operand path); either lower "
            "with use_tiled=False — flat_solve does this automatically "
            "— or enable SolverOption(fused_kernels=True), whose fused "
            "edge-pipeline kernels carry the bf16 operand tiles")
    from megba_tpu.parallel.mesh import collective_payload_cast

    wire_down, wire_up = collective_payload_cast(
        bf16_collectives and axis_name is not None)

    def psum(x):
        if axis_name is None:
            return x
        return wire_up(jax.lax.psum(wire_down(x), axis_name))

    if use_fused:
        # Fused edge-pipeline dispatch (ops/fused.py): ONE kernel per
        # direction — the Krylov-vector expansion, the coupling
        # contraction, and the segment reduction happen on the same
        # VMEM-resident edge tile.  The coupling rows are permuted into
        # each direction's bucket order ONCE here (outside the matvec
        # closures, so every CG iteration reuses the copies); padding
        # columns are zeroed by the permute, so the kernels need no
        # mask operand.  Off-TPU the same kernel bodies run under
        # Pallas interpret mode — the CPU-lane parity certificate.
        from megba_tpu.ops import fused as _fused

        fp_tp = plans.fused_to_pt
        fp_tc = plans.fused_to_cam
        interp = not _fused.kernels_supported()

        if compute_kind == ComputeKind.EXPLICIT:
            W_tp = _fused.permute_rows(W, fp_tp)
            W_tc = _fused.permute_rows(W, fp_tc)

            def hlp(p_cam: jax.Array) -> jax.Array:
                return psum(_fused.fused_coupling_apply(
                    W_tp, p_cam, fp_tp, w_in_major=True,
                    bf16_operands=bf16_ops, interpret=interp))

            def hpl(q_pt: jax.Array) -> jax.Array:
                return psum(_fused.fused_coupling_apply(
                    W_tc, q_pt, fp_tc, w_in_major=False,
                    bf16_operands=bf16_ops, interpret=interp))

        else:
            # The tiled lowering stores Jp in PT-slot order (the
            # coupling_reduce convention); the fused plans index the
            # CAM-slot stream, so bring Jp over first (one extra row
            # permute per solve, amortised across CG iterations).  The
            # dtype is pinned back: cam.mask is f32 and would silently
            # promote bf16-stored rows.
            Jp_cam = plans.to_cam(Jp).astype(Jp.dtype)
            Jc_tp = _fused.permute_rows(Jc, fp_tp)
            Jp_tp = _fused.permute_rows(Jp_cam, fp_tp)
            Jc_tc = _fused.permute_rows(Jc, fp_tc)
            Jp_tc = _fused.permute_rows(Jp_cam, fp_tc)

            def hlp(p_cam: jax.Array) -> jax.Array:
                return psum(_fused.fused_coupling_apply_implicit(
                    Jc_tp, Jp_tp, p_cam, fp_tp,
                    bf16_operands=bf16_ops, interpret=interp))

            def hpl(q_pt: jax.Array) -> jax.Array:
                return psum(_fused.fused_coupling_apply_implicit(
                    Jp_tc, Jc_tc, q_pt, fp_tc,
                    bf16_operands=bf16_ops, interpret=interp))

        return hpl, hlp

    if plans is not None:
        uk = plans.use_kernels

        if compute_kind == ComputeKind.EXPLICIT:
            cdpd = W.shape[0]

            def hlp(p_cam: jax.Array) -> jax.Array:
                cd = p_cam.shape[0]
                pd = cdpd // cd
                pe = vec(seg_expand(p_cam, plans.cam, uk))  # [cd, nCamSlots]
                te = _edge_cam_to_pt_explicit(W, pe, cd, pd, up, acc)
                return psum(seg_reduce(plans.to_pt(te), plans.pt, uk))

            def hpl(q_pt: jax.Array) -> jax.Array:
                pd = q_pt.shape[0]
                cd = cdpd // pd
                qe = vec(plans.to_cam(
                    seg_expand(q_pt, plans.pt, uk)))  # [pd, nCamSlots]
                te = _edge_pt_to_cam_explicit(W, qe, cd, pd, up, acc)
                return psum(seg_reduce(te, plans.cam, uk))

        else:
            from megba_tpu.ops.segtiles import (
                coupling_expand,
                coupling_reduce,
            )

            ocd, opd = Jc.shape[0], Jp.shape[0]

            def hlp(p_cam: jax.Array) -> jax.Array:
                cd = p_cam.shape[0]
                od = ocd // cd
                pd = opd // od
                # u = Jc p per edge (fused gather+matvec, cam order); the
                # [od] rows hop to pt order; J^T u reduces to points
                # (fused matvec+reduce).  The expanded [cd]/[pd] per-edge
                # rows never touch HBM.
                u = coupling_expand(p_cam, Jc, plans.cam, cd, uk)
                u_pt = plans.to_pt(u)
                return psum(coupling_reduce(Jp, u_pt, plans.pt, pd, uk))

            def hpl(q_pt: jax.Array) -> jax.Array:
                pd = q_pt.shape[0]
                od = opd // pd
                cd = ocd // od
                u = coupling_expand(q_pt, Jp, plans.pt, pd, uk)
                u_cam = plans.to_cam(u)
                return psum(coupling_reduce(Jc, u_cam, plans.cam, cd, uk))

        return hpl, hlp

    if compute_kind == ComputeKind.EXPLICIT:
        cdpd = W.shape[0]
        # cd/pd from the gathered vector shapes at call time.

        def hlp(p_cam: jax.Array) -> jax.Array:
            cd = p_cam.shape[0]
            pd = cdpd // cd
            pe = vec(gather_fm(p_cam, cam_idx))  # [cd, nE]
            te = _edge_cam_to_pt_explicit(W, pe, cd, pd, up, acc)
            return psum(segsum_fm(te, pt_idx, num_points))

        def hpl(q_pt: jax.Array) -> jax.Array:
            pd = q_pt.shape[0]
            cd = cdpd // pd
            qe = vec(gather_fm(q_pt, pt_idx))  # [pd, nE]
            te = _edge_pt_to_cam_explicit(W, qe, cd, pd, up, acc)
            return psum(segsum_fm(te, cam_idx, num_cameras,
                                  indices_are_sorted=cam_sorted))

    else:
        ocd, opd = Jc.shape[0], Jp.shape[0]

        def hlp(p_cam: jax.Array) -> jax.Array:
            cd = p_cam.shape[0]
            od = ocd // cd
            pd = opd // od
            pe = vec(gather_fm(p_cam, cam_idx))
            te = _edge_cam_to_pt_fwd(Jc, Jp, pe, cd, pd, od, up, acc, vec)
            return psum(segsum_fm(te, pt_idx, num_points))

        def hpl(q_pt: jax.Array) -> jax.Array:
            pd = q_pt.shape[0]
            od = opd // pd
            cd = ocd // od
            qe = vec(gather_fm(q_pt, pt_idx))
            te = _edge_pt_to_cam_fwd(Jc, Jp, qe, cd, pd, od, up, acc, vec)
            return psum(segsum_fm(te, cam_idx, num_cameras,
                                  indices_are_sorted=cam_sorted))

    return hpl, hlp


def make_matvec_2d(
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    tile_plan,
    pt_idx: jax.Array,
    Hpp_d: jax.Array,
    Hll_inv: jax.Array,
    num_cameras: int,
    num_points: int,
    compute_kind: ComputeKind,
    axis_name,
    mixed_precision: bool = False,
    bf16_ops: bool = False,
    bf16_collectives: bool = False,
    fused_kernels: bool = False,
):
    """Build the fused 2-D Schur matvec S·p (camera x edge mesh).

    The 1-D matvec's two WORLD-wide psums (solver/pcg.s_matvec via
    make_coupling_matvecs) become subgroup-scoped stages on the
    (EDGE_AXIS, CAM_AXIS) mesh, with the point-shard transfer
    double-buffered against the tile contraction:

      1. camera gather — LOCAL: every edge of device (e, c) touches a
         camera inside tile c (the camera-tile plan routed it there),
         so Jc·p / W·p reads this device's own tile slice of the
         replicated p.  Zero bytes.
      2. point reduction — psum_scatter over CAM_AXIS (each camera
         column takes ownership of one point shard of the partial
         scatter), then psum over the EDGE subgroup: the full [pd, Np]
         all-reduce of the 1-D path shrinks to one (C-1)/C scatter plus
         a 1/C-sized subgroup reduce.
      3. Hll⁻¹ — applied to the OWNED shard only (replicated rows,
         local slice).
      4. tile loop with DOUBLE BUFFERING: the owned point shard rotates
         around the CAM_AXIS ring (C-1 collective_permutes); at step j
         the ppermute fetching shard j+1 is issued BEFORE the
         contraction of shard j (the plan's co-observation-ordered
         bucket of edges touching it), so the ICI transfer of the next
         tile overlaps the MXU contraction of the current one.
      5. camera reduction — psum over the EDGE subgroup of the [cd, Tc]
         tile partials (1/C of the 1-D payload), then one all_gather
         over CAM_AXIS re-replicates the result.

    Every collective of THIS matvec is subgroup-scoped (replica groups
    of size E or C, never E*C — the `ba_2d_w4_f32` canonical program
    pins the census; TWO_LEVEL/MULTILEVEL coarse-correction psums in
    precond_apply still span the full axis tuple — see ARCHITECTURE),
    and the per-iteration bytes moved are strictly below the 1-D
    all-reduce scaling law (analysis/hlo.collective_bytes_moved is the
    model; the budget gate's `collective_bytes_per_sp` axis pins it).
    The CG scalars read replicated values and stay collective-free, as
    on the 1-D mesh.

    Returns a replicated-in/replicated-out `s_matvec(p)` — drop-in for
    the 1-D closure, so guards, forcing, warm starts and every
    preconditioner family compose unchanged.  Under `mixed_precision`
    the contract matches the 1-D path (bf16 edge rows upcast before
    every product, f32 Krylov vectors and accumulation — `p` is f32 by
    construction), but agreement with the 1-D result is only at the
    accuracy of the bf16-rounded operator (~1e-3 on ill-conditioned
    scenes): the per-column summation grouping differs, and a PCG run
    to stagnation resolves the operator's own rounding, not the
    grouping (tests/test_mesh2d.py compose test pins this at 1e-2).

    `bf16_ops` / `bf16_collectives` are the bf16 MXU pipeline
    (SolverOption.bf16 / .bf16_collectives): the per-edge coupling
    products run on bf16 operands with f32 accumulation
    (`_edge_precision`, the same discipline as the 1-D closures), and
    the collective gate additionally casts EVERY payload of this
    matvec's in-body collectives — the camera psum_scatter, both
    edge-subgroup psums, the C-1 ring permutes and the final camera
    all_gather — to bf16 on the wire, halving the already-subgroup-
    scoped `collective_bytes_per_sp` once more.  Both gates off lower
    byte-identically to the PR 14 pipeline.

    `fused_kernels` swaps the RING-STEP contraction (step 4's
    gather -> per-edge product -> camera segsum) for one fused Pallas
    kernel call per step (ops/fused.fused_single_block_apply): the
    rotating point shard is the kernel's single input block and the
    camera tile its single output block, so the per-edge expanded rows
    of each bucket stay VMEM-resident.  Steps 1-3 and 5 (the local
    camera gather, the subgroup collectives, Hll⁻¹) are unchanged —
    the stage-1 point reduction keeps its XLA segsum, honestly
    documented as outside the fused surface.
    """
    edge_axis, cam_axis = axis_name
    C = tile_plan.cam_blocks
    Tc = tile_plan.tile_cams
    Sp = tile_plan.shard_points
    nc_pad = C * Tc
    np_pad = C * Sp
    cdpd = None if W is None else W.shape[0]
    ocd = None if Jc is None else Jc.shape[0]
    opd = None if Jp is None else Jp.shape[0]

    up, vec, pacc = _edge_precision(mixed_precision, bf16_ops)
    from megba_tpu.parallel.mesh import collective_payload_cast

    wire_down, wire_up = collective_payload_cast(bf16_collectives)
    if fused_kernels:
        from megba_tpu.ops import fused as _fused

        fused_interp = not _fused.kernels_supported()

    # Replicated solve quantities, padded once to the tile geometry so
    # tile/shard slices are static-shape.  Zero padding is inert: padded
    # cameras/points have zero coupling rows and are never gathered by a
    # real (unmasked) edge.
    Hpp_pad = jnp.pad(Hpp_d, ((0, nc_pad - num_cameras), (0, 0), (0, 0)))
    Hll_inv_pad = jnp.pad(Hll_inv, ((0, 0), (0, np_pad - num_points)))
    ring = [(i, (i - 1) % C) for i in range(C)]

    @jax.named_scope("megba.matvec_2d")
    def s_matvec(p: jax.Array) -> jax.Array:
        cd = p.shape[0]
        ci = jax.lax.axis_index(cam_axis)
        p_pad = jnp.pad(p, ((0, 0), (0, nc_pad - num_cameras)))
        p_t = jax.lax.dynamic_slice_in_dim(p_pad, ci * Tc, Tc, axis=1)
        # (1) local camera gather + per-edge coupling product.
        pe = vec(gather_fm(p_t, tile_plan.cam_local))  # [cd, nE_loc]
        if compute_kind == ComputeKind.EXPLICIT:
            pd = cdpd // cd
            te = _edge_cam_to_pt_explicit(
                W, pe, cd, pd, up, pacc)  # [pd, nE_loc]
        else:
            od = ocd // cd
            pd = opd // od
            te = _edge_cam_to_pt_fwd(Jc, Jp, pe, cd, pd, od, up, pacc, vec)
        # (2) point reduction: scatter over CAM, reduce over EDGE — the
        # wire casts compress both stage payloads to bf16 under the
        # collective gate (the shard stays compressed between the two).
        t_part = segsum_fm(te, pt_idx, np_pad)
        t_sh = jax.lax.psum_scatter(wire_down(t_part), cam_axis,
                                    scatter_dimension=1, tiled=True)
        t_sh = wire_up(jax.lax.psum(t_sh, edge_axis))  # [pd, Sp] owned shard
        # (3) Hll^-1 on the owned shard.
        hll_sh = jax.lax.dynamic_slice_in_dim(
            Hll_inv_pad, ci * Sp, Sp, axis=1)
        cur = block_matvec_fm(hll_sh, t_sh)
        # (4) double-buffered tile loop: issue the fetch of shard j+1,
        # THEN contract shard j's co-observation bucket.  Under the
        # collective gate the rotating point shard rides the ring as
        # bf16 (each permute moves half the bytes); the contraction
        # consumes it through the same bf16-operand policy as step 1.
        cur = wire_down(cur)
        tile_acc = jnp.zeros((cd, Tc), p.dtype)
        for j in range(C):
            nxt = (jax.lax.ppermute(cur, cam_axis, perm=ring)
                   if j < C - 1 else cur)
            s = (ci + j) % C  # j, C static ints: stays the index dtype
            slot = jax.lax.dynamic_slice_in_dim(
                tile_plan.bucket_slot, s, 1, axis=0)[0]
            ptl = jax.lax.dynamic_slice_in_dim(
                tile_plan.bucket_ptl, s, 1, axis=0)[0]
            mk = jax.lax.dynamic_slice_in_dim(
                tile_plan.bucket_mask, s, 1, axis=0)[0]
            cl = jnp.take(tile_plan.cam_local, slot)
            if fused_kernels:
                # Fused ring step: the shard gather, the coupling
                # product and the camera-tile reduction run in ONE
                # kernel over this step's co-observation bucket.  The
                # mask moves from the gathered vector onto the coupling
                # rows (padding pairs get zero rows — same algebra, and
                # the kernel then needs no mask operand).
                mkd = mk.astype(W.dtype if W is not None else Jc.dtype)
                if compute_kind == ComputeKind.EXPLICIT:
                    Wg = jnp.take(W, slot, axis=1) * mkd
                    step = _fused.fused_single_block_apply(
                        Wg, cur, ptl, cl, out_block=Tc,
                        w_in_major=False, bf16_operands=bf16_ops,
                        interpret=fused_interp)
                else:
                    Jcg = jnp.take(Jc, slot, axis=1)
                    Jpg = jnp.take(Jp, slot, axis=1) * mkd
                    step = _fused.fused_single_block_apply(
                        Jpg, cur, ptl, cl, out_block=Tc,
                        rows_out=Jcg, bf16_operands=bf16_ops,
                        interpret=fused_interp)
                tile_acc = tile_acc + step.astype(p.dtype)
                cur = nxt
                continue
            cur_g = vec(gather_fm(cur, ptl))
            qe = cur_g * mk.astype(cur_g.dtype)  # [pd, Lb]
            if compute_kind == ComputeKind.EXPLICIT:
                Wg = up(jnp.take(W, slot, axis=1))
                contrib = _edge_pt_to_cam_explicit(
                    Wg, qe, cd, pd, _ident, pacc)
            else:
                Jcg = up(jnp.take(Jc, slot, axis=1))
                Jpg = up(jnp.take(Jp, slot, axis=1))
                contrib = _edge_pt_to_cam_fwd(
                    Jcg, Jpg, qe, cd, pd, od, _ident, pacc, vec)
            tile_acc = tile_acc + segsum_fm(contrib.astype(p.dtype), cl, Tc)
            cur = nxt
        # (5) camera reduction: EDGE-subgroup psum of the tile, one
        # all_gather over CAM re-replicates (both payloads wire-cast
        # under the collective gate).
        hpl_t = wire_up(jax.lax.psum(wire_down(tile_acc), edge_axis))
        y_t = cam_block_matvec(
            jax.lax.dynamic_slice_in_dim(Hpp_pad, ci * Tc, Tc, axis=0),
            p_t) - hpl_t
        y = wire_up(jax.lax.all_gather(wire_down(y_t), cam_axis,
                                       axis=1, tiled=True))
        return y[:, :num_cameras]

    return s_matvec


# named_scope: the PCG while_loop (body traced inside this call) carries
# a navigable label in profiler traces — see observability/__init__.py.
@jax.named_scope("megba.pcg_core")
def _pcg_core(matvec, precond, b, max_iter, tol, refuse_ratio, tol_relative,
              x0=None, guard=False, max_restarts=0, fused=True):
    """Preconditioned CG over an arbitrary pytree "vector".

    One implementation of the reference's stopping + refuse semantics
    (|rho| < tol exit, schur_pcg_solver.cu:406-407; rho > refuse_ratio *
    min(rho) -> restore best iterate, :288-296) shared by the Schur
    solver (vector = one array) and the plain full-system solver
    (vector = a (camera, point) pair).  Returns
    (x, iterations, rho, r0_ratio, restarts, broken).

    `guard=True` (RobustOption.guards) arms breakdown detection on the
    Chronopoulos-Gear scalars: a non-finite or sign-flipped gamma
    (rho_new) / delta means the recurrence has left the SPD regime, and
    the guard performs an in-loop COLD RESTART from the current iterate
    — the next two body iterations repurpose the body's single matvec
    slot to (1) recompute the true residual r = b - A x and (2) re-prime
    the recurrence (p = M^-1 r, s = A p, alpha = rho/delta), then CG
    resumes.  At most `max_restarts` restarts; one more breakdown exits
    with `broken=True` and the best iterate.  The matvec stays the only
    collective site and restart iterations use the SAME slot, so the
    per-body-iteration collective census (2 all-reduces for the Schur
    S.p) is unchanged — the `ba_guarded_w2_f32` canonical program pins
    exactly this.  When no breakdown fires every selected value is
    bitwise identical to the unguarded body.

    The body is the Chronopoulos-Gear single-recurrence CG: carrying the
    auxiliary direction s = A p alongside p lets each iteration run as
    ONE pass — four fused axpys (x, r, p, s), one preconditioner apply,
    one matvec, and BOTH compensated dots (<r, u> and <u, w>) computed
    back-to-back on the freshly produced u/w instead of at two separate
    reduction points, with alpha recovered by the scalar recurrence
    alpha = gamma / (delta - beta * gamma / alpha_prev).  Iterates are
    identical to textbook PCG in exact arithmetic; the matvec count is
    k+1 (one extra A·u before the loop primes the recurrence).  The
    matvec stays the only collective site, so the census invariant —
    exactly 2 all-reduces per S·p inside the while body
    (analysis/program_audit.py pass 2) — is unchanged.

    `x0` warm-starts the iteration (r0 = b - A x0; one extra matvec,
    also outside the while body).  `tol_relative` anchors the threshold
    to the RHS energy <b, M^-1 b> — NOT the initial-guess residual
    rho0, which a good warm start drives toward _TINY_RHO and which
    would therefore either exit spuriously after 0 iterations or
    over-solve relative to an already-tiny baseline.  For x0=None the
    two anchors coincide bitwise (r0 = b).

    `fused=False` selects the TEXTBOOK-recurrence body (the bf16 MXU
    pipeline's body): the Chronopoulos-Gear fusion carries s = A·p by
    LINEARITY (s ← w + beta s), and a bf16-operand matvec is slightly
    nonlinear in its input (the vector is rounded to bf16 per apply),
    so the carried s drifts from the true A·p by ~eps_bf16 per
    iteration — measured on small BA systems the fused recurrence
    collapses (negative gamma/delta, garbage iterates) within ~20
    iterations.  The textbook body recomputes s = A·p FRESH each
    iteration: same per-iteration op counts (one matvec, one precond
    apply, two compensated dots) and the matvec stays the only
    collective site (2 all-reduces per S·p in the body — the
    `ba_bf16_w2_f32` canonical program pins it), the dots are merely
    sequential instead of back-to-back.  Warm starts, refuse, guards
    and restarts keep their semantics; a guarded restart costs ONE
    body iteration here (classic CG restarts by refreshing r = b - A x
    and re-seeding p = M⁻¹ r — there is no auxiliary recurrence to
    re-prime).
    """
    tm = jax.tree_util.tree_map

    def tdot(a, c):
        return jax.tree_util.tree_reduce(
            lambda acc, v: acc + v, tm(_dot, a, c))

    def axpy(a, x, y):  # y + a * x, leafwise
        return tm(lambda xi, yi: yi + a * xi, x, y)

    def select(pred, a, c):
        return tm(lambda ai, ci: jnp.where(pred, ai, ci), a, c)

    if x0 is None:
        x_init = tm(jnp.zeros_like, b)
        r0 = b  # x0 = 0 so r0 = b - A x0 = b
        u0 = precond(r0)
        rho0 = tdot(r0, u0)
        rhs_energy = rho0  # r0 IS b: reuse, bitwise-identical threshold
        r0_ratio = jnp.ones_like(rho0)
    else:
        x_init = x0
        r0 = axpy(jnp.asarray(-1.0, jax.tree_util.tree_leaves(b)[0].dtype),
                  matvec(x0), b)
        u0 = precond(r0)
        rho0 = tdot(r0, u0)
        ub = precond(b)
        rhs_energy = tdot(b, ub)
        # Diagnostic first, then the safeguard: a warm start whose
        # residual energy EXCEEDS the RHS energy is a worse start than
        # zero (the trust region moved the damped system out from under
        # the previous step) — fall back to the cold start, which is
        # fully available from the quantities just computed.  The
        # recorded ratio stays raw so the trace shows warm-start quality
        # honestly (values > 1 mean "fell back").
        r0_ratio = jnp.abs(rho0) / jnp.maximum(
            jnp.abs(rhs_energy), jnp.asarray(_TINY_RHO, rho0.dtype))
        use_ws = jnp.abs(rho0) <= jnp.abs(rhs_energy)
        x_init = select(use_ws, x_init, tm(jnp.zeros_like, b))
        r0 = select(use_ws, r0, b)
        u0 = select(use_ws, u0, ub)
        rho0 = jnp.where(use_ws, rho0, rhs_energy)
    # Reference semantics: absolute threshold on rho; tol_relative scales
    # it by the RHS energy, floored so a zero RHS exits immediately
    # instead of iterating into 0/0 NaNs.
    threshold = (
        jnp.maximum(tol * jnp.abs(rhs_energy),
                    jnp.asarray(_TINY_RHO, rho0.dtype))
        if tol_relative else tol
    )

    if not fused:
        return _pcg_core_classic(
            matvec, precond, b, max_iter, threshold, refuse_ratio,
            x_init, r0, u0, rho0, rhs_energy, r0_ratio,
            guard, max_restarts, tdot, axpy, select)

    # Prime the Chronopoulos-Gear recurrence: p0 = u0, s0 = A p0,
    # alpha0 = rho0 / <p0, A p0> — exactly classic CG's first alpha.
    # (Guard the division: u0 = 0 on a zero residual, where the loop
    # below never runs and alpha is never consumed.)
    w0 = matvec(u0)
    delta0 = tdot(u0, w0)
    alpha0 = rho0 / jnp.where(delta0 == 0, jnp.ones_like(delta0), delta0)

    if not guard:
        state0 = (jnp.int32(0), x_init, r0, u0, w0, alpha0, rho0,
                  jnp.abs(rho0), x_init, jnp.bool_(False))

        def cond(state):
            k, _, _, _, _, _, rho, _, _, refused = state
            return (k < max_iter) & (jnp.abs(rho) >= threshold) & (~refused)

        def body(state):
            k, x, r, p, s, alpha, rho, rho_min, x_best, _ = state
            # One fused vector pass: both solution/residual updates...
            x = axpy(alpha, p, x)
            r = axpy(-alpha, s, r)
            # ...then the only preconditioner apply and the only matvec
            # (the sole collective site: 2 psums inside the Schur S·p)...
            u = precond(r)
            w = matvec(u)
            # ...and both compensated dots on the same fresh u/w.
            rho_new = tdot(r, u)
            delta = tdot(u, w)
            beta = rho_new / rho
            alpha = rho_new / (delta - beta * rho_new / alpha)
            p = axpy(beta, p, u)  # u + beta p
            s = axpy(beta, s, w)  # w + beta s == A p, by linearity
            refused = jnp.abs(rho_new) > refuse_ratio * rho_min
            improved = jnp.abs(rho_new) < rho_min
            rho_min = jnp.where(improved, jnp.abs(rho_new), rho_min)
            x_best = select(improved, x, x_best)
            return (k + 1, x, r, p, s, alpha, rho_new, rho_min, x_best,
                    refused)

        (k, x, _, _, _, _, rho, _, x_best, refused) = jax.lax.while_loop(
            cond, body, state0)
        return (select(~refused, x, x_best), k, rho, r0_ratio,
                jnp.int32(0), jnp.bool_(False))

    # ---- guarded body (RobustOption.guards) -----------------------------
    # A 3-mode branchless body: phase 0 = normal CG step, phase 1 = the
    # restart's residual refresh (the matvec slot computes A x and
    # r := b - A x), phase 2 = recurrence re-prime (p = M^-1 r, s = A p,
    # alpha = rho / delta).  Every mode runs the SAME one precond + one
    # matvec, so the body's collective census is identical to the
    # unguarded body; a phase-0 run with no breakdown selects exactly
    # the unguarded values, bitwise.
    threshold_arr = jnp.asarray(threshold, rho0.dtype)
    # Keep-alive rho carried through restart iterations: strictly above
    # the exit threshold so cond cannot fire on a placeholder, finite by
    # construction (|rhs_energy| is, or the solve was empty).
    keepalive = jnp.maximum(jnp.abs(rhs_energy), threshold_arr) * 2.0 + 1.0
    minus_one = jnp.asarray(-1.0, rho0.dtype)

    state0 = (jnp.int32(0), x_init, r0, u0, w0, alpha0, rho0,
              jnp.abs(rho0), x_init, jnp.bool_(False),
              jnp.int32(0), jnp.int32(0), jnp.bool_(False))

    def cond(state):
        k, _, _, _, _, _, rho, _, _, refused, _, _, broken = state
        return ((k < max_iter) & (jnp.abs(rho) >= threshold)
                & (~refused) & (~broken))

    def body(state):
        (k, x, r, p, s, alpha, rho, rho_min, x_best, refused,
         phase, restarts, broken) = state
        advancing = phase == 0
        refresh = phase == 1
        reprime = phase == 2
        # Phase 0 applies the pending CG update; restart phases hold x/r.
        step = jnp.where(advancing, alpha, jnp.zeros_like(alpha))
        x = axpy(step, p, x)
        r = axpy(-step, s, r)
        u = precond(r)
        # The one matvec: A u normally, A x during the residual refresh.
        w = matvec(select(refresh, x, u))
        r = select(refresh, axpy(minus_one, w, b), r)  # b - A x
        rho_new = tdot(r, u)  # garbage during refresh (u is stale): masked
        delta = tdot(u, w)
        beta = rho_new / rho
        alpha_cg = rho_new / (delta - beta * rho_new / alpha)
        alpha_fresh = rho_new / jnp.where(
            delta == 0, jnp.ones_like(delta), delta)
        # Breakdown: the SPD invariants gamma = <r, M^-1 r> >= 0 and
        # delta = <p, A p> >= 0 broke, or the recurrence scalars left
        # the finite range.  Refresh iterations produce no real scalars.
        breakdown = (~refresh) & (
            ~(jnp.isfinite(rho_new) & jnp.isfinite(delta))
            | (rho_new < 0) | (delta < 0))
        enter = breakdown & (restarts < max_restarts)
        broken = broken | (breakdown & (restarts >= max_restarts))
        phase_next = jnp.where(enter, jnp.int32(1),
                               jnp.where(refresh, jnp.int32(2),
                                         jnp.int32(0)))
        restarts = restarts + enter.astype(jnp.int32)
        ok_adv = advancing & ~breakdown
        ok_rep = reprime & ~breakdown
        alpha = jnp.where(ok_rep, alpha_fresh,
                          jnp.where(ok_adv, alpha_cg, alpha))
        rho_next = jnp.where(enter | refresh, keepalive, rho_new)
        p = select(ok_rep, u, select(ok_adv, axpy(beta, p, u), p))
        s = select(ok_rep, w, select(ok_adv, axpy(beta, s, w), s))
        refused = ok_adv & (jnp.abs(rho_new) > refuse_ratio * rho_min)
        improved = ok_adv & (jnp.abs(rho_new) < rho_min)
        rho_min = jnp.where(improved, jnp.abs(rho_new), rho_min)
        x_best = select(improved, x, x_best)
        return (k + 1, x, r, p, s, alpha, rho_next, rho_min, x_best,
                refused, phase_next, restarts, broken)

    (k, x, _, _, _, _, rho, _, x_best, refused, _, restarts,
     broken) = jax.lax.while_loop(cond, body, state0)
    return (select(~refused & ~broken, x, x_best), k, rho, r0_ratio,
            restarts, broken)


def _pcg_core_classic(matvec, precond, b, max_iter, threshold, refuse_ratio,
                      x_init, r0, u0, rho0, rhs_energy, r0_ratio,
                      guard, max_restarts, tdot, axpy, select):
    """The textbook-recurrence PCG body (`_pcg_core(fused=False)`).

    Iterates are textbook PCG: p ← u + beta p, s = A p computed FRESH,
    alpha = rho / <p, s>.  Same per-iteration op census as the fused
    body (one matvec — the only collective site — one precond apply,
    two compensated dots); no priming matvec is needed (there is no
    auxiliary recurrence), so the matvec count is exactly k (+1 per
    warm start / restart refresh).  Stopping, refuse-best-iterate,
    breakdown-guard and restart semantics mirror the fused body; a
    guarded restart is ONE iteration whose matvec slot computes A x
    for the residual refresh r = b - A x, p = M⁻¹ r.

    This body exists for the bf16 MXU pipeline, whose operand-rounded
    matvec is nonlinear at the bf16-eps scale — see _pcg_core's
    docstring for why the fused recurrence collapses there.

    STAGNATION-EXIT semantics (the precision-aware part): a FINITE
    sign flip in the SPD scalars (gamma = <r, M⁻¹r> < 0 or
    delta = <p, A p> < 0) is not treated as a recurrence fault — on a
    bf16-operand operator it is the signature of the iterate reaching
    the operator's resolution (the quadratic forms of an eps_bf16-
    nonlinear apply go indefinite exactly when the residual
    concentrates in directions the rounding can no longer resolve;
    measured: restart-and-retry at that point re-breaks within a few
    iterations and escalates into LM recoveries on perfectly clean
    solves).  The solve instead restores the BEST iterate and exits —
    the same restore-and-stop contract as the reference's refuse
    guard, extended from "rho grew" to "rho left the SPD cone".
    Non-finite scalars (actual poison) keep the full breakdown /
    restart / broken ladder under `guard`.
    """
    def safe_div(num, den):
        return num / jnp.where(den == 0, jnp.ones_like(den), den)

    if not guard:
        state0 = (jnp.int32(0), x_init, r0, u0, rho0,
                  jnp.abs(rho0), x_init, jnp.bool_(False))

        def cond(state):
            k, _, _, _, rho, _, _, refused = state
            return (k < max_iter) & (jnp.abs(rho) >= threshold) & (~refused)

        def body(state):
            k, x, r, p, rho, rho_min, x_best, refused = state
            s = matvec(p)
            delta = tdot(p, s)
            alpha = safe_div(rho, delta)
            x = axpy(alpha, p, x)
            r = axpy(-alpha, s, r)
            u = precond(r)
            rho_new = tdot(r, u)
            beta = safe_div(rho_new, rho)
            p = axpy(beta, p, u)  # u + beta p
            stall = (rho_new < 0) | (delta < 0)
            refused = stall | (jnp.abs(rho_new) > refuse_ratio * rho_min)
            improved = (~stall) & (jnp.abs(rho_new) < rho_min)
            rho_min = jnp.where(improved, jnp.abs(rho_new), rho_min)
            x_best = select(improved, x, x_best)
            return (k + 1, x, r, p, rho_new, rho_min, x_best, refused)

        (k, x, _, _, rho, _, x_best, refused) = jax.lax.while_loop(
            cond, body, state0)
        return (select(~refused, x, x_best), k, rho, r0_ratio,
                jnp.int32(0), jnp.bool_(False))

    # ---- guarded classic body -------------------------------------------
    # Two phases: 0 = normal step, 1 = restart refresh (the matvec slot
    # computes A x; r := b - A x, p := M⁻¹ r — classic CG carries no
    # auxiliary direction, so one refresh iteration fully restarts the
    # recurrence).  Same census per iteration as the unguarded body; a
    # phase-0 run with no breakdown selects the unguarded values.
    threshold_arr = jnp.asarray(threshold, rho0.dtype)
    keepalive = jnp.maximum(jnp.abs(rhs_energy), threshold_arr) * 2.0 + 1.0
    minus_one = jnp.asarray(-1.0, rho0.dtype)

    state0 = (jnp.int32(0), x_init, r0, u0, rho0,
              jnp.abs(rho0), x_init, jnp.bool_(False),
              jnp.int32(0), jnp.int32(0), jnp.bool_(False))

    def cond(state):
        k, _, _, _, rho, _, _, refused, _, _, broken = state
        return ((k < max_iter) & (jnp.abs(rho) >= threshold)
                & (~refused) & (~broken))

    def body(state):
        (k, x, r, p, rho, rho_min, x_best, refused,
         phase, restarts, broken) = state
        advancing = phase == 0
        refresh = phase == 1
        # The one matvec: A p normally, A x during the refresh.
        w = matvec(select(refresh, x, p))
        delta = tdot(p, w)  # garbage during refresh: masked below
        alpha = safe_div(rho, delta)
        step = jnp.where(advancing, alpha, jnp.zeros_like(alpha))
        x_new = axpy(step, p, x)
        r_new = select(refresh, axpy(minus_one, w, b),  # b - A x
                       axpy(-step, w, r))
        u = precond(r_new)
        rho_new = tdot(r_new, u)
        # Two distinct failure signatures (docstring): a FINITE sign
        # flip of the SPD scalars is the bf16 operator's resolution
        # floor — restore-best-and-stop via the refuse exit, no guard
        # event; non-finite scalars are actual poison and ride the
        # breakdown/restart/broken ladder.  A refresh iteration's
        # delta is stale, but its r/u/rho_new are REAL (the refreshed
        # residual) — so only advancing iterations classify.
        finite = jnp.isfinite(rho_new) & jnp.isfinite(delta)
        stall = advancing & finite & ((rho_new < 0) | (delta < 0))
        breakdown = advancing & ~finite
        enter = breakdown & (restarts < max_restarts)
        broken = broken | (breakdown & (restarts >= max_restarts))
        restarts = restarts + enter.astype(jnp.int32)
        phase_next = jnp.where(enter, jnp.int32(1), jnp.int32(0))
        ok_adv = advancing & ~breakdown & ~stall
        x = select(ok_adv, x_new, x)
        r = select(ok_adv | refresh, r_new, r)
        beta = safe_div(rho_new, rho)
        p = select(refresh, u, select(ok_adv, axpy(beta, p, u), p))
        rho_next = jnp.where(enter, keepalive, rho_new)
        refused = stall | (ok_adv
                           & (jnp.abs(rho_new) > refuse_ratio * rho_min))
        improved = ok_adv & (jnp.abs(rho_new) < rho_min)
        rho_min = jnp.where(improved, jnp.abs(rho_new), rho_min)
        x_best = select(improved, x, x_best)
        return (k + 1, x, r, p, rho_next, rho_min, x_best, refused,
                phase_next, restarts, broken)

    (k, x, _, _, rho, _, x_best, refused, _, restarts,
     broken) = jax.lax.while_loop(cond, body, state0)
    return (select(~refused & ~broken, x, x_best), k, rho, r0_ratio,
            restarts, broken)


def plain_pcg_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
    max_iter: int = 100,
    tol: float = 1e-1,
    refuse_ratio: float = 1.0,
    tol_relative: bool = False,
    compute_kind: ComputeKind = ComputeKind.IMPLICIT,
    axis_name: Optional[str] = None,
    mixed_precision: bool = False,
    cam_sorted: bool = False,
    preconditioner: PreconditionerKind = PreconditionerKind.HPP,
    plans: Optional[DualPlans] = None,
    x0: Optional[Tuple[jax.Array, jax.Array]] = None,
    guard: bool = False,
    max_restarts: int = 0,
    precond: PrecondKind = PrecondKind.JACOBI,
    neumann_order: int = 2,
    cluster_plan=None,
    cam_fixed=None,
    smooth_omega: float = 0.0,
    tile_plan=None,
    bf16: bool = False,
    bf16_collectives: bool = False,
    fused_kernels: bool = False,  # accepted for call-site symmetry;
    # validate_options refuses fused_kernels without use_schur, so the
    # full-system path never sees it True.
) -> PCGResult:
    """Solve the damped FULL system H dx = g without Schur reduction.

    `x0` (a (dx_cam, dx_pt) pair) warm-starts the CG iteration; `tol`
    may be a traced scalar (the inexact-LM forcing path passes eta_k^2
    per LM iteration).

    `preconditioner` is accepted for signature parity and ignored: the
    full system's exact block diagonal (Hpp, Hll) IS this solver's
    preconditioner, so both kinds coincide here.  The same goes for the
    `precond` operator family and its knobs (`neumann_order`,
    `cluster_plan`, `cam_fixed`): the stronger Schur operators are
    BA/Schur-path features (validate_options rejects them with
    use_schur=False), accepted here only so the LM loop can call both
    solvers through one signature.

    The path the reference left as `// TODO(Jie Ren)` behind
    `useSchur=false` (base_problem.cpp:112-123) — implemented here: PCG
    over the concatenated (camera, point) unknowns with the block-diagonal
    H as preconditioner, coupling applied by the same matrix-free /
    per-edge-row matvecs as the Schur solver.  Useful when the point
    blocks are ill-conditioned enough that the Schur complement's
    Hll^-1 amplifies error, and as an independent cross-check of the
    Schur pipeline (both solve the same damped normal equations).
    """
    # Retrace sentinel hook (analysis/retrace.py): counts only under an
    # active jax trace — eager calls are not compilations.
    note_trace("solver.plain_pcg", system.g_cam, system.g_pt, Jc, Jp,
               static=static_key(compute_kind, axis_name, preconditioner))
    num_cameras = system.Hpp.shape[0]
    num_points = system.Hll.shape[1]

    if mixed_precision:
        raise NotImplementedError(
            "mixed_precision is only implemented for the Schur solver")
    if bf16 or bf16_collectives:
        raise NotImplementedError(
            "SolverOption.bf16 is only implemented for the Schur solver "
            "(validate_options refuses it with use_schur=False)")

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_rows_fm(system.Hll, region)
    Minv_c = block_inv(Hpp_d)
    Minv_p = block_inv_fm(Hll_d)

    hpl, hlp = make_coupling_matvecs(
        system.W, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
        compute_kind, axis_name, cam_sorted=cam_sorted, plans=plans,
    )

    def h_matvec(x):
        # [Hpp Hpl; Hlp Hll] applied blockwise      [2 psums]
        xc, xp = x
        return (cam_block_matvec(Hpp_d, xc) + hpl(xp),
                hlp(xc) + block_matvec_fm(Hll_d, xp))

    def precond(r):
        rc, rp = r
        return cam_block_matvec(Minv_c, rc), block_matvec_fm(Minv_p, rp)

    (xc, xp), k, rho, r0_ratio, restarts, broken = _pcg_core(
        h_matvec, precond, (system.g_cam, system.g_pt),
        max_iter, tol, refuse_ratio, tol_relative, x0=x0,
        guard=guard, max_restarts=max_restarts)
    return PCGResult(dx_cam=xc, dx_pt=xp, iterations=k, rho=rho,
                     r0_ratio=r0_ratio, breakdowns=restarts, broken=broken)


def schur_pcg_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
    max_iter: int = 100,
    tol: float = 1e-1,
    refuse_ratio: float = 1.0,
    tol_relative: bool = False,
    compute_kind: ComputeKind = ComputeKind.IMPLICIT,
    axis_name: Optional[str] = None,
    mixed_precision: bool = False,
    cam_sorted: bool = False,
    preconditioner: PreconditionerKind = PreconditionerKind.HPP,
    plans: Optional[DualPlans] = None,
    x0: Optional[jax.Array] = None,
    guard: bool = False,
    max_restarts: int = 0,
    precond: PrecondKind = PrecondKind.JACOBI,
    neumann_order: int = 2,
    cluster_plan=None,
    cam_fixed=None,
    smooth_omega: float = 0.0,
    tile_plan=None,
    bf16: bool = False,
    bf16_collectives: bool = False,
    fused_kernels: bool = False,
) -> PCGResult:
    """Solve the damped Schur system for (dx_cam, dx_pt), feature-major.

    Semantics follow the reference (SolverOption defaults common.h:27-33):
    `tol` is the absolute threshold on rho = <r, M^-1 r> (loop exits when
    |rho| < tol, schur_pcg_solver.cu:406-407); `refuse_ratio` is the
    divergence guard — when rho exceeds refuse_ratio * min(rho) the solver
    restores the best iterate and stops (schur_pcg_solver.cu:288-296).
    `region` is the LM trust region; damping multiplies block diagonals by
    (1 + 1/region).

    `x0` ([cd, Nc] rows, original variables) warm-starts the reduced CG
    iteration; `tol` may be a traced scalar (the inexact-LM forcing path
    passes eta_k^2 per LM iteration).

    `precond` selects the preconditioner operator family
    (solver/precond.py): JACOBI (the block diagonal picked by
    `preconditioner`, bitwise the historical solver), NEUMANN
    (`neumann_order` extra S applications per apply), TWO_LEVEL
    (needs the host-planned `cluster_plan` operand —
    ops/segtiles.cached_cluster_plan; `cam_fixed` keeps the coarse
    correction off pinned cameras), or MULTILEVEL (the recursive
    L-level hierarchy; `cluster_plan` is then a DeviceMultiLevelPlan —
    ops/segtiles.cached_multilevel_plan).  `smooth_omega` > 0 smooths
    the level-1 prolongator (smoothed aggregation) for both
    coarse-space kinds.

    `bf16` / `bf16_collectives` (SolverOption.bf16 / .bf16_collectives)
    select the bf16 MXU pipeline: the SAME Jacobi equilibration as
    `mixed_precision` (bf16 needs well-ranged operands either way),
    but the bf16 rows are fed to the products AS bf16 with f32
    accumulation (`_edge_precision`), the block-diagonal preconditioner
    apply runs on a bf16 copy of M⁻¹ with f32 accumulation
    (solver/precond.py), and the collective gate compresses the S·p
    matvec's in-body wire payloads to bf16 — while the reduced RHS,
    the back-substitution and every coarse-space build keep
    full-precision collectives (their hpl/hlp closures are built
    uncompressed below).
    """
    # Retrace sentinel hook (analysis/retrace.py): counts only under an
    # active jax trace — eager calls are not compilations.
    note_trace("solver.schur_pcg", system.g_cam, system.g_pt, Jc, Jp,
               static=static_key(compute_kind, axis_name, mixed_precision,
                                 preconditioner, precond, neumann_order,
                                 smooth_omega, bf16, bf16_collectives,
                                 fused_kernels))
    num_cameras = system.Hpp.shape[0]
    num_points = system.Hll.shape[1]
    pd = int(round(system.Hll.shape[0] ** 0.5))

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_rows_fm(system.Hll, region)
    g_cam, g_pt = system.g_cam, system.g_pt
    W = system.W

    # Both precision rungs equilibrate + bf16-cast the stored rows; they
    # differ only in WHERE the upcast happens (before vs after the
    # multiply — _edge_precision).
    equil = mixed_precision or bf16
    d_cam = d_pt = None
    if equil:
        # Jacobi (scale-then-cast) equilibration: BA Jacobian columns span
        # ~6 orders of magnitude (rotation vs focal), far beyond bf16's
        # dynamic range.  Solve the symmetrically scaled system
        # (D S D) x~ = D v with D = diag(H)^-1/2 — unit-diagonal, so the
        # bf16-cast coupling operands are well-ranged — and unscale the
        # solution at the end.
        cd = Hpp_d.shape[-1]
        dc = jax.lax.rsqrt(jnp.diagonal(Hpp_d, axis1=-2, axis2=-1))  # [Nc, cd]
        Hpp_d = Hpp_d * dc[:, :, None] * dc[:, None, :]
        d_cam = jnp.swapaxes(dc, 0, 1)  # [cd, Nc] rows
        d_pt = jax.lax.rsqrt(jnp.stack(
            [Hll_d[i * (pd + 1)] for i in range(pd)]))  # [pd, Np]
        Hll_d = Hll_d * jnp.stack(
            [d_pt[i] * d_pt[j] for i in range(pd) for j in range(pd)])
        g_cam = g_cam * d_cam
        g_pt = g_pt * d_pt
        bf = jnp.bfloat16
        if plans is not None:
            # Sorted expansions instead of random gathers; Jp's scale
            # rows must be in PT-slot order, like Jp itself.
            dc_e = seg_expand(d_cam, plans.cam, plans.use_kernels)
            dp_e_pt = seg_expand(d_pt, plans.pt, plans.use_kernels)
            dp_e = plans.to_cam(dp_e_pt) if (
                compute_kind == ComputeKind.EXPLICIT) else dp_e_pt
        else:
            dc_e = gather_fm(d_cam, cam_idx)  # [cd, nE]
            dp_e = gather_fm(d_pt, pt_idx)  # [pd, nE]
        if compute_kind == ComputeKind.EXPLICIT:
            W = jnp.stack([
                W[a * pd + b] * dc_e[a] * dp_e[b]
                for a in range(cd) for b in range(pd)
            ]).astype(bf)
        else:
            od = Jc.shape[0] // cd
            Jc = jnp.stack([
                Jc[o * cd + a] * dc_e[a]
                for o in range(od) for a in range(cd)
            ]).astype(bf)
            Jp = jnp.stack([
                Jp[o * pd + b] * dp_e[b]
                for o in range(od) for b in range(pd)
            ]).astype(bf)

    Hll_inv = block_inv_fm(Hll_d)

    hpl, hlp = make_coupling_matvecs(
        W, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
        compute_kind, axis_name, mixed_precision=mixed_precision,
        cam_sorted=cam_sorted, plans=plans, bf16_ops=bf16,
        fused_kernels=fused_kernels,
    )

    if tile_plan is not None:
        # 2-D mesh: the SINGLE matvec site becomes the fused tiled
        # pipeline with subgroup collectives + double-buffered
        # point-shard rotation (make_matvec_2d).  Everything OUTSIDE
        # the PCG body — the reduced RHS, the warm-start residual
        # priming, the back-substitution, the coarse-space builds —
        # keeps the plain hpl/hlp products above (world psums, one per
        # PCG solve, not per iteration), so the preconditioner family
        # and the guards compose unchanged.
        s_matvec = make_matvec_2d(
            W, Jc, Jp, tile_plan, pt_idx, Hpp_d, Hll_inv,
            num_cameras, num_points, compute_kind, axis_name,
            mixed_precision=mixed_precision, bf16_ops=bf16,
            bf16_collectives=bf16_collectives,
            fused_kernels=fused_kernels)
    else:
        if bf16_collectives and axis_name is not None:
            # Compressed coupling pair for the S·p matvec ONLY: the
            # in-body psums carry bf16 payloads while the reduced RHS /
            # back-substitution products below keep the full-precision
            # hpl/hlp (their psums run once per solve, not per
            # iteration — compressing them buys nothing and costs
            # accuracy exactly where the solution is assembled).
            hpl_c, hlp_c = make_coupling_matvecs(
                W, Jc, Jp, cam_idx, pt_idx, num_cameras, num_points,
                compute_kind, axis_name, mixed_precision=mixed_precision,
                cam_sorted=cam_sorted, plans=plans, bf16_ops=bf16,
                bf16_collectives=True, fused_kernels=fused_kernels,
            )
        else:
            hpl_c, hlp_c = hpl, hlp

        def s_matvec(p: jax.Array) -> jax.Array:
            # S p = Hpp_d p - Hpl Hll_d^-1 Hlp p     [2 psums]
            t = block_matvec_fm(Hll_inv, hlp_c(p))
            return cam_block_matvec(Hpp_d, p) - hpl_c(t)

    # Preconditioner operator family (solver/precond.py).  The
    # correction/coarse rows are always accumulated in full precision
    # (any bf16 operands are upcast inside the builds); the only
    # precision flag threaded through is the bf16 pipeline's
    # block-diagonal APPLY (bf16 M⁻¹ copy, f32-accumulated einsum —
    # the coarse cycles smooth with it but assemble/solve their coarse
    # systems in f32).  JACOBI reproduces the historical
    # solver bitwise; `precond_fallback` is the enum-coded per-level
    # fallback count (two-level -> block-Jacobi, SCHUR_DIAG block ->
    # Hpp).
    precond_apply, precond_fallback = make_schur_preconditioner(
        precond, preconditioner, Hpp_d, Hll_inv, W, Jc, Jp,
        cam_idx, pt_idx, num_cameras, compute_kind, axis_name,
        cam_sorted, neumann_order=neumann_order, plans=plans,
        cluster_plan=cluster_plan, cam_fixed=cam_fixed,
        s_matvec=s_matvec, smooth_omega=smooth_omega, bf16=bf16,
        fused_kernels=fused_kernels)

    # Reduced RHS v = g_cam - Hpl Hll^-1 g_pt    [1 psum]
    v = g_cam - hpl(block_matvec_fm(Hll_inv, g_pt))

    if x0 is not None and equil:
        # The CG runs in the symmetrically scaled variables x~ = x / d;
        # bring the (original-variable) warm start over.
        x0 = x0 / d_cam

    if bf16 and tol_relative:
        # Attainable-accuracy floor: a bf16-operand operator cannot
        # resolve relative preconditioned-residual energies below
        # ~eps_bf16² — an Eisenstat-Walker eta driven under the floor
        # (eta_min defaults to 1e-6) would spin the inner solve at its
        # noise floor for the full budget.  Clamp the RELATIVE
        # threshold only; an absolute `tol` has no scale to clamp
        # against (the refuse guard handles stagnation there).
        tol = jnp.maximum(jnp.asarray(tol, v.dtype),
                          jnp.asarray(_BF16_TOL_FLOOR, v.dtype))

    x, k, rho, r0_ratio, restarts, broken = _pcg_core(
        s_matvec, precond_apply, v,
        max_iter, tol, refuse_ratio, tol_relative, x0=x0,
        guard=guard, max_restarts=max_restarts, fused=not bf16)

    # Back-substitute the point update       [1 psum]
    dx_pt = block_matvec_fm(Hll_inv, g_pt - hlp(x))
    if equil:
        x = x * d_cam  # unscale back to the original variables
        dx_pt = dx_pt * d_pt
    return PCGResult(dx_cam=x, dx_pt=dx_pt, iterations=k, rho=rho,
                     r0_ratio=r0_ratio, breakdowns=restarts, broken=broken,
                     precond_fallback=precond_fallback)
