"""Dense reference solver for validation.

Builds the full damped normal-equations matrix from the Schur blocks and
solves it directly — the ground truth the PCG solver is unit-tested
against (SURVEY.md §4c: "Schur/PCG unit tests vs dense np.linalg.solve on
tiny synthetic BA problems").  Test-scale only: O((Nc*cd + Np*pd)^2)
memory.  Consumes the feature-major containers (core/fm.py) and returns
feature-major updates, matching the PCG solvers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from megba_tpu.core.fm import coupling_rows, damp_rows_fm
from megba_tpu.linear_system.builder import SchurSystem, damp_blocks


def dense_filtered_factor(
    A: jax.Array, rel_floor: float
) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
    """Spectrally filtered pseudo-inverse factor of a small symmetric A.

    Eigendecomposes A (replicated, a few hundred dofs — cheap once per
    build) and keeps only eigenvalues above `rel_floor * lambda_max`:
    `solve` then applies A⁺ = Q diag(1/lambda_kept, 0) Qᵀ.  The floor
    serves two masters at once: eigenvalues below ~1e-6·lambda_max are
    under the f32 assembly noise anyway, and near-null directions
    (gauge modes under weak LM damping) must NOT be inverted — the
    two-level preconditioner measurably LOSES to block-Jacobi when the
    coarse solve amplifies modes the Krylov iteration never needed to
    resolve (solver/precond.py has the numbers).  A⁺ is symmetric PSD
    by construction, so the preconditioner built on it stays SPD.

    Returns ((Q, inv_lam), ok): `ok` is False when the spectrum is
    non-finite or has no positive part (assembly produced garbage —
    the fallback ladder's coarse level).
    """
    lam, Q = jnp.linalg.eigh(A)
    lam_max = lam[-1]  # eigh returns ascending eigenvalues
    ok = jnp.all(jnp.isfinite(lam)) & jnp.all(jnp.isfinite(Q)) & (lam_max > 0)
    inv = jnp.where(lam > rel_floor * lam_max, 1.0 / lam,
                    jnp.zeros_like(lam))
    inv = jnp.where(jnp.isfinite(inv), inv, jnp.zeros_like(inv))
    Q = jnp.where(ok, Q, jnp.zeros_like(Q))
    return (Q, inv), ok


def dense_filtered_solve(
    factor: Tuple[jax.Array, jax.Array], b: jax.Array
) -> jax.Array:
    """Apply the filtered pseudo-inverse of `dense_filtered_factor`."""
    Q, inv = factor
    return Q @ (inv * (Q.T @ b))


def dense_reference_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Direct solve of the damped system H dx = g.

    Returns (dx_cam [cd, Nc], dx_pt [pd, Np]).
    """
    Nc, cd, _ = system.Hpp.shape
    pdpd, Np = system.Hll.shape
    pd = int(round(pdpd ** 0.5))
    od = Jc.shape[0] // cd
    n = Nc * cd + Np * pd

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_rows_fm(system.Hll, region)

    H = jnp.zeros((n, n), dtype=system.Hpp.dtype)
    # Diagonal blocks.
    for i in range(Nc):
        H = H.at[i * cd : (i + 1) * cd, i * cd : (i + 1) * cd].set(Hpp_d[i])
    off = Nc * cd
    for j in range(Np):
        blk = Hll_d[:, j].reshape(pd, pd)
        H = H.at[off + j * pd : off + (j + 1) * pd,
                 off + j * pd : off + (j + 1) * pd].set(blk)
    # Coupling: W_e = Jc_e^T Jp_e accumulated at (camera row, point col).
    W = coupling_rows(Jc, Jp, od)  # [cd*pd, nE]
    for e in range(Jc.shape[1]):
        ci = int(cam_idx[e])
        pi = int(pt_idx[e])
        blk = W[:, e].reshape(cd, pd)
        rows = slice(ci * cd, (ci + 1) * cd)
        cols = slice(off + pi * pd, off + (pi + 1) * pd)
        H = H.at[rows, cols].add(blk)
        H = H.at[cols, rows].add(blk.T)

    # Feature-major [d, N] rows flatten to the block order (vertex-major)
    # via the transpose.
    g = jnp.concatenate([
        jnp.swapaxes(system.g_cam, 0, 1).reshape(-1),
        jnp.swapaxes(system.g_pt, 0, 1).reshape(-1),
    ])
    dx = jnp.linalg.solve(H, g)
    dx_cam = jnp.swapaxes(dx[: Nc * cd].reshape(Nc, cd), 0, 1)
    dx_pt = jnp.swapaxes(dx[Nc * cd :].reshape(Np, pd), 0, 1)
    return dx_cam, dx_pt
