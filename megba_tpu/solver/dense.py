"""Dense reference solver for validation.

Builds the full damped normal-equations matrix from the Schur blocks and
solves it directly — the ground truth the PCG solver is unit-tested
against (SURVEY.md §4c: "Schur/PCG unit tests vs dense np.linalg.solve on
tiny synthetic BA problems").  Test-scale only: O((Nc*cd + Np*pd)^2)
memory.  Consumes the feature-major containers (core/fm.py) and returns
feature-major updates, matching the PCG solvers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from megba_tpu.core.fm import coupling_rows, damp_rows_fm
from megba_tpu.linear_system.builder import SchurSystem, damp_blocks


def dense_reference_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Direct solve of the damped system H dx = g.

    Returns (dx_cam [cd, Nc], dx_pt [pd, Np]).
    """
    Nc, cd, _ = system.Hpp.shape
    pdpd, Np = system.Hll.shape
    pd = int(round(pdpd ** 0.5))
    od = Jc.shape[0] // cd
    n = Nc * cd + Np * pd

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_rows_fm(system.Hll, region)

    H = jnp.zeros((n, n), dtype=system.Hpp.dtype)
    # Diagonal blocks.
    for i in range(Nc):
        H = H.at[i * cd : (i + 1) * cd, i * cd : (i + 1) * cd].set(Hpp_d[i])
    off = Nc * cd
    for j in range(Np):
        blk = Hll_d[:, j].reshape(pd, pd)
        H = H.at[off + j * pd : off + (j + 1) * pd,
                 off + j * pd : off + (j + 1) * pd].set(blk)
    # Coupling: W_e = Jc_e^T Jp_e accumulated at (camera row, point col).
    W = coupling_rows(Jc, Jp, od)  # [cd*pd, nE]
    for e in range(Jc.shape[1]):
        ci = int(cam_idx[e])
        pi = int(pt_idx[e])
        blk = W[:, e].reshape(cd, pd)
        rows = slice(ci * cd, (ci + 1) * cd)
        cols = slice(off + pi * pd, off + (pi + 1) * pd)
        H = H.at[rows, cols].add(blk)
        H = H.at[cols, rows].add(blk.T)

    # Feature-major [d, N] rows flatten to the block order (vertex-major)
    # via the transpose.
    g = jnp.concatenate([
        jnp.swapaxes(system.g_cam, 0, 1).reshape(-1),
        jnp.swapaxes(system.g_pt, 0, 1).reshape(-1),
    ])
    dx = jnp.linalg.solve(H, g)
    dx_cam = jnp.swapaxes(dx[: Nc * cd].reshape(Nc, cd), 0, 1)
    dx_pt = jnp.swapaxes(dx[Nc * cd :].reshape(Np, pd), 0, 1)
    return dx_cam, dx_pt
