"""Dense reference solver for validation.

Builds the full damped normal-equations matrix from the Schur blocks and
solves it directly — the ground truth the PCG solver is unit-tested
against (SURVEY.md §4c: "Schur/PCG unit tests vs dense np.linalg.solve on
tiny synthetic BA problems").  Test-scale only: O((Nc*cd + Np*pd)^2)
memory.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from megba_tpu.linear_system.builder import SchurSystem, damp_blocks


def dense_reference_solve(
    system: SchurSystem,
    Jc: jax.Array,
    Jp: jax.Array,
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    region: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Direct solve of the damped system H dx = g; returns (dx_cam, dx_pt)."""
    Nc, cd, _ = system.Hpp.shape
    Np, pd, _ = system.Hll.shape
    n = Nc * cd + Np * pd

    Hpp_d = damp_blocks(system.Hpp, region)
    Hll_d = damp_blocks(system.Hll, region)

    H = jnp.zeros((n, n), dtype=system.Hpp.dtype)
    # Diagonal blocks.
    for i in range(Nc):
        H = H.at[i * cd : (i + 1) * cd, i * cd : (i + 1) * cd].set(Hpp_d[i])
    off = Nc * cd
    for j in range(Np):
        H = H.at[off + j * pd : off + (j + 1) * pd, off + j * pd : off + (j + 1) * pd].set(Hll_d[j])
    # Coupling: W_e = Jc_e^T Jp_e accumulated at (camera row, point col).
    W = jnp.einsum("eoc,eop->ecp", Jc, Jp, precision=jax.lax.Precision.HIGHEST)
    for e in range(Jc.shape[0]):
        ci = int(cam_idx[e])
        pi = int(pt_idx[e])
        rows = slice(ci * cd, (ci + 1) * cd)
        cols = slice(off + pi * pd, off + (pi + 1) * pd)
        H = H.at[rows, cols].add(W[e])
        H = H.at[cols, rows].add(W[e].T)

    g = jnp.concatenate([system.g_cam.reshape(-1), system.g_pt.reshape(-1)])
    dx = jnp.linalg.solve(H, g)
    return dx[: Nc * cd].reshape(Nc, cd), dx[Nc * cd :].reshape(Np, pd)
