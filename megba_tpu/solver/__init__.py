from megba_tpu.solver.pcg import (
    PCGResult,
    block_inv,
    cam_block_matvec,
    plain_pcg_solve,
    schur_pcg_solve,
)
from megba_tpu.solver.dense import dense_reference_solve

__all__ = [
    "PCGResult",
    "block_inv",
    "cam_block_matvec",
    "plain_pcg_solve",
    "schur_pcg_solve",
    "dense_reference_solve",
]
