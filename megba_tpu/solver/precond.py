"""Pluggable Schur-complement preconditioners (the 29.6-iters/LM lever).

Bench history (BENCH_r02-r05) pinned the tol-mode inner solve at ~29.6
PCG iterations per LM step across four rounds: after the fused
Chronopoulos-Gear body and Eisenstat-Walker forcing (PR 4) removed the
outer-loop waste, the BLOCK-JACOBI preconditioner — not the matvec — is
the measured ceiling.  This module makes the preconditioner a pluggable
operator family (`SolverOption.precond`, common.PrecondKind) with three
matrix-free members that all run inside the single fused PCG program:

JACOBI — the extracted baseline: apply the inverted block diagonal
  (damped Hpp, or the true Schur diagonal under
  `PreconditionerKind.SCHUR_DIAG`).  Bitwise identical to the
  pre-subsystem solver.

NEUMANN — truncated Neumann/power-series expansion of S⁻¹ around the
  block diagonal D:  M⁻¹ = Σ_{i=0..k} (I − D⁻¹S)^i D⁻¹, applied by
  Horner recursion (z ← z + D⁻¹(r − S z), k times).  Symmetric by
  construction (each term E^i D⁻¹ is — D and S are), positive definite
  whenever the D-preconditioned spectrum stays in (0, 2) (block-Jacobi
  on damped BA systems clusters it near 1).  Each apply costs k extra
  S applications INSIDE the PCG while body — 2k extra all-reduces per
  iteration when sharded — so it trades communication for iterations
  and must be judged on wall-clock, never iteration counts alone.

TWO_LEVEL — a BA-shaped two-level (multigrid-flavoured) scheme:
  cameras are aggregated into O(sqrt(Nc)) clusters by a greedy
  co-observation-weighted host plan (ops/segtiles.build_cluster_plan,
  cached behind the plan-fingerprint LRU), R is the piecewise-constant
  aggregation over camera blocks (fixed cameras masked out), and the
  coarse operator is the EXACT Galerkin projection of the damped Schur
  complement

      A_c = R S_d Rᵀ = R G,      G = S_d Rᵀ,
      G[n, (J,b)] = (Hpp_d)_n R[n,J] − Σ_{e: cam(e)=n} W_e Hll⁻¹ V_Jᵀ,
      V_{p,I} = Σ_{e: pt(e)=p, cluster(cam(e))=I} W_e,

  assembled once per PCG solve from already-materialised quantities:
  the damped camera blocks, Hll⁻¹, and the per-edge coupling rows W_e
  (read in EXPLICIT mode, recomputed chunk-wise from the stored
  Jacobians in IMPLICIT mode — linear_system.coupling_row_provider /
  coupling_row_gather).  No black-box S applications, no new
  collective kinds: ONE psum each for V and G when sharded, both
  OUTSIDE the PCG while body.  The coarse system (a few hundred
  unknowns) is factored by a small replicated spectrally-FILTERED
  eigendecomposition (solver/dense.dense_filtered_factor — see
  _COARSE_EIG_FLOOR for why near-null modes are dropped, not inverted)
  and the apply is the SYMMETRIZED MULTIPLICATIVE two-level cycle
  (coarse correction + block-Jacobi smoothing + coarse re-correction —
  V(0,1)-cycle with exact-on-the-kept-spectrum coarse solve):

      M⁻¹ = Rᵀ A_c⁻¹ R + Pᵀ D⁻¹ P,     P = I − S_d Rᵀ A_c⁻¹ R

  Because P's S application only ever hits vectors in range(Rᵀ), the
  materialised G = S_d Rᵀ turns both "S applies" of the cycle into
  tiny replicated [cd·Nc, C·cd] matmuls — the per-apply work is two
  coarse triangular solves, two G contractions and one block-diagonal
  smooth: ZERO collectives inside the while body (the
  `ba_twolevel_w2_f32` canonical program pins exactly 2 all-reduces
  per S·p there).  Unlike the ADDITIVE combination D⁻¹ + RᵀA_c⁻¹R
  (which re-widens the spectrum wherever coarse and fine ranges
  overlap — measured 1.5x MORE iterations on the venice bench), the
  multiplicative cycle leaves coarse modes with eigenvalue exactly 1.
  M⁻¹ is SPD: both terms are PSD and their kernels are disjoint
  (P r = r on ker(R), where D⁻¹ is PD).

MULTILEVEL — TWO_LEVEL generalized to a recursive L-level hierarchy:
  the level-1 coarse space is the same co-observation aggregation, and
  every coarser level re-aggregates the previous level's cluster graph
  (host-planned once — ops/segtiles.build_multilevel_plan).  Level 1's
  Galerkin operator/coupling are assembled exactly as TWO_LEVEL's;
  every deeper level's A_{l+1} = R_l A_l R_lᵀ is a tiny replicated
  dense contraction.  The coarse solve is a recursive SYMMETRIC V(1,1)
  cycle (damped block-Jacobi pre-smooth, coarse correction on the true
  residual, post-smooth; smoother weight 1/λmax(D⁻¹A) by power
  iteration so the cycle is SPD on any spectrum), with the dense
  filtered pseudo-inverse ONLY at the coarsest level.  Zero in-body
  collectives, pinned by `ba_multilevel_w2_f32`.

Both coarse-space kinds accept SMOOTHED-AGGREGATION prolongators
(`smooth_omega` > 0): Π = Rᵀ − ω D⁻¹ S_d Rᵀ — the expander-robust
variant.  The already-materialised G₀ = S_d Rᵀ makes the smoothing
correction Y = D⁻¹G₀ one blockwise product; the exact smoothed
Galerkin costs one extra column-blocked S_d·Y pass per build
(_smooth_correction), still outside the PCG while body.

Fallback ladder (extends PR 5's Cholesky-NaN semantics one level up):
a non-finite coarse operator TRUNCATES the cycle at its level —
level 1 degrades to plain block-Jacobi (the cycle becomes EXACTLY the
base apply), a deeper level only drops the sub-hierarchy below it —
and, independently, per camera block, an indefinite SCHUR_DIAG block
falls back to the Hpp preconditioner.  Every level is COUNTED, not
silent: `PCGResult.precond_fallback` carries an enum-coded int32
(low 16 bits block count, high bits a per-level bit-field —
encode/decode below) into `SolveTrace`/`SolveReport`.

Measured (venice-10% synthetic bench, CPU lane, inexact-LM config):
NEUMANN k=1 cuts total PCG iterations 40% (70 -> 42) at 9e-8 relative
cost gap — the run_tests.sh smoke gates on >= 30%.  TWO_LEVEL is
dense-verified exact and cuts the preconditioned condition number
54 -> 4.3 on small systems, but the bench SYNTHETIC's camera graph is
an expander ((base + j*stride) mod Nc observation assignment — no
cluster structure), so its coarse space captures nothing there and
block-Jacobi stays the better default on that lane; it targets
spatially-local real scenes.  See ARCHITECTURE.md "Preconditioner
hierarchy".
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import ComputeKind, PrecondKind, PreconditionerKind
from megba_tpu.core.fm import chunked_edge_reduce, gather_fm
from megba_tpu.linear_system.builder import (
    coupling_row_gather,
    coupling_row_provider,
)
from megba_tpu.solver.dense import dense_filtered_factor, dense_filtered_solve

HI = jax.lax.Precision.HIGHEST

# Per-pair-chunk transient bound for the coarse correction contraction:
# [cd*cd, chunk] rows (~21 MB f32 at the default — same class as the
# Hessian build chunks).
_PAIR_CHUNK = 65_536

# Relative eigenvalue floor of the filtered coarse solve
# (dense.dense_filtered_factor).  Two jobs: (1) eigenvalues under
# ~1e-6·lambda_max are below the f32 assembly noise of A_c; (2) under
# weak LM damping (trust region >= ~1e4 — where the venice trajectory
# spends most accepted iterations) the gauge-like near-null modes of S
# survive into A_c, and INVERTING them amplifies directions the Krylov
# iteration never needed to resolve — measured: unfiltered coarse
# solves cost 66-78 PCG iters/LM vs block-Jacobi's flat ~43 at region
# 1e5-3e5 on the venice-3% bench, flipping the two-level win into a
# loss.  Filtered, those modes fall through to the smoother, which
# treats them exactly as block-Jacobi always has.
_COARSE_EIG_FLOOR = 1e-5

# --------------------------------------------------------------------------
# Per-level fallback encoding (SolveTrace / SolveReport observable)
# --------------------------------------------------------------------------
#
# `precond_fallback` is ONE int32 so the trace layout is unchanged; the
# ladder levels ride fixed radixes:
#   low  16 bits — BLOCK level: camera blocks whose SCHUR_DIAG Cholesky
#                  went NaN and fell back to the Hpp preconditioner;
#   high bits    — COARSE levels, a BIT-FIELD: bit (16 + l - 1) set
#                  when hierarchy coarse level l (1-based; TWO_LEVEL
#                  has exactly level 1) was non-finite and the cycle
#                  truncated there.  TWO_LEVEL's historical encoding —
#                  high half 0/1 — is exactly the 1-coarse-level case
#                  of this scheme, so old traces decode unchanged.

FALLBACK_BLOCK_RADIX = 1 << 16
# int32 sign bit keeps the bit-field at <= 15 coarse levels
# (common.validate_options caps SolverOption.max_levels accordingly).
FALLBACK_MAX_COARSE_LEVELS = 15


def encode_precond_fallback(block_count, coarse_bits=0):
    """Pack the block count + coarse-level bit-field into one int32.

    `coarse_bits` is the per-level bit-field (bit l-1 = coarse level l
    degraded); for a two-level scheme it is simply 0/1."""
    block = jnp.minimum(jnp.asarray(block_count, jnp.int32),
                        FALLBACK_BLOCK_RADIX - 1)
    return (jnp.asarray(coarse_bits, jnp.int32)
            * FALLBACK_BLOCK_RADIX + block)


def decode_precond_fallback(code) -> dict:
    """Unpack a trace code into {'block': n, 'coarse': bits} (host ints).

    `coarse` is the raw per-level bit-field; for two-level traces it is
    0/1 (the historical meaning, unchanged).  Use
    `decode_precond_fallback_levels` for the per-level view."""
    c = int(code)
    return {"block": c % FALLBACK_BLOCK_RADIX,
            "coarse": c // FALLBACK_BLOCK_RADIX}


def decode_precond_fallback_levels(code) -> list:
    """Per-coarse-level degrade flags [level 1, level 2, ...] of one
    trace code — trailing healthy levels are trimmed, so a two-level
    code decodes to [] (healthy) or [True]."""
    bits = int(code) // FALLBACK_BLOCK_RADIX
    out = []
    level = 0
    while bits and level < FALLBACK_MAX_COARSE_LEVELS:
        out.append(bool(bits & 1))
        bits >>= 1
        level += 1
    return out


# --------------------------------------------------------------------------
# Block-diagonal bases (the extracted JACOBI baseline)
# --------------------------------------------------------------------------


def cam_block_matvec(H: jax.Array, x: jax.Array) -> jax.Array:
    """[Nc, d, d] camera blocks times [d, Nc] rows -> [d, Nc] rows."""
    return jnp.einsum("nij,jn->in", H, x, precision=HI)


def cam_block_matvec_bf16(H_bf16: jax.Array, x: jax.Array) -> jax.Array:
    """The bf16-MXU-pipeline block apply: bf16 blocks x bf16 rows with
    f32 accumulation.

    `H_bf16` is a bfloat16 copy of the (equilibrated, unit-diagonal —
    well-ranged by construction) inverted block diagonal; `x` is the
    f32 residual, downcast at the operand boundary.  The contraction
    dtype is forced to float32 via `preferred_element_type` — on TPU
    this is EXACTLY the native MXU contract (bf16 operands, f32
    accumulator); default precision, not HIGHEST: a multi-pass
    bf16_3x decomposition would re-spend the bandwidth the bf16
    storage just saved.  Returns f32 rows.
    """
    return jnp.einsum("nij,jn->in", H_bf16, x.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)


def block_inv(H: jax.Array) -> jax.Array:
    """Batched inverse of SPD camera blocks [N, d, d] via Cholesky.

    The analog of the reference's cublasGmatinvBatched calls
    (schur_pcg_solver.cu:60-97); stable on the damped SPD blocks.
    Point blocks use the row-form closed-form `core.fm.block_inv_fm`.
    """
    d = H.shape[-1]
    chol = jnp.linalg.cholesky(H)
    eye = jnp.broadcast_to(jnp.eye(d, dtype=H.dtype), H.shape)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return jnp.einsum("nki,nkj->nij", inv_l, inv_l, precision=HI)


@jax.named_scope("megba.schur_diag_precond")
def _schur_diag_precond(
    Hpp_d, Hll_inv, W, Jc, Jp, cam_idx, pt_idx, num_cameras,
    compute_kind, axis_name, cam_sorted, plans=None,
):
    """True Schur block diagonal: Hpp_c - sum_e W_e Hll^-1 W_e^T.

    Chunked over edges (like the Hessian build) so the [cd*cd, chunk]
    correction rows never materialise at full edge scale — the round-1
    [nE, 9, 9] transient that made this preconditioner unusable at
    Final scale is gone.
    """
    cd = Hpp_d.shape[-1]
    pd = int(round(Hll_inv.shape[0] ** 0.5))
    dtype = Hpp_d.dtype
    nE = cam_idx.shape[0]
    od = None if Jc is None else Jc.shape[0] // cd
    rows_of = coupling_row_provider(
        W, Jc, Jp, 0 if od is None else od, compute_kind, dtype,
        plans=plans)

    def body(start, size, accs):
        (corr_a,) = accs
        ci = jax.lax.dynamic_slice_in_dim(cam_idx, start, size)
        pi = jax.lax.dynamic_slice_in_dim(pt_idx, start, size)
        hinv = gather_fm(Hll_inv, pi)  # [pd*pd, size]
        w = rows_of(start, size)  # [cd*pd, size]
        # t[a, q] = sum_p w[a, p] hinv[p, q]
        t = [sum(w[a * pd + p] * hinv[p * pd + q] for p in range(pd))
             for a in range(cd) for q in range(pd)]
        corr = jnp.stack([
            sum(t[a * pd + q] * w[b * pd + q] for q in range(pd))
            for a in range(cd) for b in range(cd)
        ])
        return (corr_a.at[:, ci].add(
            corr, indices_are_sorted=cam_sorted, mode="drop"),)

    (corr_rows,) = chunked_edge_reduce(
        nE, (jnp.zeros((cd * cd, num_cameras), dtype),), body)
    if axis_name is not None:
        corr_rows = jax.lax.psum(corr_rows, axis_name)
    corr = jnp.moveaxis(corr_rows.reshape(cd, cd, num_cameras), -1, 0)
    # In exact arithmetic Hpp_d - corr is SPD (a principal block of S),
    # but rounding (especially equilibrated bf16 operands) can push a
    # weakly-determined camera block indefinite -> Cholesky NaN.  Fall
    # back to the Hpp preconditioner for exactly those blocks instead of
    # letting NaN masquerade as convergence.  The fallback is COUNTED,
    # not silent: the block count rides PCGResult.precond_fallback into
    # the SolveTrace so an indefinite drift shows up in telemetry.
    minv_hpp = block_inv(Hpp_d)
    minv_sd = block_inv(Hpp_d - corr)
    bad = ~jnp.all(jnp.isfinite(minv_sd), axis=(-2, -1), keepdims=True)
    return jnp.where(bad, minv_hpp, minv_sd), jnp.sum(bad).astype(jnp.int32)


# --------------------------------------------------------------------------
# Two-level coarse operator (Galerkin R S_d Rᵀ from materialised blocks)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TwoLevelCoarse:
    """Assembled coarse-space state of one two-level preconditioner.

    `coarse_matrix` [C*cd, C*cd] is the exact Galerkin A_c = R S_d Rᵀ
    (cluster-major unknown ordering: coarse dof (I, a) -> I*cd + a);
    `eig_q`/`eig_inv` its spectrally-filtered pseudo-inverse factor
    (dense.dense_filtered_factor — see _COARSE_EIG_FLOOR for why the
    near-null modes are dropped rather than inverted), `ok` the health
    flag the fallback ladder keys on, `restrict_sel` the [C, Nc]
    fixed-masked aggregation matrix (R at scalar granularity), `G` the
    materialised coarse coupling S_d Rᵀ as [cd, Nc, C, cd] (fine dof
    (a, n) by coarse dof (J, b)).  Exposed as a dataclass so the
    dense-parity property tests can compare `coarse_matrix`/`G`
    against explicitly projected dense operators.
    """

    coarse_matrix: jax.Array
    eig_q: jax.Array  # [C*cd, C*cd] eigenvectors
    eig_inv: jax.Array  # [C*cd] filtered inverse eigenvalues
    ok: jax.Array  # traced bool: coarse factor finite
    restrict_sel: jax.Array  # [C, Nc]
    cluster: jax.Array  # [Nc] int32
    G: jax.Array  # [cd, Nc, C, cd] = S_d Π (Π = prolongator; plain Rᵀ)
    # Smoothed aggregation (smooth_omega > 0): the prolongator becomes
    # Π = Rᵀ − ω Y with Y = D⁻¹ S_d Rᵀ the damped-Jacobi smoothing
    # correction ([cd, Nc, C, cd], fine dof by coarse dof); G and
    # coarse_matrix above are then the SMOOTHED coupling S_d Π and
    # Galerkin Πᵀ S_d Π.  omega == 0 leaves Y None and every field
    # bitwise the PR 7 plain-aggregation state.
    omega: float = 0.0
    Y: Optional[jax.Array] = None


def _smooth_correction(
    Hpp_d, Hll_inv, rows_of, cam_idx, pt_idx, Y, axis_name,
):
    """Z = S_d · Y for the prolongator-correction block columns.

    `Y` [cd, Nc, C, cd] spans the smoothed prolongator's correction
    range; the smoothed Galerkin/coupling need S_d applied to every
    one of its C·cd columns.  Hpp_d·Y is blockwise; the coupling half
    −Hpl Hll⁻¹ Hlp Y runs the two edge-scale passes chunked over BOTH
    edges (bounded per-chunk rows, like every other build) and coarse
    columns (the [pd, Np, mc] incidence transient is the big one — mc
    is capped so it stays ~128 MB f32 at venice scale).  Sharded: one
    psum per column block for the point-incidence sums + one final
    psum for the camera rows — all once per PCG solve, OUTSIDE the PCG
    while body, the collective kind the solver already emits.
    """
    cd = Hpp_d.shape[-1]
    pd = int(round(Hll_inv.shape[0] ** 0.5))
    num_cameras = Hpp_d.shape[0]
    Np = Hll_inv.shape[1]
    dtype = Hpp_d.dtype
    nE = cam_idx.shape[0]
    C = Y.shape[2]
    m = C * cd
    Ym = Y.reshape(cd, num_cameras, m)
    hinv = Hll_inv.reshape(pd, pd, Np)
    mc_cap = max(cd, int(32_000_000 // max(pd * Np, 1)))
    edge_target = max(4096, _PAIR_CHUNK // max(1, min(m, mc_cap) // cd))
    z_cols = []
    for m0 in range(0, m, mc_cap):
        m1 = min(m0 + mc_cap, m)
        mc = m1 - m0
        Yc = jax.lax.slice_in_dim(Ym, m0, m1, axis=2)  # [cd, Nc, mc]

        def ubody(start, size, accs):
            (u_a,) = accs
            ci = jax.lax.dynamic_slice_in_dim(cam_idx, start, size)
            pi = jax.lax.dynamic_slice_in_dim(pt_idx, start, size)
            w = rows_of(start, size)  # [cd*pd, size]
            yg = jnp.take(Yc, ci, axis=1, mode="clip")  # [cd, size, mc]
            rows = jnp.stack([
                sum(w[a * pd + q][:, None] * yg[a] for a in range(cd))
                for q in range(pd)
            ])  # [pd, size, mc] = W_eᵀ Y[cam(e)]
            return (u_a.at[:, pi, :].add(rows, mode="drop"),)

        (U,) = chunked_edge_reduce(
            nE, (jnp.zeros((pd, Np, mc), dtype),), ubody,
            target=edge_target)
        if axis_name is not None:
            U = jax.lax.psum(U, axis_name)
        T = jnp.einsum("qsp,spm->qpm", hinv, U,
                       precision=HI)  # Hll⁻¹ · (Hlp Y)

        def zbody(start, size, accs):
            (z_a,) = accs
            ci = jax.lax.dynamic_slice_in_dim(cam_idx, start, size)
            pi = jax.lax.dynamic_slice_in_dim(pt_idx, start, size)
            w = rows_of(start, size)
            tg = jnp.take(T, pi, axis=1, mode="clip")  # [pd, size, mc]
            rows = jnp.stack([
                sum(w[a * pd + q][:, None] * tg[q] for q in range(pd))
                for a in range(cd)
            ])  # [cd, size, mc] = W_e · (Hll⁻¹ Hlp Y)[pt(e)]
            return (z_a.at[:, ci, :].add(rows, mode="drop"),)

        (Zb,) = chunked_edge_reduce(
            nE, (jnp.zeros((cd, num_cameras, mc), dtype),), zbody,
            target=edge_target)
        z_cols.append(Zb)
    Zcoup = jnp.concatenate(z_cols, axis=2) if len(z_cols) > 1 else z_cols[0]
    if axis_name is not None:
        Zcoup = jax.lax.psum(Zcoup, axis_name)
    Z1 = jnp.einsum("nac,cnJb->anJb", Hpp_d, Y, precision=HI)
    return Z1 - Zcoup.reshape(cd, num_cameras, C, cd)


@jax.named_scope("megba.precond_coarse_build")
def build_two_level_coarse(
    Hpp_d: jax.Array,
    Hll_inv: jax.Array,
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    cluster_plan,
    compute_kind: ComputeKind,
    axis_name: Optional[str] = None,
    cam_fixed: Optional[jax.Array] = None,
    plans=None,
    smooth_omega: float = 0.0,
    Minv: Optional[jax.Array] = None,
    cam_idx: Optional[jax.Array] = None,
    pt_idx: Optional[jax.Array] = None,
    factor: bool = True,
) -> TwoLevelCoarse:
    """Assemble + factor G = S_d Π and A_c = Πᵀ S_d Π.

    Π is the prolongator: the piecewise-constant aggregation Rᵀ when
    `smooth_omega == 0` (the PR 7 operator, bitwise), or the
    SMOOTHED-AGGREGATION Π = Rᵀ − ω D⁻¹ S_d Rᵀ = Rᵀ − ω Y otherwise —
    the already-materialised plain coupling G₀ = S_d Rᵀ makes Y one
    blockwise product, and the exact smoothed Galerkin/coupling
        G = S_d Π = G₀ − ω Z,      A_c = Πᵀ G = R G − ω Yᵀ G,
    cost one extra column-blocked S_d·Y pass (`_smooth_correction`)
    per build.  `Minv` is the smoothing block diagonal D⁻¹ (defaults
    to block_inv(Hpp_d)); `cam_idx`/`pt_idx` (this call's edge streams)
    are required only when smoothing.

    Pure gathers/scatter-adds over the host-planned index arrays
    (ops/segtiles.ClusterPlan) + one small dense eigen-factor; when the
    edge axis is sharded the per-shard V rows are psum-combined BEFORE
    the ec-pair contraction (cross-shard edges of one point are why —
    W_e Hll⁻¹ (ΣV)ᵀ needs the globally-summed V) and the per-shard G
    contributions are psum-combined after it.  Two all-reduces per
    BUILD (once per PCG solve) unsmoothed — plus two per smoothing
    column block — ALL outside the PCG while body, all the collective
    kind the solver already emits.  `factor=False` skips the coarse
    eigendecomposition (the MULTILEVEL hierarchy factors only its
    coarsest level); `ok` then reports finiteness of A_c alone.
    """
    cd = Hpp_d.shape[-1]
    pd = int(round(Hll_inv.shape[0] ** 0.5))
    dtype = Hpp_d.dtype
    num_cameras = Hpp_d.shape[0]
    C = cluster_plan.num_clusters
    n_pc = cluster_plan.n_pc
    od = None if Jc is None else Jc.shape[0] // cd
    rows_of = coupling_row_provider(
        W, Jc, Jp, 0 if od is None else od, compute_kind, dtype,
        plans=plans)
    rows_at = coupling_row_gather(
        W, Jc, Jp, 0 if od is None else od, compute_kind, dtype,
        plans=plans)
    n_edges = cluster_plan.pc_slot.shape[0]

    # V rows [cd*pd, n_pc]: per-(point, cluster) aggregated coupling.
    # Padding / masked edges carry the inert slot n_pc -> dropped (their
    # rows are zero anyway — the Jacobians are mask-multiplied).
    def vbody(start, size, accs):
        (v_a,) = accs
        sl = jax.lax.dynamic_slice_in_dim(cluster_plan.pc_slot, start, size)
        return (v_a.at[:, sl].add(rows_of(start, size), mode="drop"),)

    (V,) = chunked_edge_reduce(
        n_edges, (jnp.zeros((cd * pd, n_pc), dtype),), vbody)
    if axis_name is not None:
        V = jax.lax.psum(V, axis_name)

    # T = V · Hll⁻¹ per incidence (the point block is shared by every
    # incidence of its point; Hll⁻¹ is symmetric, so T's columns double
    # as the Hll⁻¹ Vᵀ blocks the ec contraction needs).
    hinv = gather_fm(Hll_inv, cluster_plan.pc_pt)  # [pd*pd, n_pc]
    T = jnp.stack([
        sum(V[a * pd + p] * hinv[p * pd + q] for p in range(pd))
        for a in range(cd) for q in range(pd)
    ])  # [cd*pd, n_pc]

    # ec-pair contraction: corrG[(a,b), (n,J)] += Σ_q W_e[a,q] T_s[b,q]
    # over the host-enumerated (edge, same-point-slot) pairs — the
    # coupling half of G = S_d Rᵀ, chunked so the [cd*cd, chunk] block
    # rows stay VMEM-sized.  Inert padding pairs scatter to the
    # out-of-range segment Nc*C and are dropped.
    NcC = num_cameras * C

    def gbody(start, size, accs):
        (g_a,) = accs
        le = jax.lax.dynamic_slice_in_dim(cluster_plan.ec_edge, start, size)
        ls = jax.lax.dynamic_slice_in_dim(cluster_plan.ec_slot, start, size)
        sg = jax.lax.dynamic_slice_in_dim(cluster_plan.ec_seg, start, size)
        w = rows_at(le)  # [cd*pd, size]
        t = jnp.take(T, ls, axis=1, mode="clip")  # [cd*pd, size]
        block = jnp.stack([
            sum(w[a * pd + q] * t[b * pd + q] for q in range(pd))
            for a in range(cd) for b in range(cd)
        ])  # [cd*cd, size]
        return (g_a.at[:, sg].add(block, mode="drop"),)

    (corrg_rows,) = chunked_edge_reduce(
        cluster_plan.ec_edge.shape[0],
        (jnp.zeros((cd * cd, NcC), dtype),), gbody, target=_PAIR_CHUNK)
    if axis_name is not None:
        corrg_rows = jax.lax.psum(corrg_rows, axis_name)
    corrg = corrg_rows.reshape(cd, cd, num_cameras, C).transpose(0, 2, 3, 1)

    # Fine half Hpp_d Rᵀ: Hpp is block diagonal, so camera n contributes
    # its own block to coarse column cluster(n) only.  Fixed cameras are
    # excluded from R (their identity blocks would pollute the cluster
    # sums, and the coarse correction must never move a pinned camera);
    # their W rows are already zero, so G's rows/cols there vanish and
    # the cycle degrades to pure block-Jacobi for them.
    sel = (cluster_plan.cluster[None, :]
           == jnp.arange(C, dtype=jnp.int32)[:, None]).astype(dtype)
    if cam_fixed is not None:
        sel = sel * (1.0 - cam_fixed.astype(dtype))[None, :]
    fine = jnp.einsum("nab,Jn->anJb", Hpp_d, sel, precision=HI)
    G = fine - corrg  # [cd, Nc, C, cd] = S_d Rᵀ

    Y = None
    if smooth_omega:
        if cam_idx is None or pt_idx is None:
            raise ValueError(
                "smooth_omega > 0 needs this call's cam_idx/pt_idx edge "
                "streams so S_d can be applied to the smoothing "
                "correction (make_schur_preconditioner passes them)")
        if Minv is None:
            Minv = block_inv(Hpp_d)
        om = jnp.asarray(smooth_omega, dtype)
        # Y = D⁻¹ G₀: the damped-Jacobi smoothing correction.  Its S_d
        # image Z gives the EXACT smoothed coupling and Galerkin:
        #   G = S_d Π = G₀ − ω Z,   A_c = Πᵀ G = R G − ω Yᵀ G.
        Y = jnp.einsum("nac,cnJb->anJb", Minv, G, precision=HI)
        Z = _smooth_correction(Hpp_d, Hll_inv, rows_of, cam_idx, pt_idx,
                               Y, axis_name)
        G = G - om * Z
        A = (jnp.einsum("In,anJb->IaJb", sel, G, precision=HI)
             - om * jnp.einsum("anIc,anJb->IcJb", Y, G, precision=HI)
             ).reshape(C * cd, C * cd)
    else:
        # A_c = R G (Galerkin): tiny replicated contraction.
        A = jnp.einsum("In,anJb->IaJb", sel, G,
                       precision=HI).reshape(C * cd, C * cd)
    A = 0.5 * (A + A.T)  # symmetrise away accumulation-order roundoff
    if not factor:
        # MULTILEVEL consumes A_c as a mid-hierarchy operator — only
        # the coarsest level is factored; `ok` reports assembly health.
        zq = jnp.zeros_like(A)
        return TwoLevelCoarse(
            coarse_matrix=A, eig_q=zq,
            eig_inv=jnp.zeros(A.shape[0], A.dtype),
            ok=jnp.all(jnp.isfinite(A)), restrict_sel=sel,
            cluster=cluster_plan.cluster, G=G,
            omega=smooth_omega, Y=Y)
    # Filtered pseudo-inverse instead of a Cholesky: all-fixed /
    # edge-less clusters (exactly-zero rows) and gauge-like near-null
    # modes both land UNDER the eigenvalue floor and simply receive no
    # coarse correction, rather than NaN-ing the factor or amplifying
    # noise (_COARSE_EIG_FLOOR).
    (Q, inv), ok = dense_filtered_factor(A, _COARSE_EIG_FLOOR)
    return TwoLevelCoarse(coarse_matrix=A, eig_q=Q, eig_inv=inv, ok=ok,
                          restrict_sel=sel, cluster=cluster_plan.cluster,
                          G=G, omega=smooth_omega, Y=Y)


def _restrict(coarse: TwoLevelCoarse, r: jax.Array) -> jax.Array:
    """Πᵀ r: [cd, Nc] fine rows -> [C, cd] coarse residual (Π = Rᵀ
    plain, Rᵀ − ω Y smoothed)."""
    rc = jnp.einsum("In,an->Ia", coarse.restrict_sel, r,
                    precision=HI)  # R r  [C, cd]
    if coarse.Y is not None:
        rc = rc - coarse.omega * jnp.einsum(
            "anJb,an->Jb", coarse.Y, r, precision=HI)
    return rc


def _inject(coarse: TwoLevelCoarse, y: jax.Array) -> jax.Array:
    """Π y: [C, cd] coarse value -> [cd, Nc] fine rows.

    The plain-aggregation part gathers each camera's cluster value and
    re-applies the fixed-camera mask (selᵀ y == gather + mask, without
    materialising selᵀ); the smoothed prolongator subtracts ω Y y."""
    z = jnp.swapaxes(jnp.take(y, coarse.cluster, axis=0), 0, 1)
    z = z * jnp.max(coarse.restrict_sel, axis=0)[None, :]
    if coarse.Y is not None:
        z = z - coarse.omega * jnp.einsum(
            "anJb,Jb->an", coarse.Y, y, precision=HI)
    return z


def _level1_cycle(
    coarse: TwoLevelCoarse,
    coarse_solve: Callable[[jax.Array], jax.Array],
    ok: jax.Array,
    base_apply: Callable[[jax.Array], jax.Array],
    r: jax.Array,
) -> jax.Array:
    """One symmetrized multiplicative cycle at the fine level.

        M⁻¹ r = Π B Πᵀ r + Pᵀ D⁻¹ P r,   P = I − G B Πᵀ

    with G = S_d Π materialised at build time and B = `coarse_solve`
    any SYMMETRIC coarse approximate inverse — the exact filtered A_c⁺
    for the two-level scheme, the recursive level-2 cycle for the
    multilevel hierarchy.  Both "S applies" are [cd·Nc, C·cd]
    replicated contractions: no edge-scale ops, ZERO collectives.
    Degrades bitwise to the plain base apply when `ok` is False (the
    fallback ladder's coarse level); fixed cameras receive exactly the
    base apply by the masked selector.
    """
    rc = _restrict(coarse, r)
    y = coarse_solve(rc)
    z_c = _inject(coarse, y)
    gy = jnp.einsum("anJb,Jb->an", coarse.G, y, precision=HI)  # G y
    # Pre-smoothing residual P r = r − G B Πᵀ r; gated so the ok=False
    # ladder level is EXACTLY base_apply(r), not a perturbed smooth of
    # garbage.
    u = jnp.where(ok, r - gy, r)
    w = base_apply(u)
    # Post-correction: Π B (Gᵀ w)   (Gᵀ w = Πᵀ S_d w).
    v = jnp.einsum("anJb,an->Jb", coarse.G, w, precision=HI)
    z2 = _inject(coarse, coarse_solve(v))
    return jnp.where(ok, z_c + w - z2, w)


def two_level_cycle(
    coarse: TwoLevelCoarse,
    base_apply: Callable[[jax.Array], jax.Array],
    r: jax.Array,
) -> jax.Array:
    """One symmetrized multiplicative two-level cycle ([cd, Nc] rows):
    the `_level1_cycle` with B = the exact spectrally-filtered A_c⁺."""
    C = coarse.restrict_sel.shape[0]
    cd = r.shape[0]

    def solve(rc):
        return dense_filtered_solve(
            (coarse.eig_q, coarse.eig_inv),
            rc.reshape(C * cd)).reshape(C, cd)

    return _level1_cycle(coarse, solve, coarse.ok, base_apply, r)


# --------------------------------------------------------------------------
# Recursive camera-graph hierarchy (MULTILEVEL)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class CoarseLevel:
    """One coarse level of the multilevel hierarchy (levels >= 2).

    Mid-hierarchy levels carry the dense level operator `A`
    ([C_l·cd, C_l·cd]), its spectrally-damped block-Jacobi smoother
    (`D_inv` [C_l, cd, cd] + the damping weight `omega_s` — a traced
    scalar, 1/lambda_max(D⁻¹A) from a fixed-length power iteration at
    build, so the smoothing iteration is contraction-safe on any
    spectrum) and the aggregation `assign` ([C_l]); the COARSEST level
    carries the filtered eigen-factor instead (`eig_q`/`eig_inv`,
    assign None).  `ok` is the level's health flag (operator finite;
    at the coarsest, factor ok too)."""

    A: jax.Array
    ok: jax.Array
    D_inv: Optional[jax.Array] = None
    omega_s: Optional[jax.Array] = None
    assign: Optional[jax.Array] = None
    num_next: int = 0
    eig_q: Optional[jax.Array] = None
    eig_inv: Optional[jax.Array] = None


@dataclasses.dataclass
class MultiLevelCoarse:
    """Assembled state of one L-level preconditioner: the level-1
    Galerkin assembly (edge-scale build, `TwoLevelCoarse` without its
    factor) + the dense coarse chain.  `level_ok[l-1]` gates coarse
    level l's correction — the per-level fallback bit-field rides
    these flags into the trace code."""

    level1: TwoLevelCoarse
    chain: Tuple[CoarseLevel, ...]
    level_ok: Tuple[jax.Array, ...]


def _block_diag_inv(A: jax.Array, C: int, cd: int) -> jax.Array:
    """[C, cd, cd] inverse of the cd-block diagonal of a dense level
    operator; dead blocks (all-fixed / edge-less aggregates — exactly
    zero rows) fall back to identity so the smoother stays finite (the
    residual there is zero anyway, so they contribute nothing)."""
    idx = jnp.arange(C, dtype=jnp.int32)
    blocks = A.reshape(C, cd, C, cd)[idx, :, idx, :]
    inv = block_inv(blocks)
    eye = jnp.broadcast_to(jnp.eye(cd, dtype=A.dtype), inv.shape)
    bad = ~jnp.all(jnp.isfinite(inv), axis=(-2, -1), keepdims=True)
    return jnp.where(bad, eye, inv)


def _smoother_weight(A4: jax.Array, D_inv: jax.Array) -> jax.Array:
    """omega_s = 1 / lambda_max(D⁻¹A) by a fixed 12-step power
    iteration (dense, tiny, once per build): the damped block-Jacobi
    smoothing iteration x += omega_s D⁻¹ r then has spectral radius
    ~<= 1 < 2 on ANY level spectrum, which is exactly the SPD condition
    of the symmetric V(1,1) cycle it smooths inside."""
    C, cd = D_inv.shape[0], D_inv.shape[1]
    v = jnp.ones((C, cd), A4.dtype)
    nrm = jnp.asarray(1.0, A4.dtype)
    for _ in range(12):
        w = jnp.einsum("iab,ib->ia", D_inv,
                       jnp.einsum("iajb,jb->ia", A4, v, precision=HI),
                       precision=HI)
        nrm = jnp.sqrt(jnp.sum(w * w))
        v = w / jnp.maximum(nrm, jnp.asarray(1e-30, A4.dtype))
    om = 1.0 / jnp.maximum(nrm, jnp.asarray(1.0, A4.dtype))
    return jnp.where(jnp.isfinite(om), om, jnp.asarray(1.0, A4.dtype))


@jax.named_scope("megba.precond_coarse_build")
def build_multilevel_coarse(
    Hpp_d: jax.Array,
    Hll_inv: jax.Array,
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    multilevel_plan,
    compute_kind: ComputeKind,
    axis_name: Optional[str] = None,
    cam_fixed: Optional[jax.Array] = None,
    plans=None,
    smooth_omega: float = 0.0,
    Minv: Optional[jax.Array] = None,
    cam_idx: Optional[jax.Array] = None,
    pt_idx: Optional[jax.Array] = None,
) -> MultiLevelCoarse:
    """Assemble the full hierarchy from one host-planned
    ops/segtiles.DeviceMultiLevelPlan.

    Level 1 is the (optionally smoothed) edge-scale Galerkin build
    (`build_two_level_coarse`, unfactored when deeper levels exist);
    every further level l+1 is the PLAIN-aggregation dense Galerkin
        A_{l+1} = R_l A_l R_lᵀ
    over the host-planned assignment — a tiny replicated contraction
    (the cluster counts shrink geometrically), so the hierarchy adds
    ZERO collectives beyond level 1's build psums and nothing at all
    inside the PCG while body.  Only the coarsest level pays the dense
    filtered pseudo-inverse (`dense_filtered_factor`); mid-hierarchy
    levels smooth with their own block-Jacobi diagonal."""
    cplan = multilevel_plan.base
    depth_assign = len(multilevel_plan.assign)
    level1 = build_two_level_coarse(
        Hpp_d, Hll_inv, W, Jc, Jp, cplan, compute_kind,
        axis_name=axis_name, cam_fixed=cam_fixed, plans=plans,
        smooth_omega=smooth_omega, Minv=Minv, cam_idx=cam_idx,
        pt_idx=pt_idx, factor=(depth_assign == 0))
    cd = Hpp_d.shape[-1]
    dtype = Hpp_d.dtype
    A = level1.coarse_matrix
    if depth_assign == 0:
        # Two levels deep: level 1 IS the coarsest — its factor was
        # built above (factor=True), don't factor twice.
        chain = [CoarseLevel(A=A, ok=level1.ok, eig_q=level1.eig_q,
                             eig_inv=level1.eig_inv)]
    else:
        chain = []
        sizes = multilevel_plan.level_sizes
        for i, assign in enumerate(multilevel_plan.assign):
            Cl, Cn = int(sizes[i]), int(sizes[i + 1])
            sel = (assign[None, :] == jnp.arange(
                Cn, dtype=jnp.int32)[:, None]).astype(dtype)
            A4 = A.reshape(Cl, cd, Cl, cd)
            D_inv = _block_diag_inv(A, Cl, cd)
            chain.append(CoarseLevel(
                A=A, ok=jnp.all(jnp.isfinite(A)), D_inv=D_inv,
                omega_s=_smoother_weight(A4, D_inv),
                assign=assign, num_next=Cn))
            G4 = jnp.einsum("iakb,Jk->iaJb", A4, sel,
                            precision=HI)  # A R_lᵀ
            A_next = jnp.einsum("Ii,iaJb->IaJb", sel, G4,
                                precision=HI).reshape(Cn * cd, Cn * cd)
            A = 0.5 * (A_next + A_next.T)
        # Coarsest level: the only dense factor in the hierarchy.
        (Q, inv), okc = dense_filtered_factor(A, _COARSE_EIG_FLOOR)
        chain.append(CoarseLevel(A=A, ok=okc, eig_q=Q, eig_inv=inv))
    # level_ok[l-1] gates coarse level l's correction: a level is
    # usable when its OWN operator assembled finite (the coarsest
    # additionally needs its factor) AND every ancestor is — a bad
    # level makes all deeper levels unreachable, so the bit-field
    # reads as "the cycle truncated here".
    gated = []
    alive = jnp.bool_(True)
    for lvl in chain:
        alive = alive & lvl.ok
        gated.append(alive)
    return MultiLevelCoarse(level1=level1, chain=tuple(chain),
                            level_ok=tuple(gated))


def _chain_solve(chain: Tuple[CoarseLevel, ...], level_ok, i: int,
                 rc: jax.Array) -> jax.Array:
    """Approximate A_{i+1}⁻¹ rc ([C, cd]) by a recursive SYMMETRIC
    V(1,1) cycle over the dense chain: damped block-Jacobi pre-smooth,
    recursive coarse correction on the true residual, damped post-
    smooth.  Static recursion depth (the hierarchy is host-planned),
    all replicated dense work, SPD whenever the smoothing iteration
    contracts — which `omega_s` = 1/lambda_max(D⁻¹A) guarantees.  The
    residual-based form matters: unlike the fine level (where the
    materialised G avoids edge-scale S applies and the coarse solve is
    exact-or-recursive), a MID-hierarchy correction is inexact, and
    re-smoothing its residual is what keeps the cycle's quality close
    to the exact two-level solve instead of degrading with depth."""
    lvl = chain[i]
    C, cd = rc.shape
    if lvl.assign is None:  # coarsest: exact filtered solve
        return dense_filtered_solve(
            (lvl.eig_q, lvl.eig_inv), rc.reshape(C * cd)).reshape(C, cd)
    ok_next = level_ok[i + 1]
    A4 = lvl.A.reshape(C, cd, C, cd)

    def smooth(x):
        return lvl.omega_s * jnp.einsum("iab,ib->ia", lvl.D_inv, x,
                                        precision=HI)

    def amat(x):
        return jnp.einsum("iajb,jb->ia", A4, x, precision=HI)

    z1 = smooth(rc)
    r1 = rc - amat(z1)
    rn = jnp.zeros((lvl.num_next, cd), rc.dtype).at[lvl.assign].add(r1)
    zc = jnp.take(_chain_solve(chain, level_ok, i + 1, rn), lvl.assign,
                  axis=0)  # R_lᵀ B (R_l r1)
    z2 = z1 + jnp.where(ok_next, zc, jnp.zeros_like(zc))
    r2 = rc - amat(z2)
    return z2 + smooth(r2)


def multilevel_cycle(
    mlc: MultiLevelCoarse,
    base_apply: Callable[[jax.Array], jax.Array],
    r: jax.Array,
) -> jax.Array:
    """One recursive L-level V-cycle ([cd, Nc] rows): the fine-level
    symmetrized multiplicative cycle with B = the level-2 recursive
    cycle (or the exact coarse solve when the hierarchy is 2 deep).
    SPD by induction: every level composes Π B Πᵀ + Pᵀ D⁻¹ P from an
    SPD B and a PD smoother, exactly like the two-level proof."""
    cd = r.shape[0]

    def solve(rc):
        return _chain_solve(mlc.chain, mlc.level_ok, 0,
                            rc.reshape(-1, cd))

    return _level1_cycle(mlc.level1, solve, mlc.level_ok[0], base_apply, r)


# --------------------------------------------------------------------------
# Factory
# --------------------------------------------------------------------------


def make_schur_preconditioner(
    kind: PrecondKind,
    block_kind: PreconditionerKind,
    Hpp_d: jax.Array,
    Hll_inv: jax.Array,
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    num_cameras: int,
    compute_kind: ComputeKind,
    axis_name: Optional[str],
    cam_sorted: bool,
    neumann_order: int = 2,
    plans=None,
    cluster_plan=None,
    cam_fixed=None,
    s_matvec: Optional[Callable[[jax.Array], jax.Array]] = None,
    smooth_omega: float = 0.0,
    bf16: bool = False,
    fused_kernels: bool = False,
) -> Tuple[Callable[[jax.Array], jax.Array], jax.Array]:
    """Build the reduced-system preconditioner apply for one solve.

    Returns `(apply, fallback_code)`: `apply(r [cd, Nc]) -> [cd, Nc]`
    runs inside the PCG while body; `fallback_code` is the enum-coded
    per-level fallback count (encode_precond_fallback) for the trace.
    `kind` picks the operator family (PrecondKind), `block_kind` the
    base block diagonal every family smooths with (PreconditionerKind).
    All operands are the damped, already-materialised solve quantities;
    `s_matvec` (the CG's own S·p closure) is required by NEUMANN only.
    `cluster_plan` is a DeviceClusterPlan for TWO_LEVEL, a
    DeviceMultiLevelPlan for MULTILEVEL; `smooth_omega` > 0 turns on
    the smoothed-aggregation prolongator for both coarse-space kinds.

    `bf16` (SolverOption.bf16) stores the inverted block diagonal as a
    bfloat16 copy and applies it through `cam_block_matvec_bf16` (bf16
    operands, f32 accumulation via preferred_element_type) — the base
    apply is the per-iteration bandwidth-heavy operand of every family
    (Nc·cd² block bytes per CG step), and the equilibrated M⁻¹ is
    unit-scale, well inside bf16's range.  The block diagonal itself
    (and the SCHUR_DIAG correction, the coarse Galerkin builds, and
    every coarse solve) is still COMPUTED in f32; only the apply's
    stored operand narrows — the allowed-surface contract the HLO
    auditor pins.

    `fused_kernels` (SolverOption.fused_kernels) replaces the base
    apply's einsum with the fused block-diagonal Pallas kernel
    (ops/fused.fused_block_diag_apply): M⁻¹ is laid out ONCE as
    feature-major [cd², Nc] rows and the apply runs as one kernel pass
    over camera blocks — same bf16-operand / f32-accumulation contract
    as `cam_block_matvec_bf16` when `bf16` is also set.  Every family
    smooths with the fused base apply; coarse builds/solves are
    untouched.
    """
    if block_kind == PreconditionerKind.SCHUR_DIAG:
        Minv, n_bad = _schur_diag_precond(
            Hpp_d, Hll_inv, W, Jc, Jp, cam_idx, pt_idx, num_cameras,
            compute_kind, axis_name, cam_sorted, plans=plans)
    else:
        Minv = block_inv(Hpp_d)  # reference block-Jacobi (Hpp)
        n_bad = jnp.int32(0)

    if fused_kernels:
        from megba_tpu.ops import fused as _fused

        Hrows = _fused.block_diag_rows(
            Minv.astype(jnp.bfloat16) if bf16 else Minv)
        _interp = not _fused.kernels_supported()

        def base_apply(r):
            return _fused.fused_block_diag_apply(
                Hrows, r, bf16_operands=bf16, interpret=_interp)
    elif bf16:
        Minv_bf16 = Minv.astype(jnp.bfloat16)

        def base_apply(r):
            return cam_block_matvec_bf16(Minv_bf16, r)
    else:
        def base_apply(r):
            return cam_block_matvec(Minv, r)

    if kind == PrecondKind.JACOBI:
        return base_apply, encode_precond_fallback(n_bad)

    if kind == PrecondKind.NEUMANN:
        if s_matvec is None:
            raise ValueError("NEUMANN preconditioner needs the S matvec")
        order = int(neumann_order)

        @jax.named_scope("megba.precond_neumann")
        def neumann_apply(r):
            # Horner form of Σ_{i<=k} E^i D⁻¹ r, E = I − D⁻¹S: each
            # step is one S apply (the 2-psum product) + one block
            # solve.  k is static — the unrolled chain lives inside the
            # fused while body.
            z = base_apply(r)
            for _ in range(order):
                z = z + base_apply(r - s_matvec(z))
            return z

        return neumann_apply, encode_precond_fallback(n_bad)

    if kind not in (PrecondKind.TWO_LEVEL,
                    PrecondKind.MULTILEVEL):  # pragma: no cover - closed
        raise ValueError(f"unknown precond kind {kind}")
    if cluster_plan is None:
        raise ValueError(
            f"precond={kind.name} needs a camera-cluster plan operand; "
            "the flat_solve lowering builds one automatically "
            "(ops/segtiles.cached_cluster_plan / cached_multilevel_plan)"
            " — direct schur_pcg_solve callers must pass cluster_plan=")

    if kind == PrecondKind.TWO_LEVEL:
        coarse = build_two_level_coarse(
            Hpp_d, Hll_inv, W, Jc, Jp, cluster_plan, compute_kind,
            axis_name=axis_name, cam_fixed=cam_fixed, plans=plans,
            smooth_omega=smooth_omega, Minv=Minv, cam_idx=cam_idx,
            pt_idx=pt_idx)

        @jax.named_scope("megba.precond_two_level")
        def two_level_apply(r):
            return two_level_cycle(coarse, base_apply, r)

        fallback = encode_precond_fallback(
            n_bad, jnp.where(coarse.ok, jnp.int32(0), jnp.int32(1)))
        return two_level_apply, fallback

    mlc = build_multilevel_coarse(
        Hpp_d, Hll_inv, W, Jc, Jp, cluster_plan, compute_kind,
        axis_name=axis_name, cam_fixed=cam_fixed, plans=plans,
        smooth_omega=smooth_omega, Minv=Minv, cam_idx=cam_idx,
        pt_idx=pt_idx)

    @jax.named_scope("megba.precond_multilevel")
    def multilevel_apply(r):
        return multilevel_cycle(mlc, base_apply, r)

    # Per-level bit-field: bit l-1 set when coarse level l's correction
    # is out of the cycle (its operator — or an ancestor's — degraded).
    bits = jnp.int32(0)
    for i, ok_l in enumerate(mlc.level_ok):
        bits = bits + jnp.where(ok_l, jnp.int32(0), jnp.int32(1 << i))
    return multilevel_apply, encode_precond_fallback(n_bad, bits)


__all__ = [
    "FALLBACK_BLOCK_RADIX",
    "FALLBACK_MAX_COARSE_LEVELS",
    "CoarseLevel",
    "MultiLevelCoarse",
    "TwoLevelCoarse",
    "block_inv",
    "build_multilevel_coarse",
    "build_two_level_coarse",
    "cam_block_matvec",
    "cam_block_matvec_bf16",
    "decode_precond_fallback",
    "decode_precond_fallback_levels",
    "encode_precond_fallback",
    "make_schur_preconditioner",
    "multilevel_cycle",
    "two_level_cycle",
    "_schur_diag_precond",
]
