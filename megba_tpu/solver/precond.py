"""Pluggable Schur-complement preconditioners (the 29.6-iters/LM lever).

Bench history (BENCH_r02-r05) pinned the tol-mode inner solve at ~29.6
PCG iterations per LM step across four rounds: after the fused
Chronopoulos-Gear body and Eisenstat-Walker forcing (PR 4) removed the
outer-loop waste, the BLOCK-JACOBI preconditioner — not the matvec — is
the measured ceiling.  This module makes the preconditioner a pluggable
operator family (`SolverOption.precond`, common.PrecondKind) with three
matrix-free members that all run inside the single fused PCG program:

JACOBI — the extracted baseline: apply the inverted block diagonal
  (damped Hpp, or the true Schur diagonal under
  `PreconditionerKind.SCHUR_DIAG`).  Bitwise identical to the
  pre-subsystem solver.

NEUMANN — truncated Neumann/power-series expansion of S⁻¹ around the
  block diagonal D:  M⁻¹ = Σ_{i=0..k} (I − D⁻¹S)^i D⁻¹, applied by
  Horner recursion (z ← z + D⁻¹(r − S z), k times).  Symmetric by
  construction (each term E^i D⁻¹ is — D and S are), positive definite
  whenever the D-preconditioned spectrum stays in (0, 2) (block-Jacobi
  on damped BA systems clusters it near 1).  Each apply costs k extra
  S applications INSIDE the PCG while body — 2k extra all-reduces per
  iteration when sharded — so it trades communication for iterations
  and must be judged on wall-clock, never iteration counts alone.

TWO_LEVEL — a BA-shaped two-level (multigrid-flavoured) scheme:
  cameras are aggregated into O(sqrt(Nc)) clusters by a greedy
  co-observation-weighted host plan (ops/segtiles.build_cluster_plan,
  cached behind the plan-fingerprint LRU), R is the piecewise-constant
  aggregation over camera blocks (fixed cameras masked out), and the
  coarse operator is the EXACT Galerkin projection of the damped Schur
  complement

      A_c = R S_d Rᵀ = R G,      G = S_d Rᵀ,
      G[n, (J,b)] = (Hpp_d)_n R[n,J] − Σ_{e: cam(e)=n} W_e Hll⁻¹ V_Jᵀ,
      V_{p,I} = Σ_{e: pt(e)=p, cluster(cam(e))=I} W_e,

  assembled once per PCG solve from already-materialised quantities:
  the damped camera blocks, Hll⁻¹, and the per-edge coupling rows W_e
  (read in EXPLICIT mode, recomputed chunk-wise from the stored
  Jacobians in IMPLICIT mode — linear_system.coupling_row_provider /
  coupling_row_gather).  No black-box S applications, no new
  collective kinds: ONE psum each for V and G when sharded, both
  OUTSIDE the PCG while body.  The coarse system (a few hundred
  unknowns) is factored by a small replicated spectrally-FILTERED
  eigendecomposition (solver/dense.dense_filtered_factor — see
  _COARSE_EIG_FLOOR for why near-null modes are dropped, not inverted)
  and the apply is the SYMMETRIZED MULTIPLICATIVE two-level cycle
  (coarse correction + block-Jacobi smoothing + coarse re-correction —
  V(0,1)-cycle with exact-on-the-kept-spectrum coarse solve):

      M⁻¹ = Rᵀ A_c⁻¹ R + Pᵀ D⁻¹ P,     P = I − S_d Rᵀ A_c⁻¹ R

  Because P's S application only ever hits vectors in range(Rᵀ), the
  materialised G = S_d Rᵀ turns both "S applies" of the cycle into
  tiny replicated [cd·Nc, C·cd] matmuls — the per-apply work is two
  coarse triangular solves, two G contractions and one block-diagonal
  smooth: ZERO collectives inside the while body (the
  `ba_twolevel_w2_f32` canonical program pins exactly 2 all-reduces
  per S·p there).  Unlike the ADDITIVE combination D⁻¹ + RᵀA_c⁻¹R
  (which re-widens the spectrum wherever coarse and fine ranges
  overlap — measured 1.5x MORE iterations on the venice bench), the
  multiplicative cycle leaves coarse modes with eigenvalue exactly 1.
  M⁻¹ is SPD: both terms are PSD and their kernels are disjoint
  (P r = r on ker(R), where D⁻¹ is PD).

Fallback ladder (extends PR 5's Cholesky-NaN semantics one level up):
a non-finite coarse spectrum degrades TWO_LEVEL to plain block-Jacobi
(the cycle becomes EXACTLY the base apply), and — independently, per
camera block — an indefinite SCHUR_DIAG block falls back to the Hpp
preconditioner.  Both levels are COUNTED, not silent:
`PCGResult.precond_fallback` carries an enum-coded per-level count
(encode/decode below) into `SolveTrace`/`SolveReport`.

Measured (venice-10% synthetic bench, CPU lane, inexact-LM config):
NEUMANN k=1 cuts total PCG iterations 40% (70 -> 42) at 9e-8 relative
cost gap — the run_tests.sh smoke gates on >= 30%.  TWO_LEVEL is
dense-verified exact and cuts the preconditioned condition number
54 -> 4.3 on small systems, but the bench SYNTHETIC's camera graph is
an expander ((base + j*stride) mod Nc observation assignment — no
cluster structure), so its coarse space captures nothing there and
block-Jacobi stays the better default on that lane; it targets
spatially-local real scenes.  See ARCHITECTURE.md "Preconditioner
hierarchy".
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from megba_tpu.common import ComputeKind, PrecondKind, PreconditionerKind
from megba_tpu.core.fm import chunked_edge_reduce, gather_fm
from megba_tpu.linear_system.builder import (
    coupling_row_gather,
    coupling_row_provider,
)
from megba_tpu.solver.dense import dense_filtered_factor, dense_filtered_solve

HI = jax.lax.Precision.HIGHEST

# Per-pair-chunk transient bound for the coarse correction contraction:
# [cd*cd, chunk] rows (~21 MB f32 at the default — same class as the
# Hessian build chunks).
_PAIR_CHUNK = 65_536

# Relative eigenvalue floor of the filtered coarse solve
# (dense.dense_filtered_factor).  Two jobs: (1) eigenvalues under
# ~1e-6·lambda_max are below the f32 assembly noise of A_c; (2) under
# weak LM damping (trust region >= ~1e4 — where the venice trajectory
# spends most accepted iterations) the gauge-like near-null modes of S
# survive into A_c, and INVERTING them amplifies directions the Krylov
# iteration never needed to resolve — measured: unfiltered coarse
# solves cost 66-78 PCG iters/LM vs block-Jacobi's flat ~43 at region
# 1e5-3e5 on the venice-3% bench, flipping the two-level win into a
# loss.  Filtered, those modes fall through to the smoother, which
# treats them exactly as block-Jacobi always has.
_COARSE_EIG_FLOOR = 1e-5

# --------------------------------------------------------------------------
# Per-level fallback encoding (SolveTrace / SolveReport observable)
# --------------------------------------------------------------------------
#
# `precond_fallback` is ONE int32 so the trace layout is unchanged; the
# two ladder levels ride fixed radixes:
#   low  16 bits — BLOCK level: camera blocks whose SCHUR_DIAG Cholesky
#                  went NaN and fell back to the Hpp preconditioner;
#   high bits    — COARSE level: 1 when the two-level coarse factor was
#                  non-finite and the apply degraded to block-Jacobi.

FALLBACK_BLOCK_RADIX = 1 << 16


def encode_precond_fallback(block_count, coarse_count=0):
    """Pack per-level fallback counts into one int32 trace code."""
    block = jnp.minimum(jnp.asarray(block_count, jnp.int32),
                        FALLBACK_BLOCK_RADIX - 1)
    return (jnp.asarray(coarse_count, jnp.int32)
            * FALLBACK_BLOCK_RADIX + block)


def decode_precond_fallback(code) -> dict:
    """Unpack a trace code into {'block': n, 'coarse': n} (host ints)."""
    c = int(code)
    return {"block": c % FALLBACK_BLOCK_RADIX,
            "coarse": c // FALLBACK_BLOCK_RADIX}


# --------------------------------------------------------------------------
# Block-diagonal bases (the extracted JACOBI baseline)
# --------------------------------------------------------------------------


def cam_block_matvec(H: jax.Array, x: jax.Array) -> jax.Array:
    """[Nc, d, d] camera blocks times [d, Nc] rows -> [d, Nc] rows."""
    return jnp.einsum("nij,jn->in", H, x, precision=HI)


def block_inv(H: jax.Array) -> jax.Array:
    """Batched inverse of SPD camera blocks [N, d, d] via Cholesky.

    The analog of the reference's cublasGmatinvBatched calls
    (schur_pcg_solver.cu:60-97); stable on the damped SPD blocks.
    Point blocks use the row-form closed-form `core.fm.block_inv_fm`.
    """
    d = H.shape[-1]
    chol = jnp.linalg.cholesky(H)
    eye = jnp.broadcast_to(jnp.eye(d, dtype=H.dtype), H.shape)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return jnp.einsum("nki,nkj->nij", inv_l, inv_l, precision=HI)


@jax.named_scope("megba.schur_diag_precond")
def _schur_diag_precond(
    Hpp_d, Hll_inv, W, Jc, Jp, cam_idx, pt_idx, num_cameras,
    compute_kind, axis_name, cam_sorted, plans=None,
):
    """True Schur block diagonal: Hpp_c - sum_e W_e Hll^-1 W_e^T.

    Chunked over edges (like the Hessian build) so the [cd*cd, chunk]
    correction rows never materialise at full edge scale — the round-1
    [nE, 9, 9] transient that made this preconditioner unusable at
    Final scale is gone.
    """
    cd = Hpp_d.shape[-1]
    pd = int(round(Hll_inv.shape[0] ** 0.5))
    dtype = Hpp_d.dtype
    nE = cam_idx.shape[0]
    od = None if Jc is None else Jc.shape[0] // cd
    rows_of = coupling_row_provider(
        W, Jc, Jp, 0 if od is None else od, compute_kind, dtype,
        plans=plans)

    def body(start, size, accs):
        (corr_a,) = accs
        ci = jax.lax.dynamic_slice_in_dim(cam_idx, start, size)
        pi = jax.lax.dynamic_slice_in_dim(pt_idx, start, size)
        hinv = gather_fm(Hll_inv, pi)  # [pd*pd, size]
        w = rows_of(start, size)  # [cd*pd, size]
        # t[a, q] = sum_p w[a, p] hinv[p, q]
        t = [sum(w[a * pd + p] * hinv[p * pd + q] for p in range(pd))
             for a in range(cd) for q in range(pd)]
        corr = jnp.stack([
            sum(t[a * pd + q] * w[b * pd + q] for q in range(pd))
            for a in range(cd) for b in range(cd)
        ])
        return (corr_a.at[:, ci].add(
            corr, indices_are_sorted=cam_sorted, mode="drop"),)

    (corr_rows,) = chunked_edge_reduce(
        nE, (jnp.zeros((cd * cd, num_cameras), dtype),), body)
    if axis_name is not None:
        corr_rows = jax.lax.psum(corr_rows, axis_name)
    corr = jnp.moveaxis(corr_rows.reshape(cd, cd, num_cameras), -1, 0)
    # In exact arithmetic Hpp_d - corr is SPD (a principal block of S),
    # but rounding (especially equilibrated bf16 operands) can push a
    # weakly-determined camera block indefinite -> Cholesky NaN.  Fall
    # back to the Hpp preconditioner for exactly those blocks instead of
    # letting NaN masquerade as convergence.  The fallback is COUNTED,
    # not silent: the block count rides PCGResult.precond_fallback into
    # the SolveTrace so an indefinite drift shows up in telemetry.
    minv_hpp = block_inv(Hpp_d)
    minv_sd = block_inv(Hpp_d - corr)
    bad = ~jnp.all(jnp.isfinite(minv_sd), axis=(-2, -1), keepdims=True)
    return jnp.where(bad, minv_hpp, minv_sd), jnp.sum(bad).astype(jnp.int32)


# --------------------------------------------------------------------------
# Two-level coarse operator (Galerkin R S_d Rᵀ from materialised blocks)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TwoLevelCoarse:
    """Assembled coarse-space state of one two-level preconditioner.

    `coarse_matrix` [C*cd, C*cd] is the exact Galerkin A_c = R S_d Rᵀ
    (cluster-major unknown ordering: coarse dof (I, a) -> I*cd + a);
    `eig_q`/`eig_inv` its spectrally-filtered pseudo-inverse factor
    (dense.dense_filtered_factor — see _COARSE_EIG_FLOOR for why the
    near-null modes are dropped rather than inverted), `ok` the health
    flag the fallback ladder keys on, `restrict_sel` the [C, Nc]
    fixed-masked aggregation matrix (R at scalar granularity), `G` the
    materialised coarse coupling S_d Rᵀ as [cd, Nc, C, cd] (fine dof
    (a, n) by coarse dof (J, b)).  Exposed as a dataclass so the
    dense-parity property tests can compare `coarse_matrix`/`G`
    against explicitly projected dense operators.
    """

    coarse_matrix: jax.Array
    eig_q: jax.Array  # [C*cd, C*cd] eigenvectors
    eig_inv: jax.Array  # [C*cd] filtered inverse eigenvalues
    ok: jax.Array  # traced bool: coarse factor finite
    restrict_sel: jax.Array  # [C, Nc]
    cluster: jax.Array  # [Nc] int32
    G: jax.Array  # [cd, Nc, C, cd] = S_d Rᵀ


@jax.named_scope("megba.precond_coarse_build")
def build_two_level_coarse(
    Hpp_d: jax.Array,
    Hll_inv: jax.Array,
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    cluster_plan,
    compute_kind: ComputeKind,
    axis_name: Optional[str] = None,
    cam_fixed: Optional[jax.Array] = None,
    plans=None,
) -> TwoLevelCoarse:
    """Assemble + factor G = S_d Rᵀ and A_c = R G = R S_d Rᵀ.

    Pure gathers/scatter-adds over the host-planned index arrays
    (ops/segtiles.ClusterPlan) + one small dense Cholesky; when the
    edge axis is sharded the per-shard V rows are psum-combined BEFORE
    the ec-pair contraction (cross-shard edges of one point are why —
    W_e Hll⁻¹ (ΣV)ᵀ needs the globally-summed V) and the per-shard G
    contributions are psum-combined after it.  Two all-reduces per
    BUILD (once per PCG solve), both outside the PCG while body, both
    the collective kind the solver already emits.
    """
    cd = Hpp_d.shape[-1]
    pd = int(round(Hll_inv.shape[0] ** 0.5))
    dtype = Hpp_d.dtype
    num_cameras = Hpp_d.shape[0]
    C = cluster_plan.num_clusters
    n_pc = cluster_plan.n_pc
    od = None if Jc is None else Jc.shape[0] // cd
    rows_of = coupling_row_provider(
        W, Jc, Jp, 0 if od is None else od, compute_kind, dtype,
        plans=plans)
    rows_at = coupling_row_gather(
        W, Jc, Jp, 0 if od is None else od, compute_kind, dtype,
        plans=plans)
    n_edges = cluster_plan.pc_slot.shape[0]

    # V rows [cd*pd, n_pc]: per-(point, cluster) aggregated coupling.
    # Padding / masked edges carry the inert slot n_pc -> dropped (their
    # rows are zero anyway — the Jacobians are mask-multiplied).
    def vbody(start, size, accs):
        (v_a,) = accs
        sl = jax.lax.dynamic_slice_in_dim(cluster_plan.pc_slot, start, size)
        return (v_a.at[:, sl].add(rows_of(start, size), mode="drop"),)

    (V,) = chunked_edge_reduce(
        n_edges, (jnp.zeros((cd * pd, n_pc), dtype),), vbody)
    if axis_name is not None:
        V = jax.lax.psum(V, axis_name)

    # T = V · Hll⁻¹ per incidence (the point block is shared by every
    # incidence of its point; Hll⁻¹ is symmetric, so T's columns double
    # as the Hll⁻¹ Vᵀ blocks the ec contraction needs).
    hinv = gather_fm(Hll_inv, cluster_plan.pc_pt)  # [pd*pd, n_pc]
    T = jnp.stack([
        sum(V[a * pd + p] * hinv[p * pd + q] for p in range(pd))
        for a in range(cd) for q in range(pd)
    ])  # [cd*pd, n_pc]

    # ec-pair contraction: corrG[(a,b), (n,J)] += Σ_q W_e[a,q] T_s[b,q]
    # over the host-enumerated (edge, same-point-slot) pairs — the
    # coupling half of G = S_d Rᵀ, chunked so the [cd*cd, chunk] block
    # rows stay VMEM-sized.  Inert padding pairs scatter to the
    # out-of-range segment Nc*C and are dropped.
    NcC = num_cameras * C

    def gbody(start, size, accs):
        (g_a,) = accs
        le = jax.lax.dynamic_slice_in_dim(cluster_plan.ec_edge, start, size)
        ls = jax.lax.dynamic_slice_in_dim(cluster_plan.ec_slot, start, size)
        sg = jax.lax.dynamic_slice_in_dim(cluster_plan.ec_seg, start, size)
        w = rows_at(le)  # [cd*pd, size]
        t = jnp.take(T, ls, axis=1, mode="clip")  # [cd*pd, size]
        block = jnp.stack([
            sum(w[a * pd + q] * t[b * pd + q] for q in range(pd))
            for a in range(cd) for b in range(cd)
        ])  # [cd*cd, size]
        return (g_a.at[:, sg].add(block, mode="drop"),)

    (corrg_rows,) = chunked_edge_reduce(
        cluster_plan.ec_edge.shape[0],
        (jnp.zeros((cd * cd, NcC), dtype),), gbody, target=_PAIR_CHUNK)
    if axis_name is not None:
        corrg_rows = jax.lax.psum(corrg_rows, axis_name)
    corrg = corrg_rows.reshape(cd, cd, num_cameras, C).transpose(0, 2, 3, 1)

    # Fine half Hpp_d Rᵀ: Hpp is block diagonal, so camera n contributes
    # its own block to coarse column cluster(n) only.  Fixed cameras are
    # excluded from R (their identity blocks would pollute the cluster
    # sums, and the coarse correction must never move a pinned camera);
    # their W rows are already zero, so G's rows/cols there vanish and
    # the cycle degrades to pure block-Jacobi for them.
    sel = (cluster_plan.cluster[None, :]
           == jnp.arange(C, dtype=jnp.int32)[:, None]).astype(dtype)
    if cam_fixed is not None:
        sel = sel * (1.0 - cam_fixed.astype(dtype))[None, :]
    fine = jnp.einsum("nab,Jn->anJb", Hpp_d, sel, precision=HI)
    G = fine - corrg  # [cd, Nc, C, cd] = S_d Rᵀ

    # A_c = R G (Galerkin): tiny replicated contraction.
    A = jnp.einsum("In,anJb->IaJb", sel, G,
                   precision=HI).reshape(C * cd, C * cd)
    A = 0.5 * (A + A.T)  # symmetrise away accumulation-order roundoff
    # Filtered pseudo-inverse instead of a Cholesky: all-fixed /
    # edge-less clusters (exactly-zero rows) and gauge-like near-null
    # modes both land UNDER the eigenvalue floor and simply receive no
    # coarse correction, rather than NaN-ing the factor or amplifying
    # noise (_COARSE_EIG_FLOOR).
    (Q, inv), ok = dense_filtered_factor(A, _COARSE_EIG_FLOOR)
    return TwoLevelCoarse(coarse_matrix=A, eig_q=Q, eig_inv=inv, ok=ok,
                          restrict_sel=sel, cluster=cluster_plan.cluster,
                          G=G)


def _coarse_solve_inject(coarse: TwoLevelCoarse, rc: jax.Array):
    """A_c⁺ on a [C, cd] coarse residual, plus its Rᵀ injection.

    Returns (y [C, cd], z [cd, Nc]) — the injection gathers each
    camera's cluster value and re-applies the fixed-camera mask (selᵀ y
    == gather + mask, without materialising selᵀ)."""
    C, cd = rc.shape
    y = dense_filtered_solve((coarse.eig_q, coarse.eig_inv),
                             rc.reshape(C * cd)).reshape(C, cd)
    z = jnp.swapaxes(jnp.take(y, coarse.cluster, axis=0), 0, 1)
    z = z * jnp.max(coarse.restrict_sel, axis=0)[None, :]
    return y, z


def two_level_cycle(
    coarse: TwoLevelCoarse,
    base_apply: Callable[[jax.Array], jax.Array],
    r: jax.Array,
) -> jax.Array:
    """One symmetrized multiplicative two-level cycle ([cd, Nc] rows).

        M⁻¹ r = Rᵀ A_c⁻¹ R r + Pᵀ D⁻¹ P r,   P = I − G A_c⁻¹ R

    with G = S_d Rᵀ materialised at build time, so both "S applies"
    are [cd·Nc, C·cd] replicated contractions: per-apply work is two
    tiny triangular solves + two G contractions + one block-diagonal
    smooth — no edge-scale ops, ZERO collectives.  Degrades bitwise to
    the plain base apply when the coarse factor was non-finite (the
    fallback ladder's coarse level); fixed cameras receive exactly the
    base apply by the masked selector.
    """
    rc = jnp.einsum("In,an->Ia", coarse.restrict_sel, r,
                    precision=HI)  # R r  [C, cd]
    y, z_c = _coarse_solve_inject(coarse, rc)
    gy = jnp.einsum("anJb,Jb->an", coarse.G, y, precision=HI)  # G y
    # Pre-smoothing residual P r = r − G A_c⁻¹ R r; gated so the
    # ok=False ladder level is EXACTLY base_apply(r), not a perturbed
    # smooth of garbage.
    u = jnp.where(coarse.ok, r - gy, r)
    w = base_apply(u)
    # Post-correction: Rᵀ A_c⁻¹ (Gᵀ w)   (Gᵀ w = R S_d w).
    v = jnp.einsum("anJb,an->Jb", coarse.G, w, precision=HI)
    _, z2 = _coarse_solve_inject(coarse, v)
    return jnp.where(coarse.ok, z_c + w - z2, w)


# --------------------------------------------------------------------------
# Factory
# --------------------------------------------------------------------------


def make_schur_preconditioner(
    kind: PrecondKind,
    block_kind: PreconditionerKind,
    Hpp_d: jax.Array,
    Hll_inv: jax.Array,
    W: Optional[jax.Array],
    Jc: Optional[jax.Array],
    Jp: Optional[jax.Array],
    cam_idx: jax.Array,
    pt_idx: jax.Array,
    num_cameras: int,
    compute_kind: ComputeKind,
    axis_name: Optional[str],
    cam_sorted: bool,
    neumann_order: int = 2,
    plans=None,
    cluster_plan=None,
    cam_fixed=None,
    s_matvec: Optional[Callable[[jax.Array], jax.Array]] = None,
) -> Tuple[Callable[[jax.Array], jax.Array], jax.Array]:
    """Build the reduced-system preconditioner apply for one solve.

    Returns `(apply, fallback_code)`: `apply(r [cd, Nc]) -> [cd, Nc]`
    runs inside the PCG while body; `fallback_code` is the enum-coded
    per-level fallback count (encode_precond_fallback) for the trace.
    `kind` picks the operator family (PrecondKind), `block_kind` the
    base block diagonal every family smooths with (PreconditionerKind).
    All operands are the damped, already-materialised solve quantities;
    `s_matvec` (the CG's own S·p closure) is required by NEUMANN only.
    """
    if block_kind == PreconditionerKind.SCHUR_DIAG:
        Minv, n_bad = _schur_diag_precond(
            Hpp_d, Hll_inv, W, Jc, Jp, cam_idx, pt_idx, num_cameras,
            compute_kind, axis_name, cam_sorted, plans=plans)
    else:
        Minv = block_inv(Hpp_d)  # reference block-Jacobi (Hpp)
        n_bad = jnp.int32(0)

    def base_apply(r):
        return cam_block_matvec(Minv, r)

    if kind == PrecondKind.JACOBI:
        return base_apply, encode_precond_fallback(n_bad)

    if kind == PrecondKind.NEUMANN:
        if s_matvec is None:
            raise ValueError("NEUMANN preconditioner needs the S matvec")
        order = int(neumann_order)

        @jax.named_scope("megba.precond_neumann")
        def neumann_apply(r):
            # Horner form of Σ_{i<=k} E^i D⁻¹ r, E = I − D⁻¹S: each
            # step is one S apply (the 2-psum product) + one block
            # solve.  k is static — the unrolled chain lives inside the
            # fused while body.
            z = base_apply(r)
            for _ in range(order):
                z = z + base_apply(r - s_matvec(z))
            return z

        return neumann_apply, encode_precond_fallback(n_bad)

    if kind != PrecondKind.TWO_LEVEL:  # pragma: no cover - enum closed
        raise ValueError(f"unknown precond kind {kind}")
    if cluster_plan is None:
        raise ValueError(
            "precond=TWO_LEVEL needs a camera-cluster plan operand; the "
            "flat_solve lowering builds one automatically "
            "(ops/segtiles.cached_cluster_plan) — direct schur_pcg_solve "
            "callers must pass cluster_plan=")
    coarse = build_two_level_coarse(
        Hpp_d, Hll_inv, W, Jc, Jp, cluster_plan, compute_kind,
        axis_name=axis_name, cam_fixed=cam_fixed, plans=plans)

    @jax.named_scope("megba.precond_two_level")
    def two_level_apply(r):
        return two_level_cycle(coarse, base_apply, r)

    fallback = encode_precond_fallback(
        n_bad, jnp.where(coarse.ok, jnp.int32(0), jnp.int32(1)))
    return two_level_apply, fallback


__all__ = [
    "FALLBACK_BLOCK_RADIX",
    "TwoLevelCoarse",
    "block_inv",
    "build_two_level_coarse",
    "cam_block_matvec",
    "decode_precond_fallback",
    "encode_precond_fallback",
    "make_schur_preconditioner",
    "two_level_cycle",
    "_schur_diag_precond",
]
