"""The BAL 3D bundle adjustment model family (flagship).

Camera block (9): angle-axis rotation (3), translation (3), focal, k1,
k2.  Point block (3).  Observation (2).  Mirrors the model solved by all
six reference examples (examples/BAL_Double.cpp:18-33 etc.).
"""

from megba_tpu.ops.residuals import (
    bal_residual as residual,
    bal_residual_jacobian_analytical as residual_jacobian_analytical,
)

CAMERA_DIM = 9
POINT_DIM = 3
OBS_DIM = 2

__all__ = [
    "CAMERA_DIM",
    "OBS_DIM",
    "POINT_DIM",
    "residual",
    "residual_jacobian_analytical",
]
