"""Pose-graph optimization (PGO): SE(3) between-factors.

A second optimization family beyond anything the reference supports:
MegBA's edge is hard-wired to one camera plus one landmark
(include/edge/base_edge.h — `_vertices` is indexed by CameraVertex /
PointVertex roles throughout build_linear_system.cu), so a factor
between two vertices of the SAME kind cannot be expressed there at all.
Here the family reuses the framework's TPU primitives — feature-major
rows (core/fm.py), sorted segment reductions, compensated reductions
(ops/accum.py), the shared PCG core with block-Jacobi preconditioning
(solver/pcg.py), and the reference-semantics LM trust region
(algo/lm.py) — over a single pose table with a matrix-free Gauss-Newton
operator.

Model: pose = [angle_axis (3), translation (3)]; T maps body -> world.
A measurement m on edge (i, j) is the expected relative pose
T_ij = T_i^{-1} T_j, and the residual is the right-invariant error

    E   = T_ij^{-1} (T_i^{-1} T_j)
    r   = [ log_SO3(E_R) ; E_t ]           (6 rows)

Jacobians d r / d pose_{i,j} come from forward-mode autodiff of the
exact residual (no linearised-manifold approximation), vectorised over
the edge axis.  The normal equations are never materialised: the PCG
operator applies H x = J^T J x edge-wise with two segment reductions
per product, exactly the implicit-Schur playbook of the BA path.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from megba_tpu.common import ProblemOption, strip_observability
from megba_tpu.core.fm import segsum_fm
from megba_tpu.core.host_se3 import compose, relative
from megba_tpu.core.types import pad_edges
from megba_tpu.parallel.mesh import (
    EDGE_AXIS,
    SHARD_MAP_NATIVE,
    make_mesh,
    shard_map,
)
from megba_tpu.ops import geo
from megba_tpu.ops.accum import comp_sum, comp_sum_sq
from megba_tpu.ops.robust import RobustKind, robustify
from megba_tpu.utils.backend import warn_if_x64_unavailable

POSE_DIM = 6
_TINY = 1e-30


def between_residual(pose_i: jnp.ndarray, pose_j: jnp.ndarray,
                     meas: jnp.ndarray) -> jnp.ndarray:
    """6-row between-factor residual for one edge (poses, meas: [6])."""
    Ri = geo.angle_axis_to_rotation_matrix(pose_i[:3])
    Rj = geo.angle_axis_to_rotation_matrix(pose_j[:3])
    Rm = geo.angle_axis_to_rotation_matrix(meas[:3])
    # T_i^{-1} T_j = (Ri^T Rj, Ri^T (t_j - t_i))
    R_rel = Ri.T @ Rj
    t_rel = Ri.T @ (pose_j[3:] - pose_i[3:])
    # E = T_m^{-1} (T_i^{-1} T_j)
    E_R = Rm.T @ R_rel
    E_t = Rm.T @ (t_rel - meas[3:])
    return jnp.concatenate([geo.rotation_matrix_to_angle_axis(E_R), E_t])


class PGOResult(NamedTuple):
    poses: jax.Array  # [N, 6] edge-major (public layout)
    cost: jax.Array
    initial_cost: jax.Array
    iterations: jax.Array
    accepted: jax.Array
    pcg_iterations: jax.Array
    region: jax.Array
    v: jax.Array  # trust-region back-off factor (resume state)
    stopped: jax.Array
    # Termination status (common.SolveStatus int32, algo.lm.derive_status
    # — the same semantics the BA family reports); None only on results
    # from constructors predating it.
    status: Optional[jax.Array] = None


def _linearize(poses_fm, edge_i, edge_j, meas_fm, sqrt_info, free_i, free_j,
               emask=None, axis_name=None,
               robust=None, robust_delta=1.0,
               residual_fn=between_residual, pose_dim=POSE_DIM):
    """r [rd, nE], Ji/Jj [rd, pd, nE] (weighted, fixed-masked), cost,
    wcost — rd/pd from the factor spec (6/6 for the SE(3) family).

    `emask` [nE] zeroes padding edges (sharded solves pad the edge axis
    to a multiple of world_size, same scheme as core/types.pad_edges);
    with `axis_name` set the costs are psum-reduced so every shard
    carries the replicated global values.  With a robust kernel the
    returned r/Ji/Jj are IRLS-reweighted (same scheme as the BA loop,
    algo/lm.py): `cost` is Sum rho (the accept observable) and `wcost`
    the weighted squared norm (the quadratic-model observable); without
    one they coincide.
    """
    pd = pose_dim

    def g(x12, m):
        return residual_fn(x12[:pd], x12[pd:], m)

    xi = jnp.take(poses_fm, edge_i, axis=1)  # [pd, nE]
    xj = jnp.take(poses_fm, edge_j, axis=1)
    x12 = jnp.concatenate([xi, xj])  # [2*pd, nE]
    r = jax.vmap(g, in_axes=(1, 1), out_axes=1)(x12, meas_fm)
    J = jax.vmap(jax.jacfwd(g), in_axes=(1, 1), out_axes=2)(x12, meas_fm)
    Ji, Jj = J[:, :pd], J[:, pd:]  # [rd, pd, nE]
    rd = r.shape[0]
    if sqrt_info is not None:  # [rd, rd, nE] row-form W per edge
        r = jnp.einsum("abe,be->ae", sqrt_info, r)
        Ji = jnp.einsum("abe,bce->ace", sqrt_info, Ji)
        Jj = jnp.einsum("abe,bce->ace", sqrt_info, Jj)
    # Gauge/fixed poses contribute no Jacobian columns.
    Ji = Ji * free_i
    Jj = Jj * free_j
    if emask is not None:
        r = r * emask[None, :]
        Ji = Ji * emask[None, None, :]
        Jj = Jj * emask[None, None, :]
    if robust is None or robust == RobustKind.NONE:
        wcost = comp_sum_sq(r.reshape(-1))
        cost = wcost
    else:
        # Same IRLS kernel as the BA path (ops/robust.robustify, with
        # Ji/Jj flattened to its row form).  Padding edges are inert:
        # r = 0 -> s = 0 -> rho = 0, w = 1.
        n_e = r.shape[1]
        r, Ji_f, Jj_f, rho_e = robustify(
            r, Ji.reshape(rd * pd, n_e),
            Jj.reshape(rd * pd, n_e), robust, robust_delta)
        Ji = Ji_f.reshape(rd, pd, n_e)
        Jj = Jj_f.reshape(rd, pd, n_e)
        cost = comp_sum(rho_e)
        wcost = comp_sum_sq(r.reshape(-1))
    if axis_name is not None:
        cost = jax.lax.psum(cost, axis_name)
        wcost = jax.lax.psum(wcost, axis_name)
    return r, Ji, Jj, cost, wcost


def _grad_fm(r, Ji, Jj, edge_i, edge_j, n_poses):
    """Gradient J^T r as [pd, N] feature-major (fixed poses come out zero
    because _linearize already masks their Jacobian columns)."""
    gi = jnp.einsum("oae,oe->ae", Ji, r)
    gj = jnp.einsum("oae,oe->ae", Jj, r)
    return (segsum_fm(gi, edge_i, n_poses)
            + segsum_fm(gj, edge_j, n_poses))


def _grad_and_diag(r, Ji, Jj, edge_i, edge_j, n_poses, fixed,
                   axis_name=None, pose_dim=POSE_DIM):
    """g [pd, N] and block-diagonal H rows [pd*pd, N] (identity at fixed).

    Sharded solves psum g and h BEFORE the identity guard below: a pose
    whose edges all live on other shards must see the global sum, not a
    per-shard identity block.
    """
    pd = pose_dim
    g = _grad_fm(r, Ji, Jj, edge_i, edge_j, n_poses)
    hi = jnp.einsum("oae,obe->abe", Ji, Ji).reshape(pd * pd, -1)
    hj = jnp.einsum("oae,obe->abe", Jj, Jj).reshape(pd * pd, -1)
    h = (segsum_fm(hi, edge_i, n_poses)
         + segsum_fm(hj, edge_j, n_poses))
    if axis_name is not None:
        g = jax.lax.psum(g, axis_name)
        h = jax.lax.psum(h, axis_name)
    # Fixed (and fully unobserved) poses get identity blocks so the
    # damped preconditioner stays invertible; their gradient is zero so
    # PCG leaves them untouched (same trick as the BA builder's
    # edge-less-vertex identity blocks).
    # dtype pinned: a bare jnp.eye is float64 under x64 and would upcast
    # h (and through it the whole PCG state) in float32 solves.
    eye = jnp.eye(pd, dtype=h.dtype).reshape(pd * pd, 1)
    guard = fixed | (h[0] == 0)
    h = jnp.where(guard[None, :], eye, h)
    g = g * (1.0 - fixed.astype(g.dtype))[None, :]
    return g, h


def solve_pgo(
    poses0: np.ndarray,
    edge_i: np.ndarray,
    edge_j: np.ndarray,
    meas: np.ndarray,
    option: Optional[ProblemOption] = None,
    sqrt_info: Optional[np.ndarray] = None,
    fixed: Optional[np.ndarray] = None,
    verbose: bool = False,
    initial_region: Optional[float] = None,
    initial_v: Optional[float] = None,
    factor="se3_between",
    lower_only: bool = False,
) -> PGOResult:
    """Solve a pose graph.  PUBLIC edge-major boundary.

    poses0 [N, pd], edge_i/edge_j [nE] int, meas [nE, md],
    sqrt_info [nE, rd, rd] optional, fixed [N] bool (pose 0 is fixed by
    default — the gauge anchor), with (pd, md, rd) from the registered
    pose-graph `factor` — `"se3_between"` (the default, 6/6/6:
    angle-axis + translation, byte-identical programs to the
    pre-registry driver) or `"sim3_between"` (7/7/7: scale-aware
    monocular-SLAM PGO, factors/sim3.py), or any registered
    `factors.PoseFactorSpec`.  A Schur (camera/point) factor name here
    raises typed `FactorError`; unknown names raise
    `UnknownFactorError`.  LM trust-region semantics and PCG stopping
    mirror the BA path (algo/lm.py, solver/pcg.py).

    `option.world_size > 1` shards the EDGE axis over a 1-D device mesh
    (same layout as the BA path, parallel/mesh.py): pose state is
    replicated, every per-edge array lives only on its shard, and the
    whole LM loop runs as one SPMD program with psums at the reduction
    sites (cost, gradient, block diagonal, matvec output).

    `option.robust_kind`/`robust_delta` enable IRLS robust losses
    (Huber/Cauchy, ops/robust.py) — the standard defence against bad
    loop closures; `result.cost` is then Sum rho.

    `lower_only=True` returns the `jax.stages.Lowered` of the exact PGO
    program this call would dispatch (auditor hook,
    analysis/program_audit.py; single-process only).
    """
    option = option or ProblemOption()
    # The PGO family records no SolveReport yet (README "Telemetry &
    # profiling" scopes the sink to the BA pipeline); strip BOTH
    # observability knobs (common.OBSERVABILITY_FIELDS) so neither can
    # fragment _pgo_program's lru cache or its static key.  (This
    # previously cleared only `telemetry`, so `metrics=True` silently
    # split the PGO program cache — the identity lane's cache-split /
    # key-surface-drift finding, fixed at the source.)
    option = strip_observability(option)
    # Registry dispatch (lazy import: factors/pose_graph.py imports
    # THIS module at registration time).
    from megba_tpu.factors import get_factor
    from megba_tpu.factors.registry import (
        apply_factor_solver_defaults,
        require_pose_graph,
    )

    spec = require_pose_graph(get_factor(factor), "solve_pgo")
    # Per-factor solver defaults (sim(3)'s refuse_ratio=16 — the PR 13
    # stall finding): resolved BEFORE the program cache key is formed,
    # so the default and an equivalent explicit setting share one
    # compiled program.
    option = apply_factor_solver_defaults(spec, option)
    pd, md, rd = spec.pose_dim, spec.meas_dim, spec.residual_dim
    if int(poses0.shape[1]) != pd:
        raise ValueError(
            f"solve_pgo: poses0 width {int(poses0.shape[1])} does not "
            f"match factor {spec.name!r} pose_dim {pd}")
    if np.asarray(meas).ndim != 2 or int(np.asarray(meas).shape[1]) != md:
        raise ValueError(
            f"solve_pgo: meas width "
            f"{np.asarray(meas).shape[1:] or '?'} does not match factor "
            f"{spec.name!r} meas_dim {md}")
    # f64 only when actually available (x64 enabled) — otherwise warn
    # loudly, same precision contract as flat_solve.
    warn_if_x64_unavailable(option.dtype)
    dtype = (
        jnp.float64
        if np.dtype(option.dtype) == np.float64 and jax.config.jax_enable_x64
        else jnp.float32)
    n_poses = int(poses0.shape[0])
    world = int(option.world_size)

    # Host-side prep: pad the edge axis to a multiple of world_size with
    # masked-out edges (core/types.pad_edges — one padding contract for
    # the BA and PGO families).
    edge_i = np.asarray(edge_i, np.int32)
    edge_j = np.asarray(edge_j, np.int32)
    meas_np = np.asarray(meas)
    si_np = None if sqrt_info is None else np.asarray(sqrt_info)
    if si_np is not None and si_np.shape[1:] != (rd, rd):
        raise ValueError(
            f"solve_pgo: sqrt_info must be [nE, {rd}, {rd}] for factor "
            f"{spec.name!r}, got {si_np.shape}")
    n_e = edge_i.shape[0]
    n_pad = (-n_e) % world
    emask = None
    if n_pad:
        meas_np, edge_i, edge_j, emask_np = pad_edges(
            meas_np, edge_i, edge_j, world, dtype=np.float64)
        emask = np.asarray(emask_np, dtype)
        if si_np is not None:
            si_np = np.concatenate(
                [si_np, np.zeros((n_pad, rd, rd), si_np.dtype)])

    if fixed is None:
        fixed_np = np.zeros(n_poses, bool)
        fixed_np[0] = True
    else:
        fixed_np = np.asarray(fixed, bool)

    # Host numpy until dispatch (same contract as flat_solve): the
    # jitted program uploads once, and the multi-process path builds
    # global arrays straight from host memory.
    poses_fm = np.ascontiguousarray(poses0.T).astype(dtype, copy=False)
    ei = np.asarray(edge_i)
    ej = np.asarray(edge_j)
    meas_fm = np.ascontiguousarray(meas_np.T).astype(dtype, copy=False)
    si = (None if si_np is None else np.ascontiguousarray(
        np.transpose(si_np, (1, 2, 0))).astype(dtype, copy=False))

    # emask (only when the edge axis was padded) and si (only when the
    # caller weights edges) ride as optional trailing operands, so the
    # common unpadded/unweighted solve never pays their multiplies.
    extra_keys = []
    extras = []
    if emask is not None:
        extra_keys.append("emask")
        extras.append(emask)
    if si is not None:
        extra_keys.append("si")
        extras.append(si)

    prog, mesh = _pgo_program(option, world, n_poses, np.dtype(dtype),
                              tuple(extra_keys), bool(verbose), spec)
    region0 = (option.algo_option.initial_region if initial_region is None
               else initial_region)
    v0 = 2.0 if initial_v is None else initial_v
    from megba_tpu.observability.emit import next_verbose_token

    args = [poses_fm, fixed_np, ei, ej, meas_fm,
            jnp.asarray(region0, dtype), jnp.asarray(v0, dtype),
            jnp.asarray(next_verbose_token(), jnp.int32), *extras]
    if lower_only:
        # Auditor hook (analysis/program_audit.py): the Lowered of the
        # exact PGO program this call would dispatch, shared host prep
        # and all.  Single-process only.
        return prog.lower(*args)
    if mesh is not None:
        from megba_tpu.parallel.multihost import dispatch_on_mesh

        out = dispatch_on_mesh(prog, mesh, args,
                               _pgo_in_specs(tuple(extra_keys)))
    else:
        out = prog(*args)

    cost0 = out["cost0"]
    result = PGOResult(
        poses=jnp.swapaxes(out["poses"], 0, 1),
        cost=out["cost"], initial_cost=cost0, iterations=out["k"],
        accepted=out["accepted"], pcg_iterations=out["pcg_total"],
        region=out["region"], v=out["v"], stopped=out["stop"],
        status=out["status"])
    if verbose:
        print(f"PGO: cost {float(cost0):.6e} -> {float(result.cost):.6e} "
              f"in {int(result.iterations)} LM iters "
              f"({int(result.accepted)} accepted, "
              f"{int(result.pcg_iterations)} PCG)", flush=True)
    return result


def _pgo_in_specs(extra_keys):
    """Input partition specs of the sharded PGO program, in arg order.

    One source of truth for _pgo_program's shard_map AND the dispatch
    site's multi-process globalization (they must never drift apart).
    """
    rep = P()
    spec_of = {"emask": P(EDGE_AXIS), "si": P(None, None, EDGE_AXIS)}
    return [rep, rep, P(EDGE_AXIS), P(EDGE_AXIS), P(None, EDGE_AXIS),
            rep, rep, rep, *(spec_of[k] for k in extra_keys)]


@functools.lru_cache(maxsize=32)
def _pgo_program(option: ProblemOption, world: int, n_poses: int,
                 np_dtype: np.dtype, extra_keys: tuple,
                 verbose: bool, factor_spec):
    """Build (once per configuration) the jitted PGO LM program.

    Returns (program, mesh-or-None).  Cached so repeat solves of one
    configuration — the checkpointed chunk driver, parameter sweeps —
    pay tracing + compilation once; the trust-region resume state
    (region0, v0) and the verbose-clock token ride as DYNAMIC operands,
    exactly like the BA path's get_or_build_program contract
    (parallel/mesh.py).  jit handles shape-based re-specialisation
    internally.  `factor_spec` (a registered `PoseFactorSpec`,
    hashable — part of the cache key) selects the residual family and
    is REQUIRED: a defaultable spec would let one SE(3) configuration
    land under two lru keys (None vs the spec) and trace a duplicate
    program — the one-config-one-program hazard the registry exists to
    prevent.  solve_pgo's "se3_between" default traces the identical
    program the pre-registry driver traced.
    """
    dtype = np_dtype
    algo_opt = option.algo_option
    solver_opt = option.solver_option
    axis_name = EDGE_AXIS if world > 1 else None
    pd = factor_spec.pose_dim

    from megba_tpu.observability.emit import emit_verbose_iteration
    from megba_tpu.algo.lm import eisenstat_walker_eta, initial_forcing_eta
    from megba_tpu.solver.pcg import _pcg_core, block_inv

    def run(poses_fm, fixed_j, ei, ej, meas_fm, region0, v0,
            verbose_token, *extras_in):
        kw = dict(zip(extra_keys, extras_in))
        emask = kw.get("emask")
        si_ = kw.get("si")
        free_i = 1.0 - jnp.take(fixed_j, ei).astype(dtype)[None, None, :]
        free_j = 1.0 - jnp.take(fixed_j, ej).astype(dtype)[None, None, :]

        def lin(p):
            return _linearize(p, ei, ej, meas_fm, si_, free_i, free_j,
                              emask, axis_name,
                              option.robust_kind, option.robust_delta,
                              residual_fn=factor_spec.residual_fn,
                              pose_dim=pd)

        def grad_and_diag(r, Ji, Jj):
            return _grad_and_diag(r, Ji, Jj, ei, ej, n_poses, fixed_j,
                                  axis_name, pose_dim=pd)

        def step_system(g, h_rows, Ji, Jj, region, tol, x0):
            damp = 1.0 + 1.0 / region
            h_blocks = jnp.moveaxis(h_rows.reshape(pd, pd, n_poses), -1, 0)
            # Diagonal ENTRIES of each pd x pd block: rows 0, pd+1, ...
            # of the [pd*pd, N] row store.
            h_diag = h_rows[:: pd + 1]
            h_damped = h_blocks * (
                jnp.eye(pd, dtype=dtype) * (damp - 1.0) + 1.0)
            minv = block_inv(h_damped)

            def matvec(x):  # [6, N] -> [6, N]; damped H x, matrix-free
                xi = jnp.take(x, ei, axis=1)
                xj = jnp.take(x, ej, axis=1)
                u = (jnp.einsum("oae,ae->oe", Ji, xi)
                     + jnp.einsum("oae,ae->oe", Jj, xj))
                out = (segsum_fm(jnp.einsum("oae,oe->ae", Ji, u), ei,
                                 n_poses)
                       + segsum_fm(jnp.einsum("oae,oe->ae", Jj, u), ej,
                                   n_poses))
                if axis_name is not None:
                    out = jax.lax.psum(out, axis_name)
                # LM damping scales diagonal ENTRIES by (1 + 1/region),
                # matching h_damped above and the BA path's damp_blocks
                # (reference extractOldAndApplyNewDiag semantics); x and
                # h_diag are replicated, so this is added AFTER the psum.
                dx_d = h_diag * x * (damp - 1.0)
                return out + dx_d

            def precond(x):
                return jnp.einsum("nab,bn->an", minv, x)

            dx, iters, _, _, _, _ = _pcg_core(
                matvec, precond, -g, solver_opt.max_iter, tol,
                solver_opt.refuse_ratio,
                True if solver_opt.forcing else solver_opt.tol_relative,
                x0=x0)
            return dx, iters

        r0, Ji0, Jj0, cost0, wcost0 = lin(poses_fm)
        g0, h0 = grad_and_diag(r0, Ji0, Jj0)
        # Inexact-LM knobs, same semantics as the BA loop (algo/lm.py):
        # eta_k is norm-relative (squared into the energy threshold),
        # Eisenstat-Walker choice 2 updates, warm start zeroed on reject.
        forcing = solver_opt.forcing
        warm_start = solver_opt.warm_start
        eta_min_c = jnp.asarray(solver_opt.eta_min, dtype)
        eta_max_c = jnp.asarray(solver_opt.tol, dtype)
        state0 = dict(
            k=jnp.int32(0), accepted=jnp.int32(0), pcg_total=jnp.int32(0),
            poses=poses_fm, r=r0, Ji=Ji0, Jj=Jj0, g=g0, h_rows=h0,
            cost=cost0, wcost=wcost0,
            region=jnp.asarray(region0, dtype),
            v=jnp.asarray(v0, dtype), stop=jnp.bool_(False))
        if forcing:
            state0["eta"] = initial_forcing_eta(eta_min_c, eta_max_c, dtype)
        if warm_start:
            state0["dx0"] = jnp.zeros_like(poses_fm)

        def cond(s):
            return (s["k"] < algo_opt.max_iter) & (~s["stop"])

        def body(s):
            tol_k = s["eta"] * s["eta"] if forcing else solver_opt.tol
            dx, pcg_iters = step_system(s["g"], s["h_rows"], s["Ji"],
                                        s["Jj"], s["region"], tol_k,
                                        s["dx0"] if warm_start else None)
            dx_norm = jnp.sqrt(jnp.sum(dx * dx))
            x_norm = jnp.sqrt(jnp.sum(s["poses"] ** 2))
            converged = dx_norm <= algo_opt.epsilon2 * (
                x_norm + algo_opt.epsilon1)
            poses_new = s["poses"] + dx

            # Gain ratio exactly as the BA loop (lm.py:219-260):
            # predicted = ||J dx + r||^2 (edge-sharded -> psum),
            # denominator clamped sign-preservingly.
            dxi = jnp.take(dx, ei, axis=1)
            dxj = jnp.take(dx, ej, axis=1)
            jdx = (jnp.einsum("oae,ae->oe", s["Ji"], dxi)
                   + jnp.einsum("oae,ae->oe", s["Jj"], dxj) + s["r"])
            predicted = comp_sum_sq(jdx.reshape(-1))
            if axis_name is not None:
                predicted = jax.lax.psum(predicted, axis_name)
            # The quadratic model lives in the (robust-)weighted
            # residuals, so its decrease is measured from the carried
            # weighted norm; accept uses the true (robustified) cost —
            # the exact split the BA loop makes (lm.py).  Without a
            # robust kernel the two coincide.
            denominator = jnp.minimum(predicted - s["wcost"], -_TINY)
            _, _, _, cost_new, wcost_new = lin(poses_new)
            rho = (cost_new - s["cost"]) / denominator
            accept = (cost_new < s["cost"]) & (~converged)

            # Accept branch relinearizes AND rebuilds g/h (the BA
            # loop's accept-branch rebuild, lm.py:_relinearize) — so
            # the gradient stop below reads the RELINEARIZED gradient
            # of the accepted point (reference lm_algo.cu checks the
            # post-update ||g||_inf) and the next iteration's
            # step_system reuses g/h from the carry instead of
            # recomputing.  On reject everything carries over unchanged
            # and the accept-gated stop never fires.
            def _accept_lin(_):
                r2, Ji2, Jj2, _c, _w = lin(poses_new)
                g2, h2 = grad_and_diag(r2, Ji2, Jj2)
                return r2, Ji2, Jj2, g2, h2, jnp.max(jnp.abs(g2))

            def _keep_old(_):
                return (s["r"], s["Ji"], s["Jj"], s["g"], s["h_rows"],
                        jnp.asarray(jnp.inf, dtype))

            r_n, Ji_n, Jj_n, g_n, h_n, g_inf = jax.lax.cond(
                accept, _accept_lin, _keep_old, None)
            region_accept = s["region"] / jnp.maximum(
                jnp.asarray(1.0 / 3.0, dtype), 1.0 - (2.0 * rho - 1.0) ** 3)
            s_next = dict(
                k=s["k"] + 1,
                accepted=s["accepted"]
                + jnp.where(accept, 1, 0).astype(jnp.int32),
                pcg_total=s["pcg_total"] + pcg_iters,
                poses=jnp.where(accept, poses_new, s["poses"]),
                r=r_n, Ji=Ji_n, Jj=Jj_n, g=g_n, h_rows=h_n,
                cost=jnp.where(accept, cost_new, s["cost"]),
                wcost=jnp.where(accept, wcost_new, s["wcost"]),
                region=jnp.where(accept, region_accept,
                                 s["region"] / s["v"]),
                v=jnp.where(accept, jnp.asarray(2.0, dtype), s["v"] * 2.0),
                stop=converged | (accept & (g_inf <= algo_opt.epsilon1)))
            if forcing:
                s_next["eta"] = eisenstat_walker_eta(
                    s["eta"], cost_new, s["cost"], rho, accept,
                    eta_min_c, eta_max_c, dtype)
            if warm_start:
                s_next["dx0"] = jnp.where(accept, dx, jnp.zeros_like(dx))
            if verbose:
                # Reference-style per-iteration line, same shared
                # mechanism as the BA loop (algo/lm.py).
                emit_verbose_iteration(verbose_token, s["k"], cost_new,
                                       accept, pcg_iters, axis_name)
            return s_next

        out = jax.lax.while_loop(cond, body, state0)
        # Per-edge carries (r/J/g/h) are internal; return only the
        # replicated observables so the sharded out_specs stay P().
        # Termination status: the shared derive_status semantics (no
        # fault guards in the PGO loop yet, so recoveries/fatal are
        # inert and the code splits converged / max_iter / stalled).
        from megba_tpu.algo.lm import derive_status

        status = derive_status(
            stopped=out["stop"], accepted=out["accepted"],
            recoveries=jnp.int32(0), fatal=jnp.bool_(False))
        return dict(
            poses=out["poses"], cost=out["cost"], cost0=cost0,
            k=out["k"], accepted=out["accepted"],
            pcg_total=out["pcg_total"], region=out["region"],
            v=out["v"], stop=out["stop"], status=status)

    # Retrace sentinel hook (analysis/retrace.py): one count per
    # compilation of the PGO program; zero cost once compiled.
    from megba_tpu.analysis.retrace import static_key, traced

    run = traced(
        "pgo.run", run,
        static=static_key(option, f"world{world}", n_poses, np_dtype,
                          extra_keys, verbose, factor_spec.name))

    if world > 1:
        mesh = make_mesh(world)
        in_specs = _pgo_in_specs(extra_keys)
        # poses_fm donated: the result's poses alias the input buffer
        # (solve_pgo hands over a fresh feature-major copy per call, and
        # the checkpointed chunk driver feeds each chunk's output into
        # the next call without other readers).
        # Donation is skipped under the experimental shard_map fallback
        # (freed-buffer aliasing hazard — see parallel/mesh.py).
        return jax.jit(shard_map(
            run, mesh=mesh, in_specs=tuple(in_specs), out_specs=P()),
            donate_argnums=(0,) if SHARD_MAP_NATIVE else ()), mesh
    return jax.jit(run, donate_argnums=(0,)), None


def with_priors(
    poses0: np.ndarray,
    edge_i: np.ndarray,
    edge_j: np.ndarray,
    meas: np.ndarray,
    prior_idx: np.ndarray,
    prior_poses: np.ndarray,
    prior_sqrt_info: Optional[np.ndarray] = None,
    fixed: Optional[np.ndarray] = None,
    sqrt_info: Optional[np.ndarray] = None,
):
    """Augment a pose graph with unary PRIOR factors.

    The reference lists "prior factor" as an unimplemented TODO
    (reference README.md:20); here it costs no new machinery at all: a
    prior anchoring pose i to T_prior with information Omega is EXACTLY
    a between-factor edge from a virtual FIXED pose holding T_prior to
    pose i with identity measurement — between_residual then evaluates
    [log(R_prior^T R_i); R_prior^T (t_i - t_prior)], the standard prior
    residual, and the virtual pose (fixed) contributes no columns.

    Returns (poses0', edge_i', edge_j', meas', fixed', sqrt_info')
    ready for solve_pgo / solve_pgo_checkpointed.  `prior_sqrt_info`
    [P, 6, 6] weights each prior (W^T W = Omega); when either weight
    input is present the other side is padded with identities so the
    combined sqrt_info stays well-formed.

    Note the returned pose array gains P trailing virtual poses; the
    solver result's `poses[:N]` are the real ones (the virtual anchors
    are fixed, so they come back unchanged).
    """
    poses0 = np.asarray(poses0, np.float64)
    prior_idx = np.asarray(prior_idx, np.int32)
    prior_poses = np.asarray(prior_poses, np.float64)
    n, p = poses0.shape[0], prior_idx.shape[0]
    if prior_poses.shape != (p, POSE_DIM):
        raise ValueError(
            f"prior_poses must be [{p}, {POSE_DIM}], got {prior_poses.shape}")
    if p and (prior_idx.min() < 0 or prior_idx.max() >= n):
        raise ValueError("prior_idx out of range")

    poses_aug = np.concatenate([poses0, prior_poses])
    ei_aug = np.concatenate(
        [np.asarray(edge_i, np.int32),
         np.arange(n, n + p, dtype=np.int32)])
    ej_aug = np.concatenate([np.asarray(edge_j, np.int32), prior_idx])
    meas_aug = np.concatenate(
        [np.asarray(meas, np.float64), np.zeros((p, POSE_DIM))])

    if fixed is None:
        fixed_aug = np.zeros(n + p, bool)
        # Priors ARE gauge information: only default-anchor pose 0 when
        # nothing else constrains the gauge.
        if p == 0:
            fixed_aug[0] = True
    else:
        fixed_aug = np.concatenate([np.asarray(fixed, bool),
                                    np.ones(p, bool)])
    fixed_aug[n:] = True  # virtual anchor poses never move

    n_e = np.asarray(edge_i).shape[0]
    if sqrt_info is None and prior_sqrt_info is None:
        si_aug = None
    else:
        base = (np.asarray(sqrt_info, np.float64) if sqrt_info is not None
                else np.broadcast_to(np.eye(POSE_DIM),
                                     (n_e, POSE_DIM, POSE_DIM)))
        pri = (np.asarray(prior_sqrt_info, np.float64)
               if prior_sqrt_info is not None
               else np.broadcast_to(np.eye(POSE_DIM),
                                    (p, POSE_DIM, POSE_DIM)))
        if base.shape != (n_e, POSE_DIM, POSE_DIM):
            raise ValueError(
                f"sqrt_info must be [{n_e}, {POSE_DIM}, {POSE_DIM}], "
                f"got {base.shape}")
        if pri.shape != (p, POSE_DIM, POSE_DIM):
            raise ValueError(
                f"prior_sqrt_info must be [{p}, {POSE_DIM}, {POSE_DIM}], "
                f"got {pri.shape}")
        si_aug = np.concatenate([base, pri])
    return poses_aug, ei_aug, ej_aug, meas_aug, fixed_aug, si_aug


@dataclasses.dataclass
class SyntheticPoseGraph:
    """Ground truth + drifted odometry init for a loop-closed graph."""

    poses_gt: np.ndarray  # [N, 6]
    poses0: np.ndarray
    edge_i: np.ndarray
    edge_j: np.ndarray
    meas: np.ndarray  # [nE, 6]


def spanning_tree_init(
    poses0: np.ndarray,
    edge_i: np.ndarray,
    edge_j: np.ndarray,
    meas: np.ndarray,
    fixed: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Re-initialize poses by composing measurements along a BFS tree.

    The standard pose-graph bootstrap (what g2o practitioners run before
    LM): anchors keep their input pose; every other pose is reached by
    composing between-factor measurements along a breadth-first spanning
    tree from the nearest anchor, traversing edges forward
    (T_j = T_i o m) or backward (T_i = T_j o m^{-1}).  Far more robust
    than trusting arbitrary VERTEX estimates from a .g2o export, and
    exact on noise-free odometry.  Poses unreachable from any anchor
    keep their input estimate.  Host-side numpy (core/host_se3).
    """
    from collections import deque

    poses0 = np.asarray(poses0, np.float64)
    n = poses0.shape[0]
    edge_i = np.asarray(edge_i)
    edge_j = np.asarray(edge_j)
    meas = np.asarray(meas, np.float64)
    if fixed is None:
        fixed_np = np.zeros(n, bool)
        fixed_np[0] = True
    else:
        fixed_np = np.asarray(fixed, bool)
        if not fixed_np.any():
            fixed_np = fixed_np.copy()
            fixed_np[0] = True

    adj: list[list[tuple[int, int, bool]]] = [[] for _ in range(n)]
    for k in range(len(edge_i)):
        a, b = int(edge_i[k]), int(edge_j[k])
        adj[a].append((b, k, True))   # forward: T_b = T_a o m_k
        adj[b].append((a, k, False))  # backward: T_a = T_b o m_k^{-1}

    out = poses0.copy()
    seen = fixed_np.copy()
    queue = deque(np.nonzero(fixed_np)[0].tolist())
    # Inverse measurement: T^{-1} = (R^T, -R^T t) = relative(T, identity).
    inv_meas = relative(meas, np.zeros_like(meas))
    while queue:
        a = queue.popleft()
        for b, k, forward in adj[a]:
            if seen[b]:
                continue
            seen[b] = True
            out[b] = compose(out[a], meas[k] if forward else inv_meas[k])
            queue.append(b)
    return out


def make_synthetic_pose_graph(
    num_poses: int = 32,
    loop_closures: int = 6,
    meas_noise: float = 0.0,
    drift_noise: float = 0.05,
    seed: int = 0,
) -> SyntheticPoseGraph:
    """A circle trajectory with odometry edges + random loop closures.

    Measurements are exact relative poses (+ optional noise); the init
    integrates NOISY odometry, so it drifts — the classic PGO setting
    where loop closures pull the chain back onto the circle.  All host
    math is batched numpy (core/host_se3.py), so generation scales to
    100k+ poses.
    """
    rng = np.random.default_rng(seed)
    th = 2 * np.pi * np.arange(num_poses) / num_poses
    poses_gt = np.zeros((num_poses, 6))
    poses_gt[:, 2] = th
    poses_gt[:, 3] = np.cos(th)
    poses_gt[:, 4] = np.sin(th)
    poses_gt[:, 5] = 0.05 * np.sin(3 * th)

    ei = list(range(num_poses - 1))
    ej = list(range(1, num_poses))
    for _ in range(loop_closures):
        a = int(rng.integers(0, num_poses - 4))
        b = int(rng.integers(a + 2, num_poses))
        ei.append(a)
        ej.append(b)
    ei, ej = np.asarray(ei, np.int32), np.asarray(ej, np.int32)

    meas = (relative(poses_gt[ei], poses_gt[ej])
            + meas_noise * rng.standard_normal((len(ei), 6)))

    poses0 = poses_gt.copy()
    cur = poses_gt[0].copy()
    odo_noise = drift_noise * rng.standard_normal((num_poses - 1, 6))
    for k in range(1, num_poses):
        cur = compose(cur, meas[k - 1] + odo_noise[k - 1])
        poses0[k] = cur
    return SyntheticPoseGraph(
        poses_gt=poses_gt, poses0=poses0, edge_i=ei, edge_j=ej, meas=meas)
