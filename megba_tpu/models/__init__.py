"""Model families.

`bal` — the flagship 3D Bundle-Adjustment-in-the-Large model (9-dof
cameras, 3D points, 2D reprojections): the problem family all six
reference examples solve.

`planar` — 2D bundle adjustment (3-dof SE(2) pose + focal, 2D points, 1D
image line): exercises the generic engine with different block sizes and
the rotation2D geometry op (reference src/geo/rotation2D.cu; its SE2
vertex, include/vertex/SE2_vertex.h, is dead code — this family is the
live equivalent).

Every model is just a residual function (+ optional closed-form
Jacobian); the whole solver stack is dimension-generic.
"""

from megba_tpu.models import bal, planar

__all__ = ["bal", "planar"]
