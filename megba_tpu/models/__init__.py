"""Model families.

`bal` — the flagship 3D Bundle-Adjustment-in-the-Large model (9-dof
cameras, 3D points, 2D reprojections): the problem family all six
reference examples solve.

`planar` — 2D bundle adjustment (3-dof SE(2) pose + focal, 2D points, 1D
image line): exercises the generic engine with different block sizes and
the rotation2D geometry op (reference src/geo/rotation2D.cu; its SE2
vertex, include/vertex/SE2_vertex.h, is dead code — this family is the
live equivalent).

`pgo` — SE(3) pose-graph optimization (between-factors connecting two
vertices of the SAME kind): a family the reference cannot express at
all (its BaseEdge hard-wires one camera + one landmark per edge), built
from the same feature-major / segment-reduction / PCG primitives with a
matrix-free Gauss-Newton operator.

Every model is just a residual function (+ optional closed-form
Jacobian); the BA solver stack is dimension-generic, and the PGO family
shows the primitives compose into a different normal-equation topology.
"""

from megba_tpu.models import bal, pgo, planar

__all__ = ["bal", "pgo", "planar"]
