"""Planar (2D) bundle adjustment model family.

A camera is an SE(2) pose plus focal length: [theta, tx, ty, f]; points
are 2D; each observation is the 1D image coordinate of a point on the
camera's image line:

    p_cam = R(theta) X + t        (R from geo.rotation2d_to_matrix —
                                   the live use of the reference's
                                   rotation2D kernel, src/geo/rotation2D.cu)
    u     = f * p_cam[0] / p_cam[1]
    r     = u - obs

The solver stack is dimension-generic, so this family runs through the
same LM / Schur-PCG / sharding machinery as BAL with camera_dim=4,
point_dim=2, obs_dim=1.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from megba_tpu.ops import geo

CAMERA_DIM = 4
POINT_DIM = 2
OBS_DIM = 1


def residual(camera: jnp.ndarray, point: jnp.ndarray, obs: jnp.ndarray) -> jnp.ndarray:  # megba: jit-entry
    """1D reprojection residual for one planar edge."""
    theta = camera[0]
    t = camera[1:3]
    f = camera[3]
    R = geo.rotation2d_to_matrix(theta)
    p = geo.mm(R, point[:, None])[:, 0] + t
    return f * p[0:1] / p[1:2] - obs


@dataclasses.dataclass
class SyntheticPlanar:
    """Ground truth + perturbed init for a synthetic planar scene."""

    cameras_gt: np.ndarray
    points_gt: np.ndarray
    cameras0: np.ndarray
    points0: np.ndarray
    obs: np.ndarray
    cam_idx: np.ndarray
    pt_idx: np.ndarray


def make_synthetic_planar(
    num_cameras: int = 6,
    num_points: int = 40,
    obs_per_point: int = 3,
    noise: float = 0.1,
    param_noise: float = 2e-2,
    seed: int = 0,
    dtype=np.float64,
) -> SyntheticPlanar:
    """Points in a strip ahead of +y-looking cameras along the x axis."""
    r = np.random.default_rng(seed)
    obs_per_point = min(obs_per_point, num_cameras)
    points_gt = np.stack(
        [r.uniform(-2, 2, num_points), r.uniform(4, 8, num_points)], axis=1)
    cameras_gt = np.zeros((num_cameras, 4))
    cameras_gt[:, 0] = r.normal(scale=0.05, size=num_cameras)  # small heading
    cameras_gt[:, 1] = np.linspace(-1, 1, num_cameras)  # tx along a rail
    cameras_gt[:, 2] = r.normal(scale=0.05, size=num_cameras)  # ty
    cameras_gt[:, 3] = 300.0 + r.normal(scale=3.0, size=num_cameras)  # focal

    base = r.integers(0, num_cameras, size=(num_points, 1))
    stride = 1 + r.integers(0, max(num_cameras // max(obs_per_point, 1), 1),
                            size=(num_points, 1))
    cam_idx = ((base + np.arange(obs_per_point)[None, :] * stride) % num_cameras).reshape(-1)
    pt_idx = np.repeat(np.arange(num_points), obs_per_point)

    # Ground-truth observations come from the MODEL ITSELF (residual with
    # obs=0 is the projection), so generator and residual can never
    # diverge.
    import jax

    proj = np.asarray(jax.vmap(residual)(
        cameras_gt[cam_idx], points_gt[pt_idx],
        np.zeros((len(cam_idx), 1))))
    obs = proj + r.normal(scale=noise, size=proj.shape)

    order = np.argsort(cam_idx, kind="stable")
    cameras0 = cameras_gt + r.normal(scale=param_noise, size=cameras_gt.shape) * np.array(
        [1.0, 1.0, 1.0, 50.0])
    points0 = points_gt + r.normal(scale=param_noise, size=points_gt.shape)
    return SyntheticPlanar(
        cameras_gt=cameras_gt.astype(dtype),
        points_gt=points_gt.astype(dtype),
        cameras0=cameras0.astype(dtype),
        points0=points0.astype(dtype),
        obs=obs[order].astype(dtype),
        cam_idx=cam_idx[order].astype(np.int32),
        pt_idx=pt_idx[order].astype(np.int32),
    )
