from megba_tpu.utils.debug import assert_all_finite, describe_array, print_blocks
from megba_tpu.utils.timing import PhaseTimer, trace_profile
from megba_tpu.utils.checkpoint import load_state, save_state

__all__ = [
    "PhaseTimer",
    "assert_all_finite",
    "describe_array",
    "load_state",
    "print_blocks",
    "save_state",
    "trace_profile",
]
