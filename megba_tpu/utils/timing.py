"""Timing / profiling hooks.

The reference's only observability is wall-clock deltas printed per LM
iteration (lm_algo.cu:141,157-161,215-219).  Here: `PhaseTimer` collects
named phase timings (block_until_ready-accurate), and `trace_profile`
wraps a block in a `jax.profiler` trace for TensorBoard/Perfetto — the
TPU-native upgrade path (SURVEY.md §5.1).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Optional

import jax

# ---------------------------------------------------------------------------
# Clock discipline.  These two helpers are the ONLY sanctioned raw-clock
# reads outside observability/ — the `raw-clock` lint rule
# (analysis/rules.py) forbids time.time()/time.perf_counter() everywhere
# else, so every latency measurement in the package goes through the
# monotonic clock (immune to NTP steps) and every timestamp that must be
# comparable across hosts is an explicit, named wall-clock read.


def monotonic_s() -> float:
    """Monotonic seconds for measuring durations (never wall clock)."""
    return time.perf_counter()


def wall_unix() -> float:
    """Unix wall-clock seconds, for report timestamps only — never for
    durations (NTP steps make wall-clock deltas lie)."""
    return time.time()


# Optional observer of completed PhaseTimer phases: the spans recorder
# (observability/spans.py) installs a hook so every timed phase joins the
# active trace as a child span.  Module-level on purpose — phases fire
# deep inside solve paths that never see a recorder object.  Hook
# signature: (name, duration_s).  Exceptions are swallowed: telemetry
# must never fail a solve.
_PHASE_HOOK: Optional[Callable[[str, float], None]] = None


def set_phase_hook(hook: Optional[Callable[[str, float], None]]) -> None:
    global _PHASE_HOOK
    _PHASE_HOOK = hook


class _Phase:
    """Handle yielded by PhaseTimer.phase; register outputs to sync on."""

    def __init__(self):
        self._targets = []

    def sync(self, x):
        """Mark `x` (array/pytree produced inside the block) to be
        block_until_ready'd before the phase's clock stops; returns x."""
        self._targets.append(x)
        return x


class PhaseTimer:
    """Accumulates wall-clock per named phase; device-sync aware.

    JAX dispatch is asynchronous, so an un-synced phase measures only
    dispatch time.  Register the block's outputs on the yielded handle:

        with timer.phase("pcg") as ph:
            out = ph.sync(pcg_solve(...))
    """

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        handle = _Phase()
        # TraceAnnotation: each phase shows up as a named host-side span
        # in jax.profiler traces (trace_profile -> TensorBoard/Perfetto),
        # so the phase breakdown and the profiler timeline line up.
        with jax.profiler.TraceAnnotation(f"megba.phase.{name}"):
            t0 = time.perf_counter()
            try:
                yield handle
            finally:
                for t in handle._targets:
                    jax.block_until_ready(t)
                dt = time.perf_counter() - t0
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1
                if _PHASE_HOOK is not None:
                    try:
                        _PHASE_HOOK(name, dt)
                    except Exception:
                        pass

    def count_event(self, name: str, n: int = 1) -> None:
        """Count an instantaneous event (zero duration) — e.g. the host
        plan cache's `plan_cache_hit` counter.  Shows up in `as_dict()`
        / `report()` with total_s 0.0 and `calls` = occurrence count, so
        the SolveReport/bench phase schema is unchanged."""
        self.totals.setdefault(name, 0.0)
        self.counts[name] = self.counts.get(name, 0) + n

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        """{name: {total_s, calls}} — the SolveReport `phases` payload."""
        return {name: {"total_s": self.totals[name],
                       "calls": self.counts[name]}
                for name in self.totals}

    def reset(self) -> None:
        """Drop all accumulated phases (reuse one timer across solves)."""
        self.totals.clear()
        self.counts.clear()

    def report(self) -> str:
        if not self.totals:
            return "no phases recorded"
        lines = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            t, c = self.totals[name], self.counts[name]
            lines.append(f"{name}: {t * 1e3:.1f} ms total / {c} calls = {t / c * 1e3:.2f} ms")
        total = sum(self.totals.values())
        lines.append(
            f"total: {total * 1e3:.1f} ms over {len(self.totals)} phases")
        return "\n".join(lines)


@contextlib.contextmanager
def trace_profile(logdir: Optional[str]):
    """jax.profiler trace context; no-op when logdir is None."""
    if logdir is None:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
