"""Debug / inspection helpers.

Functional parity with the reference's debug layer (include/macro.h):
`PRINT_DMEMORY`/`PRINT_DCSR` device-memory dumps (macro.h:14-84) become
`describe_array`/`print_blocks` (arrays are host-visible in JAX, so these
are formatting helpers rather than device-copy machinery), and the
`ASSERT_CUDA_NO_ERROR` / `ASSERT_HOST_NO_MEM_ERROR` macros (macro.h:49-95)
map to `assert_all_finite`, the failure mode a functional pipeline can
actually hit (NaN/Inf poisoning).  Like the reference's DEBUG-gating
(macro.h:96-108), `assert_all_finite` is a no-op inside jit unless
`debug=True` wires it through `jax.debug.callback`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def describe_array(name: str, x: Any, max_items: int = 8) -> str:
    """One-line summary: shape, dtype, range, norm, first items."""
    a = np.asarray(x)
    if a.size == 0:
        return f"{name}: shape={a.shape} (empty)"
    flat = a.reshape(-1)
    head = ", ".join(f"{v:.5g}" for v in flat[:max_items])
    finite = np.isfinite(flat)
    extra = "" if finite.all() else f" NONFINITE={int((~finite).sum())}"
    return (
        f"{name}: shape={a.shape} dtype={a.dtype} "
        f"min={flat.min():.5g} max={flat.max():.5g} "
        f"|x|={np.linalg.norm(flat):.5g}{extra} [{head}{', ...' if flat.size > max_items else ''}]"
    )


def print_blocks(name: str, blocks: Any, indices: Optional[range] = None) -> None:
    """Pretty-print a few [N, d, d] Hessian blocks (PRINT_DCSR's role of
    eyeballing assembled system content, macro.h:61-84)."""
    b = np.asarray(blocks)
    indices = indices if indices is not None else range(min(2, b.shape[0]))
    print(f"{name}: {b.shape[0]} blocks of {b.shape[1]}x{b.shape[2]}")
    for i in indices:
        with np.printoptions(precision=4, suppress=True):
            print(f"  block[{i}] =\n{np.asarray(b[i])}")


def assert_all_finite(x: jax.Array, name: str = "array", debug: bool = False) -> jax.Array:
    """Identity passthrough that raises if x contains non-finite values.

    Outside jit: checks eagerly and raises FloatingPointError.  Inside
    jit: DEBUG-gated like the reference's macros (macro.h:96-108) — a
    no-op unless `debug=True`, in which case a host callback raises; note
    JAX dispatch is asynchronous, so the error surfaces at the next
    blocking point wrapped in a runtime error naming this message, not as
    a catchable FloatingPointError at the call site.  For a catchable
    check, assert on concrete outputs outside jit.
    """
    if isinstance(x, jax.core.Tracer):
        if debug:
            def _check(bad_count):
                if int(bad_count) > 0:
                    raise FloatingPointError(
                        f"{name} contains {int(bad_count)} non-finite values"
                    )

            jax.debug.callback(_check, jnp.sum(~jnp.isfinite(x)))
        return x
    a = np.asarray(x)
    if not np.isfinite(a).all():
        raise FloatingPointError(
            f"{name} contains {int((~np.isfinite(a)).sum())} non-finite values"
        )
    return x
