"""Capture and parse the LM solver's verbose per-iteration lines.

The per-iteration `iter k: cost ...` line
(observability/emit.py:_emit_verbose_line — the reference's observable,
lm_algo.cu:149-162) is the source of the
cost-curve evidence artifacts (DOUBLE_PARITY.json, MIXED_PRECISION.json).
One shared parser keeps those scripts in lockstep with the emit format:
a format drift raises here instead of silently producing empty curves
in the committed artifacts.
"""

from __future__ import annotations

import contextlib
import io
import re
from typing import Callable, Optional

# The cost group matches nan/inf too: a diverged run's iterations must
# stay visible in the parsed curve (and the committed artifacts) instead
# of vanishing — an all-nan solve previously looked like a verbose
# format drift rather than the divergence it was.
_LINE = re.compile(
    r"iter (\d+): cost (-?(?:[0-9.eE+-]+|nan|inf)) .*accept (True|False) "
    r"pcg_iters (\d+)")


def parse_verbose_curve(text: str, require: bool = True) -> list[dict]:
    """Verbose solver stdout -> [{iter, cost, accept, pcg_iters}, ...]."""
    curve = [
        {"iter": int(m.group(1)), "cost": float(m.group(2)),
         "accept": m.group(3) == "True", "pcg_iters": int(m.group(4))}
        for m in _LINE.finditer(text)]
    if require and not curve:
        raise ValueError(
            "no verbose iteration lines matched — did the solver's "
            "verbose format (observability/emit.py:_emit_verbose_line) "
            "change without updating utils/curves._LINE?")
    return curve


class _Tee(io.TextIOBase):
    """Buffer that also passes writes through to a live stream."""

    def __init__(self, passthrough):
        self.buf = io.StringIO()
        self._live = passthrough

    def write(self, s):
        self.buf.write(s)
        self._live.write(s)
        return len(s)

    def flush(self):
        self._live.flush()


def run_with_curve(fn: Callable[[], object],
                   block_on: Optional[Callable[[object], object]] = None,
                   tee: bool = False):
    """Run `fn` capturing stdout; return (result, curve).

    `block_on(result)` (default: jax.block_until_ready on the result)
    runs INSIDE the capture so asynchronously-emitted verbose callbacks
    have flushed before parsing.  `tee=True` additionally passes every
    line through to the real stdout as it is emitted — use it for
    long runs so a crash mid-solve still leaves the per-iteration
    forensics in the log instead of dying inside the buffer.
    """
    import sys

    import jax

    buf = _Tee(sys.stdout) if tee else io.StringIO()
    with contextlib.redirect_stdout(buf):
        result = fn()
        if block_on is None:
            jax.block_until_ready(result)
        else:
            block_on(result)
    text = buf.buf.getvalue() if tee else buf.getvalue()
    return result, parse_verbose_curve(text)


def dtype_parity_payload(solve_for, rel_tol, label="", block_on=None,
                         gap_tol=None):
    """The f64-vs-f32 parity protocol, defined once for every family.

    `solve_for(np_dtype)` runs one verbose solve and returns a result
    with cost/initial_cost/iterations/accepted/pcg_iterations fields
    (LMResult and PGOResult both qualify).  Runs f64 then f32, captures
    both curves, and returns the payload dict with the two runs, the
    final-cost relative difference, and the PER-ITERATION relative gaps
    over the common prefix of the two curves (the trajectories must
    track each other, not merely coincide at the optimum).

    Pass criterion: final relative difference <= `rel_tol` AND the
    maximum per-iteration gap <= `gap_tol` (default `100 * rel_tol` —
    two orders looser than the final-cost bar, because mid-trajectory
    f32 rounding legitimately wobbles before convergence pulls the
    curves together; committed artifacts sit ~1e-7 at rel_tol=1e-4).
    When the runs take different iteration counts the payload records
    `iterations_equal=False` and `curve_len_{f64,f32}` instead of
    silently zip-truncating the comparison.
    """
    import numpy as np

    from megba_tpu.utils.timing import monotonic_s

    runs = {}
    for dtype in (np.float64, np.float32):
        t0 = monotonic_s()
        res, curve = run_with_curve(lambda: solve_for(dtype),
                                    block_on=block_on)
        elapsed = monotonic_s() - t0
        runs[np.dtype(dtype).name] = {
            "initial_cost": float(res.initial_cost),
            "final_cost": float(res.cost),
            "iterations": int(res.iterations),
            "accepted": int(res.accepted),
            "pcg_iterations": int(res.pcg_iterations),
            "elapsed_s": round(elapsed, 3),
            "curve": curve,
        }
        print(f"[{label}] {np.dtype(dtype).name}: "
              f"{float(res.initial_cost):.6e} -> {float(res.cost):.6e} "
              f"in {int(res.iterations)} iters ({elapsed:.1f}s)",
              flush=True)
    r64, r32 = runs["float64"], runs["float32"]
    gap_tol = 100.0 * rel_tol if gap_tol is None else gap_tol
    rel = abs(r32["final_cost"] - r64["final_cost"]) / max(
        r64["final_cost"], 1e-300)
    gaps = [
        abs(b["cost"] - a["cost"]) / max(abs(a["cost"]), 1e-300)
        for a, b in zip(r64["curve"], r32["curve"])]
    max_gap = max(gaps, default=0.0)
    payload = {
        "runs": runs,
        "final_rel_diff": rel,
        "curve_rel_gaps": gaps,
        "max_curve_rel_gap": max_gap,
        "iterations_equal": len(r64["curve"]) == len(r32["curve"]),
        "curve_len_f64": len(r64["curve"]),
        "curve_len_f32": len(r32["curve"]),
        "rel_tol": rel_tol,
        "gap_tol": gap_tol,
        "pass": bool(rel <= rel_tol and max_gap <= gap_tol),
    }
    print(f"[{label}] final rel diff {rel:.3e}, max curve gap "
          f"{max_gap:.3e} over {len(gaps)} common iters "
          f"({'PASS' if payload['pass'] else 'FAIL'} at rel_tol={rel_tol}, "
          f"gap_tol={gap_tol})",
          flush=True)
    return payload
