"""Capture and parse the LM solver's verbose per-iteration lines.

The per-iteration `iter k: cost ...` line (algo/lm.py:_emit_verbose_line
— the reference's observable, lm_algo.cu:149-162) is the source of the
cost-curve evidence artifacts (DOUBLE_PARITY.json, MIXED_PRECISION.json).
One shared parser keeps those scripts in lockstep with the emit format:
a format drift raises here instead of silently producing empty curves
in the committed artifacts.
"""

from __future__ import annotations

import contextlib
import io
import re
from typing import Callable, Optional

_LINE = re.compile(
    r"iter (\d+): cost ([0-9.eE+-]+) .*accept (True|False) "
    r"pcg_iters (\d+)")


def parse_verbose_curve(text: str, require: bool = True) -> list[dict]:
    """Verbose solver stdout -> [{iter, cost, accept, pcg_iters}, ...]."""
    curve = [
        {"iter": int(m.group(1)), "cost": float(m.group(2)),
         "accept": m.group(3) == "True", "pcg_iters": int(m.group(4))}
        for m in _LINE.finditer(text)]
    if require and not curve:
        raise ValueError(
            "no verbose iteration lines matched — did the solver's "
            "verbose format (algo/lm.py:_emit_verbose_line) change "
            "without updating utils/curves._LINE?")
    return curve


class _Tee(io.TextIOBase):
    """Buffer that also passes writes through to a live stream."""

    def __init__(self, passthrough):
        self.buf = io.StringIO()
        self._live = passthrough

    def write(self, s):
        self.buf.write(s)
        self._live.write(s)
        return len(s)

    def flush(self):
        self._live.flush()


def run_with_curve(fn: Callable[[], object],
                   block_on: Optional[Callable[[object], object]] = None,
                   tee: bool = False):
    """Run `fn` capturing stdout; return (result, curve).

    `block_on(result)` (default: jax.block_until_ready on the result)
    runs INSIDE the capture so asynchronously-emitted verbose callbacks
    have flushed before parsing.  `tee=True` additionally passes every
    line through to the real stdout as it is emitted — use it for
    long runs so a crash mid-solve still leaves the per-iteration
    forensics in the log instead of dying inside the buffer.
    """
    import sys

    import jax

    buf = _Tee(sys.stdout) if tee else io.StringIO()
    with contextlib.redirect_stdout(buf):
        result = fn()
        if block_on is None:
            jax.block_until_ready(result)
        else:
            block_on(result)
    text = buf.buf.getvalue() if tee else buf.getvalue()
    return result, parse_verbose_curve(text)
