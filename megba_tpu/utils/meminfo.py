"""XLA memory analysis of the production single-chip LM program.

Shared by the evidence scripts (scripts/hbm_budget.py,
scripts/jacobian_mode_bench.py): one definition of "lower + compile the
single-solve program for this synthetic problem and read
compiled.memory_analysis()" so the two artifacts can never measure
subtly different programs.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def single_solve_memory_analysis(s, option, residual_jac_fn,
                                 keys: tuple = ()) -> dict:
    """memory_analysis() of the jitted single-device solve, as a dict.

    `s` is a synthetic problem (io/synthetic.make_synthetic_bal result);
    edges are camera-sorted + quantum-padded exactly as flat_solve's
    non-tiled path does, and the program is the production
    _build_single_solve one.  The returned dict ALWAYS carries
    `n_edges_padded` (callers size analytic models from it); the
    XLA byte fields are present only when the backend exposes a
    memory analysis.
    """
    import jax.numpy as jnp

    from megba_tpu.core.types import pad_edges
    from megba_tpu.native import sort_edges_by_camera
    from megba_tpu.observability.emit import next_verbose_token
    from megba_tpu.solve import EDGE_QUANTUM, _build_single_solve

    dtype = np.dtype(option.dtype)
    n_cam = s.cameras0.shape[0]
    perm = sort_edges_by_camera(s.cam_idx, n_cam)
    obs, ci, pi = s.obs[perm], s.cam_idx[perm], s.pt_idx[perm]
    obs, ci, pi, mask = pad_edges(obs, ci, pi, EDGE_QUANTUM, dtype=dtype)

    jitted = _build_single_solve(residual_jac_fn, option, keys, False, True)
    args = (
        jnp.asarray(np.ascontiguousarray(s.cameras0.T)),
        jnp.asarray(np.ascontiguousarray(s.points0.T)),
        jnp.asarray(np.ascontiguousarray(obs.T)),
        jnp.asarray(ci), jnp.asarray(pi), jnp.asarray(mask),
        jnp.asarray(1e3, dtype), jnp.asarray(2.0, dtype),
        jnp.asarray(next_verbose_token(), jnp.int32), None)
    ma = jitted.lower(*args).compile().memory_analysis()
    out: dict = {"n_edges_padded": int(obs.shape[0])}
    if ma is None:
        return out
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    out["peak_estimate_bytes"] = (
        out.get("argument_size_in_bytes", 0)
        + out.get("output_size_in_bytes", 0)
        + out.get("temp_size_in_bytes", 0)
        - out.get("alias_size_in_bytes", 0))
    return out


def device_memory_stats(device=None) -> Optional[dict]:
    """Live allocator stats of one device, or None when unavailable.

    TPU/GPU backends expose `Device.memory_stats()` (bytes_in_use,
    peak_bytes_in_use, bytes_limit, ...); XLA:CPU does not — telemetry
    (observability/report.py) records whatever the backend offers and
    omits the section otherwise, so reports stay backend-portable.
    Unlike `single_solve_memory_analysis` this costs no compilation: it
    reads counters, so it is cheap enough for the per-solve report path.
    """
    import jax

    if device is None:
        local = jax.local_devices()
        if not local:
            return None
        device = local[0]
    try:
        stats = device.memory_stats()
    except (AttributeError, RuntimeError, NotImplementedError):
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, np.integer))}
