"""Call-shape-normalising memoisation.

`functools.lru_cache` keys the RAW call shape: `f(x)` and `f(arg=x)` are
two different cache entries even though they run the same code on the
same value.  For ordinary pure functions that is merely a wasted slot;
for ENGINE/PROGRAM factories it is a correctness hazard — every cache in
the solver stack that keys on engine identity (the jit program caches,
the serving compile pool, the retrace sentinel's static keys) silently
doubles when one call site spells a keyword and another does not, and
the duplicate engine then costs a full duplicate trace + XLA compile.

PR 6 fixed exactly that footgun on `make_residual_jacobian_fn` with a
hand-written positional-binding wrapper, and PR 8 repeated the pattern
on `batched_solve_program`.  `normalized_lru_cache` is the general
form: it binds every call against the wrapped function's signature
(defaults applied), so ALL spellings of one logical call — positional,
keyword, defaulted, reordered keywords — collapse onto a single cache
entry.  The factor registry's engine lookups (megba_tpu/factors/
engine.py) ride it too, which is what makes "one factor config, one
engine object, one compiled program" a structural property instead of a
call-site convention.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def normalized_lru_cache(maxsize: int = 64) -> Callable[[F], F]:
    """`functools.lru_cache` behind signature-normalised call binding.

    Every call is bound against the wrapped function's signature with
    defaults applied and forwarded as a canonical positional tuple, so
    keyword vs positional vs defaulted spellings of the same logical
    call hit ONE entry.  Var-positional/var-keyword parameters are
    rejected at decoration time: they have no canonical positional
    form, and a factory taking **kwargs should not be memoised this way.

    The wrapper exposes `cache_clear()` / `cache_info()` (forwarded to
    the underlying lru) and `__wrapped__` (the original function).
    """

    def deco(fn: F) -> F:
        sig = inspect.signature(fn)
        for p in sig.parameters.values():
            if p.kind in (inspect.Parameter.VAR_POSITIONAL,
                          inspect.Parameter.VAR_KEYWORD):
                raise TypeError(
                    f"normalized_lru_cache cannot canonicalise *args/"
                    f"**kwargs parameter {p.name!r} of {fn.__qualname__}")
        order = tuple(sig.parameters)
        cached = functools.lru_cache(maxsize=maxsize)(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()
            return cached(*(bound.arguments[name] for name in order))

        wrapper.cache_clear = cached.cache_clear  # type: ignore[attr-defined]
        wrapper.cache_info = cached.cache_info  # type: ignore[attr-defined]
        return wrapper  # type: ignore[return-value]

    return deco
