"""Backend health probing (shared by bench.py and __graft_entry__.py).

The axon TPU tunnel is single-client; a client that died mid-claim can
wedge it so that JAX backend initialisation hangs forever.  Probing in a
child process with a timeout lets driver-facing scripts fall back to CPU
and keep reporting instead of hanging.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_probe_result: Optional[bool] = None


def accelerator_usable(timeout_s: float = 120.0) -> bool:
    """True when `import jax; jax.devices()` completes in a subprocess.

    Cached per process (one probe covers every entry point).
    """
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True)
        _probe_result = proc.returncode == 0
    except subprocess.TimeoutExpired:
        _probe_result = False
    return _probe_result


def install_graceful_term() -> None:
    """Convert SIGTERM into a clean SystemExit (atexit runs).

    Python's default SIGTERM disposition kills the process without
    cleanup; for a process holding the single-client accelerator tunnel
    that orphans the claim server-side and wedges the tunnel for every
    later process (observed twice in this sandbox — hours of outage).  A
    clean exit lets the PJRT client teardown release the claim.  Install
    in every chip-facing entry point BEFORE backend init.
    """
    import signal

    def _term(signum, frame):
        raise SystemExit(143)

    try:
        signal.signal(signal.SIGTERM, _term)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass


def ensure_usable_backend(timeout_s: float = 120.0) -> bool:
    """Pin jax to CPU when accelerator init would hang.

    Returns True when the fallback was applied.  Honours
    MEGBA_BENCH_SKIP_PROBE=1 (no probe, trust the environment).  Must be
    called before the first jax device query of the process.
    """
    if os.environ.get("MEGBA_BENCH_SKIP_PROBE") == "1":
        return False
    if accelerator_usable(timeout_s):
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
