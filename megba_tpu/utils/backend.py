"""Backend health probing (shared by bench.py and __graft_entry__.py).

The axon TPU tunnel is single-client; a client that died mid-claim can
wedge it so that JAX backend initialisation hangs forever.  Probing in a
child process with a timeout lets driver-facing scripts fall back to CPU
and keep reporting instead of hanging.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_probe_result: Optional[bool] = None


# The probe child installs a SIGTERM -> SystemExit handler BEFORE
# touching jax so that a timed-out probe exits through PJRT client
# teardown and releases its tunnel claim (a SIGKILLed child mid-claim
# orphans the claim server-side and wedges the tunnel — the exact
# failure subprocess.run(timeout=...)'s kill() would cause here).
_PROBE_SRC = (
    "import signal\n"
    "signal.signal(signal.SIGTERM, lambda s, f: (_ for _ in ()).throw("
    "SystemExit(143)))\n"
    "import jax\n"
    "jax.devices()\n"
)


def accelerator_usable(timeout_s: float = 120.0) -> bool:
    """True when `import jax; jax.devices()` completes in a subprocess.

    Cached per process (one probe covers every entry point).  A probe
    that exceeds the timeout is SIGTERMed (clean teardown in the child),
    with SIGKILL only as a 30 s last resort.
    """
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_SRC],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _probe_result = proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        _probe_result = False
        proc.terminate()
        try:
            proc.wait(timeout=30.0)
        except subprocess.TimeoutExpired:  # stuck in C code; no choice
            proc.kill()
            proc.wait()
    return _probe_result


def enable_persistent_compile_cache(cache_dir: Optional[str] = None) -> str:
    """Point JAX at an on-disk compilation cache and make it eager.

    Chip minutes through the single-client tunnel are the scarcest
    resource in this sandbox; without a persistent cache every tunnel
    window starts by recompiling the same venice-scale programs
    (tens of seconds to minutes each).  Call before the first jit in
    every chip-facing entry point.  MEGBA_COMPILE_CACHE_DIR overrides
    the default location; returns the directory used.

    min_compile_time_secs=0 caches even fast compiles (the warmup pass
    compiles tiny shapes first), and min_entry_size_bytes=0 keeps small
    executables.  Errors reading/writing the cache stay non-fatal
    (jax_raise_persistent_cache_errors defaults False).
    """
    import jax

    if cache_dir is None:
        cache_dir = (
            os.environ.get("MEGBA_COMPILE_CACHE_DIR")
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))), ".jax_cache"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def install_graceful_term() -> None:
    """Convert SIGTERM into a clean SystemExit (atexit runs).

    Python's default SIGTERM disposition kills the process without
    cleanup; for a process holding the single-client accelerator tunnel
    that orphans the claim server-side and wedges the tunnel for every
    later process (observed twice in this sandbox — hours of outage).  A
    clean exit lets the PJRT client teardown release the claim.  Install
    in every chip-facing entry point BEFORE backend init.
    """
    import signal

    def _term(signum, frame):
        raise SystemExit(143)

    try:
        signal.signal(signal.SIGTERM, _term)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass


def respect_jax_platforms() -> Optional[str]:
    """Re-assert the caller's JAX_PLATFORMS choice over the axon plugin.

    The axon plugin's register() (sitecustomize) overrides jax_platforms
    to "axon,cpu" at interpreter startup, so the env var alone does not
    keep a process off the TPU tunnel.  Call before any device query in
    every entry point that honours JAX_PLATFORMS (bench, profilers,
    CLIs).  Returns the env value when one was applied, else None.
    """
    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        import jax

        jax.config.update("jax_platforms", plats)
    return plats or None


def ensure_usable_backend(timeout_s: float = 120.0) -> bool:
    """Pin jax to CPU when accelerator init would hang.

    Returns True when the fallback was applied.  Honours
    MEGBA_BENCH_SKIP_PROBE=1 (no probe, trust the environment), and
    skips the probe entirely when the caller pinned a non-axon platform
    via JAX_PLATFORMS — probing would claim the single-client TPU
    tunnel from a process that has no intention of using it.  Must be
    called before the first jax device query of the process.
    """
    plats = respect_jax_platforms()
    if plats and "axon" not in plats:
        return False
    if os.environ.get("MEGBA_BENCH_SKIP_PROBE") == "1":
        return False
    if accelerator_usable(timeout_s):
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True


def warn_if_x64_unavailable(dtype) -> bool:
    """Warn when a float64 request will silently compute in float32.

    One shared precision contract for every public solve entry point
    (flat_solve, solve_pgo, ...).  Returns True when the warning fired.
    """
    import numpy as np

    import jax

    if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
        import warnings

        warnings.warn(
            "ProblemOption(dtype=float64) but jax x64 is disabled — JAX "
            "will silently compute in float32. Call "
            'jax.config.update("jax_enable_x64", True) first (CPU '
            "recommended; TPU float64 is emulated) or set dtype=float32.",
            stacklevel=3,
        )
        return True
    return False
