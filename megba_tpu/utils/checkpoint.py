"""Checkpoint / resume to disk.

The reference has NO on-disk checkpointing (SURVEY.md §5.4): its only
state persistence is the in-memory backup/rollback of the LM reject step,
which this framework replaces with functional carries.  Disk
checkpointing is therefore a capability this framework ADDS: a long
Final-13682-scale solve can snapshot (cameras, points, trust-region
state) each accepted iteration and resume after preemption — the
TPU-pod operational norm.

Plain .npz is used (self-contained, no orbax directory layout needed for
a handful of dense arrays); atomic via write-to-temp + rename.

To resume with full fidelity, thread the saved trust region back in:
`AlgoOption(initial_region=float(state["region"]))` — otherwise the
resumed solve restarts from the default region and re-adapts (costing a
few extra LM iterations, not correctness).
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Optional

import numpy as np


def save_state(path: str, cameras, points, *, region: float = None,
               cost: float = None, iteration: int = None,
               extra: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Atomically snapshot solver state to `path` (.npz)."""
    payload = {
        "cameras": np.asarray(cameras),
        "points": np.asarray(points),
    }
    if region is not None:
        payload["region"] = np.asarray(region)
    if cost is not None:
        payload["cost"] = np.asarray(cost)
    if iteration is not None:
        payload["iteration"] = np.asarray(iteration)
    for k, v in (extra or {}).items():
        payload[f"extra_{k}"] = np.asarray(v)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())  # data durable before the rename is
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_state(path: str) -> Dict[str, np.ndarray]:
    """Load a snapshot; returns dict with cameras/points (+ any extras)."""
    with np.load(path) as z:
        return {k: z[k] for k in z.files}
