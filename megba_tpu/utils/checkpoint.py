"""Checkpoint / resume to disk.

The reference has NO on-disk checkpointing (SURVEY.md §5.4): its only
state persistence is the in-memory backup/rollback of the LM reject step,
which this framework replaces with functional carries.  Disk
checkpointing is therefore a capability this framework ADDS: a long
Final-13682-scale solve can snapshot (cameras, points, trust-region
state) each accepted iteration and resume after preemption — the
TPU-pod operational norm.

Plain .npz is used (self-contained, no orbax directory layout needed for
a handful of dense arrays).  Preemption safety is end to end:

- **Atomic writes**: payload goes to a same-directory temp file, is
  fsync'd (data durable BEFORE the rename commits it), then
  `os.replace`d over the target — a SIGKILL at any byte leaves either
  the complete old snapshot or the complete new one, never a torn file.
- **Content checksum + schema version**: every snapshot carries a
  blake2b digest over its arrays and a format version; `load_state`
  recomputes and compares, so a corrupted or truncated snapshot raises
  a clear ValueError instead of feeding garbage state into a resume.
  (Snapshots written before the checksum existed load with a best-
  effort pass-through — they predate the guarantee, not violate it.)

To resume with full fidelity, thread the saved trust region back in:
`AlgoOption(initial_region=float(state["region"]))` — otherwise the
resumed solve restarts from the default region and re-adapts (costing a
few extra LM iterations, not correctness).

Schema v3 adds the WORLD/TOPOLOGY header (`world_size`,
`process_index`): a snapshot records the distribution it was written
under, so the elastic shrink-world path (robustness/elastic.py) can
resume the same problem at a DIFFERENT world size knowingly —
`load_state(..., expect_world_size=...)` warns, never fails, on a
mismatch (parameters are replicated, hence world-agnostic; only the
edge re-partition changes, and that is re-derived at lowering).  v2 and
legacy checksum-free snapshots load unchanged.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import warnings
import zipfile
from typing import Dict, Optional

import numpy as np

# Bumped when the on-disk layout changes incompatibly; load_state
# refuses snapshots from a NEWER schema (an older binary must not
# half-understand a future format).  v3 = world/topology header fields
# (additive; v2 and legacy snapshots still load).
SCHEMA_VERSION = 3

_CHECKSUM_KEY = "__checksum__"
_SCHEMA_KEY = "__schema__"


def _digest(payload: Dict[str, np.ndarray]) -> np.ndarray:
    """blake2b over every array's (name, dtype, shape, bytes), key-sorted
    — deterministic regardless of insertion order; returns uint8[16]."""
    h = hashlib.blake2b(digest_size=16)
    for key in sorted(payload):
        if key == _CHECKSUM_KEY:
            continue
        a = np.ascontiguousarray(np.asarray(payload[key]))
        h.update(key.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return np.frombuffer(h.digest(), np.uint8).copy()


def save_state(path: str, cameras, points, *, region: float = None,
               cost: float = None, iteration: int = None,
               world_size: int = None, process_index: int = None,
               extra: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Atomically snapshot solver state to `path` (.npz, checksummed).

    `world_size` / `process_index` are the schema-v3 world header: the
    distribution this snapshot was written under, consumed by the
    elastic resume path's mismatch warning (`expect_world_size`)."""
    payload = {
        "cameras": np.asarray(cameras),
        "points": np.asarray(points),
    }
    if region is not None:
        payload["region"] = np.asarray(region)
    if cost is not None:
        payload["cost"] = np.asarray(cost)
    if iteration is not None:
        payload["iteration"] = np.asarray(iteration)
    if world_size is not None:
        payload["world_size"] = np.asarray(int(world_size))
    if process_index is not None:
        payload["process_index"] = np.asarray(int(process_index))
    for k, v in (extra or {}).items():
        payload[f"extra_{k}"] = np.asarray(v)
    payload[_SCHEMA_KEY] = np.asarray(SCHEMA_VERSION)
    payload[_CHECKSUM_KEY] = _digest(payload)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())  # data durable before the rename is
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    finally:
        # A crash simulation that intercepts os.replace must not leak
        # temp files next to the (still intact) previous snapshot.
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_state(path: str,
               expect_world_size: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Load + validate a snapshot; dict with cameras/points (+ extras).

    Raises ValueError with a clear message when the file is truncated /
    not an npz (a torn copy, a partial download) or when the stored
    content checksum does not match the arrays (bit rot, a concurrent
    writer that bypassed `save_state`).  Never returns garbage state.

    `expect_world_size`: the world size the RESUMING solve will run at.
    A v3 snapshot whose recorded `world_size` differs WARNS — it does
    not fail: elastic shrink-world resume is the sanctioned path, the
    replicated parameter state is world-agnostic, and the edge
    partition is re-derived at lowering.  v2/legacy snapshots carry no
    world header and load silently.
    """
    try:
        with np.load(path) as z:
            state = {k: z[k] for k in z.files}
    except FileNotFoundError:
        # A missing file is "no snapshot", not corruption — callers
        # probing for an optional snapshot must see the real error.
        raise
    except (zipfile.BadZipFile, EOFError, OSError, ValueError) as exc:
        raise ValueError(
            f"checkpoint {path!r} is corrupt or truncated "
            f"({type(exc).__name__}: {exc}); delete it and restart, or "
            "point checkpoint_path at an intact snapshot") from exc
    schema = state.pop(_SCHEMA_KEY, None)
    if schema is not None and int(schema) > SCHEMA_VERSION:
        raise ValueError(
            f"checkpoint {path!r} was written by a newer schema "
            f"(v{int(schema)} > supported v{SCHEMA_VERSION}); upgrade "
            "before resuming")
    checksum = state.pop(_CHECKSUM_KEY, None)
    if checksum is not None:
        full = dict(state)
        if schema is not None:
            full[_SCHEMA_KEY] = schema
        want = _digest(full)
        if not np.array_equal(np.asarray(checksum), want):
            raise ValueError(
                f"checkpoint {path!r} failed its content checksum — the "
                "snapshot is corrupt; refusing to resume from garbage "
                "state (delete it and restart)")
    if expect_world_size is not None and "world_size" in state:
        saved_ws = int(state["world_size"])
        if saved_ws != int(expect_world_size):
            warnings.warn(
                f"checkpoint {path!r} was written at world_size "
                f"{saved_ws} but this solve runs at world_size "
                f"{int(expect_world_size)}; resuming anyway (elastic "
                "shrink/grow resume — parameters are replicated and "
                "world-agnostic, the edge partition is re-derived)",
                stacklevel=2)
    return state
