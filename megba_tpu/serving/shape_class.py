"""Shape classes: canonical padded buckets for fleet solves.

The fleet traffic shape (thousands of independent small-to-mid BA
problems) would naively compile one XLA program per distinct
(n_cam, n_pt, n_edge) triple — unbounded compile volume, exactly the
shape instability the retrace sentinel (analysis/retrace.py) polices.
This module quantises a problem's dimensions onto a configurable
bucketing ladder so that EVERY problem maps to one of a small, closed
set of padded shapes, and one compiled program per bucket serves all of
them, forever.

The padding is built from the machinery the solver already trusts:

- the edge axis is padded exactly like `solve.flat_solve` does
  (core/types.pad_edges: masked-out edges repeating the last edge's
  vertex indices, so camera-sortedness survives and segment reductions
  see in-range indices), just to the bucket size instead of the minimal
  EDGE_QUANTUM multiple;
- padded cameras/points are appended as ZERO parameter blocks flagged
  through the existing `cam_fixed` / `pt_fixed` masks, which zero their
  Jacobian columns and pin their Hessian blocks to identity
  (linear_system/builder.weight_system_inputs / build_schur_system) —
  their gradient is identically zero, so PCG leaves their components at
  exactly 0.0 and the LM carry never moves them.

Both mechanisms contribute literal zeros to every reduction, so a
padded solve is BITWISE identical to the unpadded one on this backend
(tests/test_serving.py pins this; the edge ladder grows by powers of
two on top of EDGE_QUANTUM, which keeps the compensated-sum fold
pattern of real data unchanged when zero rows are appended).

All buckets are powers of two times a floor, so the ladder is monotone
(more of anything never lands in a smaller bucket) and its size is
logarithmic in the problem-size range.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from megba_tpu.core.fm import EDGE_QUANTUM


def _round_up_pow2_multiple(n: int, floor: int) -> int:
    """Smallest `floor * 2**k` (k >= 0) that is >= n."""
    out = floor
    while out < n:
        out *= 2
    return out


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """The bucketing ladder: floors + power-of-two growth per axis.

    `edge_floor` must be a multiple of EDGE_QUANTUM: the solver's
    chunked edge reductions require it, and power-of-two growth on top
    of the quantum keeps zero-padding bitwise-neutral through the
    compensated-sum trees (ops/accum.comp_sum folds whole zero rows
    away exactly).  `lane_floor` buckets the BATCH axis the same way so
    a bucket's compiled program count stays logarithmic in the batch
    sizes the dispatch queue produces.
    """

    cam_floor: int = 4
    pt_floor: int = 16
    edge_floor: int = EDGE_QUANTUM
    lane_floor: int = 1

    def __post_init__(self) -> None:
        for name in ("cam_floor", "pt_floor", "edge_floor", "lane_floor"):
            v = getattr(self, name)
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        if self.edge_floor % EDGE_QUANTUM:
            raise ValueError(
                f"edge_floor must be a multiple of EDGE_QUANTUM "
                f"({EDGE_QUANTUM}), got {self.edge_floor}")

    def bucket_cams(self, n: int) -> int:
        return _round_up_pow2_multiple(int(n), self.cam_floor)

    def bucket_points(self, n: int) -> int:
        return _round_up_pow2_multiple(int(n), self.pt_floor)

    def bucket_edges(self, n: int) -> int:
        return _round_up_pow2_multiple(int(n), self.edge_floor)

    def bucket_lanes(self, n: int) -> int:
        return _round_up_pow2_multiple(int(n), self.lane_floor)


@dataclasses.dataclass(frozen=True)
class ShapeClass:
    """One padded bucket: the static shape every member solves at.

    Hashable + orderable; the dict key the batcher groups problems
    under and the compile pool keys programs by (together with the lane
    count and the option fingerprint).  `dtype` is the numpy dtype NAME
    so the class is JSON-serializable for warmup manifests.
    """

    n_cam: int
    n_pt: int
    n_edge: int
    dtype: str

    def __str__(self) -> str:  # manifest / stats key
        return f"c{self.n_cam}_p{self.n_pt}_e{self.n_edge}_{self.dtype}"

    def to_dict(self) -> Dict[str, Any]:
        return {"n_cam": self.n_cam, "n_pt": self.n_pt,
                "n_edge": self.n_edge, "dtype": self.dtype}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ShapeClass":
        return cls(n_cam=int(d["n_cam"]), n_pt=int(d["n_pt"]),
                   n_edge=int(d["n_edge"]), dtype=str(d["dtype"]))


def classify(n_cam: int, n_pt: int, n_edge: int, dtype,
             ladder: BucketLadder) -> ShapeClass:
    """Canonicalize raw problem dimensions onto the ladder."""
    if n_cam < 1 or n_pt < 1 or n_edge < 1:
        raise ValueError(
            f"degenerate problem: n_cam={n_cam} n_pt={n_pt} n_edge={n_edge}")
    return ShapeClass(
        n_cam=ladder.bucket_cams(n_cam),
        n_pt=ladder.bucket_points(n_pt),
        n_edge=ladder.bucket_edges(n_edge),
        dtype=np.dtype(dtype).name,
    )


@dataclasses.dataclass
class PaddedProblem:
    """One problem lowered to its shape class (host numpy, edge-major).

    Edges are camera-sorted and padded to `shape.n_edge` with mask-0
    slots; cameras/points are zero-padded to the bucket with the pad
    region flagged in `cam_fixed` / `pt_fixed`.  `n_cam/n_pt/n_edge`
    remember the REAL sizes for slicing results back out.
    """

    shape: ShapeClass
    cameras: np.ndarray  # [n_cam_bucket, cd]
    points: np.ndarray  # [n_pt_bucket, pd]
    obs: np.ndarray  # [n_edge_bucket, od]
    cam_idx: np.ndarray  # [n_edge_bucket] int32
    pt_idx: np.ndarray  # [n_edge_bucket] int32
    mask: np.ndarray  # [n_edge_bucket] dtype 0/1
    cam_fixed: np.ndarray  # [n_cam_bucket] bool, True on padding
    pt_fixed: np.ndarray  # [n_pt_bucket] bool, True on padding
    n_cam: int
    n_pt: int
    n_edge: int
    # The camera-sort permutation the REAL edges took (None if they were
    # already sorted): any per-edge side-channel vector — e.g. a
    # FaultPlan's edge_nan (robustness/faults.lower_fault_plan) — must
    # ride the same reorder to land on the same physical edges.
    perm: Optional[np.ndarray] = None


def pad_to_class(cameras: np.ndarray, points: np.ndarray, obs: np.ndarray,
                 cam_idx: np.ndarray, pt_idx: np.ndarray,
                 shape: ShapeClass,
                 edge_mask: Optional[np.ndarray] = None,
                 cam_fixed: Optional[np.ndarray] = None,
                 pt_fixed: Optional[np.ndarray] = None) -> PaddedProblem:
    """Lower one problem's host arrays onto its shape class.

    Mirrors `solve.flat_solve`'s host prep for the non-tiled path:
    dtype cast, camera sort (native counting sort), edge padding — then
    the bucket's camera/point zero-padding with fixed-mask flags on the
    pad region.  Padded edges repeat the last REAL edge's vertex
    indices (pad_edges), which point at real vertices, so the masked
    residual evaluation stays finite.

    `edge_mask` ([nE], caller's edge order, values in [0, 1]) rides the
    camera-sort permutation and MULTIPLIES into the padding mask —
    exactly `flat_solve(..., edge_mask=)`'s soft-delete/downweight
    semantics, so a triage-repaired problem (robustness/triage.py)
    lowers onto its bucket as pure operands.  `cam_fixed` / `pt_fixed`
    ([Nc]/[Np] bool) OR into the padding-region flags the same way.
    None of the three changes the program: the batched solve always
    carries mask/cam_fixed/pt_fixed operands.
    """
    from megba_tpu.core.types import is_cam_sorted, pad_edges
    from megba_tpu.native import sort_edges_by_camera

    dtype = np.dtype(shape.dtype)
    cameras = np.asarray(cameras).astype(dtype, copy=False)
    points = np.asarray(points).astype(dtype, copy=False)
    obs = np.asarray(obs).astype(dtype, copy=False)
    cam_idx = np.asarray(cam_idx, dtype=np.int32)
    pt_idx = np.asarray(pt_idx, dtype=np.int32)
    n_cam, n_pt, n_edge = cameras.shape[0], points.shape[0], obs.shape[0]
    if n_cam > shape.n_cam or n_pt > shape.n_pt or n_edge > shape.n_edge:
        raise ValueError(
            f"problem ({n_cam} cams, {n_pt} pts, {n_edge} edges) does not "
            f"fit shape class {shape}")
    em = None
    if edge_mask is not None:
        em = np.asarray(edge_mask).astype(dtype, copy=False).reshape(-1)
        if em.shape[0] != n_edge:
            raise ValueError(
                f"edge_mask has {em.shape[0]} entries for a problem "
                f"with {n_edge} edges")

    perm = None
    if not is_cam_sorted(cam_idx):
        perm = sort_edges_by_camera(cam_idx, n_cam)
        cam_idx, pt_idx, obs = cam_idx[perm], pt_idx[perm], obs[perm]
        if em is not None:
            em = em[perm]

    # pad_edges pads to a MULTIPLE of its argument; the bucket size is
    # the multiple here, and n_edge <= shape.n_edge, so the result is
    # exactly one bucket long.
    obs, cam_idx, pt_idx, mask = pad_edges(
        obs, cam_idx, pt_idx, shape.n_edge, dtype=dtype)
    if em is not None:
        # 1*em on the real region, 0 stays 0 on the pad region (the
        # flat_solve identity: 1.0 * {0.0, 1.0} is exact, and fractional
        # downweights ride unchanged).
        mask = mask * np.concatenate(
            [em, np.ones(mask.shape[0] - em.shape[0], dtype)])

    pad_c = shape.n_cam - n_cam
    pad_p = shape.n_pt - n_pt
    if pad_c:
        cameras = np.concatenate(
            [cameras, np.zeros((pad_c, cameras.shape[1]), dtype)])
    if pad_p:
        points = np.concatenate(
            [points, np.zeros((pad_p, points.shape[1]), dtype)])
    cam_fixed_out = np.zeros(shape.n_cam, dtype=bool)
    cam_fixed_out[n_cam:] = True
    if cam_fixed is not None:
        cam_fixed_out[:n_cam] |= np.asarray(cam_fixed, bool).reshape(-1)
    pt_fixed_out = np.zeros(shape.n_pt, dtype=bool)
    pt_fixed_out[n_pt:] = True
    if pt_fixed is not None:
        pt_fixed_out[:n_pt] |= np.asarray(pt_fixed, bool).reshape(-1)
    cam_fixed, pt_fixed = cam_fixed_out, pt_fixed_out

    return PaddedProblem(
        shape=shape, cameras=cameras, points=points, obs=obs,
        cam_idx=cam_idx, pt_idx=pt_idx, mask=mask,
        cam_fixed=cam_fixed, pt_fixed=pt_fixed,
        n_cam=n_cam, n_pt=n_pt, n_edge=n_edge, perm=perm)
