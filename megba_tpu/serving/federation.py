"""Federation tier: one router, N worker processes, a fleet of fleets.

`solve_many` / `FleetQueue` saturate ONE host; this module is the
scale-out story (ROADMAP item 2): a `FleetRouter` fronting N worker
PROCESSES, each running the whole single-host serving stack (compile
pool + batched mega-solves) behind a small length-prefixed pickle RPC
over its stdin/stdout pipes — the same subprocess discipline as the
kill-resume harness (robustness/harness.py): workers are real
processes that really die, stderr is the log channel, and the RPC
channel carries nothing but frames.

Three connected mechanisms:

- **Shape-class routing, occupancy-aware.**  Problems shard across
  workers BY SHAPE CLASS, not round-robin: all problems of one bucket
  flow to one worker until stolen or rerouted, so per-host bucket
  occupancy stays high (padding waste — which `FleetStats` measures —
  is paid per DISPATCH; splitting a bucket across hosts would pay it
  twice at half the lane fill).  A new class lands on the worker that
  already has it WARM (artifact-loaded executables first), then the
  least-loaded worker (`RoutingTable`, a pure host policy class).

- **Work-stealing for hot buckets.**  An idle worker pulls queued
  problems for buckets IT HAS WARM from the deepest backlog of a busy
  peer — before it would compile anything new.  Stealing moves work,
  never assignments: the hot bucket keeps its home, the thief drains
  overflow with a program it already holds (typically loaded from the
  shared `ArtifactStore` in milliseconds).

- **Host-loss rerouting.**  A dead worker is a dispatch exception plus
  a requeue, exactly the PR 8 retry-ladder stance: liveness is PR 9's
  `HeartbeatBoard` (workers beat heartbeat files; the router observes
  counter changes on its own clock) plus pipe-EOF/process-exit
  detection, a loss is a typed `WorkerLostError`, the lost worker's
  in-flight and queued problems re-route to survivors with bounded
  `max_reroutes` and `worker_lost`/`rerouted` counters — never
  silently, never wedging `flush()`.

Cold start is the third leg (serving/artifacts.py): workers warm from
a manifest + serialized-executable store, so a fresh replica's
cold-start-to-first-solve is I/O-bound — its first fleet dispatches
with ZERO traces (the worker certifies this against the retrace
sentinel and reports the count in its hello).

Everything host-side here is plain threads, pipes and pickle — no new
collectives, no device code; the workers' solve programs are byte-wise
the single-host ones, so a federated fleet's results are BITWISE the
`solve_many` results at the same shape classes (padding exactness,
PR 6) no matter how routing, stealing or rerouting scattered them.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import pickle
import select
import struct
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from megba_tpu import observability as _obs
from megba_tpu.serving.resilience import DeadlineExceeded
from megba_tpu.utils.timing import monotonic_s, wall_unix

_LEN = struct.Struct(">Q")
_MAX_FRAME = 1 << 34  # 16 GiB: a corrupted length header fails fast


class FrameError(ConnectionError):
    """The RPC stream ended or produced a malformed frame."""


class WorkerLostError(RuntimeError):
    """A federation worker died (or stopped beating) with work on it.

    `worker_id` names the worker, `reason` what was observed (pipe EOF,
    process exit code, heartbeat staleness).  Problems that exhaust
    `max_reroutes` across successive losses fail with this error — the
    caller sees WHY, never a hang.
    """

    def __init__(self, worker_id: str, reason: str) -> None:
        self.worker_id = worker_id
        self.reason = reason
        super().__init__(f"federation worker {worker_id!r} lost: {reason}")


# ---------------------------------------------------------------------------
# Length-prefixed pickle frames over pipes
# ---------------------------------------------------------------------------


class FrameChannel:
    """One duplex frame stream over a (read fd, write file) pair.

    Frames are `>Q` length + pickle.  `recv` reads the UNDERLYING fd
    directly (private buffer, never a BufferedReader) so the
    select-based timeout/poll path can never stall on bytes hidden in a
    Python-level buffer.  `poll` is called between read slices and may
    raise to abort the wait (the router's liveness hook)."""

    def __init__(self, rfile, wfile) -> None:
        self._rfd = rfile.fileno()
        self._rfile = rfile  # owned: kept for close()
        self._wfile = wfile
        self._buf = bytearray()
        self._slice_s = 0.05

    def send(self, obj: Any) -> None:
        body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        self._wfile.write(_LEN.pack(len(body)) + body)
        self._wfile.flush()

    def _fill(self, need: int, deadline: Optional[float],
              poll: Optional[Callable[[], None]]) -> None:
        while len(self._buf) < need:
            if poll is not None:
                poll()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("no complete frame within the budget")
            ready, _, _ = select.select([self._rfd], [], [], self._slice_s)
            if not ready:
                continue
            chunk = os.read(self._rfd, 1 << 20)
            if not chunk:
                raise FrameError("stream closed mid-frame"
                                 if self._buf else "stream closed")
            self._buf.extend(chunk)

    def recv(self, timeout_s: Optional[float] = None,
             poll: Optional[Callable[[], None]] = None) -> Any:
        # ONE deadline spans header + body: a worker stalling between
        # the two must not double the effective watchdog budget.
        deadline = None if timeout_s is None else (
            time.monotonic() + timeout_s)
        self._fill(_LEN.size, deadline, poll)
        (length,) = _LEN.unpack(bytes(self._buf[:_LEN.size]))
        if length > _MAX_FRAME:
            raise FrameError(f"frame length {length} exceeds sanity cap")
        del self._buf[:_LEN.size]
        self._fill(length, deadline, poll)
        body = bytes(self._buf[:length])
        del self._buf[:length]
        return pickle.loads(body)

    def close(self) -> None:
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Routing policy (pure host state, unit-testable without processes)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WorkerView:
    """What the routing policy may know about one worker."""

    worker_id: str
    warm: set  # bucket strs with a ready (artifact/compiled) program
    alive: bool = True
    assigned: set = dataclasses.field(default_factory=set)  # bucket strs
    routed: int = 0  # problems ever routed here (load tiebreak)


class RoutingTable:
    """Shape-class → worker assignment with warm-first affinity.

    Policy, in order: (1) sticky — a bucket keeps its worker while that
    worker lives (occupancy: one home per bucket fills lanes instead of
    splitting them); (2) warm-first — a NEW bucket goes to a live
    worker that already holds its program (artifact-loaded executables
    make this common after one export cycle); (3) least-loaded — fewest
    assigned buckets, then fewest routed problems, then worker id (a
    deterministic tiebreak so tests and reruns route identically).

    `steal_candidate` picks what an idle worker should pull: the
    DEEPEST backlog among buckets homed on other live workers that the
    thief has WARM — it never volunteers a bucket it would have to
    compile for (that would trade queueing delay for compile delay).

    Pure host state over caller-supplied views; the router drives it
    under its own lock.
    """

    def __init__(self) -> None:
        self.assignment: Dict[str, str] = {}  # bucket str -> worker id

    def route(self, bucket: str,
              workers: Dict[str, WorkerView]) -> Optional[str]:
        homed = self.assignment.get(bucket)
        if homed is not None and workers[homed].alive:
            return homed
        alive = [w for w in workers.values() if w.alive]
        if not alive:
            return None
        warm = [w for w in alive if bucket in w.warm]
        pool = warm or alive
        best = min(pool, key=lambda w: (len(w.assigned), w.routed,
                                        w.worker_id))
        self.assignment[bucket] = best.worker_id
        best.assigned.add(bucket)
        return best.worker_id

    def steal_candidate(self, thief: str, workers: Dict[str, WorkerView],
                        depths: Dict[str, int]) -> Optional[str]:
        """Bucket the idle `thief` should pull work from, or None."""
        view = workers[thief]
        candidates = [
            (depth, bucket) for bucket, depth in depths.items()
            if depth > 0 and bucket in view.warm
            and self.assignment.get(bucket) not in (None, thief)
            and workers[self.assignment[bucket]].alive
        ]
        if not candidates:
            return None
        _, bucket = max(candidates, key=lambda c: (c[0], c[1]))
        return bucket

    def reassign_lost(self, lost: str,
                      workers: Dict[str, WorkerView]) -> List[str]:
        """Forget every bucket homed on `lost`; they re-route on next
        pick.  Returns the orphaned bucket names."""
        orphaned = [b for b, w in self.assignment.items() if w == lost]
        for b in orphaned:
            del self.assignment[b]
        if lost in workers:
            workers[lost].assigned.clear()
        return orphaned


# ---------------------------------------------------------------------------
# Federation stats
# ---------------------------------------------------------------------------


class FederationStats:
    """Router-level counters: where problems ran, what moved, what died.

    The per-worker `FleetStats` still live inside each worker (their
    dispatch telemetry embeds them); this object is the ROUTER's view —
    the one `summarize --aggregate`'s federation block renders."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.router = uuid.uuid4().hex[:12]
        self.problems = 0  # megba: guarded-by(_lock); resolved via router
        self.problems_by_worker: Dict[str, int] = {}  # megba: guarded-by(_lock)
        self.steals = 0  # megba: guarded-by(_lock); one per pulled batch
        self.stolen_problems = 0  # megba: guarded-by(_lock)
        self.reroutes = 0  # megba: guarded-by(_lock); requeued off a loss
        self.reroute_failures = 0  # megba: guarded-by(_lock); max_reroutes hit
        self.workers_lost = 0  # megba: guarded-by(_lock)
        self.sheds = 0  # megba: guarded-by(_lock); shed before dispatch
        self.deadline_misses = 0  # megba: guarded-by(_lock); delivered late
        self.cold_start: Dict[str, Dict[str, Any]] = {}  # megba: guarded-by(_lock); worker -> hello
        self.first_solve: Dict[str, Dict[str, Any]] = {}  # megba: guarded-by(_lock)
        self.lost_workers: List[str] = []  # megba: guarded-by(_lock)

    def record_batch(self, worker_id: str, n: int, stolen: bool) -> None:
        with self._lock:
            self.problems += n
            self.problems_by_worker[worker_id] = (
                self.problems_by_worker.get(worker_id, 0) + n)
            if stolen:
                self.steals += 1
                self.stolen_problems += n

    def record_reroute(self, n: int) -> None:
        with self._lock:
            self.reroutes += n

    def record_reroute_failure(self, n: int = 1) -> None:
        with self._lock:
            self.reroute_failures += n

    def record_worker_lost(self, worker_id: str) -> None:
        with self._lock:
            self.workers_lost += 1
            self.lost_workers.append(worker_id)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.sheds += n

    def record_deadline_miss(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_misses += n

    def record_cold_start(self, worker_id: str,
                          info: Dict[str, Any]) -> None:
        with self._lock:
            self.cold_start[worker_id] = dict(info)

    def record_first_solve(self, worker_id: str,
                           info: Dict[str, Any]) -> None:
        with self._lock:
            self.first_solve[worker_id] = dict(info)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "router": self.router,
                "problems": self.problems,
                "problems_by_worker": dict(self.problems_by_worker),
                "steals": self.steals,
                "stolen_problems": self.stolen_problems,
                "reroutes": self.reroutes,
                "reroute_failures": self.reroute_failures,
                "workers_lost": self.workers_lost,
                "lost_workers": list(self.lost_workers),
                "sheds": self.sheds,
                "deadline_misses": self.deadline_misses,
                "cold_start": {k: dict(v)
                               for k, v in self.cold_start.items()},
                "first_solve": {k: dict(v)
                                for k, v in self.first_solve.items()},
            }

    def report(self) -> str:
        d = self.as_dict()
        per = " / ".join(
            f"{w}:{n}" for w, n in sorted(d["problems_by_worker"].items()))
        lines = [
            f"federation: {d['problems']} problems ({per or 'none'}), "
            f"{d['steals']} steals ({d['stolen_problems']} problems), "
            f"{d['reroutes']} rerouted, {d['workers_lost']} workers lost"]
        for w, cs in sorted(d["cold_start"].items()):
            fs = d["first_solve"].get(w) or {}
            lines.append(
                f"  {w}: cold start {cs.get('mode', '?')} "
                f"{cs.get('warm_s', float('nan')):.3f}s "
                f"({cs.get('artifact_loads', 0)} loaded / "
                f"{cs.get('artifact_compiles', 0)} compiled)"
                + (f", first solve {fs.get('traces')} traces"
                   if fs else ""))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Worker process (the --worker entry point)
# ---------------------------------------------------------------------------


def _worker_main() -> int:
    """Run one federation worker: frames in on fd 0, frames out on the
    ORIGINAL fd 1; fd 1 is then pointed at stderr so any stray print
    from a library can never corrupt the frame stream."""
    rpc_in = os.fdopen(os.dup(0), "rb", buffering=0)
    rpc_out = os.fdopen(os.dup(1), "wb", buffering=0)
    os.dup2(2, 1)
    chan = FrameChannel(rpc_in, rpc_out)

    cfg = chan.recv()
    if cfg.get("op") != "config":
        chan.send({"ok": False, "error": f"expected config, got {cfg!r}"})
        return 2
    worker_id = cfg["worker_id"]
    # Tag this process's fleet telemetry with the worker id BEFORE any
    # serving import reads it (batcher reads it per report).
    os.environ["MEGBA_FEDERATION_WORKER"] = worker_id
    # CPU pinning (router `pin_cpus=`): restrict this worker to its core
    # slice BEFORE the first dispatch, so the lazily-built XLA:CPU
    # thread pool's threads inherit the affinity — N workers then run
    # true data-parallel instead of thrashing one shared pool.
    affinity = cfg.get("cpu_affinity")
    if affinity:
        try:
            os.sched_setaffinity(0, set(int(c) for c in affinity))
        except (AttributeError, OSError):  # non-Linux / restricted
            pass

    from megba_tpu.analysis import retrace
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving.batcher import solve_many
    from megba_tpu.serving.compile_pool import CompilePool
    from megba_tpu.serving.stats import FleetStats
    from megba_tpu.utils.timing import PhaseTimer

    # `option` (observability-STRIPPED: telemetry AND metrics,
    # common.OBSERVABILITY_FIELDS) feeds warmup and fingerprints — the
    # program caches are observability-agnostic by contract; previously
    # only `telemetry` was cleared here, so a metrics-armed fleet config
    # warmed programs dispatch could never hit (the identity lane's
    # key-surface-drift finding, fixed at the source).  `solve_option`
    # carries this worker's sink AND the config's metrics flag into
    # solve_many, which strips both again before touching any cache, so
    # warm and dispatch agree on keys.
    from megba_tpu.common import strip_observability

    base_option = cfg["option"]
    option = strip_observability(base_option)
    ladder = cfg.get("ladder")
    stats = FleetStats()
    timer = PhaseTimer()
    pool = CompilePool(stats=stats, artifacts=cfg.get("artifacts"),
                       timer=timer)
    engine = make_residual_jacobian_fn(mode=option.jacobian_mode)
    telemetry = cfg.get("telemetry")
    solve_option = dataclasses.replace(base_option,
                                       telemetry=telemetry or None)

    # Heartbeat: PR 9's liveness board, beaten from a daemon thread.
    hb = cfg.get("heartbeat")
    if hb:
        from megba_tpu.robustness.elastic import HeartbeatBoard

        board = HeartbeatBoard(hb["dir"], int(hb["rank"]),
                               int(hb["world"]))
        interval = float(hb.get("interval_s", 0.25))

        def _beat() -> None:
            while True:
                board.beat()
                time.sleep(interval)

        threading.Thread(target=_beat, daemon=True,
                         name="megba-fed-heartbeat").start()

    # Cold start: warm the manifest's buckets (artifact-load when the
    # store holds them, compile otherwise) and report the split.
    t0 = monotonic_s()
    warmed = 0
    try:
        if cfg.get("manifest"):
            warmed = pool.warm_from_manifest(
                cfg["manifest"], engine, option,
                strict=bool(cfg.get("strict_manifest", False)))
    except Exception as exc:
        chan.send({"ok": False, "error": repr(exc),
                   "worker_id": worker_id})
        return 3
    warm_s = monotonic_s() - t0
    loads = stats.artifact_loads
    # Store-less warms compile without touching the artifact counters
    # (they describe a store that must exist) — the timer's phase count
    # is the mode signal either way.
    compiles = timer.counts.get("warm_compile", 0)
    mode = ("artifact" if loads and not compiles
            else "compile" if compiles else "cold")
    warm_set = sorted({str(_shape_of(e)) for e in pool.entries()})
    chan.send({
        "ok": True, "op": "hello", "worker_id": worker_id,
        "pid": os.getpid(), "warm": warm_set, "warmed": warmed,
        "cold_start": {
            "mode": mode, "warm_s": warm_s, "buckets": warmed,
            "artifact_loads": loads, "artifact_compiles": compiles,
            "phases": timer.as_dict(),
        },
    })

    first_solve: Optional[Dict[str, Any]] = None
    try:
        while True:
            try:
                req = chan.recv()
            except FrameError:
                return 0  # router went away: no work without it
            op = req.get("op")
            if op == "shutdown":
                chan.send({"ok": True})
                return 0
            if op == "stats":
                chan.send({"ok": True, "stats": stats.as_dict(),
                           "phases": timer.as_dict()})
                continue
            if op == "metrics":
                # Observability harvesting seam: the router merges these
                # per-worker registry snapshots (metrics_snapshot()).
                registry = _obs.metrics_registry()
                chan.send({"ok": True, "metrics": (
                    None if registry is None else registry.snapshot())})
                continue
            if op != "solve":
                chan.send({"ok": False, "error": f"unknown op {op!r}"})
                continue
            problems = req["problems"]
            recorder = _obs.span_recorder()
            try:
                base = retrace.snapshot()
                t0 = monotonic_s()
                # The router's trace context rides the solve frame; the
                # worker's whole solve joins it as a child span and the
                # spans recorded under it ship back in the reply.
                scope = (contextlib.nullcontext() if recorder is None
                         else recorder.adopt(
                             "worker_solve", req.get("trace"),
                             worker=worker_id, problems=len(problems)))
                with scope:
                    results = solve_many(problems, solve_option,
                                         ladder=ladder, pool=pool,
                                         stats=stats, timer=timer)
                wall = monotonic_s() - t0
                if first_solve is None:
                    traces = sum(
                        v - base.get(k, 0)
                        for k, v in retrace.snapshot().items()
                        if k[0].startswith("serving.batched")
                        and v > base.get(k, 0))
                    first_solve = {"traces": int(traces), "wall_s": wall,
                                   "problems": len(problems)}
                # Traces are per-iteration device history — large, and
                # the router's callers read costs/params/status;
                # telemetry (the per-problem SolveReports written ABOVE,
                # worker-side) already persisted them for whoever wants
                # forensics.
                slim = [dataclasses.replace(r, trace=None)
                        for r in results]
                chan.send({
                    "ok": True, "results": slim,
                    "warm": sorted({str(_shape_of(e))
                                    for e in pool.entries()}),
                    "first_solve": first_solve,
                    "spans": (None if recorder is None
                              else recorder.drain()),
                })
            except Exception as exc:  # solve failed: typed reply, serve on
                import traceback

                flight = _obs.flight_recorder()
                if flight is not None:
                    flight.record("solve_error", worker=worker_id,
                                  problems=len(problems),
                                  error=repr(exc))
                chan.send({"ok": False, "error": repr(exc),
                           "traceback": traceback.format_exc(),
                           "spans": (None if recorder is None
                                     else recorder.drain())})
    except BaseException:
        # Worker is crashing out of the serve loop (router still thinks
        # it is alive): dump the flight ring before dying so the last
        # ~256 events survive the process.  SIGKILL deaths cannot run
        # this — the ROUTER's recorder covers those (_on_worker_lost).
        flight = _obs.flight_recorder()
        if flight is not None:
            flight.record("worker_crash", worker=worker_id)
            from megba_tpu.observability import flight as _flight

            _flight.dump_default("worker_crash")
        raise


def _shape_of(entry: Dict[str, Any]):
    from megba_tpu.serving.shape_class import ShapeClass

    return ShapeClass.from_dict(entry["shape"])


# ---------------------------------------------------------------------------
# Router-side worker handle
# ---------------------------------------------------------------------------


class WorkerHandle:
    """One spawned worker: process + channel + router-side bookkeeping.

    `request` is strictly lockstep at the FRAME level (the worker's
    serve loop answers one request at a time, in arrival order) but no
    lock is ever held across the blocking reply read: sends are
    serialized under `_req_lock` and stamped with a ticket, and replies
    are read in ticket order under the `_turn` condition — the reader
    whose turn it is owns the pipe with every lock released, so an
    out-of-band `metrics` pull never stalls a lock behind a whole solve
    RPC (the blocking-under-lock shape lint lane 6 polices).  Every
    death signal — pipe EOF, process exit, heartbeat DEAD — converts
    into a typed `WorkerLostError`."""

    def __init__(self, worker_id: str, proc: subprocess.Popen,
                 chan: FrameChannel, log_path: str,
                 liveness: Optional[Callable[[], Optional[str]]] = None,
                 ) -> None:
        self.worker_id = worker_id
        self.proc = proc
        self.chan = chan
        self.log_path = log_path
        self.liveness = liveness
        # `warm`/`alive` are confined to this worker's serve thread once
        # it starts (spawn-time writes order-before via Thread.start;
        # close() reads only after joining it).  Cross-thread consumers
        # go through FleetRouter's locked `_views` mirror instead — see
        # metrics_snapshot().
        self.warm: set = set()
        self.alive = True
        self.pid = proc.pid
        self.rank = 0  # heartbeat-board rank, set by the router at spawn
        # Serializes SENDS (the channel is strictly lockstep, so two
        # concurrent writers would interleave frames) and hands out
        # reply tickets; never held across a read.
        self._req_lock = threading.Lock()
        self._next_send = 0  # megba: guarded-by(_req_lock)
        # Orders reply reads: replies arrive in send order (the worker
        # serve loop is single-threaded FIFO), so ticket n reads the
        # n-th reply — exclusivity without holding anything during the
        # blocking recv.
        self._turn = threading.Condition()
        self._next_recv = 0  # megba: guarded-by(_turn)

    def _poll(self) -> None:
        rc = self.proc.poll()
        if rc is not None:
            raise WorkerLostError(self.worker_id,
                                  f"process exited rc={rc}")
        if self.liveness is not None:
            reason = self.liveness()
            if reason:
                raise WorkerLostError(self.worker_id, reason)

    def request(self, msg: Dict[str, Any],
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        try:
            with self._req_lock:
                self.chan.send(msg)
                ticket = self._next_send
                self._next_send += 1
            with self._turn:
                while self._next_recv != ticket:
                    self._turn.wait()
            try:
                # Our turn: ticket order makes this thread the sole
                # reader, with no lock held across the blocking recv.
                return self.chan.recv(timeout_s=timeout_s,
                                      poll=self._poll)
            finally:
                # Always pass the turn — even on a broken pipe the next
                # ticket holder must wake (its own recv then raises).
                with self._turn:
                    self._next_recv += 1
                    self._turn.notify_all()
        except (FrameError, BrokenPipeError, OSError) as exc:
            rc = self.proc.poll()
            raise WorkerLostError(
                self.worker_id,
                f"rpc stream broke ({type(exc).__name__}: {exc}); "
                f"process rc={rc}") from exc

    def log_tail(self, max_bytes: int = 8192) -> str:
        try:
            with open(self.log_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(size - max_bytes, 0))
                return fh.read().decode(errors="replace")
        except OSError:
            return "<no worker log>"

    def terminate(self) -> None:
        self.alive = False
        self.chan.close()
        if self.proc.poll() is None:
            self.proc.kill()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class _Routed:
    problem: Any  # FleetProblem
    future: Future
    bucket: str  # shape-class str (routing granularity)
    key: Tuple  # (ShapeClass, dims) — batching granularity
    enqueued: float
    deadline: Optional[float] = None
    reroutes: int = 0


class FleetRouter:
    """Front door of the federation tier: submit → Future, N workers.

    Mirrors `FleetQueue`'s surface (submit/flush/close/context-manager,
    Future-per-problem) one level up: submissions shard across worker
    PROCESSES by shape class, idle workers steal hot buckets they have
    warm, and a dead worker's problems re-route to survivors (bounded
    by `max_reroutes`) with typed counters.  `artifacts` + `manifest`
    give workers the millisecond cold start (serving/artifacts.py);
    without them workers compile on first warm like any fresh service.

    `workers=` injects pre-built worker handles (anything with
    `worker_id`/`warm`/`alive`/`request`/`terminate`) — the unit tests
    drive the full routing/steal/reroute machinery through in-process
    stubs with zero subprocesses and zero compiles.
    """

    def __init__(
        self,
        option=None,
        *,
        n_workers: int = 2,
        max_batch: int = 16,
        ladder=None,
        artifacts: Optional[str] = None,
        manifest: Optional[str] = None,
        strict_manifest: bool = False,
        stats: Optional[FederationStats] = None,
        timer=None,
        steal: bool = True,
        max_reroutes: int = 2,
        heartbeat_dir: Optional[str] = None,
        dead_after_s: float = 5.0,
        warm_timeout_s: float = 1800.0,
        watchdog_s: float = 1800.0,
        telemetry: Optional[str] = None,
        worker_env: Optional[Dict[str, str]] = None,
        pin_cpus: bool = False,
        workers: Optional[Sequence[Any]] = None,
    ) -> None:
        from megba_tpu.common import ProblemOption
        from megba_tpu.serving.batcher import _check_option
        from megba_tpu.serving.shape_class import BucketLadder
        from megba_tpu.utils.timing import PhaseTimer

        option = option or ProblemOption()
        _check_option(option)
        if n_workers < 1 and workers is None:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if max_reroutes < 0:
            raise ValueError(
                f"max_reroutes must be >= 0, got {max_reroutes}")
        self.option = option
        self.ladder = ladder or BucketLadder()
        self.max_batch = int(max_batch)
        self.steal = bool(steal)
        self.max_reroutes = int(max_reroutes)
        self.watchdog_s = float(watchdog_s)
        self.stats = stats or FederationStats()
        self.timer = PhaseTimer() if timer is None else timer
        self.telemetry = telemetry

        self._lock = threading.Condition()
        self._pending: Dict[Tuple, List[_Routed]] = {}  # megba: guarded-by(_lock)
        self._npending = 0  # megba: guarded-by(_lock)
        self._closed = False  # megba: guarded-by(_lock)
        self.pinned = False  # did worker CPU pinning actually apply?
        self._own_hb_dir: Optional[str] = None
        # Deadline-carrying items currently pending: the shed scan is
        # O(pending) under the router lock on every serve-thread wakeup,
        # so it only runs while this is nonzero (deadline-free fleets —
        # the common case — pay nothing).
        self._ndeadline = 0  # megba: guarded-by(_lock)
        self._inflight = 0  # megba: guarded-by(_lock)
        self._closing = False  # megba: guarded-by(_lock)
        self._table = RoutingTable()  # megba: guarded-by(_lock)
        self._views: Dict[str, WorkerView] = {}  # megba: guarded-by(_lock)
        # Serializes HeartbeatBoard.observe across serve threads: the
        # board's observation maps are thread-confined state, and every
        # worker's liveness closure may poll concurrently.
        self._hb_lock = threading.Lock()
        self._board = None  # set once in _spawn_workers, pre-thread-start

        if workers is not None:
            self.workers: Dict[str, Any] = {w.worker_id: w for w in workers}
        else:
            self.workers = self._spawn_workers(
                n_workers, artifacts, manifest, strict_manifest,
                heartbeat_dir, dead_after_s, warm_timeout_s,
                worker_env or {}, pin_cpus)
        for w in self.workers.values():
            self._views[w.worker_id] = WorkerView(
                worker_id=w.worker_id, warm=set(w.warm),
                alive=w.alive)
        self._threads = [
            threading.Thread(target=self._serve, args=(w,),
                             name=f"megba-fed-{w.worker_id}", daemon=True)
            for w in self.workers.values()
        ]
        for t in self._threads:
            t.start()

    # -- spawning --------------------------------------------------------
    def _spawn_workers(self, n, artifacts, manifest, strict_manifest,
                       heartbeat_dir, dead_after_s, warm_timeout_s,
                       worker_env, pin_cpus=False) -> Dict[str, WorkerHandle]:
        import jax

        from megba_tpu.robustness.elastic import HeartbeatBoard, RankState

        env = dict(os.environ)
        # Workers must land on the parent's backend/precision: the
        # conftest-style in-process config flips don't propagate to
        # children, the env vars do.
        env.setdefault("JAX_PLATFORMS", jax.default_backend())
        if jax.config.jax_enable_x64:
            env["JAX_ENABLE_X64"] = "1"
        env.update(worker_env)

        # `pin_cpus`: split the host's cores into contiguous slices, one
        # per worker — each XLA:CPU thread pool then owns its slice
        # instead of all workers thrashing one shared set (the
        # data-parallel deployment shape, one host's cores = one
        # worker's world).  True = cores // n each; an int = exactly
        # that many cores per worker (the bench's equal-resource
        # scaling sweeps pin fed_1 and fed_n to the SAME per-worker
        # slice so the 1→N curve compares like with like).
        slices: List[Optional[List[int]]] = [None] * n
        if pin_cpus:
            try:
                cores = sorted(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                cores = []
            per = (int(pin_cpus) if pin_cpus is not True
                   else (len(cores) // n if cores else 0))
            if per >= 1 and len(cores) >= per * n:
                slices = [cores[i * per:(i + 1) * per] for i in range(n)]
            else:
                import warnings as _warnings

                _warnings.warn(
                    f"pin_cpus={pin_cpus!r} needs {per or 1} core(s) x "
                    f"{n} workers but only {len(cores)} are available; "
                    "workers run UNPINNED (a benchmark reading "
                    "equal-resource scaling from this run would be "
                    "comparing asymmetric configurations)", stacklevel=3)
        self.pinned = slices[0] is not None if slices else False

        if heartbeat_dir is None:
            heartbeat_dir = tempfile.mkdtemp(prefix="megba_fed_hb_")
            self._own_hb_dir = heartbeat_dir  # removed on close()
        world = n + 1  # rank 0 = the router (observer only)
        self._board = HeartbeatBoard(
            heartbeat_dir, 0, world, dead_after_s=dead_after_s)
        self._dead_state = RankState.DEAD

        handles: Dict[str, WorkerHandle] = {}
        pending: List[Tuple[WorkerHandle, Any]] = []
        try:
            for i in range(n):
                wid = f"w{i}"
                log = tempfile.NamedTemporaryFile(
                    prefix=f"megba_fed_{wid}_", suffix=".log",
                    delete=False)
                # -c entry rather than -m: runpy would re-execute the
                # module it had already imported via the package
                # __init__, a known double-module footgun.
                proc = subprocess.Popen(
                    [sys.executable, "-c",
                     "import sys; "
                     "from megba_tpu.serving.federation import "
                     "_worker_main; sys.exit(_worker_main())"],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    stderr=log, env=env)
                log.close()
                chan = FrameChannel(proc.stdout, proc.stdin)
                rank = i + 1
                # Heartbeat liveness is armed AFTER the hello: a worker
                # spends its first seconds importing jax before it can
                # beat, and the board's join grace (dead_after_s) is
                # sized for steady-state loss detection, not interpreter
                # startup on a loaded host.  Until then, pipe EOF and
                # process exit (checked every recv slice) cover real
                # startup deaths.
                handle = WorkerHandle(wid, proc, chan, log.name,
                                      liveness=None)
                handle.rank = rank
                chan.send({
                    "op": "config", "worker_id": wid,
                    "option": self.option, "ladder": self.ladder,
                    "artifacts": artifacts, "manifest": manifest,
                    "strict_manifest": strict_manifest,
                    "heartbeat": {"dir": heartbeat_dir, "rank": rank,
                                  "world": world},
                    "cpu_affinity": slices[i],
                    "telemetry": (None if self.telemetry is None
                                  else f"{self.telemetry}.{wid}"),
                })
                pending.append((handle, None))
                handles[wid] = handle
            for handle, _ in pending:
                try:
                    hello = handle.chan.recv(timeout_s=warm_timeout_s,
                                             poll=handle._poll)
                except (FrameError, WorkerLostError, TimeoutError) as exc:
                    raise RuntimeError(
                        f"federation worker {handle.worker_id} failed to "
                        f"come up: {exc}\n--- worker log ---\n"
                        f"{handle.log_tail()}") from exc
                if not hello.get("ok"):
                    raise RuntimeError(
                        f"federation worker {handle.worker_id} refused "
                        f"config: {hello.get('error')}\n--- worker log "
                        f"---\n{handle.log_tail()}")
                handle.warm = set(hello.get("warm", ()))
                handle.liveness = self._liveness_for(handle.rank,
                                                    handle.worker_id)
                self.stats.record_cold_start(
                    handle.worker_id, hello.get("cold_start", {}))
        except Exception:
            for handle in handles.values():
                handle.terminate()
            raise
        return handles

    def _liveness_for(self, rank: int, wid: str):
        def check() -> Optional[str]:
            if self._board is None:
                return None
            with self._hb_lock:
                states = self._board.observe()
                stale = self._board.staleness(rank)
            if states.get(rank) is self._dead_state:
                return (f"heartbeat dead (rank {rank} silent "
                        f"{stale:.2f}s)")
            return None

        return check

    # -- submission ------------------------------------------------------
    def _key_for(self, problem) -> Tuple:
        from megba_tpu.serving.shape_class import classify

        n_cam, n_pt, n_edge = problem.dims()
        sc = classify(n_cam, n_pt, n_edge, self.option.dtype, self.ladder)
        # The factor name rides the dims element (same 2-tuple shape the
        # routing/steal sites unpack): a routed batch must be one
        # residual family, exactly like the local queue's bucket key.
        dims = (int(problem.cameras.shape[1]),
                int(problem.points.shape[1]), int(problem.obs.shape[1]),
                str(getattr(problem, "factor", "bal")))
        return (sc, dims)

    def submit(self, problem, deadline_s: Optional[float] = None) -> Future:
        """Enqueue one problem; the Future resolves to its FleetResult
        (or raises `WorkerLostError` after `max_reroutes` losses /
        `DeadlineExceeded` when shed / whatever its worker's solve
        raised)."""
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        key = self._key_for(problem)
        now = time.monotonic()
        item = _Routed(
            problem=problem, future=Future(), bucket=str(key[0]), key=key,
            enqueued=now,
            deadline=None if deadline_s is None else now + deadline_s)
        with self._lock:
            if self._closing:
                raise RuntimeError("FleetRouter is closed")
            if not any(v.alive for v in self._views.values()):
                raise WorkerLostError("*", "no surviving workers")
            self._pending.setdefault(key, []).append(item)
            self._npending += 1
            if item.deadline is not None:
                self._ndeadline += 1
            self._lock.notify_all()
        return item.future

    def submit_many(self, problems: Sequence[Any],
                    deadline_s: Optional[float] = None) -> List[Future]:
        """Enqueue a whole fleet ATOMICALLY (one lock acquisition): no
        worker can pick a partial bucket mid-submission, so batch
        composition — and therefore the (bucket, lanes) programs hit —
        is deterministic for a given fleet.  A replica whose artifacts
        were exported from a `solve_many` pass over the same fleet then
        dispatches it entirely from the store (the zero-trace cold-start
        contract); per-problem `submit` keeps the latency-shaped
        streaming semantics instead."""
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        now = time.monotonic()
        items = []
        for problem in problems:
            key = self._key_for(problem)
            items.append(_Routed(
                problem=problem, future=Future(), bucket=str(key[0]),
                key=key, enqueued=now,
                deadline=None if deadline_s is None else now + deadline_s))
        with self._lock:
            if self._closing:
                raise RuntimeError("FleetRouter is closed")
            if not any(v.alive for v in self._views.values()):
                raise WorkerLostError("*", "no surviving workers")
            for item in items:
                self._pending.setdefault(item.key, []).append(item)
            self._npending += len(items)
            self._ndeadline += sum(
                1 for item in items if item.deadline is not None)
            self._lock.notify_all()
        return [item.future for item in items]

    def flush(self) -> None:
        """Block until every submitted problem has RESOLVED (result,
        reroute-exhaustion error, shed, or solve error).  Worker losses
        during the wait re-route work and keep the flush honest: it
        returns only when nothing is pending OR in flight."""
        with self._lock:
            while self._npending > 0 or self._inflight > 0:
                self._lock.wait()

    def close(self) -> None:
        """Drain, stop serve threads, shut workers down, emit the
        federation telemetry report.  Idempotent: a second close (e.g.
        context-manager exit after an explicit close) is a no-op — in
        particular it must not append a duplicate federation report
        line to the telemetry sink."""
        with self._lock:
            already = self._closed
            self._closing = True
            self._closed = True
            self._lock.notify_all()
        if already:
            return
        for t in self._threads:
            t.join()
        for w in self.workers.values():
            if w.alive:
                try:
                    w.request({"op": "shutdown"}, timeout_s=30.0)
                    proc = getattr(w, "proc", None)
                    if proc is not None:  # let the clean exit land
                        proc.wait(timeout=10)
                except (WorkerLostError, TimeoutError,
                        subprocess.TimeoutExpired):
                    pass
            w.terminate()
            # Clean-exit worker logs are noise; keep a log only when
            # the worker died abnormally (its tail is the forensics
            # WorkerLostError already quoted).
            rc = getattr(getattr(w, "proc", None), "returncode", None)
            log_path = getattr(w, "log_path", None)
            if log_path and rc == 0:
                try:
                    os.unlink(log_path)
                except OSError:
                    pass
        if self._own_hb_dir is not None:
            import shutil

            shutil.rmtree(self._own_hb_dir, ignore_errors=True)
        if self.telemetry:
            append_federation_report(self.option, self.stats, self.timer,
                                     self.telemetry)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- observability harvesting ----------------------------------------
    def metrics_snapshot(self) -> Optional[Dict[str, Any]]:
        """Fleet-wide merged metrics snapshot, or None when the plane
        is off (`MEGBA_METRICS` unset everywhere).

        Pulls each live worker's registry snapshot over the RPC channel
        (a new lockstep `metrics` op, serialized against the serve
        thread by the handle's request lock) and merges it with the
        router's own — counters/histograms sum, gauges too (depth-style
        gauges are per-process, so the sum reads as fleet totals).  The
        merge iterates sorted names and sorted label keys, so repeated
        pulls on an idle fleet are bitwise identical — the stable seam
        a self-tuning router (ROADMAP item 4) can diff between policy
        adjustments.  Workers that died, or stubs that do not speak the
        op, are skipped rather than failed: harvesting is forensic and
        must never take the fleet down.
        """
        snaps: List[Dict[str, Any]] = []
        registry = _obs.metrics_registry()
        if registry is not None:
            snaps.append(registry.snapshot())
        # Liveness comes from the locked `_views` mirror, not the
        # handles' `alive` flags: a serve thread declaring a loss writes
        # the flag concurrently with this pull, and the router lock is
        # the only ordering the two threads share (guarded-by contract).
        with self._lock:
            live = [w for w in self.workers.values()
                    if self._views[w.worker_id].alive]
        for w in live:
            try:
                reply = w.request({"op": "metrics"}, timeout_s=60.0)
            except Exception:
                continue  # lost mid-pull or stub without the op
            if isinstance(reply, dict) and reply.get("ok") \
                    and reply.get("metrics") is not None:
                snaps.append(reply["metrics"])
        if not snaps:
            return None
        from megba_tpu.observability import metrics as _metrics

        return _metrics.merge_snapshots(snaps)

    # -- dispatch --------------------------------------------------------
    @staticmethod
    def _resolve(future: Future, result=None, exc=None) -> None:
        try:
            if future.cancelled():
                return
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(result)
        except InvalidStateError:
            pass

    def _shed_expired_locked(self, now: float) -> List[_Routed]:
        if self._ndeadline <= 0:
            return []
        shed: List[_Routed] = []
        removed = 0
        for key in list(self._pending):
            items = self._pending[key]
            keep: List[_Routed] = []
            for it in items:  # one O(n) partition pass per bucket
                if it.future.cancelled():
                    removed += 1
                    if it.deadline is not None:
                        self._ndeadline -= 1
                elif it.deadline is not None and now >= it.deadline:
                    removed += 1
                    self._ndeadline -= 1
                    shed.append(it)
                else:
                    keep.append(it)
            if len(keep) != len(items):
                if keep:
                    self._pending[key] = keep
                else:
                    del self._pending[key]
        if removed:
            self._npending = sum(len(v) for v in self._pending.values())
        return shed

    def _depths_locked(self) -> Dict[str, int]:
        depths: Dict[str, int] = {}
        for (sc, _dims), items in self._pending.items():
            if items:
                depths[str(sc)] = depths.get(str(sc), 0) + len(items)
        return depths

    def _pick_locked(self, wid: str) -> Tuple[Optional[List[_Routed]], bool]:
        """(batch, stolen) for worker `wid`, or (None, False)."""
        view = self._views[wid]
        # 1) buckets homed here (or routable here), oldest first
        candidates = []
        for key, items in self._pending.items():
            if not items:
                continue
            bucket = str(key[0])
            homed = self._table.assignment.get(bucket)
            if homed is None:
                homed = self._table.route(bucket, self._views)
            if homed == wid:
                candidates.append((min(it.enqueued for it in items), key))
        if candidates:
            # Tiebreak on the bucket string: submit_many stamps a whole
            # fleet with ONE enqueue time, and (ShapeClass, dims) keys
            # do not order.
            _, key = min(candidates, key=lambda c: (c[0], str(c[1][0]),
                                                    c[1][1]))
            return self._take_locked(key, view), False
        # 2) steal: deepest warm backlog homed on a live peer
        if self.steal:
            bucket = self._table.steal_candidate(
                wid, self._views, self._depths_locked())
            if bucket is not None:
                for key, items in self._pending.items():
                    if str(key[0]) == bucket and items:
                        return self._take_locked(key, view), True
        return None, False

    def _take_locked(self, key: Tuple, view: WorkerView) -> List[_Routed]:
        items = self._pending[key]
        take = items[:self.max_batch]
        rest = items[self.max_batch:]
        if rest:
            self._pending[key] = rest
        else:
            del self._pending[key]
        self._npending -= len(take)
        self._ndeadline -= sum(
            1 for it in take if it.deadline is not None)
        view.routed += len(take)
        return take

    def _serve(self, worker) -> None:
        wid = worker.worker_id
        while True:
            batch: Optional[List[_Routed]] = None
            stolen = False
            shed_out: Optional[List[_Routed]] = None
            with self._lock:
                while True:
                    if not self._views[wid].alive:
                        return
                    now = time.monotonic()
                    shed = self._shed_expired_locked(now)
                    if shed:
                        # Shed futures resolve OUTSIDE the lock (a
                        # done-callback re-entering the router must not
                        # self-deadlock on the non-reentrant Condition);
                        # they count as in-flight until resolved so
                        # flush() cannot observe "drained" early — the
                        # FleetQueue shed discipline.
                        self._inflight += len(shed)
                        shed_out = shed
                        break
                    batch, stolen = self._pick_locked(wid)
                    if batch is not None:
                        break
                    if (self._closing and self._npending == 0
                            and self._inflight == 0):
                        return
                    # Wake on submit/reroute/close; the timed slice also
                    # re-checks deadlines so sheds stay prompt.
                    self._lock.wait(timeout=0.05)
                if batch is not None:
                    self._inflight += len(batch)
            if shed_out is not None:
                self.stats.record_shed(len(shed_out))
                self.timer.count_event("federation_shed", len(shed_out))
                for it in shed_out:
                    self._resolve(it.future, exc=DeadlineExceeded(
                        f"problem {it.problem.name!r} shed before "
                        "dispatch (deadline expired)"))
                with self._lock:
                    self._inflight -= len(shed_out)
                    self._lock.notify_all()
                continue
            try:
                try:
                    msg: Dict[str, Any] = {
                        "op": "solve",
                        "problems": [it.problem for it in batch]}
                    recorder = _obs.span_recorder()
                    scope = (contextlib.nullcontext()
                             if recorder is None else recorder.span(
                                 "fed_dispatch", bucket=batch[0].bucket,
                                 worker=wid, problems=len(batch),
                                 stolen=stolen))
                    with scope:
                        if recorder is not None:
                            msg["trace"] = recorder.context()
                        reply = worker.request(
                            msg, timeout_s=self.watchdog_s)
                    if recorder is not None and reply.get("spans"):
                        recorder.ingest(reply["spans"])
                except (WorkerLostError, TimeoutError) as exc:
                    if isinstance(exc, TimeoutError):
                        exc = WorkerLostError(
                            wid, "solve exceeded the "
                            f"{self.watchdog_s:.0f}s watchdog budget")
                    self._on_worker_lost(worker, batch, exc)
                    return
                now = time.monotonic()
                if reply.get("ok") and len(reply.get("results", ())) != len(
                        batch):
                    # A short/long ok-reply must fail the batch TYPED —
                    # zip truncation would strand the tail futures
                    # unresolved past flush() forever ("never silently").
                    reply = {"ok": False, "error": (
                        f"worker returned {len(reply.get('results', ()))} "
                        f"results for a {len(batch)}-problem batch")}
                if reply.get("ok"):
                    results = reply["results"]
                    worker.warm = set(reply.get("warm", worker.warm))
                    with self._lock:
                        self._views[wid].warm = set(worker.warm)
                    if reply.get("first_solve") is not None:
                        self.stats.record_first_solve(
                            wid, reply["first_solve"])
                    self.stats.record_batch(wid, len(batch), stolen)
                    registry = _obs.metrics_registry()
                    if registry is not None:
                        registry.counter(
                            "megba_fed_dispatch_total",
                            "Problems dispatched per shape-class bucket "
                            "and worker").inc(
                                len(batch), bucket=batch[0].bucket,
                                worker=wid)
                        if stolen:
                            registry.counter(
                                "megba_fed_steal_total",
                                "Problems moved by work-stealing").inc(
                                    len(batch), bucket=batch[0].bucket,
                                    worker=wid)
                    if stolen:
                        self.timer.count_event("federation_steal")
                        self.timer.count_event(
                            "federation_stolen_problems", len(batch))
                    for it, fr in zip(batch, results):
                        fr.latency_s = now - it.enqueued
                        if (it.deadline is not None
                                and now >= it.deadline):
                            # The FleetQueue contract: a late result is
                            # DELIVERED, flagged, counted — never
                            # silently on time.
                            fr.deadline_missed = True
                            self.stats.record_deadline_miss()
                            self.timer.count_event(
                                "federation_deadline_miss")
                        self._resolve(it.future, result=fr)
                else:
                    err = RuntimeError(
                        f"worker {wid} solve failed: "
                        f"{reply.get('error')}")
                    for it in batch:
                        self._resolve(it.future, exc=err)
            except Exception as exc:  # never die silently mid-batch
                # A router-side bug must fail THIS batch typed and keep
                # the thread serving — a dead serve thread would wedge
                # flush() forever (the FleetQueue dispatcher contract).
                for it in batch:
                    self._resolve(it.future, exc=exc)
            finally:
                with self._lock:
                    self._inflight -= len(batch)
                    self._lock.notify_all()

    def _on_worker_lost(self, worker, batch: List[_Routed],
                        exc: WorkerLostError) -> None:
        """Typed loss handling: count it, reroute the in-flight batch
        (bounded), re-home the dead worker's buckets, keep serving."""
        wid = worker.worker_id
        worker.alive = False
        worker.terminate()
        self.stats.record_worker_lost(wid)
        self.timer.count_event("federation_worker_lost")
        registry = _obs.metrics_registry()
        if registry is not None:
            registry.counter("megba_fed_worker_lost_total",
                             "Federation workers lost").inc(worker=wid)
        flight = _obs.flight_recorder()
        if flight is not None:
            # The router-side crash record for deaths the worker could
            # not announce (SIGKILL, OOM): what died, why, and what it
            # had in flight — then dump the ring, because the fleet may
            # be about to fail outright if this was the last survivor.
            flight.record(
                "worker_lost", worker=wid, reason=exc.reason,
                inflight=len(batch),
                buckets=sorted({it.bucket for it in batch})[:8])
        # Failures are COLLECTED under the lock and resolved outside it:
        # a future's done-callback may re-enter the router, and the
        # Condition's lock is not reentrant.  The failed items count as
        # in-flight until resolved (the caller's finally decrements the
        # batch; _inflight covers it throughout).
        to_fail: List[Tuple[Future, WorkerLostError]] = []
        with self._lock:
            self._views[wid].alive = False
            self._table.reassign_lost(wid, self._views)
            survivors = any(v.alive for v in self._views.values())
            rerouted = 0
            for it in batch:
                it.reroutes += 1
                if not survivors:
                    to_fail.append((it.future, WorkerLostError(
                        wid, f"{exc.reason}; no surviving workers")))
                elif it.reroutes > self.max_reroutes:
                    self.stats.record_reroute_failure()
                    to_fail.append((it.future, WorkerLostError(
                        wid, f"{exc.reason}; rerouted {it.reroutes - 1} "
                        f"times (max_reroutes={self.max_reroutes})")))
                else:
                    self._pending.setdefault(it.key, []).append(it)
                    self._npending += 1
                    if it.deadline is not None:
                        self._ndeadline += 1
                    rerouted += 1
            if rerouted:
                self.stats.record_reroute(rerouted)
                self.timer.count_event("federation_reroute", rerouted)
                if registry is not None:
                    for it in batch:
                        if it.reroutes <= self.max_reroutes:
                            registry.counter(
                                "megba_fed_reroute_total",
                                "Problems rerouted off lost workers"
                            ).inc(bucket=it.bucket)
                if flight is not None:
                    flight.record("reroute", worker=wid, n=rerouted)
            if not survivors:
                # Nothing can serve the queue: fail it all, typed.
                for key in list(self._pending):
                    for it in self._pending.pop(key):
                        to_fail.append((it.future, WorkerLostError(
                            wid, f"{exc.reason}; no surviving workers")))
                self._npending = 0
                self._ndeadline = 0
            # in-flight accounting: the serve loop's finally owns the
            # decrement (this handler runs inside its try)
            self._lock.notify_all()
        for future, err in to_fail:
            self._resolve(future, exc=err)
        with self._lock:
            self._lock.notify_all()  # flush waiters re-check after fails
        if flight is not None:
            from megba_tpu.observability import flight as _flight

            _flight.dump_default(f"worker_lost:{wid}")


def append_federation_report(option, stats: FederationStats, timer,
                             path: str) -> None:
    """One router-lifetime SolveReport line carrying the federation
    block — what `summarize --aggregate`'s federation view renders."""
    from megba_tpu.observability.report import (
        SolveReport,
        append_report,
        backend_topology,
        config_to_dict,
    )

    rep = SolveReport(
        problem={"kind": "federation_router"},
        config=config_to_dict(option),
        backend=backend_topology(),
        phases=timer.as_dict(),
        result={},
        federation=stats.as_dict(),
        created_unix=wall_unix(),
    )
    append_report(rep, path)


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        sys.exit(_worker_main())
    print(__doc__)
    sys.exit(2)
