"""Federation tier: one router, N worker processes, a fleet of fleets.

`solve_many` / `FleetQueue` saturate ONE host; this module is the
scale-out story (ROADMAP item 2): a `FleetRouter` fronting N worker
PROCESSES, each running the whole single-host serving stack (compile
pool + batched mega-solves) behind a small length-prefixed pickle RPC
over its stdin/stdout pipes — the same subprocess discipline as the
kill-resume harness (robustness/harness.py): workers are real
processes that really die, stderr is the log channel, and the RPC
channel carries nothing but frames.

Three connected mechanisms:

- **Shape-class routing, occupancy-aware.**  Problems shard across
  workers BY SHAPE CLASS, not round-robin: all problems of one bucket
  flow to one worker until stolen or rerouted, so per-host bucket
  occupancy stays high (padding waste — which `FleetStats` measures —
  is paid per DISPATCH; splitting a bucket across hosts would pay it
  twice at half the lane fill).  A new class lands on the worker that
  already has it WARM (artifact-loaded executables first), then the
  least-loaded worker (`RoutingTable`, a pure host policy class).

- **Work-stealing for hot buckets.**  An idle worker pulls queued
  problems for buckets IT HAS WARM from the deepest backlog of a busy
  peer — before it would compile anything new.  Stealing moves work,
  never assignments: the hot bucket keeps its home, the thief drains
  overflow with a program it already holds (typically loaded from the
  shared `ArtifactStore` in milliseconds).

- **Host-loss rerouting.**  A dead worker is a dispatch exception plus
  a requeue, exactly the PR 8 retry-ladder stance: liveness is PR 9's
  `HeartbeatBoard` (workers beat heartbeat files; the router observes
  counter changes on its own clock) plus pipe-EOF/process-exit
  detection, a loss is a typed `WorkerLostError`, the lost worker's
  in-flight and queued problems re-route to survivors with bounded
  `max_reroutes` and `worker_lost`/`rerouted` counters — never
  silently, never wedging `flush()`.

Cold start is the third leg (serving/artifacts.py): workers warm from
a manifest + serialized-executable store, so a fresh replica's
cold-start-to-first-solve is I/O-bound — its first fleet dispatches
with ZERO traces (the worker certifies this against the retrace
sentinel and reports the count in its hello).

Everything host-side here is plain threads, pipes and pickle — no new
collectives, no device code; the workers' solve programs are byte-wise
the single-host ones, so a federated fleet's results are BITWISE the
`solve_many` results at the same shape classes (padding exactness,
PR 6) no matter how routing, stealing or rerouting scattered them.

Transports (PR 20, serving/transport.py): the frame stream runs over
subprocess pipes (`transport="pipe"`, the single-host default) or TCP
(`transport="tcp"`): the router binds a listening socket, workers dial
in (or the router dials bind-mode workers via `connect=`) and
register through a token/version/fingerprint handshake.  A dropped TCP
connection is NOT a worker loss: the handle enters a
capped-exponential-backoff reconnect window (`ReconnectPolicy`,
deterministic seeded jitter) during which the worker's buckets detour
to warm peers while its assignment survives; in-flight requests are
resent idempotently by sequence id (the worker's reply cache dedups),
and only window exhaustion or process death converts to the
`WorkerLostError` reroute path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import uuid
import warnings
import zlib
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from megba_tpu import observability as _obs
from megba_tpu.serving.resilience import DeadlineExceeded
from megba_tpu.serving.transport import (
    FrameError,
    HandshakeError,
    PipeTransport,
    ReconnectPolicy,
    TcpTransport,
    is_heartbeat,
    parse_address,
    refusal_frame,
    ack_frame,
    verify_register,
)
from megba_tpu.utils.timing import monotonic_s, wall_unix

# Back-compat alias: the pipe frame channel moved to transport.py and
# grew the integrity-checked frame header; the name stays importable.
FrameChannel = PipeTransport


class ColdDispatchWarning(UserWarning):
    """A dispatch targeted a worker with no ready program for its
    (bucket, lanes, rung) key — a compile-on-dispatch latency cliff the
    artifact manifest should have covered.  Warned ONCE per missing
    key; every occurrence counts (`fed_cold_dispatch`)."""


class WorkerLostError(RuntimeError):
    """A federation worker died (or stopped beating) with work on it.

    `worker_id` names the worker, `reason` what was observed (pipe EOF,
    process exit code, heartbeat staleness).  Problems that exhaust
    `max_reroutes` across successive losses fail with this error — the
    caller sees WHY, never a hang.
    """

    def __init__(self, worker_id: str, reason: str) -> None:
        self.worker_id = worker_id
        self.reason = reason
        super().__init__(f"federation worker {worker_id!r} lost: {reason}")


# ---------------------------------------------------------------------------
# Connection supervision primitives
# ---------------------------------------------------------------------------


class _ConnSuspect(Exception):
    """Internal: the connection looks dead (heartbeat silence) but the
    worker may be fine behind it — enter the reconnect window rather
    than the loss path."""


class _NeverTransport:
    """Placeholder transport for a TCP handle awaiting its first
    registration: every operation reports 'not connected', which the
    reconnect machinery treats like any other dropped link."""

    def send(self, obj: Any) -> None:
        raise BrokenPipeError("worker not yet connected")

    def recv(self, timeout_s: Optional[float] = None,
             poll: Optional[Callable[[], None]] = None) -> Any:
        raise FrameError("worker not yet connected")

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Routing policy (pure host state, unit-testable without processes)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WorkerView:
    """What the routing policy may know about one worker.

    `alive` is the terminal flag (process dead / reconnect budget
    exhausted — buckets re-home); `connected` is the transient one (the
    TCP link dropped but the worker may return inside its reconnect
    window — buckets DETOUR to warm peers, the assignment survives)."""

    worker_id: str
    warm: set  # bucket strs with a ready (artifact/compiled) program
    alive: bool = True
    connected: bool = True
    assigned: set = dataclasses.field(default_factory=set)  # bucket strs
    routed: int = 0  # problems ever routed here (load tiebreak)


class RoutingTable:
    """Shape-class → worker assignment with warm-first affinity.

    Policy, in order: (1) sticky — a bucket keeps its worker while that
    worker lives (occupancy: one home per bucket fills lanes instead of
    splitting them); (2) warm-first — a NEW bucket goes to a live
    worker that already holds its program (artifact-loaded executables
    make this common after one export cycle); (3) least-loaded — fewest
    assigned buckets, then fewest routed problems, then worker id (a
    deterministic tiebreak so tests and reruns route identically).

    `steal_candidate` picks what an idle worker should pull: the
    DEEPEST backlog among buckets homed on other live workers that the
    thief has WARM — it never volunteers a bucket it would have to
    compile for (that would trade queueing delay for compile delay).

    Pure host state over caller-supplied views; the router drives it
    under its own lock.
    """

    def __init__(self) -> None:
        self.assignment: Dict[str, str] = {}  # bucket str -> worker id

    def route(self, bucket: str,
              workers: Dict[str, WorkerView]) -> Optional[str]:
        homed = self.assignment.get(bucket)
        if homed is not None and workers[homed].alive:
            if workers[homed].connected:
                return homed
            # Home is inside its reconnect window: DETOUR this pick to
            # a connected peer that already holds the program, without
            # re-homing — the assignment survives the flap, but work
            # keeps flowing (routable-away).  No warm peer: wait for
            # the home to return rather than compile elsewhere.
            detour = [w for w in workers.values()
                      if w.alive and w.connected and bucket in w.warm]
            if detour:
                best = min(detour, key=lambda w: (len(w.assigned),
                                                  w.routed, w.worker_id))
                return best.worker_id
            return homed
        alive = [w for w in workers.values()
                 if w.alive and w.connected]
        if not alive:
            # Every survivor is mid-reconnect: fall back to any live
            # worker so routing still lands somewhere (the dispatch
            # will ride that handle's reconnect window).
            alive = [w for w in workers.values() if w.alive]
        if not alive:
            return None
        warm = [w for w in alive if bucket in w.warm]
        pool = warm or alive
        best = min(pool, key=lambda w: (len(w.assigned), w.routed,
                                        w.worker_id))
        self.assignment[bucket] = best.worker_id
        best.assigned.add(bucket)
        return best.worker_id

    def steal_candidate(self, thief: str, workers: Dict[str, WorkerView],
                        depths: Dict[str, int]) -> Optional[str]:
        """Bucket the idle `thief` should pull work from, or None."""
        view = workers[thief]
        candidates = [
            (depth, bucket) for bucket, depth in depths.items()
            if depth > 0 and bucket in view.warm
            and self.assignment.get(bucket) not in (None, thief)
            and workers[self.assignment[bucket]].alive
        ]
        if not candidates:
            return None
        _, bucket = max(candidates, key=lambda c: (c[0], c[1]))
        return bucket

    def reassign_lost(self, lost: str,
                      workers: Dict[str, WorkerView]) -> List[str]:
        """Forget every bucket homed on `lost`; they re-route on next
        pick.  Returns the orphaned bucket names."""
        orphaned = [b for b, w in self.assignment.items() if w == lost]
        for b in orphaned:
            del self.assignment[b]
        if lost in workers:
            workers[lost].assigned.clear()
        return orphaned


# ---------------------------------------------------------------------------
# Federation stats
# ---------------------------------------------------------------------------


class FederationStats:
    """Router-level counters: where problems ran, what moved, what died.

    The per-worker `FleetStats` still live inside each worker (their
    dispatch telemetry embeds them); this object is the ROUTER's view —
    the one `summarize --aggregate`'s federation block renders."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.router = uuid.uuid4().hex[:12]
        self.problems = 0  # megba: guarded-by(_lock); resolved via router
        self.problems_by_worker: Dict[str, int] = {}  # megba: guarded-by(_lock)
        self.steals = 0  # megba: guarded-by(_lock); one per pulled batch
        self.stolen_problems = 0  # megba: guarded-by(_lock)
        self.reroutes = 0  # megba: guarded-by(_lock); requeued off a loss
        self.reroute_failures = 0  # megba: guarded-by(_lock); max_reroutes hit
        self.escalations = 0  # megba: guarded-by(_lock); ladder consults past max_reroutes
        self.cold_dispatches = 0  # megba: guarded-by(_lock); dispatches with no warm program on target
        self.workers_lost = 0  # megba: guarded-by(_lock)
        self.sheds = 0  # megba: guarded-by(_lock); shed before dispatch
        self.deadline_misses = 0  # megba: guarded-by(_lock); delivered late
        self.cold_start: Dict[str, Dict[str, Any]] = {}  # megba: guarded-by(_lock); worker -> hello
        self.first_solve: Dict[str, Dict[str, Any]] = {}  # megba: guarded-by(_lock)
        self.lost_workers: List[str] = []  # megba: guarded-by(_lock)

    def record_batch(self, worker_id: str, n: int, stolen: bool) -> None:
        with self._lock:
            self.problems += n
            self.problems_by_worker[worker_id] = (
                self.problems_by_worker.get(worker_id, 0) + n)
            if stolen:
                self.steals += 1
                self.stolen_problems += n

    def record_reroute(self, n: int) -> None:
        with self._lock:
            self.reroutes += n

    def record_reroute_failure(self, n: int = 1) -> None:
        with self._lock:
            self.reroute_failures += n

    def record_escalation(self, n: int = 1) -> None:
        with self._lock:
            self.escalations += n

    def record_cold_dispatch(self, n: int = 1) -> None:
        with self._lock:
            self.cold_dispatches += n

    def record_worker_lost(self, worker_id: str) -> None:
        with self._lock:
            self.workers_lost += 1
            self.lost_workers.append(worker_id)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.sheds += n

    def record_deadline_miss(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_misses += n

    def record_cold_start(self, worker_id: str,
                          info: Dict[str, Any]) -> None:
        with self._lock:
            self.cold_start[worker_id] = dict(info)

    def record_first_solve(self, worker_id: str,
                           info: Dict[str, Any]) -> None:
        with self._lock:
            self.first_solve[worker_id] = dict(info)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "router": self.router,
                "problems": self.problems,
                "problems_by_worker": dict(self.problems_by_worker),
                "steals": self.steals,
                "stolen_problems": self.stolen_problems,
                "reroutes": self.reroutes,
                "reroute_failures": self.reroute_failures,
                "escalations": self.escalations,
                "cold_dispatches": self.cold_dispatches,
                "workers_lost": self.workers_lost,
                "lost_workers": list(self.lost_workers),
                "sheds": self.sheds,
                "deadline_misses": self.deadline_misses,
                "cold_start": {k: dict(v)
                               for k, v in self.cold_start.items()},
                "first_solve": {k: dict(v)
                                for k, v in self.first_solve.items()},
            }

    def report(self) -> str:
        d = self.as_dict()
        per = " / ".join(
            f"{w}:{n}" for w, n in sorted(d["problems_by_worker"].items()))
        lines = [
            f"federation: {d['problems']} problems ({per or 'none'}), "
            f"{d['steals']} steals ({d['stolen_problems']} problems), "
            f"{d['reroutes']} rerouted, {d['workers_lost']} workers lost"]
        for w, cs in sorted(d["cold_start"].items()):
            fs = d["first_solve"].get(w) or {}
            lines.append(
                f"  {w}: cold start {cs.get('mode', '?')} "
                f"{cs.get('warm_s', float('nan')):.3f}s "
                f"({cs.get('artifact_loads', 0)} loaded / "
                f"{cs.get('artifact_compiles', 0)} compiled)"
                + (f", first solve {fs.get('traces')} traces"
                   if fs else ""))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Worker process (the --worker entry point)
# ---------------------------------------------------------------------------


def _worker_main() -> int:
    """Pipe-worker entry (the `-c` spawn string imports this name);
    the serve loop itself lives in serving/worker.py, shared with the
    TCP bootstrap CLI."""
    from megba_tpu.serving.worker import pipe_worker_main

    return pipe_worker_main()


def _shape_of(entry: Dict[str, Any]):
    from megba_tpu.serving.shape_class import ShapeClass

    return ShapeClass.from_dict(entry["shape"])


# ---------------------------------------------------------------------------
# Router-side worker handle
# ---------------------------------------------------------------------------


class WorkerHandle:
    """One spawned worker: process + channel + router-side bookkeeping.

    `request` is strictly lockstep at the FRAME level (the worker's
    serve loop answers one request at a time, in arrival order) but no
    lock is ever held across the blocking reply read: sends are
    serialized under `_req_lock` and stamped with a ticket, and replies
    are read in ticket order under the `_turn` condition — the reader
    whose turn it is owns the pipe with every lock released, so an
    out-of-band `metrics` pull never stalls a lock behind a whole solve
    RPC (the blocking-under-lock shape lint lane 6 polices).  Every
    request carries a sequence id; the reader skims heartbeat frames
    and drops stale duplicate replies, matching on its own seq.  Every
    death signal — pipe EOF, process exit, heartbeat DEAD — converts
    into a typed `WorkerLostError`, and the FIRST observed death is
    recorded so every later waiter fails FAST instead of re-spending a
    full watchdog budget on a connection already known dead."""

    def __init__(self, worker_id: str, proc: Optional[subprocess.Popen],
                 chan, log_path: str,
                 liveness: Optional[Callable[[], Optional[str]]] = None,
                 ) -> None:
        self.worker_id = worker_id
        self.proc = proc
        self.chan = chan
        self.log_path = log_path
        self.liveness = liveness
        # `warm`/`alive` are confined to this worker's serve thread once
        # it starts (spawn-time writes order-before via Thread.start;
        # close() reads only after joining it).  Cross-thread consumers
        # go through FleetRouter's locked `_views` mirror instead — see
        # metrics_snapshot().
        self.warm: set = set()
        self.alive = True
        self.pid = proc.pid if proc is not None else None
        self.rank = 0  # heartbeat-board rank, set by the router at spawn
        # First observed death reason: write-once latch (benign racing
        # writers would record equivalent reasons); readers fail fast
        # without waiting on a channel that can never answer.
        self._death: Optional[str] = None
        self.last_rx = monotonic_s()  # any frame (incl. heartbeats)
        # Serializes SENDS (the channel is strictly lockstep, so two
        # concurrent writers would interleave frames) and hands out
        # reply tickets; never held across a read.
        self._req_lock = threading.Lock()
        self._next_send = 0  # megba: guarded-by(_req_lock)
        self._seq = 0  # megba: guarded-by(_req_lock); request sequence ids
        # Orders reply reads: replies arrive in send order (the worker
        # serve loop is single-threaded FIFO), so ticket n reads the
        # n-th reply — exclusivity without holding anything during the
        # blocking recv.
        self._turn = threading.Condition()
        self._next_recv = 0  # megba: guarded-by(_turn)

    def _record_death(self, reason: str) -> None:
        if self._death is None:
            self._death = reason

    def _check_death(self) -> None:
        death = self._death
        if death is not None:
            raise WorkerLostError(self.worker_id,
                                  f"{death} (fail-fast: recorded death)")

    def _poll(self) -> None:
        if self.proc is not None:
            rc = self.proc.poll()
            if rc is not None:
                raise WorkerLostError(self.worker_id,
                                      f"process exited rc={rc}")
        if self.liveness is not None:
            reason = self.liveness()
            if reason:
                raise WorkerLostError(self.worker_id, reason)

    def request(self, msg: Dict[str, Any],
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        self._check_death()
        try:
            with self._req_lock:
                seq = self._seq
                self._seq += 1
                msg = dict(msg)
                msg["seq"] = seq
                self.chan.send(msg)
                ticket = self._next_send
                self._next_send += 1
            with self._turn:
                while self._next_recv != ticket:
                    self._turn.wait()
            try:
                # Our turn: ticket order makes this thread the sole
                # reader, with no lock held across the blocking recv.
                self._check_death()
                return self._recv_reply(seq, timeout_s)
            finally:
                # Always pass the turn — even on a broken pipe the next
                # ticket holder must wake (its fail-fast check or its
                # own recv then raises).
                with self._turn:
                    self._next_recv += 1
                    self._turn.notify_all()
        except WorkerLostError as exc:
            self._record_death(exc.reason)
            raise
        except (FrameError, BrokenPipeError, OSError) as exc:
            rc = self.proc.poll() if self.proc is not None else None
            reason = (f"rpc stream broke ({type(exc).__name__}: {exc}); "
                      f"process rc={rc}")
            self._record_death(reason)
            raise WorkerLostError(self.worker_id, reason) from exc

    def _recv_reply(self, seq: int,
                    timeout_s: Optional[float]) -> Dict[str, Any]:
        """Read frames until this request's reply: heartbeats update
        liveness and are skimmed; a reply with an older seq is a stale
        duplicate (post-reconnect resend race) and is dropped."""
        deadline = None if timeout_s is None else (
            monotonic_s() + timeout_s)
        while True:
            remaining = None if deadline is None else max(
                deadline - monotonic_s(), 0.0)
            frame = self.chan.recv(timeout_s=remaining, poll=self._poll)
            self.last_rx = monotonic_s()
            if is_heartbeat(frame):
                continue
            fseq = frame.get("seq") if isinstance(frame, dict) else None
            if fseq is not None and fseq != seq:
                continue
            return frame

    def log_tail(self, max_bytes: int = 8192) -> str:
        try:
            with open(self.log_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(size - max_bytes, 0))
                return fh.read().decode(errors="replace")
        except OSError:
            return "<no worker log>"

    def terminate(self) -> None:
        self.alive = False
        self.chan.close()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass


class TcpWorkerHandle(WorkerHandle):
    """A worker reached over TCP: the channel can DROP without the
    worker dying.

    On a connection failure the reader does not raise `WorkerLostError`
    — it enters the reconnect window: wait (on the router's own clock)
    for the accept/dial machinery to `adopt` a fresh transport, then
    RESEND its request with the SAME sequence id.  The worker's reply
    cache makes the resend idempotent: work it already did is answered
    from cache, never re-executed.  Only window exhaustion or process
    death converts to the typed loss path."""

    def __init__(self, worker_id: str, chan, *,
                 proc: Optional[subprocess.Popen] = None,
                 log_path: str = "",
                 reconnect: Optional[ReconnectPolicy] = None,
                 conn_dead_after_s: float = 5.0,
                 on_event: Optional[Callable[..., None]] = None) -> None:
        super().__init__(worker_id, proc, chan, log_path, liveness=None)
        self.reconnect = reconnect or ReconnectPolicy()
        self.conn_dead_after_s = float(conn_dead_after_s)
        self.incarnation = 0
        self._on_event = on_event
        # Transport generation: bumped by adopt(); readers stranded on
        # a dead connection wait here for the replacement.
        self._tlock = threading.Condition()
        self._epoch = 0  # megba: guarded-by(_tlock)

    def _emit(self, event: str, **fields: Any) -> None:
        if self._on_event is not None:
            self._on_event(event, worker=self.worker_id, **fields)

    def adopt(self, transport, incarnation: int) -> None:
        """Install a freshly-registered connection (accept/dial thread)
        and wake every reader waiting out the reconnect window."""
        with self._tlock:
            old = self.chan
            self.chan = transport
            self.incarnation = int(incarnation)
            self._epoch += 1
            epoch = self._epoch
            self.last_rx = monotonic_s()
            self._tlock.notify_all()
        try:
            old.close()
        except OSError:
            pass
        # Epoch 1 is the worker's FIRST registration — that is a
        # connect, not a reconnect (the metric must count recoveries).
        self._emit("reconnect" if epoch > 1 else "connect",
                   incarnation=int(incarnation))

    def _poll(self) -> None:
        if self.proc is not None:
            rc = self.proc.poll()
            if rc is not None:
                raise WorkerLostError(self.worker_id,
                                      f"process exited rc={rc}")
        if (self.conn_dead_after_s > 0
                and monotonic_s() - self.last_rx > self.conn_dead_after_s):
            raise _ConnSuspect(
                f"no frames or heartbeats for {self.conn_dead_after_s:.1f}s")

    def request(self, msg: Dict[str, Any],
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        self._check_death()
        try:
            with self._req_lock:
                seq = self._seq
                self._seq += 1
                msg = dict(msg)
                msg["seq"] = seq
                with self._tlock:
                    sent_epoch = self._epoch
                try:
                    self.chan.send(msg)
                except OSError:
                    # Connection already down: take the ticket anyway;
                    # the reader resends once a transport is adopted.
                    sent_epoch -= 1
                ticket = self._next_send
                self._next_send += 1
            with self._turn:
                while self._next_recv != ticket:
                    self._turn.wait()
            try:
                self._check_death()
                return self._reply_with_reconnect(
                    msg, seq, sent_epoch, timeout_s)
            finally:
                with self._turn:
                    self._next_recv += 1
                    self._turn.notify_all()
        except WorkerLostError as exc:
            self._record_death(exc.reason)
            raise

    def _reply_with_reconnect(self, msg: Dict[str, Any], seq: int,
                              sent_epoch: int,
                              timeout_s: Optional[float],
                              ) -> Dict[str, Any]:
        deadline = None if timeout_s is None else (
            monotonic_s() + timeout_s)
        # The staleness clock starts when we BEGIN listening: nobody
        # drains heartbeats while the handle is idle, so a healthy
        # worker's beats sit unread in the socket buffer and last_rx
        # goes stale — an idle gap must not read as silence.
        self.last_rx = max(self.last_rx, monotonic_s())
        while True:
            self._check_death()
            with self._tlock:
                cur_epoch = self._epoch
                chan = self.chan
            if cur_epoch > sent_epoch:
                # Reconnected since this request went out: resend with
                # the same seq (idempotent — the worker's dedup cache
                # answers anything it already executed from cache).
                try:
                    chan.send(msg)
                except OSError:
                    self._await_reconnect(cur_epoch, deadline)
                    continue
                sent_epoch = cur_epoch
                self._emit("resend", seq=seq, op=msg.get("op"))
            remaining = None if deadline is None else max(
                deadline - monotonic_s(), 0.0)
            try:
                frame = chan.recv(timeout_s=remaining, poll=self._poll)
            except _ConnSuspect as exc:
                self._emit("conn_lost", reason=str(exc))
                self._await_reconnect(cur_epoch, deadline)
                continue
            except TimeoutError:
                raise  # the watchdog budget: the serve loop types it
            except (FrameError, OSError) as exc:
                self._emit("conn_lost",
                           reason=f"{type(exc).__name__}: {exc}")
                self._await_reconnect(cur_epoch, deadline)
                continue
            self.last_rx = monotonic_s()
            if is_heartbeat(frame):
                continue
            fseq = frame.get("seq") if isinstance(frame, dict) else None
            if fseq is not None and fseq != seq:
                continue  # stale duplicate from before the reconnect
            return frame

    def _await_reconnect(self, seen_epoch: int,
                         watchdog_deadline: Optional[float]) -> None:
        """Wait out the reconnect window on the router's own clock:
        returns once a NEWER transport than `seen_epoch` is adopted;
        raises typed on window exhaustion, process death, or watchdog
        expiry.  The Condition wait releases the lock (the sanctioned
        blocking-under-lock shape)."""
        window_end = monotonic_s() + self.reconnect.window_s
        with self._tlock:
            while self._epoch <= seen_epoch:
                self._check_death()
                if self.proc is not None:
                    rc = self.proc.poll()
                    if rc is not None:
                        raise WorkerLostError(
                            self.worker_id,
                            f"process exited rc={rc} during the "
                            "reconnect window")
                now = monotonic_s()
                if watchdog_deadline is not None and now >= watchdog_deadline:
                    raise TimeoutError(
                        "watchdog budget expired inside the reconnect "
                        "window")
                if now >= window_end:
                    raise WorkerLostError(
                        self.worker_id,
                        "reconnect window exhausted "
                        f"({self.reconnect.window_s:.1f}s without "
                        "re-registration)")
                self._tlock.wait(timeout=0.05)

    def terminate(self) -> None:
        self._record_death("terminated by router")
        with self._tlock:
            self._tlock.notify_all()  # readers fail fast, not time out
        super().terminate()


# ---------------------------------------------------------------------------
# The router
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class _Routed:
    problem: Any  # FleetProblem
    future: Future
    bucket: str  # shape-class str (routing granularity)
    key: Tuple  # (ShapeClass, dims) — batching granularity
    enqueued: float
    deadline: Optional[float] = None
    reroutes: int = 0
    seq: int = 0  # submission sequence (escalation backoff seed)
    escalated: bool = False  # ladder consulted once past max_reroutes
    not_before: Optional[float] = None  # escalation backoff gate


class FleetRouter:
    """Front door of the federation tier: submit → Future, N workers.

    Mirrors `FleetQueue`'s surface (submit/flush/close/context-manager,
    Future-per-problem) one level up: submissions shard across worker
    PROCESSES by shape class, idle workers steal hot buckets they have
    warm, and a dead worker's problems re-route to survivors (bounded
    by `max_reroutes`) with typed counters.  `artifacts` + `manifest`
    give workers the millisecond cold start (serving/artifacts.py);
    without them workers compile on first warm like any fresh service.

    `workers=` injects pre-built worker handles (anything with
    `worker_id`/`warm`/`alive`/`request`/`terminate`) — the unit tests
    drive the full routing/steal/reroute machinery through in-process
    stubs with zero subprocesses and zero compiles.
    """

    def __init__(
        self,
        option=None,
        *,
        n_workers: int = 2,
        max_batch: int = 16,
        ladder=None,
        artifacts: Optional[str] = None,
        manifest: Optional[str] = None,
        strict_manifest: bool = False,
        stats: Optional[FederationStats] = None,
        timer=None,
        steal: bool = True,
        max_reroutes: int = 2,
        heartbeat_dir: Optional[str] = None,
        dead_after_s: float = 5.0,
        warm_timeout_s: float = 1800.0,
        watchdog_s: float = 1800.0,
        telemetry: Optional[str] = None,
        worker_env: Optional[Dict[str, str]] = None,
        pin_cpus: bool = False,
        workers: Optional[Sequence[Any]] = None,
        transport: str = "pipe",
        bind: Optional[str] = None,
        advertise: Optional[str] = None,
        connect: Sequence[str] = (),
        token: Optional[str] = None,
        reconnect: Optional[ReconnectPolicy] = None,
        conn_dead_after_s: float = 5.0,
        hb_interval_s: float = 0.25,
        accept_new: bool = False,
        escalation=None,
    ) -> None:
        from megba_tpu.common import ProblemOption
        from megba_tpu.serving.batcher import _check_option
        from megba_tpu.serving.shape_class import BucketLadder
        from megba_tpu.utils.timing import PhaseTimer

        option = option or ProblemOption()
        _check_option(option)
        if transport not in ("pipe", "tcp"):
            raise ValueError(
                f"transport must be 'pipe' or 'tcp', got {transport!r}")
        if transport == "pipe" and (bind or advertise or connect
                                    or accept_new):
            raise ValueError(
                "bind/advertise/connect/accept_new require "
                "transport='tcp'")
        allow_zero = transport == "tcp" and (connect or accept_new)
        if n_workers < 1 and workers is None and not allow_zero:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if max_reroutes < 0:
            raise ValueError(
                f"max_reroutes must be >= 0, got {max_reroutes}")
        self.option = option
        self.ladder = ladder or BucketLadder()
        self.max_batch = int(max_batch)
        self.steal = bool(steal)
        self.max_reroutes = int(max_reroutes)
        self.watchdog_s = float(watchdog_s)
        self.warm_timeout_s = float(warm_timeout_s)
        self.stats = stats or FederationStats()
        self.timer = PhaseTimer() if timer is None else timer
        self.telemetry = telemetry
        self.transport = transport
        self.escalation = escalation
        self.reconnect = reconnect or ReconnectPolicy()
        self._token = token
        self._conn_dead_after_s = float(conn_dead_after_s)
        self._hb_interval_s = float(hb_interval_s)
        self._accept_new = bool(accept_new)
        self.address: Optional[str] = None  # tcp: the bound host:port
        self._lsock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._dial_threads: List[threading.Thread] = []
        self._env_fp: Dict[str, str] = {}
        self._artifacts = artifacts
        self._manifest = manifest
        self._strict_manifest = bool(strict_manifest)
        self._slices: Dict[str, Any] = {}  # wid -> cpu affinity slice

        self._lock = threading.Condition()
        self._nsubmitted = 0  # megba: guarded-by(_lock)
        self._cold_warned: set = set()  # megba: guarded-by(_lock); warned (bucket, lanes, rung)
        self._hello: Dict[str, Dict[str, Any]] = {}  # megba: guarded-by(_lock); tcp registration rendezvous
        self._closing_accept = False  # megba: guarded-by(_lock)
        self._redial: Dict[str, threading.Event] = {}  # megba: guarded-by(_lock); dial addr -> wake event
        self._wid_addr: Dict[str, str] = {}  # megba: guarded-by(_lock); wid -> dialed addr
        self._pending: Dict[Tuple, List[_Routed]] = {}  # megba: guarded-by(_lock)
        self._npending = 0  # megba: guarded-by(_lock)
        self._closed = False  # megba: guarded-by(_lock)
        self.pinned = False  # did worker CPU pinning actually apply?
        self._own_hb_dir: Optional[str] = None
        # Deadline-carrying items currently pending: the shed scan is
        # O(pending) under the router lock on every serve-thread wakeup,
        # so it only runs while this is nonzero (deadline-free fleets —
        # the common case — pay nothing).
        self._ndeadline = 0  # megba: guarded-by(_lock)
        self._inflight = 0  # megba: guarded-by(_lock)
        self._closing = False  # megba: guarded-by(_lock)
        self._table = RoutingTable()  # megba: guarded-by(_lock)
        self._views: Dict[str, WorkerView] = {}  # megba: guarded-by(_lock)
        # Serializes HeartbeatBoard.observe across serve threads: the
        # board's observation maps are thread-confined state, and every
        # worker's liveness closure may poll concurrently.
        self._hb_lock = threading.Lock()
        self._board = None  # set once in _spawn_workers, pre-thread-start

        if workers is not None:
            self.workers: Dict[str, Any] = {w.worker_id: w for w in workers}
        elif transport == "tcp":
            self.workers = self._spawn_workers_tcp(
                n_workers, warm_timeout_s, worker_env or {}, pin_cpus,
                bind, advertise, connect)
        else:
            self.workers = self._spawn_workers(
                n_workers, artifacts, manifest, strict_manifest,
                heartbeat_dir, dead_after_s, warm_timeout_s,
                worker_env or {}, pin_cpus)
        with self._lock:
            for w in self.workers.values():
                if w.worker_id not in self._views:  # tcp path pre-filled
                    self._views[w.worker_id] = WorkerView(
                        worker_id=w.worker_id, warm=set(w.warm),
                        alive=w.alive)
            self._threads = [
                threading.Thread(target=self._serve, args=(w,),
                                 name=f"megba-fed-{w.worker_id}",
                                 daemon=True)
                for w in self.workers.values()
            ]
        for t in self._threads:
            t.start()

    # -- spawning --------------------------------------------------------
    def _spawn_workers(self, n, artifacts, manifest, strict_manifest,
                       heartbeat_dir, dead_after_s, warm_timeout_s,
                       worker_env, pin_cpus=False) -> Dict[str, WorkerHandle]:
        import jax

        from megba_tpu.robustness.elastic import HeartbeatBoard, RankState

        env = dict(os.environ)
        # Workers must land on the parent's backend/precision: the
        # conftest-style in-process config flips don't propagate to
        # children, the env vars do.
        env.setdefault("JAX_PLATFORMS", jax.default_backend())
        if jax.config.jax_enable_x64:
            env["JAX_ENABLE_X64"] = "1"
        env.update(worker_env)

        slices = self._compute_cpu_slices(n, pin_cpus)

        if heartbeat_dir is None:
            heartbeat_dir = tempfile.mkdtemp(prefix="megba_fed_hb_")
            self._own_hb_dir = heartbeat_dir  # removed on close()
        world = n + 1  # rank 0 = the router (observer only)
        self._board = HeartbeatBoard(
            heartbeat_dir, 0, world, dead_after_s=dead_after_s)
        self._dead_state = RankState.DEAD

        handles: Dict[str, WorkerHandle] = {}
        pending: List[Tuple[WorkerHandle, Any]] = []
        try:
            for i in range(n):
                wid = f"w{i}"
                log = tempfile.NamedTemporaryFile(
                    prefix=f"megba_fed_{wid}_", suffix=".log",
                    delete=False)
                # -c entry rather than -m: runpy would re-execute the
                # module it had already imported via the package
                # __init__, a known double-module footgun.
                proc = subprocess.Popen(
                    [sys.executable, "-c",
                     "import sys; "
                     "from megba_tpu.serving.federation import "
                     "_worker_main; sys.exit(_worker_main())"],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    stderr=log, env=env)
                log.close()
                chan = FrameChannel(proc.stdout, proc.stdin)
                rank = i + 1
                # Heartbeat liveness is armed AFTER the hello: a worker
                # spends its first seconds importing jax before it can
                # beat, and the board's join grace (dead_after_s) is
                # sized for steady-state loss detection, not interpreter
                # startup on a loaded host.  Until then, pipe EOF and
                # process exit (checked every recv slice) cover real
                # startup deaths.
                handle = WorkerHandle(wid, proc, chan, log.name,
                                      liveness=None)
                handle.rank = rank
                chan.send({
                    "op": "config", "worker_id": wid,
                    "option": self.option, "ladder": self.ladder,
                    "artifacts": artifacts, "manifest": manifest,
                    "strict_manifest": strict_manifest,
                    "heartbeat": {"dir": heartbeat_dir, "rank": rank,
                                  "world": world},
                    "cpu_affinity": slices[i],
                    "telemetry": (None if self.telemetry is None
                                  else f"{self.telemetry}.{wid}"),
                })
                pending.append((handle, None))
                handles[wid] = handle
            for handle, _ in pending:
                try:
                    hello = handle.chan.recv(timeout_s=warm_timeout_s,
                                             poll=handle._poll)
                except (FrameError, WorkerLostError, TimeoutError) as exc:
                    raise RuntimeError(
                        f"federation worker {handle.worker_id} failed to "
                        f"come up: {exc}\n--- worker log ---\n"
                        f"{handle.log_tail()}") from exc
                if not hello.get("ok"):
                    raise RuntimeError(
                        f"federation worker {handle.worker_id} refused "
                        f"config: {hello.get('error')}\n--- worker log "
                        f"---\n{handle.log_tail()}")
                handle.warm = set(hello.get("warm", ()))
                handle.liveness = self._liveness_for(handle.rank,
                                                    handle.worker_id)
                self.stats.record_cold_start(
                    handle.worker_id, hello.get("cold_start", {}))
        except Exception:
            for handle in handles.values():
                handle.terminate()
            raise
        return handles

    def _compute_cpu_slices(self, n: int, pin_cpus) -> List[Any]:
        # `pin_cpus`: split the host's cores into contiguous slices, one
        # per worker — each XLA:CPU thread pool then owns its slice
        # instead of all workers thrashing one shared set (the
        # data-parallel deployment shape, one host's cores = one
        # worker's world).  True = cores // n each; an int = exactly
        # that many cores per worker (the bench's equal-resource
        # scaling sweeps pin fed_1 and fed_n to the SAME per-worker
        # slice so the 1→N curve compares like with like).
        slices: List[Optional[List[int]]] = [None] * n
        if pin_cpus and n:
            try:
                cores = sorted(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                cores = []
            per = (int(pin_cpus) if pin_cpus is not True
                   else (len(cores) // n if cores else 0))
            if per >= 1 and len(cores) >= per * n:
                slices = [cores[i * per:(i + 1) * per] for i in range(n)]
            else:
                warnings.warn(
                    f"pin_cpus={pin_cpus!r} needs {per or 1} core(s) x "
                    f"{n} workers but only {len(cores)} are available; "
                    "workers run UNPINNED (a benchmark reading "
                    "equal-resource scaling from this run would be "
                    "comparing asymmetric configurations)", stacklevel=4)
        self.pinned = slices[0] is not None if slices else False
        return slices

    # -- TCP fabric ------------------------------------------------------
    def _spawn_workers_tcp(self, n, warm_timeout_s, worker_env, pin_cpus,
                           bind, advertise, connect) -> Dict[str, Any]:
        """Bind the fleet socket, spawn n workers that dial (back) in,
        start the accept/dial supervision threads, and block until
        every spawned worker has registered and said hello."""
        import jax

        from megba_tpu.serving.artifacts import current_environment

        env = dict(os.environ)
        # Workers must land on the parent's backend/precision: the
        # conftest-style in-process config flips don't propagate to
        # children, the env vars do.
        env.setdefault("JAX_PLATFORMS", jax.default_backend())
        if jax.config.jax_enable_x64:
            env["JAX_ENABLE_X64"] = "1"
        env.update(worker_env)
        if self._token:
            env["MEGBA_FED_TOKEN"] = self._token
        self._env_fp = current_environment()

        slices = self._compute_cpu_slices(n, pin_cpus)
        host, port = parse_address(bind or "127.0.0.1:0")
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((host, port))
        lsock.listen(64)
        lsock.settimeout(0.2)  # accept slices re-check the closing flag
        self._lsock = lsock
        bound = lsock.getsockname()
        self.address = f"{bound[0]}:{bound[1]}"
        # `advertise` is what the spawned workers DIAL — normally the
        # bound address, but a chaos proxy (robustness/netfaults.py) or
        # a NAT sits between in tests and real deployments.
        dial_addr = advertise or self.address

        handles: Dict[str, Any] = {}
        self.workers = handles  # accept thread resolves handles here
        expected: List[str] = []
        for i in range(n):
            wid = f"w{i}"
            self._slices[wid] = slices[i]
            log = tempfile.NamedTemporaryFile(
                prefix=f"megba_fed_{wid}_", suffix=".log", delete=False)
            # -c entry rather than -m: runpy would re-execute the
            # module it had already imported via the package __init__,
            # a known double-module footgun.
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "import sys; from megba_tpu.serving.worker import "
                 "main; sys.exit(main(sys.argv[1:]))",
                 "--connect", dial_addr, "--worker-id", wid,
                 "--hb-interval", str(self._hb_interval_s),
                 "--reconnect-attempts", str(self.reconnect.max_attempts),
                 "--reconnect-base", str(self.reconnect.base_s),
                 "--reconnect-cap", str(self.reconnect.cap_s),
                 "--reconnect-window", str(self.reconnect.window_s),
                 "--reconnect-jitter", str(self.reconnect.jitter),
                 "--reconnect-seed", str(self.reconnect.seed)],
                stdin=subprocess.DEVNULL, stdout=log,
                stderr=subprocess.STDOUT, env=env)
            log.close()
            handle = TcpWorkerHandle(
                wid, _NeverTransport(), proc=proc, log_path=log.name,
                reconnect=self.reconnect,
                conn_dead_after_s=self._conn_dead_after_s,
                on_event=self._transport_event)
            handle.rank = i + 1
            with self._lock:
                handles[wid] = handle
                # Disconnected until the register+hello lands.
                self._views[wid] = WorkerView(
                    worker_id=wid, warm=set(), alive=True,
                    connected=False)
            expected.append(wid)

        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="megba-fed-accept")
        self._accept_thread.start()
        for addr in connect:
            t = threading.Thread(target=self._dial_loop,
                                 args=(str(addr),), daemon=True,
                                 name=f"megba-fed-dial-{addr}")
            t.start()
            self._dial_threads.append(t)

        # Rendezvous: the accept thread fills _hello as registrations
        # complete; fail fast on worker death, typed on timeout.
        fail_msg: Optional[str] = None
        deadline = monotonic_s() + warm_timeout_s
        with self._lock:
            while fail_msg is None:
                missing = [w for w in expected if w not in self._hello]
                bad = [(w, h) for w, h in self._hello.items()
                       if not h.get("ok")]
                if bad:
                    wid, h = bad[0]
                    fail_msg = (
                        f"federation worker {wid} refused config: "
                        f"{h.get('error')}\n--- worker log ---\n"
                        f"{handles[wid].log_tail()}")
                    break
                if not missing:
                    break
                for wid in missing:
                    proc = handles[wid].proc
                    if proc is not None and proc.poll() is not None:
                        fail_msg = (
                            f"federation worker {wid} exited "
                            f"rc={proc.returncode} before registering"
                            f"\n--- worker log ---\n"
                            f"{handles[wid].log_tail()}")
                        break
                if fail_msg is None and monotonic_s() > deadline:
                    fail_msg = (
                        f"federation workers {missing} failed to "
                        f"register within {warm_timeout_s:.0f}s")
                if fail_msg is None:
                    self._lock.wait(timeout=0.2)
        if fail_msg is not None:
            self._teardown_tcp()
            raise RuntimeError(fail_msg)
        return handles

    def _config_for(self, wid: str) -> Dict[str, Any]:
        return {
            "op": "config", "worker_id": wid,
            "option": self.option, "ladder": self.ladder,
            "artifacts": self._artifacts, "manifest": self._manifest,
            "strict_manifest": self._strict_manifest,
            "cpu_affinity": self._slices.get(wid),
            "telemetry": (None if self.telemetry is None
                          else f"{self.telemetry}.{wid}"),
        }

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                if self._closing_accept:
                    return
            try:
                sock, _peer = self._lsock.accept()
            except TimeoutError:
                continue
            except OSError:
                return  # listener closed: router shutting down
            self._register_connection(sock)

    def _register_connection(self, sock) -> Optional[str]:
        """Run the register handshake on one fresh connection; on
        success adopt the transport into the worker's handle (waking
        any reader stuck in its reconnect window).  Returns the worker
        id, or None when the connection was refused/dropped."""
        t = TcpTransport(sock)
        reg: Any = None
        try:
            reg = t.recv(timeout_s=10.0)
            wid = verify_register(reg, self._token, self._env_fp)
        except HandshakeError as exc:
            self._transport_event(
                "handshake_refused",
                worker=str((reg or {}).get("worker_id", "?")
                           if isinstance(reg, dict) else "?"),
                field=exc.field)
            with contextlib.suppress(OSError):
                t.send(refusal_frame(exc))
            t.close()
            return None
        except (FrameError, TimeoutError, OSError):
            t.close()
            return None

        with self._lock:
            handle = self.workers.get(wid)
            view = self._views.get(wid)
            was_alive = bool(view.alive) if view is not None else False
        if handle is None and not self._accept_new:
            exc = HandshakeError("worker_id", wid,
                                 "a registered worker id")
            with contextlib.suppress(OSError):
                t.send(refusal_frame(exc))
            t.close()
            return None

        fresh = handle is None or not was_alive
        needs_config = fresh or bool(reg.get("needs_config", True))
        try:
            if needs_config:
                t.send(ack_frame("config", self._token, wid,
                                 config=self._config_for(wid)))
            else:
                t.send(ack_frame("resume", self._token, wid))
            hello = t.recv(timeout_s=self.warm_timeout_s)
        except (FrameError, TimeoutError, OSError):
            t.close()
            return None
        if not isinstance(hello, dict) or not hello.get("ok"):
            with self._lock:
                self._hello[wid] = (hello if isinstance(hello, dict)
                                    else {"ok": False,
                                          "error": repr(hello)})
                self._lock.notify_all()
            t.close()
            return None

        if fresh and (handle is None or not was_alive):
            # Unknown id (accept_new) or a worker previously declared
            # LOST re-registering after a restart: the old handle's
            # death latch is permanent, so it gets a replacement (and a
            # fresh serve thread below).
            handle = TcpWorkerHandle(
                wid, _NeverTransport(), proc=None,
                reconnect=self.reconnect,
                conn_dead_after_s=self._conn_dead_after_s,
                on_event=self._transport_event)
        warm = set(hello.get("warm", ()))
        handle.warm = set(warm)
        handle.adopt(t, int(reg.get("incarnation", 0)))
        serve_thread: Optional[threading.Thread] = None
        with self._lock:
            self.workers[wid] = handle
            view = self._views.get(wid)
            if view is None or not view.alive:
                self._views[wid] = WorkerView(
                    worker_id=wid, warm=set(warm), alive=True,
                    connected=True)
                if view is not None:
                    self._transport_event("revived", worker=wid)
                serve_thread = threading.Thread(
                    target=self._serve, args=(handle,),
                    name=f"megba-fed-{wid}", daemon=True)
                self._threads.append(serve_thread)
            else:
                view.connected = True
                view.warm = set(warm)
            self._hello[wid] = hello
            self._lock.notify_all()
        if hello.get("cold_start"):
            self.stats.record_cold_start(wid, hello["cold_start"])
        if serve_thread is not None:
            serve_thread.start()
        return wid

    def _dial_loop(self, addr: str) -> None:
        """Router-initiated connections for bind-mode workers: dial,
        hand the socket to the register flow, then sleep until the
        connection drops (conn_lost wakes us) and redial under the
        reconnect policy's deterministic backoff."""
        key = zlib.crc32(addr.encode())
        attempt = 0
        ev = threading.Event()
        while True:
            with self._lock:
                if self._closing_accept:
                    return
                self._redial[addr] = ev
            try:
                sock = socket.create_connection(parse_address(addr),
                                                timeout=5.0)
                sock.settimeout(None)
            except OSError:
                attempt += 1
                if attempt > self.reconnect.max_attempts:
                    self._transport_event("dial_exhausted", worker=addr)
                    return
                time.sleep(self.reconnect.backoff_s(key, attempt))
                continue
            wid = self._register_connection(sock)
            if wid is None:
                attempt += 1
                if attempt > self.reconnect.max_attempts:
                    self._transport_event("dial_exhausted", worker=addr)
                    return
                time.sleep(self.reconnect.backoff_s(key, attempt))
                continue
            attempt = 0
            with self._lock:
                self._wid_addr[wid] = addr
            ev.clear()
            ev.wait()  # conn_lost (or close) wakes the redial

    def _transport_event(self, event: str, worker: str = "?",
                         **fields: Any) -> None:
        """Every transport event lands in all three observability
        planes (metrics counter, zero-duration span, flight record) +
        the phase timer; conn_lost additionally flips the routing view
        to detour mode and wakes the redial thread."""
        self.timer.count_event(f"transport_{event}")
        registry = _obs.metrics_registry()
        if registry is not None:
            registry.counter(
                f"megba_transport_{event}_total",
                f"Federation transport events: {event}").inc(
                    worker=worker)
        recorder = _obs.span_recorder()
        if recorder is not None:
            with recorder.span(f"transport_{event}", worker=worker,
                               **fields):
                pass
        flight = _obs.flight_recorder()
        if flight is not None:
            flight.record(f"transport_{event}", worker=worker, **fields)
        if event == "conn_lost":
            ev = None
            with self._lock:
                view = self._views.get(worker)
                if view is not None:
                    view.connected = False
                ev = self._redial.get(self._wid_addr.get(worker, ""))
                self._lock.notify_all()
            if ev is not None:
                ev.set()

    def _teardown_tcp(self) -> None:
        with self._lock:
            self._closing_accept = True
            events = list(self._redial.values())
            self._lock.notify_all()
        for ev in events:
            ev.set()
        if self._lsock is not None:
            with contextlib.suppress(OSError):
                self._lsock.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        for w in list(self.workers.values()):
            w.terminate()

    def _liveness_for(self, rank: int, wid: str):
        def check() -> Optional[str]:
            if self._board is None:
                return None
            with self._hb_lock:
                states = self._board.observe()
                stale = self._board.staleness(rank)
            if states.get(rank) is self._dead_state:
                return (f"heartbeat dead (rank {rank} silent "
                        f"{stale:.2f}s)")
            return None

        return check

    # -- submission ------------------------------------------------------
    def _key_for(self, problem) -> Tuple:
        from megba_tpu.serving.shape_class import classify

        n_cam, n_pt, n_edge = problem.dims()
        sc = classify(n_cam, n_pt, n_edge, self.option.dtype, self.ladder)
        # The factor name rides the dims element (same 2-tuple shape the
        # routing/steal sites unpack): a routed batch must be one
        # residual family, exactly like the local queue's bucket key.
        dims = (int(problem.cameras.shape[1]),
                int(problem.points.shape[1]), int(problem.obs.shape[1]),
                str(getattr(problem, "factor", "bal")))
        return (sc, dims)

    def submit(self, problem, deadline_s: Optional[float] = None) -> Future:
        """Enqueue one problem; the Future resolves to its FleetResult
        (or raises `WorkerLostError` after `max_reroutes` losses /
        `DeadlineExceeded` when shed / whatever its worker's solve
        raised)."""
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        key = self._key_for(problem)
        now = time.monotonic()
        item = _Routed(
            problem=problem, future=Future(), bucket=str(key[0]), key=key,
            enqueued=now,
            deadline=None if deadline_s is None else now + deadline_s)
        with self._lock:
            if self._closing:
                raise RuntimeError("FleetRouter is closed")
            if not any(v.alive for v in self._views.values()):
                raise WorkerLostError("*", "no surviving workers")
            item.seq = self._nsubmitted
            self._nsubmitted += 1
            self._pending.setdefault(key, []).append(item)
            self._npending += 1
            if item.deadline is not None:
                self._ndeadline += 1
            self._lock.notify_all()
        return item.future

    def submit_many(self, problems: Sequence[Any],
                    deadline_s: Optional[float] = None) -> List[Future]:
        """Enqueue a whole fleet ATOMICALLY (one lock acquisition): no
        worker can pick a partial bucket mid-submission, so batch
        composition — and therefore the (bucket, lanes) programs hit —
        is deterministic for a given fleet.  A replica whose artifacts
        were exported from a `solve_many` pass over the same fleet then
        dispatches it entirely from the store (the zero-trace cold-start
        contract); per-problem `submit` keeps the latency-shaped
        streaming semantics instead."""
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        now = time.monotonic()
        items = []
        for problem in problems:
            key = self._key_for(problem)
            items.append(_Routed(
                problem=problem, future=Future(), bucket=str(key[0]),
                key=key, enqueued=now,
                deadline=None if deadline_s is None else now + deadline_s))
        with self._lock:
            if self._closing:
                raise RuntimeError("FleetRouter is closed")
            if not any(v.alive for v in self._views.values()):
                raise WorkerLostError("*", "no surviving workers")
            for item in items:
                item.seq = self._nsubmitted
                self._nsubmitted += 1
                self._pending.setdefault(item.key, []).append(item)
            self._npending += len(items)
            self._ndeadline += sum(
                1 for item in items if item.deadline is not None)
            self._lock.notify_all()
        return [item.future for item in items]

    def flush(self) -> None:
        """Block until every submitted problem has RESOLVED (result,
        reroute-exhaustion error, shed, or solve error).  Worker losses
        during the wait re-route work and keep the flush honest: it
        returns only when nothing is pending OR in flight."""
        with self._lock:
            while self._npending > 0 or self._inflight > 0:
                self._lock.wait()

    def close(self) -> None:
        """Drain, stop serve threads, shut workers down, emit the
        federation telemetry report.  Idempotent: a second close (e.g.
        context-manager exit after an explicit close) is a no-op — in
        particular it must not append a duplicate federation report
        line to the telemetry sink."""
        with self._lock:
            already = self._closed
            self._closing = True
            self._closed = True
            self._lock.notify_all()
        if already:
            return
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join()
        for w in list(self.workers.values()):
            if w.alive:
                try:
                    w.request({"op": "shutdown"}, timeout_s=30.0)
                    proc = getattr(w, "proc", None)
                    if proc is not None:  # let the clean exit land
                        proc.wait(timeout=10)
                except (WorkerLostError, TimeoutError,
                        subprocess.TimeoutExpired):
                    pass
            w.terminate()
            # Clean-exit worker logs are noise; keep a log only when
            # the worker died abnormally (its tail is the forensics
            # WorkerLostError already quoted).
            rc = getattr(getattr(w, "proc", None), "returncode", None)
            log_path = getattr(w, "log_path", None)
            if log_path and rc == 0:
                try:
                    os.unlink(log_path)
                except OSError:
                    pass
        if self._lsock is not None:
            # TCP fabric: stop accepting/redialing AFTER the shutdown
            # handshakes above (they ride the live connections), then
            # reap the supervision threads.
            with self._lock:
                self._closing_accept = True
                redial_events = list(self._redial.values())
                self._lock.notify_all()
            for ev in redial_events:
                ev.set()
            with contextlib.suppress(OSError):
                self._lsock.close()
            if self._accept_thread is not None:
                self._accept_thread.join(timeout=5.0)
            for t in self._dial_threads:
                t.join(timeout=5.0)
        if self._own_hb_dir is not None:
            import shutil

            shutil.rmtree(self._own_hb_dir, ignore_errors=True)
        if self.telemetry:
            append_federation_report(self.option, self.stats, self.timer,
                                     self.telemetry)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- observability harvesting ----------------------------------------
    def metrics_snapshot(self) -> Optional[Dict[str, Any]]:
        """Fleet-wide merged metrics snapshot, or None when the plane
        is off (`MEGBA_METRICS` unset everywhere).

        Pulls each live worker's registry snapshot over the RPC channel
        (a new lockstep `metrics` op, serialized against the serve
        thread by the handle's request lock) and merges it with the
        router's own — counters/histograms sum, gauges too (depth-style
        gauges are per-process, so the sum reads as fleet totals).  The
        merge iterates sorted names and sorted label keys, so repeated
        pulls on an idle fleet are bitwise identical — the stable seam
        a self-tuning router (ROADMAP item 4) can diff between policy
        adjustments.  Workers that died, or stubs that do not speak the
        op, are skipped rather than failed: harvesting is forensic and
        must never take the fleet down.
        """
        snaps: List[Dict[str, Any]] = []
        registry = _obs.metrics_registry()
        if registry is not None:
            snaps.append(registry.snapshot())
        # Liveness comes from the locked `_views` mirror, not the
        # handles' `alive` flags: a serve thread declaring a loss writes
        # the flag concurrently with this pull, and the router lock is
        # the only ordering the two threads share (guarded-by contract).
        # A disconnected (reconnect-window) worker is skipped too: a
        # metrics pull over a dead link would burn the 60s budget.
        with self._lock:
            live = [w for w in self.workers.values()
                    if self._views[w.worker_id].alive
                    and self._views[w.worker_id].connected]
        for w in live:
            try:
                reply = w.request({"op": "metrics"}, timeout_s=60.0)
            except Exception:
                continue  # lost mid-pull or stub without the op
            if isinstance(reply, dict) and reply.get("ok") \
                    and reply.get("metrics") is not None:
                snaps.append(reply["metrics"])
        if not snaps:
            return None
        from megba_tpu.observability import metrics as _metrics

        return _metrics.merge_snapshots(snaps)

    # -- dispatch --------------------------------------------------------
    @staticmethod
    def _resolve(future: Future, result=None, exc=None) -> None:
        try:
            if future.cancelled():
                return
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(result)
        except InvalidStateError:
            pass

    def _shed_expired_locked(self, now: float) -> List[_Routed]:
        if self._ndeadline <= 0:
            return []
        shed: List[_Routed] = []
        removed = 0
        for key in list(self._pending):
            items = self._pending[key]
            keep: List[_Routed] = []
            for it in items:  # one O(n) partition pass per bucket
                if it.future.cancelled():
                    removed += 1
                    if it.deadline is not None:
                        self._ndeadline -= 1
                elif it.deadline is not None and now >= it.deadline:
                    removed += 1
                    self._ndeadline -= 1
                    shed.append(it)
                else:
                    keep.append(it)
            if len(keep) != len(items):
                if keep:
                    self._pending[key] = keep
                else:
                    del self._pending[key]
        if removed:
            self._npending = sum(len(v) for v in self._pending.values())
        return shed

    @staticmethod
    def _ready(items: List[_Routed], now: float) -> List[_Routed]:
        # Escalated items park behind a `not_before` backoff gate; they
        # stay pending (flush-visible) but undispatchable until due.
        return [it for it in items
                if it.not_before is None or it.not_before <= now]

    def _depths_locked(self, now: float) -> Dict[str, int]:
        depths: Dict[str, int] = {}
        for (sc, _dims), items in self._pending.items():
            n = len(self._ready(items, now))
            if n:
                depths[str(sc)] = depths.get(str(sc), 0) + n
        return depths

    def _pick_locked(self, wid: str, now: float) -> Tuple[
            Optional[List[_Routed]], bool, bool]:
        """(batch, stolen, cold) for worker `wid`, or (None, False,
        False).  `cold` flags a dispatch whose bucket has no artifact
        on the target worker — a compile-on-dispatch latency cliff the
        coverage-gap satellite surfaces."""
        view = self._views[wid]
        if not view.connected:
            # Reconnect window: this worker keeps its assignment but
            # takes no new work; route() detours its buckets meanwhile.
            return None, False, False
        # 1) buckets homed here (or routable/detoured here), oldest first
        candidates = []
        for key, items in self._pending.items():
            ready = self._ready(items, now)
            if not ready:
                continue
            bucket = str(key[0])
            # route() (not the raw assignment) so a disconnected home's
            # buckets detour to warm connected peers for the window.
            homed = self._table.route(bucket, self._views)
            if homed == wid:
                candidates.append((min(it.enqueued for it in ready),
                                   key))
        if candidates:
            # Tiebreak on the bucket string: submit_many stamps a whole
            # fleet with ONE enqueue time, and (ShapeClass, dims) keys
            # do not order.
            _, key = min(candidates, key=lambda c: (c[0], str(c[1][0]),
                                                    c[1][1]))
            cold = str(key[0]) not in view.warm
            return self._take_locked(key, view, now), False, cold
        # 2) steal: deepest warm backlog homed on a live peer
        if self.steal:
            bucket = self._table.steal_candidate(
                wid, self._views, self._depths_locked(now))
            if bucket is not None:
                for key, items in self._pending.items():
                    if str(key[0]) == bucket and self._ready(items, now):
                        # Stealing requires warmth, so never cold.
                        return (self._take_locked(key, view, now),
                                True, False)
        return None, False, False

    def _take_locked(self, key: Tuple, view: WorkerView,
                     now: float) -> List[_Routed]:
        items = self._pending[key]
        take = self._ready(items, now)[:self.max_batch]
        taken = set(map(id, take))
        rest = [it for it in items if id(it) not in taken]
        if rest:
            self._pending[key] = rest
        else:
            del self._pending[key]
        self._npending -= len(take)
        self._ndeadline -= sum(
            1 for it in take if it.deadline is not None)
        view.routed += len(take)
        return take

    def _serve(self, worker) -> None:
        wid = worker.worker_id
        while True:
            batch: Optional[List[_Routed]] = None
            stolen = False
            cold = False
            shed_out: Optional[List[_Routed]] = None
            with self._lock:
                while True:
                    if not self._views[wid].alive:
                        return
                    now = time.monotonic()
                    shed = self._shed_expired_locked(now)
                    if shed:
                        # Shed futures resolve OUTSIDE the lock (a
                        # done-callback re-entering the router must not
                        # self-deadlock on the non-reentrant Condition);
                        # they count as in-flight until resolved so
                        # flush() cannot observe "drained" early — the
                        # FleetQueue shed discipline.
                        self._inflight += len(shed)
                        shed_out = shed
                        break
                    batch, stolen, cold = self._pick_locked(wid, now)
                    if batch is not None:
                        break
                    if (self._closing and self._npending == 0
                            and self._inflight == 0):
                        return
                    # Wake on submit/reroute/close; the timed slice also
                    # re-checks deadlines so sheds stay prompt.
                    self._lock.wait(timeout=0.05)
                if batch is not None:
                    self._inflight += len(batch)
            if shed_out is not None:
                self.stats.record_shed(len(shed_out))
                self.timer.count_event("federation_shed", len(shed_out))
                for it in shed_out:
                    self._resolve(it.future, exc=DeadlineExceeded(
                        f"problem {it.problem.name!r} shed before "
                        "dispatch (deadline expired)"))
                with self._lock:
                    self._inflight -= len(shed_out)
                    self._lock.notify_all()
                continue
            if cold:
                # Coverage-gap satellite: a dispatch whose (bucket,
                # lanes, rung) has no artifact on the target is a
                # compile-on-dispatch — count it every time, warn ONCE
                # per missing key so lane-rung holes surface without
                # spamming a hot path.
                self.stats.record_cold_dispatch(len(batch))
                self.timer.count_event("fed_cold_dispatch", len(batch))
                registry = _obs.metrics_registry()
                if registry is not None:
                    registry.counter(
                        "megba_fed_cold_dispatch_total",
                        "Dispatches with no artifact on the target "
                        "worker (compile-on-dispatch)").inc(
                            len(batch), bucket=batch[0].bucket,
                            worker=wid)
                lanes = len(batch)
                warn_key = (batch[0].bucket, lanes, 0)
                first = False
                with self._lock:
                    if warn_key not in self._cold_warned:
                        self._cold_warned.add(warn_key)
                        first = True
                if first:
                    warnings.warn(ColdDispatchWarning(
                        f"cold dispatch: no artifact for bucket="
                        f"{batch[0].bucket!r} lanes={lanes} rung=0 on "
                        f"worker {wid!r} — this batch compiles on "
                        "dispatch (export artifacts for this key to "
                        "remove the latency cliff)"), stacklevel=2)
            try:
                try:
                    msg: Dict[str, Any] = {
                        "op": "solve",
                        "problems": [it.problem for it in batch]}
                    recorder = _obs.span_recorder()
                    scope = (contextlib.nullcontext()
                             if recorder is None else recorder.span(
                                 "fed_dispatch", bucket=batch[0].bucket,
                                 worker=wid, problems=len(batch),
                                 stolen=stolen))
                    with scope:
                        if recorder is not None:
                            msg["trace"] = recorder.context()
                        reply = worker.request(
                            msg, timeout_s=self.watchdog_s)
                    if recorder is not None and reply.get("spans"):
                        recorder.ingest(reply["spans"])
                except (WorkerLostError, TimeoutError) as exc:
                    if isinstance(exc, TimeoutError):
                        exc = WorkerLostError(
                            wid, "solve exceeded the "
                            f"{self.watchdog_s:.0f}s watchdog budget")
                    self._on_worker_lost(worker, batch, exc)
                    return
                now = time.monotonic()
                if reply.get("ok") and len(reply.get("results", ())) != len(
                        batch):
                    # A short/long ok-reply must fail the batch TYPED —
                    # zip truncation would strand the tail futures
                    # unresolved past flush() forever ("never silently").
                    reply = {"ok": False, "error": (
                        f"worker returned {len(reply.get('results', ()))} "
                        f"results for a {len(batch)}-problem batch")}
                if reply.get("ok"):
                    results = reply["results"]
                    worker.warm = set(reply.get("warm", worker.warm))
                    with self._lock:
                        self._views[wid].warm = set(worker.warm)
                    if reply.get("first_solve") is not None:
                        self.stats.record_first_solve(
                            wid, reply["first_solve"])
                    self.stats.record_batch(wid, len(batch), stolen)
                    registry = _obs.metrics_registry()
                    if registry is not None:
                        registry.counter(
                            "megba_fed_dispatch_total",
                            "Problems dispatched per shape-class bucket "
                            "and worker").inc(
                                len(batch), bucket=batch[0].bucket,
                                worker=wid)
                        if stolen:
                            registry.counter(
                                "megba_fed_steal_total",
                                "Problems moved by work-stealing").inc(
                                    len(batch), bucket=batch[0].bucket,
                                    worker=wid)
                    if stolen:
                        self.timer.count_event("federation_steal")
                        self.timer.count_event(
                            "federation_stolen_problems", len(batch))
                    for it, fr in zip(batch, results):
                        fr.latency_s = now - it.enqueued
                        if (it.deadline is not None
                                and now >= it.deadline):
                            # The FleetQueue contract: a late result is
                            # DELIVERED, flagged, counted — never
                            # silently on time.
                            fr.deadline_missed = True
                            self.stats.record_deadline_miss()
                            self.timer.count_event(
                                "federation_deadline_miss")
                        self._resolve(it.future, result=fr)
                else:
                    err = RuntimeError(
                        f"worker {wid} solve failed: "
                        f"{reply.get('error')}")
                    for it in batch:
                        self._resolve(it.future, exc=err)
            except Exception as exc:  # never die silently mid-batch
                # A router-side bug must fail THIS batch typed and keep
                # the thread serving — a dead serve thread would wedge
                # flush() forever (the FleetQueue dispatcher contract).
                for it in batch:
                    self._resolve(it.future, exc=exc)
            finally:
                with self._lock:
                    self._inflight -= len(batch)
                    self._lock.notify_all()

    def _on_worker_lost(self, worker, batch: List[_Routed],
                        exc: WorkerLostError) -> None:
        """Typed loss handling: count it, reroute the in-flight batch
        (bounded), re-home the dead worker's buckets, keep serving."""
        wid = worker.worker_id
        worker.alive = False
        worker.terminate()
        self.stats.record_worker_lost(wid)
        self.timer.count_event("federation_worker_lost")
        registry = _obs.metrics_registry()
        if registry is not None:
            registry.counter("megba_fed_worker_lost_total",
                             "Federation workers lost").inc(worker=wid)
        flight = _obs.flight_recorder()
        if flight is not None:
            # The router-side crash record for deaths the worker could
            # not announce (SIGKILL, OOM): what died, why, and what it
            # had in flight — then dump the ring, because the fleet may
            # be about to fail outright if this was the last survivor.
            flight.record(
                "worker_lost", worker=wid, reason=exc.reason,
                inflight=len(batch),
                buckets=sorted({it.bucket for it in batch})[:8])
        # Failures are COLLECTED under the lock and resolved outside it:
        # a future's done-callback may re-enter the router, and the
        # Condition's lock is not reentrant.  The failed items count as
        # in-flight until resolved (the caller's finally decrements the
        # batch; _inflight covers it throughout).
        to_fail: List[Tuple[Future, WorkerLostError]] = []
        escalated = 0
        with self._lock:
            self._views[wid].alive = False
            self._table.reassign_lost(wid, self._views)
            survivors = any(v.alive for v in self._views.values())
            rerouted = 0
            for it in batch:
                it.reroutes += 1
                if not survivors:
                    to_fail.append((it.future, WorkerLostError(
                        wid, f"{exc.reason}; no surviving workers")))
                elif it.reroutes > self.max_reroutes:
                    if (self.escalation is not None
                            and self.escalation.retry_dispatch_errors
                            and not it.escalated):
                        # Router-level escalation (ROADMAP 4d): consult
                        # the EscalationPolicy ladder ONCE before
                        # failing typed — one extra retry behind the
                        # policy's deterministic seeded backoff.  The
                        # same-clock rule applies: `not_before` joins
                        # enqueued/deadline on time.monotonic(), never
                        # the handle-side monotonic_s() epoch.
                        it.escalated = True
                        it.not_before = (
                            time.monotonic()
                            + self.escalation.backoff_s(it.seq,
                                                        it.reroutes))
                        self._pending.setdefault(it.key, []).append(it)
                        self._npending += 1
                        if it.deadline is not None:
                            self._ndeadline += 1
                        escalated += 1
                        continue
                    self.stats.record_reroute_failure()
                    to_fail.append((it.future, WorkerLostError(
                        wid, f"{exc.reason}; rerouted {it.reroutes - 1} "
                        f"times (max_reroutes={self.max_reroutes}, "
                        "escalation "
                        + ("consumed" if it.escalated else "off")
                        + ")")))
                else:
                    self._pending.setdefault(it.key, []).append(it)
                    self._npending += 1
                    if it.deadline is not None:
                        self._ndeadline += 1
                    rerouted += 1
            if rerouted:
                self.stats.record_reroute(rerouted)
                self.timer.count_event("federation_reroute", rerouted)
                if registry is not None:
                    for it in batch:
                        if it.reroutes <= self.max_reroutes:
                            registry.counter(
                                "megba_fed_reroute_total",
                                "Problems rerouted off lost workers"
                            ).inc(bucket=it.bucket)
                if flight is not None:
                    flight.record("reroute", worker=wid, n=rerouted)
            if escalated:
                self.stats.record_escalation(escalated)
                self.timer.count_event("fed_escalation", escalated)
                if registry is not None:
                    registry.counter(
                        "megba_fed_escalation_total",
                        "Problems retried via the escalation ladder "
                        "after reroute exhaustion").inc(
                            escalated, worker=wid)
                if flight is not None:
                    flight.record("escalation", worker=wid, n=escalated)
            if not survivors:
                # Nothing can serve the queue: fail it all, typed.
                for key in list(self._pending):
                    for it in self._pending.pop(key):
                        to_fail.append((it.future, WorkerLostError(
                            wid, f"{exc.reason}; no surviving workers")))
                self._npending = 0
                self._ndeadline = 0
            # in-flight accounting: the serve loop's finally owns the
            # decrement (this handler runs inside its try)
            self._lock.notify_all()
        for future, err in to_fail:
            self._resolve(future, exc=err)
        with self._lock:
            self._lock.notify_all()  # flush waiters re-check after fails
        if flight is not None:
            from megba_tpu.observability import flight as _flight

            _flight.dump_default(f"worker_lost:{wid}")


def append_federation_report(option, stats: FederationStats, timer,
                             path: str) -> None:
    """One router-lifetime SolveReport line carrying the federation
    block — what `summarize --aggregate`'s federation view renders."""
    from megba_tpu.observability.report import (
        SolveReport,
        append_report,
        backend_topology,
        config_to_dict,
    )

    rep = SolveReport(
        problem={"kind": "federation_router"},
        config=config_to_dict(option),
        backend=backend_topology(),
        phases=timer.as_dict(),
        result={},
        federation=stats.as_dict(),
        created_unix=wall_unix(),
    )
    append_report(rep, path)


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        sys.exit(_worker_main())
    print(__doc__)
    sys.exit(2)
