"""Fleet resilience: deadlines, escalation ladders, circuit breakers.

PR 5 made ONE solve self-healing (on-device guards, `SolveStatus`
termination semantics); PR 6 built the fleet service around batched
bucket programs.  This module is the policy layer that makes the
SERVICE survive what the guards cannot: solves that end unusable
(`STALLED` / `FATAL_NONFINITE` / non-finite cost), dispatches that
throw, problems nobody is still waiting for, and buckets whose program
keeps failing.  `serving/queue.py` is the enforcement point — this file
holds the pure, host-side state machines so they are unit-testable
without a dispatcher thread.

Four cooperating mechanisms:

- **Deadlines** (`FleetQueue.submit(..., deadline_s=...)`): an expired
  problem is SHED before dispatch — its Future raises
  `DeadlineExceeded` and no device time is burned on an answer nobody
  wants; a result that completes late is still delivered but flagged
  `FleetResult.deadline_missed`, never silently.
- **Retry-with-escalation** (`EscalationPolicy`): a bounded ladder of
  per-rung option transforms.  Rung 0 is the solve as submitted;
  rung 1 arms the PR 5 guards and inflates initial damping (an OPERAND
  — `initial_region` rides the compiled program, no recompile);
  rung 2 drops to conservative solver settings (block-Jacobi
  preconditioning, no forcing/warm-start, a bigger PCG budget);
  rung 3 re-solves in f64.  Escalated re-solves re-enter the normal
  bucket path, so they reuse the warmed `CompilePool` programs for
  their (bucket, rung) — a rung that only changes operands costs
  nothing, a rung that changes the option compiles AT MOST once per
  bucket (the retrace sentinel certifies this in CI).  Backoff between
  attempts is deterministic-jittered: seeded by (policy seed, problem
  sequence number, attempt), so a replayed submission order replays
  the identical schedule.
- **Admission control** (`RejectPolicy`): a `max_pending` bound on the
  queue.  `RAISE` fails fast with `QueueRejected`; `BLOCK` waits up to
  `block_timeout_s` for capacity, then rejects.  Load-shed and
  queue-depth counters land in `FleetStats`.
- **Per-bucket circuit breaker** (`CircuitBreaker`): `trip_after`
  consecutive DISPATCH failures (exceptions, not solve statuses — a
  lane that stalls is that lane's problem; a program that throws is
  the bucket's) open the breaker: submits to the bucket fail fast with
  `BucketTripped` carrying the tripped reason.  After `cooldown_s` the
  breaker goes half-open and admits ONE probe batch; success closes
  it, failure re-opens.  Every transition is a `FleetStats` counter
  and a PhaseTimer `breaker_*` event in telemetry.

Detection deliberately reuses PR 5's `SolveStatus` rather than new
device-side signals: the statuses are already computed inside the
jitted program at zero marginal cost, already per-lane under vmap, and
already proven by the fault-injection harness — the fleet layer only
has to READ them (see ARCHITECTURE.md "Serving resilience").
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, FrozenSet, Optional

import numpy as np

from megba_tpu.common import (
    PrecondKind,
    PreconditionerKind,
    ProblemOption,
    RETRYABLE_STATUSES,
    status_retryable,
)


class DeadlineExceeded(Exception):
    """The problem's deadline expired before dispatch; it was shed."""


class QueueRejected(Exception):
    """Admission control refused the submit (queue at max_pending)."""


class BucketTripped(Exception):
    """The bucket's circuit breaker is open; submit failed fast.

    `reason` carries the failure that tripped it (the breaker's memory
    of WHY, so callers see the root cause, not just 'tripped')."""

    def __init__(self, bucket: str, reason: str) -> None:
        super().__init__(f"bucket {bucket} is tripped: {reason}")
        self.bucket = bucket
        self.reason = reason


class RejectPolicy(enum.Enum):
    """What `FleetQueue.submit` does when the queue is at max_pending.

    RAISE = fail fast (`QueueRejected`) — the caller owns backpressure.
    BLOCK = wait up to `block_timeout_s` for capacity, then reject —
    backpressure propagates to the submitting thread.
    """

    RAISE = 0
    BLOCK = 1


@dataclasses.dataclass(frozen=True)
class EscalationPolicy:
    """The bounded retry ladder for unusable solve outcomes.

    `max_rungs` bounds the ladder (rungs 0..max_rungs-1; 4 covers the
    full transform set below, smaller values truncate it).  A solve is
    escalated when `should_retry` fires on its outcome — or, with
    `retry_dispatch_errors`, when its dispatch raised — and a rung
    remains.  Backoff before attempt k is
    `backoff_base_s * backoff_factor**(k-1)`, jittered by a
    DETERMINISTIC factor in [1-jitter, 1+jitter] seeded from
    (`seed`, problem sequence, attempt): retries de-synchronise (no
    thundering re-dispatch herd) yet replay exactly under a fixed seed.

    Rung transforms (cumulative — each rung keeps the previous rungs'
    hardening):

    | rung | change | cost |
    |---|---|---|
    | 0 | as submitted | — |
    | 1 | `RobustOption(guards=True)` + initial trust region divided by `damping_deflation` | one compile per bucket (option changed), damping is an operand |
    | 2 | conservative solver: `precond=JACOBI`, `preconditioner=HPP`, no forcing / warm-start / mixed precision, fused kernels off, 2x PCG budget | one compile per bucket |
    | 3 | f64 re-solve (dtype=float64) | new shape class (dtype is part of it) — its own bucket program |
    """

    max_rungs: int = 4
    retry_statuses: FrozenSet = RETRYABLE_STATUSES
    retry_dispatch_errors: bool = True
    backoff_base_s: float = 0.02
    backoff_factor: float = 2.0
    backoff_jitter: float = 0.5
    damping_deflation: float = 16.0
    seed: int = 0

    def __post_init__(self) -> None:
        if self.max_rungs < 1:
            raise ValueError(f"max_rungs must be >= 1, got {self.max_rungs}")
        if self.backoff_base_s < 0:
            raise ValueError("backoff_base_s must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError("backoff_factor must be >= 1")
        if not 0.0 <= self.backoff_jitter < 1.0:
            raise ValueError(
                f"backoff_jitter must be in [0, 1), got "
                f"{self.backoff_jitter}")
        if not self.damping_deflation >= 1.0:
            raise ValueError("damping_deflation must be >= 1")

    # -- outcome classification -----------------------------------------
    def should_retry(self, status, final_cost=None) -> bool:
        """Is this solve outcome worth a rung up the ladder?  Delegates
        to `common.status_retryable` with this policy's status set, so
        the one predicate cannot drift between the library helper and
        the ladder."""
        return status_retryable(status, final_cost,
                                statuses=self.retry_statuses)

    # -- per-rung option transforms -------------------------------------
    def option_for_rung(self, base: ProblemOption,
                        rung: int) -> ProblemOption:
        """The ProblemOption attempt `rung` solves under (cumulative)."""
        if not 0 <= rung < self.max_rungs:
            raise ValueError(
                f"rung must be in [0, {self.max_rungs}), got {rung}")
        option = base
        if rung >= 1:
            option = dataclasses.replace(
                option, robust_option=dataclasses.replace(
                    option.robust_option, guards=True))
        if rung >= 2:
            # Conservative rung: every precision shortcut off — the
            # mixed rung AND the bf16 MXU pipeline (its collective
            # compression rides along; bf16_collectives without bf16 is
            # refused by validate_options) — and the fused edge-pipeline
            # kernels (back to the battle-tested XLA/segtiles lowering).
            option = dataclasses.replace(
                option, mixed_precision_pcg=False,
                solver_option=dataclasses.replace(
                    option.solver_option,
                    precond=PrecondKind.JACOBI,
                    preconditioner=PreconditionerKind.HPP,
                    forcing=False, warm_start=False,
                    bf16=False, bf16_collectives=False,
                    fused_kernels=False,
                    max_iter=2 * option.solver_option.max_iter))
        if rung >= 3:
            option = dataclasses.replace(option, dtype=np.float64)
        return option

    def initial_region_for_rung(self, base: ProblemOption,
                                rung: int) -> Optional[float]:
        """Rung >= 1 inflates initial damping (trust region divided by
        `damping_deflation`) — purely an operand, never a recompile.
        None = the option's own default (rung 0)."""
        if rung < 1:
            return None
        return float(base.algo_option.initial_region
                     / self.damping_deflation)

    # -- backoff ---------------------------------------------------------
    def backoff_s(self, seq: int, attempt: int) -> float:
        """Deterministic-jittered backoff before attempt `attempt`
        (>= 1) of problem `seq` (its submission sequence number)."""
        if attempt < 1:
            raise ValueError(f"attempt must be >= 1, got {attempt}")
        base = self.backoff_base_s * self.backoff_factor ** (attempt - 1)
        if self.backoff_jitter == 0.0 or base == 0.0:
            return base
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(seq), int(attempt)]))
        factor = 1.0 + self.backoff_jitter * (2.0 * float(rng.random()) - 1.0)
        return base * factor


@dataclasses.dataclass(frozen=True)
class BreakerPolicy:
    """Circuit-breaker tuning: trip threshold + half-open cooldown."""

    trip_after: int = 3
    cooldown_s: float = 1.0

    def __post_init__(self) -> None:
        if self.trip_after < 1:
            raise ValueError(f"trip_after must be >= 1, got "
                             f"{self.trip_after}")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")


class BreakerState(enum.Enum):
    CLOSED = 0  # serving normally
    OPEN = 1  # tripped: submits fail fast until cooldown elapses
    HALF_OPEN = 2  # one probe batch in flight; its outcome decides


@dataclasses.dataclass
class _BucketBreaker:
    state: BreakerState = BreakerState.CLOSED
    consecutive_failures: int = 0
    opened_at: float = 0.0
    reason: str = ""


class CircuitBreaker:
    """Per-bucket breaker registry (bucket key -> state machine).

    NOT thread-safe by itself: the queue calls every method under its
    own lock (one shared mutex keeps breaker state, pending buckets and
    stats counters mutually consistent — breaker state is deliberately
    keyed SEPARATELY from `FleetQueue._pending`, which prunes empty
    buckets, while trip history must survive an empty queue).

    Callbacks: `on_event(event, bucket, reason)` fires on every
    transition (`trip`, `probe`, `recover`, `fast_fail`) so the queue
    can mirror transitions into FleetStats/PhaseTimer telemetry without
    this module importing either.
    """

    def __init__(self, policy: Optional[BreakerPolicy] = None,
                 on_event=None) -> None:
        self.policy = policy or BreakerPolicy()
        self._on_event = on_event
        self._buckets: Dict[str, _BucketBreaker] = {}

    def _emit(self, event: str, bucket: str, reason: str = "") -> None:
        if self._on_event is not None:
            self._on_event(event, bucket, reason)

    def _get(self, bucket: str) -> _BucketBreaker:
        b = self._buckets.get(bucket)
        if b is None:
            b = self._buckets[bucket] = _BucketBreaker()
        return b

    def state(self, bucket: str) -> BreakerState:
        return self._get(bucket).state

    # -- submit side -----------------------------------------------------
    def check_submit(self, bucket: str, now: Optional[float] = None) -> None:
        """Raise `BucketTripped` when the bucket is open and still
        cooling down (the fail-fast contract); a bucket past cooldown
        accepts submits — they will ride the half-open probe."""
        b = self._get(bucket)
        if b.state is not BreakerState.OPEN:
            return
        now = time.monotonic() if now is None else now
        if now - b.opened_at < self.policy.cooldown_s:
            self._emit("fast_fail", bucket, b.reason)
            raise BucketTripped(bucket, b.reason)

    # -- dispatch side ---------------------------------------------------
    def admit(self, bucket: str, now: Optional[float] = None) -> bool:
        """May the dispatcher send a batch to this bucket now?

        CLOSED: yes.  OPEN within cooldown: no.  OPEN past cooldown:
        yes — the breaker moves to HALF_OPEN and this batch is the
        probe.  HALF_OPEN: no (one probe at a time)."""
        b = self._get(bucket)
        if b.state is BreakerState.CLOSED:
            return True
        if b.state is BreakerState.HALF_OPEN:
            return False
        now = time.monotonic() if now is None else now
        if now - b.opened_at >= self.policy.cooldown_s:
            b.state = BreakerState.HALF_OPEN
            self._emit("probe", bucket, b.reason)
            return True
        return False

    def reopen_at(self, bucket: str) -> Optional[float]:
        """Monotonic time the bucket becomes probe-able (None when it
        isn't OPEN) — the dispatcher's sleep bound."""
        b = self._get(bucket)
        if b.state is not BreakerState.OPEN:
            return None
        return b.opened_at + self.policy.cooldown_s

    def record_success(self, bucket: str) -> None:
        b = self._get(bucket)
        if b.state is BreakerState.HALF_OPEN:
            self._emit("recover", bucket, b.reason)
        b.state = BreakerState.CLOSED
        b.consecutive_failures = 0
        b.reason = ""

    def record_failure(self, bucket: str, reason: str,
                       now: Optional[float] = None) -> None:
        b = self._get(bucket)
        b.consecutive_failures += 1
        b.reason = reason
        # A failed half-open probe re-opens immediately; a closed bucket
        # trips once the consecutive-failure streak reaches the policy
        # threshold.
        if (b.state is BreakerState.HALF_OPEN
                or b.consecutive_failures >= self.policy.trip_after):
            b.state = BreakerState.OPEN
            b.opened_at = time.monotonic() if now is None else now
            self._emit("trip", bucket, reason)
