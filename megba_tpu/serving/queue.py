"""Async dispatch queue: submit problems, get Future-style handles.

The latency-shaping half of the serving layer.  `FleetQueue.submit`
enqueues one problem and returns a `concurrent.futures.Future`
resolving to its `FleetResult`; a dispatcher thread groups pending
problems by shape class and flushes a bucket when either

- it holds `max_batch` problems (occupancy-driven flush), or
- its OLDEST problem has waited `max_wait_s` (deadline-driven flush —
  the knob trading per-problem latency against batch occupancy).

All JAX work happens on the dispatcher thread (one dispatch at a time,
matching the single-device serving contract); submitters only touch
host queues.  A failed batch propagates its exception to every future
in that batch and the queue keeps serving — one poisoned problem never
wedges the service.

On top of that, the fleet-resilience layer (serving/resilience.py)
turns the queue from a batcher into something deployable:

- **deadlines**: `submit(problem, deadline_s=...)` — an expired
  problem is SHED before dispatch (its Future raises
  `DeadlineExceeded`); one that completes late is delivered flagged
  `FleetResult.deadline_missed`.
- **retry-with-escalation**: pass `escalation=EscalationPolicy(...)`
  and solves ending `STALLED`/`FATAL_NONFINITE` (or with a non-finite
  cost, or whose dispatch raised) are re-enqueued one rung up the
  ladder with deterministic-jittered backoff, up to
  `EscalationPolicy.max_rungs` attempts; the final `FleetResult`
  carries `attempts`/`rung`/per-attempt `history`.
- **admission control**: `max_pending` bounds the queue;
  `RejectPolicy.RAISE` fails fast with `QueueRejected`,
  `RejectPolicy.BLOCK` waits up to `block_timeout_s` for capacity.
- **circuit breaker**: consecutive dispatch failures trip a bucket
  (submits fail fast with `BucketTripped`); after
  `BreakerPolicy.cooldown_s` one half-open probe batch decides
  recovery.

`close()` drains everything still pending, then joins the thread;
`FleetQueue` is a context manager (`with FleetQueue(...) as q:`),
futures from a drained close still resolve, and `close()` is
idempotent.  `flush()` dispatches everything NOW (batch-wait
deadlines, backoff and breaker cooldowns ignored; per-problem
deadlines still shed) and blocks — on a real drained notification,
not a poll — until every taken problem has resolved.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from megba_tpu import observability as _obs
from megba_tpu.common import ProblemOption
from megba_tpu.serving.batcher import (
    FleetProblem,
    _check_option,
    _solve_bucket,
    _strip_telemetry,
)
from megba_tpu.serving.compile_pool import CompilePool
from megba_tpu.serving.resilience import (
    BreakerPolicy,
    CircuitBreaker,
    DeadlineExceeded,
    EscalationPolicy,
    QueueRejected,
    RejectPolicy,
)
from megba_tpu.serving.shape_class import BucketLadder, ShapeClass, classify
from megba_tpu.serving.stats import FleetStats
from megba_tpu.utils.backend import warn_if_x64_unavailable
from megba_tpu.utils.timing import PhaseTimer


@dataclasses.dataclass(eq=False)  # identity semantics: items hold arrays
class _Pending:
    problem: FleetProblem
    future: Future
    enqueued: float  # monotonic seconds
    seq: int  # submission sequence number (deterministic backoff seed)
    deadline: Optional[float] = None  # absolute monotonic; None = no deadline
    rung: int = 0  # current escalation rung
    attempts: int = 0  # dispatch attempts so far
    not_before: float = 0.0  # backoff release time (monotonic)
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)


class FleetQueue:
    """Deadline-batched async front door for `solve_many`-style solves.

    Knobs: `max_batch` caps a bucket's flush size (also the occupancy
    trigger); `max_wait_s` bounds how long a lone problem waits for
    batch-mates.  `ladder`/`pool`/`stats` default to fresh instances —
    a production service passes a warmed pool so the dispatch path
    never compiles.

    Resilience knobs (serving/resilience.py): `escalation` arms the
    retry ladder (None = unusable outcomes and dispatch errors go
    straight to the caller, the pre-resilience contract); `breaker`
    tunes the per-bucket circuit breaker; `max_pending` +
    `reject_policy` + `block_timeout_s` bound admission; `chaos`
    (robustness.faults.DispatchChaos) injects deterministic dispatch
    failures / delays for tests and the CI chaos smoke.
    """

    def __init__(
        self,
        option: Optional[ProblemOption] = None,
        *,
        max_batch: int = 16,
        max_wait_s: float = 0.02,
        ladder: Optional[BucketLadder] = None,
        pool: Optional[CompilePool] = None,
        stats: Optional[FleetStats] = None,
        timer: Optional[PhaseTimer] = None,
        escalation: Optional[EscalationPolicy] = None,
        breaker: Optional[BreakerPolicy] = None,
        max_pending: Optional[int] = None,
        reject_policy: RejectPolicy = RejectPolicy.RAISE,
        block_timeout_s: float = 5.0,
        chaos=None,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if max_pending is not None and max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1 or None, got {max_pending}")
        if block_timeout_s < 0:
            raise ValueError(
                f"block_timeout_s must be >= 0, got {block_timeout_s}")
        option = option or ProblemOption()
        _check_option(option)
        self._option, self._telemetry, self._report_option = (
            _strip_telemetry(option))
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.ladder = ladder or BucketLadder()
        self.stats = stats or FleetStats()
        self.pool = pool or CompilePool(stats=self.stats)
        self.timer = PhaseTimer() if timer is None else timer
        self.escalation = escalation
        self.max_pending = max_pending
        self.reject_policy = reject_policy
        self.block_timeout_s = block_timeout_s
        self._chaos = chaos
        self.breaker = CircuitBreaker(
            breaker or BreakerPolicy(), on_event=self._breaker_event)
        if escalation is not None:
            # Fail configuration errors at construction, not mid-retry:
            # every rung's option transform must validate — and warn NOW
            # if a rung's dtype cannot actually be computed (the f64
            # re-solve rung is a silent f32 no-op without jax x64; the
            # synchronous path warns via solve_many, this is the queue's
            # equivalent).
            for rung in range(escalation.max_rungs):
                rung_opt = escalation.option_for_rung(self._option, rung)
                _check_option(rung_opt)
                warn_if_x64_unavailable(np.dtype(rung_opt.dtype))

        self._lock = threading.Condition()
        # (shape class, feature dims, factor, escalation rung) ->
        # pending items.  Rung is part of the key because each rung
        # solves under its own option (its own compiled program);
        # factor is part of the key because each residual family is its
        # own engine — a bucket is one family by construction.  Empty
        # buckets are PRUNED when their last item is taken — breaker
        # state lives in `self.breaker`, keyed separately, so trip
        # history survives an empty queue.
        self._pending: Dict[
            Tuple[ShapeClass, Tuple[int, int, int], str, int],
            List[_Pending]] = {}  # megba: guarded-by(_lock)
        self._inflight = 0  # megba: guarded-by(_lock); taken, unresolved
        self._npending = 0  # megba: guarded-by(_lock); O(1) pending gauge
        self._seq = 0  # megba: guarded-by(_lock)
        self._closing = False  # megba: guarded-by(_lock)
        # Active flush() count, not a bool: concurrent flushes must not
        # clobber each other's drain mode (the first to finish would
        # otherwise strand the second behind backoff/breaker waits).
        self._force = 0  # megba: guarded-by(_lock)
        self._thread = threading.Thread(
            target=self._run, name="megba-fleet-dispatch", daemon=True)
        self._thread.start()

    # -- resilience plumbing ---------------------------------------------
    def _breaker_event(self, event: str, bucket: str, reason: str) -> None:
        self.stats.record_breaker(event)
        self.timer.count_event(f"breaker_{event}")
        flight = _obs.flight_recorder()
        if flight is not None:
            flight.record("breaker", event=event, bucket=bucket,
                          reason=reason)

    def _rung_option(self, rung: int) -> ProblemOption:
        if rung == 0 or self.escalation is None:
            return self._option
        return self.escalation.option_for_rung(self._option, rung)

    def _rung_report_option(self, rung: int) -> ProblemOption:
        """The config a rung's telemetry reports claim: the RUNG's
        transforms applied to the caller's (telemetry-carrying) option —
        a rung-2 report must say guards=True/JACOBI, not the rung-0
        config the problem was submitted under."""
        if rung == 0 or self.escalation is None:
            return self._report_option
        return self.escalation.option_for_rung(self._report_option, rung)

    def _triage_problem(self, problem: FleetProblem, policy,
                        spec) -> FleetProblem:
        """Run pre-flight triage on one submission (host-side, on the
        submitter's thread).  Raises `ProblemRejected` under REJECT;
        returns the (possibly repaired) problem otherwise, with the
        HealthReport dict attached so it rides FleetResult/telemetry."""
        from megba_tpu.robustness.triage import TriageAction, triage_problem

        # The problem's own mask/fixed operands ride into the checks so
        # triage sees the graph the solver will (see check_problem);
        # the (already dim-validated) factor spec dispatches the
        # geometric hooks — a non-projective family skips
        # cheirality/parallax entirely.
        outcome = triage_problem(problem.cameras, problem.points,
                                 problem.obs, problem.cam_idx,
                                 problem.pt_idx, policy,
                                 edge_mask=problem.edge_mask,
                                 cam_fixed=problem.cam_fixed,
                                 pt_fixed=problem.pt_fixed,
                                 factor=spec)
        health = outcome.report.to_dict()
        rep = outcome.repair
        if rep is None or rep.is_noop:
            if outcome.report.degenerate:
                # WARN on a degenerate problem: flagged, not touched.
                self.stats.record_triage("warned")
                self.timer.count_event("triage_warn")
            return dataclasses.replace(problem, health=health)
        assert outcome.action == TriageAction.REPAIR
        self.stats.record_triage("repaired", rep.counters())
        self.timer.count_event("triage_repair")
        for name, n in rep.counters().items():
            if n:
                self.timer.count_event(f"triage_{name}", n)
        cameras, points, obs = rep.merged_arrays(
            problem.cameras, problem.points, problem.obs)
        em, cf, pf = rep.merge_operands(
            problem.edge_mask, problem.cam_fixed, problem.pt_fixed)
        return dataclasses.replace(
            problem, cameras=cameras, points=points, obs=obs,
            edge_mask=em, cam_fixed=cf, pt_fixed=pf, health=health)

    def _key_for(
        self, problem: FleetProblem, rung: int,
    ) -> Tuple[ShapeClass, Tuple[int, int, int], str, int]:
        opt = self._rung_option(rung)
        n_cam, n_pt, n_edge = problem.dims()
        sc = classify(n_cam, n_pt, n_edge, opt.dtype, self.ladder)
        dims = (int(problem.cameras.shape[1]),
                int(problem.points.shape[1]), int(problem.obs.shape[1]))
        return (sc, dims, problem.factor, rung)

    def _depth_locked(self) -> int:
        """Pending problems that still want service: client-cancelled
        items don't hold admission capacity (the dispatcher drops them
        at its next pass)."""
        return sum(1 for items in self._pending.values()
                   for it in items if not it.future.cancelled())

    # -- submission ------------------------------------------------------
    def submit(self, problem: FleetProblem,
               deadline_s: Optional[float] = None,
               triage=None) -> "Future":
        """Enqueue one problem; the Future resolves to its FleetResult
        (or raises what its batch raised / `DeadlineExceeded` when it
        was shed / `QueueRejected` / `BucketTripped` /
        `ProblemRejected` when triage refused it).

        `deadline_s` is relative to NOW: once it expires the problem is
        shed before dispatch; a result completing after it is delivered
        flagged `deadline_missed`.

        `triage` (robustness.triage.TriagePolicy) arms CONTENT
        admission control next to `max_pending`'s capacity admission:
        the problem is health-checked on the submitter's thread (host
        NumPy, milliseconds) BEFORE it touches the queue.  Under
        REJECT a degenerate problem's Future resolves immediately with
        `ProblemRejected` (full HealthReport attached) — it never
        holds queue capacity, never enters the escalation ladder, and
        costs ZERO device time.  Under REPAIR the repaired problem
        (masks + sanitised arrays as pure operands) is enqueued in its
        place; under WARN the report is attached and the problem rides
        unchanged.  Without `triage`, the shared ingestion gate
        (io/bal.validate_problem) still refuses non-finite/duplicate
        poison by raising at this boundary.
        """
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        from megba_tpu.serving.batcher import (
            _problem_spec,
            _validate_problem,
        )

        # Factor resolution + block-dim check FIRST: an unknown name or
        # wrong-width array must fail typed here, before the triage
        # hooks (which index the spec's columns) could trip on it.
        spec = _problem_spec(problem)
        if triage is not None:
            from megba_tpu.robustness.triage import ProblemRejected

            try:
                problem = self._triage_problem(problem, triage, spec)
            except ProblemRejected as exc:
                # Content rejection resolves the Future FAST: no queue
                # capacity held, no escalation ladder, zero dispatch.
                self.stats.record_triage("rejected")
                self.timer.count_event("triage_reject")
                f: Future = Future()
                f.set_exception(exc)
                return f
        # The shared ingestion gate still runs after triage when the
        # policy's structural pass (which subsumes the duplicate check)
        # was disabled — _validate_problem skips itself otherwise.  The
        # option rides along for the robust-eligibility refusal (a
        # robust kernel on a robust_ok=False family fails typed here,
        # exactly like flat_solve's boundary).
        _validate_problem(problem, option=self._option)
        key = self._key_for(problem, rung=0)
        now = time.monotonic()
        item = _Pending(
            problem=problem, future=Future(), enqueued=now, seq=-1,
            deadline=None if deadline_s is None else now + deadline_s)
        with self._lock:
            if self._closing:
                raise RuntimeError("FleetQueue is closed")
            # Breaker fast-fail: a tripped bucket refuses work instantly
            # instead of queueing problems that will sit out a cooldown.
            self.breaker.check_submit(str(key[0]), now)
            # Admission decisions use the authoritative scan — a
            # lazily-discovered client cancel() must free capacity, and
            # max_pending bounds the scan on the services that care.
            # The peak gauge rides the O(1) _npending counter instead,
            # so an UNBOUNDED queue never pays per-submit scans.
            if (self.max_pending is not None
                    and self._depth_locked() >= self.max_pending):
                if self.reject_policy is RejectPolicy.RAISE:
                    self.stats.record_reject()
                    raise QueueRejected(
                        f"queue at max_pending={self.max_pending}")
                wait_until = time.monotonic() + self.block_timeout_s
                while (self._depth_locked() >= self.max_pending
                       and not self._closing):
                    remaining = wait_until - time.monotonic()
                    if remaining <= 0:
                        self.stats.record_reject()
                        raise QueueRejected(
                            f"queue at max_pending={self.max_pending} "
                            f"for {self.block_timeout_s}s")
                    self._lock.wait(timeout=remaining)
                if self._closing:
                    raise RuntimeError("FleetQueue is closed")
            item.seq = self._seq
            self._seq += 1
            self._pending.setdefault(key, []).append(item)
            self._npending += 1
            self.stats.record_depth(self._npending)
            self._lock.notify_all()
        return item.future

    def flush(self) -> None:
        """Dispatch everything pending NOW (batch-wait deadlines,
        backoff and breaker cooldowns ignored — per-problem deadlines
        still shed: an expired problem resolves `DeadlineExceeded`, a
        force-dispatch would not make its answer wanted again) and
        block until every taken problem has RESOLVED — drained
        notification, not a poll.  `_force` is reset in a `finally` so
        an exception mid-flush (timeout signal, KeyboardInterrupt) can
        never wedge later deadline flushes."""
        with self._lock:
            self._force += 1
            self._lock.notify_all()
            try:
                while any(self._pending.values()) or self._inflight > 0:
                    self._lock.wait()
            finally:
                self._force -= 1
                self._lock.notify_all()

    def close(self) -> None:
        """Drain pending work, then stop the dispatcher thread.
        Idempotent: repeat calls re-join the (finished) thread."""
        with self._lock:
            self._closing = True
            self._lock.notify_all()
        self._thread.join()

    def __enter__(self) -> "FleetQueue":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- dispatcher ------------------------------------------------------
    @staticmethod
    def _resolve(future: Future, result=None, exc=None) -> None:
        """Resolve a future, tolerating a client-side cancel() racing
        the check (set_* on a just-cancelled future raises
        InvalidStateError, which must never kill the dispatcher)."""
        try:
            if future.cancelled():
                return
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(result)
        except InvalidStateError:  # the client's cancel won the race
            pass

    def _shed_expired_locked(self, now: float) -> List[_Pending]:
        """Remove deadline-expired items from every bucket (their
        futures are failed OUTSIDE the lock by the caller).  Items
        whose future was cancelled client-side are dropped too — a
        cancel before dispatch costs zero device time."""
        shed: List[_Pending] = []
        kept = 0
        for key in list(self._pending):
            items = self._pending[key]
            keep = []
            for it in items:
                if it.future.cancelled():
                    continue
                if it.deadline is not None and now >= it.deadline:
                    shed.append(it)
                else:
                    keep.append(it)
            if len(keep) == len(items):
                # Nothing removed: keep the existing list (no per-wakeup
                # reallocation churn on a deep deadline-free queue; the
                # wakeup is O(pending items) regardless — _ripe_buckets
                # walks them too — and admission control is the tool
                # that bounds it).
                kept += len(items)
            elif keep:
                self._pending[key] = keep
                kept += len(keep)
            else:
                del self._pending[key]
        self._npending = kept
        return shed

    def _ripe_buckets(self, now: float, drain: bool):
        """Buckets due for flush + the sleep until the next event
        (bucket deadline, problem deadline, backoff release, breaker
        cooldown expiry — whichever comes first)."""
        ripe = []
        wake: Optional[float] = None

        def note(t: Optional[float]) -> None:
            nonlocal wake
            if t is not None and t > now and (wake is None or t < wake):
                wake = t

        for key, items in self._pending.items():
            if not items:
                continue
            for it in items:
                note(it.deadline)  # shed promptly, not at next flush
                if it.not_before > now:
                    note(it.not_before)
            eligible = [it for it in items
                        if drain or it.not_before <= now]
            if not eligible:
                continue
            oldest = min(it.enqueued for it in eligible)
            due = (drain or len(eligible) >= self.max_batch
                   or now >= oldest + self.max_wait_s)
            if not due:
                note(oldest + self.max_wait_s)
                continue
            # Breaker gate LAST: `admit` flips OPEN->HALF_OPEN (probe)
            # as a side effect, so only consult it for a batch that
            # would otherwise dispatch right now.  Drain (flush/close)
            # bypasses it: drained futures must resolve.
            if not drain and not self.breaker.admit(str(key[0]), now):
                note(self.breaker.reopen_at(str(key[0])))
                continue
            ripe.append(key)
        timeout = None if wake is None else max(wake - now, 0.0)
        return ripe, timeout

    def _run(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                shed = self._shed_expired_locked(now)
                if shed:
                    self.stats.record_shed(len(shed))
                    self.timer.count_event("deadline_shed", len(shed))
                    flight = _obs.flight_recorder()
                    if flight is not None:
                        flight.record(
                            "queue_shed", count=len(shed),
                            names=[it.problem.name for it in shed[:8]])
                    # Shed items count as in-flight until their futures
                    # carry DeadlineExceeded (set outside the lock):
                    # flush() must not observe "drained" while a shed
                    # future is still unresolved.
                    self._inflight += 1
                drain = self._closing or self._force
                ripe, timeout = self._ripe_buckets(now, drain)
                batches = []
                for key in ripe:
                    items = self._pending[key]
                    eligible = [it for it in items
                                if drain or it.not_before <= now]
                    take = eligible[:self.max_batch]
                    rest = [it for it in items if it not in take]
                    if rest:
                        self._pending[key] = rest
                    else:
                        del self._pending[key]  # prune: no empty buckets
                    self._npending -= len(take)
                    self._inflight += 1
                    batches.append((key, take))
                stop = (not batches and not shed and self._closing
                        and not any(self._pending.values()))
                self._lock.notify_all()
                if stop:
                    return
                if not batches and not shed:
                    self._lock.wait(timeout=timeout)
                    continue
            if shed:
                for it in shed:
                    self._resolve(it.future, exc=DeadlineExceeded(
                        f"problem {it.problem.name!r} shed before "
                        f"dispatch (deadline expired; rung {it.rung}, "
                        f"{it.attempts} attempts)"))
                with self._lock:
                    self._inflight -= 1
                    self._lock.notify_all()
            for key, taken in batches:
                try:
                    self._dispatch(key, taken)
                except Exception as exc:  # never kill the dispatcher
                    for it in taken:
                        if not it.future.done():
                            self._resolve(it.future, exc=exc)
                finally:
                    with self._lock:
                        self._inflight -= 1
                        self._lock.notify_all()

    def _requeue_locked(self, item: _Pending) -> None:
        """Push one item back onto the ladder at the next rung with
        deterministic-jittered backoff (see EscalationPolicy)."""
        item.rung += 1
        backoff = self.escalation.backoff_s(item.seq, item.attempts)
        item.not_before = time.monotonic() + backoff
        key = self._key_for(item.problem, item.rung)
        self._pending.setdefault(key, []).append(item)
        self._npending += 1
        self.stats.record_retry(item.rung)
        self.timer.count_event("fleet_retry")
        flight = _obs.flight_recorder()
        if flight is not None:
            flight.record("escalation_retry", name=item.problem.name,
                          rung=item.rung, attempts=item.attempts)

    def _dispatch(self, key, taken: List[_Pending]) -> None:
        sc, _dims, factor, rung = key
        bucket = str(sc)
        option = self._rung_option(rung)
        initial_region = (None if self.escalation is None else
                          self.escalation.initial_region_for_rung(
                              self._option, rung))
        for it in taken:
            it.attempts += 1
        items = [(i, p.problem) for i, p in enumerate(taken)]
        # Per-factor engine, resolved per dispatch (memoised: one
        # factor+mode = one engine object process-wide, so this costs a
        # dict hit, and a mixed-factor queue can never cross-batch).
        from megba_tpu.factors import engine_for

        engine = engine_for(factor, option.jacobian_mode)
        t_dispatch = time.monotonic()
        for it in taken:
            # Submit-to-dispatch wait (first attempt only: a retry's
            # wait would double-count its earlier dispatch).
            if it.attempts == 1:
                self.stats.record_wait(bucket, t_dispatch - it.enqueued)
        try:
            if self._chaos is not None:
                self._chaos.before_dispatch(bucket)
            solved = _solve_bucket(
                items, sc, option, engine, self.ladder,
                self.pool, self.stats, self.timer, self._telemetry,
                self._rung_report_option(rung),
                initial_region=initial_region,
                rung=rung, attempts=rung + 1, factor=factor)
        except Exception as exc:  # fan out or escalate, keep serving
            self._on_dispatch_failure(bucket, taken, exc)
            return
        with self._lock:
            self.breaker.record_success(bucket)
        now = time.monotonic()
        retries: List[_Pending] = []
        for lane_i, fr in solved:
            it = taken[lane_i]
            fr.latency_s = now - it.enqueued
            fr.history = list(it.history)
            expired = it.deadline is not None and now >= it.deadline
            if (self.escalation is not None and not expired
                    and it.rung + 1 < self.escalation.max_rungs
                    and self.escalation.should_retry(fr.status, fr.cost)):
                it.history.append({
                    "rung": it.rung, "status": int(fr.status),
                    "status_name": fr.status_name, "error": None})
                retries.append(it)
                continue
            if expired:
                fr.deadline_missed = True
                self.stats.record_deadline_miss()
                self.timer.count_event("deadline_miss")
            self._resolve(it.future, result=fr)
        if retries:
            with self._lock:
                for it in retries:
                    self._requeue_locked(it)
                self._lock.notify_all()

    def _on_dispatch_failure(self, bucket: str, taken: List[_Pending],
                             exc: Exception) -> None:
        flight = _obs.flight_recorder()
        if flight is not None:
            flight.record("dispatch_failure", bucket=bucket,
                          problems=len(taken), error=repr(exc))
        with self._lock:
            self.breaker.record_failure(bucket, repr(exc))
        now = time.monotonic()
        retries: List[_Pending] = []
        for it in taken:
            expired = it.deadline is not None and now >= it.deadline
            if (self.escalation is not None
                    and self.escalation.retry_dispatch_errors
                    and it.rung + 1 < self.escalation.max_rungs
                    and not expired):
                it.history.append({"rung": it.rung, "status": None,
                                   "status_name": None, "error": repr(exc)})
                retries.append(it)
            else:
                if expired:
                    # The dispatch error is the diagnostic the caller
                    # needs, but the expired deadline must not vanish
                    # from the counters (it was dispatched in time, so
                    # it is a miss, not a shed).
                    self.stats.record_deadline_miss()
                    self.timer.count_event("deadline_miss")
                self._resolve(it.future, exc=exc)
        if retries:
            with self._lock:
                for it in retries:
                    self._requeue_locked(it)
                self._lock.notify_all()
