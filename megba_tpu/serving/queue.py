"""Async dispatch queue: submit problems, get Future-style handles.

The latency-shaping half of the serving layer.  `FleetQueue.submit`
enqueues one problem and returns a `concurrent.futures.Future`
resolving to its `FleetResult`; a dispatcher thread groups pending
problems by shape class and flushes a bucket when either

- it holds `max_batch` problems (occupancy-driven flush), or
- its OLDEST problem has waited `max_wait_s` (deadline-driven flush —
  the knob trading per-problem latency against batch occupancy).

All JAX work happens on the dispatcher thread (one dispatch at a time,
matching the single-device serving contract); submitters only touch
host queues.  A failed batch propagates its exception to every future
in that batch and the queue keeps serving — one poisoned problem never
wedges the service.

`close()` drains everything still pending, then joins the thread;
`FleetQueue` is a context manager (`with FleetQueue(...) as q:`), and
futures from a drained close still resolve.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from megba_tpu.common import ProblemOption
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.serving.batcher import (
    FleetProblem,
    _check_option,
    _solve_bucket,
    _strip_telemetry,
)
from megba_tpu.serving.compile_pool import CompilePool
from megba_tpu.serving.shape_class import BucketLadder, ShapeClass, classify
from megba_tpu.serving.stats import FleetStats
from megba_tpu.utils.timing import PhaseTimer


@dataclasses.dataclass
class _Pending:
    problem: FleetProblem
    future: Future
    enqueued: float  # monotonic seconds


class FleetQueue:
    """Deadline-batched async front door for `solve_many`-style solves.

    Knobs: `max_batch` caps a bucket's flush size (also the occupancy
    trigger); `max_wait_s` bounds how long a lone problem waits for
    batch-mates.  `ladder`/`pool`/`stats` default to fresh instances —
    a production service passes a warmed pool so the dispatch path
    never compiles.
    """

    def __init__(
        self,
        option: Optional[ProblemOption] = None,
        *,
        max_batch: int = 16,
        max_wait_s: float = 0.02,
        ladder: Optional[BucketLadder] = None,
        pool: Optional[CompilePool] = None,
        stats: Optional[FleetStats] = None,
        timer: Optional[PhaseTimer] = None,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        option = option or ProblemOption()
        _check_option(option)
        self._option, self._telemetry, self._report_option = (
            _strip_telemetry(option))
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.ladder = ladder or BucketLadder()
        self.stats = stats or FleetStats()
        self.pool = pool or CompilePool(stats=self.stats)
        self.timer = PhaseTimer() if timer is None else timer
        self._engine = make_residual_jacobian_fn(
            mode=self._option.jacobian_mode)

        self._lock = threading.Condition()
        self._pending: Dict[Tuple[ShapeClass, Tuple[int, int, int]],
                            List[_Pending]] = {}
        self._closing = False
        self._force = False
        self._thread = threading.Thread(
            target=self._run, name="megba-fleet-dispatch", daemon=True)
        self._thread.start()

    # -- submission ------------------------------------------------------
    def submit(self, problem: FleetProblem) -> "Future":
        """Enqueue one problem; the Future resolves to its FleetResult
        (or raises what its batch raised)."""
        n_cam, n_pt, n_edge = problem.dims()
        sc = classify(n_cam, n_pt, n_edge, self._option.dtype, self.ladder)
        dims = (int(problem.cameras.shape[1]), int(problem.points.shape[1]),
                int(problem.obs.shape[1]))
        item = _Pending(problem=problem, future=Future(),
                        enqueued=time.monotonic())
        with self._lock:
            if self._closing:
                raise RuntimeError("FleetQueue is closed")
            self._pending.setdefault((sc, dims), []).append(item)
            self._lock.notify()
        return item.future

    def flush(self) -> None:
        """Dispatch everything pending NOW (ignore deadlines) and block
        until it has been handed to the solver."""
        with self._lock:
            self._force = True
            self._lock.notify()
            while any(self._pending.values()):
                self._lock.wait(timeout=0.01)
            self._force = False

    def close(self) -> None:
        """Drain pending work, then stop the dispatcher thread."""
        with self._lock:
            self._closing = True
            self._lock.notify()
        self._thread.join()

    def __enter__(self) -> "FleetQueue":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- dispatcher ------------------------------------------------------
    def _ripe_buckets(self, now: float, drain: bool):
        """Buckets due for flush + the sleep until the next deadline."""
        ripe = []
        next_deadline = None
        for key, items in self._pending.items():
            if not items:
                continue
            deadline = items[0].enqueued + self.max_wait_s
            if drain or len(items) >= self.max_batch or now >= deadline:
                ripe.append(key)
            elif next_deadline is None or deadline < next_deadline:
                next_deadline = deadline
        timeout = (None if next_deadline is None
                   else max(next_deadline - now, 0.0))
        return ripe, timeout

    def _run(self) -> None:
        while True:
            with self._lock:
                ripe, timeout = self._ripe_buckets(
                    time.monotonic(), drain=self._closing or self._force)
                if not ripe:
                    if self._closing:
                        return
                    self._lock.wait(timeout=timeout)
                    continue
                batches = []
                for key in ripe:
                    items = self._pending[key]
                    take, rest = items[:self.max_batch], items[self.max_batch:]
                    self._pending[key] = rest
                    batches.append((key, take))
                self._lock.notify_all()
            for (sc, _dims), taken in batches:
                self._dispatch(sc, taken)

    def _dispatch(self, shape: ShapeClass, taken: List[_Pending]) -> None:
        items = [(i, p.problem) for i, p in enumerate(taken)]
        try:
            solved = _solve_bucket(
                items, shape, self._option, self._engine, self.ladder,
                self.pool, self.stats, self.timer, self._telemetry,
                self._report_option)
        except Exception as exc:  # fan the failure out, keep serving
            for p in taken:
                if not p.future.cancelled():
                    p.future.set_exception(exc)
            return
        for lane_i, fr in solved:
            fut = taken[lane_i].future
            fr.latency_s = time.monotonic() - taken[lane_i].enqueued
            if not fut.cancelled():
                fut.set_result(fr)
