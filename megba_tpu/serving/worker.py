"""Federation worker: the serve loop behind every transport, plus the
`python -m megba_tpu.serving.worker` bootstrap CLI.

PR 12's worker lived inside `federation._worker_main`, welded to the
stdin/stdout pipe pair.  This module splits it into:

- **`WorkerRuntime`** — the transport-agnostic core: apply one config
  (affinity, telemetry tags, artifact warm-up), answer one request at a
  time (`solve`/`stats`/`metrics`/`shutdown`), and run the serve loop
  over any `Transport`.  Replies are cached by request sequence id
  (`DedupCache`) BEFORE they are sent, so a router resend after a
  reconnect is served from cache — a retry can never double-solve.

- **The bootstrap CLI** — `--connect HOST:PORT` dials a router (the
  normal multi-host shape: workers reach out, NAT-friendly) and
  `--bind HOST:PORT` listens for one (workers behind no egress).
  Either way the WORKER speaks first: a `register` frame carrying the
  token MAC, protocol version, environment fingerprint and incarnation
  counter; the router answers `config` (first join — full solver
  config over the wire) or `resume` (reconnect — the warmed compile
  pool survives), both MAC'd back so the worker authenticates the
  router too.  Version or fingerprint drift is refused TYPED on either
  side and is fatal (no retry loop against a router that will never
  accept us); a dropped connection re-dials under the deterministic
  seeded backoff of `ReconnectPolicy` and re-registers with the same
  worker id and `incarnation + 1`.

While connected, a beater thread ships `{"__hb__": n}` frames between
replies (the transport's send lock keeps them from interleaving with
reply bytes); the router observes them on ITS own monotonic clock —
the PR 9 `HeartbeatBoard` stance, with the channel replacing the
heartbeat files that cannot span hosts.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import os
import socket
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from megba_tpu import observability as _obs
from megba_tpu.serving.transport import (
    DedupCache,
    FrameError,
    HandshakeError,
    ReconnectPolicy,
    TcpTransport,
    Transport,
    heartbeat_frame,
    is_heartbeat,
    parse_address,
    register_frame,
    verify_ack,
)
from megba_tpu.utils.timing import monotonic_s


class WorkerRuntime:
    """One worker's solver state + request handling, transport-free.

    Constructing it applies the config (env tag, CPU affinity, solver
    imports); `warm()` runs the cold start and returns the hello frame;
    `serve(chan)` then answers requests until shutdown (returns 0) or
    connection loss (returns None — the caller owns reconnect policy:
    the pipe worker exits, the TCP worker re-dials)."""

    def __init__(self, worker_id: str, cfg: Dict[str, Any]) -> None:
        self.worker_id = worker_id
        self.cfg = cfg
        # Tag this process's fleet telemetry with the worker id BEFORE
        # any serving import reads it (batcher reads it per report).
        os.environ["MEGBA_FEDERATION_WORKER"] = worker_id
        # CPU pinning (router `pin_cpus=`): restrict this worker to its
        # core slice BEFORE the first dispatch, so the lazily-built
        # XLA:CPU thread pool's threads inherit the affinity.
        affinity = cfg.get("cpu_affinity")
        if affinity:
            try:
                os.sched_setaffinity(0, set(int(c) for c in affinity))
            except (AttributeError, OSError):  # non-Linux / restricted
                pass

        from megba_tpu.ops.residuals import make_residual_jacobian_fn
        from megba_tpu.serving.compile_pool import CompilePool
        from megba_tpu.serving.stats import FleetStats
        from megba_tpu.utils.timing import PhaseTimer

        # `option` (observability-STRIPPED: telemetry AND metrics,
        # common.OBSERVABILITY_FIELDS) feeds warmup and fingerprints —
        # the program caches are observability-agnostic by contract;
        # `solve_option` carries this worker's sink AND the config's
        # metrics flag into solve_many, which strips both again before
        # touching any cache, so warm and dispatch agree on keys.
        from megba_tpu.common import strip_observability

        base_option = cfg["option"]
        self.option = strip_observability(base_option)
        self.ladder = cfg.get("ladder")
        self.stats = FleetStats()
        self.timer = PhaseTimer()
        self.pool = CompilePool(stats=self.stats,
                                artifacts=cfg.get("artifacts"),
                                timer=self.timer)
        self.engine = make_residual_jacobian_fn(
            mode=self.option.jacobian_mode)
        telemetry = cfg.get("telemetry")
        self.solve_option = dataclasses.replace(
            base_option, telemetry=telemetry or None)
        self.dedup = DedupCache()
        self._first_solve: Optional[Dict[str, Any]] = None

        # File heartbeats: PR 9's liveness board, beaten from a daemon
        # thread — the single-host (pipe) shape; TCP fleets beat over
        # the channel instead (files cannot span hosts).
        hb = cfg.get("heartbeat")
        if hb:
            from megba_tpu.robustness.elastic import HeartbeatBoard

            board = HeartbeatBoard(hb["dir"], int(hb["rank"]),
                                   int(hb["world"]))
            interval = float(hb.get("interval_s", 0.25))

            def _beat() -> None:
                while True:
                    board.beat()
                    time.sleep(interval)

            threading.Thread(target=_beat, daemon=True,
                             name="megba-fed-heartbeat").start()

    # -- cold start ------------------------------------------------------
    def warm(self) -> Dict[str, Any]:
        """Warm the manifest's buckets; return the hello frame (`ok`
        False with the error on a warm failure)."""
        t0 = monotonic_s()
        warmed = 0
        try:
            if self.cfg.get("manifest"):
                warmed = self.pool.warm_from_manifest(
                    self.cfg["manifest"], self.engine, self.option,
                    strict=bool(self.cfg.get("strict_manifest", False)))
        except Exception as exc:
            return {"ok": False, "error": repr(exc),
                    "worker_id": self.worker_id}
        warm_s = monotonic_s() - t0
        loads = self.stats.artifact_loads
        # Store-less warms compile without touching the artifact
        # counters (they describe a store that must exist) — the
        # timer's phase count is the mode signal either way.
        compiles = self.timer.counts.get("warm_compile", 0)
        mode = ("artifact" if loads and not compiles
                else "compile" if compiles else "cold")
        return {
            "ok": True, "op": "hello", "worker_id": self.worker_id,
            "pid": os.getpid(), "warm": self.warm_set(),
            "warmed": warmed,
            "cold_start": {
                "mode": mode, "warm_s": warm_s, "buckets": warmed,
                "artifact_loads": loads, "artifact_compiles": compiles,
                "phases": self.timer.as_dict(),
            },
        }

    def warm_set(self) -> List[str]:
        return sorted({str(_shape_of(e)) for e in self.pool.entries()})

    # -- request handling ------------------------------------------------
    def handle(self, req: Dict[str, Any]) -> Tuple[Dict[str, Any], bool]:
        """Answer one request; returns (reply, stop)."""
        op = req.get("op")
        if op == "shutdown":
            return {"ok": True}, True
        if op == "stats":
            return {"ok": True, "stats": self.stats.as_dict(),
                    "phases": self.timer.as_dict()}, False
        if op == "metrics":
            # Observability harvesting seam: the router merges these
            # per-worker registry snapshots (metrics_snapshot()).
            registry = _obs.metrics_registry()
            return {"ok": True, "metrics": (
                None if registry is None else registry.snapshot())}, False
        if op != "solve":
            return {"ok": False, "error": f"unknown op {op!r}"}, False
        return self._solve(req), False

    def _solve(self, req: Dict[str, Any]) -> Dict[str, Any]:
        from megba_tpu.analysis import retrace
        from megba_tpu.serving.batcher import solve_many

        problems = req["problems"]
        recorder = _obs.span_recorder()
        try:
            base = retrace.snapshot()
            t0 = monotonic_s()
            # The router's trace context rides the solve frame; the
            # worker's whole solve joins it as a child span and the
            # spans recorded under it ship back in the reply.
            scope = (contextlib.nullcontext() if recorder is None
                     else recorder.adopt(
                         "worker_solve", req.get("trace"),
                         worker=self.worker_id,
                         problems=len(problems)))
            with scope:
                results = solve_many(problems, self.solve_option,
                                     ladder=self.ladder, pool=self.pool,
                                     stats=self.stats, timer=self.timer)
            wall = monotonic_s() - t0
            if self._first_solve is None:
                traces = sum(
                    v - base.get(k, 0)
                    for k, v in retrace.snapshot().items()
                    if k[0].startswith("serving.batched")
                    and v > base.get(k, 0))
                self._first_solve = {"traces": int(traces),
                                     "wall_s": wall,
                                     "problems": len(problems)}
            # Traces are per-iteration device history — large, and the
            # router's callers read costs/params/status; telemetry (the
            # per-problem SolveReports written ABOVE, worker-side)
            # already persisted them for whoever wants forensics.
            slim = [dataclasses.replace(r, trace=None) for r in results]
            return {
                "ok": True, "results": slim,
                "warm": self.warm_set(),
                "first_solve": self._first_solve,
                "spans": (None if recorder is None
                          else recorder.drain()),
            }
        except Exception as exc:  # solve failed: typed reply, serve on
            import traceback

            flight = _obs.flight_recorder()
            if flight is not None:
                flight.record("solve_error", worker=self.worker_id,
                              problems=len(problems), error=repr(exc))
            return {"ok": False, "error": repr(exc),
                    "traceback": traceback.format_exc(),
                    "spans": (None if recorder is None
                              else recorder.drain())}

    # -- serve loop ------------------------------------------------------
    def serve(self, chan: Transport) -> Optional[int]:
        """Answer requests until shutdown (-> 0) or connection loss
        (-> None).  Every reply with a sequence id is cached BEFORE it
        is sent: if the send dies mid-frame, the router's resend of the
        same seq is served from cache, never re-executed."""
        while True:
            try:
                req = chan.recv()
            except (FrameError, OSError):
                # FrameError (EOF/desync) or a raw socket error
                # (ECONNRESET): connection gone, caller owns what's
                # next (pipe worker exits, TCP worker re-dials).
                return None
            if is_heartbeat(req):
                continue  # tolerated, though only workers beat today
            seq = req.get("seq") if isinstance(req, dict) else None
            if seq is not None:
                cached = self.dedup.get(seq)
                if cached is not None:
                    self.timer.count_event("transport_dedup_hit")
                    registry = _obs.metrics_registry()
                    if registry is not None:
                        registry.counter(
                            "megba_transport_dedup_total",
                            "Resent requests served from the reply "
                            "cache instead of re-executing").inc(
                                worker=self.worker_id)
                    flight = _obs.flight_recorder()
                    if flight is not None:
                        flight.record("dedup_hit",
                                      worker=self.worker_id, seq=seq)
                    try:
                        chan.send(cached)
                    except OSError:
                        return None
                    continue
            reply, stop = self.handle(req)
            if seq is not None:
                reply = dict(reply)
                reply["seq"] = seq
                self.dedup.put(seq, reply)
            try:
                chan.send(reply)
            except OSError:
                return None
            if stop:
                return 0


def _shape_of(entry: Dict[str, Any]):
    from megba_tpu.serving.shape_class import ShapeClass

    return ShapeClass.from_dict(entry["shape"])


@contextlib.contextmanager
def _crash_flight_dump(worker_id: str):
    """Dump the flight ring when the serve loop dies abnormally (router
    still thinks the worker is alive).  SIGKILL deaths cannot run this
    — the ROUTER's recorder covers those (_on_worker_lost)."""
    try:
        yield
    except BaseException:
        flight = _obs.flight_recorder()
        if flight is not None:
            flight.record("worker_crash", worker=worker_id)
            from megba_tpu.observability import flight as _flight

            _flight.dump_default("worker_crash")
        raise


# ---------------------------------------------------------------------------
# Pipe entry (what federation._worker_main delegates to)
# ---------------------------------------------------------------------------


def pipe_worker_main() -> int:
    """Run one pipe-spawned worker: frames in on fd 0, frames out on
    the ORIGINAL fd 1; fd 1 is then pointed at stderr so any stray
    print from a library can never corrupt the frame stream."""
    from megba_tpu.serving.transport import PipeTransport

    rpc_in = os.fdopen(os.dup(0), "rb", buffering=0)
    rpc_out = os.fdopen(os.dup(1), "wb", buffering=0)
    os.dup2(2, 1)
    chan = PipeTransport(rpc_in, rpc_out)

    cfg = chan.recv()
    if cfg.get("op") != "config":
        chan.send({"ok": False, "error": f"expected config, got {cfg!r}"})
        return 2
    worker_id = cfg["worker_id"]
    runtime = WorkerRuntime(worker_id, cfg)
    hello = runtime.warm()
    chan.send(hello)
    if not hello.get("ok"):
        return 3
    with _crash_flight_dump(worker_id):
        rc = runtime.serve(chan)
    return 0 if rc is None else rc  # pipe EOF = router gone: clean exit


# ---------------------------------------------------------------------------
# TCP bootstrap CLI
# ---------------------------------------------------------------------------


class _Beater:
    """Per-connection heartbeat thread: `{"__hb__": n}` frames between
    replies.  Stops on `stop()` or the first send failure (the serve
    loop notices the same dead connection on its next recv)."""

    def __init__(self, chan: Transport, worker_id: str,
                 interval_s: float) -> None:
        self._chan = chan
        self._worker_id = worker_id
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._n = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="megba-fed-chan-beat")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            self._n += 1
            try:
                self._chan.send(
                    heartbeat_frame(self._n, self._worker_id))
            except OSError:
                return

    def stop(self) -> None:
        self._stop.set()


def _dial(addr: Tuple[str, int], timeout_s: float) -> socket.socket:
    sock = socket.create_connection(addr, timeout=timeout_s)
    sock.settimeout(None)
    return sock


def run_tcp_worker(
    worker_id: str,
    *,
    connect: Optional[str] = None,
    bind: Optional[str] = None,
    token: Optional[str] = None,
    reconnect: Optional[ReconnectPolicy] = None,
    hb_interval_s: float = 0.25,
    handshake_timeout_s: float = 30.0,
) -> int:
    """Join (and keep rejoining) a router fleet over TCP.

    Returns 0 on a clean router-commanded shutdown, 1 on a typed
    handshake refusal or reconnect-budget exhaustion.  The compile pool
    and dedup cache survive reconnects (the whole point of `resume`);
    only a fresh process starts cold.
    """
    if (connect is None) == (bind is None):
        raise ValueError("exactly one of connect/bind is required")
    policy = reconnect or ReconnectPolicy()
    key = zlib.crc32(worker_id.encode())  # stable per-worker jitter seed

    from megba_tpu.serving.artifacts import current_environment

    env = current_environment()
    runtime: Optional[WorkerRuntime] = None
    incarnation = 0
    attempt = 0
    lsock: Optional[socket.socket] = None
    if bind is not None:
        host, port = parse_address(bind)
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((host, port))
        lsock.listen(1)
        print(f"[{worker_id}] listening on "
              f"{lsock.getsockname()[0]}:{lsock.getsockname()[1]}",
              file=sys.stderr, flush=True)

    while True:
        try:
            if lsock is not None:
                sock, peer = lsock.accept()
            else:
                sock = _dial(parse_address(connect), handshake_timeout_s)
        except OSError as exc:
            attempt += 1
            if attempt > policy.max_attempts:
                print(f"[{worker_id}] reconnect budget exhausted "
                      f"({policy.max_attempts} attempts): {exc}",
                      file=sys.stderr, flush=True)
                return 1
            time.sleep(policy.backoff_s(key, attempt))
            continue

        chan = TcpTransport(sock)
        beater: Optional[_Beater] = None
        try:
            chan.send(dict(
                register_frame(worker_id, token, incarnation,
                               os.getpid(), env),
                needs_config=runtime is None))
            ack = chan.recv(timeout_s=handshake_timeout_s)
            op = verify_ack(ack, token, worker_id)
            if op == "config":
                runtime = WorkerRuntime(worker_id, ack["config"])
                chan.send(runtime.warm())
            else:  # resume: warmed pool survives; re-hello with it
                if runtime is None:
                    raise HandshakeError("resume", "no runtime",
                                         "a prior config")
                chan.send({"ok": True, "op": "hello",
                           "worker_id": worker_id, "pid": os.getpid(),
                           "warm": runtime.warm_set(),
                           "resumed": True, "incarnation": incarnation})
        except HandshakeError as exc:
            # Drift refusals are fatal: retrying against a router that
            # will never accept this build only burns the backoff.
            print(f"[{worker_id}] {exc}", file=sys.stderr, flush=True)
            chan.close()
            return 1
        except (FrameError, TimeoutError, OSError) as exc:
            chan.close()
            attempt += 1
            if attempt > policy.max_attempts:
                print(f"[{worker_id}] reconnect budget exhausted "
                      f"({policy.max_attempts} attempts): {exc}",
                      file=sys.stderr, flush=True)
                return 1
            time.sleep(policy.backoff_s(key, attempt))
            continue

        attempt = 0  # registered: the window resets
        beater = _Beater(chan, worker_id, hb_interval_s)
        try:
            with _crash_flight_dump(worker_id):
                rc = runtime.serve(chan)
        finally:
            beater.stop()
            chan.close()
        if rc is not None:
            return rc  # router-commanded shutdown
        incarnation += 1  # connection lost: re-register


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m megba_tpu.serving.worker",
        description="megba federation worker (TCP bootstrap)")
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--connect", metavar="HOST:PORT",
                      help="dial a router at this address")
    mode.add_argument("--bind", metavar="HOST:PORT",
                      help="listen for a router at this address")
    parser.add_argument("--worker-id", required=True,
                        help="stable worker identity (survives restarts)")
    parser.add_argument("--token", default=None,
                        help="shared fleet token (default: "
                             "$MEGBA_FED_TOKEN)")
    parser.add_argument("--hb-interval", type=float, default=0.25,
                        metavar="S", help="channel heartbeat period")
    parser.add_argument("--reconnect-attempts", type=int, default=8)
    parser.add_argument("--reconnect-base", type=float, default=0.05,
                        metavar="S")
    parser.add_argument("--reconnect-cap", type=float, default=2.0,
                        metavar="S")
    parser.add_argument("--reconnect-window", type=float, default=30.0,
                        metavar="S")
    parser.add_argument("--reconnect-jitter", type=float, default=0.5)
    parser.add_argument("--reconnect-seed", type=int, default=0)
    args = parser.parse_args(argv)
    token = (args.token if args.token is not None
             else os.environ.get("MEGBA_FED_TOKEN") or None)
    policy = ReconnectPolicy(
        max_attempts=args.reconnect_attempts,
        base_s=args.reconnect_base, cap_s=args.reconnect_cap,
        window_s=args.reconnect_window, jitter=args.reconnect_jitter,
        seed=args.reconnect_seed)
    try:
        return run_tcp_worker(
            args.worker_id, connect=args.connect, bind=args.bind,
            token=token, reconnect=policy,
            hb_interval_s=args.hb_interval)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
