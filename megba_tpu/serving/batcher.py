"""Batched mega-solve: many independent BA problems, one compiled program.

`solve_many` is the synchronous public entry point of the serving
layer: it buckets problems by shape class (serving/shape_class.py),
stacks each bucket into a leading lane axis, and drives ONE jitted
`vmap`'d LM solve per bucket (serving/compile_pool.py).  Per-problem
convergence masking is native: JAX's while_loop batching freezes a
converged lane's carry bitwise (per-lane select) while the other lanes
keep iterating, and per-problem `SolveStatus`, trace and cost come back
per lane.  Results are returned in submission order; the async
dispatch queue (serving/queue.py) reuses `_solve_bucket` for its
deadline-flushed batches.

Padding guarantees (shape_class.py) make a lane's result bitwise
identical to the same problem solved alone at the same shape class —
the batched path changes WHERE a problem computes, never what.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from megba_tpu import observability as _obs
from megba_tpu.common import (
    ProblemOption,
    status_name,
    strip_observability,
    validate_options,
)
from megba_tpu.observability.trace import SolveTrace
from megba_tpu.serving.compile_pool import CompilePool
from megba_tpu.serving.shape_class import (
    BucketLadder,
    PaddedProblem,
    ShapeClass,
    classify,
    pad_to_class,
)
from megba_tpu.serving.stats import FleetStats
from megba_tpu.utils.backend import warn_if_x64_unavailable
from megba_tpu.utils.timing import PhaseTimer, monotonic_s


@dataclasses.dataclass
class FleetProblem:
    """One independent BA problem in the fleet (edge-major host arrays).

    The serving layer's ingestion unit: conventional [N, d] numpy
    layouts, exactly what `solve.flat_solve` accepts.  `name` tags the
    problem through stats/telemetry fan-out."""

    cameras: np.ndarray  # [Nc, cd]
    points: np.ndarray  # [Np, pd]
    obs: np.ndarray  # [nE, od]
    cam_idx: np.ndarray  # [nE]
    pt_idx: np.ndarray  # [nE]
    name: str = ""
    # Optional seeded fault (robustness/faults.FaultPlan, NATURAL edge
    # order) — the serving chaos harness's injection point.  A problem
    # carrying a plan rides the batched FAULTED program (its plan
    # lowered through the same sort/padding as its edges, batch-mates
    # on inert plans); problems without plans in a plan-free batch ride
    # the ordinary program unchanged.
    fault_plan: Optional[Any] = None
    # Optional repair operands (robustness/triage.py, NATURAL edge /
    # vertex order): `edge_mask` [nE] in [0, 1] soft-deletes or
    # downweights edges, `cam_fixed`/`pt_fixed` freeze blocks.  Folded
    # into the bucket's padding masks by pad_to_class — pure operands
    # of the batched program, so a repaired problem and its pristine
    # batch-mates share one compilation.  `health` carries the triage
    # HealthReport dict through to FleetResult / telemetry.
    edge_mask: Optional[np.ndarray] = None
    cam_fixed: Optional[np.ndarray] = None
    pt_fixed: Optional[np.ndarray] = None
    health: Optional[Dict[str, Any]] = None
    # Which registered residual family this problem solves under
    # (factors/registry.py).  The fleet layer is factor-agnostic by
    # construction: problems group by (factor, shape class, block
    # dims), each group resolves its own engine, and engine identity is
    # already in every program-cache key — so a fleet can mix rig,
    # radial, prior and BAL problems with zero cross-factor retraces.
    factor: str = "bal"

    @classmethod
    def from_synthetic(cls, s, name: str = "",
                       factor: str = "bal") -> "FleetProblem":
        """Wrap a synthetic scene (initial parameters).  Accepts any of
        the generator dataclasses exposing cameras0/points0/obs/
        cam_idx/pt_idx (io.synthetic.SyntheticBAL, factors.rig.
        SyntheticRig, factors.radial.SyntheticRadial, ...)."""
        return cls(cameras=s.cameras0, points=s.points0, obs=s.obs,
                   cam_idx=s.cam_idx, pt_idx=s.pt_idx, name=name,
                   factor=factor)

    def dims(self) -> Tuple[int, int, int]:
        return (int(self.cameras.shape[0]), int(self.points.shape[0]),
                int(self.obs.shape[0]))


@dataclasses.dataclass
class FleetResult:
    """One problem's slice of a batched solve (host numpy, unpadded)."""

    name: str
    shape: ShapeClass  # the bucket this problem solved in
    lane: int  # its lane in the batched dispatch
    lanes: int  # total lanes dispatched in that batch
    cameras: np.ndarray  # [Nc, cd] solved parameters
    points: np.ndarray  # [Np, pd]
    cost: np.ndarray  # final accepted cost (0-d, solve dtype)
    initial_cost: np.ndarray
    iterations: int
    accepted: int
    pcg_iterations: int
    status: int  # common.SolveStatus code
    recoveries: int
    latency_s: float  # batch wall clock this problem rode
    trace: Optional[SolveTrace] = None  # per-lane convergence history
    # -- fleet-resilience context (serving/resilience.py) ---------------
    # True when the result completed AFTER the submitted deadline (it is
    # delivered anyway, but never silently).
    deadline_missed: bool = False
    # Escalation history: total attempts (1 = first try succeeded), the
    # rung this result solved at, and one record per PRIOR attempt
    # ({"rung", "status", "status_name", "error"}) — the error field
    # carries dispatch-level exceptions, status the solve outcomes.
    attempts: int = 1
    rung: int = 0
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # Pre-flight triage context (robustness/triage.py): the HealthReport
    # dict of the submitted problem when triage ran (None otherwise).
    health: Optional[Dict[str, Any]] = None

    @property
    def status_name(self) -> str:
        return status_name(self.status)


def _strip_telemetry(option: ProblemOption) -> Tuple[ProblemOption, Optional[str], ProblemOption]:
    """Resolve the telemetry sink and strip the observability knobs
    (`telemetry` AND `metrics` — same contract as solve.flat_solve:
    program caches must stay observability-agnostic).  The resolved
    metrics flag survives on the returned `report_option`, which is
    what instrumentation sites gate on."""
    telemetry = option.telemetry or os.environ.get("MEGBA_TELEMETRY") or None
    report_option = option
    option = strip_observability(option)
    return option, telemetry, report_option


def _check_option(option: ProblemOption) -> None:
    validate_options(option)
    if option.world_size != 1:
        raise ValueError(
            "serving batches over a leading lane axis on a single "
            "program; world_size must be 1 (got "
            f"{option.world_size}) — shard the FLEET across hosts, not "
            "one problem across devices")


def _problem_spec(p: FleetProblem, index: int = -1):
    """Resolve + dim-check a fleet problem's factor spec (typed
    `UnknownFactorError`/`FactorError` at the ingestion boundary)."""
    from megba_tpu.factors import get_factor, validate_factor_arrays
    from megba_tpu.factors.registry import require_schur

    where = (f"FleetProblem {p.name!r}" if p.name
             else f"FleetProblem #{index}" if index >= 0
             else "FleetProblem")
    spec = require_schur(get_factor(p.factor), where)
    validate_factor_arrays(spec, p.cameras, p.points, p.obs, where=where)
    return spec


def _validate_problem(p: FleetProblem, index: int = -1,
                      option: Optional[ProblemOption] = None) -> None:
    """The serving layer's ingestion gate: the SAME semantic validation
    the BAL parsers apply (io/bal.validate_problem), so duplicate
    (cam, pt) edges and non-finite values cannot sneak into a batch
    through `solve_many` / `FleetQueue.submit` when no triage policy is
    armed.  Factor-dispatched: the duplicate-edge refusal only applies
    to families declaring `unique_edges` (a rig legitimately repeats a
    (body, point) pair once per physical camera; a prior may repeat a
    constraint), and with `option` given a robust kernel on a
    `robust_ok=False` family is refused typed HERE — the same refusal
    `flat_solve(factor=)` makes, so the fleet path cannot silently
    IRLS-downweight a marginalization prior.  Skipped only when the
    problem carries a triage `health` record whose STRUCTURAL pass ran
    — that pass subsumes this gate's duplicate check (non-finite checks
    are unconditional in triage), so a `TriagePolicy(structural=False)`
    submission still hits the gate here."""
    spec = _problem_spec(p, index)
    if option is not None and not spec.robust_ok:
        from megba_tpu.factors.registry import FactorError
        from megba_tpu.ops.robust import RobustKind

        if option.robust_kind != RobustKind.NONE:
            raise FactorError(
                f"factor {spec.name!r} is not robust-kernel eligible "
                "(robust_ok=False — e.g. a marginalization prior must "
                "not be IRLS-downweighted); submit it under "
                "robust_kind=NONE")
    if p.health is not None and p.health.get("structural", False):
        return
    from megba_tpu.io.bal import validate_problem

    if p.name:
        where = f"FleetProblem {p.name!r}"
    elif index >= 0:
        where = f"FleetProblem #{index}"
    else:
        where = "FleetProblem"
    validate_problem(p.cameras, p.points, p.obs, p.cam_idx, p.pt_idx,
                     where=where, unique_edges=spec.unique_edges)


def _group_by_bucket(problems: Sequence[FleetProblem], option: ProblemOption,
                     ladder: BucketLadder):
    """index-preserving grouping:
    (shape, (cd, pd, od), factor) -> [(i, problem)].

    The factor name is part of the key even though two factors RARELY
    share block dims: if they ever did, batching them together would
    hand one factor's lanes to the other's engine — the bucket must be
    one residual family by construction.
    """
    groups: Dict[Tuple, List[Tuple[int, FleetProblem]]] = {}
    for i, p in enumerate(problems):
        n_cam, n_pt, n_edge = p.dims()
        sc = classify(n_cam, n_pt, n_edge, option.dtype, ladder)
        dims = (int(p.cameras.shape[1]), int(p.points.shape[1]),
                int(p.obs.shape[1]))
        groups.setdefault((sc, dims, p.factor), []).append((i, p))
    return groups


def _stack_bucket(padded: Sequence[PaddedProblem], lanes: int, dtype):
    """Stack padded problems into lane-axis operands (feature-major).

    Lane padding (to the lane ladder) REPEATS lane 0: a duplicate lane
    is shape-correct, converges exactly like its original (so it can
    never extend the while loop beyond the real lanes' horizon), and is
    dropped on fan-out."""
    idx = list(range(len(padded))) + [0] * (lanes - len(padded))
    cams = np.stack([np.ascontiguousarray(padded[k].cameras.T) for k in idx])
    pts = np.stack([np.ascontiguousarray(padded[k].points.T) for k in idx])
    obs = np.stack([np.ascontiguousarray(padded[k].obs.T) for k in idx])
    cam_idx = np.stack([padded[k].cam_idx for k in idx])
    pt_idx = np.stack([padded[k].pt_idx for k in idx])
    mask = np.stack([padded[k].mask for k in idx]).astype(dtype)
    cam_fixed = np.stack([padded[k].cam_fixed for k in idx])
    pt_fixed = np.stack([padded[k].pt_fixed for k in idx])
    return cams, pts, obs, cam_idx, pt_idx, mask, cam_fixed, pt_fixed


def _lane_result(batched, i: int):
    """Slice lane i out of a batched LMResult pytree."""
    return jax.tree_util.tree_map(lambda a: a[i], batched)


def _phase_delta(before: Dict[str, Any], after: Dict[str, Any]):
    """This batch's slice of a (possibly long-lived, cumulative)
    PhaseTimer: `after - before` per phase, zero-delta phases dropped —
    so every telemetry report carries its OWN batch's wall clock, not
    the service's lifetime totals."""
    out: Dict[str, Any] = {}
    for name, v in after.items():
        b = before.get(name, {"total_s": 0.0, "calls": 0})
        d = {"total_s": v["total_s"] - b["total_s"],
             "calls": v["calls"] - b["calls"]}
        if d["total_s"] or d["calls"]:
            out[name] = d
    return out


def _solve_bucket(
    items: Sequence[Tuple[int, FleetProblem]],
    shape: ShapeClass,
    option: ProblemOption,
    engine,
    ladder: BucketLadder,
    pool: CompilePool,
    stats: FleetStats,
    timer: PhaseTimer,
    telemetry: Optional[str],
    report_option: ProblemOption,
    *,
    initial_region: Optional[float] = None,
    rung: int = 0,
    attempts: int = 1,
    factor: str = "bal",
) -> List[Tuple[int, FleetResult]]:
    """Solve one bucket's problems in a single batched dispatch.

    `initial_region` overrides the option's trust-region start (an
    OPERAND — the escalation ladder's damping inflation rides the same
    compiled program).  `rung`/`attempts` are the escalation context
    stamped onto results and telemetry (rung 0 / attempt 1 = a plain
    first try).  Any item carrying a `FleetProblem.fault_plan` switches
    the batch onto the FAULTED program variant with per-lane plans
    (inert for unpoisoned lanes) — the serving chaos path.
    """
    dtype = np.dtype(option.dtype)
    n_real = len(items)
    lanes = ladder.bucket_lanes(n_real)
    phases_before = timer.as_dict()
    faulted = any(p.fault_plan is not None for _, p in items)
    # Observability plane (all host-side; None when off — the compiled
    # program below is byte-identical either way, HLO-audit-pinned).
    recorder = _obs.span_recorder()
    span_scope = (contextlib.nullcontext() if recorder is None
                  else recorder.span("solve_bucket", bucket=str(shape),
                                     factor=factor, lanes=lanes,
                                     problems=n_real, rung=rung))
    with span_scope:
        return _solve_bucket_inner(
            items, shape, option, engine, ladder, pool, stats, timer,
            telemetry, report_option, initial_region=initial_region,
            rung=rung, attempts=attempts, factor=factor, dtype=dtype,
            n_real=n_real, lanes=lanes, phases_before=phases_before,
            faulted=faulted)


def _solve_bucket_inner(
    items, shape, option, engine, ladder, pool, stats, timer,
    telemetry, report_option, *, initial_region, rung, attempts, factor,
    dtype, n_real, lanes, phases_before, faulted,
) -> List[Tuple[int, FleetResult]]:
    with timer.phase("lowering"):
        padded = [pad_to_class(p.cameras, p.points, p.obs, p.cam_idx,
                               p.pt_idx, shape, edge_mask=p.edge_mask,
                               cam_fixed=p.cam_fixed, pt_fixed=p.pt_fixed)
                  for _, p in items]
        operands = _stack_bucket(padded, lanes, dtype)
        plan_stack = None
        if faulted:
            from megba_tpu.robustness.faults import (
                inert_fault_plan,
                lower_fault_plan,
                stack_fault_plans,
            )

            plans = []
            for (_, p), pp in zip(items, padded):
                if p.fault_plan is None:
                    plans.append(inert_fault_plan(
                        shape.n_edge, shape.n_pt, dtype))
                else:
                    plans.append(lower_fault_plan(
                        p.fault_plan, n_edges=shape.n_edge,
                        n_points=shape.n_pt, dtype=dtype, perm=pp.perm))
            # Lane padding repeats lane 0's operands (_stack_bucket), so
            # it must repeat lane 0's plan too — a padding lane then
            # behaves exactly like its original and cannot extend the
            # while-loop horizon past the real lanes'.
            plans.extend(plans[0] for _ in range(lanes - len(plans)))
            plan_stack = stack_fault_plans(plans)
    cd = operands[0].shape[1]
    pd = operands[1].shape[1]
    od = operands[2].shape[1]

    with timer.phase("program"):
        # `factor` rides to the pool's manifest entry (not the program
        # key — engine identity covers that) so a mixed-factor
        # service's manifest warms each bucket with its own engine.
        program = pool.program(engine, option, shape, lanes, cd, pd, od,
                               faulted=faulted, factor=factor)
    ir = jnp.asarray(option.algo_option.initial_region
                     if initial_region is None else initial_region, dtype)
    iv = jnp.asarray(2.0, dtype)

    t0 = monotonic_s()
    with timer.phase("dispatch"):
        if faulted:
            result = program(*operands, ir, iv, plan_stack)
        else:
            result = program(*operands, ir, iv)
    with timer.phase("execute") as ph:
        ph.sync(result.cost)
    wall = monotonic_s() - t0

    edges_real = sum(p.n_edge for p in padded)
    stats.record_batch(str(shape), lanes, n_real, edges_real,
                       shape.n_edge, wall)
    registry = _obs.metrics_registry(report_option.metrics)
    if registry is not None:
        from megba_tpu.observability import metrics as _metrics

        registry.counter(
            "megba_fleet_batches_total",
            "Batched dispatches per (bucket, factor, rung)").inc(
                1, bucket=str(shape), factor=factor, rung=rung)
        registry.counter(
            "megba_fleet_problems_total",
            "Problems solved per (bucket, factor)").inc(
                n_real, bucket=str(shape), factor=factor)
        registry.histogram(
            "megba_fleet_batch_latency_seconds",
            "Batch dispatch+execute wall clock").observe(
                wall, bucket=str(shape), factor=factor)
        registry.histogram(
            "megba_fleet_lane_fill_ratio",
            "Real lanes / dispatched lanes per batch",
            buckets=_metrics.RATIO_BUCKETS).observe(
                n_real / lanes, bucket=str(shape))
        registry.histogram(
            "megba_fleet_edge_fill_ratio",
            "Real edges / padded edge capacity per batch",
            buckets=_metrics.RATIO_BUCKETS).observe(
                edges_real / (lanes * shape.n_edge), bucket=str(shape))

    out: List[Tuple[int, FleetResult]] = []
    for lane, ((orig_i, prob), pp) in enumerate(zip(items, padded)):
        lane_res = _lane_result(result, lane)
        fr = FleetResult(
            name=prob.name,
            shape=shape,
            lane=lane,
            lanes=lanes,
            cameras=np.asarray(lane_res.cameras).T[:pp.n_cam],
            points=np.asarray(lane_res.points).T[:pp.n_pt],
            cost=np.asarray(lane_res.cost),
            initial_cost=np.asarray(lane_res.initial_cost),
            iterations=int(lane_res.iterations),
            accepted=int(lane_res.accepted),
            pcg_iterations=int(lane_res.pcg_iterations),
            status=int(lane_res.status),
            recoveries=int(lane_res.recoveries),
            latency_s=wall,
            trace=lane_res.trace,
            rung=rung,
            attempts=attempts,
            health=prob.health,
        )
        out.append((orig_i, fr))
        if registry is not None:
            registry.histogram(
                "megba_solve_lm_iterations",
                "LM iterations per solved problem",
                buckets=_metrics.ITER_BUCKETS).observe(
                    fr.iterations, bucket=str(shape), factor=factor)
            registry.histogram(
                "megba_solve_pcg_iterations",
                "Total PCG iterations per solved problem",
                buckets=_metrics.ITER_BUCKETS).observe(
                    fr.pcg_iterations, bucket=str(shape), factor=factor)
            registry.counter(
                "megba_solve_status_total",
                "Solve outcomes by SolveStatus name").inc(
                    1, status=fr.status_name, bucket=str(shape))
        if telemetry and jax.process_index() == 0:
            from megba_tpu.observability.report import (
                append_report,
                build_report,
            )

            problem_shape = {
                "num_cameras": pp.n_cam,
                "num_points": pp.n_pt,
                "num_edges": pp.n_edge,
                "num_edges_padded": shape.n_edge,
                "world_size": 1,
            }
            fleet = {
                "name": prob.name,
                "bucket": str(shape),
                "lane": lane,
                "lanes": lanes,
                "batch_problems": n_real,
                "latency_s": wall,
                "batch_problems_per_sec": n_real / wall if wall > 0 else 0.0,
                "rung": rung,
                "attempts": attempts,
                "stats": stats.as_dict(),
            }
            # Federation workers (serving/federation.py) tag every fleet
            # report with their worker id, so a merged multi-worker
            # telemetry stream stays attributable per host.
            fed_worker = os.environ.get("MEGBA_FEDERATION_WORKER")
            if fed_worker:
                fleet["worker"] = fed_worker
            append_report(
                build_report(report_option, lane_res,
                             _phase_delta(phases_before, timer.as_dict()),
                             problem_shape, fleet=fleet,
                             health=prob.health), telemetry)
    return out


def solve_many(
    problems: Sequence[FleetProblem],
    option: Optional[ProblemOption] = None,
    *,
    ladder: Optional[BucketLadder] = None,
    pool: Optional[CompilePool] = None,
    stats: Optional[FleetStats] = None,
    timer: Optional[PhaseTimer] = None,
) -> List[FleetResult]:
    """Solve many independent BA problems through bucketed batched
    programs; results come back in submission order.

    PUBLIC BOUNDARY of the serving layer.  Problems are grouped by
    shape class (ladder-padded (n_cam, n_pt, n_edge, dtype)); each
    group runs as ONE batched dispatch of the vmapped LM program, so a
    fleet of N problems costs `len(buckets)` dispatches, not N — and,
    with a warmed `pool`, zero compilations.  Each result carries the
    problem's own convergence story (`SolveStatus`, cost, trace): one
    slow lane never changes its neighbours' answers (bitwise), it only
    rides the same program longer.

    `ladder` / `pool` / `stats` default to fresh instances; long-lived
    services pass their own so programs, manifests and counters persist
    across calls.  Telemetry (option knob or MEGBA_TELEMETRY) appends
    one SolveReport per PROBLEM with a `fleet` block (bucket, lane,
    batch latency, service counters).
    """
    option = option or ProblemOption()
    _check_option(option)
    for i, p in enumerate(problems):
        _validate_problem(p, i, option)
    option, telemetry, report_option = _strip_telemetry(option)
    warn_if_x64_unavailable(np.dtype(option.dtype))
    ladder = ladder or BucketLadder()
    stats = stats or FleetStats()
    pool = pool or CompilePool(stats=stats)
    timer = PhaseTimer() if timer is None else timer
    from megba_tpu.factors import engine_for

    results: List[Optional[FleetResult]] = [None] * len(problems)
    for (shape, _dims, factor), items in _group_by_bucket(
            problems, option, ladder).items():
        # One engine per factor group (memoised: a factor resolves to
        # ONE engine object process-wide, so a mixed-factor fleet pays
        # exactly one program per (factor, bucket) — the zero-cross-
        # factor-retrace contract the sentinel certifies).
        engine = engine_for(factor, option.jacobian_mode)
        for orig_i, fr in _solve_bucket(
                items, shape, option, engine, ladder, pool, stats, timer,
                telemetry, report_option, factor=factor):
            results[orig_i] = fr
    return results  # type: ignore[return-value]
