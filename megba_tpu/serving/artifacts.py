"""Artifact store: serialized bucket EXECUTABLES for millisecond cold start.

The compile pool (serving/compile_pool.py) makes first-request latency
dispatch-only — but only after someone paid the compiles.  A fresh
replica paying minutes of XLA compile before its first solve is the
cold-start problem this module removes: the whole-program-bundling
move of the Julia→TPU full-AOT line (PAPERS.md, arXiv 1810.09868)
applied to the fleet's bucket programs.

The seam (probed on this jaxlib): `jax.experimental.serialize_executable`
round-trips a `jax.stages.Compiled` through bytes — `serialize` emits
the XLA executable plus the call's pytree defs, `deserialize_and_load`
rebuilds a `Compiled` with ZERO Python tracing and ZERO XLA compile.
A replica warming from artifacts therefore reaches its first solve
without ever invoking the program builders (retrace-sentinel-certified
by the federation worker and tests/test_federation.py), and dispatches
BITWISE the same executable the exporter ran (same XLA bytes).

Store layout: one file per (bucket program, option fingerprint) under a
root directory, named by a content-independent KEY digest so a warming
replica can look artifacts up without an index:

    <root>/<shape>_l<lanes>_<digest16>.megbaexe

File format (the PR 5 checkpoint hardening pattern): an 8-byte magic,
a 16-byte blake2b digest of the body, then the pickled document —
{"schema", "meta", "payload", "in_tree", "out_tree"}.  `load` verifies
magic + digest before unpickling, then checks the recorded environment
(jax / jaxlib versions, backend platform) against the running process.
EVERY failure mode — missing file, truncated/corrupt body, schema or
version mismatch, a deserialize the runtime refuses — degrades to
`None` with a typed warning: the caller falls back to compile (and
refreshes the artifact), never to a wrong or stale answer.

The environment check is deliberately NOT part of the filename key:
a stale artifact must be FOUND and diagnosed (warned, recompiled,
refreshed in place), not silently shadowed by a cache miss.

Two probed jaxlib hazards shaped the bring-up (jax 0.4.37 / jaxlib
0.4.36, XLA:CPU): (1) an executable SATISFIED FROM the persistent
compile cache re-serializes into a blob missing its object code
("Symbols not found" on load in a fresh process) — so every compile
destined for serialization bypasses that cache
(compile_pool._portable_compile_scope); (2) a deserialized executable
with LAPACK custom calls segfaults in a process that never dispatched
those kernels natively — so `load` primes them first
(`_prime_native_kernels`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import warnings
from typing import Any, Dict, List, Optional

_MAGIC = b"MEGBAEXE"
ARTIFACT_SCHEMA = "megba_tpu.fleet_artifact/v1"
_DIGEST_SIZE = 16


class ArtifactWarning(UserWarning):
    """An artifact could not be used; the caller falls back to compile."""


_PRIMED = False
_PRIME_LOCK = __import__("threading").Lock()


def _prime_native_kernels() -> None:
    """Dispatch one tiny Cholesky + triangular solve before the first
    deserialized executable runs.

    Probed jaxlib hazard (jax 0.4.37 / jaxlib 0.4.36, XLA:CPU): a
    deserialized executable whose program contains LAPACK custom calls
    (Cholesky / triangular solve — the Schur block inversions) SEGFAULTS
    in a process that has never dispatched those kernels natively; the
    lazy registration/initialization the first real dispatch performs
    is what the deserialized code path needs and skips.  Importing the
    registration module is NOT enough (probed) — one real dispatch is.
    Toy programs without LAPACK calls round-trip fine unprimed.
    """
    global _PRIMED
    with _PRIME_LOCK:
        if _PRIMED:
            return
        import jax
        import jax.numpy as jnp

        eye = jnp.eye(3, dtype=jnp.float32)
        jax.block_until_ready(jnp.linalg.cholesky(eye))
        jax.block_until_ready(jax.scipy.linalg.solve_triangular(
            eye, jnp.ones(3, dtype=jnp.float32), lower=True))
        _PRIMED = True


@dataclasses.dataclass(frozen=True)
class ArtifactKey:
    """Identity of one serialized bucket program.

    `option_fingerprint` is the retrace sentinel's `static_key(engine,
    option)` — the same string that makes two configs share a jit
    program makes them share an artifact.  The rest mirrors
    `compile_pool.pool_key`'s shape half.
    """

    option_fingerprint: str
    shape: str  # ShapeClass str form (c#_p#_e#_dtype)
    lanes: int
    cd: int
    pd: int
    od: int
    faulted: bool = False

    def digest(self) -> str:
        h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
        h.update(repr(dataclasses.astuple(self)).encode())
        return h.hexdigest()

    def filename(self) -> str:
        return f"{self.shape}_l{self.lanes}_{self.digest()}.megbaexe"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def current_environment() -> Dict[str, str]:
    """The version/backend triple an executable is only valid under.

    XLA executables are not stable across jaxlib releases or backend
    platforms; `load` refuses (with a warning) when any of these
    differ from the recorded values.
    """
    import jax
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
    }


class ArtifactStore:
    """On-disk store of serialized bucket executables.

    Thread-safety: `save` writes are atomic (temp + rename) so
    concurrent exporters converge on a complete file; `load` reads a
    completed file or nothing.  The store keeps no in-memory state, so
    one directory can be shared by an exporting service and any number
    of warming replicas (NFS/GCS-fuse style shared storage in a real
    deployment, a tmpdir in the tests).
    """

    def __init__(self, root: str) -> None:
        self.root = str(root)

    def path_for(self, key: ArtifactKey) -> str:
        return os.path.join(self.root, key.filename())

    # -- export ----------------------------------------------------------
    def save(self, key: ArtifactKey, compiled) -> str:
        """Serialize one `jax.stages.Compiled` under `key` (atomic)."""
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        doc = {
            "schema": ARTIFACT_SCHEMA,
            "meta": {"key": key.to_dict(), "env": current_environment()},
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        body = pickle.dumps(doc)
        digest = hashlib.blake2b(body, digest_size=_DIGEST_SIZE).digest()
        os.makedirs(self.root, exist_ok=True)
        path = self.path_for(key)
        # Unique tmp per saver (mkstemp, not path+'.tmp'): two replicas
        # compile-and-refreshing the same missing bucket concurrently
        # must not truncate each other's half-written file — each
        # writes its own tmp and the atomic replace races are
        # whole-file, so the published artifact is always complete.
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(_MAGIC)
                fh.write(digest)
                fh.write(body)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path

    # -- import ----------------------------------------------------------
    def _read_doc(self, path: str) -> Optional[Dict[str, Any]]:
        """Verified document, or None (warned) on any corruption."""
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            return None  # not present: plain miss, no warning
        head = len(_MAGIC) + _DIGEST_SIZE
        if len(blob) <= head or blob[: len(_MAGIC)] != _MAGIC:
            warnings.warn(
                f"{path}: not a fleet artifact (bad magic or truncated "
                "header); falling back to compile", ArtifactWarning,
                stacklevel=3)
            return None
        digest = blob[len(_MAGIC):head]
        body = blob[head:]
        if hashlib.blake2b(body, digest_size=_DIGEST_SIZE).digest() != digest:
            warnings.warn(
                f"{path}: artifact checksum mismatch (corrupt or "
                "truncated); falling back to compile", ArtifactWarning,
                stacklevel=3)
            return None
        try:
            doc = pickle.loads(body)
        except Exception as exc:
            warnings.warn(
                f"{path}: artifact body failed to unpickle ({exc!r}); "
                "falling back to compile", ArtifactWarning, stacklevel=3)
            return None
        if not isinstance(doc, dict) or doc.get("schema") != ARTIFACT_SCHEMA:
            warnings.warn(
                f"{path}: unknown artifact schema "
                f"{doc.get('schema') if isinstance(doc, dict) else doc!r}; "
                "falling back to compile", ArtifactWarning, stacklevel=3)
            return None
        return doc

    def load(self, key: ArtifactKey):
        """`jax.stages.Compiled` for `key`, or None with a typed warning
        naming why (corruption, version/backend mismatch, runtime
        refusal) — the caller compiles instead, and a later `save`
        refreshes the stale file in place."""
        path = self.path_for(key)
        doc = self._read_doc(path)
        if doc is None:
            return None
        recorded = (doc.get("meta") or {}).get("env") or {}
        env = current_environment()
        mismatched = [
            f"{name}={recorded.get(name)!r} (running {env[name]!r})"
            for name in ("jax", "jaxlib", "backend")
            if recorded.get(name) != env[name]
        ]
        if mismatched:
            warnings.warn(
                f"{path}: artifact was exported under a different "
                f"environment — {', '.join(mismatched)}; falling back to "
                "compile-and-refresh", ArtifactWarning, stacklevel=2)
            return None
        from jax.experimental import serialize_executable

        try:
            _prime_native_kernels()
            return serialize_executable.deserialize_and_load(
                doc["payload"], doc["in_tree"], doc["out_tree"])
        except Exception as exc:
            warnings.warn(
                f"{path}: runtime refused the serialized executable "
                f"({exc!r}); falling back to compile", ArtifactWarning,
                stacklevel=2)
            return None

    # -- introspection ---------------------------------------------------
    def entries(self) -> List[str]:
        """Artifact filenames currently in the store (sorted)."""
        try:
            return sorted(n for n in os.listdir(self.root)
                          if n.endswith(".megbaexe"))
        except OSError:
            return []

    def content_digest(self, key: ArtifactKey) -> Optional[str]:
        """blake2b hexdigest of the verified artifact BODY (the pinned
        round-trip identity tests compare — a re-export of the same
        executable under the same environment is byte-identical)."""
        doc_path = self.path_for(key)
        try:
            with open(doc_path, "rb") as fh:
                blob = fh.read()
        except OSError:
            return None
        head = len(_MAGIC) + _DIGEST_SIZE
        if len(blob) <= head:
            return None
        return hashlib.blake2b(
            blob[head:], digest_size=_DIGEST_SIZE).hexdigest()
