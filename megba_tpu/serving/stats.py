"""FleetStats: service-level counters for the many-problem solver.

The per-solve observability story (SolveTrace / SolveReport /
PhaseTimer) answers "what did THIS solve do"; a fleet service needs the
aggregate view: problems/sec at fixed convergence (the roadmap's
throughput metric — NOT LM iters/sec), how full the shape buckets run,
how much padded work the ladder wastes, and whether the compile pool is
actually absorbing compilations.

One `FleetStats` instance is shared by the batcher, the compile pool
and the dispatch queue; every mutation is lock-protected (the queue's
dispatcher thread and caller threads both touch it).  `as_dict()` is
the JSON view embedded in telemetry SolveReports (the `fleet` field)
and `report()` the human-readable block.

When the metrics plane is armed (`MEGBA_METRICS`), every `record_*`
call ALSO lands in the process metrics registry
(observability/metrics.py) — FleetStats is the one choke point the
queue / pool / resilience machinery already routes through, so the
Prometheus series come for free without touching each call site.  The
gate is one env lookup when off (`_registry()` returns None and never
imports the metrics module).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from megba_tpu import observability as _obs


def _registry():
    return _obs.metrics_registry()


class FleetStats:
    """Aggregate fleet counters; thread-safe; cheap enough to always on."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.problems = 0  # megba: guarded-by(_lock); real problems solved (padding lanes excluded)
        self.batches = 0  # megba: guarded-by(_lock); batched dispatches
        self.solve_seconds = 0.0  # megba: guarded-by(_lock); wall clock inside batched dispatches
        self.lane_slots = 0  # megba: guarded-by(_lock); lanes dispatched, padding lanes included
        self.edge_slots = 0  # megba: guarded-by(_lock); lane-edge slots dispatched (lanes * bucket)
        self.edges_real = 0  # megba: guarded-by(_lock); raw (unpadded) edges across real problems
        self.pool_hits = 0  # megba: guarded-by(_lock); dispatches served by an already-built program
        self.pool_misses = 0  # megba: guarded-by(_lock); dispatches that had to build/compile
        # -- artifact store (serving/artifacts.py): the cold-start split —
        self.artifact_loads = 0  # megba: guarded-by(_lock); buckets warmed from serialized executables
        self.artifact_compiles = 0  # megba: guarded-by(_lock); buckets that paid a real compile
        self.per_bucket: Dict[str, Dict[str, int]] = {}  # megba: guarded-by(_lock)
        # -- resilience counters (serving/resilience.py mechanisms) ------
        self.sheds = 0  # megba: guarded-by(_lock); problems shed before dispatch (deadline expired)
        self.deadline_misses = 0  # megba: guarded-by(_lock); results delivered AFTER their deadline
        self.retries = 0  # megba: guarded-by(_lock); escalation re-enqueues (ladder rungs climbed)
        self.retries_by_rung: Dict[int, int] = {}  # megba: guarded-by(_lock); target rung -> count
        self.rejected = 0  # megba: guarded-by(_lock); submits refused by admission control
        self.breaker_trips = 0  # megba: guarded-by(_lock); bucket breakers opened
        self.breaker_probes = 0  # megba: guarded-by(_lock); half-open probe batches admitted
        self.breaker_recoveries = 0  # megba: guarded-by(_lock); probes that closed the breaker
        self.breaker_fast_fails = 0  # megba: guarded-by(_lock); submits failed fast on a tripped bucket
        self.queue_depth_peak = 0  # megba: guarded-by(_lock); max pending problems ever observed
        # -- pre-flight triage counters (robustness/triage.py) -----------
        self.triage_rejected = 0  # megba: guarded-by(_lock); problems refused with ZERO dispatch
        self.triage_repaired = 0  # megba: guarded-by(_lock); problems auto-repaired before enqueue
        self.triage_warned = 0  # megba: guarded-by(_lock); degenerate problems passed through flagged
        self.triage_points_fixed = 0  # megba: guarded-by(_lock); point blocks frozen by repairs
        self.triage_edges_masked = 0  # megba: guarded-by(_lock); edges soft-deleted by repairs
        self.triage_cams_anchored = 0  # megba: guarded-by(_lock); gauge anchors added by repairs
        self.triage_edges_downweighted = 0  # megba: guarded-by(_lock); robust-downweighted outliers

    # -- recording -------------------------------------------------------
    def record_batch(self, bucket: str, lanes: int, n_real: int,
                     edges_real: int, edge_bucket: int,
                     wall_s: float) -> None:
        with self._lock:
            self.problems += n_real
            self.batches += 1
            self.solve_seconds += wall_s
            self.lane_slots += lanes
            self.edge_slots += lanes * edge_bucket
            self.edges_real += edges_real
            b = self.per_bucket.setdefault(
                bucket, {"problems": 0, "batches": 0, "lane_slots": 0})
            b["problems"] += n_real
            b["batches"] += 1
            b["lane_slots"] += lanes

    def record_pool(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.pool_hits += 1
            else:
                self.pool_misses += 1
        reg = _registry()
        if reg is not None:
            reg.counter(
                "megba_pool_requests_total",
                "Compile-pool program requests by outcome").inc(
                    1, outcome="hit" if hit else "miss")

    def record_artifact(self, loaded: bool) -> None:
        """One bucket warmed: `loaded`=True rode a serialized executable
        (I/O-bound cold start), False paid a trace + XLA compile."""
        with self._lock:
            if loaded:
                self.artifact_loads += 1
            else:
                self.artifact_compiles += 1
        reg = _registry()
        if reg is not None:
            reg.counter(
                "megba_pool_warm_total",
                "Bucket warm-ups: artifact load vs real compile").inc(
                    1, outcome="artifact_load" if loaded else "compile")

    # -- resilience recording (called by FleetQueue under its own lock,
    # but kept self-locking so direct callers stay safe) ----------------
    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.sheds += n
        reg = _registry()
        if reg is not None:
            reg.counter("megba_queue_shed_total",
                        "Problems shed before dispatch").inc(n)

    def record_deadline_miss(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_misses += n
        reg = _registry()
        if reg is not None:
            reg.counter("megba_queue_deadline_misses_total",
                        "Results delivered after their deadline").inc(n)

    def record_retry(self, rung: int) -> None:
        """One problem re-enqueued at escalation rung `rung`."""
        with self._lock:
            self.retries += 1
            self.retries_by_rung[int(rung)] = (
                self.retries_by_rung.get(int(rung), 0) + 1)
        reg = _registry()
        if reg is not None:
            reg.counter("megba_queue_retries_total",
                        "Escalation re-enqueues by target rung").inc(
                            1, rung=int(rung))

    def record_reject(self, n: int = 1) -> None:
        with self._lock:
            self.rejected += n
        reg = _registry()
        if reg is not None:
            reg.counter("megba_queue_rejected_total",
                        "Submits refused by admission control").inc(n)

    def record_breaker(self, event: str) -> None:
        """One breaker transition: trip / probe / recover / fast_fail."""
        field = {"trip": "breaker_trips", "probe": "breaker_probes",
                 "recover": "breaker_recoveries",
                 "fast_fail": "breaker_fast_fails"}.get(event)
        if field is None:
            raise ValueError(f"unknown breaker event {event!r}")
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)
        reg = _registry()
        if reg is not None:
            reg.counter("megba_breaker_events_total",
                        "Circuit-breaker transitions by event").inc(
                            1, event=event)

    def record_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = depth
        reg = _registry()
        if reg is not None:
            g = reg.gauge("megba_queue_depth",
                          "Pending problems in the dispatch queue")
            g.set(depth)
            reg.gauge("megba_queue_depth_peak",
                      "High-water mark of pending problems").max(depth)

    def record_wait(self, bucket: str, wait_s: float) -> None:
        """Submit-to-dispatch wait of one problem (monotonic seconds);
        FleetStats itself keeps no wait state — this exists purely as
        the queue's bridge into the metrics histogram."""
        reg = _registry()
        if reg is not None:
            reg.histogram("megba_queue_wait_seconds",
                          "Submit-to-dispatch wait per problem").observe(
                              wait_s, bucket=bucket)

    def record_triage(self, action: str,
                      repair: Optional[Dict[str, int]] = None) -> None:
        """One triaged problem: `action` is 'rejected' / 'repaired' /
        'warned'; `repair` carries TriageRepair.counters() for repairs."""
        field = {"rejected": "triage_rejected",
                 "repaired": "triage_repaired",
                 "warned": "triage_warned"}.get(action)
        if field is None:
            raise ValueError(f"unknown triage action {action!r}")
        reg = _registry()
        if reg is not None:
            reg.counter("megba_triage_total",
                        "Triaged problems by action").inc(1, action=action)
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)
            if repair:
                self.triage_points_fixed += int(
                    repair.get("points_fixed", 0))
                self.triage_edges_masked += int(
                    repair.get("edges_masked", 0))
                self.triage_cams_anchored += int(
                    repair.get("cams_anchored", 0))
                self.triage_edges_downweighted += int(
                    repair.get("edges_downweighted", 0))

    # -- derived metrics -------------------------------------------------
    def problems_per_sec(self) -> float:
        with self._lock:
            if self.solve_seconds <= 0.0:
                return 0.0
            return self.problems / self.solve_seconds

    def padding_waste(self) -> float:
        """Fraction of dispatched lane-edge slots that carried no real
        edge — the price of the ladder's quantisation (padded edges AND
        whole padding lanes both count as waste)."""
        with self._lock:
            if self.edge_slots == 0:
                return 0.0
            return 1.0 - self.edges_real / self.edge_slots

    def occupancy(self) -> Dict[str, float]:
        """bucket -> mean real problems per dispatched lane slot."""
        with self._lock:
            return {
                k: (b["problems"] / b["lane_slots"] if b["lane_slots"] else 0.0)
                for k, b in self.per_bucket.items()
            }

    def pool_hit_rate(self) -> float:
        with self._lock:
            n = self.pool_hits + self.pool_misses
            return self.pool_hits / n if n else 0.0

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            base = {
                "problems": self.problems,
                "batches": self.batches,
                "solve_seconds": self.solve_seconds,
                "lane_slots": self.lane_slots,
                "edge_slots": self.edge_slots,
                "edges_real": self.edges_real,
                "pool_hits": self.pool_hits,
                "pool_misses": self.pool_misses,
                "artifact_loads": self.artifact_loads,
                "artifact_compiles": self.artifact_compiles,
                "per_bucket": {k: dict(v)
                               for k, v in self.per_bucket.items()},
                "sheds": self.sheds,
                "deadline_misses": self.deadline_misses,
                "retries": self.retries,
                "retries_by_rung": {str(k): v for k, v
                                    in self.retries_by_rung.items()},
                "rejected": self.rejected,
                "breaker_trips": self.breaker_trips,
                "breaker_probes": self.breaker_probes,
                "breaker_recoveries": self.breaker_recoveries,
                "breaker_fast_fails": self.breaker_fast_fails,
                "queue_depth_peak": self.queue_depth_peak,
                "triage_rejected": self.triage_rejected,
                "triage_repaired": self.triage_repaired,
                "triage_warned": self.triage_warned,
                "triage_points_fixed": self.triage_points_fixed,
                "triage_edges_masked": self.triage_edges_masked,
                "triage_cams_anchored": self.triage_cams_anchored,
                "triage_edges_downweighted": self.triage_edges_downweighted,
            }
        base["problems_per_sec"] = self.problems_per_sec()
        base["padding_waste"] = self.padding_waste()
        base["bucket_occupancy"] = self.occupancy()
        base["pool_hit_rate"] = self.pool_hit_rate()
        return base

    def report(self) -> str:
        d = self.as_dict()
        lines = [
            f"fleet: {d['problems']} problems in {d['batches']} batches "
            f"({d['solve_seconds']:.3f}s solve wall, "
            f"{d['problems_per_sec']:.1f} problems/s)",
            f"  padding waste: {100 * d['padding_waste']:.1f}% of "
            f"lane-edge slots",
            f"  compile pool: {d['pool_hits']} hits / {d['pool_misses']} "
            f"misses ({100 * d['pool_hit_rate']:.0f}% hit rate)",
        ]
        if d["artifact_loads"] or d["artifact_compiles"]:
            lines.append(
                f"  artifact store: {d['artifact_loads']} loaded / "
                f"{d['artifact_compiles']} compiled")
        if (d["sheds"] or d["retries"] or d["rejected"]
                or d["deadline_misses"] or d["breaker_trips"]
                or d["breaker_fast_fails"]):
            lines.append(
                f"  resilience: {d['retries']} retries, {d['sheds']} shed, "
                f"{d['deadline_misses']} deadline-missed, "
                f"{d['rejected']} rejected; breaker: {d['breaker_trips']} "
                f"trips / {d['breaker_probes']} probes / "
                f"{d['breaker_recoveries']} recoveries / "
                f"{d['breaker_fast_fails']} fast-fails "
                f"(peak depth {d['queue_depth_peak']})")
        if d["triage_rejected"] or d["triage_repaired"] or d["triage_warned"]:
            lines.append(
                f"  triage: {d['triage_rejected']} rejected / "
                f"{d['triage_repaired']} repaired / "
                f"{d['triage_warned']} warned "
                f"({d['triage_points_fixed']} points fixed, "
                f"{d['triage_edges_masked']} edges masked, "
                f"{d['triage_cams_anchored']} cams anchored, "
                f"{d['triage_edges_downweighted']} edges downweighted)")
        for bucket, occ in sorted(d["bucket_occupancy"].items()):
            b = d["per_bucket"][bucket]
            lines.append(
                f"  {bucket}: {b['problems']} problems / "
                f"{b['batches']} batches, occupancy {100 * occ:.0f}%")
        return "\n".join(lines)
