"""FleetStats: service-level counters for the many-problem solver.

The per-solve observability story (SolveTrace / SolveReport /
PhaseTimer) answers "what did THIS solve do"; a fleet service needs the
aggregate view: problems/sec at fixed convergence (the roadmap's
throughput metric — NOT LM iters/sec), how full the shape buckets run,
how much padded work the ladder wastes, and whether the compile pool is
actually absorbing compilations.

One `FleetStats` instance is shared by the batcher, the compile pool
and the dispatch queue; every mutation is lock-protected (the queue's
dispatcher thread and caller threads both touch it).  `as_dict()` is
the JSON view embedded in telemetry SolveReports (the `fleet` field)
and `report()` the human-readable block.
"""

from __future__ import annotations

import threading
from typing import Any, Dict


class FleetStats:
    """Aggregate fleet counters; thread-safe; cheap enough to always on."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.problems = 0  # real problems solved (padding lanes excluded)
        self.batches = 0  # batched dispatches
        self.solve_seconds = 0.0  # wall clock inside batched dispatches
        self.lane_slots = 0  # lanes dispatched, padding lanes included
        self.edge_slots = 0  # lane-edge slots dispatched (lanes * bucket)
        self.edges_real = 0  # raw (unpadded) edges across real problems
        self.pool_hits = 0  # dispatches served by an already-built program
        self.pool_misses = 0  # dispatches that had to build/compile
        self.per_bucket: Dict[str, Dict[str, int]] = {}

    # -- recording -------------------------------------------------------
    def record_batch(self, bucket: str, lanes: int, n_real: int,
                     edges_real: int, edge_bucket: int,
                     wall_s: float) -> None:
        with self._lock:
            self.problems += n_real
            self.batches += 1
            self.solve_seconds += wall_s
            self.lane_slots += lanes
            self.edge_slots += lanes * edge_bucket
            self.edges_real += edges_real
            b = self.per_bucket.setdefault(
                bucket, {"problems": 0, "batches": 0, "lane_slots": 0})
            b["problems"] += n_real
            b["batches"] += 1
            b["lane_slots"] += lanes

    def record_pool(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.pool_hits += 1
            else:
                self.pool_misses += 1

    # -- derived metrics -------------------------------------------------
    def problems_per_sec(self) -> float:
        with self._lock:
            if self.solve_seconds <= 0.0:
                return 0.0
            return self.problems / self.solve_seconds

    def padding_waste(self) -> float:
        """Fraction of dispatched lane-edge slots that carried no real
        edge — the price of the ladder's quantisation (padded edges AND
        whole padding lanes both count as waste)."""
        with self._lock:
            if self.edge_slots == 0:
                return 0.0
            return 1.0 - self.edges_real / self.edge_slots

    def occupancy(self) -> Dict[str, float]:
        """bucket -> mean real problems per dispatched lane slot."""
        with self._lock:
            return {
                k: (b["problems"] / b["lane_slots"] if b["lane_slots"] else 0.0)
                for k, b in self.per_bucket.items()
            }

    def pool_hit_rate(self) -> float:
        with self._lock:
            n = self.pool_hits + self.pool_misses
            return self.pool_hits / n if n else 0.0

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            base = {
                "problems": self.problems,
                "batches": self.batches,
                "solve_seconds": self.solve_seconds,
                "lane_slots": self.lane_slots,
                "edge_slots": self.edge_slots,
                "edges_real": self.edges_real,
                "pool_hits": self.pool_hits,
                "pool_misses": self.pool_misses,
                "per_bucket": {k: dict(v)
                               for k, v in self.per_bucket.items()},
            }
        base["problems_per_sec"] = self.problems_per_sec()
        base["padding_waste"] = self.padding_waste()
        base["bucket_occupancy"] = self.occupancy()
        base["pool_hit_rate"] = self.pool_hit_rate()
        return base

    def report(self) -> str:
        d = self.as_dict()
        lines = [
            f"fleet: {d['problems']} problems in {d['batches']} batches "
            f"({d['solve_seconds']:.3f}s solve wall, "
            f"{d['problems_per_sec']:.1f} problems/s)",
            f"  padding waste: {100 * d['padding_waste']:.1f}% of "
            f"lane-edge slots",
            f"  compile pool: {d['pool_hits']} hits / {d['pool_misses']} "
            f"misses ({100 * d['pool_hit_rate']:.0f}% hit rate)",
        ]
        for bucket, occ in sorted(d["bucket_occupancy"].items()):
            b = d["per_bucket"][bucket]
            lines.append(
                f"  {bucket}: {b['problems']} problems / "
                f"{b['batches']} batches, occupancy {100 * occ:.0f}%")
        return "\n".join(lines)
