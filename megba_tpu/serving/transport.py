"""Federation transport: integrity-checked frames over pipes or TCP.

The federation RPC (serving/federation.py) used to speak bare
`>Q`-length + pickle frames over subprocess pipes.  This module is the
transport seam underneath it, grown for multi-host fleets:

- **One frame format, two carriers.**  Every frame is
  `magic(4) | payload_length(>Q, 8) | blake2b-128 digest(16) | payload`.
  `PipeTransport` (the old `FrameChannel`, renamed) runs it over a
  (read fd, write file) pair; `TcpTransport` runs the SAME bytes over a
  socket — `encode_frame` is shared, so a frame captured off a pipe is
  byte-identical to the one a socket would carry (the round-trip
  equivalence the codec tests pin).

- **Typed integrity failures.**  A real network truncates, corrupts
  and desyncs; unpickling garbage is how a service dies confusingly.
  Each header field fails its own way, naming observed vs expected
  bytes: `FrameMagicError` (desync / foreign peer), `FrameLengthError`
  (corrupted length = allocation bomb), `FrameDigestError` (payload
  corruption), `FrameTruncatedError` (connection cut mid-frame, with
  byte counts).  All subclass `FrameError`, so every existing
  `except FrameError:` site handles the new failure taxonomy unchanged.

- **Supervision policy + handshake.**  `ReconnectPolicy` is the
  capped-exponential-backoff window a dropped connection gets before it
  converts to a worker loss — deterministic seeded jitter, the exact
  `EscalationPolicy.backoff_s` stance (PR 8): reconnect storms
  de-synchronise yet replay bitwise under a fixed seed.  The
  register/ack handshake authenticates BOTH directions with a keyed
  HMAC over a shared token and refuses protocol-version or
  environment-fingerprint drift typed (`HandshakeError` names the
  field, observed, expected) — a worker built against a different
  jaxlib must be refused at the door, not discovered as a bitwise
  mismatch three dispatches later.

- **Idempotent resend support.**  `DedupCache` is the worker-side
  reply cache keyed by per-request sequence id: a router that resends
  after a reconnect gets the CACHED reply for work the worker already
  did — a retry can never double-solve.

Trust model: the token handshake gates fleet MEMBERSHIP (who may
register, who may command), not payload safety — frames are pickle, so
the fabric is for trusted networks (loopback, a private cluster
subnet), same as any pickle-RPC tier.

All timing here flows through `utils.timing.monotonic_s` — this module
and `robustness/netfaults.py` are strict raw-clock lint territory
(even `time.monotonic` is banned; the deadline arithmetic below must
share the clock the supervision state machine reads).
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import os
import pickle
import select
import socket
import struct
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from megba_tpu.utils.timing import monotonic_s

MAGIC = b"MGB2"
_LEN = struct.Struct(">Q")
_DIGEST_SIZE = 16
HEADER_SIZE = len(MAGIC) + _LEN.size + _DIGEST_SIZE
_MAX_FRAME = 1 << 34  # 16 GiB: a corrupted length header fails fast

#: Bumped whenever the frame format or the RPC op vocabulary changes
#: incompatibly; the handshake refuses a mismatch typed.
PROTOCOL_VERSION = 2

#: Key of the heartbeat frames that ride the channel between replies.
HEARTBEAT_KEY = "__hb__"


class FrameError(ConnectionError):
    """The RPC stream ended or produced a malformed frame."""


class FrameMagicError(FrameError):
    """Frame header does not start with the protocol magic: the stream
    desynchronised, or the peer is not a megba federation endpoint."""

    def __init__(self, observed: bytes) -> None:
        self.observed = bytes(observed)
        self.expected = MAGIC
        super().__init__(
            f"bad frame magic: observed {self.observed!r}, expected "
            f"{MAGIC!r} (stream desync or non-protocol peer)")


class FrameLengthError(FrameError):
    """Declared payload length exceeds the sanity cap — a corrupted
    header must fail fast, not allocate gigabytes."""

    def __init__(self, length: int) -> None:
        self.length = int(length)
        self.cap = _MAX_FRAME
        super().__init__(
            f"frame length {self.length} exceeds sanity cap "
            f"{_MAX_FRAME} (corrupted header / length bomb)")


class FrameDigestError(FrameError):
    """Payload bytes do not match the header digest: corruption in
    flight; the payload is never unpickled."""

    def __init__(self, observed: str, expected: str) -> None:
        self.observed = observed
        self.expected = expected
        super().__init__(
            f"frame digest mismatch: payload hashed to {observed}, "
            f"header declared {expected} (payload corrupted in flight)")


class FrameTruncatedError(FrameError):
    """The stream closed with a partial frame in the buffer."""

    def __init__(self, got: int, need: int, where: str) -> None:
        self.got = int(got)
        self.need = int(need)
        super().__init__(
            f"stream closed mid-frame ({where}): got {self.got} of "
            f"{self.need} bytes")


def encode_frame(obj: Any) -> bytes:
    """Serialize one object to its on-wire frame bytes (carrier
    independent: pipes and sockets ship exactly these bytes)."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).digest()
    return MAGIC + _LEN.pack(len(payload)) + digest + payload


def check_header(header: bytes) -> Tuple[int, bytes]:
    """Validate a 28-byte frame header; return (payload_len, digest)."""
    if len(header) != HEADER_SIZE:
        raise FrameTruncatedError(len(header), HEADER_SIZE, "header")
    if header[:len(MAGIC)] != MAGIC:
        raise FrameMagicError(header[:len(MAGIC)])
    (length,) = _LEN.unpack(header[len(MAGIC):len(MAGIC) + _LEN.size])
    if length > _MAX_FRAME:
        raise FrameLengthError(length)
    return int(length), header[len(MAGIC) + _LEN.size:]


def check_payload(payload: bytes, digest: bytes) -> Any:
    """Verify payload bytes against the header digest, then unpickle."""
    observed = hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).digest()
    if observed != digest:
        raise FrameDigestError(observed.hex(), digest.hex())
    return pickle.loads(payload)


def decode_frame(data: bytes) -> Any:
    """Decode one complete frame from raw bytes (the codec tests' and
    forensic tooling's entry; transports stream instead)."""
    length, digest = check_header(data[:HEADER_SIZE])
    body = data[HEADER_SIZE:]
    if len(body) < length:
        raise FrameTruncatedError(len(body), length, "payload")
    return check_payload(body[:length], digest)


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class Transport:
    """One duplex frame stream: `send(obj)` / `recv() -> obj` / `close`.

    `recv` reads the UNDERLYING fd directly (private buffer, never a
    BufferedReader) so the select-based timeout/poll path can never
    stall on bytes hidden in a Python-level buffer.  `poll` is called
    between read slices and may raise to abort the wait (the router's
    liveness hook).  ONE deadline spans header + body: a peer stalling
    between the two must not double the effective watchdog budget.

    Sends are serialized under an internal lock so a heartbeat thread
    and a request sender can share the channel without interleaving
    frame bytes; the lock is never held across any blocking read.
    """

    def __init__(self) -> None:
        self._buf = bytearray()
        self._slice_s = 0.05
        self._send_lock = threading.Lock()

    # -- carrier hooks (subclass responsibility) ------------------------
    def _read_fd(self) -> int:
        raise NotImplementedError

    def _read_chunk(self) -> bytes:
        """Read up to ~1 MiB; b'' means EOF.  Only called readable."""
        raise NotImplementedError

    def _write_bytes(self, data: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    # -- frame API -------------------------------------------------------
    def send(self, obj: Any) -> None:
        frame = encode_frame(obj)
        with self._send_lock:
            self._write_bytes(frame)

    def _fill(self, need: int, deadline: Optional[float],
              poll: Optional[Callable[[], None]], where: str) -> None:
        while len(self._buf) < need:
            if poll is not None:
                poll()
            if deadline is not None and monotonic_s() > deadline:
                raise TimeoutError("no complete frame within the budget")
            ready, _, _ = select.select([self._read_fd()], [], [],
                                        self._slice_s)
            if not ready:
                continue
            try:
                chunk = self._read_chunk()
            except BlockingIOError:  # spurious readability
                continue
            if not chunk:
                if self._buf:
                    raise FrameTruncatedError(len(self._buf), need, where)
                raise FrameError("stream closed")
            self._buf.extend(chunk)

    def recv(self, timeout_s: Optional[float] = None,
             poll: Optional[Callable[[], None]] = None) -> Any:
        deadline = None if timeout_s is None else (
            monotonic_s() + timeout_s)
        self._fill(HEADER_SIZE, deadline, poll, "header")
        length, digest = check_header(bytes(self._buf[:HEADER_SIZE]))
        del self._buf[:HEADER_SIZE]
        self._fill(length, deadline, poll, "payload")
        body = bytes(self._buf[:length])
        del self._buf[:length]
        return check_payload(body, digest)


class PipeTransport(Transport):
    """Frame stream over a (read file, write file) pair — the original
    `FrameChannel`, carrying the upgraded integrity-checked frames."""

    def __init__(self, rfile, wfile) -> None:
        super().__init__()
        self._rfd = rfile.fileno()
        self._rfile = rfile  # owned: kept for close()
        self._wfile = wfile

    def _read_fd(self) -> int:
        return self._rfd

    def _read_chunk(self) -> bytes:
        return os.read(self._rfd, 1 << 20)

    def _write_bytes(self, data: bytes) -> None:
        self._wfile.write(data)
        self._wfile.flush()

    def close(self) -> None:
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass


class TcpTransport(Transport):
    """Frame stream over a connected TCP socket.

    `TCP_NODELAY` is set: frames are request/response units, and a
    40 ms Nagle stall on every small control frame would dominate
    heartbeat and handshake latency.
    """

    def __init__(self, sock: socket.socket) -> None:
        super().__init__()
        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (socketpair in tests)
        # Bound sendall: a partitioned peer that stops ACKing would
        # otherwise block a send forever WHILE the sender holds its
        # request lock.  30s is past any healthy send; the resulting
        # socket.timeout is an OSError, i.e. the normal send-failure
        # path (reads never hit it — they recv only after select says
        # readable).
        sock.settimeout(30.0)
        self._closed = False

    def _read_fd(self) -> int:
        return self._sock.fileno()

    def _read_chunk(self) -> bytes:
        return self._sock.recv(1 << 20)

    def _write_bytes(self, data: bytes) -> None:
        self._sock.sendall(data)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def parse_address(addr: str) -> Tuple[str, int]:
    """'host:port' (or '[v6addr]:port') -> (host, port), typed on
    malformed input."""
    host, sep, port = addr.rpartition(":")
    if not sep or not host:
        raise ValueError(f"address must be 'host:port', got {addr!r}")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(
            f"address port must be an integer, got {addr!r}") from None


# ---------------------------------------------------------------------------
# Reconnect policy (the PR 8 backoff stance, applied to connections)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReconnectPolicy:
    """Capped-exponential-backoff reconnect window for a dropped
    connection.

    A connection loss is NOT a worker loss: the worker process may be
    fine behind a flapping link.  The dropped side retries with backoff
    `min(base_s * factor**(attempt-1), cap_s)`, jittered by a
    DETERMINISTIC factor in [1-jitter, 1+jitter] seeded from
    (`seed`, connection key, attempt) — reconnect storms across a fleet
    de-synchronise, yet a fixed seed replays the exact schedule (the
    `EscalationPolicy.backoff_s` stance).  `window_s` bounds the whole
    window on the SUPERVISOR's clock: only its exhaustion (or process
    death) converts the connection loss into a `WorkerLostError`.
    """

    max_attempts: int = 8
    base_s: float = 0.05
    factor: float = 2.0
    cap_s: float = 2.0
    jitter: float = 0.5
    window_s: float = 30.0
    seed: int = 0

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_s < 0:
            raise ValueError("base_s must be >= 0")
        if self.factor < 1.0:
            raise ValueError("factor must be >= 1")
        if self.cap_s < self.base_s:
            raise ValueError("cap_s must be >= base_s")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(
                f"jitter must be in [0, 1), got {self.jitter}")
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")

    def backoff_s(self, key: int, attempt: int) -> float:
        """Deterministic-jittered backoff before reconnect `attempt`
        (>= 1) of connection `key` (e.g. a worker rank)."""
        if attempt < 1:
            raise ValueError(f"attempt must be >= 1, got {attempt}")
        base = min(self.base_s * self.factor ** (attempt - 1), self.cap_s)
        if self.jitter == 0.0 or base == 0.0:
            return base
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(key), int(attempt)]))
        factor = 1.0 + self.jitter * (2.0 * float(rng.random()) - 1.0)
        return base * factor


# ---------------------------------------------------------------------------
# Registration handshake
# ---------------------------------------------------------------------------


class HandshakeError(ConnectionError):
    """Registration refused: the peer drifted on `field` (token,
    protocol version, or an environment-fingerprint component)."""

    def __init__(self, field: str, observed: Any, expected: Any) -> None:
        self.field = field
        self.observed = observed
        self.expected = expected
        super().__init__(
            f"federation handshake refused: {field} drift "
            f"(observed {observed!r}, expected {expected!r})")


def _mac(token: Optional[str], purpose: str, worker_id: str) -> str:
    key = (token or "").encode()
    msg = f"megba-fed-v{PROTOCOL_VERSION}:{purpose}:{worker_id}".encode()
    return hmac.new(key, msg, hashlib.blake2b).hexdigest()


def register_frame(worker_id: str, token: Optional[str],
                   incarnation: int, pid: int,
                   env: Dict[str, str]) -> Dict[str, Any]:
    """The worker's first frame on any (re)connection."""
    return {
        "op": "register",
        "worker_id": worker_id,
        "protocol": PROTOCOL_VERSION,
        "mac": _mac(token, "register", worker_id),
        "incarnation": int(incarnation),
        "pid": int(pid),
        "env": dict(env),
    }


def verify_register(reg: Dict[str, Any], token: Optional[str],
                    env: Dict[str, str]) -> str:
    """Validate a register frame against this router's expectations;
    returns the worker id, or raises `HandshakeError` naming the
    drifted field.  Token first: an unauthenticated peer learns nothing
    about our protocol or environment from the refusal."""
    if not isinstance(reg, dict) or reg.get("op") != "register":
        raise HandshakeError("op", (reg or {}).get("op")
                             if isinstance(reg, dict) else type(reg),
                             "register")
    wid = str(reg.get("worker_id", ""))
    if not wid:
        raise HandshakeError("worker_id", reg.get("worker_id"),
                             "a non-empty id")
    expected_mac = _mac(token, "register", wid)
    if not hmac.compare_digest(str(reg.get("mac", "")), expected_mac):
        raise HandshakeError("token", "<mac mismatch>", "<shared token>")
    if reg.get("protocol") != PROTOCOL_VERSION:
        raise HandshakeError("protocol", reg.get("protocol"),
                             PROTOCOL_VERSION)
    peer_env = reg.get("env") or {}
    for field in sorted(set(env) | set(peer_env)):
        if peer_env.get(field) != env.get(field):
            raise HandshakeError(f"env:{field}", peer_env.get(field),
                                 env.get(field))
    return wid


def ack_frame(op: str, token: Optional[str], worker_id: str,
              **extra: Any) -> Dict[str, Any]:
    """Router's reply to a register: `config` (first join) or `resume`
    (reconnect), MAC'd so the worker can verify the router too."""
    out = {"op": op, "mac": _mac(token, f"ack:{op}", worker_id)}
    out.update(extra)
    return out


def verify_ack(ack: Dict[str, Any], token: Optional[str],
               worker_id: str) -> str:
    """Worker-side check of the router's ack; returns the ack op."""
    if not isinstance(ack, dict):
        raise HandshakeError("ack", type(ack), "a dict frame")
    op = ack.get("op")
    if op == "refused":
        raise HandshakeError(str(ack.get("field", "?")),
                             ack.get("observed"), ack.get("expected"))
    if op not in ("config", "resume"):
        raise HandshakeError("ack-op", op, "config|resume")
    expected_mac = _mac(token, f"ack:{op}", worker_id)
    if not hmac.compare_digest(str(ack.get("mac", "")), expected_mac):
        raise HandshakeError("router-token", "<mac mismatch>",
                             "<shared token>")
    return str(op)


def refusal_frame(exc: HandshakeError) -> Dict[str, Any]:
    return {"op": "refused", "field": exc.field,
            "observed": exc.observed, "expected": exc.expected}


# ---------------------------------------------------------------------------
# Worker-side reply dedup (idempotent resend support)
# ---------------------------------------------------------------------------


class DedupCache:
    """Bounded seq -> reply cache: the idempotent-resend half of the
    no-double-solve contract.

    The worker stores every reply here (keyed by the request's sequence
    id) BEFORE sending it; a resent request after a reconnect returns
    the cached reply instead of re-executing.  Capacity-bounded FIFO:
    the router's lockstep protocol keeps at most a handful of requests
    outstanding, so a small cache covers every legal resend while
    bounding memory on a long-lived worker.
    """

    def __init__(self, capacity: int = 64) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._cache: "OrderedDict[int, Any]" = OrderedDict()  # megba: guarded-by(_lock)
        self.hits = 0  # megba: guarded-by(_lock); resends served from cache

    def get(self, seq: int) -> Optional[Any]:
        with self._lock:
            reply = self._cache.get(int(seq))
            if reply is not None:
                self.hits += 1
            return reply

    def put(self, seq: int, reply: Any) -> None:
        with self._lock:
            self._cache[int(seq)] = reply
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)

    def hit_count(self) -> int:
        with self._lock:
            return self.hits


def heartbeat_frame(count: int, worker_id: str) -> Dict[str, Any]:
    return {HEARTBEAT_KEY: int(count), "worker_id": worker_id}


def is_heartbeat(frame: Any) -> bool:
    return isinstance(frame, dict) and HEARTBEAT_KEY in frame
