"""Serving layer: megba_tpu as a many-problem solver service.

The solver library (solve.flat_solve) makes ONE problem saturate the
hardware; this package makes THOUSANDS of independent small-to-mid
problems do it:

- shape_class.py — canonical padded buckets (a configurable
  power-of-two ladder) so a heterogeneous fleet maps onto a small,
  closed set of compiled programs; padding is bitwise-exact no-op work.
- batcher.py — `solve_many`: stack a bucket's problems on a leading
  lane axis and drive one jitted vmapped LM solve with per-lane
  convergence masking and per-problem SolveStatus/trace fan-out.
- compile_pool.py — bucket programs AOT-precompiled at service start
  from persisted warmup manifests; first-request latency is
  dispatch-only.
- queue.py — `FleetQueue`: async submission with Future handles and
  deadline-based batch flush (max-wait / max-batch knobs).
- resilience.py — the policy layer that makes the service survive bad
  outcomes: per-problem deadlines (shed before dispatch, flagged
  after), a bounded retry-with-escalation ladder (`EscalationPolicy`),
  admission control (`RejectPolicy` + max_pending), and a per-bucket
  circuit breaker with half-open probes.
- stats.py — `FleetStats`: problems/sec at fixed convergence, bucket
  occupancy, padding waste, compile-pool hit rate, plus the resilience
  counters (sheds, retries, rejections, breaker transitions).
- artifacts.py — `ArtifactStore`: bucket EXECUTABLES serialized to
  disk (jax AOT export), so a fresh replica warms its working set in
  milliseconds of I/O instead of minutes of compile; stale/corrupt
  artifacts fall back to compile-and-refresh with typed warnings.
- federation.py — `FleetRouter`: the scale-OUT tier — N worker
  processes each running this whole stack, submissions sharded by
  shape class (occupancy-aware), idle workers stealing hot buckets
  they have warm, dead workers detected (PR 9 heartbeats + pipe EOF)
  and their problems rerouted to survivors, typed and counted.
- transport.py — the frame wire under the RPC: magic + length +
  blake2b-digest framed pickles over pipes (`PipeTransport`) or
  sockets (`TcpTransport`), typed `FrameError`s for every corruption
  mode, the register/ack token handshake, `ReconnectPolicy` backoff,
  and the worker-side `DedupCache` that makes resends idempotent.
- worker.py — the worker half of the federation RPC: `WorkerRuntime`
  (transport-free solver state + request handling) plus the
  `python -m megba_tpu.serving.worker` TCP bootstrap CLI
  (dial/listen, re-registration after connection loss).
"""

from megba_tpu.serving.artifacts import ArtifactKey, ArtifactStore
from megba_tpu.serving.batcher import FleetProblem, FleetResult, solve_many
from megba_tpu.serving.compile_pool import (
    CompilePool,
    ManifestMismatch,
    lower_bucket,
)
from megba_tpu.serving.federation import (
    ColdDispatchWarning,
    FederationStats,
    FleetRouter,
    RoutingTable,
    WorkerLostError,
)
from megba_tpu.serving.queue import FleetQueue
from megba_tpu.serving.resilience import (
    BreakerPolicy,
    BreakerState,
    BucketTripped,
    CircuitBreaker,
    DeadlineExceeded,
    EscalationPolicy,
    QueueRejected,
    RejectPolicy,
)
from megba_tpu.serving.shape_class import (
    BucketLadder,
    PaddedProblem,
    ShapeClass,
    classify,
    pad_to_class,
)
from megba_tpu.serving.stats import FleetStats
from megba_tpu.serving.transport import (
    DedupCache,
    FrameDigestError,
    FrameError,
    FrameLengthError,
    FrameMagicError,
    FrameTruncatedError,
    HandshakeError,
    PipeTransport,
    ReconnectPolicy,
    TcpTransport,
)
from megba_tpu.serving.worker import WorkerRuntime

__all__ = [
    "ArtifactKey",
    "ArtifactStore",
    "BreakerPolicy",
    "BreakerState",
    "BucketLadder",
    "BucketTripped",
    "CircuitBreaker",
    "ColdDispatchWarning",
    "CompilePool",
    "DeadlineExceeded",
    "DedupCache",
    "EscalationPolicy",
    "FederationStats",
    "FleetProblem",
    "FleetQueue",
    "FleetResult",
    "FleetRouter",
    "FleetStats",
    "FrameDigestError",
    "FrameError",
    "FrameLengthError",
    "FrameMagicError",
    "FrameTruncatedError",
    "HandshakeError",
    "ManifestMismatch",
    "PaddedProblem",
    "PipeTransport",
    "QueueRejected",
    "ReconnectPolicy",
    "RejectPolicy",
    "RoutingTable",
    "ShapeClass",
    "TcpTransport",
    "WorkerLostError",
    "WorkerRuntime",
    "classify",
    "lower_bucket",
    "pad_to_class",
    "solve_many",
]
