"""Compile pool: the batched LM program, AOT-precompiled per bucket.

One vmapped, jitted LM solve serves every problem of a shape bucket
(serving/shape_class.py).  This module owns that program:

- `batched_solve_program` builds the jitted `vmap`'d `lm_solve` for an
  (engine, option) pair — ONE callable per configuration, memoised
  module-level exactly like `solve._cached_single_solve`, so repeated
  batches can never rebuild it around a fresh closure (the silent
  retrace bug the sentinel polices).
- `CompilePool.program(...)` hands the batcher a callable for a
  (shape class, lanes) bucket.  If the bucket was warmed, that callable
  IS the AOT `jax.stages.Compiled` executable — dispatch-only latency,
  no tracing on the request path.  Otherwise the shared jitted callable
  compiles on first dispatch and the pool records the bucket as ready.
- `CompilePool.warm(...)` AOT-lowers + compiles buckets from abstract
  `jax.ShapeDtypeStruct`s — no problem data needed — through the same
  builder the dispatch path uses, so what the pool warms is
  byte-for-byte the program requests will run.  With the persistent
  compile cache enabled (utils/backend.enable_persistent_compile_cache)
  the XLA compile itself is a disk hit across service restarts.
- Warmup manifests (`save_manifest` / `warm_from_manifest`) persist the
  observed buckets as JSON so a restarted service precompiles its whole
  working set before taking traffic.

The AOT store is MODULE-level (shared by every pool instance in the
process): two pools warming/dispatching the same bucket must reuse one
trace, or the retrace sentinel would rightly flag the duplicate.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from megba_tpu.algo.lm import lm_solve
from megba_tpu.analysis.retrace import static_key, traced
from megba_tpu.serving.shape_class import ShapeClass

MANIFEST_SCHEMA = "megba_tpu.fleet_manifest/v1"

# (engine, option, shape, lanes, cd, pd, od) -> jax.stages.Compiled
_AOT: Dict[Tuple, Any] = {}
# keys already compiled through the jitted dispatch path (jit-cache hot)
_DISPATCHED: set = set()
# keys a warm() is compiling right now (reservation against duplicate
# AOT compiles when warms race each other)
_WARMING: set = set()
_LOCK = threading.Lock()


def _build_batched_solve(residual_jac_fn, option, faulted=False):
    """The batched mega-solve: `vmap`'d LM over a leading problem axis.

    Every lane carries its own problem (parameters, observations,
    indices, masks); the trust-region start state is shared (fresh
    solves).  Per-lane convergence masking falls out of JAX's
    while_loop batching rule: a lane whose `cond` has gone False keeps
    its carry through a per-lane select — it freezes BITWISE while the
    other lanes keep iterating — and the loop runs until every lane's
    predicate clears.  Per-lane `SolveStatus`, trace and cost come back
    as leading-axis stacks on the returned LMResult pytree.

    `faulted=True` builds the CHAOS variant: a per-lane
    `robustness.faults.FaultPlan` pytree (stacked on the lane axis,
    in_axes=0) rides as one extra operand, so a poisoned lane and its
    inert batch-mates share a single compiled program — the serving
    chaos harness's isolation contract lives on this path.  It is a
    separate retrace-sentinel site (`serving.batched_faulted`) so the
    <=1-compile-per-bucket certification stays per-variant.

    The parameter stacks are donated (same rationale as
    solve._build_single_solve): the batcher stacks fresh operands per
    batch and never reads them back.
    """

    def one(cameras, points, obs, cam_idx, pt_idx, mask, cam_fixed,
            pt_fixed, init_region, init_v, fault_plan=None):
        return lm_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx, mask,
            option, cam_fixed=cam_fixed, pt_fixed=pt_fixed,
            cam_sorted=True, initial_region=init_region,
            initial_v=init_v, fault_plan=fault_plan)

    if faulted:
        batched = jax.vmap(
            one, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, 0))
        site = "serving.batched_faulted"
    else:
        batched = jax.vmap(one,
                           in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None))
        site = "serving.batched"
    return jax.jit(
        traced(site, batched,
               static=static_key(residual_jac_fn, option, site)),
        donate_argnums=(0, 1))


# Long-lived engines only (make_residual_jacobian_fn is itself memoised,
# so the default BAL engines qualify); mirrors _cached_single_solve.
_cached_batched_solve = functools.lru_cache(maxsize=64)(_build_batched_solve)


def batched_solve_program(residual_jac_fn, option, faulted=False):
    """Call-shape-normalising front for the lru cache: positional,
    keyword and defaulted spellings of `faulted` must hit ONE entry (the
    same double-cache footgun make_residual_jacobian_fn fixed in PR 6 —
    two entries would mean two jit wrappers and a duplicate trace)."""
    return _cached_batched_solve(residual_jac_fn, option, bool(faulted))


def _abstract_args(shape: ShapeClass, lanes: int, cd: int, pd: int,
                   od: int, faulted: bool = False) -> Tuple:
    """ShapeDtypeStructs matching the batcher's operand layout
    (feature-major stacks, leading lane axis)."""
    dt = np.dtype(shape.dtype)
    s = jax.ShapeDtypeStruct
    args = (
        s((lanes, cd, shape.n_cam), dt),  # cameras
        s((lanes, pd, shape.n_pt), dt),  # points
        s((lanes, od, shape.n_edge), dt),  # obs
        s((lanes, shape.n_edge), np.int32),  # cam_idx
        s((lanes, shape.n_edge), np.int32),  # pt_idx
        s((lanes, shape.n_edge), dt),  # mask
        s((lanes, shape.n_cam), np.bool_),  # cam_fixed
        s((lanes, shape.n_pt), np.bool_),  # pt_fixed
        s((), dt),  # init_region
        s((), dt),  # init_v
    )
    if faulted:
        from megba_tpu.robustness.faults import FaultPlan

        args = args + (FaultPlan(
            edge_nan=s((lanes, shape.n_edge), dt),
            point_crush=s((lanes, shape.n_pt), dt),
            window=s((lanes, 2), np.int32),
            offset=s((lanes,), np.int32)),)
    return args


def pool_key(engine, option, shape: ShapeClass, lanes: int, cd: int,
             pd: int, od: int, faulted: bool = False) -> Tuple:
    return (engine, option, shape, int(lanes), int(cd), int(pd), int(od),
            bool(faulted))


def lower_bucket(engine, option, shape: ShapeClass, lanes: int,
                 cd: int = 9, pd: int = 3, od: int = 2,
                 faulted: bool = False):
    """AOT-lower one bucket program (`jax.stages.Lowered`).

    The compiled-program auditor's entry point for the batched canonical
    program (`ba_batched_b4_f32`): same builder, same operand layout,
    same donation flags as production dispatch.
    """
    jitted = batched_solve_program(engine, option, faulted)
    return jitted.lower(*_abstract_args(shape, lanes, cd, pd, od, faulted))


class CompilePool:
    """Bucket-program registry + warmup for one fleet service.

    `stats` (serving.stats.FleetStats) receives a hit/miss per
    `program()` request: a hit means the request rode an
    already-compiled program (AOT-warmed or previously dispatched) —
    the compile-pool hit rate a service's first-request latency lives
    and dies by.
    """

    def __init__(self, stats=None) -> None:
        self._stats = stats
        self._seen: Dict[Tuple, Dict[str, Any]] = {}  # key -> manifest entry
        self._lock = threading.Lock()

    # -- dispatch path ---------------------------------------------------
    def program(self, engine, option, shape: ShapeClass, lanes: int,
                cd: int, pd: int, od: int, faulted: bool = False):
        """Callable for one bucket; prefers the AOT executable."""
        key = pool_key(engine, option, shape, lanes, cd, pd, od, faulted)
        self._note(key, shape, lanes, cd, pd, od, faulted)
        with _LOCK:
            compiled = _AOT.get(key)
            hit = compiled is not None or key in _DISPATCHED
        if self._stats is not None:
            self._stats.record_pool(hit)
        if compiled is not None:
            return compiled
        jitted = batched_solve_program(engine, option, faulted)

        def run(*args):
            out = jitted(*args)
            # Mark the bucket jit-cache hot only once a dispatch has
            # actually compiled and returned: a failed first dispatch
            # must leave warm() able to build the bucket, and must not
            # count later requests as pool hits.
            with _LOCK:
                _DISPATCHED.add(key)
            return out

        return run

    # -- warmup ----------------------------------------------------------
    def warm(self, engine, option, entries: Sequence[Dict[str, Any]]) -> int:
        """AOT-compile the given buckets; returns how many were built.

        `entries` are manifest-entry dicts ({"shape": {...}, "lanes": n,
        "cd": .., "pd": .., "od": ..}).  Buckets already in the AOT
        store are skipped (idempotent warmup)."""
        built = 0
        for e in entries:
            shape = ShapeClass.from_dict(e["shape"])
            lanes = int(e["lanes"])
            cd, pd, od = int(e.get("cd", 9)), int(e.get("pd", 3)), \
                int(e.get("od", 2))
            faulted = bool(e.get("faulted", False))
            key = pool_key(engine, option, shape, lanes, cd, pd, od, faulted)
            self._note(key, shape, lanes, cd, pd, od, faulted)
            with _LOCK:
                if key in _AOT or key in _DISPATCHED or key in _WARMING:
                    continue
                _WARMING.add(key)
            try:
                compiled = lower_bucket(engine, option, shape, lanes,
                                        cd, pd, od, faulted).compile()
                with _LOCK:
                    _AOT[key] = compiled
            finally:
                with _LOCK:
                    _WARMING.discard(key)
            built += 1
        return built

    # -- manifests -------------------------------------------------------
    def _note(self, key: Tuple, shape: ShapeClass, lanes: int, cd: int,
              pd: int, od: int, faulted: bool = False) -> None:
        entry = {"shape": shape.to_dict(), "lanes": int(lanes),
                 "cd": int(cd), "pd": int(pd), "od": int(od)}
        if faulted:
            # Additive manifest field: pre-PR-8 manifests (no key) read
            # back as the plain program, which is what they warmed.
            entry["faulted"] = True
        with self._lock:
            self._seen.setdefault(key, entry)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._seen.values()]

    def save_manifest(self, path: str, option=None) -> None:
        """Persist every bucket this pool has seen (atomic write)."""
        doc = {
            "schema": MANIFEST_SCHEMA,
            "option": None if option is None else static_key(option),
            "entries": self.entries(),
        }
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def warm_from_manifest(self, path: str, engine, option) -> int:
        """Load a manifest and AOT-compile its buckets for `option`.

        A manifest recorded under a different option fingerprint still
        names valid SHAPES, but the programs it warmed are not the ones
        this service will run — warn and compile for the given option
        anyway (the shapes are the expensive knowledge)."""
        with open(path) as fh:
            doc = json.load(fh)
        if doc.get("schema") != MANIFEST_SCHEMA:
            raise ValueError(
                f"{path}: not a fleet warmup manifest "
                f"(schema={doc.get('schema')!r})")
        recorded = doc.get("option")
        if recorded is not None and recorded != static_key(option):
            warnings.warn(
                f"{path}: manifest was recorded under a different option "
                "configuration; warming its shape classes for the current "
                "options", stacklevel=2)
        return self.warm(engine, option, doc.get("entries", ()))
