"""Compile pool: the batched LM program, AOT-precompiled per bucket.

One vmapped, jitted LM solve serves every problem of a shape bucket
(serving/shape_class.py).  This module owns that program:

- `batched_solve_program` builds the jitted `vmap`'d `lm_solve` for an
  (engine, option) pair — ONE callable per configuration, memoised
  module-level exactly like `solve._cached_single_solve`, so repeated
  batches can never rebuild it around a fresh closure (the silent
  retrace bug the sentinel polices).
- `CompilePool.program(...)` hands the batcher a callable for a
  (shape class, lanes) bucket.  If the bucket was warmed, that callable
  IS the AOT `jax.stages.Compiled` executable — dispatch-only latency,
  no tracing on the request path.  Otherwise the shared jitted callable
  compiles on first dispatch and the pool records the bucket as ready.
- `CompilePool.warm(...)` AOT-lowers + compiles buckets from abstract
  `jax.ShapeDtypeStruct`s — no problem data needed — through the same
  builder the dispatch path uses, so what the pool warms is
  byte-for-byte the program requests will run.  With the persistent
  compile cache enabled (utils/backend.enable_persistent_compile_cache)
  the XLA compile itself is a disk hit across service restarts.
- Warmup manifests (`save_manifest` / `warm_from_manifest`) persist the
  observed buckets as JSON so a restarted service precompiles its whole
  working set before taking traffic.
- An optional `ArtifactStore` (serving/artifacts.py) removes even the
  restart compiles: `export_artifacts` serializes every AOT executable
  this pool holds, and a pool constructed over the same store loads
  them back — `warm`/`warm_from_manifest` then reach ready WITHOUT
  tracing or compiling anything (millisecond cold start; a
  version/fingerprint-mismatched or corrupt artifact falls back to
  compile-and-refresh with a warning, never a wrong program).

The AOT store is MODULE-level (shared by every pool instance in the
process): two pools warming/dispatching the same bucket must reuse one
trace, or the retrace sentinel would rightly flag the duplicate.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from megba_tpu.algo.lm import lm_solve
from megba_tpu.analysis.retrace import static_key, traced
from megba_tpu.serving.artifacts import ArtifactKey, ArtifactStore
from megba_tpu.serving.shape_class import ShapeClass
from megba_tpu.utils.memo import normalized_lru_cache

MANIFEST_SCHEMA = "megba_tpu.fleet_manifest/v1"


class ManifestMismatch(ValueError):
    """A warmup manifest's recorded option configuration does not match
    the one the service is warming for, and the caller asked for
    `strict=` refusal instead of the warn-and-recompile default.

    `fields` names the mismatched option fields (dotted paths into the
    ProblemOption tree) so an operator can see WHICH knob drifted
    between the manifest's recording service and this replica.
    """

    def __init__(self, path: str, fields: List[str]) -> None:
        self.path = path
        self.fields = list(fields)
        super().__init__(
            f"{path}: manifest was recorded under a different option "
            f"configuration (mismatched: {', '.join(self.fields)}); "
            "refusing to warm under strict=True — re-export the manifest "
            "for this configuration or drop strict to recompile")


def _flatten_config(d: Any, prefix: str = "") -> Dict[str, Any]:
    """Dotted-path flattening of a config_to_dict tree, for naming
    exactly which option fields a stale manifest disagrees on."""
    out: Dict[str, Any] = {}
    if isinstance(d, dict):
        for k, v in d.items():
            out.update(_flatten_config(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = d
    return out


def _sans_telemetry(option):
    """Strip the observability knobs (common.OBSERVABILITY_FIELDS:
    telemetry sink AND the metrics flag): programs (and therefore pool
    keys, artifact fingerprints and manifests) are
    observability-agnostic by the serving layer's contract — the
    dispatch path strips them before every cache
    (batcher._strip_telemetry), so the warm/export paths must key the
    same way or a sink-carrying option would warm programs dispatch can
    never hit.  The getattr guard keeps this total over the duck-typed
    option stand-ins some pool tests pass; real options delegate to the
    canonical common.strip_observability."""
    if (getattr(option, "telemetry", None) is not None
            or getattr(option, "metrics", False)):
        from megba_tpu.common import strip_observability

        return strip_observability(option)
    return option


def _config_mismatches(recorded: Dict[str, Any],
                       current: Dict[str, Any]) -> List[str]:
    a, b = _flatten_config(recorded), _flatten_config(current)
    # The observability knobs never reach a program (the serving layer
    # strips them before every cache/build — batcher._strip_telemetry),
    # so two services differing only in where they log / whether they
    # count warmed the SAME programs: not a mismatch.  The exclusion
    # set is DERIVED from the one strip registry
    # (common.OBSERVABILITY_FIELDS) rather than spelled here, so this
    # comparison surface cannot drift from what the strip sites clear
    # ("metrics" in the registry also covers manifests recorded before
    # the knob existed — absent vs default-False is not drift).
    from megba_tpu.common import OBSERVABILITY_FIELDS

    return sorted(k for k in set(a) | set(b)
                  if k not in OBSERVABILITY_FIELDS
                  and a.get(k) != b.get(k))

# (engine, option, shape, lanes, cd, pd, od) -> jax.stages.Compiled
_AOT: Dict[Tuple, Any] = {}
# keys already compiled through the jitted dispatch path (jit-cache hot)
_DISPATCHED: set = set()
# keys a warm() is compiling right now (reservation against duplicate
# AOT compiles when warms race each other)
_WARMING: set = set()
# keys whose _AOT entry was DESERIALIZED from an artifact: re-serializing
# such an executable reproduces the persistent-cache hazard below, so
# export skips them (the store already holds their good artifact).
_FROM_ARTIFACT: set = set()
# keys whose _AOT entry was compiled INSIDE _portable_compile_scope —
# the only handles export may serialize as-is; anything else (possibly
# satisfied from the persistent cache) is re-compiled portably first.
_PORTABLE: set = set()
_LOCK = threading.Lock()
_COMPILE_SCOPE_LOCK = threading.Lock()


@contextlib.contextmanager
def _portable_compile_scope():
    """Compile with the XLA persistent compile cache BYPASSED.

    Probed jaxlib hazard (jax 0.4.37 / jaxlib 0.4.36, XLA:CPU): an
    executable satisfied FROM the persistent compile cache re-serializes
    into a blob missing its jitted object code — a fresh process
    deserializing it fails with `INTERNAL: Symbols not found: [...]`.
    A freshly compiled executable round-trips fine.  So every compile
    whose result will be SERIALIZED into the artifact store runs inside
    this scope: the compile is honestly fresh (full object code in the
    blob) at the price of ignoring a possible disk hit — paid once per
    export, saved on every replica that warms from the artifact.

    The config flip is process-global, hence the scope lock: concurrent
    warms serialize through here rather than racing the restore.  The
    flip alone is NOT enough on this jax: the cache object and its
    "is the cache used" decision are memoised at first use
    (`compilation_cache._cache_checked`), so the scope also resets the
    cache state on entry and exit — entry makes the disabled dir take
    effect, exit lets the restored dir re-initialize lazily.
    """
    import jax

    def _reset() -> None:
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # private API drifted: config flip still holds
            pass

    with _COMPILE_SCOPE_LOCK:
        old = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        _reset()
        try:
            yield
        finally:
            jax.config.update("jax_compilation_cache_dir", old)
            _reset()


def _build_batched_solve(residual_jac_fn, option, faulted=False):
    """The batched mega-solve: `vmap`'d LM over a leading problem axis.

    Every lane carries its own problem (parameters, observations,
    indices, masks); the trust-region start state is shared (fresh
    solves).  Per-lane convergence masking falls out of JAX's
    while_loop batching rule: a lane whose `cond` has gone False keeps
    its carry through a per-lane select — it freezes BITWISE while the
    other lanes keep iterating — and the loop runs until every lane's
    predicate clears.  Per-lane `SolveStatus`, trace and cost come back
    as leading-axis stacks on the returned LMResult pytree.

    `faulted=True` builds the CHAOS variant: a per-lane
    `robustness.faults.FaultPlan` pytree (stacked on the lane axis,
    in_axes=0) rides as one extra operand, so a poisoned lane and its
    inert batch-mates share a single compiled program — the serving
    chaos harness's isolation contract lives on this path.  It is a
    separate retrace-sentinel site (`serving.batched_faulted`) so the
    <=1-compile-per-bucket certification stays per-variant.

    The parameter stacks are donated (same rationale as
    solve._build_single_solve): the batcher stacks fresh operands per
    batch and never reads them back.
    """

    def one(cameras, points, obs, cam_idx, pt_idx, mask, cam_fixed,
            pt_fixed, init_region, init_v, fault_plan=None):
        return lm_solve(
            residual_jac_fn, cameras, points, obs, cam_idx, pt_idx, mask,
            option, cam_fixed=cam_fixed, pt_fixed=pt_fixed,
            cam_sorted=True, initial_region=init_region,
            initial_v=init_v, fault_plan=fault_plan)

    if faulted:
        batched = jax.vmap(
            one, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, 0))
        site = "serving.batched_faulted"
    else:
        batched = jax.vmap(one,
                           in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None))
        site = "serving.batched"
    return jax.jit(
        traced(site, batched,
               static=static_key(residual_jac_fn, option, site)),
        donate_argnums=(0, 1))


# Long-lived engines only (make_residual_jacobian_fn / the factor
# registry's engine_for are themselves memoised, so every registered
# factor's engines qualify); mirrors _cached_single_solve.
_cached_batched_solve = normalized_lru_cache(maxsize=64)(
    _build_batched_solve)


def batched_solve_program(residual_jac_fn, option, faulted=False):
    """Call-shape-normalising front for the lru cache: positional,
    keyword and defaulted spellings of `faulted` must hit ONE entry (the
    same double-cache footgun make_residual_jacobian_fn fixed in PR 6,
    now the shared utils/memo.normalized_lru_cache — two entries would
    mean two jit wrappers and a duplicate trace).  `faulted` is coerced
    to bool here so truthy ints cannot split the key either.

    This PUBLIC cache front also strips the observability knobs
    (common.OBSERVABILITY_FIELDS, via _sans_telemetry): the pool/batcher
    paths arrive pre-stripped (identity pass-through, same lru slots),
    but a DIRECT caller with a sink-carrying option must hit the same
    compiled program — previously it silently split the cache (the
    identity lane's key-surface-drift finding, fixed at the source)."""
    return _cached_batched_solve(residual_jac_fn, _sans_telemetry(option),
                                 bool(faulted))


def _abstract_args(shape: ShapeClass, lanes: int, cd: int, pd: int,
                   od: int, faulted: bool = False) -> Tuple:
    """ShapeDtypeStructs matching the batcher's operand layout
    (feature-major stacks, leading lane axis)."""
    dt = np.dtype(shape.dtype)
    s = jax.ShapeDtypeStruct
    args = (
        s((lanes, cd, shape.n_cam), dt),  # cameras
        s((lanes, pd, shape.n_pt), dt),  # points
        s((lanes, od, shape.n_edge), dt),  # obs
        s((lanes, shape.n_edge), np.int32),  # cam_idx
        s((lanes, shape.n_edge), np.int32),  # pt_idx
        s((lanes, shape.n_edge), dt),  # mask
        s((lanes, shape.n_cam), np.bool_),  # cam_fixed
        s((lanes, shape.n_pt), np.bool_),  # pt_fixed
        s((), dt),  # init_region
        s((), dt),  # init_v
    )
    if faulted:
        from megba_tpu.robustness.faults import FaultPlan

        args = args + (FaultPlan(
            edge_nan=s((lanes, shape.n_edge), dt),
            point_crush=s((lanes, shape.n_pt), dt),
            window=s((lanes, 2), np.int32),
            offset=s((lanes,), np.int32)),)
    return args


def pool_key(engine, option, shape: ShapeClass, lanes: int, cd: int,
             pd: int, od: int, faulted: bool = False) -> Tuple:
    return (engine, option, shape, int(lanes), int(cd), int(pd), int(od),
            bool(faulted))


def lower_bucket(engine, option, shape: ShapeClass, lanes: int,
                 cd: int = 9, pd: int = 3, od: int = 2,
                 faulted: bool = False):
    """AOT-lower one bucket program (`jax.stages.Lowered`).

    The compiled-program auditor's entry point for the batched canonical
    program (`ba_batched_b4_f32`): same builder, same operand layout,
    same donation flags as production dispatch.
    """
    jitted = batched_solve_program(engine, option, faulted)
    return jitted.lower(*_abstract_args(shape, lanes, cd, pd, od, faulted))


class CompilePool:
    """Bucket-program registry + warmup for one fleet service.

    `stats` (serving.stats.FleetStats) receives a hit/miss per
    `program()` request: a hit means the request rode an
    already-compiled program (AOT-warmed or previously dispatched) —
    the compile-pool hit rate a service's first-request latency lives
    and dies by.
    """

    def __init__(self, stats=None, artifacts=None, timer=None) -> None:
        self._stats = stats
        self._seen: Dict[Tuple, Dict[str, Any]] = {}  # megba: guarded-by(_lock); key -> manifest entry
        self._lock = threading.Lock()
        # `artifacts` — an ArtifactStore (or its root path) of serialized
        # executables (serving/artifacts.py): warm()/program() try the
        # store before compiling, and `export_artifacts` fills it.
        if isinstance(artifacts, str):
            artifacts = ArtifactStore(artifacts)
        self.artifacts: Optional[ArtifactStore] = artifacts
        # `timer` (utils.timing.PhaseTimer) — cold-start observability:
        # artifact loads vs compiles land as `artifact_load` /
        # `warm_compile` phases with real wall clock.
        self._timer = timer

    def _artifact_key(self, engine, option, shape: ShapeClass, lanes: int,
                      cd: int, pd: int, od: int,
                      faulted: bool) -> ArtifactKey:
        return ArtifactKey(
            option_fingerprint=static_key(engine, option),
            shape=str(shape), lanes=int(lanes), cd=int(cd), pd=int(pd),
            od=int(od), faulted=bool(faulted))

    def _try_artifact(self, key: Tuple, akey: ArtifactKey):
        """Install `akey`'s serialized executable under `key` if the
        store holds a valid one; returns it (or None).  Reserves the key
        against concurrent warms exactly like the compile path."""
        if self.artifacts is None:
            return None
        with _LOCK:
            existing = _AOT.get(key)
            if existing is not None:
                return existing
            if key in _WARMING:
                return None  # a compile is already racing; let it win
            _WARMING.add(key)
        compiled = None
        try:
            ctx = (self._timer.phase("artifact_load")
                   if self._timer is not None else contextlib.nullcontext())
            with ctx:
                compiled = self.artifacts.load(akey)
            if compiled is not None:
                with _LOCK:
                    _AOT[key] = compiled
                    _FROM_ARTIFACT.add(key)
        finally:
            with _LOCK:
                _WARMING.discard(key)
        if compiled is not None and self._stats is not None:
            self._stats.record_artifact(True)
        return compiled

    @staticmethod
    def _entry_engine(entry: Dict[str, Any], engine, option):
        """The engine a manifest entry warms with: entries recorded
        with a `factor` name resolve THEIR OWN engine through the
        registry (a mixed-factor service's manifest must not warm rig
        buckets with the BAL engine — wrong dims trace-crash, and a
        dim-coincident family would silently compile wrong physics);
        legacy/factor-less entries use the caller's engine, exactly the
        pre-registry behaviour."""
        factor = entry.get("factor")
        if not factor:
            return engine
        from megba_tpu.factors import engine_for

        return engine_for(factor, option.jacobian_mode)

    # -- dispatch path ---------------------------------------------------
    def program(self, engine, option, shape: ShapeClass, lanes: int,
                cd: int, pd: int, od: int, faulted: bool = False,
                factor: Optional[str] = None):
        """Callable for one bucket; prefers the AOT executable.
        `factor` (a registered family name) is recorded on the manifest
        entry so `warm_from_manifest` can resolve the bucket's OWN
        engine later; it does not key the program — `engine` identity
        already does."""
        option = _sans_telemetry(option)
        key = pool_key(engine, option, shape, lanes, cd, pd, od, faulted)
        self._note(key, shape, lanes, cd, pd, od, faulted, factor)
        with _LOCK:
            compiled = _AOT.get(key)
            hit = compiled is not None or key in _DISPATCHED
        if compiled is None and not hit and self.artifacts is not None:
            # Dispatch-path artifact fallback: a bucket this pool never
            # warmed may still exist serialized (another replica's
            # export, a previous life of this one) — loading it here is
            # still compile-free and counts as a pool hit: the request
            # rides an already-built executable.
            compiled = self._try_artifact(
                key, self._artifact_key(engine, option, shape, lanes,
                                        cd, pd, od, faulted))
            hit = compiled is not None
        if self._stats is not None:
            self._stats.record_pool(hit)
        if compiled is not None:
            return compiled
        jitted = batched_solve_program(engine, option, faulted)

        def run(*args):
            out = jitted(*args)
            # Mark the bucket jit-cache hot only once a dispatch has
            # actually compiled and returned: a failed first dispatch
            # must leave warm() able to build the bucket, and must not
            # count later requests as pool hits.
            with _LOCK:
                _DISPATCHED.add(key)
            return out

        return run

    # -- warmup ----------------------------------------------------------
    def warm(self, engine, option, entries: Sequence[Dict[str, Any]]) -> int:
        """AOT-compile the given buckets; returns how many were built.

        `entries` are manifest-entry dicts ({"shape": {...}, "lanes": n,
        "cd": .., "pd": .., "od": .., ["factor": name]}).  An entry
        naming a `factor` warms with THAT family's engine
        (`_entry_engine`) — the mixed-factor cold-start contract;
        factor-less (legacy) entries use the given `engine`.  Buckets
        already in the AOT store are skipped (idempotent warmup).  With
        an `ArtifactStore` attached, each bucket first tries a
        serialized-executable load — compile-free, I/O-bound — and only
        a miss (or a stale/corrupt artifact, which warns) pays the
        trace + XLA compile; freshly compiled programs are saved back
        so the store heals itself."""
        option = _sans_telemetry(option)
        built = 0
        for e in entries:
            shape = ShapeClass.from_dict(e["shape"])
            lanes = int(e["lanes"])
            cd, pd, od = int(e.get("cd", 9)), int(e.get("pd", 3)), \
                int(e.get("od", 2))
            faulted = bool(e.get("faulted", False))
            entry_engine = self._entry_engine(e, engine, option)
            key = pool_key(entry_engine, option, shape, lanes, cd, pd, od,
                           faulted)
            self._note(key, shape, lanes, cd, pd, od, faulted,
                       e.get("factor"))
            akey = self._artifact_key(entry_engine, option, shape, lanes,
                                      cd, pd, od, faulted)
            with _LOCK:
                already = key in _AOT or key in _DISPATCHED
            if already:
                continue
            if self._try_artifact(key, akey) is not None:
                built += 1
                continue
            with _LOCK:
                if key in _AOT or key in _DISPATCHED or key in _WARMING:
                    continue
                _WARMING.add(key)
            try:
                # With a store attached this compile's executable will
                # be serialized — bypass the persistent compile cache so
                # the blob is portable (see _portable_compile_scope).
                scope = (_portable_compile_scope() if self.artifacts
                         is not None else contextlib.nullcontext())
                timing = (self._timer.phase("warm_compile")
                          if self._timer is not None
                          else contextlib.nullcontext())
                with scope, timing:
                    compiled = lower_bucket(
                        entry_engine, option, shape, lanes, cd, pd, od,
                        faulted).compile()
                with _LOCK:
                    _AOT[key] = compiled
                    if self.artifacts is not None:
                        _PORTABLE.add(key)
            finally:
                with _LOCK:
                    _WARMING.discard(key)
            if self.artifacts is not None:
                # The artifact counters describe the STORE's cold-start
                # split; a store-less warm is plain AOT compilation and
                # must not report misses against a store that does not
                # exist.
                if self._stats is not None:
                    self._stats.record_artifact(False)
                # Compile-and-refresh: the miss (or stale file) is now a
                # valid artifact for the next replica — best-effort,
                # because the compiled program in hand must win over a
                # read-only/full shared store (the degrade contract:
                # fall back to compile, never fail the warm).
                try:
                    self.artifacts.save(akey, compiled)
                except Exception as exc:  # serializer refusal, I/O, ...
                    from megba_tpu.serving.artifacts import ArtifactWarning

                    warnings.warn(
                        f"could not refresh artifact for {shape} "
                        f"(lanes={lanes}): {exc!r}; the compiled program "
                        "is warm in-process, the store keeps its stale "
                        "entry", ArtifactWarning, stacklevel=2)
            built += 1
        return built

    def export_artifacts(self, engine, option,
                         compile_missing: bool = True) -> int:
        """Serialize every bucket this pool has seen for (engine,
        option) into the attached store; returns how many were written.
        The exporting service pairs this with `save_manifest`: the
        manifest names the working set, the artifacts make warming it
        compile-free.

        Buckets that went jit-cache hot through DISPATCH hold no
        `Compiled` handle to serialize; with `compile_missing` (the
        default) they are AOT-compiled here — one extra trace per such
        bucket, identical signature.  ALL export compiles bypass the
        persistent compile cache (`_portable_compile_scope`: a
        cache-satisfied executable serializes into a blob a fresh
        process cannot load — the probed "Symbols not found" jaxlib
        hazard), and for the same reason EVERY seen bucket is
        re-compiled here unless its `_AOT` handle is known
        fresh-compiled: warm()-built handles with a store attached
        qualify, artifact-LOADED handles are skipped (the store already
        holds their good blob).  Export is an OFFLINE operation (a
        service checkpointing its working set), so the compile cost and
        re-traces are paid off the request path; a retrace-sentinel
        window around an export should `allow()` the duplicates
        explicitly."""
        if self.artifacts is None:
            raise ValueError("CompilePool has no ArtifactStore attached")
        option = _sans_telemetry(option)
        written = 0
        for e in self.entries():
            shape = ShapeClass.from_dict(e["shape"])
            lanes = int(e["lanes"])
            cd, pd, od = int(e.get("cd", 9)), int(e.get("pd", 3)), \
                int(e.get("od", 2))
            faulted = bool(e.get("faulted", False))
            # Factor-recorded entries export under THEIR engine (same
            # per-entry resolution warm() makes — a mixed-factor
            # service's export must not re-lower every bucket through
            # one family's physics).
            entry_engine = self._entry_engine(e, engine, option)
            key = pool_key(entry_engine, option, shape, lanes, cd, pd, od,
                           faulted)
            with _LOCK:
                compiled = _AOT.get(key)
                from_artifact = key in _FROM_ARTIFACT
                portable = key in _PORTABLE
            if from_artifact:
                continue  # its portable blob is already in the store
            if compiled is None or not portable:
                if not compile_missing:
                    continue
                with _portable_compile_scope():
                    compiled = lower_bucket(entry_engine, option, shape,
                                            lanes, cd, pd, od,
                                            faulted).compile()
                with _LOCK:
                    _AOT[key] = compiled
                    _PORTABLE.add(key)
            self.artifacts.save(
                self._artifact_key(entry_engine, option, shape, lanes, cd,
                                   pd, od, faulted), compiled)
            written += 1
        return written

    # -- manifests -------------------------------------------------------
    def _note(self, key: Tuple, shape: ShapeClass, lanes: int, cd: int,
              pd: int, od: int, faulted: bool = False,
              factor: Optional[str] = None) -> None:
        entry = {"shape": shape.to_dict(), "lanes": int(lanes),
                 "cd": int(cd), "pd": int(pd), "od": int(od)}
        if faulted:
            # Additive manifest field: pre-PR-8 manifests (no key) read
            # back as the plain program, which is what they warmed.
            entry["faulted"] = True
        if factor:
            # Additive too: warm()/export resolve this entry's OWN
            # engine from the registry; factor-less entries (legacy
            # manifests, direct-engine callers) warm with the caller's
            # engine as always.
            entry["factor"] = str(factor)
        with self._lock:
            self._seen.setdefault(key, entry)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(v) for v in self._seen.values()]

    def save_manifest(self, path: str, option=None) -> None:
        """Persist every bucket this pool has seen (atomic write).

        Alongside the opaque option fingerprint, the manifest records a
        STRUCTURED `option_config` (observability.report.config_to_dict)
        so a mismatch on load can name the exact fields that drifted —
        the `strict=` refusal path needs names, not just inequality."""
        option_config = None
        if option is not None:
            option = _sans_telemetry(option)
            from megba_tpu.observability.report import config_to_dict

            option_config = config_to_dict(option)
        doc = {
            "schema": MANIFEST_SCHEMA,
            "option": None if option is None else static_key(option),
            "option_config": option_config,
            "entries": self.entries(),
        }
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def warm_from_manifest(self, path: str, engine, option,
                           strict: bool = False) -> int:
        """Load a manifest and warm its buckets for `option` (artifact
        load when a store is attached, AOT compile otherwise).

        A manifest recorded under a different option fingerprint still
        names valid SHAPES, but the programs it warmed are not the ones
        this service will run — by default, warn and compile for the
        given option anyway (the shapes are the expensive knowledge).
        `strict=True` REFUSES instead with a typed `ManifestMismatch`
        naming the drifted fields: a federation worker warming from a
        shared artifact store must not silently recompile every bucket
        (its cold-start contract is I/O-bound) just because the exporter
        ran one knob off."""
        with open(path) as fh:
            doc = json.load(fh)
        if doc.get("schema") != MANIFEST_SCHEMA:
            raise ValueError(
                f"{path}: not a fleet warmup manifest "
                f"(schema={doc.get('schema')!r})")
        # Compare telemetry-stripped: the sink path is not part of any
        # program (see _config_mismatches) and would otherwise make two
        # identical services look mismatched.
        compare_option = _sans_telemetry(option)
        recorded = doc.get("option")
        if recorded is not None and recorded != static_key(compare_option):
            recorded_config = doc.get("option_config")
            if recorded_config is not None:
                from megba_tpu.observability.report import config_to_dict

                fields = _config_mismatches(recorded_config,
                                            config_to_dict(option))
            else:
                # Pre-strict manifests carry only the opaque fingerprint.
                fields = ["<option fingerprint; manifest predates "
                          "structured option_config>"]
            if strict:
                raise ManifestMismatch(path, fields)
            warnings.warn(
                f"{path}: manifest was recorded under a different option "
                f"configuration (mismatched: {', '.join(fields)}); "
                "warming its shape classes for the current options",
                stacklevel=2)
        return self.warm(engine, option, doc.get("entries", ()))


def reset_process_cache() -> None:
    """Drop every process-level compiled-program handle (_AOT store,
    dispatched-key set, in-flight warms).  This does NOT clear jax's own
    jit caches — it simulates a FRESH REPLICA's compile-pool state so a
    single process can certify the artifact path (load → dispatch with
    zero traces) that normally spans an export process and an import
    process.  Test/benchmark helper; a real service never needs it."""
    with _LOCK:
        _AOT.clear()
        _DISPATCHED.clear()
        _WARMING.clear()
        _FROM_ARTIFACT.clear()
        _PORTABLE.clear()
