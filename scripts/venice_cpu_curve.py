"""Venice-scale cost-vs-time curve on the CPU backend (ours only).

scipy cannot run Venice scale (5M observations, 3M parameters — see
ANCHOR.json's ladybug-shape anchor for the external comparison); this
records OUR solver's time-to-quality curve at the headline problem
shape so the judged metric (BASELINE.md: cost-vs-time at identical
flags) has a committed raw artifact even while the TPU tunnel is down.
1-iteration chunks through the shared flat_solve pipeline (one compiled
program; trust-region state rides as dynamic operands); compile is
excluded via a warmup chunk.

Usage: python scripts/venice_cpu_curve.py   (CPU; ~5-10 min on one core)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LM_ITERS = 15


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from megba_tpu.common import (
        AlgoOption,
        ComputeKind,
        JacobianMode,
        ProblemOption,
        SolverOption,
    )
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    nc, npts, opp = 1778, 993_923, 5_001_946 / 993_923  # venice shape
    s = make_synthetic_bal(
        num_cameras=nc, num_points=npts, obs_per_point=opp, seed=0,
        param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)
    nE = int(s.obs.shape[0])
    print(f"venice curve: {nc} cams / {npts} pts / {nE} edges (f32, cpu)",
          flush=True)

    option = ProblemOption(
        dtype=np.float32,
        compute_kind=ComputeKind.IMPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=1, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(),  # reference defaults: tol=1e-1
    )
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    # Lower ONCE (sort/pad/transpose/upload), then drive the compiled
    # program directly — per-iteration timings must not include host
    # lowering of the ~0.5 GB edge arrays the curve would otherwise
    # redo every chunk.
    import jax.numpy as jnp

    from megba_tpu.algo.lm import lm_solve
    from megba_tpu.core.fm import EDGE_QUANTUM
    from megba_tpu.core.types import is_cam_sorted, pad_edges

    obs, cam_idx, pt_idx = s.obs, s.cam_idx, s.pt_idx
    if not is_cam_sorted(cam_idx):
        from megba_tpu.native import sort_edges_by_camera

        perm = sort_edges_by_camera(cam_idx, nc)
        cam_idx, pt_idx, obs = cam_idx[perm], pt_idx[perm], obs[perm]
    obs, cam_idx, pt_idx, mask = pad_edges(
        obs, cam_idx, pt_idx, EDGE_QUANTUM, dtype=np.float32)
    args = (
        jnp.asarray(np.ascontiguousarray(obs.T)),
        jnp.asarray(cam_idx), jnp.asarray(pt_idx),
        jnp.asarray(mask.astype(np.float32)),
    )
    solve = jax.jit(
        lambda cams, pts, region, v: lm_solve(
            f, cams, pts, *args, option, cam_sorted=True,
            initial_region=region, initial_v=v))

    cams = jnp.asarray(np.ascontiguousarray(s.cameras0.T))
    pts = jnp.asarray(np.ascontiguousarray(s.points0.T))
    region = jnp.asarray(option.algo_option.initial_region, jnp.float32)
    v = jnp.asarray(2.0, jnp.float32)
    jax.block_until_ready(solve(cams, pts, region, v).cost)  # compile

    curve = []
    t_total = 0.0
    initial_cost = None
    for it in range(1, LM_ITERS + 1):
        t0 = time.perf_counter()
        res = solve(cams, pts, region, v)
        jax.block_until_ready(res.cost)
        t_total += time.perf_counter() - t0
        cams, pts = res.cameras, res.points
        region, v = res.region, res.v
        if initial_cost is None:
            initial_cost = float(res.initial_cost)
        curve.append(dict(iter=it, t_s=round(t_total, 3),
                          cost=float(res.cost),
                          pcg_iters=int(res.pcg_iterations)))
        print(json.dumps(curve[-1]), flush=True)
        if bool(res.stopped):
            break

    out = dict(
        problem=dict(cameras=nc, points=npts, edges=nE, dtype="float32",
                     backend="cpu", shape="venice problem-1778-993923"),
        flags="reference defaults (tol=1e-1, refuse_ratio=1.0)",
        initial_cost=initial_cost,
        curve=curve,
        note="CPU backend, 1 host core — time-to-quality shape, not a "
             "hardware perf claim.",
    )
    with open("VENICE_CPU_CURVE.json", "w") as fh:
        json.dump(out, fh, indent=1)
    print("wrote VENICE_CPU_CURVE.json", flush=True)


if __name__ == "__main__":
    main()
