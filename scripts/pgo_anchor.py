"""External quality anchor for the PGO family: our LM vs scipy TRF.

Companion to scripts/quality_anchor.py (the BA anchor): runs OUR SE(3)
pose-graph solver and scipy.optimize.least_squares (method='trf') on
the IDENTICAL objective — the exact between-factor residual of
models/pgo.py, batch-evaluated via jax for scipy too, so neither side
is handicapped by a different model.  Records cost-vs-time for both
into PGO_ANCHOR.json.

Usage: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/pgo_anchor.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from megba_tpu.utils.backend import respect_jax_platforms

NUM_POSES = 300
CLOSURES = 60
LM_ITERS = 30
SCIPY_BUDGETS = [4, 8, 16, 32, 64]


def main() -> None:
    respect_jax_platforms()
    import jax

    # CPU-only by design (like scripts/quality_anchor.py): never let a
    # bare invocation touch the single-client TPU tunnel.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import (
        between_residual,
        make_synthetic_pose_graph,
        solve_pgo,
    )

    g = make_synthetic_pose_graph(
        num_poses=NUM_POSES, loop_closures=CLOSURES, drift_noise=0.05,
        meas_noise=0.02, seed=21)
    n = g.poses_gt.shape[0]
    n_e = len(g.edge_i)

    def option(max_iter):
        return ProblemOption(
            dtype=np.float64,
            algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-12,
                                   epsilon2=1e-15),
            solver_option=SolverOption(max_iter=120, tol=1e-14,
                                       refuse_ratio=1e30))

    # Ours: ONE compiled max_iter=1 program, chained through the
    # trust-region resume operands (initial_region/initial_v) so the
    # cumulative t_s measures solving, not per-config recompiles —
    # exactly the quality_anchor.py methodology.
    step_opt = option(1)
    solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, step_opt)  # compile
    ours = []
    poses = g.poses0
    region = None
    v = None
    t_cum = 0.0
    initial_cost = None
    for k in range(1, LM_ITERS + 1):
        t0 = time.perf_counter()
        res = solve_pgo(poses, g.edge_i, g.edge_j, g.meas, step_opt,
                        initial_region=region, initial_v=v)
        jax.block_until_ready(res.cost)
        t_cum += time.perf_counter() - t0
        if initial_cost is None:
            initial_cost = float(res.initial_cost)
        poses = np.asarray(res.poses)
        region = float(res.region)
        v = float(res.v)
        ours.append({"iter": k, "t_s": round(t_cum, 4),
                     "cost": float(res.cost)})
        if bool(res.stopped):
            break

    # scipy on the identical objective: residuals via the SAME
    # between_residual batch, pose 0 frozen like our default gauge.
    from scipy.optimize import least_squares

    batched = jax.jit(jax.vmap(between_residual))
    meas_j = jnp.asarray(g.meas)
    ei, ej = g.edge_i, g.edge_j

    def residuals_flat(x):
        poses = jnp.asarray(
            np.concatenate([g.poses0[:1].ravel(), x]).reshape(n, 6))
        return np.asarray(batched(poses[ei], poses[ej], meas_j)).ravel()

    residuals_flat(g.poses0[1:].ravel())  # warmup/compile
    scipy_rows = []
    for budget in SCIPY_BUDGETS:
        t0 = time.perf_counter()
        sp = least_squares(
            residuals_flat, g.poses0[1:].ravel(), method="trf",
            xtol=1e-15, ftol=1e-15, gtol=1e-14, max_nfev=budget)
        scipy_rows.append({
            "max_nfev": budget,
            "t_s": round(time.perf_counter() - t0, 4),
            "cost": float(2.0 * sp.cost),
            "nfev": int(sp.nfev)})
        if int(sp.nfev) < budget:
            break  # converged on tolerance, larger budgets are identical

    out = {
        "problem": {"poses": n, "edges": n_e, "dtype": "float64",
                    "backend": jax.devices()[0].platform,
                    "shape": "drifted loop-closure SE(3) graph, "
                             "meas_noise 0.02"},
        "initial_cost": initial_cost,
        "ours": ours,
        "scipy": scipy_rows,
        "note": "identical objective both sides (models/pgo."
                "between_residual batch); scipy TRF with 2-point "
                "finite-difference Jacobian over the jax-evaluated "
                "residual (its standard configuration for black-box "
                "residuals); pose 0 frozen as the gauge anchor in both.",
    }
    path = os.path.join(os.path.dirname(__file__), "..", "PGO_ANCHOR.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({"ours_final": ours[-1], "scipy_final": scipy_rows[-1],
                      "initial_cost": initial_cost}))


if __name__ == "__main__":
    main()
