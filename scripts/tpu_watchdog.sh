#!/bin/bash
# TPU tunnel watchdog: probe periodically; the moment the backend comes
# up, hand off to the full measurement pass (scripts/run_tpu_round.sh).
# Launch detached:  nohup bash scripts/tpu_watchdog.sh >> tpu_probe.log 2>&1 &
#
# Every probe attempt (success or timeout) is appended to tpu_probe.log
# with a UTC timestamp so a wedged-all-round tunnel leaves committed
# evidence (VERDICT r02 item 7).  The probe runs in a subprocess with a
# generous timeout: backend acquisition through the single-client tunnel
# can take minutes when healthy, and a hung probe must not block the
# loop forever.
set -u
cd "$(dirname "$0")/.."

PROBE_TIMEOUT="${PROBE_TIMEOUT:-300}"
SLEEP_BETWEEN="${SLEEP_BETWEEN:-900}"
MAX_HOURS="${MAX_HOURS:-11}"
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))

attempt=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  attempt=$((attempt + 1))
  echo "=== probe attempt $attempt $(date -u +%Y-%m-%dT%H:%M:%SZ) (timeout ${PROBE_TIMEOUT}s) ==="
  # The probe installs a SIGTERM handler BEFORE touching jax so the
  # `timeout` TERM produces a clean PJRT teardown (releases any partial
  # tunnel claim); -k 30 SIGKILLs only if the child is stuck in C code.
  if timeout -k 30 "$PROBE_TIMEOUT" python -c "
import signal
signal.signal(signal.SIGTERM, lambda s, f: (_ for _ in ()).throw(SystemExit(143)))
import jax
print('devices:', jax.devices(), flush=True)
"; then
    echo "=== tunnel ALIVE at $(date -u +%Y-%m-%dT%H:%M:%SZ); launching TPU round ==="
    bash scripts/run_tpu_round.sh >> tpu_round.log 2>&1
    echo "=== TPU round finished at $(date -u +%Y-%m-%dT%H:%M:%SZ) (see tpu_round.log) ==="
    exit 0
  else
    echo "--- probe failed/timed out (rc=$?) at $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  fi
  sleep "$SLEEP_BETWEEN"
done
echo "=== watchdog deadline reached $(date -u +%Y-%m-%dT%H:%M:%SZ); tunnel never came up ==="
