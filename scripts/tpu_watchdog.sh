#!/bin/bash
# TPU tunnel watchdog: detect a live tunnel fast and cheaply, then hand
# off to the full measurement pass (scripts/run_tpu_round.sh).
# Launch detached:  nohup bash scripts/tpu_watchdog.sh >> tpu_probe.log 2>&1 &
#
# Two-stage probing (WEDGE.md):  the axon PJRT client's first network
# leg is GET http://127.0.0.1:8083/init — when the loopback relay is
# down (the observed wedge mode, every outage round 1-5), that connect
# is refused instantly and jax.devices() retries forever inside native
# code.  So stage 1 is a 1-second pure-bash TCP pre-check of
# 127.0.0.1:8083 every POLL_S seconds: no jax, no claim, nothing that
# can be SIGKILLed mid-claim, and a tunnel window is noticed within
# ~POLL_S instead of up to 15 min into it.  Only when the port accepts
# does stage 2 run the real SIGTERM-handled jax probe (which can still
# take minutes when healthy).
#
# State TRANSITIONS are logged with UTC timestamps (plus a heartbeat
# every HEARTBEAT_N polls) so a wedged-all-round tunnel leaves committed
# evidence without megabytes of refused-connect spam.
set -u
cd "$(dirname "$0")/.."

PROBE_TIMEOUT="${PROBE_TIMEOUT:-300}"
POLL_S="${POLL_S:-45}"
HEARTBEAT_N="${HEARTBEAT_N:-40}"      # ~30 min at POLL_S=45
BACKOFF_S="${BACKOFF_S:-900}"         # after a relay-up-but-probe-dead probe
MAX_HOURS="${MAX_HOURS:-11}"
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))

relay_up() {
  timeout 1 bash -c 'echo > /dev/tcp/127.0.0.1/8083' 2>/dev/null
}

jax_probe() {
  # SIGTERM handler BEFORE jax so the `timeout` TERM produces a clean
  # PJRT teardown (releases any partial tunnel claim); -k 30 SIGKILLs
  # only if the child is stuck in native code.
  timeout -k 30 "$PROBE_TIMEOUT" python -c "
import signal
signal.signal(signal.SIGTERM, lambda s, f: (_ for _ in ()).throw(SystemExit(143)))
import jax
print('devices:', jax.devices(), flush=True)
"
}

state="unknown"
poll=0
down_polls=0
echo "=== watchdog start $(date -u +%Y-%m-%dT%H:%M:%SZ) (poll ${POLL_S}s, pre-check 127.0.0.1:8083) ==="
while [ "$(date +%s)" -lt "$deadline" ]; do
  poll=$((poll + 1))
  if relay_up; then
    down_polls=0
    echo "=== relay :8083 ACCEPTING at $(date -u +%Y-%m-%dT%H:%M:%SZ) (poll $poll); running jax probe ==="
    jax_probe
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "=== tunnel ALIVE at $(date -u +%Y-%m-%dT%H:%M:%SZ); launching TPU round ==="
      bash scripts/run_tpu_round.sh >> tpu_round.log 2>&1
      echo "=== TPU round finished at $(date -u +%Y-%m-%dT%H:%M:%SZ) (see tpu_round.log) ==="
      exit 0
    fi
    # rc=124: timeout's SIGTERM sufficed (clean teardown). rc=137: the
    # child was stuck in native code and took the -k SIGKILL. The
    # distinction is round-4 evidence — keep it accurate in the log.
    echo "--- relay up but jax probe failed (rc=$rc) at $(date -u +%Y-%m-%dT%H:%M:%SZ) — init/claim-leg failure mode (WEDGE.md); backing off ${BACKOFF_S}s"
    state="relay-up-probe-dead"
    sleep "$BACKOFF_S"
    continue
  fi
  down_polls=$((down_polls + 1))
  if [ "$state" != "relay-down" ]; then
    echo "--- relay :8083 refused at $(date -u +%Y-%m-%dT%H:%M:%SZ) (poll $poll): tunnel down (relay absent)"
    state="relay-down"
  elif [ $((down_polls % HEARTBEAT_N)) -eq 0 ]; then
    echo "--- heartbeat $(date -u +%Y-%m-%dT%H:%M:%SZ): relay down for $down_polls consecutive polls (~$((down_polls * POLL_S / 60)) min)"
  fi
  sleep "$POLL_S"
done
echo "=== watchdog deadline reached $(date -u +%Y-%m-%dT%H:%M:%SZ); tunnel never came up ==="
