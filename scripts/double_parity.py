"""Double-semantics parity: f64 vs f32(+compensated reductions).

The reference's default examples are double precision end to end
(reference examples/BAL_Double.cpp:50-58, fp64 cuBLAS dispatch in its
wrapper layer); on TPU this framework instead runs f32 storage with
compensated f32 reductions (ops/accum.py) and makes a *semantic* claim:
the optimizer follows the same trajectory to the same optimum within
the f32 representation floor.  VERDICT r04 item 4 asks for that claim
to be MEASURED, not made by construction.

This script runs the identical problem (generated once in f64, cast for
the f32 run) through the identical LM configuration in both dtypes on
the CPU backend, captures the per-iteration cost curves from the
solver's verbose lines (the reference's own observable,
lm_algo.cu:149-162), and writes DOUBLE_PARITY.json with both curves and
their relative gaps.  Exit code is nonzero if the final costs disagree
beyond the stated tolerance, so CI can run a small-scale version.

Usage:
  MEGBA_PARITY_CONFIGS=trafalgar,venice [MEGBA_BENCH_SCALE=1.0] \
      python scripts/double_parity.py
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Final-cost agreement tolerance: the f32 cost functional at the f64
# optimum differs from the f64 cost by O(eps_f32 * kappa); 1e-4 relative
# is conservative for these conditionings and catches any real
# divergence (a wrong trajectory lands orders of magnitude away).
REL_TOL = 1e-4


def run_one(cfg_name: str, scale: float):
    import jax

    from megba_tpu.common import (
        AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve
    import bench as B

    c = B.CONFIGS[cfg_name]
    n_cam = max(8, int(c.cameras * scale))
    n_pt = max(64, int(c.points * scale))
    s = make_synthetic_bal(
        num_cameras=n_cam, num_points=n_pt, obs_per_point=c.obs_per_point,
        seed=0, param_noise=1e-2, pixel_noise=0.5, dtype=np.float64)

    jac = JacobianMode[c.jacobian]
    ck = ComputeKind[c.compute]
    f = make_residual_jacobian_fn(mode=jac)

    from megba_tpu.utils.curves import dtype_parity_payload

    def solve_for(dtype):
        option = ProblemOption(
            dtype=np.dtype(dtype),
            compute_kind=ck,
            jacobian_mode=jac,
            algo_option=AlgoOption(max_iter=20, epsilon1=1e-14,
                                   epsilon2=1e-16),
            solver_option=SolverOption(max_iter=50, tol=1e-12,
                                       refuse_ratio=1e30),
        )
        return flat_solve(
            f,
            s.cameras0.astype(dtype), s.points0.astype(dtype),
            s.obs.astype(dtype),
            s.cam_idx, s.pt_idx, option, verbose=True)

    out = {"config": cfg_name, "scale": scale, "cameras": n_cam,
           "points": n_pt, "edges": int(s.obs.shape[0]),
           "jacobian": c.jacobian, "compute": c.compute}
    out.update(dtype_parity_payload(
        solve_for, REL_TOL, label=cfg_name,
        block_on=lambda r: jax.block_until_ready(r.cost)))
    return out


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache, respect_jax_platforms)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    respect_jax_platforms()
    enable_persistent_compile_cache()

    configs = os.environ.get(
        "MEGBA_PARITY_CONFIGS", "trafalgar,venice").split(",")
    scale = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))
    results = [run_one(name.strip(), scale) for name in configs if name]
    payload = {"rel_tol": REL_TOL,
               "all_pass": all(r["pass"] for r in results),
               "results": results}
    path = os.environ.get("MEGBA_PARITY_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "DOUBLE_PARITY.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    print(f"wrote {path}; all_pass={payload['all_pass']}", flush=True)
    return 0 if payload["all_pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
