#!/bin/bash
# Static-analysis + sanitizer lane (megba_tpu/analysis/).
#
# Seven gates, all required (scripts/run_tests.sh invokes this, so
# tier-1 cannot pass with a violation in any of them):
#
#   1. the JAX-contract linter runs CLEAN on the package;
#   2. the linter FIRES on the seeded bad-pattern fixture (a rule that
#      silently stops matching is itself a regression);
#   3. the strict-dtype sanitizer lane: small end-to-end BA + PGO solves
#      under jax_numpy_dtype_promotion=strict + jax_debug_nans;
#   4. the compiled-program auditor: AOT-lower + compile the canonical
#      solver programs on CPU and audit the emitted HLO for host
#      transfers, the per-PCG-iteration collective pattern, dtype
#      leaks, the allowed-bf16 surface, materialised donation, and
#      FLOP/byte drift against the committed ANALYSIS_BUDGET.json (no
#      solver execution involved);
#   5. the weak-literal dtype-leak lane: the AST rule for the bug class
#      hand-fixed in PRs 3 and 6 (bare float literals in jnp.where
#      branches / jnp.clip bounds materialise f64 constants under x64)
#      run standalone over the package — gate 1 includes it, but this
#      lane keeps the dtype-surface story visible as its own step
#      beside gate 4's bf16 surface census;
#   6. the concurrency contract lane: guarded-by race detection,
#      lock-order deadlock analysis, and blocking-under-lock checks
#      over the host serving tier, plus must-fire / must-stay-silent
#      checks on the seeded concurrency fixtures (each of the three
#      rule ids must appear in the bad fixture's findings);
#   7. the program-identity contract lane: stale-program fingerprint
#      coverage (every lowering-read option field reaches the static
#      key), cache-split detection (keyed-but-never-lowering-read
#      fields), and key-surface drift analysis (strip helpers,
#      hardcoded exclusion tuples, un-stripped cache fronts,
#      operand-as-static branches) over the whole package, with the
#      same must-fire / must-stay-silent fixture gates as lane 6.
set -e -o pipefail
cd "$(dirname "$0")/.."

echo "[lint] JAX-contract linter on megba_tpu/"
python -m megba_tpu.analysis.lint megba_tpu/

echo "[lint] linter must fire on the seeded bad-pattern fixture"
if python -m megba_tpu.analysis.lint tests/data/lint_fixtures/bad_patterns.py \
    > /dev/null 2>&1; then
    echo "ERROR: linter exited 0 on tests/data/lint_fixtures/bad_patterns.py" >&2
    exit 1
fi

echo "[lint] linter must stay silent on the good-pattern fixture"
python -m megba_tpu.analysis.lint tests/data/lint_fixtures/good_patterns.py

echo "[lint] strict-dtype promotion + debug-nans sanitizer lane"
JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 python -m megba_tpu.analysis.strict_dtype

echo "[lint] compiled-program audit (HLO census + AOT budget gate)"
python -m megba_tpu.analysis.audit --check

echo "[lint] weak-literal dtype-leak lane (lane 5)"
python -m megba_tpu.analysis.lint --rule weak-literal megba_tpu/

echo "[lint] concurrency contract lane (lane 6)"
python -m megba_tpu.analysis.lint --rule guarded-by --rule lock-order \
    --rule blocking-under-lock megba_tpu/

echo "[lint] concurrency rules must fire on the seeded bad fixture"
CONC_BAD=tests/data/lint_fixtures/bad_concurrency.py
if conc_out=$(python -m megba_tpu.analysis.lint --rule guarded-by \
    --rule lock-order --rule blocking-under-lock "$CONC_BAD" 2>&1); then
    echo "ERROR: concurrency linter exited 0 on $CONC_BAD" >&2
    exit 1
fi
for rule in guarded-by lock-order blocking-under-lock; do
    if ! grep -q " $rule " <<< "$conc_out"; then
        echo "ERROR: rule $rule produced no finding on $CONC_BAD" >&2
        echo "$conc_out" >&2
        exit 1
    fi
done

echo "[lint] concurrency rules must stay silent on the good fixture"
python -m megba_tpu.analysis.lint --rule guarded-by --rule lock-order \
    --rule blocking-under-lock tests/data/lint_fixtures/good_concurrency.py

echo "[lint] program-identity contract lane (lane 7)"
python -m megba_tpu.analysis.lint --rule stale-program --rule cache-split \
    --rule key-surface-drift megba_tpu/

echo "[lint] identity rules must fire on the seeded bad fixture"
IDENT_BAD=tests/data/lint_fixtures/bad_identity.py
if ident_out=$(python -m megba_tpu.analysis.lint --rule stale-program \
    --rule cache-split --rule key-surface-drift "$IDENT_BAD" 2>&1); then
    echo "ERROR: identity linter exited 0 on $IDENT_BAD" >&2
    exit 1
fi
for rule in stale-program cache-split key-surface-drift; do
    if ! grep -q " $rule " <<< "$ident_out"; then
        echo "ERROR: rule $rule produced no finding on $IDENT_BAD" >&2
        echo "$ident_out" >&2
        exit 1
    fi
done

echo "[lint] each identity rule must fire standalone (per-rule exit codes)"
for rule in stale-program cache-split key-surface-drift; do
    if python -m megba_tpu.analysis.lint --rule "$rule" "$IDENT_BAD" \
        > /dev/null 2>&1; then
        echo "ERROR: rule $rule alone exited 0 on $IDENT_BAD" >&2
        exit 1
    fi
done

echo "[lint] identity rules must stay silent on the good fixture"
python -m megba_tpu.analysis.lint --rule stale-program --rule cache-split \
    --rule key-surface-drift tests/data/lint_fixtures/good_identity.py

echo "lint lane OK"
