#!/bin/bash
# Full test suite in two processes.
#
# A single pytest process accumulates hundreds of XLA:CPU JIT
# compilations over the full suite; on this sandbox's jaxlib that
# reproducibly segfaults inside backend_compile once the volume is
# high enough (the same tests pass in isolation or in either half —
# the crash is in the compiler's own native code, not the framework).
# Two processes keep every test exercised with headroom.
set -e -o pipefail
cd "$(dirname "$0")/.."

# Static checks first: the JAX-contract linter + strict-dtype sanitizer
# lane (scripts/lint.sh) are cheap and fail fast, so a contract
# violation can never hide behind a green unit-test run.
bash scripts/lint.sh

FIRST=$(ls tests/test_[a-o]*.py)
SECOND=$(ls tests/test_[p-z]*.py)

python -m pytest $FIRST -q -p no:cacheprovider "$@"
python -m pytest $SECOND -q -p no:cacheprovider "$@"

# Observability smoke: a tiny telemetry-on solve must produce a JSONL
# SolveReport that the summarize CLI can render (the end-to-end contract
# of megba_tpu/observability/, beyond what the unit tests pin).
SMOKE=$(mktemp /tmp/megba_obs_smoke.XXXXXX.jsonl)
trap 'rm -f "$SMOKE"' EXIT
JAX_PLATFORMS=cpu MEGBA_TELEMETRY="$SMOKE" python - <<'PY'
import numpy as np

from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solve import flat_solve

s = make_synthetic_bal(num_cameras=4, num_points=24, obs_per_point=3,
                       seed=0, param_noise=4e-2, pixel_noise=0.3,
                       dtype=np.float32)
option = ProblemOption(dtype=np.float32,
                       algo_option=AlgoOption(max_iter=3),
                       solver_option=SolverOption(max_iter=8, tol=1e-8))
res = flat_solve(make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF),
                 s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)
assert res.trace is not None and int(res.iterations) > 0
PY
JAX_PLATFORMS=cpu python -m megba_tpu.observability.summarize "$SMOKE" | grep -q "phases:"
echo "observability smoke OK"
