#!/bin/bash
# Full test suite in two processes.
#
# A single pytest process accumulates hundreds of XLA:CPU JIT
# compilations over the full suite; on this sandbox's jaxlib that
# reproducibly segfaults inside backend_compile once the volume is
# high enough (the same tests pass in isolation or in either half —
# the crash is in the compiler's own native code, not the framework).
# Two processes keep every test exercised with headroom.
set -e -o pipefail
cd "$(dirname "$0")/.."

FIRST=$(ls tests/test_[a-o]*.py)
SECOND=$(ls tests/test_[p-z]*.py)

python -m pytest $FIRST -q -p no:cacheprovider "$@"
python -m pytest $SECOND -q -p no:cacheprovider "$@"
