#!/bin/bash
# Full test suite in two processes.
#
# A single pytest process accumulates hundreds of XLA:CPU JIT
# compilations over the full suite; on this sandbox's jaxlib that
# reproducibly segfaults inside backend_compile once the volume is
# high enough (the same tests pass in isolation or in either half —
# the crash is in the compiler's own native code, not the framework).
# Two processes keep every test exercised with headroom.
set -e -o pipefail
cd "$(dirname "$0")/.."

# Static checks first: the JAX-contract linter + strict-dtype sanitizer
# lane (scripts/lint.sh) are cheap and fail fast, so a contract
# violation can never hide behind a green unit-test run.
bash scripts/lint.sh

FIRST=$(ls tests/test_[a-o]*.py)
SECOND=$(ls tests/test_[p-z]*.py)

python -m pytest $FIRST -q -p no:cacheprovider "$@"
python -m pytest $SECOND -q -p no:cacheprovider "$@"

# Observability smoke: a tiny telemetry-on solve must produce a JSONL
# SolveReport that the summarize CLI can render (the end-to-end contract
# of megba_tpu/observability/, beyond what the unit tests pin).
SMOKE=$(mktemp /tmp/megba_obs_smoke.XXXXXX.jsonl)
trap 'rm -f "$SMOKE"' EXIT
JAX_PLATFORMS=cpu MEGBA_TELEMETRY="$SMOKE" python - <<'PY'
import numpy as np

from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solve import flat_solve

s = make_synthetic_bal(num_cameras=4, num_points=24, obs_per_point=3,
                       seed=0, param_noise=4e-2, pixel_noise=0.3,
                       dtype=np.float32)
option = ProblemOption(dtype=np.float32,
                       algo_option=AlgoOption(max_iter=3),
                       solver_option=SolverOption(max_iter=8, tol=1e-8))
res = flat_solve(make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF),
                 s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)
assert res.trace is not None and int(res.iterations) > 0
PY
JAX_PLATFORMS=cpu python -m megba_tpu.observability.summarize "$SMOKE" | grep -q "phases:"
echo "observability smoke OK"

# Inexact-LM smoke: venice-10% convergence-mode bench with the
# MEGBA_BENCH_FORCING=1 head-to-head — adaptive forcing + warm starts
# must cut total PCG iterations >= 30% at an unchanged final cost
# (the curve-parity gap_tol regime, utils/curves), and the comparison
# rides the bench JSON line.  MEGBA_BENCH_FLEET=16 rides the SAME bench
# run: 16 heterogeneous synthetic problems (io/synthetic.make_fleet)
# solved as a serial flat_solve loop vs one batched solve_many pass
# (serving layer) — steady-state batched problems/sec must strictly
# beat the serial loop and every lane must report a terminal
# SolveStatus.  MEGBA_BENCH_BF16=1 rides the same run too: the bf16
# MXU pipeline head-to-head (cost band + guard cleanliness + halved
# bytes axes; asserted below, certified in BENCH_bf16.json).
# MEGBA_BENCH_OBS=1 rides the same run as well: the observability-plane
# overhead head-to-head (ISSUE 16) — solve_many with the plane off vs
# metrics+spans on, interleaved best-of-6 pairs, <= 2% overhead band
# (asserted below, certified in BENCH_obs.json).
FORCING_OUT=$(mktemp /tmp/megba_forcing_smoke.XXXXXX.json)
trap 'rm -f "$SMOKE" "$FORCING_OUT"' EXIT
JAX_PLATFORMS=cpu MEGBA_BENCH_CONFIG=venice MEGBA_BENCH_SCALE=0.1 \
MEGBA_BENCH_CONVERGENCE=0 MEGBA_BENCH_FORCING=1 MEGBA_BENCH_FLEET=16 \
MEGBA_BENCH_PRECOND=neumann MEGBA_BENCH_NEUMANN_ORDER=1 \
MEGBA_BENCH_BF16=1 MEGBA_BENCH_OBS=1 \
  python bench.py > "$FORCING_OUT"
python - "$FORCING_OUT" <<'PY'
import json
import sys

line = [l for l in open(sys.argv[1]) if l.strip().startswith("{")][-1]
fc = json.loads(line)["extra"]["forcing"]
print("inexact-LM smoke:", json.dumps(fc))
assert fc["pcg_reduction"] >= 0.30, (
    f"forcing cut only {100 * fc['pcg_reduction']:.1f}% of PCG iterations "
    "(need >= 30%)")
assert fc["cost_rel_gap"] <= 1e-2, (
    f"forcing moved the final cost by {fc['cost_rel_gap']:.2e} "
    "(> 1e-2 curve gap_tol)")

# Preconditioner smoke (ISSUE 7): under the SAME inexact-LM production
# config, the Neumann operator family must cut total PCG iterations
# >= 30% vs block-Jacobi at <= 1e-2 relative final-cost gap.  (The
# two-level operator is pinned structurally by the ba_twolevel_w2_f32
# audit program and tests/test_precond.py; on THIS bench's synthetic
# expander-like camera graph it has no cluster structure to exploit, so
# the iteration gate rides the operator that wins here — see
# ARCHITECTURE.md "Preconditioner hierarchy".)
pc = json.loads(line)["extra"]["precond"]
print("precond smoke:", json.dumps(pc))
assert pc["kind"] == "neumann", pc
assert pc["pcg_reduction"] >= 0.30, (
    f"{pc['kind']} cut only {100 * pc['pcg_reduction']:.1f}% of PCG "
    "iterations vs block-Jacobi (need >= 30%)")
assert pc["cost_rel_gap"] <= 1e-2, (
    f"{pc['kind']} moved the final cost by {pc['cost_rel_gap']:.2e} "
    "(> 1e-2 curve gap_tol)")

fl = json.loads(line)["extra"]["fleet"]
print("fleet smoke:", json.dumps(fl))
TERMINAL = {"converged", "max_iter", "stalled", "recovered",
            "fatal_nonfinite"}
assert fl["problems"] >= 16, fl
assert set(fl["statuses"]) <= TERMINAL and fl["statuses"], (
    f"non-terminal per-lane status in {fl['statuses']}")
# Sanity band, not a parity proof: this lane runs f32/x64-off, where
# camera/point bucket padding reorders compensated sums and the
# un-converged trajectories drift ~1e-2 relative.  The strict contract
# (bitwise padding, rtol 1e-6 vs flat_solve) is pinned under x64 by
# tests/test_serving.py.
assert fl["max_cost_rel_gap"] <= 5e-2, (
    f"batched final costs drifted {fl['max_cost_rel_gap']:.2e} from the "
    "serial loop (> 5e-2 f32 sanity band)")
assert fl["problems_per_sec_batched"] > fl["problems_per_sec_serial"], (
    f"batched {fl['problems_per_sec_batched']} problems/s did not beat "
    f"the serial loop at {fl['problems_per_sec_serial']} problems/s")

# bf16 MXU pipeline smoke (ISSUE 15): the SAME venice-10% run solved
# f32 vs bf16 under the inexact-LM config with PR 5's guards ARMED —
# the bf16 candidate must converge within the documented cost-gap band
# with ZERO guard/recovery/breakdown events (a clean bf16 run must not
# lean on the containment machinery), and the auditor's
# collective_bytes_per_sp axis must come out at exactly HALF the f32
# program's, live (re-audited in-process) and committed
# (ANALYSIS_BUDGET.json).  Certified in BENCH_bf16.json.
bf = json.loads(line)["extra"]["bf16"]
print("bf16 smoke:", json.dumps({k: bf[k] for k in (
    "cost_rel_gap", "cost_gap_band", "pcg_iters_delta",
    "guard_events_bf16", "committed_bytes_per_sp")}))
assert bf["cost_rel_gap"] <= bf["cost_gap_band"], (
    f"bf16 final cost drifted {bf['cost_rel_gap']:.2e} from the f32 "
    f"control (> {bf['cost_gap_band']:.0e} documented band)")
assert bf["guard_events_bf16"] == 0, (
    f"bf16 tripped {bf['guard_events_bf16']} guard/recovery event(s) "
    "on a clean run")
assert bf["bf16"]["status"] in TERMINAL and bf["bf16"]["recoveries"] == 0
for cand, ctrl in (("ba_bf16_w2_f32", "ba_sharded_w2_f32"),
                   ("ba_bf16_2d_w4_f32", "ba_2d_w4_f32")):
    c = bf["committed_bytes_per_sp"]
    assert c[cand] == 0.5 * c[ctrl], (
        f"{cand} bytes/sp {c[cand]} is not half of {ctrl}'s {c[ctrl]}")
live = bf["audited_live"]
if live:
    assert live["ba_bf16_w2_f32"]["collective_bytes_per_sp"] == \
        0.5 * live["ba_sharded_w2_f32"]["collective_bytes_per_sp"], live
    assert not any(v["violations"] for v in live.values()), live

# Observability-plane overhead smoke (ISSUE 16): the SAME venice-10%
# run re-solves the fleet with metrics+spans armed vs the plane off
# (interleaved best-of-6 pairs so container drift cancels).  The plane
# is host-side only — the jitted programs are byte-identical (pinned by
# the audit gate) — so the overhead must sit inside the 2% band, and
# the instrumented side must actually have instrumented (non-empty
# metric families + spans).  Certified in BENCH_obs.json.
ob = json.loads(line)["extra"]["obs"]
print("obs overhead smoke:", json.dumps(ob))
assert ob["within_band"] and ob["overhead_pct"] <= ob["band_pct"], (
    f"observability plane cost {ob['overhead_pct']:.2f}% on the fleet "
    f"pass (> {ob['band_pct']:.0f}% band)")
assert ob["metric_families"] > 0 and ob["spans"] > 0, (
    f"instrumented side recorded nothing: {ob}")
PY
echo "inexact-LM + fleet + bf16 + obs smoke OK"

# Fused edge-pipeline smoke (ISSUE 19): the venice scene solved through
# the fused Pallas kernels (gather -> contract -> scatter in one kernel
# per direction + fused M^-1 apply) vs the tiled XLA lowering on the
# SAME edge plans, guards armed both sides.  The acceptance pin:
# end-to-end LM cost within 1e-5 with ZERO guard/recovery events, and
# the analytical edge-budget axes must show the fusion actually deletes
# transient HBM round-trips.  Off-TPU the kernels run under Pallas
# INTERPRET mode — the parity certificate, but per-grid-step host
# execution makes venice-10% (~500k edges) wall-clock-prohibitive on
# CPU runners — so the CPU gate runs the identical contract at
# venice-1% (~50k edges: same multi-bucket multi-tile plan structure,
# ~100 tiles per direction); the venice-10% fused certification rides
# the TPU window (scripts/run_tpu_round.sh), where the kernels compile
# through Mosaic.  Certified in BENCH_fused.json (lane-tagged).
FUSED_OUT=$(mktemp /tmp/megba_fused_smoke.XXXXXX.json)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$FUSED_OUT"' EXIT
JAX_PLATFORMS=cpu MEGBA_BENCH_CONFIG=venice MEGBA_BENCH_SCALE=0.01 \
MEGBA_BENCH_CONVERGENCE=0 MEGBA_BENCH_FUSED=1 \
  python bench.py > "$FUSED_OUT"
python - "$FUSED_OUT" <<'PY'
import json
import sys

line = [l for l in open(sys.argv[1]) if l.strip().startswith("{")][-1]
fu = json.loads(line)["extra"]["fused"]
print("fused smoke:", json.dumps({k: fu[k] for k in (
    "cost_rel_gap", "cost_gap_band", "guard_events_fused",
    "transient_bytes_deleted_per_sp", "scene")}))
TERMINAL = {"converged", "max_iter", "stalled", "recovered",
            "fatal_nonfinite"}
assert fu["cost_rel_gap"] <= fu["cost_gap_band"], (
    f"fused final cost drifted {fu['cost_rel_gap']:.2e} from the tiled "
    f"XLA lowering (> {fu['cost_gap_band']:.0e} acceptance band)")
assert fu["guard_events_fused"] == 0, (
    f"fused run tripped {fu['guard_events_fused']} guard/recovery "
    "event(s) on a clean run")
assert fu["fused_pallas"]["status"] in TERMINAL, fu["fused_pallas"]
assert fu["tiles"] and fu["tiles"]["plan"] == "tiled_1d", (
    f"fused solve did not report tile metrics: {fu['tiles']}")
assert fu["tiles"]["fused_to_pt"]["tiles"] > 1, (
    f"fused smoke degenerated to a single tile: {fu['tiles']}")
assert fu["transient_bytes_deleted_per_sp"] > 0, (
    "edge-budget pricing shows no transient traffic deleted — the "
    f"fused arm is not cheaper: {fu}")
PY
echo "fused edge-pipeline smoke OK"

# Locality-scene multilevel smoke (ISSUE 11): the venice-10% bench on
# a RING-locality scene (banded camera co-observation — the structure
# real BAL graphs have; MEGBA_BENCH_LOCALITY=ring) with the MULTILEVEL
# camera-graph hierarchy as candidate.  Unlike the expander scene
# (where the coarse space is structurally inert — PR 7's honest
# negative result, and why the Neumann smoke above stays as-is), the
# locality scene has the cluster-constant slow modes the coarse space
# exists to remove: the hierarchy must cut total PCG iterations >= 30%
# vs block-Jacobi at <= 1e-2 relative final-cost gap, and the JSON
# line must carry the hierarchy depth + per-level fallback decode.
LOCALITY_OUT=$(mktemp /tmp/megba_locality_smoke.XXXXXX.json)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$LOCALITY_OUT"' EXIT
JAX_PLATFORMS=cpu MEGBA_BENCH_CONFIG=venice MEGBA_BENCH_SCALE=0.1 \
MEGBA_BENCH_CONVERGENCE=0 MEGBA_BENCH_LOCALITY=ring \
MEGBA_BENCH_PRECOND=multilevel \
  python bench.py > "$LOCALITY_OUT"
python - "$LOCALITY_OUT" <<'PY'
import json
import sys

line = [l for l in open(sys.argv[1]) if l.strip().startswith("{")][-1]
d = json.loads(line)
assert d["extra"]["locality"] == "ring", d["extra"].get("locality")
pc = d["extra"]["precond"]
print("locality multilevel smoke:", json.dumps(pc))
assert pc["kind"] == "multilevel", pc
assert pc["locality"] == "ring", pc
# The hierarchy actually went past two levels on this scene.
assert pc["hierarchy_levels"] >= 3, pc
assert pc["pcg_reduction"] >= 0.30, (
    f"multilevel cut only {100 * pc['pcg_reduction']:.1f}% of PCG "
    "iterations vs block-Jacobi on the locality scene (need >= 30%)")
assert pc["cost_rel_gap"] <= 1e-2, (
    f"multilevel moved the final cost by {pc['cost_rel_gap']:.2e} "
    "(> 1e-2 curve gap_tol)")
# Healthy hierarchy: the win must come from the full cycle, not a
# degraded one (fallback rides the JSON line either way).
fb = pc["fallback"] or {}
assert not fb.get("coarse"), f"hierarchy degraded during the smoke: {fb}"
PY
echo "locality multilevel smoke OK"

# Fault-injection smoke: venice-10% with a NaN burst seeded at GLOBAL
# LM iteration 3 — i.e. at the checkpointed driver's chunk-resume
# relinearisation, the preemption-recovery worst case.  With
# RobustOption guards the solve must recover on-device
# (status=recovered) and land within rtol 1e-5 of the clean final cost,
# single-device AND world-2; the same injection with guards off must
# yield a non-finite cost — proving the guard, not luck, did the work.
JAX_PLATFORMS=cpu python - <<'PY'
import dataclasses
import os
import tempfile

import numpy as np

# World-2 on a CPU host needs forced virtual devices, exactly as
# tests/conftest.py arranges for the pytest lanes.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.algo.checkpointed import solve_checkpointed
from megba_tpu.common import (
    AlgoOption, ComputeKind, JacobianMode, ProblemOption, RobustOption,
    SolverOption, SolveStatus, status_name)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.robustness.faults import make_nan_burst

s = make_synthetic_bal(num_cameras=177, num_points=99392,
                       obs_per_point=5_001_946 / 993_923, seed=0,
                       param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)
option = ProblemOption(
    dtype=np.float32, compute_kind=ComputeKind.IMPLICIT,
    jacobian_mode=JacobianMode.ANALYTICAL,
    algo_option=AlgoOption(max_iter=14, epsilon1=1e-12, epsilon2=1e-15),
    solver_option=SolverOption(max_iter=30, tol=1e-10, refuse_ratio=1e30))
guarded = dataclasses.replace(option, robust_option=RobustOption(guards=True))
f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
args = (f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx)
plan = make_nan_burst(s.obs.shape[0], [11, 4242], start=3, stop=4)
d = tempfile.mkdtemp(prefix="megba_fault_smoke_")


def two_phase(opt, name, fault=None):
    # Phase 1 runs iterations 0-2 and snapshots; phase 2 resumes —
    # its chunk-initial relinearisation IS global iteration 3, where
    # the burst is seeded.
    ck = os.path.join(d, name + ".npz")
    short = dataclasses.replace(opt, algo_option=dataclasses.replace(
        opt.algo_option, max_iter=3))
    solve_checkpointed(*args, short, checkpoint_path=ck,
                       checkpoint_every=3, use_tiled=False)
    kw = {} if fault is None else {"fault_plan": fault}
    return solve_checkpointed(*args, opt, checkpoint_path=ck,
                              checkpoint_every=20, use_tiled=False, **kw)


for world in (1, 2):
    opt_w = dataclasses.replace(option, world_size=world)
    guard_w = dataclasses.replace(guarded, world_size=world)
    clean = two_phase(opt_w, f"clean_w{world}")
    off = two_phase(opt_w, f"off_w{world}", plan)
    assert not np.isfinite(float(off.cost)), (
        f"world {world}: guards-off injection should have poisoned the "
        f"cost, got {float(off.cost)}")
    on = two_phase(guard_w, f"on_w{world}", plan)
    gap = abs(float(on.cost) - float(clean.cost)) / abs(float(clean.cost))
    print(f"fault smoke w{world}: clean={float(clean.cost):.8e} "
          f"guarded={float(on.cost):.8e} gap={gap:.2e} "
          f"status={status_name(on.status)} recoveries={int(on.recoveries)}",
          flush=True)
    assert int(on.status) == SolveStatus.RECOVERED, status_name(on.status)
    assert gap <= 1e-5, f"world {world}: recovered cost off by {gap:.2e}"
PY
echo "fault-injection smoke OK"

# Serving chaos smoke (ISSUE 8): a 16-problem mixed fleet through a
# resilient FleetQueue — 2 NaN-poisoned problems must heal via the
# escalation ladder (RECOVERED at rung >= 1), 1 deadline-doomed problem
# must be shed before dispatch, and the 13 clean problems must land
# BITWISE at parity with an unpoisoned solve_many control (same
# batches, only the poison gate differs).  A chaos-tripped bucket must
# fail submits fast; escalated re-solves certify <= 1 compile per
# (bucket, rung) via the retrace sentinel; the dispatcher thread must
# survive all of it; and `summarize --aggregate` must render the
# retry/shed/deadline-miss/breaker counters from the report stream.
CHAOS_SINK=$(mktemp /tmp/megba_chaos_smoke.XXXXXX.jsonl)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$LOCALITY_OUT" "$CHAOS_SINK"' EXIT
JAX_PLATFORMS=cpu MEGBA_CHAOS_SINK="$CHAOS_SINK" python - <<'PY'
import dataclasses
import os

import numpy as np

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.analysis import retrace
from megba_tpu.common import AlgoOption, ProblemOption, SolverOption, SolveStatus
from megba_tpu.io.synthetic import make_fleet
from megba_tpu.observability import summarize
from megba_tpu.robustness.faults import (
    DispatchChaos, InjectedDispatchError, close_fault_window, make_nan_burst)
from megba_tpu.serving import (
    BreakerPolicy, BucketTripped, BucketLadder, EscalationPolicy,
    DeadlineExceeded, FleetProblem, FleetQueue, FleetStats, classify,
    solve_many)

OPT = ProblemOption(dtype=np.float64, algo_option=AlgoOption(max_iter=6),
                    solver_option=SolverOption(max_iter=12, tol=1e-10))
sink = os.environ["MEGBA_CHAOS_SINK"]

fleet = [FleetProblem.from_synthetic(s, name=f"chaos{i}")
         for i, s in enumerate(make_fleet(16, size_range=(12, 96), seed=0,
                                          dtype=np.float64))]
ladder = BucketLadder()
buckets = {}
for i, p in enumerate(fleet):
    buckets.setdefault(classify(*p.dims(), OPT.dtype, ladder), []).append(i)
# poison 2 members of the most-populated bucket (they need clean
# batch-mates to prove isolation); doom one problem from another bucket
big = max(buckets.values(), key=len)
poisoned_idx = set(big[:2])
doomed_idx = next(i for i in range(16) if i not in poisoned_idx
                  and i not in set(big))

def poison(p):
    plan = make_nan_burst(p.obs.shape[0], [1, 5], start=0, stop=1,
                          n_points=p.points.shape[0], dtype=np.float64)
    return dataclasses.replace(p, fault_plan=plan)

submitted = [poison(p) if i in poisoned_idx else p
             for i, p in enumerate(fleet)]

# --- phase 1: breaker trip + fast-fail (chaos dies pre-solve) ---------
# Two SAME-bucket problems fail consecutively (the heterogeneous fleet
# spans several buckets; the breaker is per bucket, so the trip must
# come from one bucket's own streak), then a third submit to that
# bucket must fail fast.
assert len(big) >= 3, buckets
stats = FleetStats()
chaos = DispatchChaos(fail_first=99)
with FleetQueue(OPT, max_batch=1, max_wait_s=0.0, stats=stats, chaos=chaos,
                breaker=BreakerPolicy(trip_after=2, cooldown_s=600.0)) as q:
    for i in big[:2]:
        try:
            q.submit(fleet[i]).result(timeout=60)
            raise AssertionError("injected dispatch failure did not fire")
        except InjectedDispatchError:
            pass
    try:
        q.submit(fleet[big[2]])
        raise AssertionError("tripped bucket accepted a submit")
    except BucketTripped as e:
        print("chaos smoke: tripped-bucket fast-fail OK:", e)
assert stats.breaker_trips == 1 and stats.breaker_fast_fails == 1, (
    stats.as_dict())

# --- phase 2: the mixed fleet through the resilient queue -------------
base = retrace.snapshot()
opt_tele = dataclasses.replace(OPT, telemetry=sink)
with FleetQueue(opt_tele, max_batch=16, max_wait_s=30.0, stats=stats,
                escalation=EscalationPolicy(backoff_base_s=0.01,
                                            seed=0)) as q:
    futs = []
    for i, p in enumerate(submitted):
        futs.append(q.submit(p, deadline_s=0.0 if i == doomed_idx
                             else None))
    q.flush()
    assert q._thread.is_alive(), "dispatcher thread died"
    assert all(f.done() for f in futs), "flush returned with open futures"
    results = {}
    shed = None
    for i, f in enumerate(futs):
        try:
            results[i] = f.result(timeout=1)
        except DeadlineExceeded:
            shed = i

new = {k: v - base.get(k, 0) for k, v in retrace.snapshot().items()
       if k[0].startswith("serving.batched") and v > base.get(k, 0)}
assert all(d <= 1 for d in new.values()), (
    f"duplicate batched-program trace (cache bust): {new}")
print(f"chaos smoke: {sum(new.values())} batched programs traced, "
      "<= 1 per (bucket, rung)")

assert shed == doomed_idx, f"doomed problem {doomed_idx} was not shed"
for i in poisoned_idx:
    r = results[i]
    assert r.status == int(SolveStatus.RECOVERED), (i, r.status_name)
    assert r.attempts == 2 and r.rung == 1, (r.attempts, r.rung)
    assert r.history[0]["status"] in (int(SolveStatus.STALLED),
                                      int(SolveStatus.FATAL_NONFINITE))
    assert np.isfinite(float(r.cost))
print(f"chaos smoke: {len(poisoned_idx)} poisoned problems RECOVERED "
      "via escalation")

# --- clean-problem parity: bitwise vs the unpoisoned control ----------
# Control = the same fleet minus the doomed problem, poison windows
# CLOSED: identical batch compositions and operands except the poison
# gate, so clean results must be bit-identical.
control_probs = [dataclasses.replace(
                     p, fault_plan=close_fault_window(p.fault_plan))
                 if p.fault_plan is not None else p
                 for i, p in enumerate(submitted) if i != doomed_idx]
control = solve_many(control_probs, OPT, ladder=ladder)
ctrl = {}
k = 0
for i in range(16):
    if i == doomed_idx:
        continue
    ctrl[i] = control[k]
    k += 1
clean = [i for i in range(16)
         if i not in poisoned_idx and i != doomed_idx]
assert len(clean) == 13
for i in clean:
    r, c = results[i], ctrl[i]
    assert int(r.status) == int(c.status), (i, r.status_name, c.status_name)
    assert r.cameras.tobytes() == c.cameras.tobytes(), (
        f"clean problem {i}: params drifted from the unpoisoned control")
    assert r.cost.tobytes() == c.cost.tobytes(), i
    assert not r.deadline_missed and r.attempts == 1
print("chaos smoke: 13 clean problems BITWISE at parity with the "
      "unpoisoned control")

d = stats.as_dict()
assert d["sheds"] == 1 and d["retries"] == 2, d
assert d["breaker_trips"] == 1, d

# --- aggregate CLI surfaces the resilience counters -------------------
out = summarize.aggregate_paths([sink])
print(out)
assert "status recovered: 2" in out, out
assert "2 escalated attempts (max rung 1)" in out, out
assert "2 retries" in out and "1 shed" in out, out
assert "breaker: 1 trips" in out, out
assert summarize.main(["--aggregate", sink]) == 0
PY
echo "serving chaos smoke OK"

# Triage smoke (ISSUE 10): pre-flight problem triage at venice-10%
# scale and through the fleet queue.  (1) venice-10% with injected
# degeneracies (600 deg-1 far points, 120 behind-camera points):
# REJECT must fail fast — host milliseconds, ZERO device dispatch
# (retrace sentinel + no dispatch phase) — and REPAIR must converge
# within 1e-5 of the clean (un-injected) run: the repairs soft-delete
# exactly the injected pathology, so the surviving system IS the clean
# one.  (2) A fleet of 16 with 3 poisoned problems through
# FleetQueue.submit(triage=...): 2 REJECTed futures resolve instantly
# (never dispatched), 1 REPAIRed problem solves in-batch, and the 13
# clean batch-mates stay BITWISE identical to a solve_many control of
# the same composition.  `summarize --aggregate` renders the triage
# counters from the report stream.
TRIAGE_SINK=$(mktemp /tmp/megba_triage_smoke.XXXXXX.jsonl)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$LOCALITY_OUT" "$CHAOS_SINK" "$TRIAGE_SINK"' EXIT
JAX_PLATFORMS=cpu python - <<'PY'
import time

import numpy as np

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.analysis import retrace
from megba_tpu.common import (
    AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption,
    status_name)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.robustness.triage import (
    ProblemRejected, TriageAction, TriagePolicy)
from megba_tpu.solve import flat_solve
from megba_tpu.utils.timing import PhaseTimer

kw = dict(num_cameras=177, num_points=99392,
          obs_per_point=5_001_946 / 993_923, seed=0, param_noise=1e-2,
          pixel_noise=0.5, dtype=np.float32)
clean = make_synthetic_bal(**kw)
deg = make_synthetic_bal(**kw, n_orphan_points=600, n_behind_camera=120)
option = ProblemOption(
    dtype=np.float32, compute_kind=ComputeKind.IMPLICIT,
    jacobian_mode=JacobianMode.ANALYTICAL,
    algo_option=AlgoOption(max_iter=10, epsilon1=1e-12, epsilon2=1e-15),
    solver_option=SolverOption(max_iter=30, tol=1e-10, refuse_ratio=1e30))
f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

# -- REJECT: fast, typed, zero dispatch --------------------------------
base = retrace.snapshot()
timer = PhaseTimer()
t0 = time.perf_counter()
try:
    flat_solve(f, deg.cameras0, deg.points0, deg.obs, deg.cam_idx,
               deg.pt_idx, option, use_tiled=False, timer=timer,
               triage=TriagePolicy())
    raise AssertionError("degenerate venice problem was not rejected")
except ProblemRejected as e:
    wall = time.perf_counter() - t0
    counts = e.report.counts()
assert counts["under_constrained_point"] == 720, counts  # 600 + 120 starved
assert counts["behind_camera"] == 240, counts
assert "dispatch" not in timer.totals and "lowering" not in timer.totals, (
    timer.totals)
assert retrace.snapshot() == base, "REJECT traced a program"
assert wall < 30.0, f"REJECT took {wall:.1f}s (want host-side fast-fail)"
print(f"triage smoke: venice-10% REJECT in {wall * 1e3:.0f} ms, "
      f"zero dispatch, findings {counts}")

# -- REPAIR: converges to the clean run --------------------------------
rc = flat_solve(f, clean.cameras0, clean.points0, clean.obs, clean.cam_idx,
                clean.pt_idx, option, use_tiled=False)
rr = flat_solve(f, deg.cameras0, deg.points0, deg.obs, deg.cam_idx,
                deg.pt_idx, option, use_tiled=False,
                triage=TriagePolicy(on_degenerate=TriageAction.REPAIR))
gap = abs(float(rr.cost) - float(rc.cost)) / abs(float(rc.cost))
print(f"triage smoke: clean={float(rc.cost):.8e} "
      f"repaired={float(rr.cost):.8e} gap={gap:.2e} "
      f"status={status_name(rr.status)}")
assert gap <= 1e-5, f"triaged REPAIR cost off the clean run by {gap:.2e}"
PY
JAX_PLATFORMS=cpu MEGBA_TRIAGE_SINK="$TRIAGE_SINK" python - <<'PY'
import dataclasses
import os

import numpy as np

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_fleet
from megba_tpu.observability import summarize
from megba_tpu.robustness.triage import (
    ProblemRejected, TriageAction, TriagePolicy, triage_problem)
from megba_tpu.serving import (
    BucketLadder, FleetProblem, FleetQueue, FleetStats, classify,
    solve_many)

OPT = ProblemOption(dtype=np.float64, algo_option=AlgoOption(max_iter=6),
                    solver_option=SolverOption(max_iter=12, tol=1e-10))
sink = os.environ["MEGBA_TRIAGE_SINK"]

fleet = [FleetProblem.from_synthetic(s, name=f"triage{i}")
         for i, s in enumerate(make_fleet(16, size_range=(12, 96), seed=0,
                                          dtype=np.float64))]
ladder = BucketLadder()
buckets = {}
for i, p in enumerate(fleet):
    buckets.setdefault(classify(*p.dims(), OPT.dtype, ladder), []).append(i)
big = max(buckets.values(), key=len)
assert len(big) >= 2, buckets


def poison(p):
    # Append one deg-1 point: same bucket class is NOT required for the
    # reject pair (they never join a batch), and the repair pair keeps
    # its bucket if the point/edge counts stay under the rungs.
    pts = np.concatenate([p.points, [[0.05, 0.05, 0.05]]])
    return dataclasses.replace(
        p, points=pts,
        cam_idx=np.concatenate([p.cam_idx, [0]]).astype(np.int32),
        pt_idx=np.concatenate([p.pt_idx,
                               [p.points.shape[0]]]).astype(np.int32),
        obs=np.concatenate([p.obs, [[0.0, 0.0]]]))


reject_idx = [i for i in range(16) if i not in big][:2]
assert len(reject_idx) == 2, buckets
# The repaired problem must stay in its CLEAN batch-mates' bucket after
# the poison appends a point+edge, or the in-batch isolation claim is
# vacuous.
repair_idx = next(
    i for i in big
    if classify(*poison(fleet[i]).dims(), OPT.dtype, ladder)
    == classify(*fleet[i].dims(), OPT.dtype, ladder))
poisoned = set(reject_idx) | {repair_idx}
submitted = [poison(p) if i in poisoned else p for i, p in enumerate(fleet)]

stats = FleetStats()
opt_tele = dataclasses.replace(OPT, telemetry=sink)
results = {}
with FleetQueue(opt_tele, max_batch=16, max_wait_s=30.0, stats=stats) as q:
    futs = {}
    for i, p in enumerate(submitted):
        if i in reject_idx:
            futs[i] = q.submit(p, triage=TriagePolicy())
            assert futs[i].done(), "rejected future not resolved at submit"
        elif i == repair_idx:
            futs[i] = q.submit(p, triage=TriagePolicy(
                on_degenerate=TriageAction.REPAIR))
        else:
            futs[i] = q.submit(p)
    q.flush()
    for i, fu in futs.items():
        if i in reject_idx:
            try:
                fu.result()
                raise AssertionError(f"problem {i} was not rejected")
            except ProblemRejected:
                pass
        else:
            results[i] = fu.result(timeout=60)
assert stats.triage_rejected == 2 and stats.triage_repaired == 1, (
    stats.as_dict())
print(f"triage smoke: 2 rejected at submit, 1 repaired in-batch "
      f"({stats.triage_points_fixed} pts fixed, "
      f"{stats.triage_edges_masked} edges masked)")

# Control: the same composition built by hand — rejected problems
# dropped, the repaired one hand-repaired — so batches match exactly
# and the 13 clean problems must be BITWISE identical.
out = triage_problem(
    submitted[repair_idx].cameras, submitted[repair_idx].points,
    submitted[repair_idx].obs, submitted[repair_idx].cam_idx,
    submitted[repair_idx].pt_idx,
    TriagePolicy(on_degenerate=TriageAction.REPAIR))
hand = dataclasses.replace(
    submitted[repair_idx], edge_mask=out.repair.edge_mask,
    cam_fixed=out.repair.cam_fixed, pt_fixed=out.repair.pt_fixed,
    health=out.report.to_dict())
control_probs, control_ids = [], []
for i in range(16):
    if i in reject_idx:
        continue
    control_probs.append(hand if i == repair_idx else submitted[i])
    control_ids.append(i)
control = dict(zip(control_ids, solve_many(control_probs, OPT,
                                           ladder=ladder)))
clean_ids = [i for i in range(16) if i not in poisoned]
assert len(clean_ids) == 13
for i in clean_ids:
    r, c = results[i], control[i]
    assert r.cameras.tobytes() == c.cameras.tobytes(), (
        f"clean problem {i} drifted next to a repaired batch-mate")
    assert r.cost.tobytes() == c.cost.tobytes(), i
r, c = results[repair_idx], control[repair_idx]
assert r.cameras.tobytes() == c.cameras.tobytes(), "repair != hand-repair"
assert np.isfinite(float(r.cost))
print("triage smoke: 13 clean batch-mates BITWISE identical to control, "
      "queue repair == hand repair")

out_text = summarize.aggregate_paths([sink])
print(out_text)
assert "triage: 2 rejected / 1 repaired" in out_text, out_text
assert "1 points fixed" in out_text and "1 edges masked" in out_text, out_text
assert "under_constrained_point=1" in out_text, out_text
PY
echo "triage smoke OK"

# Mixed-factor fleet smoke (ISSUE 13): the factor registry's
# servability contract.  A fleet mixing FOUR residual families — rig
# BA (shared body extrinsic, repeated (body, point) pairs), full-
# intrinsics radial pinhole, GPS/IMU-style unary pose priors, and BAL —
# rides ONE FleetQueue: problems must group per (factor, shape class)
# (a bucket is one residual family by construction), every result must
# come back terminal, the whole fleet must respect the <= 1 compile per
# (factor, bucket) retrace budget, a REPEATED fleet must trace NOTHING,
# and every problem must land BITWISE identical to its per-factor
# solve_many control (cross-factor batching changes scheduling, never
# answers).
JAX_PLATFORMS=cpu python - <<'PY'
import os

import numpy as np

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.analysis import retrace
from megba_tpu.common import AlgoOption, ProblemOption, SolverOption, SolveStatus
from megba_tpu.factors.priors import make_synthetic_priors
from megba_tpu.factors.radial import make_synthetic_radial
from megba_tpu.factors.rig import make_synthetic_rig
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.serving import FleetProblem, FleetQueue, solve_many
from megba_tpu.serving.batcher import _group_by_bucket
from megba_tpu.serving.shape_class import BucketLadder

OPT = ProblemOption(dtype=np.float64, algo_option=AlgoOption(max_iter=6),
                    solver_option=SolverOption(max_iter=20, tol=1e-9))


def fleet():
    probs = []
    for i in range(3):
        probs.append(FleetProblem.from_synthetic(
            make_synthetic_rig(seed=i), name=f"rig{i}", factor="rig"))
        probs.append(FleetProblem.from_synthetic(
            make_synthetic_radial(seed=i), name=f"rad{i}",
            factor="pinhole_radial"))
        s = make_synthetic_priors(seed=i)
        probs.append(FleetProblem(
            cameras=s.cameras0, points=s.points0, obs=s.obs,
            cam_idx=s.cam_idx, pt_idx=s.pt_idx, name=f"pri{i}",
            factor="pose_prior"))
        probs.append(FleetProblem.from_synthetic(
            make_synthetic_bal(seed=i), name=f"bal{i}"))
    return probs


probs = fleet()
groups = _group_by_bucket(probs, OPT, BucketLadder())
for (sc, dims, factor), items in groups.items():
    assert {p.factor for _, p in items} == {factor}, (sc, factor)
factors_seen = {factor for (_, _, factor) in groups}
assert factors_seen == {"rig", "pinhole_radial", "pose_prior", "bal"}, (
    factors_seen)
print(f"mixed-factor smoke: {len(probs)} problems -> {len(groups)} "
      f"(factor, bucket) groups across {len(factors_seen)} families")

base = retrace.snapshot()
with FleetQueue(OPT, max_batch=4, max_wait_s=0.01) as q:
    futs = [q.submit(p) for p in probs]
    q.flush()
    queued = [f.result(timeout=600) for f in futs]
new = {k: v - base.get(k, 0) for k, v in retrace.snapshot().items()
       if k[0].startswith("serving.batched") and v > base.get(k, 0)}
assert all(d <= 1 for d in new.values()), (
    f"duplicate batched-program trace (cross-factor cache bust): {new}")
terminal = {int(SolveStatus.CONVERGED), int(SolveStatus.MAX_ITER),
            int(SolveStatus.RECOVERED)}
assert all(int(r.status) in terminal for r in queued), [
    (r.name, r.status_name) for r in queued]
print(f"mixed-factor smoke: {sum(new.values())} programs traced "
      "(<= 1 per (factor, bucket)), all results terminal")

# a repeated fleet is compile-free: every (factor, bucket) program hot
base2 = retrace.snapshot()
repeat = solve_many(fleet(), OPT)
new2 = {k: v - base2.get(k, 0) for k, v in retrace.snapshot().items()
        if v > base2.get(k, 0)}
assert not new2, f"repeat mixed fleet traced: {new2}"
print("mixed-factor smoke: repeated fleet traced ZERO programs")

# batch-mates bitwise vs per-factor solve_many controls
by_name = {r.name: r for r in queued}
for factor in sorted(factors_seen):
    sub = [p for p in fleet() if p.factor == factor]
    control = solve_many(sub, OPT)
    for p, c in zip(sub, control):
        r = by_name[p.name]
        assert r.cameras.tobytes() == c.cameras.tobytes(), (
            f"{p.name}: mixed-fleet params drifted from the "
            f"per-factor control")
        assert r.points.tobytes() == c.points.tobytes(), p.name
        assert np.asarray(r.cost).tobytes() == np.asarray(
            c.cost).tobytes(), p.name
print("mixed-factor smoke: every problem BITWISE identical to its "
      "per-factor solve_many control")
PY
echo "mixed-factor fleet smoke OK"

# Federation smoke (ISSUE 12): the scale-out tier end to end.  A
# 16-problem mixed f64 fleet is first solved single-host (the control)
# through a CompilePool that then EXPORTS its working set — manifest +
# serialized executables (portable compiles, see
# serving/compile_pool._portable_compile_scope).  A 2-worker
# FleetRouter warms from those artifacts: every bucket must load
# (mode=artifact, zero compiles) and the first fleet must dispatch with
# ZERO traces (worker-side retrace-sentinel certification).  One worker
# is then SIGKILLed mid-fleet — a real host loss: its in-flight
# problems must re-route to the survivor (typed counters), flush() must
# return with every future resolved (the no-wedge gate), and all 16
# results must be BITWISE identical to the single-host control
# (shape-class padding exactness makes federated placement
# result-invariant).  `summarize --aggregate` must render the
# federation block from the merged telemetry streams.
#
# The observability PLANE (ISSUE 16) rides the same smoke with all
# three knobs armed: the router must harvest a merged Prometheus-ready
# metrics snapshot from itself + the surviving worker (bitwise-
# deterministic across repeated idle pulls), the trace recorder must
# export ONE merged Chrome/Perfetto trace-event JSON spanning router
# and worker pids (worker spans ride the RPC replies home), and the
# w1 SIGKILL must leave a flight-recorder dump on disk.  The
# concurrency stress (ISSUE 17) then solves a 200-problem fleet while
# 4 reader threads hammer metrics_snapshot() over the shared RPC
# stream and router lock — the live regression behind the guarded-by /
# lock-order / blocking-under-lock contracts in lint lane 6.
FED_DIR=$(mktemp -d /tmp/megba_federation_smoke.XXXXXX)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$LOCALITY_OUT" "$CHAOS_SINK" "$TRIAGE_SINK"; rm -rf "$FED_DIR"' EXIT
JAX_PLATFORMS=cpu MEGBA_FED_DIR="$FED_DIR" \
MEGBA_METRICS=1 MEGBA_TRACE=1 MEGBA_FLIGHT="$FED_DIR/flight.jsonl" \
  python - <<'PY'
import json
import os
import signal
import time

import numpy as np

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_fleet
from megba_tpu.observability import summarize
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.serving import (
    CompilePool, FleetProblem, FleetRouter, FleetStats, solve_many)

work = os.environ["MEGBA_FED_DIR"]
OPT = ProblemOption(dtype=np.float64, algo_option=AlgoOption(max_iter=6),
                    solver_option=SolverOption(max_iter=12, tol=1e-10))
engine = make_residual_jacobian_fn(mode=OPT.jacobian_mode)
fleet = [FleetProblem.from_synthetic(s, name=f"fed{i}")
         for i, s in enumerate(make_fleet(16, size_range=(12, 96), seed=0,
                                          dtype=np.float64))]

# -- exporter service: the control solve + the working-set export ------
store_root = os.path.join(work, "artifacts")
stats = FleetStats()
pool = CompilePool(stats=stats, artifacts=store_root)
control = solve_many(fleet, OPT, pool=pool, stats=stats)
manifest = os.path.join(work, "manifest.json")
pool.save_manifest(manifest, option=OPT)
t0 = time.perf_counter()
n_exported = pool.export_artifacts(engine, OPT)
print(f"federation smoke: exported {n_exported} bucket executables in "
      f"{time.perf_counter() - t0:.1f}s")
assert n_exported >= 3, n_exported

# -- fresh replicas: 2 workers, millisecond-class warm, zero traces ----
sink = os.path.join(work, "telemetry.jsonl")
t0 = time.perf_counter()
router = FleetRouter(OPT, n_workers=2, artifacts=store_root,
                     manifest=manifest, strict_manifest=True,
                     telemetry=sink)
up_s = time.perf_counter() - t0
d0 = router.stats.as_dict()
for wid, cs in d0["cold_start"].items():
    assert cs["mode"] == "artifact", (wid, cs)
    assert cs["artifact_compiles"] == 0, (wid, cs)
loads = sum(cs["artifact_loads"] for cs in d0["cold_start"].values())
print(f"federation smoke: 2 workers artifact-warmed in {up_s:.1f}s "
      f"({loads} executables loaded, 0 compiled)")

# -- a real host loss mid-fleet ----------------------------------------
# submit_many: the fleet enqueues ATOMICALLY, so batch composition
# reproduces the exporter's solve_many batches exactly and the
# zero-trace assertion below cannot flake on a mid-submission partial
# pick (a different lane rung would miss the store and compile).
# Kill IMMEDIATELY after: nothing has resolved yet, several buckets
# are pending, so w1's serve thread is guaranteed to pick a batch and
# hit the dead pipe — deterministic reroutes >= 1 with no sleep race.
futs = router.submit_many(fleet)
victim = router.workers["w1"]
os.kill(victim.pid, signal.SIGKILL)
t0 = time.perf_counter()
router.flush()  # the no-wedge gate: returns with every future resolved
flush_s = time.perf_counter() - t0
results = [f.result(timeout=5) for f in futs]  # none may raise

# -- observability plane: merged metrics snapshot, idle-pull determinism
# (before close(): the pull needs the surviving worker's RPC alive) ----
from megba_tpu.observability import metrics as obs_metrics

snap = router.metrics_snapshot()
assert snap is not None, "metrics_snapshot returned None with plane armed"
assert obs_metrics.snapshot_to_json(snap) == \
    obs_metrics.snapshot_to_json(router.metrics_snapshot()), (
    "metrics_snapshot drifted between two pulls on an idle fleet")
prom = obs_metrics.render_prometheus(snap)
for series in ("megba_fleet_batch_latency_seconds_bucket{",
               "megba_solve_lm_iterations_bucket{",
               "megba_fed_dispatch_total{",
               "megba_fed_worker_lost_total{"):
    assert series in prom, f"missing {series!r} in merged exposition"
n_series = sum(1 for l in prom.splitlines() if not l.startswith("#"))
print(f"federation smoke: merged metrics snapshot OK "
      f"({len(snap['metrics'])} families, {n_series} samples, "
      "2 idle pulls bitwise-equal)")

# -- concurrency stress (ISSUE 17): 200-problem fleet with the metrics
# plane pulled concurrently from 4 reader threads WHILE solving.  The
# pulls ride the same RPC stream as dispatch (WorkerHandle.request's
# ticket-turn ordering) and the same router lock as the dispatch
# bookkeeping (the guarded-by contracts) — a regression in either
# wedges flush or kills a serve thread, and a snapshot race shows up
# as a malformed/None pull.  All buckets are warm: zero compiles. -----
import threading as _threading

stress = [FleetProblem.from_synthetic(s, name=f"stress{i}")
          for i, s in enumerate(
              s for _ in range(13)
              for s in make_fleet(16, size_range=(12, 96), seed=0,
                                  dtype=np.float64))][:200]
stop_pulls = _threading.Event()
pull_errs = []
pull_counts = [0] * 4

def _puller(slot):
    while not stop_pulls.is_set():
        try:
            s = router.metrics_snapshot()
            assert s is not None and "metrics" in s, s
            pull_counts[slot] += 1
        except Exception as exc:  # noqa: BLE001 - collected and re-raised
            pull_errs.append(f"reader {slot}: {type(exc).__name__}: {exc}")
            return
        time.sleep(0.02)

readers = [_threading.Thread(target=_puller, args=(i,), daemon=True)
           for i in range(4)]
t0 = time.perf_counter()
for r in readers:
    r.start()
stress_futs = router.submit_many(stress)
router.flush()
stress_results = [f.result(timeout=5) for f in stress_futs]
stop_pulls.set()
for r in readers:
    r.join(timeout=10)
assert not any(r.is_alive() for r in readers), "metrics reader wedged"
assert not pull_errs, pull_errs
assert min(pull_counts) >= 1, pull_counts
for i, r in enumerate(stress_results):
    # NOT bitwise vs control: 13 copies of one shape class co-batch
    # into lane compositions the 16-problem control never saw.  Close
    # agreement still catches cross-thread corruption cold.
    c = control[i % 16]
    assert int(r.status) == int(c.status), (r.name, r.status, c.status)
    assert np.allclose(r.cameras, c.cameras, rtol=1e-6, atol=1e-9), r.name
    assert np.allclose(r.cost, c.cost, rtol=1e-6), (r.name, r.cost, c.cost)

# Idle again: 4 concurrent pulls must merge to ONE bitwise snapshot.
idle_json = [None] * 4

def _idle_pull(slot):
    idle_json[slot] = obs_metrics.snapshot_to_json(router.metrics_snapshot())

idlers = [_threading.Thread(target=_idle_pull, args=(i,)) for i in range(4)]
for r in idlers:
    r.start()
for r in idlers:
    r.join(timeout=10)
assert all(j is not None for j in idle_json), "idle pull hung or died"
assert len(set(idle_json)) == 1, "concurrent idle pulls disagree"
print(f"federation smoke: 200-problem stress under {sum(pull_counts)} "
      f"concurrent metric pulls in {time.perf_counter() - t0:.1f}s, "
      "4 idle pulls bitwise-equal, 200/200 results match control")

router.close()
d = router.stats.as_dict()
assert d["workers_lost"] == 1 and d["lost_workers"] == ["w1"], d
assert d["reroutes"] >= 1, d
assert sum(d["problems_by_worker"].values()) == 216, d  # 16 + 200 stress
assert d["first_solve"]["w0"]["traces"] == 0, d["first_solve"]
for r, c in zip(results, control):
    assert r.cameras.tobytes() == c.cameras.tobytes(), r.name
    assert r.cost.tobytes() == c.cost.tobytes(), r.name
    assert int(r.status) == int(c.status), r.name
print(f"federation smoke: w1 SIGKILLed mid-fleet, {d['reroutes']} problems "
      f"rerouted, flush returned in {flush_s:.1f}s, 16/16 BITWISE vs the "
      "single-host solve_many control")

# -- merged Chrome/Perfetto trace export (router + worker pids) --------
from megba_tpu.observability import spans as obs_spans

trace_path = os.path.join(work, "trace.json")
recorded = obs_spans.default_recorder().drain()
assert recorded, "no spans recorded with MEGBA_TRACE armed"
obs_spans.write_chrome_trace(trace_path, recorded)
with open(trace_path) as fh:
    doc = json.load(fh)
events = doc["traceEvents"]
assert events and all("ph" in e and "pid" in e for e in events), "bad events"
names = {e["name"] for e in events if e["ph"] == "X"}
assert "fed_dispatch" in names and "worker_solve" in names, names
procs = {e["args"]["name"] for e in events if e["ph"] == "M"}
assert len(procs) >= 2, f"trace spans only {procs} — worker spans missing"
traces = {e["args"]["trace_id"] for e in events
          if e["ph"] == "X" and "trace_id" in e.get("args", {})}
print(f"federation smoke: merged trace OK ({len(events)} events across "
      f"{sorted(procs)}, {len(traces)} traces)")

# -- flight-recorder dump left by the w1 host loss ---------------------
from megba_tpu.observability import flight as obs_flight

dumps = obs_flight.load_dumps(os.environ["MEGBA_FLIGHT"])
assert dumps, "no flight dump on disk after the w1 SIGKILL"
assert any(dmp["reason"].startswith("worker_lost") for dmp in dumps), (
    [dmp["reason"] for dmp in dumps])
kinds = {e["kind"] for dmp in dumps for e in dmp["events"]}
assert "worker_lost" in kinds, kinds
print(f"federation smoke: flight dump OK ({len(dumps)} dump(s), "
      f"kinds={sorted(kinds)})")

# -- aggregate + fleet CLI render the merged telemetry streams ---------
streams = [p for p in (sink, sink + ".w0", sink + ".w1")
           if os.path.exists(p)]
out = summarize.aggregate_paths(streams)
print(out)
assert "1 workers lost" in out, out
assert "rerouted" in out, out
assert "cold start w0: artifact" in out, out
assert "first solve 0 traces" in out, out
fleet_out = summarize.fleet_paths(streams)
print(fleet_out)
assert "traced:" in fleet_out, (
    "fleet table shows no traced solves with MEGBA_TRACE armed")
PY
echo "federation smoke OK"

# Elastic chaos smoke (ISSUE 9): a REAL 2-process gloo solve on the
# venice-10% configuration (f64), rank 1 SIGKILL'd the moment the first
# world-2 snapshot lands.  Rank 0 must surface a typed WorkerLost
# within the watchdog budget (latency asserted), resume at world 1 from
# the schema-v3 snapshot via resume_elastic, EXIT 0 on its own (the
# harness's survivor wait is the no-wedge gate), and match the
# uninterrupted world-2 run at rtol 1e-6 on cost+params with equal
# SolveStatus.  `summarize --aggregate` must render the elastic
# counters from the telemetry stream.  Gated on the same gloo probe as
# the multi-process pytest lane: a jaxlib without CPU collectives skips
# loudly instead of failing.
if JAX_PLATFORMS=cpu python -c "import sys
from megba_tpu.parallel.multihost import cpu_cross_process_collectives_available
sys.exit(0 if cpu_cross_process_collectives_available() else 3)"; then
ELASTIC_DIR=$(mktemp -d /tmp/megba_elastic_smoke.XXXXXX)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$LOCALITY_OUT" "$CHAOS_SINK" "$TRIAGE_SINK"; rm -rf "$FED_DIR" "$ELASTIC_DIR"' EXIT
JAX_PLATFORMS=cpu MEGBA_ELASTIC_DIR="$ELASTIC_DIR" python - <<'PY'
import importlib.util
import os
import re
import socket
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

import numpy as np

from megba_tpu.observability import summarize
from megba_tpu.robustness.harness import run_world_until_snapshot_then_kill
from megba_tpu.utils.checkpoint import load_state

work = os.environ["MEGBA_ELASTIC_DIR"]
repo = os.getcwd()
worker = os.path.join(repo, "tests", "_elastic_worker.py")

with socket.socket() as s:
    s.bind(("localhost", 0))
    port = s.getsockname()[1]

hb = os.path.join(work, "hb")
ck0 = os.path.join(work, "ck.r0.npz")
ck1 = os.path.join(work, "ck.r1.npz")
out0 = os.path.join(work, "result.npz")
sink = os.path.join(work, "telemetry.jsonl")
env = dict(os.environ)
env.pop("XLA_FLAGS", None)  # each worker pins its own single device
env["JAX_PLATFORMS"] = "cpu"
env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
env["MEGBA_TELEMETRY"] = sink


def argv(rank, ck, out):
    return [sys.executable, worker, str(rank), str(port), "2", ck, out,
            "venice10", hb]


t0 = time.monotonic()
outcome = run_world_until_snapshot_then_kill(
    [argv(0, ck0, out0), argv(1, ck1, "-")], ck0, kill_rank=1,
    rendezvous_argv=[sys.executable, "-m", "megba_tpu.parallel.multihost",
                     "--serve", str(port), "2"],
    timeout=1800.0, survivor_timeout=1800.0, env=env)
print(f"elastic smoke: world-2 venice-10% ran {time.monotonic() - t0:.1f}s, "
      f"rcs={outcome.returncodes}")
assert outcome.returncodes[1] < 0, outcome.outputs[1]
assert outcome.returncodes[0] == 0, outcome.outputs[0]
out = outcome.outputs[0]
m = re.search(r"ELASTIC-DETECT kind=(\w+) latency=([0-9.]+) "
              r"budget=([0-9.]+)", out)
assert m, f"no detection line:\n{out}"
kind, latency, budget = m.group(1), float(m.group(2)), float(m.group(3))
assert kind == "worker_lost", out
assert latency <= budget, (latency, budget)
print(f"elastic smoke: rank 1 loss detected in {latency:.3f}s "
      f"(watchdog budget {budget:.0f}s)")
assert "ELASTIC-RESUME world=1" in out, out
assert int(load_state(ck0)["world_size"]) == 1

# Parity vs the uninterrupted world-2 run (single-process, virtual
# devices: same mesh size, same program, same collectives as the
# 2-process world — the equivalence test_multihost.py pins).
spec = importlib.util.spec_from_file_location("_elastic_worker", worker)
ew = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ew)
from megba_tpu.algo.checkpointed import solve_checkpointed
from megba_tpu.common import JacobianMode
from megba_tpu.ops.residuals import make_residual_jacobian_fn

s, option = ew.build_problem("venice10", 2)
f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
ref = solve_checkpointed(
    f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
    checkpoint_path=os.path.join(work, "clean.npz"),
    checkpoint_every=ew.CHECKPOINT_EVERY, use_tiled=False)
res = dict(np.load(out0))
assert int(res["status"]) == int(ref.status), (
    int(res["status"]), int(ref.status))
np.testing.assert_allclose(float(res["cost"]), float(ref.cost), rtol=1e-6)
np.testing.assert_allclose(res["cameras"], np.asarray(ref.cameras),
                           rtol=1e-6, atol=1e-9)
np.testing.assert_allclose(res["points"], np.asarray(ref.points),
                           rtol=1e-6, atol=1e-9)
gap = abs(float(res["cost"]) - float(ref.cost)) / abs(float(ref.cost))
print(f"elastic smoke: shrink-world parity OK "
      f"(cost relgap {gap:.2e}, status {int(ref.status)})")

agg = summarize.aggregate_paths([sink])
print(agg)
assert "1 workers lost" in agg and "1 resumes" in agg, agg
assert "time-to-detection" in agg, agg
PY
echo "elastic chaos smoke OK"
else
echo "elastic chaos smoke SKIPPED: jaxlib CPU client lacks gloo collectives"
fi

# Network-chaos federation smoke (ISSUE 20): the multi-host transport
# under a hostile network.  The SAME 16-problem fleet is solved three
# ways — single-host solve_many (control), a 2-worker PIPE fleet, and
# a 2-worker TCP fleet whose workers dial the router THROUGH a
# deterministic chaos proxy (robustness/netfaults.py) — and all three
# must agree BITWISE (shape-class padding exactness makes the carrier
# result-invariant).  Mid-flight the proxy PARTITIONS the fleet while
# a cold-bucket solve is executing on a worker: the worker's reply
# send dies, it re-dials under seeded backoff (refused until heal),
# re-registers with `resume`, and the router's stranded reader resends
# the SAME sequence id — which the worker answers from its reply cache
# (the dedup counter is asserted: a resend can never double-solve).
# One worker is then SIGKILLed — a real host loss, distinct from the
# connection loss above: its problems must re-route to the survivor,
# typed and counted, and flush() must return with every future
# resolved (the no-wedge gate).  Every transport event must land in
# all three observability planes (metrics, spans, flight ring).
NETFED_DIR=$(mktemp -d /tmp/megba_netchaos_smoke.XXXXXX)
trap 'rm -f "$SMOKE" "$FORCING_OUT" "$LOCALITY_OUT" "$CHAOS_SINK" "$TRIAGE_SINK"; rm -rf "$FED_DIR" ${ELASTIC_DIR:+"$ELASTIC_DIR"} "$NETFED_DIR"' EXIT
JAX_PLATFORMS=cpu MEGBA_NETFED_DIR="$NETFED_DIR" \
MEGBA_METRICS=1 MEGBA_TRACE=1 MEGBA_FLIGHT="$NETFED_DIR/flight.jsonl" \
  python - <<'PY'
import os
import signal
import socket
import time

import numpy as np

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)

from megba_tpu.utils.backend import enable_persistent_compile_cache

enable_persistent_compile_cache()

from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_fleet
from megba_tpu.observability import metrics as obs_metrics
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.robustness.netfaults import ChaosTcpProxy
from megba_tpu.serving.transport import ReconnectPolicy
from megba_tpu.serving import (
    CompilePool, FleetProblem, FleetRouter, FleetStats, solve_many)

work = os.environ["MEGBA_NETFED_DIR"]
OPT = ProblemOption(dtype=np.float64, algo_option=AlgoOption(max_iter=6),
                    solver_option=SolverOption(max_iter=12, tol=1e-10))
engine = make_residual_jacobian_fn(mode=OPT.jacobian_mode)
fleet = [FleetProblem.from_synthetic(s, name=f"net{i}")
         for i, s in enumerate(make_fleet(16, size_range=(12, 96), seed=3,
                                          dtype=np.float64))]

# -- single-host control + artifact export (millisecond worker warms) --
store = os.path.join(work, "artifacts")
stats = FleetStats()
pool = CompilePool(stats=stats, artifacts=store)
control = solve_many(fleet, OPT, pool=pool, stats=stats)
manifest = os.path.join(work, "manifest.json")
pool.save_manifest(manifest, option=OPT)
n_exported = pool.export_artifacts(engine, OPT)
print(f"network chaos smoke: exported {n_exported} bucket executables")

# A cold bucket the manifest does NOT cover (the 16-fleet's sizes pad
# to <=128 points; these pad to 256): its compile-on-dispatch runs for
# seconds on the worker — a deterministic in-flight window for the
# partition below (and a live ColdDispatchWarning).
big = [FleetProblem.from_synthetic(s, name=f"cold{i}")
       for i, s in enumerate(make_fleet(2, size_range=(150, 220), seed=9,
                                        dtype=np.float64))]
big_control = solve_many(big, OPT, pool=pool, stats=stats)

# -- pipe fleet: the same-host carrier, bitwise vs control -------------
with FleetRouter(OPT, n_workers=2, artifacts=store, manifest=manifest,
                 strict_manifest=True) as pipe_router:
    pipe_futs = pipe_router.submit_many(fleet)
    pipe_router.flush()
    pipe_results = [f.result(timeout=5) for f in pipe_futs]
for r, c in zip(pipe_results, control):
    assert r.cameras.tobytes() == c.cameras.tobytes(), r.name
    assert r.cost.tobytes() == c.cost.tobytes(), r.name
    assert int(r.status) == int(c.status), r.name
print("network chaos smoke: pipe fleet 16/16 BITWISE vs solve_many")

# -- TCP fleet, every worker connection through the chaos proxy --------
# The proxy must exist before the router (workers dial THROUGH it at
# spawn), but it needs the router's port — so a probe socket picks the
# port first and the router binds it explicitly.
probe = socket.socket()
probe.bind(("127.0.0.1", 0))
port = probe.getsockname()[1]
probe.close()
proxy = ChaosTcpProxy(f"127.0.0.1:{port}")
sink = os.path.join(work, "telemetry.jsonl")
t0 = time.perf_counter()
# The reconnect window must outlive a worker-side cold compile: the
# worker can only notice the severed link and re-dial AFTER its
# in-flight solve returns, and the partitioned cold bucket below
# compiles for tens of seconds on a CPU runner.
router = FleetRouter(OPT, n_workers=2, artifacts=store, manifest=manifest,
                     strict_manifest=True, transport="tcp",
                     bind=f"127.0.0.1:{port}", advertise=proxy.address,
                     token="netchaos-smoke", telemetry=sink,
                     reconnect=ReconnectPolicy(window_s=240.0))
print(f"network chaos smoke: 2 TCP workers registered through the "
      f"proxy in {time.perf_counter() - t0:.1f}s")

futs = router.submit_many(fleet)
router.flush()
results = [f.result(timeout=5) for f in futs]
for r, c in zip(results, control):
    assert r.cameras.tobytes() == c.cameras.tobytes(), r.name
    assert r.cost.tobytes() == c.cost.tobytes(), r.name
    assert int(r.status) == int(c.status), r.name
print("network chaos smoke: TCP fleet 16/16 BITWISE vs solve_many "
      "AND the pipe fleet")


def merged_counter(name):
    snap = router.metrics_snapshot()
    fam = (snap or {}).get("metrics", {}).get(name)
    return 0 if fam is None else int(sum(fam["series"].values()))


# -- mid-flight partition during a cold-bucket solve -------------------
futs2 = router.submit_many(big)
# Partition only once the batch is IN FLIGHT (request sent, reply
# pending): the worker is then mid-compile for seconds — the reply
# send must die on the severed connection and the router must resend.
deadline = time.monotonic() + 30.0
while router._inflight < 1:
    assert time.monotonic() < deadline, "cold batch never dispatched"
    time.sleep(0.005)
time.sleep(0.3)  # let the request cross the proxy relay
proxy.partition()
time.sleep(1.2)
proxy.heal()
t0 = time.perf_counter()
router.flush()
flush_s = time.perf_counter() - t0
results2 = [f.result(timeout=5) for f in futs2]
for r, c in zip(results2, big_control):
    assert r.cameras.tobytes() == c.cameras.tobytes(), r.name
    assert r.cost.tobytes() == c.cost.tobytes(), r.name
n_reconnect = merged_counter("megba_transport_reconnect_total")
n_resend = merged_counter("megba_transport_resend_total")
n_conn_lost = merged_counter("megba_transport_conn_lost_total")
n_dedup = merged_counter("megba_transport_dedup_total")
assert n_conn_lost >= 1, "partition left no conn_lost event"
assert n_reconnect >= 1, "no worker re-registered after the heal"
assert n_resend >= 1, "stranded reader never resent its request"
assert n_dedup >= 1, ("resend was re-executed, not served from the "
                      "worker reply cache")
counts = proxy.event_counts()
assert counts["partition"] == 1 and counts["heal"] == 1, counts
assert counts.get("refused", 0) >= 1, counts  # backoff dials hit the wall
print(f"network chaos smoke: partition healed — flush in {flush_s:.1f}s, "
      f"{n_conn_lost} conn_lost / {n_reconnect} reconnects / "
      f"{n_resend} resends / {n_dedup} dedup hits, 2/2 cold-bucket "
      "results BITWISE (no double-solve), proxy "
      f"refused {counts.get('refused', 0)} dials while partitioned")

# -- a real host loss: SIGKILL one worker, reroute to the survivor -----
victim = router.workers["w1"]
os.kill(victim.pid, signal.SIGKILL)
futs3 = router.submit_many(fleet)
t0 = time.perf_counter()
router.flush()  # the no-wedge gate: pending==0 and inflight==0
flush_s = time.perf_counter() - t0
results3 = [f.result(timeout=5) for f in futs3]
for r, c in zip(results3, control):
    assert r.cameras.tobytes() == c.cameras.tobytes(), r.name
    assert r.cost.tobytes() == c.cost.tobytes(), r.name
router.close()
d = router.stats.as_dict()
assert d["workers_lost"] == 1 and d["lost_workers"] == ["w1"], d
assert d["reroutes"] >= 1, d
assert d["cold_dispatches"] >= 2, d  # the unmanifested big bucket
print(f"network chaos smoke: w1 SIGKILLed — {d['reroutes']} problems "
      f"rerouted to the survivor, flush returned in {flush_s:.1f}s, "
      "16/16 BITWISE vs control")

# -- transport events visible in spans + flight ring -------------------
from megba_tpu.observability import flight as obs_flight
from megba_tpu.observability import spans as obs_spans

recorded = obs_spans.default_recorder().drain()
span_names = {s["name"] for s in recorded}
assert any(n.startswith("transport_") for n in span_names), span_names
dumps = obs_flight.load_dumps(os.environ["MEGBA_FLIGHT"])
assert dumps, "no flight dump after the w1 SIGKILL"
kinds = {e["kind"] for dmp in dumps for e in dmp["events"]}
assert "worker_lost" in kinds, kinds
assert any(k.startswith("transport_") for k in kinds), kinds
print(f"network chaos smoke: transport events in spans "
      f"({sorted(n for n in span_names if n.startswith('transport_'))}) "
      f"and flight ring ({sorted(k for k in kinds if k.startswith('transport_'))})")
proxy.close()
PY
echo "network chaos smoke OK"
