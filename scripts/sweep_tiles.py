"""Tile/block constant sweep for the segtiles engine (weak-spot: the
DEFAULT_TILE_*/BLOCK_* constants were VMEM back-of-envelope guesses).

For each candidate (tile_cam, block_cam, tile_pt, block_pt) this builds
the dual plans host-side and reports the analytic cost model everywhere:

  - padding overhead (slots / real edges) per plan,
  - one-hot matmul FLOPs per Hessian build and per PCG coupling product
    (the [B, T] one-hot contraction is pure overhead the MXU eats — the
    question the sweep answers is when it stops being free),
  - per-kernel VMEM footprint (all operand + output blocks must fit).

On a TPU backend it ALSO times the three hot kernels per candidate
(jtj_grad_reduce, coupling_expand, coupling_reduce) and ranks by
measured per-LM-iteration kernel time; off-TPU the ranking is by the
analytic model only (clearly labelled).  Writes SWEEP_RAW.json.

Usage: MEGBA_BENCH_CONFIG=venice [MEGBA_BENCH_SCALE=x] python scripts/sweep_tiles.py
Never kill this mid-run on the TPU (single-client tunnel).
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIG = os.environ.get("MEGBA_BENCH_CONFIG", "venice")
SCALE = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))

# Candidate grids.  block_cam stays modest (camera axis is short); the
# point axis trades padding (small block -> more all-padding tiles when
# points/block are sparse) against one-hot width (big block -> wider
# [B, T] contraction per tile).
TILES_CAM = [1024, 2048, 4096]
BLOCKS_CAM = [128, 256]
TILES_PT = [512, 1024, 2048]
BLOCKS_PT = [1024, 2048, 4096]

CD, PD, OD = 9, 3, 2


def analytic(plan_c, plan_p):
    """Per-LM-iteration one-hot FLOPs + padding + VMEM for one candidate."""
    sc, sp = plan_c.n_slots, plan_p.n_slots
    bc, bp = plan_c.block, plan_p.block
    # One-hot contraction FLOPs: every slot row is matmul'd against its
    # tile's [B, T] one-hot.  Build touches (cd*cd+cd) cam rows and
    # (pd*pd+pd) pt rows; each PCG iteration runs one expand (d rows) +
    # one reduce (d rows) on each side.
    build = 2 * (CD * CD + CD) * bc * sc + 2 * (PD * PD + PD) * bp * sp
    per_pcg = 2 * (CD * bc * sc + PD * bp * sp) * 2  # expand+reduce, 2 sides
    pad_c = sc / max(plan_c.n_edges, 1)
    pad_p = sp / max(plan_p.n_edges, 1)
    # VMEM per grid step (f32 words): the biggest kernel is the jtj
    # build — J block [od*cd, T], onehot [B, T], feature rows
    # [cd*cd+cd, T], output [cd*cd+cd, B].
    feat = CD * CD + CD
    vmem_words = (OD * CD + bc + feat) * plan_c.tile + feat * bc
    return dict(
        onehot_build_flops=build,
        onehot_per_pcg_flops=per_pcg,
        padding_cam=round(pad_c, 4),
        padding_pt=round(pad_p, 4),
        vmem_mb=round(vmem_words * 4 / 2**20, 2),
    )


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache,
        install_graceful_term,
    )

    install_graceful_term()
    enable_persistent_compile_cache()
    import jax

    from megba_tpu.utils.backend import respect_jax_platforms

    respect_jax_platforms()
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench as B  # noqa: E402

    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.segtiles import (
        build_tile_plan,
        coupling_expand,
        coupling_reduce,
        device_plan,
        jtj_grad_reduce,
        probe_kernels,
    )

    cfg = B.CONFIGS[CONFIG]
    nc = max(8, int(cfg.cameras * SCALE))
    npts = max(64, int(cfg.points * SCALE))
    s = make_synthetic_bal(
        num_cameras=nc, num_points=npts, obs_per_point=cfg.obs_per_point,
        seed=0, param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)
    nE = s.obs.shape[0]
    on_tpu = jax.default_backend() == "tpu" and probe_kernels()
    print(f"backend={jax.default_backend()} kernels={'ON' if on_tpu else 'off'} "
          f"config={CONFIG} {nc} cams / {npts} pts / {nE} edges", flush=True)

    rng = np.random.default_rng(0)

    def timed(fn, *args, reps=5):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    rows = []
    for tc, bc, tp, bp in itertools.product(
            TILES_CAM, BLOCKS_CAM, TILES_PT, BLOCKS_PT):
        t0 = time.perf_counter()
        plan_c = build_tile_plan(s.cam_idx, nc, tc, bc)
        pt_of_slot = np.where(
            plan_c.mask > 0, s.pt_idx[plan_c.perm], npts - 1)
        plan_p = build_tile_plan(pt_of_slot.astype(np.int64), npts, tp, bp)
        plan_s = time.perf_counter() - t0
        row = dict(tile_cam=tc, block_cam=bc, tile_pt=tp, block_pt=bp,
                   n_slots_cam=plan_c.n_slots, n_slots_pt=plan_p.n_slots,
                   plan_build_s=round(plan_s, 3), **analytic(plan_c, plan_p))
        if on_tpu:
            dpc, dpp = device_plan(plan_c), device_plan(plan_p)
            mc = jnp.asarray(plan_c.mask)
            Jc = jnp.asarray(rng.standard_normal(
                (OD * CD, plan_c.n_slots)).astype(np.float32)) * mc
            rr = jnp.asarray(rng.standard_normal(
                (OD, plan_c.n_slots)).astype(np.float32)) * mc
            mp = jnp.asarray(plan_p.mask)
            Jp = jnp.asarray(rng.standard_normal(
                (OD * PD, plan_p.n_slots)).astype(np.float32)) * mp
            vt = jnp.asarray(rng.standard_normal(
                (CD, nc)).astype(np.float32))
            vtp = jnp.asarray(rng.standard_normal(
                (PD, npts)).astype(np.float32))
            u = jnp.asarray(rng.standard_normal(
                (OD, plan_p.n_slots)).astype(np.float32)) * mp
            t_build = timed(lambda: jtj_grad_reduce(
                Jc, rr, dpc, use_kernels=True))
            t_exp = timed(lambda: coupling_expand(
                vtp, Jp, dpp, PD, use_kernels=True))
            t_red = timed(lambda: coupling_reduce(
                Jp, u, dpp, PD, use_kernels=True))
            row.update(
                jtj_ms=round(t_build * 1e3, 3),
                coupling_expand_ms=round(t_exp * 1e3, 3),
                coupling_reduce_ms=round(t_red * 1e3, 3),
                per_pcg_ms=round((t_exp + t_red) * 1e3, 3),
            )
        rows.append(row)
        print(json.dumps(row), flush=True)

    key = (lambda r: r["jtj_ms"] + 30 * r["per_pcg_ms"]) if on_tpu else (
        lambda r: r["onehot_build_flops"] + 30 * r["onehot_per_pcg_flops"])
    best = min(rows, key=key)
    ranking = "measured (jtj + 30 PCG iters)" if on_tpu else (
        "ANALYTIC ONLY (no TPU): one-hot FLOPs, jtj + 30 PCG iters")
    print(f"\nbest by {ranking}:\n{json.dumps(best)}", flush=True)
    with open("SWEEP_RAW.json", "w") as fh:
        json.dump(dict(config=CONFIG, scale=SCALE,
                       backend=jax.default_backend(), measured=bool(on_tpu),
                       ranking=ranking, rows=rows, best=best), fh, indent=1)
    print("wrote SWEEP_RAW.json", flush=True)


if __name__ == "__main__":
    main()
