"""Double-semantics parity for the POSE-GRAPH family (f64 vs f32).

DOUBLE_PARITY.json covers the flagship BA family; this is the same
protocol for the second family: an identical city-scale pose graph
(generated once in f64, cast for the f32 run) solved by solve_pgo in
both dtypes with identical flags, per-iteration curves captured from
the shared verbose emitter, final costs compared.  With measurement
noise on, the optimum is a nonzero cost both dtypes must agree on
(noise-free graphs drive the cost to the dtype floor, where a relative
comparison is meaningless).

Writes PGO_DOUBLE_PARITY.json; nonzero exit on parity failure.

Usage:
  [MEGBA_PGO_POSES=20000] [MEGBA_PGO_CLOSURES=4000] \
      python scripts/pgo_double_parity.py
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REL_TOL = 1e-4


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache, respect_jax_platforms)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    respect_jax_platforms()
    enable_persistent_compile_cache()

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import (
        make_synthetic_pose_graph, solve_pgo, spanning_tree_init)

    n_poses = int(os.environ.get("MEGBA_PGO_POSES", "20000"))
    closures = int(os.environ.get("MEGBA_PGO_CLOSURES", "4000"))
    g = make_synthetic_pose_graph(
        num_poses=n_poses, loop_closures=closures, meas_noise=0.01,
        drift_noise=0.05, seed=11)
    # Spanning-tree bootstrap (the standard PGO practice, and what the
    # examples use for drifted inits): without it a 20k-pose circle's
    # long-wavelength modes make LM+PCG converge too slowly for a
    # within-budget dtype comparison — the question here is the dtype
    # floor at the optimum, not large-graph preconditioning.
    poses0 = spanning_tree_init(
        g.poses0, g.edge_i, g.edge_j, g.meas)

    from megba_tpu.utils.curves import dtype_parity_payload

    def solve_for(dtype):
        option = ProblemOption(
            dtype=np.dtype(dtype),
            algo_option=AlgoOption(
                max_iter=int(os.environ.get("MEGBA_PGO_ITERS", "120")),
                epsilon1=1e-14, epsilon2=1e-16),
            solver_option=SolverOption(max_iter=100, tol=1e-12,
                                       refuse_ratio=1e30),
        )
        return solve_pgo(
            poses0.astype(dtype), g.edge_i, g.edge_j,
            g.meas.astype(dtype), option, verbose=True)

    out = {"poses": n_poses,
           "edges": int(g.edge_i.shape[0]),
           "meas_noise": 0.01}
    out.update(dtype_parity_payload(
        solve_for, REL_TOL, label=f"pgo {n_poses}",
        block_on=lambda r: jax.block_until_ready(r.cost)))

    path = os.environ.get("MEGBA_PGO_PARITY_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PGO_DOUBLE_PARITY.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {path}", flush=True)
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
