"""PGO capability run at city-scale: 50k poses / 60k edges end to end.

Companion to scripts/final_scale_cpu.py (the BA Final-13682 capability
run): executes the full SE(3) pose-graph pipeline — batched synthetic
generation (core/host_se3), drifted odometry init, LM + matrix-free PCG
(models/pgo.py) — at a scale matching the large public pose-graph
datasets (city10k, sphere2500 are 10-25x smaller), and records the
evidence JSON the round ledger commits.

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/pgo_scale_cpu.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from megba_tpu.utils.backend import respect_jax_platforms


def main() -> None:
    respect_jax_platforms()
    import jax

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.models.pgo import make_synthetic_pose_graph, solve_pgo

    num_poses = int(os.environ.get("MEGBA_PGO_SCALE_POSES", 50_000))
    closures = int(os.environ.get("MEGBA_PGO_SCALE_CLOSURES", 15_000))

    t0 = time.perf_counter()
    # drift 0.005/step still compounds to a badly bent circle over 50k
    # odometry steps (max translation drift ~ pose-graph diameter); the
    # noise-free measurements mean the solver must drive the cost to ~0
    # for the run to count as converged, not just improved.
    g = make_synthetic_pose_graph(
        num_poses=num_poses, loop_closures=closures, drift_noise=0.005,
        meas_noise=0.0, seed=0)
    t_gen = time.perf_counter() - t0
    n_e = len(g.edge_i)
    print(f"generated {num_poses} poses / {n_e} edges in {t_gen:.1f}s",
          flush=True)

    option = ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=30, epsilon1=1e-10,
                               epsilon2=1e-14),
        solver_option=SolverOption(max_iter=60, tol=1e-10,
                                   refuse_ratio=1e30),
    )
    t0 = time.perf_counter()
    res = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option,
                    verbose=True)
    elapsed = time.perf_counter() - t0

    drift0 = float(np.max(np.linalg.norm(
        g.poses0[:, 3:] - g.poses_gt[:, 3:], axis=1)))
    drift1 = float(np.max(np.linalg.norm(
        np.asarray(res.poses)[:, 3:] - g.poses_gt[:, 3:], axis=1)))
    out = {
        "what": "SE(3) PGO capability run, full pipeline end-to-end",
        "backend": jax.devices()[0].platform,
        "num_poses": num_poses,
        "num_edges": n_e,
        "gen_seconds": round(t_gen, 2),
        "initial_cost": float(res.initial_cost),
        "final_cost": float(res.cost),
        "lm_iterations": int(res.iterations),
        "accepted": int(res.accepted),
        "pcg_iterations": int(res.pcg_iterations),
        "elapsed_seconds": round(elapsed, 2),
        "lm_iters_per_sec": round(int(res.iterations) / elapsed, 4),
        "max_translation_drift_before": round(drift0, 4),
        "max_translation_drift_after": round(drift1, 6),
    }
    path = os.path.join(os.path.dirname(__file__), "..", "PGO_SCALE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
