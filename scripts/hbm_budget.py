"""HBM budget for the Final-13682 configuration on one v5e chip.

VERDICT r04 item 7 asks: does final-13682 (29.0M observations) fit on a
single v5e (16 GB HBM), at what dtype/chunking?  Two answers here:

1. **XLA's own number**: lower + compile the production LM program at a
   chosen scale on the current backend and read
   `compiled.memory_analysis()` (argument/output/temp/generated-code
   sizes).  Run at full scale when RAM allows; smaller scales give the
   per-edge slope for extrapolation (edge-proportional buffers dominate
   past venice scale).
2. **Analytic live-set model** from the implicit path's own shapes
   (linear_system/builder.py, solver/pcg.py): per-edge residuals r
   [od=2], Jacobians Jc [od*cd=18] and Jp [od*pd=6], obs [2], indices
   [2 int32], mask [1] — feature-major rows over nE — plus
   parameter-sized blocks (Hpp, Hll rows, PCG vectors) that stay
   sub-GB at any BAL scale.

Writes HBM_BUDGET.json and prints a table.  Usage:
  [MEGBA_BENCH_CONFIG=final] [MEGBA_BENCH_SCALE=0.1] \
      [MEGBA_MP=0|1] python scripts/hbm_budget.py
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_HBM = 16 * 1024**3  # bytes


def analytic_rows(n_cam, n_pt, n_edge, dtype_bytes, mixed):
    """Live-set bytes by buffer family for one implicit-path LM solve."""
    B = dtype_bytes
    cB = 2 if mixed else B  # bf16 coupling operands under mixed precision
    rows = {
        # Persistent per-edge operands (held across the whole solve):
        "obs [od=2, nE]": 2 * B * n_edge,
        "cam_idx+pt_idx [int32, nE]": 8 * n_edge,
        "mask [nE]": B * n_edge,
        # Linearization products (rebuilt each LM iteration, live
        # through every PCG iteration of that step):
        "r [2, nE]": 2 * B * n_edge,
        "Jc [18, nE]": 18 * cB * n_edge,
        "Jp [6, nE]": 6 * cB * n_edge,
        # Trial step keeps a second copy of r while rho is evaluated:
        "r_trial [2, nE]": 2 * B * n_edge,
        # Parameter-sized state (params + g + diag blocks + ~6 PCG
        # vectors on the reduced camera system + point-side rows):
        "params cam+pt (x2: current+trial)": 2 * (9 * n_cam + 3 * n_pt) * B,
        "Hpp [Nc,9,9] + Minv": 2 * 81 * n_cam * B,
        "Hll rows [9, Np] + inverse": 2 * 9 * n_pt * B,
        "g + PCG vectors (~8 param-sized)": 8 * (9 * n_cam + 3 * n_pt) * B,
    }
    return rows


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache, ensure_usable_backend,
        install_graceful_term)

    install_graceful_term()
    enable_persistent_compile_cache()
    fell_back = ensure_usable_backend()

    import jax

    import bench as B
    from megba_tpu.common import (
        AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    cfg_name = os.environ.get("MEGBA_BENCH_CONFIG", "final")
    scale = float(os.environ.get("MEGBA_BENCH_SCALE", "0.1"))
    mixed = os.environ.get("MEGBA_MP", "0") == "1"
    c = B.CONFIGS[cfg_name]
    n_cam = max(8, int(c.cameras * scale))
    n_pt = max(64, int(c.points * scale))
    s = make_synthetic_bal(
        num_cameras=n_cam, num_points=n_pt, obs_per_point=c.obs_per_point,
        seed=0, param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)
    n_edge = int(s.obs.shape[0])

    option = ProblemOption(
        dtype=np.float32, compute_kind=ComputeKind.IMPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL, mixed_precision_pcg=mixed,
        algo_option=AlgoOption(max_iter=8),
        solver_option=SolverOption(max_iter=30, tol=1e-10,
                                   refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    from megba_tpu.utils.meminfo import single_solve_memory_analysis

    xla = single_solve_memory_analysis(s, option, f)
    n_padded = xla.pop("n_edges_padded")

    rows = analytic_rows(n_cam, n_pt, n_padded, 4, mixed)
    total = sum(rows.values())
    # Full-scale extrapolation: per-edge bytes hold; parameter-sized
    # rows scale with the full counts.
    full_edges = 28_987_644
    fc, fp = c.cameras, c.points
    full_rows = analytic_rows(fc, fp, full_edges, 4, mixed)
    full_total = sum(full_rows.values())

    backend = jax.devices()[0].platform
    print(f"config {cfg_name} scale {scale} ({n_cam} cams, {n_pt} pts, "
          f"{n_padded} padded edges), mixed={mixed}, backend={backend}"
          + (" [CPU fallback]" if fell_back else ""))
    print(f"{'buffer family':44s} {'bytes':>14s} {'@full scale':>14s}")
    for k in rows:
        print(f"{k:44s} {rows[k]:>14,} {full_rows[k]:>14,}")
    print(f"{'TOTAL analytic live set':44s} {total:>14,} {full_total:>14,}")
    print(f"full-scale analytic vs v5e 16 GB: "
          f"{full_total / V5E_HBM:.1%} of HBM")
    if xla:
        print("XLA memory_analysis at this scale:", json.dumps(xla))

    payload = {
        "config": cfg_name, "scale": scale, "mixed": mixed,
        "backend": backend, "cpu_fallback": bool(fell_back),
        "cameras": n_cam, "points": n_pt, "edges_padded": n_padded,
        "analytic_rows_bytes": rows, "analytic_total_bytes": total,
        "full_scale": {"cameras": fc, "points": fp, "edges": full_edges,
                       "analytic_rows_bytes": full_rows,
                       "analytic_total_bytes": full_total,
                       "fraction_of_v5e_hbm": full_total / V5E_HBM},
        "xla_memory_analysis": xla or None,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "HBM_BUDGET.json")
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
