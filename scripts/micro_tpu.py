"""Microbenchmark of the per-PCG-iteration primitives at venice scale.

Run on the real chip: python scripts/micro_tpu.py
Times each primitive with block_until_ready over several reps.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

NE = 5_001_946 // 2048 * 2048 + 2048  # venice edges padded
NC = 1778
NP_ = 993_923


def timeit(name, fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:45s} {dt*1e3:10.3f} ms")
    return dt


def main():
    print(f"backend: {jax.default_backend()}  nE={NE}")
    rng = np.random.default_rng(0)
    cam_idx = np.sort(rng.integers(0, NC, NE)).astype(np.int32)
    pt_idx = rng.integers(0, NP_, NE).astype(np.int32)
    pt_sorted = np.sort(pt_idx)
    ci = jnp.asarray(cam_idx)
    pi = jnp.asarray(pt_idx)
    pis = jnp.asarray(pt_sorted)
    perm = jnp.asarray(rng.permutation(NE).astype(np.int32))

    p_cam = jnp.asarray(rng.standard_normal((9, NC)), jnp.float32)
    q_pt = jnp.asarray(rng.standard_normal((3, NP_)), jnp.float32)
    data9 = jnp.asarray(rng.standard_normal((9, NE)), jnp.float32)
    data3 = jnp.asarray(rng.standard_normal((3, NE)), jnp.float32)
    data2 = jnp.asarray(rng.standard_normal((2, NE)), jnp.float32)

    g_small = jax.jit(lambda p, i: jnp.take(p, i, axis=1))
    timeit("gather [9,Nc] by sorted cam_idx", g_small, p_cam, ci)
    timeit("gather [3,Np] by random pt_idx", g_small, q_pt, pi)
    timeit("gather [2,nE] by random perm", g_small, data2, perm)

    def scat(data, idx, n, sorted_):
        out = jnp.zeros((data.shape[0], n), data.dtype)
        return out.at[:, idx].add(
            data, indices_are_sorted=sorted_, mode="drop")

    s_cam = jax.jit(lambda d, i: scat(d, i, NC, True))
    s_pt = jax.jit(lambda d, i: scat(d, i, NP_, False))
    s_pt_srt = jax.jit(lambda d, i: scat(d, i, NP_, True))
    timeit("scatter-add [9,nE] -> Nc sorted", s_cam, data9, ci)
    timeit("scatter-add [3,nE] -> Np random", s_pt, data3, pi)
    timeit("scatter-add [3,nE] -> Np sorted", s_pt_srt, data3, pis)

    # segment_sum comparison
    from jax.ops import segment_sum

    ss = jax.jit(lambda d, i: segment_sum(
        d.T, i, num_segments=NC, indices_are_sorted=True))
    timeit("segment_sum edge-major -> Nc sorted", ss, data9, ci)

    # elementwise per-edge math: the implicit product rows
    def rowmath(Jc, pe):
        u = [sum(Jc[o * 9 + a] * pe[a] for a in range(9)) for o in range(2)]
        return jnp.stack([sum(u[o] for o in range(2))])

    Jc = jnp.asarray(rng.standard_normal((18, NE)), jnp.float32)
    rm = jax.jit(rowmath)
    pe = g_small(p_cam, ci)
    jax.block_until_ready(pe)
    timeit("row math Jc*pe [18,nE]", rm, Jc, pe)

    # comp_dot at PCG-vector size
    from megba_tpu.ops.accum import comp_dot

    v = jnp.asarray(rng.standard_normal((9, NC)), jnp.float32)
    cd_ = jax.jit(comp_dot)
    timeit("comp_dot [9,Nc]", cd_, v, v)
    big = jnp.asarray(rng.standard_normal((2, NE)), jnp.float32)
    timeit("comp_dot [2,nE] (cost reduction)", cd_, big, big)
    timeit("plain sum [2,nE]", jax.jit(lambda x: jnp.sum(x * x)), big, reps=5)

    # Pallas camera kernel at scale
    from megba_tpu.ops.pallas_kernels import (
        camera_hessian_gradient, camera_window_plan)

    ok, window = camera_window_plan(cam_idx)
    print(f"pallas plan ok={ok} window={window}")
    if ok:
        r2 = jnp.asarray(rng.standard_normal((2, NE)), jnp.float32)
        f = jax.jit(lambda jc, r, i: camera_hessian_gradient(
            jc, r, i, num_cameras=NC, window=window))
        timeit("pallas camera hessian+grad (full build)", f, Jc, r2, ci)


if __name__ == "__main__":
    main()
