#!/bin/bash
# Detached TPU measurement pass: tests -> benches -> profile -> sweep.
# Launch with:  nohup bash scripts/run_tpu_round.sh > tpu_round.log 2>&1 &
# NEVER kill any of these processes mid-run (single-client tunnel:
# killing a claim holder wedges it for hours).  Everything is sized to
# finish; progress is appended to tpu_round.log.
#
# Every artifact is git-committed THE MOMENT it lands (the tunnel wedge
# has twice eaten end-of-round results): per-config bench JSON, the tpu
# test-lane log, PROFILE_RAW.json, SWEEP_RAW.json, and tpu_round.log
# itself.
set -u -o pipefail
cd "$(dirname "$0")/.."
echo "=== $(date -u) TPU round start ==="

commit_now() {
  # Best-effort immediate evidence commit; never let a git hiccup (e.g.
  # a concurrent commit holding the index lock) or a missing artifact
  # (a failed producer) stop the measurements or drop the log commit.
  local present=(tpu_round.log)
  local f
  for f in "$@"; do [ -e "$f" ] && present+=("$f"); done
  git add -A -- "${present[@]}" 2>/dev/null || true
  git commit -m "$COMMIT_MSG" --only -- "${present[@]}" \
    >/dev/null 2>&1 || true
}

probe() {
  python - <<'EOF'
import jax
print("devices:", jax.devices(), flush=True)
EOF
}

echo "--- probe"
if ! probe; then
  echo "probe failed; aborting"; exit 1
fi

echo "--- tpu test lane"
MEGBA_TPU_TESTS=1 python -m pytest tests/ -m tpu -p no:cacheprovider -q \
  2>&1 | tee tpu_test_lane.log
COMMIT_MSG="Record TPU test-lane run" commit_now tpu_test_lane.log

echo "--- benches"
for cfg in trafalgar venice ladybug final final_mixed; do
  echo "=== bench $cfg $(date -u) ==="
  if MEGBA_BENCH_CONFIG=$cfg python bench.py | tee "BENCH_tpu_${cfg}.json"
  then
    COMMIT_MSG="Record hardware bench result: ${cfg}" \
      commit_now "BENCH_tpu_${cfg}.json"
  else
    echo "bench $cfg FAILED"
  fi
done

echo "--- profile venice"
MEGBA_BENCH_CONFIG=venice python scripts/profile_phases.py || true
COMMIT_MSG="Record hardware phase profile (venice)" commit_now PROFILE_RAW.json

echo "--- tile/block sweep venice (measured)"
MEGBA_BENCH_CONFIG=venice python scripts/sweep_tiles.py || true
COMMIT_MSG="Record hardware tile/block sweep (venice)" commit_now SWEEP_RAW.json

echo "=== $(date -u) TPU round done ==="
COMMIT_MSG="Record TPU round log" commit_now tpu_round.log
