#!/bin/bash
# Detached TPU measurement pass: tests -> benches -> profile.
# Launch with:  nohup bash scripts/run_tpu_round.sh > tpu_round.log 2>&1 &
# NEVER kill any of these processes mid-run (single-client tunnel:
# killing a claim holder wedges it for hours).  Everything is sized to
# finish; progress is appended to tpu_round.log.
set -u
cd "$(dirname "$0")/.."
echo "=== $(date -u) TPU round start ==="

probe() {
  python - <<'EOF'
import jax
print("devices:", jax.devices(), flush=True)
EOF
}

echo "--- probe"
if ! probe; then
  echo "probe failed; aborting"; exit 1
fi

echo "--- tpu test lane"
MEGBA_TPU_TESTS=1 python -m pytest tests/ -m tpu -p no:cacheprovider -q

echo "--- benches"
for cfg in trafalgar venice ladybug final final_mixed; do
  echo "=== bench $cfg $(date -u) ==="
  MEGBA_BENCH_CONFIG=$cfg python bench.py || echo "bench $cfg FAILED"
done

echo "--- profile venice"
MEGBA_BENCH_CONFIG=venice python scripts/profile_phases.py || true

echo "=== $(date -u) TPU round done ==="
