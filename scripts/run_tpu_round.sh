#!/bin/bash
# Detached TPU measurement pass, smallest-first so every chip-minute of
# an unpredictable tunnel window lands evidence before the window can
# close (VERDICT r04 next-round item 1):
#
#   warmup (tiny shapes, populates the persistent compile cache)
#   -> TPU test lane (kernel correctness on hardware, VERDICT item 3)
#   -> tile/block sweep (pick tuned constants BEFORE macro numbers)
#   -> trafalgar bench -> phase profile -> venice -> final -> final_mixed
#
# Launch with:  nohup bash scripts/run_tpu_round.sh > tpu_round.log 2>&1 &
# NEVER kill any of these processes mid-run (single-client tunnel:
# killing a claim holder wedges it for hours).  Everything is sized to
# finish; progress is appended to tpu_round.log.
#
# Every artifact is git-committed THE MOMENT it lands (the tunnel wedge
# has twice eaten end-of-round results): per-config bench JSON, the tpu
# test-lane log, SWEEP_RAW.json, PROFILE_RAW.json, and tpu_round.log
# itself.
set -u -o pipefail
cd "$(dirname "$0")/.."
echo "=== $(date -u) TPU round start ==="

# Persistent XLA compile cache: belt (env vars, inherited by every
# child) and braces (enable_persistent_compile_cache() inside each
# entry point).  Venice-scale compiles cost tens of seconds to minutes;
# paying them once per shape EVER instead of once per process is the
# single biggest lever on measurement-per-chip-minute.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=0
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

commit_now() {
  # Best-effort immediate evidence commit; never let a git hiccup (e.g.
  # a concurrent commit holding the index lock) or a missing artifact
  # (a failed producer) stop the measurements or drop the log commit.
  local present=(tpu_round.log)
  local f
  for f in "$@"; do [ -e "$f" ] && present+=("$f"); done
  git add -A -- "${present[@]}" 2>/dev/null || true
  git commit -m "$COMMIT_MSG" --only -- "${present[@]}" \
    >/dev/null 2>&1 || true
}

probe() {
  python - <<'EOF'
import jax
print("devices:", jax.devices(), flush=True)
EOF
}

echo "--- probe"
if ! probe; then
  echo "probe failed; aborting"; exit 1
fi
# The bash probe above just proved the tunnel healthy; skip the per-
# entry-point subprocess re-probe (each one claims the single-client
# tunnel for up to 120s — chip-minutes spent proving what we know).
export MEGBA_BENCH_SKIP_PROBE=1

echo "--- warmup: tiny-shape compile pass (populates the persistent cache)"
# entry() + jit in one short process: proves end-to-end lowering on
# hardware in under a minute, and if the tunnel dies mid-window later
# runs of the same shapes start from the on-disk cache.  The SIGTERM
# handler goes in BEFORE jax so a fired timeout exits through PJRT
# teardown instead of orphaning the tunnel claim (the wedge cause).
timeout -k 60 900 python - <<'EOF' 2>&1 | tail -5
import signal
signal.signal(signal.SIGTERM, lambda s, f: (_ for _ in ()).throw(SystemExit(143)))
import __graft_entry__ as G
import jax
fn, args = G.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
print("warmup entry cost:", float(out[0]))
EOF
COMMIT_MSG="TPU warmup compile pass" commit_now

echo "--- tpu test lane"
MEGBA_TPU_TESTS=1 python -m pytest tests/ -m tpu -p no:cacheprovider -q \
  2>&1 | tee tpu_test_lane.log
COMMIT_MSG="Record TPU test-lane run" commit_now tpu_test_lane.log

echo "--- tile/block sweep trafalgar-scale (measured; picks tuned constants)"
MEGBA_BENCH_CONFIG=trafalgar python scripts/sweep_tiles.py || true
COMMIT_MSG="Record hardware tile/block sweep (trafalgar)" commit_now SWEEP_RAW.json

echo "--- benches (smallest first)"
for cfg in trafalgar venice final final_mixed; do
  echo "=== bench $cfg $(date -u) ==="
  if MEGBA_BENCH_CONFIG=$cfg python bench.py | tee "BENCH_tpu_${cfg}.json"
  then
    COMMIT_MSG="Record hardware bench result: ${cfg}" \
      commit_now "BENCH_tpu_${cfg}.json"
  else
    echo "bench $cfg FAILED"
  fi
  # Phase profile right after the first successful macro bench so a
  # short window still yields a measured (not modelled) phase table.
  if [ "$cfg" = trafalgar ]; then
    echo "--- profile trafalgar $(date -u)"
    MEGBA_BENCH_CONFIG=trafalgar python scripts/profile_phases.py || true
    COMMIT_MSG="Record hardware phase profile (trafalgar)" commit_now PROFILE_RAW.json
  fi
done

echo "--- profile venice"
MEGBA_BENCH_CONFIG=venice python scripts/profile_phases.py || true
COMMIT_MSG="Record hardware phase profile (venice)" commit_now PROFILE_RAW.json

echo "--- tile/block sweep venice (measured)"
MEGBA_BENCH_CONFIG=venice python scripts/sweep_tiles.py || true
COMMIT_MSG="Record hardware tile/block sweep (venice)" commit_now SWEEP_RAW.json

echo "=== $(date -u) TPU round done ==="
COMMIT_MSG="Record TPU round log" commit_now tpu_round.log
