"""External quality anchor: our LM vs scipy TRF, cost-vs-time on CPU.

Self-consistency tests prove our paths agree with each other; this
script anchors solution QUALITY against an independent trust-region
solver on the identical objective — scipy.optimize.least_squares
(method='trf', tr_solver='lsmr') fed our analytical Jacobian as a
scipy.sparse matrix (its best configuration; finite differences would
handicap it).  Runs the ladybug-shape problem (the reference's smallest
real dataset, problem-49-7776 — BAL_Double.cpp runs the same shape):
scipy at Venice scale (5M observations, 3M parameters) is not feasible,
which is itself a scale statement the anchor records.

Output: ANCHOR.json with
  - ours:  [{iter, t_s, cost}] — cumulative wall time per LM iteration
           (compile excluded via a warmup solve on identical shapes),
  - scipy: [{max_nfev, t_s, cost, nfev, njev}] — one timed run per
           evaluation budget (least_squares has no iteration callback).

Usage: python scripts/quality_anchor.py   (CPU; does not touch the TPU)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LM_ITERS = 25
SCIPY_BUDGETS = [2, 4, 8, 16, 32]


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from scipy.optimize import least_squares
    from scipy.sparse import coo_matrix

    from megba_tpu.common import (
        AlgoOption,
        ComputeKind,
        JacobianMode,
        ProblemOption,
        SolverOption,
    )
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    nc, npts, opp = 49, 7776, 31_843 / 7776  # ladybug problem-49-7776
    s = make_synthetic_bal(
        num_cameras=nc, num_points=npts, obs_per_point=opp, seed=0,
        param_noise=1e-2, pixel_noise=0.5, dtype=np.float64)
    nE = s.obs.shape[0]
    print(f"anchor problem: {nc} cams / {npts} pts / {nE} edges (f64, cpu)",
          flush=True)

    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    f_jit = jax.jit(f)
    cam_idx, pt_idx = s.cam_idx, s.pt_idx
    obs_fm = jnp.asarray(s.obs.T)

    # ---- ours: 1-iteration chunks through the shared flat_solve
    # pipeline (one compilation via jit_cache; trust-region state rides
    # as dynamic operands) ----
    option = ProblemOption(
        dtype=np.float64,
        compute_kind=ComputeKind.EXPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=1, epsilon1=1e-12, epsilon2=1e-16),
        solver_option=SolverOption(max_iter=100, tol=1e-10,
                                   refuse_ratio=1e30),
    )
    jit_cache = {}

    def one_iter(cams, pts, region, v):
        return flat_solve(
            f, cams, pts, s.obs, cam_idx, pt_idx, option,
            initial_region=region, initial_v=v, jit_cache=jit_cache)

    # Warmup compiles the program on the production shapes; the timed
    # loop below reuses it.
    _ = one_iter(s.cameras0, s.points0, None, None)

    ours = []
    cams, pts = s.cameras0, s.points0
    region = v = None
    t_total = 0.0
    initial_cost = None
    for it in range(1, LM_ITERS + 1):
        t0 = time.perf_counter()
        res = one_iter(cams, pts, region, v)
        jax.block_until_ready(res.cost)
        t_total += time.perf_counter() - t0
        cams = np.asarray(res.cameras)
        pts = np.asarray(res.points)
        region, v = float(res.region), float(res.v)
        if initial_cost is None:
            initial_cost = float(res.initial_cost)
        ours.append(dict(iter=it, t_s=round(t_total, 4),
                         cost=float(res.cost)))
        if bool(res.stopped):
            break
    print(f"ours: {initial_cost:.6e} -> {ours[-1]['cost']:.6e} "
          f"in {ours[-1]['t_s']:.2f}s ({len(ours)} LM iters)", flush=True)

    # ---- scipy: identical objective, analytic sparse Jacobian ----
    od, cd, pd = 2, 9, 3
    n_params = nc * cd + npts * pd

    # Fixed COO pattern: rows 2e+o; cam cols then pt cols per edge.
    e_ids = np.arange(nE)
    rows_c = (2 * e_ids[None, :] + np.arange(od)[:, None])  # [od, nE]
    rows_cam = np.broadcast_to(rows_c[:, None, :], (od, cd, nE)).ravel()
    cols_cam = np.broadcast_to(
        (cam_idx * cd)[None, None, :] + np.arange(cd)[None, :, None],
        (od, cd, nE)).ravel()
    rows_pt = np.broadcast_to(rows_c[:, None, :], (od, pd, nE)).ravel()
    cols_pt = np.broadcast_to(
        (nc * cd + pt_idx * pd)[None, None, :]
        + np.arange(pd)[None, :, None], (od, pd, nE)).ravel()
    all_rows = np.concatenate([rows_cam, rows_pt])
    all_cols = np.concatenate([cols_cam, cols_pt])

    def unpack(x):
        cams = jnp.asarray(x[: nc * cd].reshape(nc, cd).T)
        pts = jnp.asarray(x[nc * cd:].reshape(npts, pd).T)
        return (jnp.take(cams, jnp.asarray(cam_idx), axis=1),
                jnp.take(pts, jnp.asarray(pt_idx), axis=1))

    def residuals(x):
        ce, pe = unpack(x)
        r, _, _ = f_jit(ce, pe, obs_fm)
        return np.asarray(r).T.ravel()  # row-major [2e+o]

    def jac(x):
        ce, pe = unpack(x)
        _, Jc, Jp = f_jit(ce, pe, obs_fm)
        # Jc [od*cd, nE] with row o*cd+a == d r_o / d cam_a: already the
        # [od, cd, nE] raveled order the COO pattern expects.
        data = np.concatenate(
            [np.asarray(Jc).ravel(), np.asarray(Jp).ravel()])
        return coo_matrix(
            (data, (all_rows, all_cols)),
            shape=(od * nE, n_params)).tocsr()

    x0 = np.concatenate([s.cameras0.ravel(), s.points0.ravel()])
    r0 = residuals(x0)
    assert abs(float(np.sum(r0 ** 2)) - initial_cost) < 1e-6 * initial_cost
    _ = jac(x0)  # warm the jit

    scipy_rows = []
    for budget in SCIPY_BUDGETS:
        t0 = time.perf_counter()
        res = least_squares(
            residuals, x0, jac=jac, method="trf", tr_solver="lsmr",
            xtol=1e-14, ftol=1e-14, gtol=1e-14, max_nfev=budget)
        dt = time.perf_counter() - t0
        scipy_rows.append(dict(
            max_nfev=budget, t_s=round(dt, 4), cost=float(2.0 * res.cost),
            nfev=int(res.nfev), njev=int(res.njev)))
        print(f"scipy max_nfev={budget:3d}: cost {2.0*res.cost:.6e} "
              f"in {dt:.2f}s", flush=True)

    out = dict(
        problem=dict(cameras=nc, points=npts, edges=nE, dtype="float64",
                     backend="cpu", shape="ladybug problem-49-7776"),
        initial_cost=initial_cost,
        ours=ours,
        scipy=scipy_rows,
        note=("scipy TRF given our analytic Jacobian as scipy.sparse; "
              "Venice scale (5M obs) is not feasible for scipy on this "
              "host — the anchor runs the reference's smallest dataset "
              "shape."),
    )
    with open("ANCHOR.json", "w") as fh:
        json.dump(out, fh, indent=1)
    print("wrote ANCHOR.json", flush=True)


if __name__ == "__main__":
    main()
