"""Mixed-precision PCG validation at scale (BASELINE.md config 5).

The final_mixed bench config runs f32 residuals with bf16-equilibrated
PCG coupling operands (solver/pcg.py).  SCALING.md knob 2 claims the
trade is "~20% more PCG iterations for ~half the coupling bandwidth";
VERDICT r04 item 5 asks for that claim to be measured at venice scale
on the CPU backend so config 5 becomes a pure bench run when hardware
answers.

Protocol: identical venice-shaped synthetic problem, identical LM
configuration, bounded iterations; one solve with mixed_precision_pcg
off, one with it on.  Records per-iteration cost curves + PCG iteration
counts, quantifies the PCG-iteration penalty and the convergence gap,
writes MIXED_PRECISION.json.  Nonzero exit when convergence parity
fails (final costs differ beyond REL_TOL) so a small-scale version can
run in CI.

Usage:
  [MEGBA_BENCH_SCALE=1.0] [MEGBA_MP_CONFIG=venice] \
      python scripts/mixed_precision_validation.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# bf16 coupling perturbs the Krylov directions, so the accepted-step
# sequence can differ late in the solve; the optimum itself must agree
# to f32-floor-ish precision.  1e-3 relative on the final cost is the
# parity bar (a busted mixed path misses by orders of magnitude).
REL_TOL = 1e-3


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache, respect_jax_platforms)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    respect_jax_platforms()
    enable_persistent_compile_cache()

    from megba_tpu.common import (
        AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve
    import bench as B

    cfg_name = os.environ.get("MEGBA_MP_CONFIG", "venice")
    scale = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))
    c = B.CONFIGS[cfg_name]
    n_cam = max(8, int(c.cameras * scale))
    n_pt = max(64, int(c.points * scale))
    s = make_synthetic_bal(
        num_cameras=n_cam, num_points=n_pt, obs_per_point=c.obs_per_point,
        seed=0, param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)

    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    out = {"config": cfg_name, "scale": scale, "cameras": n_cam,
           "points": n_pt, "edges": int(s.obs.shape[0]), "runs": {}}
    for mixed in (False, True):
        option = ProblemOption(
            dtype=np.float32,
            compute_kind=ComputeKind.IMPLICIT,
            jacobian_mode=JacobianMode.ANALYTICAL,
            mixed_precision_pcg=mixed,
            algo_option=AlgoOption(max_iter=15, epsilon1=1e-12,
                                   epsilon2=1e-15),
            solver_option=SolverOption(max_iter=60, tol=1e-9,
                                       refuse_ratio=1e30),
        )
        from megba_tpu.utils.curves import run_with_curve

        t0 = time.perf_counter()
        res, curve = run_with_curve(
            lambda: flat_solve(
                f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                option, verbose=True),
            block_on=lambda r: jax.block_until_ready(r.cost))
        elapsed = time.perf_counter() - t0
        key = "bf16_coupling" if mixed else "f32"
        out["runs"][key] = {
            "initial_cost": float(res.initial_cost),
            "final_cost": float(res.cost),
            "iterations": int(res.iterations),
            "accepted": int(res.accepted),
            "pcg_iterations": int(res.pcg_iterations),
            "pcg_iters_per_lm": round(
                int(res.pcg_iterations) / max(int(res.iterations), 1), 2),
            "elapsed_s": round(elapsed, 3),
            "curve": curve,
        }
        print(f"[{cfg_name}] {key}: {float(res.initial_cost):.6e} -> "
              f"{float(res.cost):.6e}, {int(res.pcg_iterations)} PCG iters "
              f"over {int(res.iterations)} LM iters ({elapsed:.1f}s)",
              flush=True)

    rf, rm = out["runs"]["f32"], out["runs"]["bf16_coupling"]
    rel = abs(rm["final_cost"] - rf["final_cost"]) / max(
        rf["final_cost"], 1e-300)
    # PCG-iteration penalty per LM iteration: the bandwidth trade's cost.
    penalty = (rm["pcg_iters_per_lm"] / max(rf["pcg_iters_per_lm"], 1e-9)
               ) - 1.0
    out["final_rel_diff"] = rel
    out["pcg_iter_penalty"] = round(penalty, 4)
    out["rel_tol"] = REL_TOL
    out["pass"] = bool(rel <= REL_TOL)
    print(f"[{cfg_name}] final rel diff {rel:.3e} "
          f"({'PASS' if out['pass'] else 'FAIL'} at {REL_TOL}); "
          f"PCG iteration penalty {penalty:+.1%}", flush=True)

    path = os.environ.get("MEGBA_MP_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "MIXED_PRECISION.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {path}", flush=True)
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
