"""Per-phase LM-step profiler with FLOP/byte accounting (PROFILE.md data).

Times each phase of the LM iteration separately on the current backend
(designed for the real chip) at a chosen bench config, computes
closed-form FLOP and HBM-byte counts, and reports MFU / bandwidth
utilisation per phase.  Writes PROFILE_RAW.json and prints a table.

Usage: MEGBA_BENCH_CONFIG=venice python scripts/profile_phases.py
Never kill this mid-run on the TPU (single-client tunnel).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIG = os.environ.get("MEGBA_BENCH_CONFIG", "venice")
SCALE = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))

# v5e peaks (per chip): bf16 MXU 197 TFLOP/s, HBM 819 GB/s.  f32 matmul
# rides the MXU at ~1/2..1/4 of bf16 depending on pass decomposition;
# MFU is reported against the bf16 peak (the honest "of what the chip
# can do" number).
PEAK_FLOPS = 197e12
PEAK_BW = 819e9


def timeit(fn, *args, reps=10):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache,
        install_graceful_term,
    )

    install_graceful_term()
    enable_persistent_compile_cache()
    import jax

    from megba_tpu.utils.backend import respect_jax_platforms

    respect_jax_platforms()
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench as B  # noqa: E402  (bench.py at repo root)

    from megba_tpu.common import ComputeKind, JacobianMode
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.linear_system.builder import (
        build_schur_system, weight_system_inputs)
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.ops.segtiles import make_dual_plans
    from megba_tpu.solver.pcg import make_coupling_matvecs

    cfg = B.CONFIGS[CONFIG]
    nc = max(8, int(cfg.cameras * SCALE))
    npts = max(64, int(cfg.points * SCALE))
    dtype = np.float32
    s = make_synthetic_bal(
        num_cameras=nc, num_points=npts, obs_per_point=cfg.obs_per_point,
        seed=0, param_noise=1e-2, pixel_noise=0.5, dtype=dtype)
    nE = s.obs.shape[0]
    print(f"backend={jax.default_backend()} config={CONFIG} "
          f"{nc} cams / {npts} pts / {nE} edges", flush=True)

    t_plan0 = time.perf_counter()
    plan_c, plans = make_dual_plans(s.cam_idx, s.pt_idx, nc, npts)
    t_plan = time.perf_counter() - t_plan0
    perm, pmask = plan_c.perm, plan_c.mask
    obs_p = jnp.asarray((s.obs[perm] * pmask[:, None]).T.astype(dtype))
    ci = jnp.asarray(plan_c.seg)
    pi = jnp.asarray(np.where(pmask > 0, s.pt_idx[perm], 0))
    mask = jnp.asarray(pmask.astype(dtype))
    cams = jnp.asarray(s.cameras0.T.astype(dtype))
    pts = jnp.asarray(s.points0.T.astype(dtype))
    nslots = plan_c.n_slots
    nslots_pt = int(plans.pt.mask.shape[0])

    f = make_residual_jacobian_fn(mode=JacobianMode[cfg.jacobian])

    @jax.jit
    def linearize(cams, pts):
        r, Jc, Jp = f(jnp.take(cams, ci, axis=1),
                      jnp.take(pts, pi, axis=1), obs_p)
        r, Jc, Jp = weight_system_inputs(r, Jc, Jp, ci, pi, mask)
        return r, Jc, plans.to_pt(Jp)

    r, Jc, Jp = linearize(cams, pts)

    @jax.jit
    def build(r, Jc, Jp):
        return build_schur_system(
            r, Jc, Jp, ci, pi, nc, npts,
            compute_kind=ComputeKind.IMPLICIT, plans=plans)

    system = build(r, Jc, Jp)

    hpl, hlp = make_coupling_matvecs(
        None, Jc, Jp, ci, pi, nc, npts, ComputeKind.IMPLICIT, plans=plans)
    hlp_j = jax.jit(hlp)
    hpl_j = jax.jit(hpl)
    p = jnp.asarray(np.random.default_rng(0).standard_normal(
        (9, nc)), jnp.float32)
    q = hlp_j(p)

    from megba_tpu.ops.accum import comp_dot
    dots = jax.jit(lambda a: comp_dot(a, a))

    phases = {}
    phases["linearize"] = timeit(linearize, cams, pts)
    phases["build"] = timeit(build, r, Jc, Jp)
    phases["hlp (Hlp.p)"] = timeit(hlp_j, p)
    phases["hpl (Hpl.q)"] = timeit(hpl_j, q)
    phases["pcg dot [9,Nc]"] = timeit(dots, p)

    B4 = 4
    od, cd, pd = 2, 9, 3
    byte_counts = {
        # read obs+params gathered (via take) + write r, Jc, Jp (+ Jp perm)
        "linearize": (2 + cd + pd) * B4 * nslots
        + (2 + od * cd) * B4 * nslots + (od * pd) * B4 * (nslots + 2 * nslots_pt),
        # read Jc+r (cam) and Jp+r_pt; write block diagonals (small)
        "build": (od * cd + od) * B4 * nslots
        + (od * pd + 2 * od) * B4 * nslots_pt,
        # read Jc (expand side) + write u + perm u + read Jp (reduce side)
        "hlp (Hlp.p)": (od * cd + od) * B4 * nslots
        + 3 * od * B4 * nslots_pt + od * pd * B4 * nslots_pt,
        "hpl (Hpl.q)": (od * pd + od) * B4 * nslots_pt
        + 3 * od * B4 * nslots + od * cd * B4 * nslots,
        "pcg dot [9,Nc]": 2 * 9 * nc * B4,
    }
    flop_counts = {
        "linearize": 2 * 700 * nslots,  # ~700 flops/edge analytical J
        "build": 2 * (od * (cd * cd + cd)) * nslots
        + 2 * (od * (pd * pd + pd)) * nslots_pt
        + 2 * (plan_c.block * (cd * cd + cd)) * nslots  # one-hot matmul
        + 2 * (plans.pt.block * (pd * pd + pd)) * nslots_pt,
        "hlp (Hlp.p)": 2 * plans.cam.block * cd * nslots // plan_c.tile * plan_c.tile
        + 2 * od * cd * nslots + 2 * od * pd * nslots_pt
        + 2 * plans.pt.block * pd * nslots_pt,
        "hpl (Hpl.q)": 2 * plans.pt.block * pd * nslots_pt
        + 2 * od * pd * nslots_pt + 2 * od * cd * nslots
        + 2 * plans.cam.block * cd * nslots,
        "pcg dot [9,Nc]": 8 * 9 * nc,
    }

    rows = []
    print(f"\nplan build (host): {t_plan*1e3:.0f} ms")
    print(f"{'phase':20s} {'ms':>9s} {'GB/s':>8s} {'BW%':>6s} "
          f"{'TFLOP/s':>9s} {'MFU%':>6s}")
    for k, dt in phases.items():
        gbs = byte_counts[k] / dt / 1e9
        tf = flop_counts[k] / dt / 1e12
        rows.append(dict(phase=k, ms=dt * 1e3, gbps=gbs,
                         bw_pct=100 * gbs * 1e9 / PEAK_BW,
                         tflops=tf, mfu_pct=100 * tf * 1e12 / PEAK_FLOPS))
        print(f"{k:20s} {dt*1e3:9.3f} {gbs:8.1f} "
              f"{100*gbs*1e9/PEAK_BW:6.1f} {tf:9.2f} "
              f"{100*tf*1e12/PEAK_FLOPS:6.1f}", flush=True)

    per_pcg = phases["hlp (Hlp.p)"] + phases["hpl (Hpl.q)"] + \
        3 * phases["pcg dot [9,Nc]"]
    print(f"\n~per-PCG-iteration (2 products + 3 dots): {per_pcg*1e3:.2f} ms")
    out = dict(config=CONFIG, scale=SCALE, backend=jax.default_backend(),
               n_edges=nE, n_slots=nslots, n_slots_pt=nslots_pt,
               cameras=nc, points=npts, plan_build_s=t_plan, phases=rows,
               per_pcg_ms=per_pcg * 1e3)
    with open("PROFILE_RAW.json", "w") as fh:
        json.dump(out, fh, indent=1)
    print("wrote PROFILE_RAW.json", flush=True)


if __name__ == "__main__":
    main()
