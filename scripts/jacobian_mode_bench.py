"""Analytical vs autodiff Jacobian: the measured differential.

The reference advertises its analytical-derivatives mode as ~30% faster
and ~40% lighter than its autodiff mode (reference README.md:16).  Both
modes exist here and agree numerically (tests/test_residuals.py); this
script MEASURES the differential on the current backend — per-LM-
iteration wall time under a fixed iteration budget plus XLA's
memory_analysis of both programs — and writes JACOBIAN_MODES.json.

On CPU this is clearly-labelled stand-in evidence (the fusion/layout
trade on the MXU differs); the same script runs unchanged on the chip
when the tunnel answers.

Usage:
  [MEGBA_BENCH_CONFIG=venice] [MEGBA_BENCH_SCALE=0.2] \
      python scripts/jacobian_mode_bench.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache, ensure_usable_backend,
        install_graceful_term)

    install_graceful_term()
    enable_persistent_compile_cache()
    fell_back = ensure_usable_backend()

    import jax

    import bench as B
    from megba_tpu.common import (
        AlgoOption, ComputeKind, JacobianMode, ProblemOption, SolverOption)
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    cfg_name = os.environ.get("MEGBA_BENCH_CONFIG", "venice")
    scale = float(os.environ.get("MEGBA_BENCH_SCALE", "0.2"))
    c = B.CONFIGS[cfg_name]
    n_cam = max(8, int(c.cameras * scale))
    n_pt = max(64, int(c.points * scale))
    s = make_synthetic_bal(
        num_cameras=n_cam, num_points=n_pt, obs_per_point=c.obs_per_point,
        seed=0, param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)

    LM_ITERS, PCG_ITERS = 6, 30
    out = {"config": cfg_name, "scale": scale, "cameras": n_cam,
           "points": n_pt, "edges": int(s.obs.shape[0]),
           "backend": jax.devices()[0].platform,
           "cpu_fallback": bool(fell_back),
           "lm_iters": LM_ITERS, "pcg_iters": PCG_ITERS, "runs": {}}
    for mode in (JacobianMode.ANALYTICAL, JacobianMode.AUTODIFF):
        option = ProblemOption(
            dtype=np.float32,
            compute_kind=ComputeKind.IMPLICIT,
            jacobian_mode=mode,
            # Timing protocol (same as bench.py): huge refuse_ratio +
            # loose stops force exactly LM_ITERS full iterations of
            # linearize+build+PCG, so both modes do identical work.
            algo_option=AlgoOption(max_iter=LM_ITERS, epsilon1=1e-14,
                                   epsilon2=1e-16),
            solver_option=SolverOption(max_iter=PCG_ITERS, tol=1e-12,
                                       refuse_ratio=1e30),
        )
        f = make_residual_jacobian_fn(mode=mode)

        def run():
            r = flat_solve(
                f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx,
                option)
            jax.block_until_ready(r.cost)
            return r

        res = run()  # compile + warm
        t0 = time.perf_counter()
        res = run()
        elapsed = time.perf_counter() - t0

        # XLA's memory analysis of this mode's program (the reference
        # claims analytical is ~40% lighter).
        from megba_tpu.utils.meminfo import single_solve_memory_analysis

        ma = single_solve_memory_analysis(s, option, f)
        mem = None
        if "temp_size_in_bytes" in ma:
            mem = {
                "temp_size_bytes": ma["temp_size_in_bytes"],
                "argument_size_bytes": ma["argument_size_in_bytes"],
            }
        out["runs"][mode.name.lower()] = {
            "lm_iter_ms": round(elapsed / LM_ITERS * 1e3, 2),
            "final_cost": float(res.cost),
            "iterations": int(res.iterations),
            "memory_analysis": mem,
        }
        print(f"[{cfg_name} x{scale}] {mode.name}: "
              f"{elapsed / LM_ITERS * 1e3:.1f} ms/LM-iter "
              f"(cost {float(res.cost):.6e})", flush=True)

    a = out["runs"]["analytical"]["lm_iter_ms"]
    d = out["runs"]["autodiff"]["lm_iter_ms"]
    out["analytical_time_vs_autodiff"] = round(a / d - 1.0, 4)
    print(f"analytical vs autodiff time: {a / d - 1.0:+.1%} "
          f"(reference claims ~-30% on CUDA)", flush=True)
    ma_a = out["runs"]["analytical"]["memory_analysis"]
    ma_d = out["runs"]["autodiff"]["memory_analysis"]
    if ma_a and ma_d:
        out["analytical_temp_vs_autodiff"] = round(
            ma_a["temp_size_bytes"] / ma_d["temp_size_bytes"] - 1.0, 4)
        print(f"analytical vs autodiff temp memory: "
              f"{out['analytical_temp_vs_autodiff']:+.1%} "
              f"(reference claims ~-40%)", flush=True)

    path = os.environ.get("MEGBA_JM_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "JACOBIAN_MODES.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()
