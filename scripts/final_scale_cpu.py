"""Final-13682 scale END-TO-END capability run on the CPU backend.

The reference's implicit mode exists for BAL Final problem-13682-4456117
(~29M observations, README.md:19); SCALING.md's Final row was
extrapolated from a half-scale dry run.  This script executes the full
pipeline — synthesis, lowering, implicit tiled-or-chunked build, damped
Schur-PCG, LM accept/reject — at the REAL edge count and records
measured wall times + peak RSS to FINAL_CPU.json.  It is a capability
proof (clearly labelled cpu), not a perf number; the perf half runs on
the chip via run_tpu_round.sh (bench config `final`).

Usage: python scripts/final_scale_cpu.py   (CPU only; ~15-30 min on one core)
"""
from __future__ import annotations

import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401

    from megba_tpu.common import (
        AlgoOption,
        ComputeKind,
        JacobianMode,
        ProblemOption,
        SolverOption,
    )
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    nc, npts, opp = 13_682, 4_456_117, 28_987_644 / 4_456_117
    t0 = time.perf_counter()
    s = make_synthetic_bal(
        num_cameras=nc, num_points=npts, obs_per_point=opp, seed=0,
        param_noise=1e-2, pixel_noise=0.5, dtype=np.float32)
    t_synth = time.perf_counter() - t0
    nE = int(s.obs.shape[0])
    print(f"synth: {nc} cams / {npts} pts / {nE} edges in {t_synth:.1f}s "
          f"(rss {rss_gb():.1f} GB)", flush=True)

    # Env knobs so the same runner covers the 2-iteration capability
    # proof AND a convergence run (MEGBA_FINAL_ITERS=10 ... -> plateau
    # at the synthetic noise floor; VERDICT r04 weak-spot 6).
    max_iter = int(os.environ.get("MEGBA_FINAL_ITERS", "2"))
    pcg_iter = int(os.environ.get("MEGBA_FINAL_PCG", "8"))
    out_path = os.environ.get("MEGBA_FINAL_OUT", "FINAL_CPU.json")
    option = ProblemOption(
        dtype=np.float32,
        compute_kind=ComputeKind.IMPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-12,
                               epsilon2=1e-15),
        solver_option=SolverOption(max_iter=pcg_iter, tol=1e-10,
                                   refuse_ratio=1e30),
    )
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    from megba_tpu.utils.curves import run_with_curve

    t0 = time.perf_counter()
    res, curve = run_with_curve(
        lambda: flat_solve(
            f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
            verbose=True),
        block_on=lambda r: jax.block_until_ready(r.cost),
        tee=True)  # 200s+/iter at this scale: keep live crash forensics
    t_solve = time.perf_counter() - t0
    iters = int(res.iterations)
    out = dict(
        backend=jax.default_backend(),
        capability_proof=True,
        cameras=nc, points=npts, edges=nE,
        synth_s=round(t_synth, 1),
        solve_s=round(t_solve, 1),
        lm_iters=iters,
        pcg_iters=int(res.pcg_iterations),
        s_per_lm_iter=round(t_solve / max(iters, 1), 2),
        initial_cost=float(res.initial_cost),
        cost=float(res.cost),
        accepted=int(res.accepted),
        peak_rss_gb=round(rss_gb(), 2),
        # Statistical floor of the synthetic: E[min Sum e^2] for least
        # squares with Gaussian pixel noise sigma is
        # (n_residuals - n_fitted_params) * sigma^2 — the fitted DOF
        # absorb their share of the noise.  sigma=0.5 and od=2 match
        # the make_synthetic_bal call above.
        noise_floor_cost=round(
            (nE * 2 - (9 * nc + 3 * npts)) * 0.5**2, 1),
        curve=curve,
        note=("end-to-end Final-13682 scale on the CPU backend "
              "(includes compile in solve_s; 1 host core). Capability "
              "evidence only — chip perf comes from bench config "
              "'final' via run_tpu_round.sh."),
    )
    print(json.dumps(out), flush=True)
    assert np.isfinite(out["cost"]) and out["cost"] < out["initial_cost"]
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {out_path}", flush=True)


if __name__ == "__main__":
    main()
