"""Benchmark harness — prints ONE JSON line for the driver.

Measures LM iterations/second on a synthetic Venice-1778-scale problem
(1778 cameras, ~1M observations — the BASELINE.md config 3 shape) with
the analytical Jacobian and the implicit (matrix-free) Schur PCG, float32,
on whatever accelerator JAX provides (the real TPU chip under the driver).

The reference repo publishes no absolute numbers (BASELINE.md); the
`vs_baseline` field is computed against ASSUMED_BASELINE_LM_ITERS_PER_SEC,
an order-of-magnitude estimate of the reference's per-LM-iteration rate
on its 2-GPU Venice demo config (README.md:56-58) — to be replaced when a
measured reference number exists.
"""

from __future__ import annotations

import json
import time

import numpy as np

import os

ASSUMED_BASELINE_LM_ITERS_PER_SEC = 10.0

# MEGBA_BENCH_SCALE in (0, 1] shrinks the problem for smoke tests.
_SCALE = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))
NUM_CAMERAS = max(8, int(1778 * _SCALE))
NUM_POINTS = max(64, int(99_392 * _SCALE))  # ~Venice/10 point count; obs count matches
OBS_PER_POINT = 10  # ~994k observations at full scale — Venice-1778's edge count
LM_ITERS = 8
PCG_ITERS = 30


def _probe_pallas(cam_idx):
    """Decide whether to route the Hessian build through the Pallas kernel.

    MEGBA_BENCH_PALLAS=0 disables, =1 forces; default 'auto' enables only
    if the plan is feasible AND the kernel actually compiles+matches on a
    small input on this backend (so an unexpected Mosaic lowering failure
    degrades to the XLA path instead of killing the benchmark).
    """
    import jax
    import jax.numpy as jnp

    from megba_tpu.ops.pallas_kernels import camera_hessian_gradient, camera_window_plan

    mode = os.environ.get("MEGBA_BENCH_PALLAS", "auto")
    if mode == "0":
        return None
    ok, window = camera_window_plan(cam_idx)
    if not ok:
        return None
    plan = (512, window)
    if mode == "1":
        return plan
    if jax.default_backend() != "tpu":
        # Off-TPU the kernel runs in interpret mode — correct but slow;
        # only the real TPU lowering is a performance win.
        return None
    try:
        n, cd, od = 1024, 9, 2
        jc = jnp.ones((n, od, cd), jnp.float32)
        r = jnp.ones((n, od), jnp.float32)
        ci = jnp.asarray(np.repeat(np.arange(8), n // 8), jnp.int32)
        hpp, g = camera_hessian_gradient(
            jc, r, ci, num_cameras=8, tile=512, window=window,
            interpret=False)  # probe only runs on the TPU backend
        expect = float(n // 8 * od)
        assert abs(float(hpp[0, 0, 0]) - expect) < 1e-2
        return plan
    except Exception as e:  # pragma: no cover - backend specific
        import sys

        print(f"pallas probe failed ({type(e).__name__}); using XLA path",
              file=sys.stderr, flush=True)
        return None


def main() -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from megba_tpu.utils.backend import ensure_usable_backend

    backend_note = ""
    if ensure_usable_backend():
        backend_note = " [accelerator init hung; CPU fallback]"

    import jax
    import jax.numpy as jnp

    from megba_tpu.common import (
        AlgoOption,
        ComputeKind,
        JacobianMode,
        ProblemOption,
        SolverOption,
    )
    from megba_tpu.algo import lm_solve
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    dtype = np.float32
    s = make_synthetic_bal(
        num_cameras=NUM_CAMERAS,
        num_points=NUM_POINTS,
        obs_per_point=OBS_PER_POINT,
        seed=0,
        param_noise=1e-2,
        pixel_noise=0.5,
        dtype=dtype,
    )
    n_edge = s.obs.shape[0]

    option = ProblemOption(
        dtype=dtype,
        compute_kind=ComputeKind.IMPLICIT,
        jacobian_mode=JacobianMode.ANALYTICAL,
        algo_option=AlgoOption(max_iter=LM_ITERS, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(max_iter=PCG_ITERS, tol=1e-10, refuse_ratio=1e30),
    )
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    args = (
        jnp.asarray(s.cameras0),
        jnp.asarray(s.points0),
        jnp.asarray(s.obs),
        jnp.asarray(s.cam_idx),
        jnp.asarray(s.pt_idx),
        jnp.ones(n_edge, dtype=dtype),
    )

    from megba_tpu.core.types import is_cam_sorted

    cam_sorted = is_cam_sorted(s.cam_idx)
    pallas_plan = _probe_pallas(s.cam_idx) if cam_sorted else None
    solve = jax.jit(
        lambda cams, pts, obs, ci, pi, m: lm_solve(
            f, cams, pts, obs, ci, pi, m, option, cam_sorted=cam_sorted,
            pallas_plan=pallas_plan)
    )

    # Warmup (compile) — not timed.
    res = solve(*args)
    jax.block_until_ready(res.cost)
    iters = int(res.iterations)

    t0 = time.perf_counter()
    res = solve(*args)
    jax.block_until_ready(res.cost)
    elapsed = time.perf_counter() - t0

    lm_iters_per_sec = iters / elapsed
    print(
        json.dumps(
            {
                "metric": f"LM iters/sec, synthetic Venice-1778 scale ({n_edge} edges), f32 analytical implicit, 1 chip{backend_note}",
                "value": round(lm_iters_per_sec, 3),
                "unit": "LM iters/s",
                "vs_baseline": round(lm_iters_per_sec / ASSUMED_BASELINE_LM_ITERS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
