"""Benchmark harness — prints ONE JSON line for the driver.

Measures LM iterations/second on a synthetic problem shaped like one of
the five BASELINE.md configurations (MEGBA_BENCH_CONFIG = ladybug /
trafalgar / venice / final / final_mixed; default venice — 1778 cameras,
993,923 points, ~5.0M observations, analytical Jacobian, implicit Schur
PCG, float32) on whatever accelerator JAX provides (the real TPU chip
under the driver).

Problem shapes match the real BAL datasets: camera and point counts are
exact; the observation count is matched via a fractional obs-per-point
(the sandbox has no network egress, so the geometry is synthetic — see
megba_tpu/io/synthetic.py).

The reference repo publishes no absolute numbers (BASELINE.md), so
`vs_baseline` is computed against a DERIVED reference rate: a memory-
bandwidth + launch-latency roofline of the reference's CUDA pipeline
(explicit CSR SpMV or implicit edge-scatter, per config) running the
same problem shape and the same PCG iteration count on one A100-40GB.
The full derivation, constants, and their sources are written down in
BASELINE.md §"Derived baseline".
"""

from __future__ import annotations

import json
import time

import numpy as np

import os

# The five BASELINE.md configs, selectable via MEGBA_BENCH_CONFIG
# (default: venice — the headline metric).  cameras/points are the real
# BAL dataset counts; obs_per_point is chosen so the synthetic edge
# count matches the dataset's observation count (BASELINE.md table).
from typing import NamedTuple


class BenchConfig(NamedTuple):
    cameras: int
    points: int
    obs_per_point: float
    dtype: str
    jacobian: str
    compute: str
    mixed: bool = False
    force_cpu: bool = False
    # Reference-side model inputs for the derived baseline (BASELINE.md):
    # the dtype the reference example for this config runs in, and whether
    # its solver path is implicit (matrix-free) or explicit (CSR SpMV).
    ref_dtype_bytes: int = 8
    ref_implicit: bool = False


CONFIGS = {
    # BAL Ladybug problem-49-7776 (31,843 obs): BAL_Double, CPU, world 1.
    "ladybug": BenchConfig(49, 7776, 31_843 / 7776, "float64", "AUTODIFF",
                           "EXPLICIT", force_cpu=True),
    # BAL Trafalgar problem-257-65132 (225,911 obs): BAL_Float autodiff.
    "trafalgar": BenchConfig(257, 65_132, 225_911 / 65_132, "float32",
                             "AUTODIFF", "EXPLICIT", ref_dtype_bytes=4),
    # BAL Venice problem-1778-993923 (~5.0M obs): BAL_Double_analytical.
    "venice": BenchConfig(1778, 993_923, 5_001_946 / 993_923, "float32",
                          "ANALYTICAL", "IMPLICIT"),
    # BAL Final problem-13682-4456117 (~29.0M obs): analytical implicit.
    "final": BenchConfig(13_682, 4_456_117, 28_987_644 / 4_456_117, "float32",
                         "ANALYTICAL", "IMPLICIT", ref_implicit=True),
    # Final, mixed precision: fp32 residuals + bf16 PCG.
    "final_mixed": BenchConfig(13_682, 4_456_117, 28_987_644 / 4_456_117,
                               "float32", "ANALYTICAL", "IMPLICIT", mixed=True,
                               ref_implicit=True),
}


def derived_baseline_lm_iters_per_sec(
    n_edge: int,
    n_cam: int,
    n_pt: int,
    pcg_iters: float,
    ref_dtype_bytes: int,
    implicit: bool,
) -> float:
    """Reference (MegBA/CUDA) LM-iteration rate modelled on one A100-40GB.

    Roofline = HBM traffic / (efficiency x bandwidth) + kernel-launch and
    host-sync latency.  Traffic counts follow the reference's own data
    structures (SURVEY.md §3.3/§3.5); constants documented in BASELINE.md.
    """
    B = ref_dtype_bytes
    nnz = 27 * n_edge  # scalar nnz of Hpl: 9x3 block per edge
    # Two forward passes per LM iter (reference re-runs forward for rho and
    # rebuilds on accept): read 12 param scalars, write 24 J + 2 e per edge.
    fwd_bytes = 2 * (12 + 24 + 2) * B * n_edge
    # Hessian build: read J + e, write Hpl/Hlp CSR + block diags + g.
    build_bytes = (26 + 2 * 27) * B * n_edge + (81 * n_cam + 9 * n_pt) * B
    if implicit:
        # Per PCG iter: EMulx + ETMulx re-read Jc(18)+Jp(6) per edge + idx.
        per_pcg = 2 * 24 * B * n_edge + 2 * 8 * n_edge
    else:
        # Per PCG iter: two CSR SpMVs read vals + int32 colInd.
        per_pcg = 2 * nnz * (B + 4)
    # Both paths: Hpp gemv, Hll^-1 apply, ~4 full camera+point vector sweeps.
    per_pcg += (81 * n_cam + 9 * n_pt + 4 * (9 * n_cam + 3 * n_pt)) * B
    total_bytes = fwd_bytes + build_bytes + pcg_iters * per_pcg

    A100_BW = 1.555e12  # A100-40GB peak HBM bandwidth, B/s
    EFF = 0.60          # generous streaming efficiency for cuSPARSE/cuBLAS
    bw_time = total_bytes / (EFF * A100_BW)

    # Latency: the reference's op-per-kernel autodiff (~40 launches/forward,
    # SURVEY.md §3.4), ~10 kernels + 2 host-blocking dot reductions per PCG
    # iter (§3.5), ~6 host syncs per LM iter (§3.2).
    LAUNCH, SYNC = 5e-6, 10e-6
    lat_time = (2 * 40 + 10) * LAUNCH + pcg_iters * (10 * LAUNCH + 2 * SYNC) + 6 * SYNC
    return 1.0 / (bw_time + lat_time)

CONFIG = os.environ.get("MEGBA_BENCH_CONFIG", "venice")
if CONFIG not in CONFIGS:
    raise SystemExit(
        f"unknown MEGBA_BENCH_CONFIG {CONFIG!r}; choose from {sorted(CONFIGS)}")

# MEGBA_BENCH_SCALE in (0, 1] shrinks the problem for smoke tests.
_SCALE = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))

# MEGBA_BENCH_MESH2D=<ExC> (e.g. "2x2"): 2-D mesh head-to-head vs the
# 1-D edge sharding at the same world size (mesh2d_head_to_head).  The
# backend needs E*C devices; on the CPU lane that means forcing virtual
# host devices BEFORE backend init, so the knob is resolved here.
_MESH2D_SPEC = os.environ.get("MEGBA_BENCH_MESH2D", "")


def _parse_mesh2d(spec: str):
    try:
        e, c = spec.lower().replace(" ", "").split("x")
        e, c = int(e), int(c)
    except ValueError:
        raise SystemExit(
            f"MEGBA_BENCH_MESH2D must look like '2x2', got {spec!r}")
    if e < 1 or c < 1:
        raise SystemExit(
            f"MEGBA_BENCH_MESH2D needs positive factors, got {spec!r}")
    return e, c


if _MESH2D_SPEC:
    _E2D, _C2D = _parse_mesh2d(_MESH2D_SPEC)
    # Raise-to-floor, not append-if-absent: a pre-set LOWER count
    # (persisted dev-shell/CI XLA_FLAGS) would otherwise silently skip
    # the whole head-to-head.  Importing the audit module is safe here:
    # it only touches XLA_FLAGS, and the backend has not initialised.
    from megba_tpu.analysis.audit import ensure_host_device_floor

    os.environ["XLA_FLAGS"] = ensure_host_device_floor(
        os.environ.get("XLA_FLAGS", ""), _E2D * _C2D)

# MEGBA_BENCH_BF16=1: bf16 MXU pipeline vs f32 head-to-head
# (bf16_head_to_head) writing BENCH_bf16.json.  The structural half of
# the evidence live-audits the ba_bf16_w2_f32 canonical program (world
# 2), so the CPU lane needs >= 2 virtual devices before backend init.
_BF16_BENCH = os.environ.get("MEGBA_BENCH_BF16") == "1"
if _BF16_BENCH:
    from megba_tpu.analysis.audit import ensure_host_device_floor

    os.environ["XLA_FLAGS"] = ensure_host_device_floor(
        os.environ.get("XLA_FLAGS", ""), 2)
# MEGBA_BENCH_OBS=1: observability-plane overhead head-to-head
# (obs_head_to_head) writing BENCH_obs.json.  Entirely host-side — the
# plane never touches the jitted programs — so no device floor needed.
_OBS_BENCH = os.environ.get("MEGBA_BENCH_OBS") == "1"
# MEGBA_BENCH_FUSED=1: fused Pallas edge-pipeline kernels vs the tiled
# XLA lowering (fused_head_to_head) writing BENCH_fused.json.
# Single-device tiled path — no device floor needed.
_FUSED_BENCH = os.environ.get("MEGBA_BENCH_FUSED") == "1"
_C = CONFIGS[CONFIG]
NUM_CAMERAS = max(8, int(_C.cameras * _SCALE))
NUM_POINTS = max(64, int(_C.points * _SCALE))
OBS_PER_POINT = _C.obs_per_point
LM_ITERS = 8
PCG_ITERS = 30


def _status_name(res):
    if getattr(res, "status", None) is None:
        return None
    from megba_tpu.common import status_name

    return status_name(res.status)


def fleet_head_to_head(n_problems: int, dtype, timer) -> dict:
    """Serial flat_solve loop vs batched solve_many over one fleet.

    Both sides solve the SAME `io/synthetic.make_fleet` problems to the
    same convergence settings, and both are fully warmed (compiles +
    host plan caches) before timing, so the comparison is steady-state
    dispatch throughput — the regime a long-lived service runs in.  The
    serial side pays one `flat_solve` call per problem (per-call host
    prep + dispatch); the batched side pays one padded dispatch per
    shape bucket.

    `max_cost_rel_gap` compares the batched lanes against the serial
    loop at each problem's NATURAL shape.  Runs at the surrounding
    bench dtype: under the default f32 lane (x64 off) camera/point
    padding reorders the compensated sums, so un-converged trajectories
    drift ~1e-2 relative — a sanity band, not a parity proof.  The
    bitwise-padding / rtol-1e-6 parity contract is pinned where it is
    provable, in tests/test_serving.py under x64.
    """
    import jax

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.synthetic import make_fleet
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving import FleetProblem, FleetStats, solve_many
    from megba_tpu.solve import flat_solve

    opt = ProblemOption(
        dtype=dtype,
        algo_option=AlgoOption(max_iter=8),
        solver_option=SolverOption(max_iter=12, tol=1e-8))
    fleet = make_fleet(n_problems, size_range=(16, 64), seed=0, dtype=dtype)
    probs = [FleetProblem.from_synthetic(s, name=f"fleet{i}")
             for i, s in enumerate(fleet)]
    f = make_residual_jacobian_fn(mode=opt.jacobian_mode)

    def serial_pass():
        out = [flat_solve(f, p.cameras, p.points, p.obs, p.cam_idx,
                          p.pt_idx, opt, use_tiled=False) for p in probs]
        jax.block_until_ready([r.cost for r in out])
        return out

    with timer.phase("fleet_warm_serial"):
        serial_pass()
    t0 = time.perf_counter()
    with timer.phase("fleet_serial"):
        serial = serial_pass()
    serial_s = time.perf_counter() - t0

    with timer.phase("fleet_warm_batched"):
        solve_many(probs, opt)
    stats = FleetStats()
    t0 = time.perf_counter()
    with timer.phase("fleet_batched"):
        batched = solve_many(probs, opt, stats=stats)
    batched_s = time.perf_counter() - t0

    cost_gap = max(
        abs(float(b.cost) - float(s.cost)) / max(abs(float(s.cost)), 1e-30)
        for b, s in zip(batched, serial))
    d = stats.as_dict()
    return {
        "problems": n_problems,
        "problems_per_sec_serial": round(n_problems / serial_s, 2),
        "problems_per_sec_batched": round(n_problems / batched_s, 2),
        "speedup": round(serial_s / batched_s, 3),
        "serial_s": round(serial_s, 4),
        "batched_s": round(batched_s, 4),
        "buckets": len(d["per_bucket"]),
        "padding_waste": round(d["padding_waste"], 4),
        "statuses": sorted({b.status_name for b in batched}),
        "serial_statuses": sorted(
            {_status_name(r) for r in serial}),
        "max_cost_rel_gap": cost_gap,
    }


def obs_head_to_head(n_problems: int, dtype, timer) -> dict:
    """Observability-plane overhead: solve_many with the plane OFF vs
    metrics+spans ON over the same warmed fleet.

    Both sides solve the SAME `make_fleet` problems (the
    fleet_head_to_head generator) after a shared warm pass; the jitted
    programs are byte-identical either way (the plane is host-side only,
    gated by `analysis/audit --check`), so any delta is pure host
    instrumentation cost — registry increments, span records, phase-hook
    dispatch.  Each side is timed best-of-3 (shared noisy container; see
    federation_head_to_head's rationale), and the acceptance band is
    <= 2% overhead (`within_band`), asserted by scripts/run_tests.sh on
    the venice lane.  Also written to BENCH_obs.json.
    """
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.synthetic import make_fleet
    from megba_tpu.observability import metrics as _metrics
    from megba_tpu.observability import spans as _spans
    from megba_tpu.serving import FleetProblem, solve_many

    opt = ProblemOption(
        dtype=dtype,
        algo_option=AlgoOption(max_iter=8),
        solver_option=SolverOption(max_iter=12, tol=1e-8))
    fleet = make_fleet(n_problems, size_range=(16, 64), seed=0, dtype=dtype)
    probs = [FleetProblem.from_synthetic(s, name=f"obs{i}")
             for i, s in enumerate(fleet)]

    def timed_pass() -> float:
        t0 = time.perf_counter()
        solve_many(probs, opt)
        return time.perf_counter() - t0

    # Neither side may inherit ambient plane state from the dev shell.
    saved = {k: os.environ.pop(k, None)
             for k in ("MEGBA_METRICS", "MEGBA_TRACE", "MEGBA_FLIGHT")}
    try:
        with timer.phase("obs_warm"):
            solve_many(probs, opt)
        # Arm metrics + spans (flight only fires on crash paths, so it
        # adds nothing to a clean run) against fresh default instances.
        _metrics.reset_default_registry()
        _spans.reset_default_recorder()
        # INTERLEAVED best-of-6 pairs: sequential blocks would charge
        # any monotone container drift (frequency scaling, a noisy
        # neighbour arriving) entirely to whichever side ran second —
        # on this shared box that drift alone exceeds the 2% band.
        # Alternating off/on reps puts both sides in the same weather,
        # and min() discards the on side's one-time lazy-import cost.
        off_s = on_s = float("inf")
        for _ in range(6):
            os.environ.pop("MEGBA_METRICS", None)
            os.environ.pop("MEGBA_TRACE", None)
            with timer.phase("obs_off"):
                off_s = min(off_s, timed_pass())
            os.environ["MEGBA_METRICS"] = "1"
            os.environ["MEGBA_TRACE"] = "1"
            with timer.phase("obs_on"):
                on_s = min(on_s, timed_pass())
        snap = _metrics.default_registry().snapshot()
        n_spans = len(_spans.default_recorder().drain())
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _metrics.reset_default_registry()
        _spans.reset_default_recorder()

    overhead_pct = 100.0 * (on_s - off_s) / off_s
    result = {
        "problems": n_problems,
        "off_s": round(off_s, 4),
        "on_s": round(on_s, 4),
        "overhead_pct": round(overhead_pct, 3),
        "band_pct": 2.0,
        "within_band": bool(overhead_pct <= 2.0),
        # Evidence the instrumented side actually instrumented: the
        # number of metric families populated and spans recorded.
        "metric_families": len(snap["metrics"]),
        "spans": n_spans,
    }
    artifact_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_obs.json")
    with open(artifact_path, "w") as fh:
        json.dump(result, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return result


def federation_head_to_head(n_workers: int, dtype, timer) -> dict:
    """Single-host FleetQueue vs an N-worker FleetRouter on one fleet,
    plus the replica cold-start split (artifact-load vs compile).

    THROUGHPUT: all sides solve the same `make_fleet` problems (the
    fleet_head_to_head generator, scaled up), fully warmed, each
    configuration timed best-of-2 (this sandbox is a shared 2-core
    container with a cgroup CPU quota — single measurements swing 2-3x
    under noisy neighbours, and two simultaneous pinned processes
    measure only ~1.15x ONE process's throughput, i.e. the quota caps
    aggregate compute below 2 honest cores).  Because of that cap the
    whole-machine comparison cannot show real scale-out here; the
    curve that CAN be certified on this lane is EQUAL-RESOURCE
    scaling: fed_1 and fed_N workers pinned to the SAME per-worker
    core slice (cores // N each), so the 1→N ratio measures what the
    router/stealing/IPC layer costs and gains per added host —
    `scaling_equal_resources` is the ROADMAP "~linear 1→N" observable,
    `scaling_vs_single_queue` is recorded for honesty with the machine
    context attached.

    COLD START: one fresh worker warmed from serialized artifacts vs
    one compiling from scratch, both measured config→fleet-solved over
    the same manifest buckets, full fleet submitted atomically
    (submit_many) so batch composition reproduces the exporter's and
    the artifact worker dispatches it with ZERO traces (worker-side
    retrace-sentinel certification, reported in the JSON).  Set
    MEGBA_BENCH_FEDERATION_COLD=0 to skip the (compile-heavy) cold
    half.  Results land in BENCH_federation.json next to the JSON line.
    """
    import tempfile

    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption
    from megba_tpu.io.synthetic import make_fleet
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.serving import (
        CompilePool,
        FleetProblem,
        FleetQueue,
        FleetRouter,
        FleetStats,
        solve_many,
    )

    n_problems = int(os.environ.get(
        "MEGBA_BENCH_FEDERATION_PROBLEMS", "32") or "32")
    opt = ProblemOption(
        dtype=dtype,
        algo_option=AlgoOption(max_iter=8),
        solver_option=SolverOption(max_iter=12, tol=1e-8))
    fleet = make_fleet(n_problems, size_range=(16, 64), seed=0, dtype=dtype)
    probs = [FleetProblem.from_synthetic(s, name=f"fed{i}")
             for i, s in enumerate(fleet)]
    engine = make_residual_jacobian_fn(mode=opt.jacobian_mode)

    root = tempfile.mkdtemp(prefix="megba_bench_fed_")
    manifest = os.path.join(root, "manifest.json")

    # -- exporter: deterministic bucket discovery through solve_many
    # (one batch per bucket — exactly what submit_many through the
    # router reproduces), then the portable-executable export ----------
    export_pool = CompilePool(stats=FleetStats(), artifacts=root)
    with timer.phase("federation_warm_export_pool"):
        solve_many(probs, opt, pool=export_pool)
    export_pool.save_manifest(manifest, option=opt)
    with timer.phase("federation_export"):
        exported = export_pool.export_artifacts(engine, opt)

    # -- single-host baseline: a warmed FleetQueue (own pool, jit path;
    # max_wait large so flush() drives one deterministic batch per
    # bucket) ----------------------------------------------------------
    qpool = CompilePool(stats=FleetStats())

    def queue_pass():
        stats = FleetStats()
        with FleetQueue(opt, max_batch=n_problems, max_wait_s=30.0,
                        pool=qpool, stats=stats) as q:
            futs = [q.submit(p) for p in probs]
            q.flush()
            out = [f.result(timeout=600) for f in futs]
        return out, stats

    with timer.phase("federation_warm_single"):
        queue_pass()
    single_s = float("inf")
    for _ in range(2):  # best-of-2: noisy-neighbour suppression
        t0 = time.perf_counter()
        with timer.phase("federation_single"):
            queue_pass()
        single_s = min(single_s, time.perf_counter() - t0)

    # -- cold start: artifact replica vs compile replica -----------------
    # Both replicas dispatch the FULL fleet, submitted atomically
    # (submit_many) with max_batch >= any bucket's population: batch
    # composition then reproduces the exporter's solve_many batches
    # exactly, so the artifact replica's first fleet rides the store
    # end to end — zero traces, the sentinel-certified contract.
    def replica_cold_start(artifacts):
        router = FleetRouter(opt, n_workers=1, artifacts=artifacts,
                             manifest=manifest, max_batch=n_problems)
        try:
            t0 = time.perf_counter()
            futs = router.submit_many(probs)
            router.flush()
            [f.result(timeout=600) for f in futs]
            first_solve_s = time.perf_counter() - t0
            d = router.stats.as_dict()
            cs = d["cold_start"]["w0"]
            fs = d["first_solve"]["w0"]
            return {
                "mode": cs["mode"],
                "warm_s": round(cs["warm_s"], 3),
                "first_solve_s": round(first_solve_s, 3),
                "cold_start_to_first_solve_s": round(
                    cs["warm_s"] + first_solve_s, 3),
                "buckets": cs["buckets"],
                "artifact_loads": cs["artifact_loads"],
                "artifact_compiles": cs["artifact_compiles"],
                "first_solve_traces": fs["traces"],
            }
        finally:
            router.close()

    cold = None
    if os.environ.get("MEGBA_BENCH_FEDERATION_COLD", "1") != "0":
        with timer.phase("federation_cold_artifact"):
            from_artifacts = replica_cold_start(root)
        with timer.phase("federation_cold_compile"):
            from_compile = replica_cold_start(None)
        cold = {
            "from_artifacts": from_artifacts,
            "from_compile": from_compile,
            "speedup": round(
                from_compile["cold_start_to_first_solve_s"]
                / max(from_artifacts["cold_start_to_first_solve_s"], 1e-9),
                2),
        }

    # -- federated throughput: equal-resource 1→N scaling ----------------
    # Every worker — in BOTH sweeps — is pinned to the same-size core
    # slice (cores // n_workers), so fed_1 is "one host" and fed_n is
    # "n hosts" of identical resources; the ratio is the scale-out
    # curve, isolated from this container's aggregate CPU quota.
    try:
        n_cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n_cores = os.cpu_count() or 1
    per_worker_cores = max(1, n_cores // n_workers)

    def router_pass(workers):
        router = FleetRouter(opt, n_workers=workers, artifacts=root,
                             manifest=manifest, strict_manifest=True,
                             max_batch=n_problems,
                             pin_cpus=per_worker_cores)
        try:
            with timer.phase(f"federation_warm_x{workers}"):
                futs = router.submit_many(probs)
                router.flush()
                [f.result(timeout=600) for f in futs]
            wall = float("inf")
            out = None
            for _ in range(2):  # best-of-2
                t0 = time.perf_counter()
                with timer.phase(f"federation_x{workers}"):
                    futs = router.submit_many(probs)
                    router.flush()
                    res = [f.result(timeout=600) for f in futs]
                dt = time.perf_counter() - t0
                if dt < wall:
                    wall, out = dt, res
            return out, wall, router.stats.as_dict(), router.pinned
        finally:
            router.close()

    _, fed1_s, fed1_stats, fed1_pinned = router_pass(1)
    fed_out, fedn_s, fed_stats, fedn_pinned = router_pass(n_workers)

    result = {
        "workers": n_workers,
        "problems": n_problems,
        "exported_artifacts": exported,
        "machine": {
            "cores": n_cores,
            "per_worker_cores": per_worker_cores,
            # Equal-resource scaling is only CERTIFIED when pinning
            # actually applied in BOTH sweeps (n_workers > cores
            # leaves workers unpinned, with a warning — the ratio is
            # then asymmetric and must not be read as the curve).
            "pinned": bool(fed1_pinned and fedn_pinned),
            "note": ("shared container with a cgroup CPU quota: two "
                     "simultaneous pinned processes measure ~1.15x ONE "
                     "process (aggregate compute capped), so "
                     "scaling_vs_single_queue understates real "
                     "multi-host scale-out; scaling_equal_resources is "
                     "the certified curve"),
        },
        "problems_per_sec_single_queue": round(n_problems / single_s, 2),
        "problems_per_sec_federated_1": round(n_problems / fed1_s, 2),
        "problems_per_sec_federated_n": round(n_problems / fedn_s, 2),
        "scaling_vs_single_queue": round(single_s / fedn_s, 3),
        "scaling_equal_resources": round(fed1_s / fedn_s, 3),
        "single_queue_s": round(single_s, 3),
        "federated_1_s": round(fed1_s, 3),
        "federated_n_s": round(fedn_s, 3),
        "steals": fed_stats["steals"],
        "problems_by_worker": fed_stats["problems_by_worker"],
        "first_solve_traces": {
            w: fs.get("traces")
            for w, fs in fed_stats.get("first_solve", {}).items()},
        "statuses": sorted({r.status_name for r in fed_out}),
        "cold_start": cold,
    }
    artifact_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_federation.json")
    with open(artifact_path, "w") as fh:
        json.dump(result, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return result


def mesh2d_head_to_head(s, base_option, edge_shards, cam_blocks,
                        timer) -> dict:
    """2-D (edge_shards x cam_blocks) mesh vs 1-D edge sharding at the
    SAME world size on the same scene (MEGBA_BENCH_MESH2D=<ExC>).

    Records wall-clock (both sides warmed first), the static
    bytes-moved-per-CG-step census of each compiled program (ring
    model, analysis/hlo.collective_bytes_moved over the PCG-body
    collectives — the same model the budget gate pins), the tile
    geometry, and the co-observation plan's streaming reuse factor.
    Results land in BENCH_mesh2d.json.

    HONESTY TAG: this container's bench lane is CPU-only (~1.2 cores of
    aggregate quota), where virtual-device collectives are memcpys —
    wall-clock CANNOT show the ICI win here and usually shows 2-D
    slightly slower (the tile loop adds launches).  The structural
    bytes/census numbers are the transferable evidence; the wall-clock
    is recorded so the CPU-lane overhead is known, not hidden.
    """
    import dataclasses as _dc

    import jax

    from megba_tpu.analysis import hlo as hlo_mod
    from megba_tpu.analysis.program_audit import pcg_body_collective_summary
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.ops.segtiles import cached_camera_tile_plan
    from megba_tpu.solve import flat_solve

    world = edge_shards * cam_blocks
    if len(jax.devices()) < world:
        return {"skipped": f"need {world} devices, have "
                           f"{len(jax.devices())}"}
    f = make_residual_jacobian_fn(mode=base_option.jacobian_mode)

    def opt_for(mesh2d: bool):
        return _dc.replace(
            base_option, world_size=world,
            solver_option=_dc.replace(
                base_option.solver_option, mesh_2d=mesh2d,
                cam_blocks=cam_blocks if mesh2d else 0))

    def run(label, mesh2d):
        opt = opt_for(mesh2d)
        kw = dict(use_tiled=False, timer=timer)
        # Census FIRST: the lower_only compile primes the persistent
        # cache, so the warm solve below pays the trace but not a
        # second XLA compile (the census itself would otherwise be a
        # third full compile-path round trip per side).
        lowered = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                             s.pt_idx, opt, use_tiled=False,
                             lower_only=True)
        ops = hlo_mod.parse_compiled_ops(lowered.compile().as_text())
        with timer.phase(f"mesh2d_warm_{label}"):
            flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                       s.pt_idx, opt, **kw)
        t0 = time.perf_counter()
        with timer.phase(f"mesh2d_solve_{label}"):
            res = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                             s.pt_idx, opt, **kw)
            # Dispatch is async: without this the cheaper side can
            # report its enqueue time, not its solve time.
            jax.block_until_ready(res)
        elapsed = time.perf_counter() - t0
        body, census, bytes_moved = pcg_body_collective_summary(ops, world)
        return res, {
            "elapsed_s": round(elapsed, 3),
            "lm_iters": int(res.iterations),
            "pcg_iters": int(res.pcg_iterations),
            "collective_bytes_per_sp": round(bytes_moved, 1),
            "pcg_body_census": census,
            "pcg_body_group_sizes": sorted(
                {op.group_size(world) or world for op in body}),
        }

    res1, side1 = run("1d", mesh2d=False)
    res2, side2 = run("2d", mesh2d=True)
    # Cache hit by construction: the 2-D flat_solve above planned the
    # identical geometry through the same fingerprint LRU.
    (plan, _), _ = cached_camera_tile_plan(
        s.cam_idx, s.pt_idx, len(s.cameras0), len(s.points0),
        edge_shards, cam_blocks)
    rel_gap = abs(float(res2.cost) - float(res1.cost)) / max(
        float(res1.cost), 1e-30)
    result = {
        "lane": f"CPU fallback ({jax.default_backend()}); wall-clock "
                "shows the tile-loop overhead, NOT the ICI overlap win "
                "— the bytes/census axes are the transferable evidence",
        "mesh": f"{edge_shards}x{cam_blocks}",
        "world_size": world,
        "scene": {"cameras": len(s.cameras0), "points": len(s.points0),
                  "edges": int(s.obs.shape[0])},
        "one_d": side1,
        "two_d": side2,
        "bytes_per_sp_ratio_2d_vs_1d": round(
            side2["collective_bytes_per_sp"]
            / max(side1["collective_bytes_per_sp"], 1e-30), 4),
        "tile_plan": {
            "cam_blocks": plan.cam_blocks,
            "tile_cams": plan.tile_cams,
            "shard_points": plan.shard_points,
            "tiles_per_matvec": plan.cam_blocks,  # the C-step loop
            "edges_padded": plan.n_edges_padded,
            "bucket_width": plan.bucket_width,
            "reuse": plan.reuse,
        },
        "final_cost_rel_gap": rel_gap,
    }
    artifact_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_mesh2d.json")
    with open(artifact_path, "w") as fh:
        json.dump(result, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return result


def bf16_head_to_head(s, base_option, timer) -> dict:
    """bf16 MXU pipeline vs f32 under the production inexact-LM config
    (MEGBA_BENCH_BF16=1): the same scene, forcing + warm starts, PR 5's
    guards ARMED on both sides — the contract is that bf16 converges
    within the documented cost-gap band of the f32 control with ZERO
    guard/recovery events (a clean bf16 run must not lean on the
    containment machinery), certified in BENCH_bf16.json together with
    the structurally-pinned halved bytes axis.

    HONESTY TAG: this lane is CPU — XLA:CPU float-normalizes bf16
    compute to f32-with-converts, so wall-clock here measures the
    CONVERT OVERHEAD, not the MXU/bandwidth win; the transferable
    evidence is the cost-parity curve and the auditor's structural
    axes (bf16-only dot operands with f32 accumulation, and
    collective_bytes_per_sp at exactly half the f32 programs' —
    ba_bf16_w2_f32 is re-audited LIVE here, the committed
    ANALYSIS_BUDGET.json supplies the 2-D pair).
    """
    import dataclasses as _dc

    import jax

    from megba_tpu.common import RobustOption, SolverOption
    from megba_tpu.observability.report import _decode_fallback_totals
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    f = make_residual_jacobian_fn(mode=base_option.jacobian_mode)

    def opt_for(bf16: bool):
        return _dc.replace(
            base_option,
            robust_option=RobustOption(guards=True),
            solver_option=SolverOption(
                max_iter=PCG_ITERS, refuse_ratio=1e30,
                forcing=True, warm_start=True, bf16=bf16))

    def run(label, bf16):
        opt = opt_for(bf16)
        kw = dict(use_tiled=False, timer=timer)
        with timer.phase(f"bf16_warm_{label}"):
            jax.block_until_ready(
                flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                           s.pt_idx, opt, **kw).cost)
        t0 = time.perf_counter()
        with timer.phase(f"bf16_solve_{label}"):
            res = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                             s.pt_idx, opt, **kw)
            jax.block_until_ready(res)
        elapsed = time.perf_counter() - t0
        iters = int(res.iterations)
        trace = res.trace
        level = _decode_fallback_totals(trace, iters) or {}
        return res, {
            "elapsed_s": round(elapsed, 3),
            "lm_iters": iters,
            "accepted": int(res.accepted),
            "pcg_iters_total": int(res.pcg_iterations),
            "cost": float(res.cost),
            "status": _status_name(res),
            # Decoded guard/recovery evidence: LM-contained recoveries,
            # in-loop PCG breakdown restarts, and per-level
            # preconditioner fallbacks — all must be ZERO on a clean
            # run, bf16 included.
            "recoveries": int(res.recoveries),
            "pcg_breakdowns": int(np.asarray(
                trace.pcg_breakdown[:iters]).sum()),
            "precond_fallbacks": dict(level),
        }

    res32, side32 = run("f32", bf16=False)
    resbf, sidebf = run("bf16", bf16=True)
    gap = abs(sidebf["cost"] - side32["cost"]) / max(
        abs(side32["cost"]), 1e-30)

    # Structural axes via the auditor: live w2 pair (cheap tiny
    # programs, persistent compile cache), committed budget for the
    # 2-D pair.
    audited = {}
    if len(jax.devices()) >= 2:
        from megba_tpu.analysis import program_audit

        specs = program_audit.program_specs()
        for name in ("ba_sharded_w2_f32", "ba_bf16_w2_f32"):
            with timer.phase(f"bf16_audit_{name}"):
                a = program_audit.audit_program(specs[name])
            audited[name] = {
                "collective_bytes_per_sp": a.metrics()[
                    "collective_bytes_per_sp"],
                "violations": a.violations(),
            }
    from megba_tpu.analysis import budget as budget_mod

    committed = budget_mod.load_baseline()
    committed_axis = {
        name: committed.get(name, {}).get("collective_bytes_per_sp")
        for name in ("ba_sharded_w2_f32", "ba_bf16_w2_f32",
                     "ba_2d_w4_f32", "ba_bf16_2d_w4_f32")}

    result = {
        "lane": f"CPU fallback ({jax.default_backend()}): bf16 compute "
                "is float-normalized to f32-with-converts here, so "
                "wall-clock shows convert overhead, NOT the MXU win — "
                "cost parity + the structural axes are the evidence",
        "config": "inexact-LM (forcing + warm starts), guards armed, "
                  f"pcg_max_iter={PCG_ITERS}",
        "scene": {"cameras": len(s.cameras0), "points": len(s.points0),
                  "edges": int(s.obs.shape[0])},
        "f32": side32,
        "bf16": sidebf,
        "cost_rel_gap": gap,
        # The documented acceptance band (ARCHITECTURE.md "Precision
        # ladder"): the bf16 operator carries ~eps_bf16-class accuracy,
        # and the inexact-LM trajectory resolves the OPERATOR, not the
        # arithmetic — venice-class scenes land well inside 2e-2.
        "cost_gap_band": 2e-2,
        "pcg_iters_delta": (sidebf["pcg_iters_total"]
                            - side32["pcg_iters_total"]),
        "guard_events_bf16": (sidebf["recoveries"]
                              + sidebf["pcg_breakdowns"]),
        "audited_live": audited,
        "committed_bytes_per_sp": committed_axis,
    }
    artifact_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_bf16.json")
    with open(artifact_path, "w") as fh:
        json.dump(result, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return result


def fused_head_to_head(s, base_option, timer) -> dict:
    """Fused edge-pipeline kernels vs the tiled XLA lowering
    (MEGBA_BENCH_FUSED=1): the same scene on the SAME tiled edge plans,
    production inexact-LM config, guards ARMED on both sides — the
    contract is end-to-end LM cost parity within 1e-5 with ZERO
    guard/recovery events (a clean fused run must not lean on the
    containment machinery), plus the structural bytes story: the
    per-S·p HBM budget with and without the transient gather/product
    round-trips the fusion deletes, priced live for this scene by
    analysis/edge_budget and pinned for the canonical programs in
    ANALYSIS_BUDGET.json.

    HONESTY TAG: off-TPU the Pallas kernels run under INTERPRET mode —
    wall-clock here measures the Python-level kernel interpreter (orders
    of magnitude slower than both XLA:CPU and the Mosaic lowering), so
    the fused side's elapsed time is NOT evidence of the VMEM-residency
    win and no speedup ratio is reported from this lane.  The
    transferable evidence is the cost-parity band, the zero-guard
    certificate, and the analytical bytes_touched_per_sp delta.
    """
    import dataclasses as _dc
    import tempfile

    import jax

    from megba_tpu.common import RobustOption, SolverOption
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    f = make_residual_jacobian_fn(mode=base_option.jacobian_mode)
    tele = tempfile.NamedTemporaryFile(
        mode="r", suffix=".jsonl", delete=False)
    tele.close()

    def opt_for(fused: bool):
        return _dc.replace(
            base_option,
            robust_option=RobustOption(guards=True),
            telemetry=tele.name,
            solver_option=SolverOption(
                max_iter=PCG_ITERS, refuse_ratio=1e30,
                forcing=True, warm_start=True, fused_kernels=fused))

    def run(label, fused):
        opt = opt_for(fused)
        kw = dict(use_tiled=True, timer=timer)
        with timer.phase(f"fused_warm_{label}"):
            jax.block_until_ready(
                flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                           s.pt_idx, opt, **kw).cost)
        t0 = time.perf_counter()
        with timer.phase(f"fused_solve_{label}"):
            res = flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                             s.pt_idx, opt, **kw)
            jax.block_until_ready(res)
        elapsed = time.perf_counter() - t0
        iters = int(res.iterations)
        return res, {
            "elapsed_s": round(elapsed, 3),
            "lm_iters": iters,
            "accepted": int(res.accepted),
            "pcg_iters_total": int(res.pcg_iterations),
            "cost": float(res.cost),
            "status": _status_name(res),
            "recoveries": int(res.recoveries),
            "pcg_breakdowns": int(np.asarray(
                res.trace.pcg_breakdown[:iters]).sum()),
        }

    res32, side_xla = run("xla", fused=False)
    resf, side_fused = run("pallas", fused=True)
    gap = abs(side_fused["cost"] - side_xla["cost"]) / max(
        abs(side_xla["cost"]), 1e-30)

    # Per-solve tile/reuse metrics ride the telemetry report; the last
    # line is the fused run.
    tiles = None
    try:
        lines = [ln for ln in open(tele.name) if ln.strip()]
        if lines:
            tiles = json.loads(lines[-1]).get("tiles")
    finally:
        os.unlink(tele.name)

    # The structural half: price this scene's per-S·p HBM bytes with
    # the transient gather/product round-trips (tiled XLA lowering) and
    # without them (fused kernels) — same plan, same dtype surface, so
    # the delta IS the traffic the fusion deletes.
    from megba_tpu.analysis import budget as budget_mod
    from megba_tpu.analysis import edge_budget
    from megba_tpu.ops.segtiles import cached_dual_plans

    (plan_c, _plans), _ = cached_dual_plans(
        np.asarray(s.cam_idx), np.asarray(s.pt_idx),
        len(s.cameras0), len(s.points0))
    geom = dict(num_cameras=len(s.cameras0), cd=9,
                num_points=len(s.points0), pd=3, rd=2,
                edge_slots=plan_c.n_slots)
    arm_xla = edge_budget.schur_sp_budget(**geom, transient_roundtrips=True)
    arm_fused = edge_budget.schur_sp_budget(**geom,
                                            transient_roundtrips=False)
    committed = budget_mod.load_baseline()
    committed_axes = {
        name: {k: committed.get(name, {}).get(k)
               for k in ("flops_per_sp", "bytes_touched_per_sp")}
        for name in ("ba_tiled_f32", "ba_bf16_w2_f32")}

    result = {
        "lane": f"CPU fallback ({jax.default_backend()}): the Pallas "
                "kernels run under INTERPRET mode here — the fused "
                "side's wall-clock measures the kernel interpreter, "
                "not the VMEM-residency win, so no speedup ratio is "
                "reported; cost parity + zero guard events + the "
                "analytical bytes delta are the evidence",
        "config": "inexact-LM (forcing + warm starts), guards armed, "
                  f"pcg_max_iter={PCG_ITERS}, tiled plans both sides",
        "scene": {"cameras": len(s.cameras0), "points": len(s.points0),
                  "edges": int(s.obs.shape[0])},
        "xla_tiled": side_xla,
        "fused_pallas": side_fused,
        "cost_rel_gap": gap,
        # The ISSUE acceptance band: end-to-end LM cost within 1e-5 of
        # the unfused lowering with zero guard events.
        "cost_gap_band": 1e-5,
        "guard_events_fused": (side_fused["recoveries"]
                               + side_fused["pcg_breakdowns"]),
        "tiles": tiles,
        "bytes_per_sp_with_transients": arm_xla["bytes_touched_per_sp"],
        "bytes_per_sp_fused": arm_fused["bytes_touched_per_sp"],
        "transient_bytes_deleted_per_sp": (
            arm_xla["bytes_touched_per_sp"]
            - arm_fused["bytes_touched_per_sp"]),
        "flops_per_sp": arm_fused["flops_per_sp"],
        "committed_axes": committed_axes,
    }
    artifact_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_fused.json")
    with open(artifact_path, "w") as fh:
        json.dump(result, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return result


def main() -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from megba_tpu.utils.backend import (
        enable_persistent_compile_cache,
        ensure_usable_backend,
        install_graceful_term,
    )

    install_graceful_term()
    # Persistent on-disk compile cache: a tunnel window must not spend
    # its first chip-minutes recompiling venice-scale programs that a
    # previous run (or the CPU fallback of the same shapes) already
    # compiled (VERDICT r04 weak-spot 2).
    enable_persistent_compile_cache()

    # ensure_usable_backend re-asserts the caller's JAX_PLATFORMS over
    # the axon plugin's startup override and skips the tunnel probe
    # entirely for non-axon pins — a CPU smoke run can neither hang on
    # nor claim the single-client TPU tunnel.
    backend_note = ""
    if _C.force_cpu:
        # This config is CPU by design; no accelerator probe needed.
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif ensure_usable_backend():
        backend_note = " [accelerator init hung; CPU fallback]"

    import jax
    import jax.numpy as jnp

    from megba_tpu.common import (
        AlgoOption,
        ComputeKind,
        JacobianMode,
        ProblemOption,
        SolverOption,
    )
    from megba_tpu.algo import lm_solve
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    dtype_name, jac_name, ck_name = _C.dtype, _C.jacobian, _C.compute
    mixed = _C.mixed
    if dtype_name == "float64":
        jax.config.update("jax_enable_x64", True)
    dtype = np.dtype(dtype_name)
    jac_mode = JacobianMode[jac_name]
    compute_kind = ComputeKind[ck_name]

    # MEGBA_BENCH_LOCALITY=ring|grid swaps the expander observation
    # assignment for a locality-structured scene (banded camera
    # co-observation — the structure real BAL graphs have and the
    # camera-graph coarse-space preconditioners need; see
    # io/synthetic.py).  Default: the historical expander scene.
    locality = os.environ.get("MEGBA_BENCH_LOCALITY") or None
    s = make_synthetic_bal(
        num_cameras=NUM_CAMERAS,
        num_points=NUM_POINTS,
        obs_per_point=OBS_PER_POINT,
        seed=0,
        param_noise=1e-2,
        pixel_noise=0.5,
        dtype=dtype,
        locality=locality,
    )
    n_edge = s.obs.shape[0]

    option = ProblemOption(
        dtype=dtype,
        compute_kind=compute_kind,
        jacobian_mode=jac_mode,
        mixed_precision_pcg=mixed,
        algo_option=AlgoOption(max_iter=LM_ITERS, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(max_iter=PCG_ITERS, tol=1e-10, refuse_ratio=1e30),
    )
    f = make_residual_jacobian_fn(mode=jac_mode)

    # Feature-major tiled lowering (ops/segtiles.py): the dual-plan slot
    # order replaces the camera sort + quantum padding, and every
    # segment reduction / expansion in the solver becomes a block-aligned
    # MXU one-hot matmul (scatter-free).  TPU + float32 only: on a CPU
    # fallback the tiled plan's XLA lowering is slower AND fatter than
    # the chunked scatter-add build, so benching it there measures the
    # wrong engine (the r02 regression).  f64 (ladybug) always keeps the
    # classic chunked path.
    from megba_tpu.core.fm import EDGE_QUANTUM
    from megba_tpu.core.types import is_cam_sorted, pad_edges

    from megba_tpu.solve import default_use_tiled

    # Phase breakdown (utils/timing.PhaseTimer) rides the JSON line so
    # committed BENCH_*.json artifacts carry per-phase wall clocks, and
    # feeds the optional SolveReport below.
    from megba_tpu.utils.timing import PhaseTimer

    timer = PhaseTimer()

    tiled = default_use_tiled(dtype)
    plans = None
    if tiled:
        from megba_tpu.ops.segtiles import cached_dual_plans, probe_kernels

        # Host plan cache (ops/segtiles.py): bench reruns in one process
        # (and the production flat_solve path) reuse the ~270 ms plan
        # build; hits are counted into the phase breakdown.
        with timer.phase("plan"):
            (plan_c, plans), plan_hit = cached_dual_plans(
                s.cam_idx, s.pt_idx, NUM_CAMERAS, NUM_POINTS,
                use_kernels=probe_kernels())
            if plan_hit:
                timer.count_event("plan_cache_hit")
        perm, pmask = plan_c.perm, plan_c.mask
        obs_p = s.obs[perm] * pmask[:, None].astype(dtype)
        cam_idx_p = plan_c.seg
        pt_idx_p = np.where(pmask > 0, s.pt_idx[perm], 0).astype(np.int32)
        mask = pmask.astype(dtype)
        cam_sorted = True
    else:
        obs_p, cam_idx_p, pt_idx_p, mask = pad_edges(
            s.obs, s.cam_idx, s.pt_idx, EDGE_QUANTUM, dtype=dtype)
        cam_sorted = is_cam_sorted(s.cam_idx)
    args = (
        jnp.asarray(s.cameras0.T),
        jnp.asarray(s.points0.T),
        jnp.asarray(np.ascontiguousarray(obs_p.T)),
        jnp.asarray(cam_idx_p),
        jnp.asarray(pt_idx_p),
        jnp.asarray(mask),
    )

    def timed_solve(opt, label, cluster_plan=None):
        solve = jax.jit(
            lambda cams, pts, obs, ci, pi, m, pl, cp: lm_solve(
                f, cams, pts, obs, ci, pi, m, opt, cam_sorted=cam_sorted,
                plans=pl, cluster_plan=cp)
        )
        # Warmup (compile) — not part of the metric, but recorded as a
        # phase so the compile cost is visible in the artifact.
        with timer.phase(f"compile_{label}") as ph:
            ph.sync(solve(*args, plans, cluster_plan).cost)
        t0 = time.perf_counter()
        with timer.phase(f"solve_{label}") as ph:
            res = ph.sync(solve(*args, plans, cluster_plan))
        return res, time.perf_counter() - t0

    res, elapsed = timed_solve(option, "throughput")
    iters = int(res.iterations)
    lm_iters_per_sec = iters / elapsed

    # Convergence-mode pass: the reference's DEFAULT solver flags
    # (common.h:27-33 — tol=1e-1, refuse_ratio=1.0), the regime
    # BASELINE.md's cost-vs-time metric is defined in.  The throughput
    # pass above (tol=1e-10) does near-fixed work per LM iteration; this
    # one measures the time-to-quality observable.  It is a second
    # compiled program; MEGBA_BENCH_CONVERGENCE=0 skips it when the
    # accelerator window is too precious for a second large compile.
    conv = None
    if os.environ.get("MEGBA_BENCH_CONVERGENCE", "1") != "0":
        import dataclasses as _dc

        conv_option = _dc.replace(option, solver_option=SolverOption())
        conv_res, conv_elapsed = timed_solve(conv_option, "convergence")
        conv_iters = int(conv_res.iterations)
        conv_pcg = int(conv_res.pcg_iterations)
        conv = {
            "lm_iters_per_sec": round(conv_iters / conv_elapsed, 3),
            "lm_iters": conv_iters,
            "accepted": int(conv_res.accepted),
            "pcg_iters_per_lm": round(
                float(conv_res.pcg_iterations) / max(conv_iters, 1), 2),
            # Plateau-metric context (ISSUE 7): WHICH preconditioner
            # operator produced this pcg_iters_per_lm, and what one
            # inner iteration costs wall-clock (each fused PCG
            # iteration performs exactly one precond apply + one S·p,
            # so this is the per-apply cost ceiling) — tracked in the
            # artifact instead of only in round prose.
            "precond": conv_option.solver_option.precond.name.lower(),
            "pcg_iter_ms": round(
                1000.0 * conv_elapsed / max(conv_pcg, 1), 3),
            "cost_reduction": round(
                float(conv_res.initial_cost)
                / max(float(conv_res.cost), 1e-30), 3),
            "elapsed_s": round(conv_elapsed, 3),
        }
    # Inexact-LM head-to-head (MEGBA_BENCH_FORCING=1): the same LM
    # budget with adaptive Eisenstat-Walker forcing + PCG warm starts
    # (SolverOption(forcing=True, warm_start=True)) vs the fixed
    # tight-tolerance regime above (tol=1e-10, cold starts — the
    # configuration FINAL_CONVERGENCE.json / the throughput pass run,
    # and the waste ISSUE 4 targets: every LM iteration pays ~30 PCG
    # iterations regardless of how inaccurate its linearization is).
    # Contract: total PCG iterations down >= 30%, final cost unmoved
    # within the curve gap_tol (scripts/run_tests.sh asserts it).
    forcing_cmp = None
    if os.environ.get("MEGBA_BENCH_FORCING") == "1":
        import dataclasses as _dcf

        forcing_option = _dcf.replace(option, solver_option=SolverOption(
            max_iter=PCG_ITERS, refuse_ratio=1e30,
            forcing=True, warm_start=True))
        f_res, f_elapsed = timed_solve(forcing_option, "forcing")
        base_pcg = int(res.pcg_iterations)
        f_pcg = int(f_res.pcg_iterations)
        base_cost = float(res.cost)
        forcing_cmp = {
            "lm_iters": int(f_res.iterations),
            "accepted": int(f_res.accepted),
            "pcg_iters_total": f_pcg,
            "pcg_iters_total_fixed_tol": base_pcg,
            "pcg_reduction": round(1.0 - f_pcg / max(base_pcg, 1), 4),
            "cost": float(f_res.cost),
            "cost_fixed_tol": base_cost,
            "cost_rel_gap": round(
                abs(float(f_res.cost) - base_cost)
                / max(abs(base_cost), 1e-30), 6),
            "elapsed_s": round(f_elapsed, 3),
            "speedup_vs_fixed_tol": round(elapsed / f_elapsed, 3),
        }
    # Preconditioner head-to-head (MEGBA_BENCH_PRECOND=<kind>): the
    # SAME inexact-LM production config (forcing + warm starts — the
    # regime PR 4 made the default optimum) solved twice, differing
    # ONLY in the preconditioner operator family, so the comparison
    # attributes iterations and wall-clock to the operator and nothing
    # else.  This is the ISSUE 7 plateau observable: total PCG
    # iterations, relative final-cost gap, wall-clock ratio, and the
    # per-inner-iteration cost delta (= what one stronger apply costs).
    # MEGBA_BENCH_CLUSTERS / MEGBA_BENCH_NEUMANN_ORDER tune the knobs.
    precond_cmp = None
    precond_kind_env = os.environ.get("MEGBA_BENCH_PRECOND", "")
    if precond_kind_env:
        import dataclasses as _dcp

        from megba_tpu.common import PrecondKind

        cand_kind = PrecondKind[precond_kind_env.upper()]
        n_clusters = int(os.environ.get("MEGBA_BENCH_CLUSTERS", "0") or "0")
        n_order = int(os.environ.get("MEGBA_BENCH_NEUMANN_ORDER", "1"))
        # Hierarchy / smoothed-aggregation knobs (MULTILEVEL /
        # TWO_LEVEL): per-level coarsening factor, total level cap,
        # prolongator smoothing weight.
        coarsen = float(os.environ.get("MEGBA_BENCH_COARSEN", "4.0"))
        n_levels = int(os.environ.get("MEGBA_BENCH_LEVELS", "3"))
        sm_omega = float(os.environ.get("MEGBA_BENCH_SMOOTH_OMEGA", "0.0"))
        base_opt = _dcp.replace(option, solver_option=SolverOption(
            max_iter=100, refuse_ratio=1e30, forcing=True, warm_start=True))
        cand_opt = _dcp.replace(option, solver_option=SolverOption(
            max_iter=100, refuse_ratio=1e30, forcing=True, warm_start=True,
            precond=cand_kind, neumann_order=n_order,
            coarse_clusters=n_clusters, coarsen_factor=coarsen,
            max_levels=n_levels, smooth_omega=sm_omega))
        cand_cluster_plan = None
        hierarchy_levels = None
        if cand_kind == PrecondKind.TWO_LEVEL:
            from megba_tpu.ops.segtiles import cached_cluster_plan

            with timer.phase("plan"):
                (_, cand_cluster_plan), _hit = cached_cluster_plan(
                    np.asarray(cam_idx_p), np.asarray(pt_idx_p),
                    NUM_CAMERAS, NUM_POINTS, n_clusters,
                    mask=np.asarray(mask), smooth_omega=sm_omega)
            hierarchy_levels = 2
        elif cand_kind == PrecondKind.MULTILEVEL:
            from megba_tpu.ops.segtiles import cached_multilevel_plan

            with timer.phase("plan"):
                (mplan, cand_cluster_plan), _hit = cached_multilevel_plan(
                    np.asarray(cam_idx_p), np.asarray(pt_idx_p),
                    NUM_CAMERAS, NUM_POINTS, n_clusters,
                    mask=np.asarray(mask), coarsen_factor=coarsen,
                    max_levels=n_levels, smooth_omega=sm_omega)
            # fine level + every planned coarse level
            hierarchy_levels = 1 + len(mplan.level_sizes)
        p_base, p_base_s = timed_solve(base_opt, "precond_base")
        p_cand, p_cand_s = timed_solve(cand_opt, "precond_cand",
                                       cluster_plan=cand_cluster_plan)
        # Per-level fallback totals decoded from the candidate's trace
        # (solver/precond.py enum codes): the head-to-head artifact
        # records whether the stronger operator actually ran its full
        # hierarchy or spent iterations degraded.
        from megba_tpu.observability.report import _decode_fallback_totals

        cand_fallback = _decode_fallback_totals(
            p_cand.trace, int(p_cand.iterations))
        b_pcg, c_pcg = int(p_base.pcg_iterations), int(p_cand.pcg_iterations)
        b_cost = float(p_base.cost)
        b_iter_ms = 1000.0 * p_base_s / max(b_pcg, 1)
        c_iter_ms = 1000.0 * p_cand_s / max(c_pcg, 1)
        precond_cmp = {
            "kind": cand_kind.name.lower(),
            "baseline_kind": "jacobi",
            "locality": locality,
            "coarse_clusters": n_clusters,
            "neumann_order": n_order,
            "coarsen_factor": coarsen,
            "max_levels": n_levels,
            "smooth_omega": sm_omega,
            "hierarchy_levels": hierarchy_levels,
            "fallback": cand_fallback,
            "pcg_iters_total": c_pcg,
            "pcg_iters_total_jacobi": b_pcg,
            "pcg_reduction": round(1.0 - c_pcg / max(b_pcg, 1), 4),
            "cost": float(p_cand.cost),
            "cost_jacobi": b_cost,
            "cost_rel_gap": round(
                abs(float(p_cand.cost) - b_cost) / max(abs(b_cost), 1e-30),
                6),
            "elapsed_s": round(p_cand_s, 3),
            "elapsed_s_jacobi": round(p_base_s, 3),
            "speedup_vs_jacobi": round(p_base_s / p_cand_s, 3),
            # Per-inner-iteration wall cost (one precond apply + one
            # S·p each): the delta is what the stronger apply costs.
            "pcg_iter_ms": round(c_iter_ms, 3),
            "pcg_iter_ms_jacobi": round(b_iter_ms, 3),
            "precond_apply_extra_ms": round(c_iter_ms - b_iter_ms, 3),
        }
    # Fleet head-to-head (MEGBA_BENCH_FLEET=<n>): n heterogeneous small
    # problems (io/synthetic.make_fleet) solved as a serial flat_solve
    # loop vs one batched solve_many pass (serving/batcher.py), both
    # warmed first so the metric is steady-state problems/sec at fixed
    # convergence — the roadmap's fleet throughput observable — not
    # compile amortisation.  scripts/run_tests.sh asserts batched > serial
    # and a terminal per-lane SolveStatus.
    fleet_cmp = None
    n_fleet = int(os.environ.get("MEGBA_BENCH_FLEET", "0") or "0")
    if n_fleet:
        fleet_cmp = fleet_head_to_head(n_fleet, dtype, timer)
    # Federation head-to-head (MEGBA_BENCH_FEDERATION=<n_workers>): the
    # scale-OUT complement — an n-worker FleetRouter (worker processes
    # warmed from serialized artifacts) vs the single-host FleetQueue on
    # the same fleet, plus the replica cold-start split (artifact-load
    # vs compile-from-scratch, zero-trace certified).  Also written to
    # BENCH_federation.json as a standalone artifact.
    federation_cmp = None
    n_fed = int(os.environ.get("MEGBA_BENCH_FEDERATION", "0") or "0")
    if n_fed:
        federation_cmp = federation_head_to_head(n_fed, dtype, timer)
    # 2-D mesh head-to-head (MEGBA_BENCH_MESH2D=<ExC>): the 2-D
    # camera x edge distribution vs 1-D edge sharding at the same world
    # size — bytes-moved per CG step, subgroup census, tile/reuse
    # geometry, and (CPU-lane-tagged) wall-clock.  Also written to
    # BENCH_mesh2d.json as a standalone artifact.
    mesh2d_cmp = None
    if _MESH2D_SPEC:
        mesh2d_cmp = mesh2d_head_to_head(s, option, _E2D, _C2D, timer)
    # bf16 MXU pipeline head-to-head (MEGBA_BENCH_BF16=1): f32 vs bf16
    # under the production inexact-LM config with guards armed — cost
    # parity band, PCG-iteration delta, decoded guard/recovery counts
    # (must be zero on the clean run), and the auditor's halved
    # collective_bytes_per_sp axes.  Also written to BENCH_bf16.json.
    bf16_cmp = None
    if _BF16_BENCH:
        bf16_cmp = bf16_head_to_head(s, option, timer)
    # Fused edge-pipeline head-to-head (MEGBA_BENCH_FUSED=1): Pallas
    # mega-kernels vs the tiled XLA lowering on the same plans — cost
    # parity band, zero-guard certificate, tile/reuse geometry, and the
    # analytical transient-bytes-deleted axis (interpret-mode
    # honesty-tagged off-TPU).  Also written to BENCH_fused.json.
    fused_cmp = None
    if _FUSED_BENCH:
        fused_cmp = fused_head_to_head(s, option, timer)
    # Observability-plane overhead head-to-head (MEGBA_BENCH_OBS=1):
    # solve_many with the plane off vs metrics+spans on, same warmed
    # fleet, <= 2% acceptance band.  Also written to BENCH_obs.json.
    obs_cmp = None
    if _OBS_BENCH:
        obs_cmp = obs_head_to_head(max(n_fleet, 8), dtype, timer)
    # Charge the reference model the S·p products this run actually
    # executed (the PCG can exit below the 30-iteration cap), so both
    # sides of vs_baseline do the same algorithmic work.  The fused
    # Chronopoulos-Gear body performs iterations+1 matvecs per PCG
    # solve (one pre-loop product primes the recurrence), so the model
    # is charged the +1 too — otherwise vs_baseline would flatter this
    # implementation by one uncharged matvec per LM iteration.
    measured_pcg_per_lm = float(res.pcg_iterations) / max(iters, 1)
    baseline = derived_baseline_lm_iters_per_sec(
        n_edge=n_edge,
        n_cam=NUM_CAMERAS,
        n_pt=NUM_POINTS,
        pcg_iters=measured_pcg_per_lm + 1.0,
        ref_dtype_bytes=_C.ref_dtype_bytes,
        implicit=_C.ref_implicit,
    )
    backend = jax.default_backend()
    # A TPU-targeted config that ran on anything else is a FALLBACK: its
    # number is not comparable to the accelerator baseline, so
    # vs_baseline is withheld (null) and the fallback is flagged at top
    # level — a driver reading this JSON cannot mistake a CPU number for
    # a chip number.  ladybug is CPU by design (the reference's
    # BAL_Double example is measured CPU-side too), so it keeps its
    # ratio.
    fallback = (not _C.force_cpu) and backend != "tpu"
    vs_baseline = (
        None if fallback else round(lm_iters_per_sec / baseline, 3))
    # Opt-in compiled-program audit embed (MEGBA_BENCH_AUDIT=1): the
    # static census of the canonical CPU-lowered programs rides the
    # bench line, so a committed BENCH_*.json can show a perf move next
    # to the collective/FLOP-budget story of the same tree.  Off by
    # default — it costs extra CPU lowers/compiles inside a possibly
    # precious accelerator window.
    audit_summaries = None
    if os.environ.get("MEGBA_BENCH_AUDIT") == "1":
        # Context rides with the summaries: unlike the CLI gate, this
        # embed lowers on THIS process's backend and x64 setting — on a
        # TPU backend with x64 off the dtype census is vacuous and the
        # cost metrics are not comparable to the (CPU, x64-on)
        # ANALYSIS_BUDGET.json.  `audit --check` is the gate; this is
        # the bench line's descriptive snapshot, labeled as such.
        # Never let a failed audit discard a finished measurement: the
        # timing loop already ran, so ANY embed error (import included)
        # becomes data in the line rather than a crashed bench.
        try:
            from megba_tpu.analysis import program_audit

            # The SPMD program needs a 2-device mesh; a single-device
            # bench topology audits just the single-device program (the
            # audit CLI lane always forces >= 2 virtual CPU devices).
            names = ["ba_single_f32"]
            if len(jax.devices()) >= 2:
                names.append("ba_sharded_w2_f32")
            audit_summaries = {
                "backend": backend,
                "x64": bool(jax.config.jax_enable_x64),
                "gate": "python -m megba_tpu.analysis.audit --check",
                "programs": {
                    name: audit.summary()
                    for name, audit in program_audit.audit_all(names).items()
                },
            }
        except Exception as exc:  # audit must not kill the bench line
            audit_summaries = {
                "backend": backend,
                "error": f"{type(exc).__name__}: {exc}",
            }
    print(
        json.dumps(
            {
                "metric": (
                    f"LM iters/sec, synthetic {CONFIG} "
                    f"({NUM_CAMERAS} cams / {NUM_POINTS} pts / {n_edge} edges, "
                    f"{measured_pcg_per_lm:.1f} PCG iters/LM), "
                    f"{dtype_name} {jac_name.lower()} {ck_name.lower()}"
                    f"{' bf16-mixed' if mixed else ''}"
                    f"{f' locality={locality}' if locality else ''}, "
                    f"1 chip [{backend}]{backend_note}"
                ),
                "value": round(lm_iters_per_sec, 3),
                "unit": "LM iters/s",
                "vs_baseline": vs_baseline,
                "fallback": fallback,
                "extra": {
                    "backend": backend,
                    # Scene structure (MEGBA_BENCH_LOCALITY): None =
                    # the historical expander assignment.
                    "locality": locality,
                    # Termination semantics (common.SolveStatus): a
                    # driver reading this line can tell a converged
                    # number from a stalled or recovered one.
                    "status": _status_name(res),
                    "tiled_engine": bool(tiled),
                    "lm_iter_ms": round(1000.0 * elapsed / iters, 3),
                    "pcg_iters_per_lm": round(measured_pcg_per_lm, 2),
                    "pcg_iters_per_sec": round(
                        lm_iters_per_sec * measured_pcg_per_lm, 1),
                    "derived_baseline_lm_iters_per_sec": round(baseline, 3),
                    "baseline_model": "A100-40GB roofline, BASELINE.md",
                    # Reference-default flags (tol=1e-1, refuse_ratio=1):
                    # the time-to-quality regime of BASELINE.md's metric.
                    "convergence_mode": conv,
                    # Inexact-LM head-to-head (MEGBA_BENCH_FORCING=1):
                    # forcing+warm_start vs the fixed tight-tol regime.
                    "forcing": forcing_cmp,
                    # Preconditioner head-to-head
                    # (MEGBA_BENCH_PRECOND=<kind>): the candidate
                    # operator vs block-Jacobi under the same
                    # inexact-LM config.
                    "precond": precond_cmp,
                    # Fleet head-to-head (MEGBA_BENCH_FLEET=<n>):
                    # batched solve_many vs serial flat_solve loop.
                    "fleet": fleet_cmp,
                    # Federation head-to-head
                    # (MEGBA_BENCH_FEDERATION=<n_workers>): n-worker
                    # router vs single-host FleetQueue + cold-start
                    # split; also lands in BENCH_federation.json.
                    "federation": federation_cmp,
                    # 2-D mesh head-to-head (MEGBA_BENCH_MESH2D=<ExC>):
                    # subgroup-collective bytes-moved + tile/reuse
                    # geometry vs 1-D; also lands in BENCH_mesh2d.json.
                    "mesh2d": mesh2d_cmp,
                    # bf16 MXU pipeline head-to-head
                    # (MEGBA_BENCH_BF16=1): cost parity + guard
                    # cleanliness + halved bytes axes; also lands in
                    # BENCH_bf16.json.
                    "bf16": bf16_cmp,
                    # Fused edge-pipeline head-to-head
                    # (MEGBA_BENCH_FUSED=1): Pallas kernels vs tiled
                    # XLA — cost parity + zero guards + transient-bytes
                    # delta; also lands in BENCH_fused.json.
                    "fused": fused_cmp,
                    # Observability-plane overhead (MEGBA_BENCH_OBS=1):
                    # plane off vs metrics+spans on, <= 2% band; also
                    # lands in BENCH_obs.json.
                    "obs": obs_cmp,
                    # Per-phase wall clocks (compile vs solve, per pass)
                    # so BENCH_*.json artifacts carry phase timings.
                    "phases": {
                        name: {"total_s": round(d["total_s"], 4),
                               "calls": d["calls"]}
                        for name, d in timer.as_dict().items()
                    },
                    # analysis/program_audit summaries (MEGBA_BENCH_AUDIT=1).
                    "program_audit": audit_summaries,
                },
            }
        )
    )

    # Same opt-in sink as solve.flat_solve: one structured SolveReport
    # per bench run when MEGBA_TELEMETRY is set (off: nothing imported).
    telemetry = os.environ.get("MEGBA_TELEMETRY")
    if telemetry:
        from megba_tpu.observability.report import append_report, build_report

        append_report(
            build_report(option, res, timer.as_dict(), {
                "num_cameras": NUM_CAMERAS,
                "num_points": NUM_POINTS,
                "num_edges": int(n_edge),
                "num_edges_padded": int(args[2].shape[-1]),
                "world_size": 1,
                "bench_config": CONFIG,
            }, audit=audit_summaries), telemetry)


if __name__ == "__main__":
    main()
