"""Benchmark harness — prints ONE JSON line for the driver.

Measures LM iterations/second on a synthetic problem shaped like one of
the five BASELINE.md configurations (MEGBA_BENCH_CONFIG = ladybug /
trafalgar / venice / final / final_mixed; default venice — 1778 cameras,
~1M observations, analytical Jacobian, implicit Schur PCG, float32) on
whatever accelerator JAX provides (the real TPU chip under the driver).

The reference repo publishes no absolute numbers (BASELINE.md); the
`vs_baseline` field is computed against ASSUMED_BASELINE_LM_ITERS_PER_SEC,
an order-of-magnitude estimate of the reference's per-LM-iteration rate
on its 2-GPU Venice demo config (README.md:56-58) — to be replaced when a
measured reference number exists.
"""

from __future__ import annotations

import json
import time

import numpy as np

import os

ASSUMED_BASELINE_LM_ITERS_PER_SEC = 10.0

# The five BASELINE.md configs, selectable via MEGBA_BENCH_CONFIG
# (default: venice — the headline metric).  Shapes approximate the BAL
# dataset of the same name (cameras and observation count match; the
# synthetic point count is scaled so obs_per_point stays ~10).
from typing import NamedTuple


class BenchConfig(NamedTuple):
    cameras: int
    points: int
    obs_per_point: int
    dtype: str
    jacobian: str
    compute: str
    mixed: bool = False
    force_cpu: bool = False


CONFIGS = {
    # BAL Ladybug problem-49-7776: BAL_Double semantics, CPU, world 1.
    "ladybug": BenchConfig(49, 7776, 4, "float64", "AUTODIFF", "EXPLICIT", force_cpu=True),
    # BAL Trafalgar problem-257-65132: BAL_Float autodiff, single chip.
    "trafalgar": BenchConfig(257, 22_544, 10, "float32", "AUTODIFF", "EXPLICIT"),
    # BAL Venice problem-1778-993923: analytical, distributed PCG shape.
    "venice": BenchConfig(1778, 99_392, 10, "float32", "ANALYTICAL", "IMPLICIT"),
    # BAL Final problem-13682-4456117: analytical implicit.
    "final": BenchConfig(13_682, 445_612, 10, "float32", "ANALYTICAL", "IMPLICIT"),
    # Final, mixed precision: fp32 residuals + bf16 PCG.
    "final_mixed": BenchConfig(13_682, 445_612, 10, "float32", "ANALYTICAL", "IMPLICIT", mixed=True),
}

CONFIG = os.environ.get("MEGBA_BENCH_CONFIG", "venice")
if CONFIG not in CONFIGS:
    raise SystemExit(
        f"unknown MEGBA_BENCH_CONFIG {CONFIG!r}; choose from {sorted(CONFIGS)}")

# MEGBA_BENCH_SCALE in (0, 1] shrinks the problem for smoke tests.
_SCALE = float(os.environ.get("MEGBA_BENCH_SCALE", "1.0"))
_C = CONFIGS[CONFIG]
NUM_CAMERAS = max(8, int(_C.cameras * _SCALE))
NUM_POINTS = max(64, int(_C.points * _SCALE))
OBS_PER_POINT = _C.obs_per_point
LM_ITERS = 8
PCG_ITERS = 30


def _probe_pallas(cam_idx):
    """Decide whether to route the Hessian build through the Pallas kernel.

    MEGBA_BENCH_PALLAS=0 disables, =1 forces; default 'auto' enables only
    if the plan is feasible AND the kernel actually compiles+matches on a
    small input on this backend (so an unexpected Mosaic lowering failure
    degrades to the XLA path instead of killing the benchmark).
    """
    import jax
    import jax.numpy as jnp

    from megba_tpu.ops.pallas_kernels import camera_hessian_gradient, camera_window_plan

    mode = os.environ.get("MEGBA_BENCH_PALLAS", "auto")
    if mode == "0":
        return None
    ok, window = camera_window_plan(cam_idx)
    if not ok:
        return None
    plan = (512, window)
    if mode == "1":
        return plan
    if jax.default_backend() != "tpu":
        # Off-TPU the kernel runs in interpret mode — correct but slow;
        # only the real TPU lowering is a performance win.
        return None
    try:
        n, cd, od = 1024, 9, 2
        jc = jnp.ones((n, od, cd), jnp.float32)
        r = jnp.ones((n, od), jnp.float32)
        ci = jnp.asarray(np.repeat(np.arange(8), n // 8), jnp.int32)
        hpp, g = camera_hessian_gradient(
            jc, r, ci, num_cameras=8, tile=512, window=window,
            interpret=False)  # probe only runs on the TPU backend
        expect = float(n // 8 * od)
        assert abs(float(hpp[0, 0, 0]) - expect) < 1e-2
        return plan
    except Exception as e:  # pragma: no cover - backend specific
        import sys

        print(f"pallas probe failed ({type(e).__name__}); using XLA path",
              file=sys.stderr, flush=True)
        return None


def main() -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from megba_tpu.utils.backend import ensure_usable_backend

    backend_note = ""
    if _C.force_cpu:
        # This config is CPU by design; no accelerator probe needed.
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif ensure_usable_backend():
        backend_note = " [accelerator init hung; CPU fallback]"

    import jax
    import jax.numpy as jnp

    from megba_tpu.common import (
        AlgoOption,
        ComputeKind,
        JacobianMode,
        ProblemOption,
        SolverOption,
    )
    from megba_tpu.algo import lm_solve
    from megba_tpu.io.synthetic import make_synthetic_bal
    from megba_tpu.ops.residuals import make_residual_jacobian_fn

    dtype_name, jac_name, ck_name = _C.dtype, _C.jacobian, _C.compute
    mixed = _C.mixed
    if dtype_name == "float64":
        jax.config.update("jax_enable_x64", True)
    dtype = np.dtype(dtype_name)
    jac_mode = JacobianMode[jac_name]
    compute_kind = ComputeKind[ck_name]

    s = make_synthetic_bal(
        num_cameras=NUM_CAMERAS,
        num_points=NUM_POINTS,
        obs_per_point=OBS_PER_POINT,
        seed=0,
        param_noise=1e-2,
        pixel_noise=0.5,
        dtype=dtype,
    )
    n_edge = s.obs.shape[0]

    option = ProblemOption(
        dtype=dtype,
        compute_kind=compute_kind,
        jacobian_mode=jac_mode,
        mixed_precision_pcg=mixed,
        algo_option=AlgoOption(max_iter=LM_ITERS, epsilon1=1e-12, epsilon2=1e-15),
        solver_option=SolverOption(max_iter=PCG_ITERS, tol=1e-10, refuse_ratio=1e30),
    )
    f = make_residual_jacobian_fn(mode=jac_mode)

    args = (
        jnp.asarray(s.cameras0),
        jnp.asarray(s.points0),
        jnp.asarray(s.obs),
        jnp.asarray(s.cam_idx),
        jnp.asarray(s.pt_idx),
        jnp.ones(n_edge, dtype=dtype),
    )

    from megba_tpu.core.types import is_cam_sorted

    cam_sorted = is_cam_sorted(s.cam_idx)
    pallas_plan = (
        _probe_pallas(s.cam_idx)
        if cam_sorted and dtype == np.float32 else None
    )
    solve = jax.jit(
        lambda cams, pts, obs, ci, pi, m: lm_solve(
            f, cams, pts, obs, ci, pi, m, option, cam_sorted=cam_sorted,
            pallas_plan=pallas_plan)
    )

    # Warmup (compile) — not timed.
    res = solve(*args)
    jax.block_until_ready(res.cost)
    iters = int(res.iterations)

    t0 = time.perf_counter()
    res = solve(*args)
    jax.block_until_ready(res.cost)
    elapsed = time.perf_counter() - t0

    lm_iters_per_sec = iters / elapsed
    print(
        json.dumps(
            {
                "metric": (
                    f"LM iters/sec, synthetic {CONFIG} scale ({n_edge} edges), "
                    f"{dtype_name} {jac_name.lower()} {ck_name.lower()}"
                    f"{' bf16-mixed' if mixed else ''}, 1 chip{backend_note}"
                ),
                "value": round(lm_iters_per_sec, 3),
                "unit": "LM iters/s",
                "vs_baseline": round(lm_iters_per_sec / ASSUMED_BASELINE_LM_ITERS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
