"""Residual/Jacobian engine tests: analytical vs autodiff vs finite diff."""

import jax
import jax.numpy as jnp
import numpy as np

from megba_tpu.common import JacobianMode
from megba_tpu.ops.residuals import (
    apply_sqrt_info,
    bal_residual,
    bal_residual_jacobian_analytical,
    make_residual_jacobian_fn,
)


def random_edge(r):
    # A sane BAL-like camera: point in front of camera after transform.
    w = r.normal(size=3) * 0.1
    t = r.normal(size=3) * 0.5 + np.array([0, 0, 5.0])
    cam = np.concatenate([w, t, [500.0 + r.normal() * 10, 1e-7, 1e-13]])
    pt = r.normal(size=3) + np.array([0, 0, -10.0])
    obs = r.normal(size=2) * 100
    return jnp.asarray(cam), jnp.asarray(pt), jnp.asarray(obs)


def test_analytical_matches_autodiff():
    r = np.random.default_rng(0)
    for _ in range(20):
        cam, pt, obs = random_edge(r)
        res_a, Jc_a, Jp_a = bal_residual_jacobian_analytical(cam, pt, obs)
        res = bal_residual(cam, pt, obs)
        Jc, Jp = jax.jacfwd(bal_residual, argnums=(0, 1))(cam, pt, obs)
        np.testing.assert_allclose(res_a, res, rtol=1e-12)
        np.testing.assert_allclose(Jc_a, Jc, rtol=1e-9, atol=1e-9)
        np.testing.assert_allclose(Jp_a, Jp, rtol=1e-9, atol=1e-9)


def test_jacobian_finite_difference():
    r = np.random.default_rng(1)
    cam, pt, obs = random_edge(r)
    _, Jc, Jp = bal_residual_jacobian_analytical(cam, pt, obs)
    eps = 1e-6
    for i in range(9):
        d = np.zeros(9)
        d[i] = eps
        fd = (
            np.asarray(bal_residual(cam + d, pt, obs))
            - np.asarray(bal_residual(cam - d, pt, obs))
        ) / (2 * eps)
        np.testing.assert_allclose(Jc[:, i], fd, rtol=1e-4, atol=1e-4)
    for i in range(3):
        d = np.zeros(3)
        d[i] = eps
        fd = (
            np.asarray(bal_residual(cam, pt + d, obs))
            - np.asarray(bal_residual(cam, pt - d, obs))
        ) / (2 * eps)
        np.testing.assert_allclose(Jp[:, i], fd, rtol=1e-4, atol=1e-4)


def test_forward_and_reverse_autodiff_agree():
    r = np.random.default_rng(5)
    edges = [random_edge(r) for _ in range(8)]
    cams = jnp.stack([e[0] for e in edges], axis=-1)
    pts = jnp.stack([e[1] for e in edges], axis=-1)
    obs = jnp.stack([e[2] for e in edges], axis=-1)
    fa = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    fb = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF_FORWARD)
    ra, Jca, Jpa = fa(cams, pts, obs)
    rb, Jcb, Jpb = fb(cams, pts, obs)
    np.testing.assert_allclose(ra, rb, rtol=1e-12)
    np.testing.assert_allclose(Jca, Jcb, rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(Jpa, Jpb, rtol=1e-10, atol=1e-12)


def test_vectorised_modes_agree():
    r = np.random.default_rng(2)
    edges = [random_edge(r) for _ in range(16)]
    cams = jnp.stack([e[0] for e in edges], axis=-1)
    pts = jnp.stack([e[1] for e in edges], axis=-1)
    obs = jnp.stack([e[2] for e in edges], axis=-1)
    fa = make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF)
    fb = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    ra, Jca, Jpa = jax.jit(fa)(cams, pts, obs)
    rb, Jcb, Jpb = jax.jit(fb)(cams, pts, obs)
    assert ra.shape == (2, 16) and Jca.shape == (18, 16) and Jpa.shape == (6, 16)
    np.testing.assert_allclose(ra, rb, rtol=1e-12)
    np.testing.assert_allclose(Jca, Jcb, rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(Jpa, Jpb, rtol=1e-9, atol=1e-9)


def test_sqrt_info_weighting():
    r = np.random.default_rng(3)
    cam, pt, obs = random_edge(r)
    res, Jc, Jp = bal_residual_jacobian_analytical(cam, pt, obs)
    # Feature-major single-edge arrays: rows x 1 edge.
    res_f, Jc_f, Jp_f = res.reshape(2, 1), Jc.reshape(18, 1), Jp.reshape(6, 1)
    L = np.array([[2.0, 0.0], [1.0, 3.0]])
    L_f = jnp.asarray(L.reshape(4, 1))
    rw, Jcw, Jpw = apply_sqrt_info(res_f, Jc_f, Jp_f, L_f)
    np.testing.assert_allclose(rw[:, 0], L @ np.asarray(res))
    np.testing.assert_allclose(np.asarray(Jcw[:, 0]).reshape(2, 9), L @ np.asarray(Jc))
    np.testing.assert_allclose(np.asarray(Jpw[:, 0]).reshape(2, 3), L @ np.asarray(Jp))
    # Identity passthrough.
    r2, _, _ = apply_sqrt_info(res_f, Jc_f, Jp_f, None)
    assert r2 is res_f
