"""Network-chaos harness tests: deterministic TCP fault injection.

Compile-free tier-1: a scripted echo upstream behind `ChaosTcpProxy`,
asserting the relay is transparent when the plan is clean, that
partition/heal sever and refuse deterministically, that truncation
surfaces as the transport's typed failure on the victim side, and that
the seeded plan replays bitwise.
"""

import socket
import threading

import numpy as np
import pytest

from megba_tpu.robustness.netfaults import ChaosTcpProxy, NetFaultPlan
from megba_tpu.serving.transport import (
    FrameError,
    TcpTransport,
    parse_address,
)


@pytest.fixture
def echo_upstream():
    """A framed echo server; yields its 'host:port'."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(16)
    addr = "%s:%d" % srv.getsockname()
    stop = threading.Event()

    def acceptor():
        while not stop.is_set():
            srv.settimeout(0.2)
            try:
                conn, _ = srv.accept()
            except TimeoutError:
                continue
            except OSError:
                return

            def serve(conn=conn):
                chan = TcpTransport(conn)
                try:
                    while True:
                        chan.send({"echo": chan.recv(timeout_s=10.0)})
                except (FrameError, TimeoutError, OSError):
                    chan.close()

            threading.Thread(target=serve, daemon=True).start()

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    yield addr
    stop.set()
    srv.close()
    t.join(timeout=5.0)


def _connect(proxy):
    return TcpTransport(
        socket.create_connection(parse_address(proxy.address)))


def test_clean_plan_is_transparent_relay(echo_upstream):
    with ChaosTcpProxy(echo_upstream) as proxy:
        chan = _connect(proxy)
        msg = {"x": np.arange(64.0), "n": 7}
        chan.send(msg)
        out = chan.recv(timeout_s=5.0)
        np.testing.assert_array_equal(out["echo"]["x"], msg["x"])
        assert proxy.event_counts() == {"accept": 1}
        chan.close()


def test_partition_severs_refuses_then_heals(echo_upstream):
    with ChaosTcpProxy(echo_upstream) as proxy:
        chan = _connect(proxy)
        chan.send({"n": 1})
        assert chan.recv(timeout_s=5.0) == {"echo": {"n": 1}}
        proxy.partition()
        # Live connection severed: the next exchange fails typed.
        with pytest.raises((FrameError, OSError, TimeoutError)):
            chan.send({"n": 2})
            chan.recv(timeout_s=2.0)
        # New connections refused (accept-then-close) while partitioned.
        with pytest.raises((FrameError, OSError, TimeoutError)):
            c2 = _connect(proxy)
            c2.send({"n": 3})
            c2.recv(timeout_s=2.0)
        proxy.heal()
        c3 = _connect(proxy)
        c3.send({"n": 4})
        assert c3.recv(timeout_s=5.0) == {"echo": {"n": 4}}
        counts = proxy.event_counts()
        assert counts["partition"] == 1 and counts["heal"] == 1
        assert counts.get("refused", 0) >= 1
        c3.close()
        chan.close()


def test_truncate_fault_surfaces_as_typed_frame_failure(echo_upstream):
    plan = NetFaultPlan(seed=11, truncate_rate=1.0)
    with ChaosTcpProxy(echo_upstream, plan) as proxy:
        chan = _connect(proxy)
        chan.send({"payload": b"z" * 8192})
        # The request is truncated toward the upstream, which then
        # drops the connection — the client observes a typed frame
        # failure (FrameError subclass) or a raw socket error, never
        # garbage unpickling.
        with pytest.raises((FrameError, OSError, TimeoutError)):
            chan.recv(timeout_s=5.0)
        assert proxy.event_counts().get("truncate", 0) >= 1
        chan.close()


def test_drop_fault_kills_connection(echo_upstream):
    plan = NetFaultPlan(seed=5, drop_rate=1.0)
    with ChaosTcpProxy(echo_upstream, plan) as proxy:
        chan = _connect(proxy)
        chan.send({"n": 1})
        with pytest.raises((FrameError, OSError, TimeoutError)):
            chan.recv(timeout_s=5.0)
        assert proxy.event_counts().get("drop", 0) >= 1
        chan.close()


def test_plan_validation_and_seeded_determinism():
    with pytest.raises(ValueError):
        NetFaultPlan(drop_rate=1.5)
    with pytest.raises(ValueError):
        NetFaultPlan(delay_s=-1.0)
    p = NetFaultPlan(seed=7, drop_rate=0.3, truncate_rate=0.1)
    a = [float(p.rng(0, 0).random()) for _ in range(4)]
    b = [float(p.rng(0, 0).random()) for _ in range(4)]
    assert a == b  # same (seed, conn, direction) stream replays
    assert a != [float(p.rng(0, 1).random()) for _ in range(4)]
    assert a != [float(p.rng(1, 0).random()) for _ in range(4)]
