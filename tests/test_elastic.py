"""Elastic-distribution unit tests: compile-free tier-1 coverage.

Everything here runs without tracing or compiling a solver program
(tier-1 is near its time budget): the HeartbeatBoard and
CollectiveWatchdog state machines under injected clocks, the
ElasticMonitor guard with real threads but trivial host functions (the
no-wedge regression), the multihost init/shutdown state machine with
the jax calls monkeypatched out, the schema-v3 checkpoint header, the
local-devices mesh scope, the N-process harness driven by stub
subprocesses, and the summarize --aggregate elastic view.  The
real-collectives / real-SIGKILL lane lives in
tests/test_elastic_killresume.py (slow) and the run_tests.sh elastic
smoke.
"""

import json
import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from megba_tpu.robustness.elastic import (
    CollectiveTimeout,
    CollectiveWatchdog,
    ElasticConfig,
    ElasticError,
    ElasticMonitor,
    HeartbeatBoard,
    RankState,
    WorkerLost,
)
from megba_tpu.utils.checkpoint import SCHEMA_VERSION, load_state, save_state


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# --------------------------------------------------- HeartbeatBoard


def test_board_classifies_alive_straggler_dead(tmp_path):
    clock = FakeClock()
    b0 = HeartbeatBoard(str(tmp_path), 0, 2, straggler_after_s=1.0,
                        dead_after_s=3.0, clock=clock)
    b1 = HeartbeatBoard(str(tmp_path), 1, 2, straggler_after_s=1.0,
                        dead_after_s=3.0, clock=clock)
    b1.beat()
    assert b0.observe() == {1: RankState.ALIVE}
    clock.advance(1.5)  # past straggler, short of dead
    assert b0.observe() == {1: RankState.STRAGGLER}
    b1.beat()  # a fresh beat resurrects the straggler
    assert b0.observe() == {1: RankState.ALIVE}
    clock.advance(3.0)
    assert b0.observe() == {1: RankState.DEAD}
    assert b0.dead_ranks() == [1]
    assert b0.staleness(1) == pytest.approx(3.0)


def test_board_never_seen_rank_unknown_then_dead(tmp_path):
    """A rank that never joins is UNKNOWN inside the join grace
    (anchored at the FIRST observation, not process start), DEAD past
    it — a worker that never came up is as lost as one that died."""
    clock = FakeClock(100.0)
    b0 = HeartbeatBoard(str(tmp_path), 0, 3, straggler_after_s=0.5,
                        dead_after_s=2.0, clock=clock)
    assert b0.observe() == {1: RankState.UNKNOWN, 2: RankState.UNKNOWN}
    clock.advance(1.9)
    assert b0.observe() == {1: RankState.UNKNOWN, 2: RankState.UNKNOWN}
    clock.advance(0.2)
    assert b0.observe() == {1: RankState.DEAD, 2: RankState.DEAD}


def test_board_beat_counter_not_wall_clock(tmp_path):
    """Liveness rides counter CHANGES on the observer's clock — a peer
    whose file content never changes goes stale even though the file
    exists, and cross-process wall clocks are never compared."""
    clock = FakeClock()
    b0 = HeartbeatBoard(str(tmp_path), 0, 2, straggler_after_s=0.5,
                        dead_after_s=1.0, clock=clock)
    with open(b0.path_for(1), "w") as fh:
        fh.write("7 123\n")  # frozen counter
    assert b0.observe() == {1: RankState.ALIVE}
    clock.advance(0.7)
    assert b0.observe() == {1: RankState.STRAGGLER}
    clock.advance(0.5)
    assert b0.observe() == {1: RankState.DEAD}
    # A torn/garbage file reads as "no beat", not a crash.
    with open(b0.path_for(1), "w") as fh:
        fh.write("not-a-counter")
    assert b0.observe() == {1: RankState.DEAD}


def test_board_validates_configuration(tmp_path):
    with pytest.raises(ValueError, match="outside world"):
        HeartbeatBoard(str(tmp_path), 3, 2)
    with pytest.raises(ValueError, match="straggler_after_s"):
        HeartbeatBoard(str(tmp_path), 0, 2, straggler_after_s=5.0,
                       dead_after_s=1.0)


# --------------------------------------------------- CollectiveWatchdog


def test_watchdog_arm_check_disarm_across_dispatches():
    clock = FakeClock()
    w = CollectiveWatchdog(clock=clock)
    t1 = w.arm("chunk@iter0", 10.0, now=0.0)
    assert w.armed_count() == 1
    assert w.check(t1, now=9.0) == pytest.approx(9.0)
    assert w.disarm(t1, now=9.5) == pytest.approx(9.5)
    assert w.armed_count() == 0
    # Re-arming for the next dispatch is a fresh deadline.
    t2 = w.arm("chunk@iter2", 10.0, now=20.0)
    assert w.check(t2, now=29.0) == pytest.approx(9.0)
    w.disarm(t2, now=29.0)
    assert w.timeouts == 0


def test_watchdog_timeout_payload_and_counter():
    w = CollectiveWatchdog(clock=FakeClock())
    tok = w.arm("chunk@iter4", 2.0, now=100.0)
    assert w.expired(now=101.0) == []
    assert w.expired(now=103.0) == [(tok, "chunk@iter4", 3.0)]
    with pytest.raises(CollectiveTimeout) as ei:
        w.check(tok, now=103.0)
    exc = ei.value
    assert exc.label == "chunk@iter4"
    assert exc.budget_s == pytest.approx(2.0)
    assert exc.elapsed_s == pytest.approx(3.0)
    assert isinstance(exc, ElasticError)
    assert w.timeouts == 1
    # The token stays armed: the guard's cleanup still owns the disarm.
    assert w.disarm(tok, now=103.0) == pytest.approx(3.0)
    with pytest.raises(ValueError, match="not armed"):
        w.disarm(tok)


def test_watchdog_rejects_bad_budgets_and_tokens():
    w = CollectiveWatchdog(clock=FakeClock())
    with pytest.raises(ValueError, match="budget_s"):
        w.arm("x", 0.0)
    with pytest.raises(ValueError, match="not armed"):
        w.check(99)


# --------------------------------------------------- ElasticMonitor guard


def _fast_config(tmp_path, world=2, **kw):
    defaults = dict(heartbeat_dir=str(tmp_path / "hb"), rank=0, world=world,
                    interval_s=0.05, straggler_after_s=0.1,
                    dead_after_s=0.25, watchdog_s=5.0,
                    compile_grace_s=0.0, poll_s=0.02)
    defaults.update(kw)
    return ElasticConfig(**defaults)


def test_guard_dead_peer_never_wedges_and_monitor_survives(tmp_path):
    """The no-wedge contract: a dispatch parked forever with a silent
    peer surfaces as a typed WorkerLost within ~dead_after_s — and the
    monitor keeps working afterwards (the abandoned worker thread
    cannot poison the next guard)."""
    with ElasticMonitor(_fast_config(tmp_path)) as m:
        blocker = threading.Event()
        t0 = time.monotonic()
        with pytest.raises(WorkerLost) as ei:
            m.guard("chunk@iter0", blocker.wait)
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, "typed error took longer than the watchdog"
        assert ei.value.ranks == (1,)
        assert ei.value.label == "chunk@iter0"
        assert ei.value.detected_after_s <= 5.0
        assert m.workers_lost == 1
        assert len(m.detection_s) == 1
        # Monitor (dispatcher side) survives: a later guard still runs.
        assert m.guard("after", lambda: 41 + 1) == 42
        blocker.set()


def test_guard_timeout_with_live_peer_is_collective_timeout(tmp_path):
    """A wedged dispatch while every peer still beats is a
    CollectiveTimeout (straggler semantics), not a WorkerLost."""
    cfg = _fast_config(tmp_path, watchdog_s=0.3, dead_after_s=10.0,
                       straggler_after_s=5.0)
    with ElasticMonitor(cfg) as m:
        peer = HeartbeatBoard(cfg.heartbeat_dir, 1, 2)
        stop = threading.Event()

        def keep_beating():
            while not stop.wait(0.03):
                peer.beat()

        beater = threading.Thread(target=keep_beating, daemon=True)
        peer.beat()
        beater.start()
        try:
            blocker = threading.Event()
            with pytest.raises(CollectiveTimeout) as ei:
                m.guard("chunk@iter2", blocker.wait)
            assert ei.value.budget_s == pytest.approx(0.3)
            assert m.collective_timeouts == 1
            blocker.set()
        finally:
            stop.set()


def test_guard_first_dispatch_compile_grace(tmp_path):
    """The first guarded dispatch of EACH program (grace_key) gets
    watchdog_s + compile_grace_s (jit compilation rides a program's
    first call); repeats of a seen key drop to the bare budget.
    Verified through the watchdog's armed budget — no sleeping."""
    cfg = _fast_config(tmp_path, world=1, watchdog_s=1.0,
                       compile_grace_s=9.0)
    m = ElasticMonitor(cfg)
    budgets = []
    real_arm = m.watchdog.arm

    def spy_arm(label, budget_s, now=None):
        budgets.append(budget_s)
        return real_arm(label, budget_s, now)

    m.watchdog.arm = spy_arm
    assert m.guard("first", lambda: 1) == 1
    assert m.guard("second", lambda: 2) == 2
    assert budgets == [10.0, 1.0]
    # A DIFFERENT program (e.g. a short final chunk, or the 0-iter
    # evaluate dispatch — max_iter is static) gets its own grace.
    assert m.guard("chunk2", lambda: 9, grace_key=("chunk", 2)) == 9
    assert m.guard("chunk2b", lambda: 9, grace_key=("chunk", 2)) == 9
    assert m.guard("evaluate", lambda: 9, grace_key=("chunk", 0)) == 9
    assert budgets == [10.0, 1.0, 10.0, 1.0, 10.0]
    # A reshard re-grants every grace: the shrunk mesh re-lowers all
    # programs.
    m.record_reshard(2, 1)
    assert m.guard("resumed", lambda: 3, grace_key=("chunk", 2)) == 3
    assert budgets == [10.0, 1.0, 10.0, 1.0, 10.0, 10.0]
    m.stop()


def test_guard_classifies_dispatch_error_with_dead_peer(tmp_path):
    """gloo surfaces a SIGKILL'd peer as a transport error within
    milliseconds — before the heartbeat threshold can elapse.  The
    guard must wait out the death window and classify it WorkerLost
    (with the original error as __cause__), not leak a bare
    ValueError."""
    with ElasticMonitor(_fast_config(tmp_path)) as m:
        def exploding_dispatch():
            raise ValueError("Gloo all-reduce failed: connection reset")

        with pytest.raises(WorkerLost) as ei:
            m.guard("chunk@iter0", exploding_dispatch)
        assert isinstance(ei.value.__cause__, ValueError)
        assert m.workers_lost == 1


def test_guard_passes_through_genuine_errors_when_peers_alive(tmp_path):
    """A dispatch exception with every peer beating is the program's
    own failure and must surface unchanged."""
    cfg = _fast_config(tmp_path, dead_after_s=0.2, straggler_after_s=0.1)
    with ElasticMonitor(cfg) as m:
        peer = HeartbeatBoard(cfg.heartbeat_dir, 1, 2)
        stop = threading.Event()

        def keep_beating():
            while not stop.wait(0.03):
                peer.beat()

        beater = threading.Thread(target=keep_beating, daemon=True)
        peer.beat()
        beater.start()
        try:
            with pytest.raises(ZeroDivisionError):
                m.guard("chunk@iter0", lambda: 1 / 0)
            assert m.workers_lost == 0
        finally:
            stop.set()


def test_check_peers_retired_after_reshard(tmp_path):
    """After the reshard the lost peers are retired: liveness checks
    stop raising (the shrunk world no longer contains them)."""
    with ElasticMonitor(_fast_config(tmp_path)) as m:
        m.check_peers()  # anchors rank 1's join grace (UNKNOWN for now)
        time.sleep(0.3)  # nobody ever beats for rank 1: grace expires
        with pytest.raises(WorkerLost):
            m.check_peers()
        m.record_reshard(2, 1)
        m.check_peers()  # no raise
        m.record_reshard(2, 1)  # idempotent per transition
        assert m.reshards == 1
        m.record_resume()
        block = m.report_block()
        assert block["workers_lost"] == 1
        assert block["reshards"] == 1 and block["resumes"] == 1
        assert block["monitor"]
        # Transitions also landed as PhaseTimer events.
        counts = {k: v["calls"] for k, v in m.timer.as_dict().items()}
        assert counts["elastic_worker_lost"] == 1
        assert counts["elastic_reshard"] == 1
        assert counts["elastic_resume"] == 1


def test_monitor_ensure_contract(tmp_path):
    monitor, owned = ElasticMonitor.ensure(None)
    assert monitor is None and owned is False
    cfg = _fast_config(tmp_path, world=1)
    m1, owned = ElasticMonitor.ensure(cfg)
    assert owned is True and m1._beater.is_alive()
    m1.stop()
    m2, owned = ElasticMonitor.ensure(m1)
    assert m2 is m1 and owned is False and m1._beater.is_alive()
    m1.stop()
    with pytest.raises(TypeError, match="ElasticConfig or ElasticMonitor"):
        ElasticMonitor.ensure("nope")


def test_elastic_config_validation(tmp_path):
    with pytest.raises(ValueError, match="outside world"):
        ElasticConfig(heartbeat_dir=str(tmp_path), rank=2, world=2)
    with pytest.raises(ValueError, match="watchdog_s"):
        ElasticConfig(heartbeat_dir=str(tmp_path), watchdog_s=0.0)
    with pytest.raises(ValueError, match="straggler_after_s"):
        ElasticConfig(heartbeat_dir=str(tmp_path), straggler_after_s=9.0,
                      dead_after_s=1.0)


# --------------------------------------------------- multihost state machine


def test_initialize_idempotent_and_reinit_after_shutdown(monkeypatch):
    """The satellite contract: exact-repeat init is a no-op; different
    params while initialized raise; after shutdown_multihost a process
    may legally re-initialize at a DIFFERENT world size."""
    from megba_tpu.parallel import multihost as mh

    inits = []
    state = {"up": False}
    monkeypatch.setattr(mh, "_distributed_is_initialized",
                        lambda: state["up"])
    monkeypatch.setattr(mh, "_elastic_connect",
                        lambda addr, pid: f"client:{addr}:{pid}")

    def fake_install(client, addr, n, pid):
        inits.append((client, addr, n, pid))
        state["up"] = True

    monkeypatch.setattr(mh, "_install_distributed_state", fake_install)
    monkeypatch.setattr(mh.jax, "process_index", lambda: 0)
    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
    monkeypatch.setattr(mh.jax, "local_devices", lambda: [object()])
    monkeypatch.setattr(mh.jax, "devices", lambda: [object(), object()])
    monkeypatch.setattr(mh, "_initialized_with", None)

    info = mh.initialize_multihost("localhost:1234", 2, 0, elastic=True)
    assert info["process_count"] == 2 and len(inits) == 1
    # Exact repeat: idempotent, no second bring-up.
    mh.initialize_multihost("localhost:1234", 2, 0, elastic=True)
    assert len(inits) == 1
    # Different params while initialized: hard error naming the remedy.
    with pytest.raises(RuntimeError, match="shutdown_multihost"):
        mh.initialize_multihost("localhost:1234", 1, 0, elastic=True)

    # Shutdown (abandon): resets the record without touching the
    # barrier-bearing paths; re-init at a DIFFERENT world size is legal.
    class FakeState:
        client = "c"
        coordinator_address = "a"

    fake = FakeState()
    monkeypatch.setattr(mh, "_global_state", lambda: fake)
    assert mh.shutdown_multihost(abandon=True) is True
    assert fake.client is None
    state["up"] = False
    mh.initialize_multihost("localhost:1234", 1, 0, elastic=True)
    assert len(inits) == 2 and inits[-1][2] == 1
    # Cleanup for other tests.
    monkeypatch.setattr(mh, "_initialized_with", None)


def test_shutdown_not_initialized_is_noop(monkeypatch):
    from megba_tpu.parallel import multihost as mh

    monkeypatch.setattr(mh, "_distributed_is_initialized", lambda: False)
    assert mh.shutdown_multihost() is False


class _FakeClient:
    def __init__(self, block=False):
        self.block = block
        self.started = threading.Event()
        self.calls = 0

    def shutdown(self):
        self.started.set()
        self.calls += 1
        if self.block:
            threading.Event().wait()  # never returns (dead-peer barrier)


def test_shutdown_graceful_bounded_when_peer_dead(monkeypatch):
    """The cooperative path must never block past timeout_s: a shutdown
    barrier wedged on a dead peer is abandoned (daemon thread, working
    only on CAPTURED refs — it can never clobber a later re-init's
    state) and the jax-level state force-reset, like abandon=True."""
    from megba_tpu.parallel import multihost as mh

    monkeypatch.setattr(mh, "_distributed_is_initialized", lambda: True)
    client = _FakeClient(block=True)

    class FakeState:
        coordinator_address = "a"

    fake = FakeState()
    fake.client = client
    monkeypatch.setattr(mh, "_global_state", lambda: fake)
    t0 = time.monotonic()
    assert mh.shutdown_multihost(timeout_s=0.2) is True
    assert time.monotonic() - t0 < 2.0
    assert client.started.is_set() and fake.client is None


def test_shutdown_graceful_fast_path(monkeypatch):
    """Cooperative teardown: the CAPTURED client's barrier runs (not
    jax.distributed.shutdown, whose eventual return would null whatever
    client is globally installed at that moment), then the jax-level
    refs are dropped by this call itself."""
    from megba_tpu.parallel import multihost as mh

    monkeypatch.setattr(mh, "_distributed_is_initialized", lambda: True)
    client = _FakeClient()
    fake = type("S", (), {"coordinator_address": "a"})()
    fake.client = client
    monkeypatch.setattr(mh, "_global_state", lambda: fake)
    assert mh.shutdown_multihost(timeout_s=5.0) is True
    assert client.calls == 1
    assert fake.client is None


def test_elastic_requires_explicit_rendezvous():
    from megba_tpu.parallel import multihost as mh

    with pytest.raises(ValueError, match="explicit"):
        mh.initialize_multihost(elastic=True)


# --------------------------------------------------- mesh local scope


def test_make_mesh_local_devices_only_scope():
    import jax

    from megba_tpu.parallel.mesh import (
        local_devices_only,
        local_only_active,
        make_mesh,
    )

    assert not local_only_active()
    with local_devices_only():
        assert local_only_active()
        with local_devices_only():  # re-entrant
            assert local_only_active()
        assert local_only_active()
        mesh = make_mesh(2)
        pi = jax.process_index()
        assert all(d.process_index == pi for d in mesh.devices.flat)
    assert not local_only_active()


# --------------------------------------------------- checkpoint schema v3


def test_snapshot_world_header_roundtrip(tmp_path):
    path = str(tmp_path / "snap.npz")
    save_state(path, np.ones((2, 2)), np.zeros((3,)), region=1.0,
               iteration=4, world_size=8, process_index=3)
    st = load_state(path)
    assert int(st["world_size"]) == 8
    assert int(st["process_index"]) == 3
    assert SCHEMA_VERSION == 3


def test_snapshot_world_mismatch_warns_not_fails(tmp_path):
    path = str(tmp_path / "snap.npz")
    save_state(path, np.ones((2, 2)), np.zeros((3,)), world_size=2)
    with pytest.warns(UserWarning, match="elastic shrink/grow"):
        st = load_state(path, expect_world_size=1)
    assert int(st["world_size"]) == 2  # loaded anyway: the sanctioned path
    # Matching world: silent.
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        load_state(path, expect_world_size=2)


def test_snapshot_v2_and_legacy_load_unchanged(tmp_path):
    from megba_tpu.utils import checkpoint as ckpt

    # A v2 snapshot (pre-world-header): loads silently even when the
    # caller states an expectation — there is nothing to compare.
    path = str(tmp_path / "v2.npz")
    payload = {"cameras": np.ones((2, 2)), "points": np.zeros((3,)),
               ckpt._SCHEMA_KEY: np.asarray(2)}
    payload[ckpt._CHECKSUM_KEY] = ckpt._digest(payload)
    np.savez(path, **payload)
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        st = load_state(path, expect_world_size=4)
    assert "world_size" not in st
    # Legacy checksum-free: best-effort pass-through, unchanged.
    legacy = str(tmp_path / "legacy.npz")
    np.savez(legacy, cameras=np.ones((2, 2)), points=np.zeros((3,)))
    st = load_state(legacy, expect_world_size=4)
    np.testing.assert_array_equal(st["cameras"], np.ones((2, 2)))


def test_snapshot_v3_corrupt_truncated_repinned(tmp_path):
    """The corruption contract survives the v3 header: truncation and
    checksum failure still refuse with the same clear errors."""
    path = str(tmp_path / "v3.npz")
    save_state(path, np.ones((4, 4)), np.zeros((5,)), region=2.0,
               iteration=1, world_size=2, process_index=0)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(ValueError, match="corrupt or truncated"):
        load_state(path)
    # Valid zip, tampered array: only the content checksum catches it.
    save_state(path, np.ones((4, 4)), np.zeros((5,)), world_size=2)
    with np.load(path) as z:
        st = {k: z[k] for k in z.files}
    st["world_size"] = np.asarray(7)  # tampered header, stale checksum
    np.savez(path, **st)
    with pytest.raises(ValueError, match="checksum"):
        load_state(path)


# --------------------------------------------------- world kill harness


def _stub_worker(body: str) -> list:
    return [sys.executable, "-c", textwrap.dedent(body)]


def test_world_harness_kills_rank_and_collects_survivors(tmp_path):
    from megba_tpu.robustness.harness import run_world_until_snapshot_then_kill

    snap = str(tmp_path / "snap.npz")
    rank0 = _stub_worker(f"""
        import time
        open({snap!r}, "w").write("x" * 64)
        time.sleep(0.8)   # "detect + resume", then exit on its own
        print("rank0 resumed")
    """)
    rank1 = _stub_worker("""
        import time
        time.sleep(300)   # parked in the "collective" until SIGKILLed
    """)
    outcome = run_world_until_snapshot_then_kill(
        [rank0, rank1], snap, kill_rank=1, timeout=30,
        survivor_timeout=30)
    assert outcome.kill_rank == 1
    assert outcome.returncodes[1] == -9  # SIGKILL, nothing graceful
    assert outcome.returncodes[0] == 0
    assert "rank0 resumed" in outcome.outputs[0]


def test_world_harness_flags_wedged_survivor(tmp_path):
    """A survivor that does NOT exit within the grace is the failure
    the harness exists to catch — named, with outputs, not a hang."""
    from megba_tpu.robustness.harness import run_world_until_snapshot_then_kill

    snap = str(tmp_path / "snap.npz")
    rank0 = _stub_worker(f"""
        import time
        open({snap!r}, "w").write("x" * 64)
        time.sleep(300)   # wedged: never exits
    """)
    rank1 = _stub_worker("import time; time.sleep(300)")
    with pytest.raises(TimeoutError, match="wedged past the watchdog"):
        run_world_until_snapshot_then_kill(
            [rank0, rank1], snap, kill_rank=1, timeout=30,
            survivor_timeout=1.0)


def test_world_harness_rejects_early_exit_before_snapshot(tmp_path):
    from megba_tpu.robustness.harness import run_world_until_snapshot_then_kill

    snap = str(tmp_path / "never.npz")
    rank0 = _stub_worker("print('crashed early'); raise SystemExit(3)")
    rank1 = _stub_worker("import time; time.sleep(300)")
    with pytest.raises(AssertionError, match="rank 0 exited"):
        run_world_until_snapshot_then_kill(
            [rank0, rank1], snap, kill_rank=1, timeout=30)


def test_world_harness_validates_kill_rank(tmp_path):
    from megba_tpu.robustness.harness import run_world_until_snapshot_then_kill

    with pytest.raises(ValueError, match="kill_rank"):
        run_world_until_snapshot_then_kill(
            [["true"]], str(tmp_path / "s.npz"), kill_rank=5)


# --------------------------------------------------- summarize elastic view


def _elastic_report_line(monitor_id, created, **counters):
    from megba_tpu.observability.report import SolveReport

    block = {"monitor": monitor_id, "rank": 0, "world": 2,
             "workers_lost": 0, "collective_timeouts": 0, "reshards": 0,
             "resumes": 0, "detection_s": []}
    block.update(counters)
    return SolveReport(
        problem={}, config={}, backend={}, phases={},
        result={"status_name": "converged"}, elastic=block,
        created_unix=created).to_json()


def test_aggregate_renders_elastic_counters(tmp_path):
    """Per-chunk elastic blocks are cumulative snapshots: the aggregate
    must keep the LAST per monitor and sum ACROSS monitors — and render
    the time-to-detection percentiles."""
    from megba_tpu.observability import summarize

    sink = tmp_path / "elastic.jsonl"
    lines = [
        # monitor A: two chunk snapshots, later one supersedes
        _elastic_report_line("aaa", 100.0, workers_lost=1,
                             detection_s=[1.5]),
        _elastic_report_line("aaa", 200.0, workers_lost=1, reshards=1,
                             resumes=1, detection_s=[1.5]),
        # monitor B: a straggler timeout on another rank
        _elastic_report_line("bbb", 150.0, collective_timeouts=2,
                             workers_lost=1, detection_s=[0.5]),
    ]
    sink.write_text("\n".join(lines) + "\n")
    out = summarize.aggregate_paths([str(sink)])
    assert ("elastic: 2 workers lost, 2 collective timeouts, 1 reshards, "
            "1 resumes (2 monitors)") in out
    assert "time-to-detection: p50 0.500s / max 1.500s over 2 losses" in out
    assert summarize.main(["--aggregate", str(sink)]) == 0


def test_report_without_elastic_block_renders_no_elastic_line(tmp_path):
    from megba_tpu.observability import summarize
    from megba_tpu.observability.report import SolveReport

    sink = tmp_path / "plain.jsonl"
    sink.write_text(SolveReport(
        problem={}, config={}, backend={}, phases={},
        result={"status_name": "converged"},
        created_unix=1.0).to_json() + "\n")
    out = summarize.aggregate_paths([str(sink)])
    assert "elastic:" not in out
