"""End-to-end LM convergence on synthetic scenes (SURVEY.md §4d)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.algo import lm_solve
from megba_tpu.common import (
    AlgoOption,
    ComputeKind,
    JacobianMode,
    ProblemOption,
    SolverOption,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn


def run_lm(compute_kind=ComputeKind.IMPLICIT, mode=JacobianMode.ANALYTICAL,
           seed=0, num_cameras=6, num_points=40, param_noise=5e-2,
           max_iter=25, pixel_noise=0.0):
    s = make_synthetic_bal(num_cameras=num_cameras, num_points=num_points,
                           obs_per_point=4, seed=seed, param_noise=param_noise,
                           pixel_noise=pixel_noise)
    option = ProblemOption(
        compute_kind=compute_kind,
        jacobian_mode=mode,
        algo_option=AlgoOption(max_iter=max_iter, initial_region=1e3,
                               epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=100, tol=1e-14, refuse_ratio=1e30),
    )
    f = make_residual_jacobian_fn(mode=mode)
    result = jax.jit(
        lambda cams, pts, obs, ci, pi, m: lm_solve(
            f, cams, pts, obs, ci, pi, m, option)
    )(
        jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(s.obs.T),
        jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx),
        jnp.ones(len(s.obs)),
    )
    return s, result


@pytest.mark.parametrize("compute_kind", [ComputeKind.IMPLICIT, ComputeKind.EXPLICIT])
def test_lm_converges_noiseless(compute_kind):
    # Perfect observations: LM must drive the cost to ~0.
    s, res = run_lm(compute_kind=compute_kind)
    assert float(res.initial_cost) > 1.0
    assert float(res.cost) < 1e-10 * float(res.initial_cost)
    assert int(res.accepted) > 0


def test_lm_autodiff_matches_analytical():
    _, res_a = run_lm(mode=JacobianMode.ANALYTICAL, pixel_noise=0.3)
    _, res_b = run_lm(mode=JacobianMode.AUTODIFF, pixel_noise=0.3)
    # Parameters are only determined up to the 7-dof BA gauge freedom, so
    # the comparable invariant is the final cost, not the raw parameters.
    np.testing.assert_allclose(float(res_a.cost), float(res_b.cost), rtol=1e-6)
    assert int(res_a.accepted) > 0 and int(res_b.accepted) > 0


def test_lm_cost_monotone_nonincreasing():
    # The accepted cost can never exceed the initial cost, and a noisy
    # problem still improves substantially.
    s, res = run_lm(pixel_noise=0.5, param_noise=3e-2)
    assert float(res.cost) < float(res.initial_cost) * 0.1


def test_lm_mixed_precision_converges():
    # Full LM with the bf16 (scale-then-cast) PCG must reach essentially
    # the same final cost as full precision: the inexact steps are
    # absorbed by the trust-region accept/reject.
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=0, param_noise=5e-2, pixel_noise=0.3)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)

    def solve(mixed):
        option = ProblemOption(
            mixed_precision_pcg=mixed,
            algo_option=AlgoOption(max_iter=30, epsilon1=1e-9, epsilon2=1e-12),
            solver_option=SolverOption(max_iter=100, tol=1e-14, refuse_ratio=1e30))
        return lm_solve(f, jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
                        jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx),
                        jnp.asarray(s.pt_idx), jnp.ones(len(s.obs)), option)

    full = solve(False)
    mixed = solve(True)
    assert float(mixed.cost) < float(mixed.initial_cost) * 1e-2
    np.testing.assert_allclose(float(mixed.cost), float(full.cost), rtol=5e-2)


def test_lm_respects_max_iter():
    _, res = run_lm(max_iter=3)
    assert int(res.iterations) <= 3


def test_lm_noop_at_optimum():
    # Starting AT the ground truth with zero noise: first step must hit
    # the epsilon2 convergence test (or g_inf) almost immediately and
    # change nothing.
    s = make_synthetic_bal(num_cameras=4, num_points=20, obs_per_point=3,
                           seed=3, param_noise=0.0, pixel_noise=0.0)
    option = ProblemOption()
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    res = lm_solve(
        f, jnp.asarray(s.cameras_gt.T), jnp.asarray(s.points_gt.T),
        jnp.asarray(s.obs.T), jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx),
        jnp.ones(len(s.obs)), option)
    assert float(res.cost) < 1e-18
    np.testing.assert_allclose(np.asarray(res.cameras).T, s.cameras_gt, atol=1e-9)
