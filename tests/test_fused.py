"""Fused edge-pipeline mega-kernels (megba_tpu/ops/fused.py).

Three layers of coverage:

- COMPILE-FREE units (tier-1): bucket-plan invariants, the option's
  identity-lane membership (fingerprint / static key), validate_options
  and flat_solve refusal arms BOTH ways, and the escalation rung-2
  strip — everything that must hold without tracing a program.
- KERNEL PARITY (slow): every fused kernel in Pallas interpret mode —
  the CPU-lane certificate — against the plain-XLA gather/contract/
  scatter oracle, f32/f64/bf16, explicit and implicit, 1-D bucket
  plans and the 2-D single-block ring step, plus the fused M⁻¹ apply.
  The bf16 arm additionally asserts the f32-accumulator contract at
  the kernel's OUTPUT dtype (the in-kernel trace assert in
  `_contract_rows` fires under interpret mode too).
- END-TO-END (slow): flat_solve fused-on vs fused-off LM cost parity
  at the pinned tolerance, including the newly-legal tiled+bf16 arm
  and the 2-D mesh composition.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megba_tpu.common import (
    AlgoOption,
    JacobianMode,
    ProblemOption,
    SolverOption,
    validate_options,
)
from megba_tpu.ops import fused
from megba_tpu.ops.fused import (
    FusedPlan,
    build_fused_dual_plans,
    build_fused_plan,
    device_fused_plan,
    fused_block_diag_apply,
    fused_coupling_apply,
    fused_coupling_apply_implicit,
    fused_plan_summary,
    fused_single_block_apply,
    permute_rows,
    reference_coupling_apply,
)


def _graph(ne=400, ni=40, no=90, seed=0, with_mask=True):
    rng = np.random.default_rng(seed)
    in_idx = rng.integers(0, ni, ne).astype(np.int32)
    out_idx = rng.integers(0, no, ne).astype(np.int32)
    mask = None
    if with_mask:
        mask = (rng.random(ne) > 0.1).astype(np.float32)
    return in_idx, out_idx, mask


def _check_plan_invariants(plan: FusedPlan, in_idx, out_idx, mask):
    real = plan.mask > 0
    n_real = int((mask > 0).sum()) if mask is not None else in_idx.shape[0]
    # Every unmasked source edge routed exactly once; padding zeroed.
    assert plan.n_edges == n_real
    assert int(real.sum()) == n_real
    src = np.nonzero(mask > 0)[0] if mask is not None else np.arange(
        in_idx.shape[0])
    assert np.array_equal(np.sort(plan.perm[real]), np.sort(src))
    assert plan.n_slots == plan.n_tiles * plan.tile
    # Per-slot locals match the source indices, block-local.
    slot_tile = np.repeat(np.arange(plan.n_tiles), plan.tile)
    assert np.array_equal(
        plan.in_local[real],
        (in_idx[plan.perm[real]] % plan.in_block).astype(np.int32))
    assert np.array_equal(
        plan.out_local[real],
        (out_idx[plan.perm[real]] % plan.out_block).astype(np.int32))
    # Every slot's GLOBAL segment lands in its tile's declared blocks.
    assert np.array_equal(
        in_idx[plan.perm[real]] // plan.in_block,
        plan.tile_in[slot_tile[real]])
    assert np.array_equal(
        out_idx[plan.perm[real]] // plan.out_block,
        plan.tile_out[slot_tile[real]])
    # Output-block visits are CONTIGUOUS runs (the sequential-
    # accumulation contract) with first-flags on every transition...
    changes = np.nonzero(plan.tile_out[1:] != plan.tile_out[:-1])[0]
    visited_runs = changes.size + 1
    assert visited_runs == np.unique(plan.tile_out).size
    want_first = np.zeros(plan.n_tiles, np.int32)
    want_first[0] = 1
    want_first[changes + 1] = 1
    assert np.array_equal(plan.tile_first, want_first)
    # ...and EVERY output block gets at least one (tail) tile, so the
    # kernel initialises the whole output buffer.
    assert np.array_equal(np.unique(plan.tile_out),
                          np.arange(plan.num_out_blocks))


# ---------------------------------------------------------------------------
# Tier-1: plan + option units (no kernel compilation)
# ---------------------------------------------------------------------------

def test_fused_plan_invariants():
    in_idx, out_idx, mask = _graph()
    plan = build_fused_plan(in_idx, out_idx, mask, 40, 90,
                            tile=16, in_block=16, out_block=32)
    _check_plan_invariants(plan, in_idx, out_idx, mask)
    assert 0.0 < plan.occupancy <= 1.0


def test_fused_plan_no_mask_and_edgeless_blocks():
    # Half the output blocks have no edges at all: they must still be
    # covered by all-padding tail tiles (zero-init, not garbage).
    in_idx, out_idx, _ = _graph(ne=64, ni=8, no=30, with_mask=False)
    out_idx = (out_idx % 7).astype(np.int32)  # blocks past 7 edgeless
    plan = build_fused_plan(in_idx, out_idx, None, 8, 30,
                            tile=8, in_block=8, out_block=4)
    _check_plan_invariants(plan, in_idx, out_idx, None)
    assert plan.num_out_blocks == 8


def test_fused_dual_plans_directions():
    cam_idx, pt_idx, mask = _graph(ne=300, ni=12, no=70, seed=3)
    fp_tp, fp_tc, dfp_tp, dfp_tc = build_fused_dual_plans(
        cam_idx, pt_idx, mask, 12, 70, tile=16, block_cam=8, block_pt=16)
    _check_plan_invariants(fp_tp, cam_idx, pt_idx, mask)
    _check_plan_invariants(fp_tc, pt_idx, cam_idx, mask)
    assert fp_tp.num_out_segments == 70 and fp_tc.num_out_segments == 12
    # Device halves are pytrees: flattenable, index arrays as leaves.
    leaves = jax.tree_util.tree_leaves(dfp_tp)
    assert len(leaves) == 7
    s = fused_plan_summary(fp_tp)
    assert set(s) == {"tiles", "tile", "occupancy", "edges", "slots"}
    assert s["edges"] == fp_tp.n_edges


def test_validate_options_refuses_fused_without_schur():
    opt = ProblemOption(use_schur=False, solver_option=SolverOption(
        fused_kernels=True))
    with pytest.raises(ValueError, match="fused_kernels"):
        validate_options(opt)
    validate_options(ProblemOption(solver_option=SolverOption(
        fused_kernels=True)))  # Schur path: legal


def test_fused_kernels_joins_option_fingerprint():
    # The serving fingerprint / bucket key is static_key(engine, option)
    # over the whole frozen option repr: toggling the flag MUST change
    # it (same-key artifacts would alias two different programs).
    from megba_tpu.analysis.retrace import static_key

    off = ProblemOption()
    on = dataclasses.replace(off, solver_option=dataclasses.replace(
        off.solver_option, fused_kernels=True))
    k_off, k_on = static_key(None, off), static_key(None, on)
    assert k_off != k_on
    assert "fused_kernels=True" in k_on
    assert "fused_kernels=False" in k_off


def test_rung2_strips_fused_kernels():
    from megba_tpu.serving.resilience import EscalationPolicy

    policy = EscalationPolicy()
    opt = ProblemOption(solver_option=SolverOption(fused_kernels=True))
    assert policy.option_for_rung(opt, 1).solver_option.fused_kernels \
        is True
    for rung in (2, 3):
        stripped = policy.option_for_rung(opt, rung)
        assert stripped.solver_option.fused_kernels is False


def _ba(nc=6, npts=40, dtype=np.float32):
    from megba_tpu.io.synthetic import make_synthetic_bal

    return make_synthetic_bal(
        num_cameras=nc, num_points=npts, obs_per_point=3, seed=0,
        param_noise=4e-2, pixel_noise=0.3, dtype=dtype)


def _solve(s, option, use_tiled=None, **kw):
    from megba_tpu.ops.residuals import make_residual_jacobian_fn
    from megba_tpu.solve import flat_solve

    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    return flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                      s.pt_idx, option, use_tiled=use_tiled, **kw)


def _opt(fused_kernels=False, bf16=False, **kw):
    return ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=4),
        solver_option=SolverOption(max_iter=12, tol=1e-8,
                                   fused_kernels=fused_kernels,
                                   bf16=bf16, **kw))


def test_flat_solve_refusal_arms():
    s = _ba()
    # fused + explicit non-tiled: refused typed, naming the knobs.
    with pytest.raises(ValueError, match="tiled edge plans"):
        _solve(s, _opt(fused_kernels=True), use_tiled=False)
    # fused + 1-D multi-device: refused typed, naming mesh_2d.
    opt_w2 = dataclasses.replace(_opt(fused_kernels=True), world_size=2)
    with pytest.raises(ValueError, match="mesh_2d=True"):
        _solve(s, opt_w2)
    # bf16 + explicit tiled WITHOUT fused: still refused — and the
    # error must name the fused alternative that makes it legal.
    with pytest.raises(ValueError, match="fused_kernels=True"):
        _solve(s, _opt(bf16=True), use_tiled=True)


# ---------------------------------------------------------------------------
# Kernel parity (interpret mode = the CPU-lane certificate)
# ---------------------------------------------------------------------------

def _implicit_reference(Jin, Jout, table, in_idx, out_idx, num_out, d_in):
    pe = jnp.take(table, in_idx, axis=1, mode="clip")
    od = Jin.shape[0] // d_in
    d_out = Jout.shape[0] // od
    u = jnp.stack([
        sum(Jin[o * d_in + a].astype(pe.dtype) * pe[a] for a in range(d_in))
        for o in range(od)])
    te = jnp.stack([
        sum(Jout[o * d_out + b].astype(u.dtype) * u[o] for o in range(od))
        for b in range(d_out)])
    out = jnp.zeros((d_out, num_out), te.dtype)
    return out.at[:, out_idx].add(te, mode="drop")


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("w_in_major", [True, False])
def test_fused_explicit_parity(dtype, w_in_major):
    rng = np.random.default_rng(1)
    in_idx, out_idx, mask = _graph(ne=500, ni=30, no=80, seed=1)
    d_in, d_out = (9, 3) if w_in_major else (3, 9)
    plan = build_fused_plan(in_idx, out_idx, mask, 30, 80,
                            tile=32, in_block=16, out_block=32)
    dplan = device_fused_plan(plan)
    W = jnp.asarray(rng.standard_normal((27, 500)), dtype) * jnp.asarray(
        mask, dtype)
    table = jnp.asarray(rng.standard_normal((d_in, 30)), dtype)
    got = fused_coupling_apply(permute_rows(W, dplan), table, dplan,
                               w_in_major=w_in_major, interpret=True)
    want = reference_coupling_apply(W, table, in_idx, out_idx, 80,
                                    w_in_major, d_in)
    tol = 1e-6 if dtype == np.float32 else 1e-12
    assert got.dtype == want.dtype == dtype
    err = float(jnp.max(jnp.abs(got - want))
                / (1.0 + jnp.max(jnp.abs(want))))
    assert err < tol


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_fused_implicit_parity(dtype):
    rng = np.random.default_rng(2)
    in_idx, out_idx, mask = _graph(ne=500, ni=30, no=80, seed=2)
    od, d_in, d_out = 2, 9, 3
    plan = build_fused_plan(in_idx, out_idx, mask, 30, 80,
                            tile=32, in_block=16, out_block=32)
    dplan = device_fused_plan(plan)
    m = jnp.asarray(mask, dtype)
    Jin = jnp.asarray(rng.standard_normal((od * d_in, 500)), dtype) * m
    Jout = jnp.asarray(rng.standard_normal((od * d_out, 500)), dtype) * m
    table = jnp.asarray(rng.standard_normal((d_in, 30)), dtype)
    got = fused_coupling_apply_implicit(
        permute_rows(Jin, dplan), permute_rows(Jout, dplan), table, dplan,
        interpret=True)
    want = _implicit_reference(Jin, Jout, table, in_idx, out_idx, 80, d_in)
    tol = 1e-5 if dtype == np.float32 else 1e-12
    err = float(jnp.max(jnp.abs(got - want))
                / (1.0 + jnp.max(jnp.abs(want))))
    assert err < tol


@pytest.mark.slow
def test_fused_bf16_accumulates_in_f32():
    # The precision-contract certificate: bf16 operand tiles, f32
    # accumulator — the kernel's OUTPUT dtype is the accumulator dtype
    # (the trace-time assert inside `_contract_rows` enforces the
    # in-kernel dtype; interpret mode runs the same trace).
    rng = np.random.default_rng(3)
    in_idx, out_idx, mask = _graph(ne=400, ni=20, no=60, seed=4)
    plan = build_fused_plan(in_idx, out_idx, mask, 20, 60,
                            tile=32, in_block=16, out_block=32)
    dplan = device_fused_plan(plan)
    W = jnp.asarray(rng.standard_normal((27, 400)), jnp.bfloat16)
    W = W * jnp.asarray(mask, jnp.bfloat16)
    table = jnp.asarray(rng.standard_normal((9, 20)), np.float32)
    got = fused_coupling_apply(permute_rows(W, dplan), table, dplan,
                               w_in_major=True, bf16_operands=True,
                               interpret=True)
    assert got.dtype == jnp.float32  # f32 accumulation, not bf16
    want = reference_coupling_apply(
        W.astype(np.float32), table, in_idx, out_idx, 60, True, 9)
    err = float(jnp.max(jnp.abs(got - want))
                / (1.0 + jnp.max(jnp.abs(want))))
    assert err < 3e-2  # bf16 operand rounding, f32 accumulation


@pytest.mark.slow
def test_fused_block_diag_parity():
    rng = np.random.default_rng(5)
    for dtype, tol in ((np.float32, 1e-6), (np.float64, 1e-13)):
        Minv = jnp.asarray(rng.standard_normal((17, 9, 9)), dtype)
        x = jnp.asarray(rng.standard_normal((9, 17)), dtype)
        Hrows = fused.block_diag_rows(Minv)
        got = fused_block_diag_apply(Hrows, x, interpret=True)
        want = jnp.einsum("cij,jc->ic", Minv, x)
        assert got.dtype == x.dtype
        err = float(jnp.max(jnp.abs(got - want))
                    / (1.0 + jnp.max(jnp.abs(want))))
        assert err < tol


@pytest.mark.slow
def test_fused_single_block_ring_step_parity():
    # The 2-D mesh ring-step contraction: one input block (the rotating
    # point shard), one output block (the camera tile).
    rng = np.random.default_rng(6)
    ne, n_in, n_out = 256, 16, 8
    in_local = jnp.asarray(rng.integers(0, n_in, ne), jnp.int32)
    out_local = jnp.asarray(rng.integers(0, n_out, ne), jnp.int32)
    W = jnp.asarray(rng.standard_normal((27, ne)), np.float32)
    table = jnp.asarray(rng.standard_normal((3, n_in)), np.float32)
    got = fused_single_block_apply(W, table, in_local, out_local,
                                   out_block=n_out, w_in_major=False,
                                   interpret=True)
    want = reference_coupling_apply(
        W, table, np.asarray(in_local), np.asarray(out_local), n_out,
        False, 3)
    err = float(jnp.max(jnp.abs(got - want))
                / (1.0 + jnp.max(jnp.abs(want))))
    assert err < 1e-6
    # Implicit two-stage arm.
    Jin = jnp.asarray(rng.standard_normal((2 * 3, ne)), np.float32)
    Jout = jnp.asarray(rng.standard_normal((2 * 9, ne)), np.float32)
    got = fused_single_block_apply(Jin, table, in_local, out_local,
                                   out_block=n_out, rows_out=Jout,
                                   interpret=True)
    want = _implicit_reference(Jin, Jout, table, np.asarray(in_local),
                               np.asarray(out_local), n_out, 3)
    err = float(jnp.max(jnp.abs(got - want))
                / (1.0 + jnp.max(jnp.abs(want))))
    assert err < 1e-5


# ---------------------------------------------------------------------------
# End-to-end LM parity (the acceptance pins)
# ---------------------------------------------------------------------------

def _rel_gap(a, b):
    return abs(float(a) - float(b)) / max(1.0, abs(float(b)))


@pytest.mark.slow
def test_flat_solve_fused_cost_parity_tiled():
    s = _ba()
    base = _solve(s, _opt(), use_tiled=True)
    fused_res = _solve(s, _opt(fused_kernels=True))
    assert _rel_gap(fused_res.cost, base.cost) < 1e-5
    assert fused_res.cost < base.initial_cost  # actually converged


@pytest.mark.slow
def test_flat_solve_fused_cost_parity_explicit_compute():
    # EXPLICIT W-contraction arm at the default short-LM config.  The
    # two arms reduce the same edge products in different orders, so
    # after a few accept/reject branch points the f32 trajectories sit
    # ~1e-5 apart — pure ordering noise, not kernel error (single-kernel
    # parity is pinned at 1e-6 above; the strict <=1e-5 end-to-end pin
    # rides the default IMPLICIT config in
    # test_flat_solve_fused_cost_parity_tiled and the run_tests.sh
    # venice smoke).  Longer LM runs only widen the branch
    # divergence, so the band here is 5e-5 at the short config.
    from megba_tpu.common import ComputeKind

    s = _ba()
    base = _solve(s, dataclasses.replace(
        _opt(), compute_kind=ComputeKind.EXPLICIT), use_tiled=True)
    fused_res = _solve(s, dataclasses.replace(
        _opt(fused_kernels=True), compute_kind=ComputeKind.EXPLICIT))
    assert _rel_gap(fused_res.cost, base.cost) < 5e-5


@pytest.mark.slow
def test_flat_solve_fused_lifts_bf16_tiled_refusal():
    # The satellite pin: tiled+bf16 is refused without fused_kernels
    # (asserted compile-free above) and LEGAL with it — and the result
    # must sit in the bf16 band of the XLA bf16 lowering, not at it
    # bit-for-bit (different operand orderings).
    s = _ba()
    fused_res = _solve(s, _opt(fused_kernels=True, bf16=True),
                       use_tiled=True)
    xla = _solve(s, _opt(bf16=True), use_tiled=False)
    assert fused_res.cost < fused_res.initial_cost
    # Both arms converge to the same decade (bf16 operand rounding).
    assert _rel_gap(fused_res.cost, xla.cost) < 0.5


@pytest.mark.slow
def test_flat_solve_fused_mesh2d_parity():
    if jax.device_count() < 4:
        pytest.skip("needs 4 devices (virtual CPU mesh)")
    s = _ba(nc=8, npts=48)
    opt = dataclasses.replace(
        _opt(), world_size=4,
        solver_option=dataclasses.replace(
            _opt().solver_option, mesh_2d=True, cam_blocks=2))
    base = _solve(s, opt, use_tiled=False)
    opt_f = dataclasses.replace(
        opt, solver_option=dataclasses.replace(
            opt.solver_option, fused_kernels=True))
    fused_res = _solve(s, opt_f, use_tiled=False)
    assert _rel_gap(fused_res.cost, base.cost) < 1e-5


@pytest.mark.slow
def test_fused_report_carries_tile_metrics(tmp_path, monkeypatch):
    # SolveReport.tiles: the reuse/occupancy metrics plus per-direction
    # fused plan summaries, rendered by summarize without error.
    import json as _json

    path = tmp_path / "t.jsonl"
    monkeypatch.setenv("MEGBA_TELEMETRY", str(path))
    s = _ba()
    _solve(s, _opt(fused_kernels=True))
    lines = path.read_text().strip().splitlines()
    doc = _json.loads(lines[-1])
    tiles = doc["tiles"]
    assert tiles["plan"] == "tiled_1d"
    assert "reuse_factor" in tiles and "occupancy" in tiles
    assert tiles["fused_to_pt"]["edges"] > 0
    assert tiles["fused_to_cam"]["slots"] >= tiles["fused_to_cam"]["edges"]
    from megba_tpu.observability.report import SolveReport
    from megba_tpu.observability.summarize import format_report

    text = format_report(SolveReport.from_json(lines[-1]))
    assert "tiles[tiled_1d]" in text
    assert "fused_to_pt" in text
