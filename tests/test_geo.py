"""Geo op unit tests: golden values + finite differences (SURVEY.md §4b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.ops import geo


def rng(seed=0):
    return np.random.default_rng(seed)


def test_angle_axis_rotate_matches_matrix():
    r = rng(1)
    for _ in range(10):
        w = jnp.asarray(r.normal(size=3))
        x = jnp.asarray(r.normal(size=3))
        R = geo.angle_axis_to_rotation_matrix(w)
        np.testing.assert_allclose(
            geo.angle_axis_rotate_point(w, x), R @ x, rtol=1e-12, atol=1e-12
        )


def test_rotation_matrix_orthonormal():
    r = rng(2)
    w = jnp.asarray(r.normal(size=3))
    R = geo.angle_axis_to_rotation_matrix(w)
    np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-12)
    np.testing.assert_allclose(np.linalg.det(R), 1.0, rtol=1e-12)


def test_small_angle_branch():
    x = jnp.asarray([1.0, 2.0, 3.0])
    for scale in [0.0, 1e-10, 1e-7]:
        w = jnp.asarray([scale, -scale, scale * 0.5])
        got = geo.angle_axis_rotate_point(w, x)
        expect = x + jnp.cross(w, x)
        np.testing.assert_allclose(got, expect, atol=1e-12)
        # And no NaNs in the gradient at exactly zero.
        J = jax.jacfwd(geo.angle_axis_rotate_point)(w, x)
        assert np.all(np.isfinite(J))


def test_rotate_known_90deg():
    # 90 degrees about z: x-axis -> y-axis.
    w = jnp.asarray([0.0, 0.0, np.pi / 2])
    x = jnp.asarray([1.0, 0.0, 0.0])
    np.testing.assert_allclose(
        geo.angle_axis_rotate_point(w, x), [0.0, 1.0, 0.0], atol=1e-12
    )


def test_rotation2d():
    th = jnp.asarray(0.3)
    R = geo.rotation2d_to_matrix(th)
    np.testing.assert_allclose(R @ R.T, np.eye(2), atol=1e-12)
    np.testing.assert_allclose(R[0, 0], np.cos(0.3))


def test_radial_distortion_zero_k():
    p = jnp.asarray([0.3, -0.2])
    out = geo.radial_distortion(p, jnp.asarray(500.0), jnp.asarray(0.0), jnp.asarray(0.0))
    np.testing.assert_allclose(out, 500.0 * p)


def test_quaternion_roundtrip():
    r = rng(3)
    for _ in range(20):
        w = jnp.asarray(r.normal(size=3))
        R = geo.angle_axis_to_rotation_matrix(w)
        q = geo.rotation_matrix_to_quaternion(R)
        R2 = geo.quaternion_to_rotation_matrix(q)
        np.testing.assert_allclose(R2, R, atol=1e-9)


def test_drotated_dangle_axis_vs_autodiff():
    r = rng(4)
    for scale in [1.0, 1e-3, 1e-9, 0.0]:
        w = jnp.asarray(r.normal(size=3) * scale)
        x = jnp.asarray(r.normal(size=3))
        got = geo.drotated_dangle_axis(w, x)
        expect = jax.jacfwd(geo.angle_axis_rotate_point)(w, x)
        np.testing.assert_allclose(got, expect, rtol=1e-8, atol=1e-10)


def test_drotated_finite_difference():
    r = rng(5)
    w = jnp.asarray(r.normal(size=3))
    x = jnp.asarray(r.normal(size=3))
    J = np.asarray(geo.drotated_dangle_axis(w, x))
    eps = 1e-6
    for i in range(3):
        dw = np.zeros(3)
        dw[i] = eps
        fd = (
            np.asarray(geo.angle_axis_rotate_point(w + dw, x))
            - np.asarray(geo.angle_axis_rotate_point(w - dw, x))
        ) / (2 * eps)
        np.testing.assert_allclose(J[:, i], fd, rtol=1e-6, atol=1e-8)
