"""Pallas Hessian kernel tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.common import JacobianMode
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.pallas_kernels import camera_hessian_gradient, camera_window_plan
from megba_tpu.ops.residuals import make_residual_jacobian_fn


def make_inputs(num_cameras=12, num_points=120, obs_per_point=6, seed=0):
    s = make_synthetic_bal(num_cameras=num_cameras, num_points=num_points,
                           obs_per_point=obs_per_point, seed=seed)
    cams = jnp.asarray(s.cameras0.T, jnp.float32)
    pts = jnp.asarray(s.points0.T, jnp.float32)
    cam_idx = jnp.asarray(s.cam_idx)
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    r, Jc, _ = f(cams[:, cam_idx], pts[:, jnp.asarray(s.pt_idx)],
                 jnp.asarray(s.obs.T, jnp.float32))
    return np.asarray(s.cam_idx), r, Jc, num_cameras


def reference_build(r, Jc, cam_idx, num_cameras):
    # Row-form reference: [cd*cd, Nc] and [cd, Nc] feature-major outputs.
    idx = jnp.asarray(cam_idx)
    od, cd = r.shape[0], Jc.shape[0] // r.shape[0]
    hpp_rows = jnp.stack([
        jax.ops.segment_sum(
            sum(Jc[o * cd + a] * Jc[o * cd + b] for o in range(od)),
            idx, num_segments=num_cameras)
        for a in range(cd) for b in range(cd)
    ])
    g_rows = jnp.stack([
        jax.ops.segment_sum(
            -sum(Jc[o * cd + a] * r[o] for o in range(od)),
            idx, num_segments=num_cameras)
        for a in range(cd)
    ])
    return hpp_rows, g_rows


def test_window_plan():
    cam_idx = np.repeat(np.arange(10), 100)  # degree 100, tile 512 spans ~7 cams
    ok, w = camera_window_plan(cam_idx, tile=512)
    assert ok and w == 16
    sparse = np.arange(100000, dtype=np.int32)  # degree 1: tile spans 512 cams
    ok, w = camera_window_plan(sparse, tile=512)
    assert not ok
    # The sliding check covers EVERY offset (shard boundaries), not just
    # tile multiples: degree exactly tile/16 at offset 0 is fine, but an
    # offset run crossing 17 cameras must bump the window.
    tricky = np.repeat(np.arange(40), 32)  # tile=512 spans 16 or 17 cams
    ok, w = camera_window_plan(tricky, tile=512)
    assert ok and w == 32


def test_pallas_rejects_float64():
    from megba_tpu.linear_system import build_schur_system
    import jax.numpy as jnp
    import pytest as _pytest

    r = jnp.zeros((2, 4), jnp.float64)
    Jc = jnp.zeros((18, 4), jnp.float64)
    Jp = jnp.zeros((6, 4), jnp.float64)
    idx = jnp.zeros(4, jnp.int32)
    with _pytest.raises(ValueError, match="float32"):
        build_schur_system(r, Jc, Jp, idx, idx, 2, 2, cam_sorted=True,
                           pallas_plan=(64, 16))
    with _pytest.raises(ValueError, match="cam_sorted"):
        build_schur_system(r.astype(jnp.float32), Jc.astype(jnp.float32),
                           Jp.astype(jnp.float32), idx, idx, 2, 2,
                           pallas_plan=(64, 16))


@pytest.mark.parametrize("tile", [64, 128])
def test_kernel_matches_segment_sum(tile):
    cam_idx, r, Jc, nc = make_inputs()
    ok, window = camera_window_plan(cam_idx, tile=tile)
    assert ok
    Hpp, g = camera_hessian_gradient(
        Jc, r, jnp.asarray(cam_idx), num_cameras=nc, tile=tile,
        window=window, interpret=True)
    Hpp_ref, g_ref = reference_build(r, Jc, cam_idx, nc)
    np.testing.assert_allclose(Hpp, Hpp_ref, rtol=2e-4, atol=1e-2)
    np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=1e-2)


def test_kernel_with_uneven_tail():
    # Edge count not a multiple of the tile: the kernel pads internally.
    cam_idx, r, Jc, nc = make_inputs(num_cameras=7, num_points=33, obs_per_point=5)
    assert len(cam_idx) % 64 != 0
    ok, window = camera_window_plan(cam_idx, tile=64)
    assert ok
    Hpp, g = camera_hessian_gradient(
        Jc, r, jnp.asarray(cam_idx), num_cameras=nc, tile=64,
        window=window, interpret=True)
    Hpp_ref, g_ref = reference_build(r, Jc, cam_idx, nc)
    np.testing.assert_allclose(Hpp, Hpp_ref, rtol=2e-4, atol=1e-2)
    np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=1e-2)


def test_lm_solve_with_pallas_plan_matches():
    # The full LM loop with the Pallas Hessian build (interpret mode)
    # must converge to the same cost as the XLA path.
    import jax.numpy as jnp
    from megba_tpu.algo import lm_solve
    from megba_tpu.common import AlgoOption, ProblemOption, SolverOption

    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=0, param_noise=4e-2, pixel_noise=0.3,
                           dtype=np.float32)
    option = ProblemOption(
        dtype=np.float32,
        algo_option=AlgoOption(max_iter=8, epsilon1=1e-9, epsilon2=1e-12),
        solver_option=SolverOption(max_iter=60, tol=1e-8, refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    ok, window = camera_window_plan(s.cam_idx, tile=64)
    assert ok
    args = (jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T), jnp.asarray(s.obs.T),
            jnp.asarray(s.cam_idx), jnp.asarray(s.pt_idx),
            jnp.ones(len(s.obs), jnp.float32))
    base = lm_solve(f, *args, option, cam_sorted=True)
    pall = lm_solve(f, *args, option, cam_sorted=True, pallas_plan=(64, window))
    np.testing.assert_allclose(float(pall.cost), float(base.cost), rtol=1e-4)


def test_kernel_last_camera_window_overhang():
    # Tiles near the end produce windows overhanging num_cameras; the
    # padded combine must not write out of bounds or lose mass.
    cam_idx, r, Jc, nc = make_inputs(num_cameras=5, num_points=40, obs_per_point=4)
    ok, window = camera_window_plan(cam_idx, tile=64)
    Hpp, g = camera_hessian_gradient(
        Jc, r, jnp.asarray(cam_idx), num_cameras=nc, tile=64,
        window=window, interpret=True)
    Hpp_ref, g_ref = reference_build(r, Jc, cam_idx, nc)
    np.testing.assert_allclose(Hpp, Hpp_ref, rtol=2e-4, atol=1e-2)
    np.testing.assert_allclose(g, g_ref, rtol=2e-4, atol=1e-2)
