"""Observability subsystem tests (megba_tpu/observability/).

Pins the contracts ISSUE 1 introduces: the on-device SolveTrace agrees
with the verbose-callback observables (single-device, sharded, and
checkpointed), SolveReport JSON round-trips, the telemetry sink is a
strict no-op when disabled, the summarize CLI renders recorded reports,
and the verbose-clock table evicts by last touch (not insertion order).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from megba_tpu.common import (
    AlgoOption,
    JacobianMode,
    ProblemOption,
    SolverOption,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solve import flat_solve
from megba_tpu.utils.curves import run_with_curve

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup(seed=0, max_iter=6):
    s = make_synthetic_bal(num_cameras=6, num_points=40, obs_per_point=4,
                           seed=seed, param_noise=4e-2, pixel_noise=0.3)
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=max_iter, epsilon1=1e-9,
                               epsilon2=1e-12),
        solver_option=SolverOption(max_iter=40, tol=1e-12,
                                   refuse_ratio=1e30))
    f = make_residual_jacobian_fn(mode=JacobianMode.ANALYTICAL)
    return s, option, f


def _solve(s, option, f, verbose=False):
    return flat_solve(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                      s.pt_idx, option, verbose=verbose)


def _assert_trace_matches_curve(res, curve):
    k = int(res.iterations)
    assert len(curve) == k
    cost = np.asarray(res.trace.cost)
    accept = np.asarray(res.trace.accept)
    pcg = np.asarray(res.trace.pcg_iters)
    assert cost.shape[0] >= k  # fixed-size buffer, masked by k
    for entry in curve:
        i = entry["iter"]
        # The verbose line prints %.6e — compare at that precision.
        np.testing.assert_allclose(cost[i], entry["cost"], rtol=2e-6)
        assert bool(accept[i]) == entry["accept"]
        assert int(pcg[i]) == entry["pcg_iters"]


def test_trace_matches_verbose_single_device():
    s, option, f = _setup()
    res, curve = run_with_curve(lambda: _solve(s, option, f, verbose=True))
    assert int(res.iterations) > 0
    _assert_trace_matches_curve(res, curve)


@pytest.mark.slow
def test_trace_matches_verbose_world2():
    # Same contract through shard_map on a 2-device CPU mesh: every
    # recorded value is replicated, so the trace rides out_specs=P().
    # slow: compiles a dedicated sharded verbose program; the fast lane
    # covers sharded solves via test_sharding and trace parity via the
    # single-device test above.
    s, option, f = _setup(seed=1)
    import dataclasses

    option2 = dataclasses.replace(option, world_size=2)
    res, curve = run_with_curve(lambda: _solve(s, option2, f, verbose=True))
    assert int(res.iterations) > 0
    _assert_trace_matches_curve(res, curve)


def test_trace_checkpointed_matches_straight_run(tmp_path):
    from megba_tpu.algo import solve_checkpointed

    s, option, f = _setup(seed=2, max_iter=9)
    straight = _solve(s, option, f)
    chunked = solve_checkpointed(
        f, s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option,
        checkpoint_path=str(tmp_path / "ck.npz"), checkpoint_every=3)
    k = int(chunked.iterations)
    assert k == int(straight.iterations)
    # Chunks stitched back together must reproduce the straight-run
    # trajectory (trust-region state carries exactly across chunks).
    np.testing.assert_allclose(
        np.asarray(chunked.trace.cost)[:k],
        np.asarray(straight.trace.cost)[:k], rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(chunked.trace.accept)[:k],
        np.asarray(straight.trace.accept)[:k])


@pytest.mark.slow
def test_trace_survives_checkpoint_resume(tmp_path):
    # slow: compiles two extra chunk-length program variants on top of
    # the chunked-stitching test above.
    from megba_tpu.algo import solve_checkpointed

    s, option, f = _setup(seed=3, max_iter=8)
    ck = str(tmp_path / "ck.npz")
    import dataclasses

    short = dataclasses.replace(
        option, algo_option=dataclasses.replace(option.algo_option,
                                                max_iter=4))
    solve_checkpointed(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                       s.pt_idx, short, checkpoint_path=ck,
                       checkpoint_every=2)
    resumed = solve_checkpointed(f, s.cameras0, s.points0, s.obs,
                                 s.cam_idx, s.pt_idx, option,
                                 checkpoint_path=ck, checkpoint_every=2)
    k = int(resumed.iterations)
    # The resumed result's trace covers the WHOLE solve, including the
    # iterations that ran before the (simulated) preemption.
    assert np.asarray(resumed.trace.cost).shape[0] == k
    straight = _solve(s, option, f)
    np.testing.assert_allclose(
        np.asarray(resumed.trace.cost)[:k],
        np.asarray(straight.trace.cost)[:k], rtol=1e-6)


def test_trace_aligned_after_pretrace_snapshot_resume(tmp_path):
    # A snapshot written BEFORE traces existed has no extra_trace_* keys;
    # resume must pad the unknowable pre-resume iterations with inert NaN
    # history so the [:iterations] masking contract still holds.
    from megba_tpu.algo import solve_checkpointed
    from megba_tpu.utils.checkpoint import load_state, save_state

    s, option, f = _setup(seed=7, max_iter=8)
    ck = str(tmp_path / "ck.npz")
    import dataclasses

    short = dataclasses.replace(
        option, algo_option=dataclasses.replace(option.algo_option,
                                                max_iter=4))
    solve_checkpointed(f, s.cameras0, s.points0, s.obs, s.cam_idx,
                       s.pt_idx, short, checkpoint_path=ck,
                       checkpoint_every=4)
    # Rewrite the snapshot as a pre-trace version would have.
    st = load_state(ck)
    save_state(ck, st["cameras"], st["points"], region=float(st["region"]),
               cost=float(st["cost"]), iteration=int(st["iteration"]),
               extra={k[len("extra_"):]: v for k, v in st.items()
                      if k.startswith("extra_")
                      and not k.startswith("extra_trace_")})
    resumed = solve_checkpointed(f, s.cameras0, s.points0, s.obs,
                                 s.cam_idx, s.pt_idx, option,
                                 checkpoint_path=ck, checkpoint_every=4)
    k = int(resumed.iterations)
    cost = np.asarray(resumed.trace.cost)
    assert cost.shape[0] == k  # aligned, not short
    assert np.all(np.isnan(cost[:4]))  # pre-resume filler
    assert np.all(np.isfinite(cost[4:k]))  # post-resume history is real
    assert np.asarray(resumed.trace.accept).dtype == np.bool_
    assert np.asarray(resumed.trace.pcg_iters).dtype == np.int32


def test_pgo_telemetry_knob_is_inert(tmp_path, monkeypatch):
    # The PGO family emits no reports yet; the host-only knob must
    # neither crash nor fragment _pgo_program's lru cache (the stripped
    # option is what reaches the cached program builder).
    monkeypatch.delenv("MEGBA_TELEMETRY", raising=False)
    from megba_tpu.models.pgo import (
        _pgo_program,
        make_synthetic_pose_graph,
        solve_pgo,
    )

    g = make_synthetic_pose_graph(num_poses=8, loop_closures=2, seed=0)
    option = ProblemOption(
        algo_option=AlgoOption(max_iter=3),
        solver_option=SolverOption(max_iter=10))
    import dataclasses

    res_plain = solve_pgo(g.poses0, g.edge_i, g.edge_j, g.meas, option)
    misses0 = _pgo_program.cache_info().misses
    res_knob = solve_pgo(
        g.poses0, g.edge_i, g.edge_j, g.meas,
        dataclasses.replace(option, telemetry=str(tmp_path / "x.jsonl")))
    assert _pgo_program.cache_info().misses == misses0  # no recompile
    np.testing.assert_allclose(float(res_knob.cost), float(res_plain.cost),
                               rtol=1e-12)
    assert not (tmp_path / "x.jsonl").exists()


def test_trace_adds_no_host_callbacks():
    # Acceptance guard: verbose-off programs must stay callback-free —
    # the trace is pure on-device ops, no debug.callback smuggled in.
    from megba_tpu.solve import _build_single_solve

    s, option, f = _setup()
    from megba_tpu.core.fm import EDGE_QUANTUM
    from megba_tpu.core.types import pad_edges

    obs, ci, pi, mask = pad_edges(s.obs, s.cam_idx, s.pt_idx, EDGE_QUANTUM,
                                  dtype=np.float64)
    jitted = _build_single_solve(f, option, (), False, True)
    txt = jitted.lower(
        jnp.asarray(s.cameras0.T), jnp.asarray(s.points0.T),
        jnp.asarray(np.ascontiguousarray(obs.T)), jnp.asarray(ci),
        jnp.asarray(pi), jnp.asarray(mask), jnp.asarray(1e3, jnp.float64),
        jnp.asarray(2.0, jnp.float64), jnp.asarray(1, jnp.int32),
        None).as_text()
    assert "callback" not in txt.lower()


def test_report_json_roundtrip():
    from megba_tpu.observability.report import SolveReport

    rep = SolveReport(
        problem={"num_cameras": 6, "num_points": 40, "num_edges": 160},
        config={"dtype": "float64", "world_size": 1},
        backend={"backend": "cpu", "device_count": 8},
        phases={"dispatch": {"total_s": 1.25, "calls": 1}},
        result={"initial_cost": 10.0, "final_cost": 1.0, "iterations": 3},
        trace={"cost": [5.0, 2.0, 1.0], "accept": [True, True, True]},
        memory=None,
        created_unix=123.5,
    )
    rep2 = SolveReport.from_json(rep.to_json())
    assert rep2 == rep
    # JSONL framing: one line, valid JSON.
    assert "\n" not in rep.to_json()
    assert json.loads(rep.to_json())["schema"] == rep.schema


def test_config_to_dict_serializes_options():
    from megba_tpu.observability.report import config_to_dict

    cfg = config_to_dict(ProblemOption())
    assert cfg["dtype"] == "float64"
    assert cfg["compute_kind"] == "IMPLICIT"
    assert cfg["jacobian_mode"] == "AUTODIFF"
    assert cfg["robust_kind"] == "NONE"
    assert cfg["solver_option"]["max_iter"] == 100
    assert cfg["algo_option"]["initial_region"] == 1e3
    json.dumps(cfg)  # must be plain JSON types all the way down


def test_telemetry_emits_report_matching_trace(tmp_path, monkeypatch):
    sink = tmp_path / "telemetry.jsonl"
    monkeypatch.setenv("MEGBA_TELEMETRY", str(sink))
    s, option, f = _setup(seed=4)
    res = _solve(s, option, f)
    assert sink.exists()
    lines = [ln for ln in sink.read_text().splitlines() if ln.strip()]
    assert len(lines) == 1
    from megba_tpu.observability.report import SolveReport

    rep = SolveReport.from_json(lines[0])
    k = int(res.iterations)
    assert rep.result["iterations"] == k
    np.testing.assert_allclose(
        rep.trace["cost"], np.asarray(res.trace.cost)[:k], rtol=1e-12)
    assert rep.trace["accept"] == [
        bool(a) for a in np.asarray(res.trace.accept)[:k]]
    assert rep.problem["num_cameras"] == 6
    assert rep.config["dtype"] == "float64"
    # The wired flat_solve phases are all present.
    assert "dispatch" in rep.phases and "execute" in rep.phases
    assert rep.phases["dispatch"]["total_s"] > 0


def test_telemetry_knob_on_problem_option(tmp_path, monkeypatch):
    monkeypatch.delenv("MEGBA_TELEMETRY", raising=False)
    sink = tmp_path / "knob.jsonl"
    s, option, f = _setup(seed=5)
    import dataclasses

    res = _solve(s, dataclasses.replace(option, telemetry=str(sink)), f)
    assert sink.exists() and int(res.iterations) > 0


def test_summarize_cli_renders_report(tmp_path, monkeypatch, capsys):
    sink = tmp_path / "telemetry.jsonl"
    monkeypatch.setenv("MEGBA_TELEMETRY", str(sink))
    s, option, f = _setup(seed=6)
    _solve(s, option, f)
    from megba_tpu.observability import summarize

    assert summarize.main([str(sink)]) == 0
    out = capsys.readouterr().out
    assert "1 report(s)" in out
    assert "iter  cost" in out  # convergence table
    assert "phases:" in out and "dispatch" in out
    assert "result: cost" in out


@pytest.mark.slow
def test_telemetry_off_is_strict_noop(tmp_path):
    # slow: cold-interpreter subprocess (full jax import + compile).
    # Subprocess: a fresh interpreter proves the sink module is never
    # imported (and no file is written) on the telemetry-off path —
    # in-process the other tests here would have imported it already.
    code = """
import os, sys
import numpy as np
from megba_tpu.common import AlgoOption, JacobianMode, ProblemOption, SolverOption
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.ops.residuals import make_residual_jacobian_fn
from megba_tpu.solve import flat_solve
s = make_synthetic_bal(num_cameras=4, num_points=20, obs_per_point=3,
                       seed=0, dtype=np.float32)
option = ProblemOption(dtype=np.float32,
                       algo_option=AlgoOption(max_iter=2),
                       solver_option=SolverOption(max_iter=5))
res = flat_solve(make_residual_jacobian_fn(mode=JacobianMode.AUTODIFF),
                 s.cameras0, s.points0, s.obs, s.cam_idx, s.pt_idx, option)
assert res.trace is not None
assert "megba_tpu.observability.report" not in sys.modules, "sink imported"
assert "megba_tpu.observability.summarize" not in sys.modules, "CLI imported"
assert "megba_tpu.observability.metrics" not in sys.modules, "metrics imported"
assert "megba_tpu.observability.spans" not in sys.modules, "spans imported"
assert "megba_tpu.observability.flight" not in sys.modules, "flight imported"
print("NOOP_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("MEGBA_TELEMETRY", None)
    for knob in ("MEGBA_METRICS", "MEGBA_TRACE", "MEGBA_FLIGHT"):
        env.pop(knob, None)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env,
                          cwd=str(tmp_path), timeout=600)
    assert proc.returncode == 0, proc.stderr
    assert "NOOP_OK" in proc.stdout
    assert list(tmp_path.glob("*.jsonl")) == []  # nothing written


def test_verbose_clock_evicts_by_last_touch(capsys):
    # Regression (ISSUE 1 satellite): a long-running solve that keeps
    # emitting lines must never lose its clock to a burst of >64 new
    # solves.  The old oldest-INSERTED eviction dropped exactly the
    # longest-lived (first-inserted) clock; last-touch keeps it.
    from megba_tpu.observability import emit

    saved = dict(emit._VERBOSE_CLOCKS)
    try:
        emit._VERBOSE_CLOCKS.clear()
        emit._emit_verbose_line(1, 0, 1.0, True, 3)  # long solve starts
        t0 = emit._VERBOSE_CLOCKS[1][0]
        for i in range(2 * emit._MAX_CLOCKS):
            emit._emit_verbose_line(1000 + i, 0, 1.0, True, 1)  # burst
            emit._emit_verbose_line(1, i + 1, 0.5, True, 1)  # still live
        assert 1 in emit._VERBOSE_CLOCKS, "live solve's clock evicted"
        assert emit._VERBOSE_CLOCKS[1][0] == t0, "clock restarted"
        assert len(emit._VERBOSE_CLOCKS) <= emit._MAX_CLOCKS + 1
    finally:
        emit._VERBOSE_CLOCKS.clear()
        emit._VERBOSE_CLOCKS.update(saved)
        capsys.readouterr()


def test_emit_problem_stats_format(capsys):
    from megba_tpu.observability.emit import emit_problem_stats

    emit_problem_stats(49, 7776, 31843, 12, 9, 1234)
    out = capsys.readouterr().out
    assert "problem: 49 cameras, 7776 points, 31843 observations" in out
    assert "Hpl blocks 1234" in out
    emit_problem_stats(1, 2, 3, 4, 5, -1)
    assert "n/a (edges unsorted)" in capsys.readouterr().out


def test_trace_to_dict_masks_tail():
    from megba_tpu.observability.trace import SolveTrace, trace_to_dict

    tr = SolveTrace.empty(5, jnp.float64)
    tr = tr.record(0, cost=2.0, grad_inf_norm=1.0, trust_region=1e3,
                   rho=0.5, accept=True, pcg_iters=7)
    tr = tr.record(1, cost=1.0, grad_inf_norm=0.5, trust_region=3e3,
                   rho=0.9, accept=False, pcg_iters=3)
    d = trace_to_dict(tr, 2)
    assert d["cost"] == [2.0, 1.0]
    assert d["accept"] == [True, False]
    assert d["pcg_iters"] == [7, 3]
    assert all(len(v) == 2 for v in d.values())
    json.dumps(d)  # plain Python scalars only
