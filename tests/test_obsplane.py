"""Observability-plane tests: metrics registry, spans, flight recorder.

Zero-compile by design (the test_serving.py tier-1 contract): the
registry / span / flight primitives are pure host objects, and the
router round-trips run through in-process stub workers — no
subprocesses, no jitted programs.  The end-to-end plane (2 real
workers, SIGKILL, merged Perfetto trace + Prometheus snapshot + flight
dump) rides the run_tests.sh federation smoke.
"""

import json
import threading

import numpy as np
import pytest

from megba_tpu import observability as obs
from megba_tpu.common import (
    AlgoOption,
    ProblemOption,
    SolverOption,
    SolveStatus,
)
from megba_tpu.io.synthetic import make_synthetic_bal
from megba_tpu.serving import (
    BucketLadder,
    FleetProblem,
    FleetResult,
    FleetRouter,
    FleetStats,
    classify,
)
from megba_tpu.serving.federation import WorkerLostError

OPT64 = ProblemOption(dtype=np.float64,
                      algo_option=AlgoOption(max_iter=6),
                      solver_option=SolverOption(max_iter=12, tol=1e-10))
LADDER = BucketLadder()


def _mk(seed, n_pt, n_cam=4):
    s = make_synthetic_bal(num_cameras=n_cam, num_points=n_pt,
                           obs_per_point=3, seed=seed, param_noise=2e-2,
                           pixel_noise=0.3, dtype=np.float64)
    return FleetProblem.from_synthetic(s, name=f"s{seed}_p{n_pt}")


def _stub_result(p) -> FleetResult:
    sc = classify(*p.dims(), OPT64.dtype, LADDER)
    return FleetResult(
        name=p.name, shape=sc, lane=0, lanes=1,
        cameras=np.asarray(p.cameras).copy(),
        points=np.asarray(p.points).copy(),
        cost=np.float64(1.0), initial_cost=np.float64(2.0),
        iterations=1, accepted=1, pcg_iterations=1,
        status=int(SolveStatus.CONVERGED), recoveries=0, latency_s=0.0)


class StubWorker:
    """In-process worker stand-in that speaks the observability ops:
    adopts the solve frame's trace context into its own SpanRecorder
    (shipping the spans back in the reply, like a real worker process)
    and answers the `metrics` op with a canned registry snapshot."""

    def __init__(self, worker_id, warm=(), behavior=None,
                 metrics_snapshot=None):
        self.worker_id = worker_id
        self.warm = set(warm)
        self.alive = True
        self.pid = 0
        self.behavior = behavior
        self.metrics_snapshot = metrics_snapshot
        self.batches = []

    def request(self, msg, timeout_s=None):
        op = msg.get("op")
        if op == "shutdown":
            return {"ok": True}
        if op == "metrics":
            return {"ok": True, "metrics": self.metrics_snapshot}
        problems = msg["problems"]
        self.batches.append([p.name for p in problems])
        if self.behavior is not None:
            return self.behavior(self, problems)
        from megba_tpu.observability import spans as spans_mod

        rec = spans_mod.SpanRecorder(process_name=self.worker_id)
        with rec.adopt("worker_solve", msg.get("trace"),
                       worker=self.worker_id):
            results = [_stub_result(p) for p in problems]
        return {"ok": True, "results": results,
                "warm": sorted(self.warm), "spans": rec.drain()}

    def terminate(self):
        self.alive = False


@pytest.fixture
def armed(monkeypatch, tmp_path):
    """Arm all three plane knobs with fresh process defaults; disarm
    and reset after, so no other in-process test observes the plane."""
    from megba_tpu.observability import flight, metrics, spans

    flight_path = tmp_path / "flight.jsonl"
    monkeypatch.setenv("MEGBA_METRICS", "1")
    monkeypatch.setenv("MEGBA_TRACE", "1")
    monkeypatch.setenv("MEGBA_FLIGHT", str(flight_path))
    metrics.reset_default_registry()
    spans.reset_default_recorder()
    flight.reset_default_recorder()
    yield flight_path
    metrics.reset_default_registry()
    spans.reset_default_recorder()
    flight.reset_default_recorder()


# ------------------------------------------------------------- gates


def test_gates_closed_by_default(monkeypatch):
    for knob in ("MEGBA_METRICS", "MEGBA_TRACE", "MEGBA_FLIGHT"):
        monkeypatch.delenv(knob, raising=False)
    assert obs.metrics_registry() is None
    assert obs.span_recorder() is None
    assert obs.flight_recorder() is None
    # the explicit per-solve knob opens the metrics gate without env
    assert obs.metrics_registry(enabled=True) is not None


# ---------------------------------------------------------- registry


def test_registry_thread_safety_under_concurrent_increments():
    from megba_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    n_threads, n_each = 8, 500
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(n_each):
            reg.counter("megba_test_total", "t").inc(bucket=f"b{tid % 2}")
            reg.gauge("megba_test_depth", "t").max(i, bucket="b0")
            reg.histogram("megba_test_lat", "t").observe(
                0.001 * (i % 7 + 1), bucket="b0")

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    snap = reg.snapshot()
    counters = snap["metrics"]["megba_test_total"]["series"]
    assert sum(counters.values()) == n_threads * n_each
    assert counters["bucket=b0"] == counters["bucket=b1"]
    hist = snap["metrics"]["megba_test_lat"]["series"]["bucket=b0"]
    assert hist["count"] == n_threads * n_each
    assert sum(hist["buckets"]) == hist["count"]  # nothing above 60s
    assert snap["metrics"]["megba_test_depth"]["series"]["bucket=b0"] == (
        n_each - 1)


def test_prometheus_exposition_golden():
    from megba_tpu.observability.metrics import (
        MetricsRegistry, render_prometheus)

    reg = MetricsRegistry()
    reg.counter("megba_solves_total", "Solves by status").inc(
        3, status="converged", bucket="B1")
    reg.counter("megba_solves_total", "Solves by status").inc(
        1, status="max_iter", bucket="B1")
    reg.gauge("megba_queue_depth", "Queue depth").set(7)
    h = reg.histogram("megba_latency_seconds", "Latency",
                      buckets=(0.1, 1.0))
    h.observe(0.05, bucket="B1")
    h.observe(0.5, bucket="B1")
    h.observe(5.0, bucket="B1")

    golden = (
        "# HELP megba_latency_seconds Latency\n"
        "# TYPE megba_latency_seconds histogram\n"
        'megba_latency_seconds_bucket{bucket="B1",le="0.1"} 1\n'
        'megba_latency_seconds_bucket{bucket="B1",le="1"} 2\n'
        'megba_latency_seconds_bucket{bucket="B1",le="+Inf"} 3\n'
        'megba_latency_seconds_sum{bucket="B1"} 5.55\n'
        'megba_latency_seconds_count{bucket="B1"} 3\n'
        "# HELP megba_queue_depth Queue depth\n"
        "# TYPE megba_queue_depth gauge\n"
        "megba_queue_depth 7\n"
        "# HELP megba_solves_total Solves by status\n"
        "# TYPE megba_solves_total counter\n"
        'megba_solves_total{bucket="B1",status="converged"} 3\n'
        'megba_solves_total{bucket="B1",status="max_iter"} 1\n'
    )
    assert render_prometheus(reg.snapshot()) == golden


def test_merge_snapshots_sums_and_is_bitwise_deterministic():
    from megba_tpu.observability.metrics import (
        MetricsRegistry, merge_snapshots, snapshot_to_json)

    def make(n):
        reg = MetricsRegistry()
        reg.counter("megba_x_total", "x").inc(n, bucket="B1")
        reg.gauge("megba_depth", "d").set(n)
        reg.histogram("megba_lat", "l").observe(0.01 * n, bucket="B1")
        return reg.snapshot()

    a, b = make(2), make(5)
    merged = merge_snapshots([a, b])
    assert merged["metrics"]["megba_x_total"]["series"]["bucket=B1"] == 7
    assert merged["metrics"]["megba_depth"]["series"][""] == 7
    assert merged["metrics"]["megba_lat"]["series"]["bucket=B1"][
        "count"] == 2
    # bitwise: merge order of equal inputs does not matter, and the
    # canonical JSON encoding is stable across repeated merges
    assert snapshot_to_json(merge_snapshots([a, b])) == snapshot_to_json(
        merge_snapshots([a, b]))
    assert (merge_snapshots([a, b])["metrics"]
            == merge_snapshots([b, a])["metrics"])


def test_merge_rejects_bucket_boundary_skew():
    from megba_tpu.observability.metrics import (
        MetricsRegistry, merge_snapshots)

    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.histogram("megba_lat", "l", buckets=(0.1, 1.0)).observe(0.5)
    r2.histogram("megba_lat", "l", buckets=(0.2, 2.0)).observe(0.5)
    with pytest.raises(ValueError, match="bucket mismatch"):
        merge_snapshots([r1.snapshot(), r2.snapshot()])


def test_fleet_stats_mirror_into_registry(armed):
    from megba_tpu.observability import metrics as metrics_mod

    stats = FleetStats()
    stats.record_shed(2)
    stats.record_retry(rung=1)
    stats.record_wait("B1", 0.02)
    snap = metrics_mod.default_registry().snapshot()
    m = snap["metrics"]
    assert m["megba_queue_shed_total"]["series"][""] == 2
    assert m["megba_queue_retries_total"]["series"]["rung=1"] == 1
    assert m["megba_queue_wait_seconds"]["series"]["bucket=B1"][
        "count"] == 1


# ------------------------------------------------------------- spans


def test_span_context_propagates_router_to_worker(armed):
    probs = [_mk(0, 16), _mk(1, 16)]
    w0 = StubWorker("w0")
    with FleetRouter(OPT64, workers=[w0], max_batch=8) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        [f.result(timeout=5) for f in futs]

    recorder = obs.span_recorder()
    assert recorder is not None
    spans = recorder.spans()
    dispatches = [s for s in spans if s["name"] == "fed_dispatch"]
    workers = [s for s in spans if s["name"] == "worker_solve"]
    assert dispatches and workers
    by_id = {s["span_id"]: s for s in spans}
    for ws in workers:
        parent = by_id[ws["parent_id"]]  # grafted under the dispatch
        assert parent["name"] == "fed_dispatch"
        assert ws["trace_id"] == parent["trace_id"]
        assert ws["process"] == "w0"


def test_chrome_trace_export_schema(armed):
    from megba_tpu.observability import spans as spans_mod

    rec = obs.span_recorder()
    with rec.span("request", bucket="B1"):
        with rec.span("solve_bucket"):
            rec.record_phase("dispatch", 0.01)
    doc = spans_mod.to_chrome_trace(rec.spans())
    assert doc["schema"] == spans_mod.SCHEMA
    events = doc["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    complete = [e for e in events if e["ph"] == "X"]
    assert meta and meta[0]["name"] == "process_name"
    assert {e["name"] for e in complete} == {
        "request", "solve_bucket", "phase.dispatch"}
    for e in complete:
        assert e["dur"] >= 0 and isinstance(e["pid"], int)
        assert 0 <= e["tid"] < (1 << 31)
        assert e["args"]["trace_id"]
    # the export is valid JSON end-to-end (the Perfetto load surface)
    json.loads(json.dumps(doc))


# ------------------------------------------------------------ flight


def test_flight_dump_rides_worker_loss(armed):
    flight_path = armed

    def die(worker, problems):
        raise WorkerLostError(worker.worker_id, "stub sigkill")

    probs = [_mk(0, 16)]
    with FleetRouter(OPT64, workers=[StubWorker("w0", behavior=die)],
                     max_batch=8, max_reroutes=0) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        with pytest.raises(WorkerLostError):
            futs[0].result(timeout=5)

    from megba_tpu.observability import flight as flight_mod

    dumps = flight_mod.load_dumps(str(flight_path))
    assert dumps, "worker loss did not dump the flight ring"
    assert dumps[-1]["reason"].startswith("worker_lost")
    kinds = [e["kind"] for e in dumps[-1]["events"]]
    assert "worker_lost" in kinds
    lost = [e for e in dumps[-1]["events"] if e["kind"] == "worker_lost"]
    assert lost[-1]["worker"] == "w0"
    assert lost[-1]["reason"] == "stub sigkill"


def test_flight_ring_is_bounded_and_ordered():
    from megba_tpu.observability.flight import FlightRecorder

    rec = FlightRecorder(capacity=4, process_name="t")
    for i in range(10):
        rec.record("tick", i=i)
    events = rec.events()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]
    assert [e["seq"] for e in events] == [7, 8, 9, 10]
    d = rec.dump_dict(reason="test")
    assert d["dropped"] == 6 and d["process"] == "t"


# -------------------------------------------------- fleet harvesting


def test_router_metrics_snapshot_merges_and_repeats_bitwise(armed):
    from megba_tpu.observability import metrics as metrics_mod

    def worker_snap(n):
        reg = metrics_mod.MetricsRegistry()
        reg.counter("megba_solve_status_total", "s").inc(
            n, status="converged", bucket="B1")
        reg.histogram("megba_fleet_batch_latency_seconds", "l").observe(
            0.01 * n, bucket="B1", factor="bal")
        return reg.snapshot()

    w0 = StubWorker("w0", metrics_snapshot=worker_snap(2))
    w1 = StubWorker("w1", metrics_snapshot=worker_snap(3))
    probs = [_mk(0, 16)]
    with FleetRouter(OPT64, workers=[w0, w1], max_batch=8) as router:
        futs = [router.submit(p) for p in probs]
        router.flush()
        [f.result(timeout=5) for f in futs]
        first = router.metrics_snapshot()
        second = router.metrics_snapshot()

    assert first is not None
    # worker series merged (2 + 3), router's own dispatch counter rides
    m = first["metrics"]
    assert m["megba_solve_status_total"]["series"][
        "bucket=B1,status=converged"] == 5
    assert m["megba_fleet_batch_latency_seconds"]["series"][
        "bucket=B1,factor=bal"]["count"] == 2
    assert sum(m["megba_fed_dispatch_total"]["series"].values()) == 1
    # bitwise-deterministic across repeated pulls on an idle fleet
    assert metrics_mod.snapshot_to_json(first) == (
        metrics_mod.snapshot_to_json(second))
    # and the merged snapshot renders as valid Prometheus text
    text = metrics_mod.render_prometheus(first)
    assert "megba_solve_status_total{" in text
    assert "megba_fed_dispatch_total{" in text


def test_router_metrics_snapshot_none_when_plane_off(monkeypatch):
    for knob in ("MEGBA_METRICS", "MEGBA_TRACE", "MEGBA_FLIGHT"):
        monkeypatch.delenv(knob, raising=False)
    w0 = StubWorker("w0")
    with FleetRouter(OPT64, workers=[w0], max_batch=8) as router:
        assert router.metrics_snapshot() is None


# --------------------------------------------------- SolveReport v2


def test_solve_report_v2_roundtrip_and_v1_readable():
    from megba_tpu.observability.report import SCHEMA, SolveReport

    rep = SolveReport(
        problem={"num_cameras": 4}, config={}, backend={}, phases={},
        result={"status_name": "converged"}, trace_id="aa" * 8,
        span_id="bb" * 8, worker="w1", created_unix=123.0)
    back = SolveReport.from_json(rep.to_json())
    assert back.schema == SCHEMA and back.schema.endswith("/v2")
    assert (back.trace_id, back.span_id, back.worker) == (
        "aa" * 8, "bb" * 8, "w1")
    # a v1 line (no identity fields) still loads, identity defaults None
    v1 = json.dumps({
        "problem": {}, "config": {}, "backend": {}, "phases": {},
        "result": {}, "schema": "megba_tpu.solve_report/v1",
        "created_unix": 1.0, "not_a_field": True})
    old = SolveReport.from_json(v1)
    assert old.trace_id is None and old.worker is None


def test_summarize_fleet_table_and_metrics_render(tmp_path, capsys):
    from megba_tpu.observability import summarize
    from megba_tpu.observability.metrics import (
        MetricsRegistry, snapshot_to_json)
    from megba_tpu.observability.report import SolveReport, append_report

    sink = tmp_path / "fleet.jsonl"
    for i, (bucket, worker, lm) in enumerate(
            [("B1", "w0", 3), ("B1", "w1", 5), ("B2", "w0", 7)]):
        append_report(SolveReport(
            problem={}, config={}, backend={}, phases={},
            result={"iterations": lm, "pcg_iterations": 2 * lm,
                    "status_name": "converged"},
            fleet={"bucket": bucket, "latency_s": 0.01 * (i + 1)},
            trace_id=f"t{i:02d}", span_id=f"s{i:02d}", worker=worker,
            created_unix=100.0 + i), str(sink))
    # one v1-style line (no worker/trace fields) must not break the table
    with open(sink, "a") as fh:
        fh.write(json.dumps({
            "problem": {}, "config": {}, "backend": {}, "phases": {},
            "result": {"iterations": 1, "pcg_iterations": 1},
            "schema": "megba_tpu.solve_report/v1",
            "created_unix": 99.0}) + "\n")

    reg = MetricsRegistry()
    reg.counter("megba_fleet_batches_total", "b").inc(
        2, bucket="B1", factor="bal", rung="0")
    snap_path = tmp_path / "metrics.json"
    snap_path.write_text(snapshot_to_json(reg.snapshot()))

    rc = summarize.main(
        ["--fleet", "--metrics", str(snap_path), str(sink)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet table: 4 solves" in out
    assert "B1" in out and "B2" in out and "unbatched" in out
    assert "by worker:" in out and "w0:" in out
    assert "traced: 3 solves in 3 traces" in out
    assert "metrics snapshot" in out
    assert "megba_fleet_batches_total" in out
