"""Import hygiene: the package must not touch devices at import time.

Backend initialisation can hang when the single-client TPU tunnel is
wedged (see utils/backend.py); every entry point defends itself with a
probe, which only works if `import megba_tpu` itself never triggers a
device query.
"""

import subprocess
import sys


def test_import_touches_no_backend():
    code = (
        "import jax\n"
        "import megba_tpu\n"
        "import megba_tpu.solve, megba_tpu.models, megba_tpu.utils\n"
        "import megba_tpu.parallel, megba_tpu.native\n"
        "import megba_tpu.analysis, megba_tpu.analysis.lint\n"
        "import megba_tpu.analysis.retrace, megba_tpu.analysis.strict_dtype\n"
        "import megba_tpu.analysis.hlo, megba_tpu.analysis.budget\n"
        "import megba_tpu.analysis.program_audit, megba_tpu.analysis.audit\n"
        "import megba_tpu.robustness, megba_tpu.robustness.faults\n"
        "import megba_tpu.robustness.harness\n"
        "import megba_tpu.robustness.elastic\n"
        "import megba_tpu.factors, megba_tpu.utils.memo\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), 'import initialized a backend'\n"
        "print('clean')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "clean" in proc.stdout
